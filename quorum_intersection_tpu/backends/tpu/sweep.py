"""Exhaustive batched candidate sweep — the exact TPU-native search for
small-to-medium SCCs.

**Verdict equivalence** (replaces the reference's branch-and-bound for
|scc| ≤ ~30, SURVEY.md §7.3 "Search ≠ sweep"): two disjoint quorums exist
inside the SCC **iff** some subset ``S ⊆ scc ∖ {scc[0]}`` satisfies

    Q := maxQuorum(S) ≠ ∅   and   maxQuorum(scc ∖ Q) ≠ ∅ .

Proof.  (⇐) Q and maxQuorum(scc ∖ Q) are quorums and disjoint by
construction.  (⇒) Let (A, B) be disjoint quorums.  At most one contains
``scc[0]``; w.l.o.g. A avoids it, so the enumeration reaches S = A.  Then
maxQuorum(A) ⊇ A ≠ ∅ (the greatest fixpoint contains every quorum inside the
candidate set), and scc ∖ maxQuorum(A) ⊇ B gives maxQuorum(scc ∖ Q) ⊇ B ≠ ∅.
∎  Fixing ``scc[0]`` out of the enumeration halves the space to 2^(|scc|-1).

This trades the reference's pruned-but-serial enumeration
(cpp:252-346 — few candidates, deep control flow) for a uniform data-parallel
one: every candidate is two batched fixpoints, thousands per device step, the
shape TPUs want.  The candidate axis shards across the mesh; the only
collective is a per-step ``pmin`` over first-hit indices (parallel/mesh.py).

The reference's whole-graph availability for the disjoint probe (Q6) is
honored via the ``frozen`` mask — nodes outside the SCC help satisfy slices
but are never filtered — so verdicts match the oracle under either scoping.
"""

from __future__ import annotations

import bisect
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from quorum_intersection_tpu.backends.base import (
    INT32_MAX,
    CancelToken,
    SccCheckResult,
    SearchCancelled,
)
from quorum_intersection_tpu.encode.circuit import (
    LANE_TILE,
    Circuit,
    ladder_up,
    pack_circuits,
    plan_packs,
    rank_order_nodes,
    restrict_circuit_pair,
)
from quorum_intersection_tpu.fbas.graph import TrustGraph
from quorum_intersection_tpu.fbas.semantics import max_quorum
from quorum_intersection_tpu.utils.env import qi_env
from quorum_intersection_tpu.utils.faults import FaultInjected, fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record
from quorum_intersection_tpu.utils.timers import Throughput

log = get_logger("backends.tpu.sweep")

# Seam for deterministic ramp-jump tests: an inline/failing fake replaces
# real threads so the jump state machine is exercised without timing races.
import threading as _threading  # noqa: E402

_thread_factory = _threading.Thread

DEFAULT_BATCH = None  # adaptive: see _auto_batch (dispatch latency dominates
# below ~32k candidates/step; small circuits sustain much larger blocks)
# Two-level enumeration: the low LO_BITS index bits decode on-device
# (kernels.decode_masks is int32-bound); the remaining high bits are a
# per-program constant availability row, so one compiled program serves
# every outer chunk.  2^44 ≈ 1.8e13 candidates ≈ 10 h at the measured
# ~500M cand/s — the practical ceiling (checkpointing makes it survivable).
LO_BITS = 30
DEFAULT_MAX_BITS = 44
# Deep pipeline: the tunneled chip's round-trip latency is ~100 ms while a
# full-ramp program's device time is ~10-35 ms, so the queue must hold many
# programs to keep the device busy (measured: 4 in flight → ~68M cand/s on a
# 2^30 sweep; 32 in flight → near device-saturation ~1G cand/s on a 31-node
# circuit).  Cost of depth: on a hit, up to max_inflight programs of device
# work are discarded, and a preempted run resumes from the oldest undrained
# program — both bounded by ~1 s of device work at full ramp.
MAX_INFLIGHT = 32
# A device program has a fixed multi-ms overhead regardless of content
# (kernels.py module docs), so as the enumeration proves large the driver
# ramps the number of sweep blocks packed per program through these values —
# small sweeps never pay the compile time of the big shapes, exhaustive
# sweeps amortize dispatch to noise.
STEPS_RAMP = (1, 8, 64, 256, 1024)
# Dispatches before the ramp may grow.  After one small validation program,
# growth JUMPS straight to the largest level the remaining work can fill —
# intermediate levels get zero programs (r3, after instrumenting the r2
# gap): the old walk — 4 dispatches at every intermediate level — pushed
# >60% of a 2^30 enumeration through sub-maximal programs and compiled
# every intermediate shape on the critical path.  Jumping compiles 2 shapes
# instead of 4-5; the FIRST program still stays small, so broken networks
# keep their fast first result, at the cost of coarser checkpoint/early-hit
# granularity from the second program onward (bounded by one max-size
# program of device work).
RAMP_DISPATCHES = 1
# Pipeline depth while a ramp-jump compile is pending.  Small (ramp-level)
# programs are pure dispatch-RTT: each drain costs one tunnel round-trip
# (~65 ms) regardless of depth, so a full 32-deep queue of them adds ~2 s of
# mandatory drains after the jump lands (measured on the r3 chip: the entire
# level-1 bucket of a warm 2^30 sweep).  Capping the queue during the
# compile window bounds that backlog to a few programs without idling the
# device — the cap lifts the moment the jump happens.
RAMP_INFLIGHT = 4


class SccTooLargeError(ValueError):
    """Raised when the SCC exceeds the sweep's enumeration width."""


# ---------------------------------------------------------------------------
# Block-guard pruning (ISSUE 10): partition the enumeration into blocks of
# 2^k consecutive windows sharing a fixed high-bit prefix, and run ONE cheap
# greatest-fixpoint test on each block's MAXIMAL candidate (the prefix's
# fixed-one nodes plus every free low-bit node).  Soundness: every window S
# of the block satisfies S ⊆ S_max, the greatest fixpoint is monotone in its
# candidate set, and a window hits only when maxQuorum(S) ≠ ∅ — so an EMPTY
# fixpoint on S_max proves no window of the block can hit, and the whole
# block skips into the certificate's `windows_pruned_guard` term as a
# checkable `(prefix, k, rule)` claim that tools/check_cert.py re-verifies
# with its own stdlib fixpoint evaluator.  The guard runs on device through
# the same fixpoint kernels as the sweep itself (kernels.
# guard_program_factory / pallas_sweep.pallas_guard_factory).

# The single guard rule this engine emits; the checker rejects unknown ids.
PRUNE_RULE_ID = "empty-max-quorum"
# Below this enumeration width the space is trivial and guard setup costs
# more than sweeping; skip pruning.
PRUNE_MIN_BITS = 6
# Prefix granularity cap: at most 2^14 = 16384 guard rows per enumeration —
# one fixpoint row per block, ~windows/2^k of extra work.
PRUNE_MAX_PREFIX_BITS = 14
# Never shrink blocks below 2^2 windows (guard row per 4 windows is the
# break-even floor: each guard row costs about one window's Q fixpoint).
PRUNE_MIN_BLOCK_BITS = 2
# Guard rows per compiled guard program (kernels.guard_program_factory
# chunk shape).
GUARD_BATCH = 4096


@dataclass
class _PrunePlan:
    """One enumeration's block-guard prune plan: the pruned blocks (as
    cert-ledger prefixes AND merged window runs for O(log) overlap
    queries) plus the surviving ranges the drive loop actually sweeps."""

    block_bits: int                 # k: windows per block = 2^k
    prefixes: List[int]             # pruned block ids (>= the resume cut)
    windows: int                    # pruned window count = len(prefixes) << k
    ranges: List[Tuple[int, int]]   # surviving [lo, hi) over [start0, total)
    runs: List[Tuple[int, int]]     # merged pruned [lo, hi) window runs
    cum: List[int]                  # pruned windows before runs[i]
    run_los: List[int] = field(default_factory=list)
    guard_rows: int = 0

    @classmethod
    def build(
        cls,
        block_bits: int,
        prefixes: List[int],
        total: int,
        start0: int,
        guard_rows: int,
    ) -> "_PrunePlan":
        runs: List[Tuple[int, int]] = []
        for p in prefixes:  # ascending
            lo, hi = p << block_bits, (p + 1) << block_bits
            if runs and runs[-1][1] == lo:
                runs[-1] = (runs[-1][0], hi)
            else:
                runs.append((lo, hi))
        cum = [0]
        for lo, hi in runs:
            cum.append(cum[-1] + (hi - lo))
        ranges: List[Tuple[int, int]] = []
        pos = start0
        for lo, hi in runs:
            if lo > pos:
                ranges.append((pos, lo))
            pos = max(pos, hi)
        if pos < total:
            ranges.append((pos, total))
        return cls(
            block_bits=block_bits,
            prefixes=list(prefixes),
            windows=len(prefixes) << block_bits,
            ranges=ranges,
            runs=runs,
            cum=cum,
            run_los=[lo for lo, _ in runs],
            guard_rows=guard_rows,
        )

    def pruned_before(self, x: int) -> int:
        """Pruned windows with index < ``x``."""
        ix = bisect.bisect_right(self.run_los, x) - 1
        if ix < 0:
            return 0
        lo, hi = self.runs[ix]
        return self.cum[ix] + min(max(x - lo, 0), hi - lo)

    def overlap(self, lo: int, hi: int) -> int:
        """Pruned windows inside ``[lo, hi)``."""
        if hi <= lo:
            return 0
        return self.pruned_before(hi) - self.pruned_before(lo)

    def skip(self, pos: int) -> int:
        """Smallest surviving window index >= ``pos``."""
        ix = bisect.bisect_right(self.run_los, pos) - 1
        if ix >= 0 and pos < self.runs[ix][1]:
            return self.runs[ix][1]
        return pos


# A jump level only reaches full throughput when enough programs of it fit
# in the remaining work to keep the dispatch pipeline loaded — 2^30 at the
# top level is exactly 2 programs, whose per-program result-fetch RTT cannot
# overlap anything (measured r3: 1.50 G cand/s vs 2.05 G at the level below
# with 8 programs).  Prefer the largest level with PIPE-many programs of
# work; fall back to the sparser 2× rule when nothing satisfies it.
JUMP_PIPE_FILL = 8


def _jump_target_ix(ramp, ix: int, base_block: int, remaining: int) -> int:
    """Largest ramp index above ``ix`` the remaining work can fill.

    The 2× fallback applies only off the FIRST level: level-1 programs are
    pure dispatch latency, so any growth beats staying put even with a
    sparse pipeline — but once at a pipe-filling level, climbing to a level
    the remainder can NOT fill would re-create the under-filled regime the
    pipe rule exists to avoid (remaining work only shrinks, so such a climb
    could never satisfy the pipe rule that the first jump already
    maximized)."""
    best = ix
    for j in range(ix + 1, len(ramp)):
        if remaining >= ramp[j] * base_block * JUMP_PIPE_FILL:
            best = j
    if best == ix and ix == 0:
        for j in range(ix + 1, len(ramp)):
            if remaining >= ramp[j] * base_block * 2:
                best = j
    return best


@dataclass(frozen=True)
class EngineResolution:
    """Typed outcome of sweep-engine selection (ISSUE 5 satellite).

    Replaces the old warn-and-swerve sites (``engine="pallas"`` with a mesh
    silently ran the XLA path behind a log line): selection is now ONE
    routing decision with a documented precedence, recorded as a
    ``sweep.engine_resolved`` telemetry event, so a run can always answer
    "which kernel engine actually executed, and why".
    """

    requested: str
    resolved: str
    reason: str


def resolve_engine(
    requested: str,
    *,
    mesh: bool,
    wide: bool,
    restricted: bool,
    circuit: Circuit,
) -> EngineResolution:
    """The single source of truth for which kernel engine a sweep runs.

    Precedence (first matching rule wins; every ``pallas``/``bitset``
    request that cannot be honored resolves to ``xla`` with the reason
    recorded):

    1. ``xla`` requested — always honored (it is the universal engine);
    2. mesh sharding — neither alternate engine has a sharded program;
    3. ``bitset`` requested — honored on any circuit with 0/1 qset
       multiplicities (wide AND restricted sweeps included: the bitset
       step packs the hi-mask on device and carries D-probe thresholds);
       multi-edges fall back to ``xla`` (a packed word holds one bit per
       member);
    4. wide (two-level, > 2^lo_bits) enumeration — the pallas kernel takes
       no hi-mask input;
    5. SCC-restricted circuit — the unpacked pallas kernel carries no
       separate D-probe thresholds (the packed driver resolves with
       ``restricted=False``: its pallas kernel does);
    6. vote counts beyond int8 — the pallas kernel is int8-only;
    7. otherwise — ``pallas`` as requested.
    """
    if requested == "xla":
        return EngineResolution(requested, "xla", "as requested")
    if mesh:
        return EngineResolution(
            requested, "xla",
            f"mesh sharding: the {requested} kernel has no sharded program",
        )
    if requested == "bitset":
        from quorum_intersection_tpu.encode.circuit import bitset_supported

        if not bitset_supported(circuit):
            return EngineResolution(
                requested, "xla",
                "qset multiplicities exceed 1: the bitset encoding holds one bit per member",
            )
        return EngineResolution(requested, "bitset", "as requested")
    if wide:
        return EngineResolution(
            requested, "xla", "wide (two-level) enumeration: the pallas kernel has no hi-mask input"
        )
    if restricted:
        return EngineResolution(
            requested, "xla", "SCC-restricted sweep: the unpacked pallas kernel has no D-probe thresholds"
        )
    from quorum_intersection_tpu.backends.tpu import pallas_sweep

    if not pallas_sweep.pallas_supported(circuit):
        return EngineResolution(
            requested, "xla", "vote counts exceed int8: the pallas kernel is int8-only"
        )
    return EngineResolution(requested, "pallas", "as requested")


def _emit_engine_resolution(resolution: EngineResolution, packed: bool = False) -> None:
    """One ``sweep.engine_resolved`` event per check — the explicit routing
    record the old warning lines never left."""
    get_run_record().event(
        "sweep.engine_resolved",
        requested=resolution.requested,
        resolved=resolution.resolved,
        reason=resolution.reason,
        packed=packed,
    )
    if resolution.resolved != resolution.requested:
        log.info(
            "sweep engine %r resolved to %r: %s",
            resolution.requested, resolution.resolved, resolution.reason,
        )


def macs_per_candidate_row(n: int, n_units: int, depth: int, lane: int = 128) -> int:
    """Shape-model MACs one candidate row costs per fixpoint iteration on a
    lane-tiled accelerator: the direct-vote matmul streams the (n, U)
    operand at lane-padded width, plus ``depth`` child-propagation passes
    over the (U, U) operand.  The LANE PADDING is counted deliberately —
    XLA pads the lane axis to 128 "for free" (encode/circuit.py PAD_LADDER
    note) and that padding is 100% wasted compute, which is exactly the
    waste lane packing reclaims.  Iteration counts and the Q/D factor are
    workload-dependent and near-identical packed vs unpacked (the packed
    fixpoint is the product of the per-group fixpoints), so they cancel in
    the packed-vs-unpacked MACs-per-verdict ratio this model exists to make
    checkable off-chip (benchmarks/sweep_vs_native.py --packed).
    """
    wn = lane * ((max(n, 1) + lane - 1) // lane)
    wu = lane * ((max(n_units, 1) + lane - 1) // lane)
    return wn * wu + depth * wu * wu


def bitset_words_per_candidate_row(
    n: int, n_units: int, depth: int, lane: int = 128
) -> int:
    """Shape-model u32 word-ops one candidate row costs per fixpoint
    iteration on the bitset twin (qi-sparse ISSUE 20): the popcount vote
    loop streams ``ceil(n/32)`` packed words against every (lane-padded)
    unit column, plus ``depth`` child passes over ``ceil(units/32)`` words.
    The dense/bitset ratio of this model against
    :func:`macs_per_candidate_row` is the ~32x arithmetic-intensity claim
    the ``--bitset`` bench rows make checkable off-chip: MACs touch one
    operand byte per node pair, words touch 4 bytes per 32 node pairs.
    """
    wn = lane * ((max(n, 1) + lane - 1) // lane)
    wu = lane * ((max(n_units, 1) + lane - 1) // lane)
    words_n = (wn + 31) // 32
    words_u = (wu + 31) // 32
    return words_n * wu + depth * words_u * wu


@dataclass
class _SweepJob:
    """One sweep problem prepared for lane packing: SCC-restricted circuit
    pair plus the graph-space decode data for witness reconstruction."""

    graph: TrustGraph
    nodes: List[int]  # graph-space scc ids (enumeration order)
    scope_to_scc: bool
    circuit: Circuit  # scoped (Q-side) restriction
    circuit_d: Optional[Circuit]  # Q6 fold for the D probe (None: scoped)
    bits: int
    total: int
    candidates: int = 0
    # qi-cert: windows of THIS job's enumeration never swept because the
    # pack's window-splitting made them redundant (a lower window hit).
    skipped: int = 0
    first_hit: Optional[int] = None
    resolved: bool = False
    intersects: Optional[bool] = None
    result: Optional[SccCheckResult] = None
    # qi-fuse: a per-job cancel (this request's deadline/client abort)
    # retired the job's lane groups mid-pack; the unswept remainder is
    # CANCELLED coverage on this job's ledger only.
    cancelled: bool = False
    cancelled_windows: int = 0
    # Rank-order provenance (ISSUE 10): stamped into the job's stats/cert
    # when the enumeration order was permuted.
    order_meta: Optional[Dict[str, object]] = None


@dataclass
class _PackGroup:
    """One lane group: a contiguous candidate window ``[lo, hi)`` of one
    job.  A job with one group sweeps its whole enumeration; extra groups
    (spare pack lanes) split it into ascending contiguous windows, and the
    job's first hit is the first hit of the LOWEST window whose every
    predecessor swept clean — identical to the unpacked FIFO order."""

    job: int
    lo: int
    hi: int
    hit: Optional[int] = None
    done: bool = False


def clamp_batch_to_index_ceiling(batch: int, lo_total: int) -> int:
    """int32 decode ceiling: the largest index a program can touch is
    ``lo_total + STEPS_RAMP[-1]·base_block`` (chunk-tail overshoot decodes as
    in-chunk aliases, but only while it stays below 2^31).  Clamp
    user-supplied batches that would cross it rather than wrap negative —
    a wrapped index decodes every non-enumerated node as available and could
    silently flip the verdict (see also the host witness recheck)."""
    max_block = max(1, ((1 << 31) - lo_total) // STEPS_RAMP[-1])
    if batch > max_block:
        log.warning(
            "batch %d would cross the 2^31 int32 index ceiling; clamping to %d",
            batch, max_block,
        )
        return max_block
    return batch


def _auto_batch(n: int) -> int:
    """Candidates per sweep block, scaled to the circuit's lane width.

    Small circuits (n ≤ 128 → one 128-lane tile) sustain 512k-row blocks
    (measured 2.6× over 32k rows on a 31-node 2^30 sweep — per-block fixed
    costs amortize); wider circuits shrink the row count to keep the
    per-sweep working set roughly constant.
    """
    lanes = 128 * ((max(n, 1) + 127) // 128)
    return min(1 << 19, max(1 << 15, (1 << 26) // lanes))


class TpuSweepBackend:
    """Exhaustive subset sweep over the quorum-bearing SCC."""

    name = "tpu-sweep"
    needs_circuit = True
    # qi-fuse: check_sccs accepts per-job cancel tokens and origins — a
    # fused batch former may hand work from different requests to one
    # call, each lane group retiring on its own request's deadline.
    supports_job_cancels = True

    def __init__(
        self,
        batch: Optional[int] = DEFAULT_BATCH,
        max_bits: int = DEFAULT_MAX_BITS,
        mesh=None,
        checkpoint=None,
        max_inflight: int = MAX_INFLIGHT,
        engine: Optional[str] = None,
        lo_bits: int = LO_BITS,
        cancel=None,
        pad_shapes: bool = True,
        order: Optional[str] = None,
        prune: Optional[bool] = None,
    ) -> None:
        self.batch = batch  # None ⇒ _auto_batch(circuit.n) at check time
        self.max_bits = max_bits
        self.lo_bits = lo_bits  # inner-chunk width of the two-level decode
        self.mesh = mesh
        self.checkpoint = checkpoint  # utils.checkpoint.SweepCheckpoint or None
        self.max_inflight = max_inflight
        # base.CancelToken or None: polled in the window-dispatch and drain
        # loops — the racing auto router stops a losing sweep promptly
        # (check_scc raises SearchCancelled; any recorded checkpoint stays
        # on disk, so a cancelled long sweep still resumes later).
        self.cancel = cancel
        # Canonical shape padding (encode.pad_targets ladder): compiled
        # program shapes collapse into buckets so the persistent compile
        # cache serves the warm-start path; False keeps exact shapes.
        self.pad_shapes = pad_shapes
        # None reads QI_SWEEP_ENGINE at check time; "xla" (default — measured
        # fastest end-to-end on dense circuits, see pallas_sweep module
        # docs), "pallas" (fused single-kernel engine), or "bitset"
        # (qi-sparse: intersect-and-popcount over packed u32 words —
        # density-routed for sparse giants).
        if engine not in (None, "xla", "pallas", "bitset"):
            raise ValueError(f"unknown sweep engine {engine!r}")
        self.engine = engine
        # Device index math is int32 (kernels.decode_masks): lo_bits > 30
        # would let chunk-tail overshoot indices wrap negative, where
        # idx >> 31 decodes every non-enumerated node as available and can
        # silently flip the verdict.
        if lo_bits > LO_BITS:
            raise ValueError(f"lo_bits={lo_bits} exceeds the int32 decode ceiling {LO_BITS}")
        # ISSUE 10 search-space reductions.  order: None reads QI_SWEEP_ORDER
        # ("rank" applies the rank-order permutation, anything else keeps the
        # natural SCC order); prune: None reads QI_SWEEP_PRUNE (block-guard
        # pruning).  Both default OFF — verdicts are identical either way
        # (tests/test_qi_prune.py), these are perf knobs.
        if order not in (None, "natural", "rank"):
            raise ValueError(f"unknown sweep order {order!r}")
        self.order = order
        self.prune = prune

    def _engine_mode(self) -> str:
        """Engine request: ctor wins; else QI_SWEEP_ENGINE ("pallas" /
        "bitset" honored, anything else — including unset — is "xla").
        The request still flows through :func:`resolve_engine`, so forcing
        ``bitset`` on an unsupported circuit degrades with a typed reason
        rather than erroring."""
        if self.engine is not None:
            return self.engine
        env = qi_env("QI_SWEEP_ENGINE").strip().lower()
        return env if env in ("pallas", "bitset") else "xla"

    def _order_mode(self) -> str:
        if self.order is not None:
            return self.order
        return (
            "rank"
            if qi_env("QI_SWEEP_ORDER").strip().lower() == "rank"
            else "natural"
        )

    def _prune_enabled(self) -> bool:
        if self.prune is not None:
            return self.prune
        return qi_env("QI_SWEEP_PRUNE").strip() not in ("", "0")

    # ---- block-guard prune planning (ISSUE 10) ---------------------------

    def _plan_pruning(
        self,
        circuit: Circuit,
        bit_nodes: np.ndarray,
        bits: int,
        total: int,
        start0: int,
        engine: str,
    ) -> Optional[_PrunePlan]:
        """Evaluate the block guards for one enumeration; None ⇒ unpruned.

        ``bit_nodes``: enumeration bit j → circuit node ``bit_nodes[j]``
        (device lane space — post-restriction local indices, or graph
        indices for an unrestricted whole-graph SCC).  ``start0`` is the
        checkpoint-resume cut: blocks not entirely at or above it stay
        unpruned, so the resumed prefix and the pruned mass never overlap
        in the certificate's ledger arithmetic.
        """
        fault_point("sweep.prune")
        if bits < PRUNE_MIN_BITS:
            return None
        prefix_bits = min(PRUNE_MAX_PREFIX_BITS, bits - PRUNE_MIN_BLOCK_BITS)
        if prefix_bits <= 0:
            return None
        k = bits - prefix_bits
        n_blocks = 1 << prefix_bits
        cols = np.asarray(bit_nodes, dtype=np.int64)
        # Block b's maximal candidate: every free low-bit node plus the
        # prefix's fixed-one nodes (bit j of b toggles bit_nodes[k + j]).
        masks = np.zeros((n_blocks, circuit.n), dtype=np.int8)
        masks[:, cols[:k]] = 1
        pref = np.arange(n_blocks, dtype=np.int64)
        hi_bits = (
            (pref[:, None] >> np.arange(prefix_bits, dtype=np.int64)[None, :]) & 1
        ).astype(np.int8)
        masks[:, cols[k:]] = hi_bits
        if engine == "pallas":
            from quorum_intersection_tpu.backends.tpu import pallas_sweep

            guard = pallas_sweep.pallas_guard_factory(circuit)
        elif engine == "bitset":
            # The block's maximal-candidate guard runs bitset-side too: the
            # guard cert's rule (empty max-quorum at the block's top) is
            # encoding-independent, so the checker validates these blocks
            # exactly as dense-proved ones (docs/PARITY.md §Encoding).
            from quorum_intersection_tpu.backends.tpu.kernels import (
                bitset_guard_program_factory,
            )

            guard = bitset_guard_program_factory(
                circuit, min(GUARD_BATCH, n_blocks)
            )
        else:
            from quorum_intersection_tpu.backends.tpu.kernels import (
                guard_program_factory,
            )

            guard = guard_program_factory(circuit, min(GUARD_BATCH, n_blocks))
        prunable = guard(masks) == 0
        # Resume cut: the first block fully at or above start0 — earlier
        # blocks ride in windows_resumed_prefix, not the pruned ledger.
        cut = (start0 + (1 << k) - 1) >> k
        prunable[:cut] = False
        prefixes = [int(p) for p in np.nonzero(prunable)[0]]
        return _PrunePlan.build(k, prefixes, total, start0, n_blocks)

    def _try_plan_pruning(
        self,
        circuit: Circuit,
        bit_nodes: np.ndarray,
        bits: int,
        total: int,
        start0: int,
        engine: str,
    ) -> Optional[_PrunePlan]:
        """Guard planning with in-place degrade: any failure — the injected
        ``sweep.prune`` fault included — falls back to the unpruned
        enumeration (``sweep.prune_degraded`` event + ``sweep.prune_errors``
        counter).  Pruning is an optimization, never a precondition for a
        verdict, so the engine rung itself never fails here."""
        if not self._prune_enabled() or self.mesh is not None:
            return None
        try:
            return self._plan_pruning(
                circuit, bit_nodes, bits, total, start0, engine
            )
        except SearchCancelled:
            raise
        # Pruning degrades IN PLACE to the unpruned sweep (ROBUSTNESS
        # sweep.prune row); the tpu-sweep rung keeps running untouched.
        # qi-lint: allow(degrade-via-ladder) — in-place optimization degrade
        except Exception as exc:  # noqa: BLE001
            rec = get_run_record()
            rec.add("sweep.prune_errors")
            rec.event("sweep.prune_degraded", cause=str(exc))
            log.warning(
                "sweep pruning degraded to unpruned enumeration (%s)", exc
            )
            return None

    @staticmethod
    def _emit_prune_telemetry(
        plans: Sequence[Optional[_PrunePlan]],
        totals: Sequence[int],
        packed: bool = False,
    ) -> None:
        """One ``sweep.pruned`` event + the counters/gauge per drive/pack."""
        live = [p for p in plans if p is not None]
        if not live:
            return
        rec = get_run_record()
        blocks = sum(len(p.prefixes) for p in live)
        windows = sum(p.windows for p in live)
        space = sum(totals)
        rec.add("sweep.blocks_pruned", blocks)
        rec.add("cert.windows_pruned_guard", windows)
        rec.gauge(
            "sweep.prune_ratio",
            round(windows / space, 6) if space else 0.0,
        )
        rec.event(
            "sweep.pruned",
            blocks=blocks, windows=windows, total=space,
            block_bits=live[0].block_bits,
            guard_rows=sum(p.guard_rows for p in live),
            packed=packed,
        )

    # ---- host-side witness reconstruction -------------------------------

    @staticmethod
    def _witness(
        graph: TrustGraph,
        scc: List[int],
        subset: List[int],
        scope_to_scc: bool,
    ) -> Tuple[List[int], List[int]]:
        """Recompute (Q, disjoint) for one hit candidate with the exact host
        semantics (cheap: two fixpoints on one candidate)."""
        avail = [False] * graph.n
        for v in subset:
            avail[v] = True
        q = max_quorum(graph, subset, avail)
        if scope_to_scc:
            avail = [False] * graph.n
            for v in scc:
                avail[v] = True
        else:
            avail = [True] * graph.n  # Q6 whole-graph availability
        for v in q:
            avail[v] = False
        disjoint = max_quorum(graph, scc, avail)
        return q, disjoint

    # ---- main entry ------------------------------------------------------

    def check_scc(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
    ) -> SccCheckResult:
        if circuit is None:
            raise ValueError("sweep backend requires the encoded circuit")
        scc = list(scc)
        # Rank-ordered windows (ISSUE 10): the permutation is applied to the
        # SCC order itself, BEFORE restriction — every downstream structure
        # (restricted circuit lanes, bit_nodes, checkpoint fingerprint,
        # witness decode through `nodes`) inherits it, and the graph-space
        # id list keeps hit decode order-transparent.
        order_meta: Optional[Dict[str, object]] = None
        if self._order_mode() == "rank" and len(scc) > 2:
            scc, order_meta = rank_order_nodes(graph, scc)
        s = len(scc)
        bits = s - 1
        if bits > self.max_bits:
            raise SccTooLargeError(
                f"|scc|={s} exceeds sweep width {self.max_bits}+1; use the frontier backend"
            )
        if self.cancel is not None and self.cancel.cancelled:
            # Pre-cancelled (the race was decided before this engine even
            # started): skip setup entirely — no device contact, no compile.
            raise SearchCancelled(f"sweep cancelled before setup (|scc|={s})")
        t0 = time.perf_counter()
        t0_monotonic = time.monotonic()
        # After t0: enabling the cache touches jax.default_backend(), whose
        # first call pays the tunnel handshake (seconds, high variance) —
        # before t0 it leaks out of the setup bucket and the end-to-end vs
        # sum-of-buckets ledger stops balancing.
        from quorum_intersection_tpu.utils.compile_cache import enable_compilation_cache

        enable_compilation_cache()

        # SCC restriction (encode.restrict_circuit_pair): when the graph is
        # wider than the SCC, project the circuit onto the SCC's columns and
        # fold the constant outside-availability into thresholds — the
        # fixpoint matmuls shrink from (B,n)x(n,U) to (B,s)x(s,U').  The
        # scoped fold drives the Q-side; the Q6 fold rides in ``circuit_d``
        # for the D-side probe (kernels.sweep_step).  ``nodes`` keeps the
        # graph-space ids for witness reconstruction.
        nodes = list(scc)
        circuit_d = None
        restricted = circuit.n > s
        if restricted:
            scoped_c, q6_c = restrict_circuit_pair(circuit, scc)
            log.debug(
                "sweep restricted to |scc|=%d: n %d->%d, units %d->%d",
                s, circuit.n, scoped_c.n, circuit.n_units, scoped_c.n_units,
            )
            circuit = scoped_c
            if not scope_to_scc:
                circuit_d = q6_c
            scc = list(range(s))

        n = circuit.n
        scc_mask = np.zeros(n, dtype=np.float32)
        scc_mask[scc] = 1.0
        frozen = None
        if not scope_to_scc and not restricted:
            frozen = np.ones(n, dtype=np.float32) - scc_mask
        bit_nodes = np.asarray(scc[1:], dtype=np.int32)

        # Two-level decode: index bit j < lo_bits toggles bit_nodes[j]
        # on-device; bit j >= lo_bits toggles hi_nodes[j - lo_bits] via a
        # per-program constant mask row (same global bit→node mapping as a
        # flat decode, so witness reconstruction below is unchanged).
        lo_bits = min(bits, self.lo_bits)
        lo_total = 1 << lo_bits if lo_bits > 0 else 1
        hi_nodes = scc[1 + lo_bits :]

        # Engine selection is ONE typed routing decision (resolve_engine's
        # documented precedence) recorded as a sweep.engine_resolved event
        # — never a warning that swerves control flow behind the log.
        resolution = resolve_engine(
            self._engine_mode(),
            mesh=self.mesh is not None,
            wide=bool(hi_nodes),
            restricted=restricted,
            circuit=circuit,
        )
        _emit_engine_resolution(resolution)
        engine = resolution.resolved

        total = 1 << bits if bits > 0 else 1
        start0 = 0
        fingerprint = None
        if self.checkpoint is not None:
            from quorum_intersection_tpu.utils.checkpoint import sweep_fingerprint

            # Ties the file to this exact enumeration: a stale checkpoint
            # from a different FBAS with an equal-size SCC must not be
            # resumed (it would skip candidates and could flip the verdict).
            fingerprint = sweep_fingerprint(
                circuit.members, circuit.child, circuit.thresholds,
                bit_nodes, scc_mask, frozen,
                # The restricted scoped/Q6 variants share every array above;
                # the D-side thresholds keep the two PROBLEMS distinct.
                None if circuit_d is None else circuit_d.thresholds,
            )
            # Unrestricted problems hash to the same first six arrays as
            # pre-r4 builds (which didn't append the D-thresholds field);
            # accept that legacy hash so an old long-run checkpoint still
            # resumes instead of restarting from zero (ADVICE r4).
            alts = ()
            if circuit_d is None:
                alts = (sweep_fingerprint(
                    circuit.members, circuit.child, circuit.thresholds,
                    bit_nodes, scc_mask, frozen,
                ),)
            start0 = self.checkpoint.resume_position(
                total, fingerprint, alt_fingerprints=alts
            )
            if start0:
                log.info("resuming sweep at candidate %d/%d", start0, total)

        # Warm-start compile path: AFTER the checkpoint fingerprint (hashed
        # over the exact unpadded arrays, so existing checkpoints keep
        # resuming) but BEFORE any device constant/program is built, round
        # the circuit up to the canonical pad ladder.  The compiled program
        # shape — the persistent compile cache's key — then depends on the
        # (bucketed) shape, not the exact node/unit counts, so a re-run of
        # the same canonical shape pays ~zero XLA compile.  Padded nodes are
        # inert (encode.pad_circuit) and every availability input below is
        # zero-extended over them.
        padded_from = None
        if self.pad_shapes and engine != "pallas":
            from quorum_intersection_tpu.encode.circuit import (
                pad_circuit,
                pad_targets,
            )

            n_to, units_to = pad_targets(circuit.n, circuit.n_units)
            if (n_to, units_to) != (circuit.n, circuit.n_units):
                padded_from = (circuit.n, circuit.n_units)
                circuit = pad_circuit(circuit, n_to, units_to)
                if circuit_d is not None:
                    circuit_d = pad_circuit(circuit_d, n_to, units_to)
                scc_mask = np.concatenate(
                    [scc_mask, np.zeros(n_to - n, dtype=scc_mask.dtype)]
                )
                if frozen is not None:
                    frozen = np.concatenate(
                        [frozen, np.zeros(n_to - n, dtype=frozen.dtype)]
                    )
                n = circuit.n

        # Block-guard pruning (ISSUE 10): narrow (single-level) unsharded
        # enumerations only — the wide two-level decode's hi mask and the
        # mesh's contiguous sub-blocks don't speak non-contiguous work yet
        # (the plan itself is None-safe everywhere below).  The guard runs
        # on the SAME device kernels as the sweep; failures degrade in
        # place to the unpruned enumeration (sweep.prune fault point).
        # Planned BEFORE batch selection: when blocks pruned, the base
        # program shrinks toward the block granularity so a surviving
        # fragment never burns a full-size program (the ramp regains large
        # programs on contiguous surviving runs).
        plan: Optional[_PrunePlan] = None
        if not hi_nodes:
            plan = self._try_plan_pruning(
                circuit, bit_nodes, bits, total, start0, engine
            )
        self._emit_prune_telemetry((plan,), (total,))
        pruned_windows = plan.windows if plan is not None else 0
        if plan is not None:
            ranges = plan.ranges
        elif start0 < total:
            ranges = [(start0, total)]
        else:
            ranges = []
        # Suffix sums: surviving work at/after each range, so the ramp-jump
        # heuristics read "remaining REAL work", not the raw index distance
        # (which would count pruned gaps as work and over-jump).
        range_suffix = [0] * (len(ranges) + 1)
        for _rix in range(len(ranges) - 1, -1, -1):
            range_suffix[_rix] = (
                range_suffix[_rix + 1] + ranges[_rix][1] - ranges[_rix][0]
            )

        batch = self.batch if self.batch is not None else _auto_batch(circuit.n)
        batch = clamp_batch_to_index_ceiling(batch, lo_total)
        if plan is not None and plan.windows:
            # Align the base program with the prune granularity (floor 512
            # rows keeps shapes sane at tiny block sizes): fragmented
            # surviving ranges cost at most one base-size program each,
            # while STEPS_RAMP still fuses up to 1024 base blocks per
            # program across contiguous surviving runs.
            batch = min(batch, max(1 << plan.block_bits, 512))
        if hi_nodes:
            # Power-of-two blocks make chunk tails exact (no aliased
            # overshoot work); correctness does not depend on it — the
            # dispatch loop advances/records only to the chunk boundary and
            # the drain masks aliased hit indices.
            batch = 1 << (min(batch, lo_total).bit_length() - 1)
        lo_nodes = np.asarray(scc[1 : 1 + lo_bits], dtype=np.int32)
        if self.mesh is not None:
            base_block, make_dispatch = self._build_sharded_step(
                circuit, lo_nodes, scc_mask, frozen, batch, circuit_d=circuit_d
            )
        elif engine == "pallas":
            # resolve_engine already ruled out mesh/wide/restricted/int8
            # conflicts — a pallas resolution here is unconditionally usable.
            from quorum_intersection_tpu.backends.tpu import pallas_sweep

            base_block, _ = pallas_sweep.plan_batch(min(batch, max(total, 1)))
            make_dispatch = pallas_sweep.pallas_sweep_program_factory(
                circuit, lo_nodes, scc_mask, frozen, base_block
            )
        elif engine == "bitset":
            base_block = min(batch, max(lo_total, 1))
            try:
                fault_point("sweep.bitset")
                from quorum_intersection_tpu.backends.tpu.kernels import (
                    bitset_sweep_program_factory,
                )

                make_dispatch = bitset_sweep_program_factory(
                    circuit, lo_nodes, scc_mask, frozen, base_block,
                    circuit_d=circuit_d,
                )
            except SearchCancelled:
                raise
            # The bitset encoding degrades IN PLACE to the dense matmul path
            # (ROBUSTNESS sweep.bitset row): same verdict/ledger contract,
            # only the fixpoint's arithmetic differs, so the tpu-sweep rung
            # keeps running untouched.
            # qi-lint: allow(degrade-via-ladder) — in-place encoding degrade
            except Exception as exc:  # noqa: BLE001
                rec_b = get_run_record()
                rec_b.add("sweep.bitset_errors")
                rec_b.event("sweep.bitset_degraded", cause=str(exc), packed=False)
                log.warning(
                    "bitset sweep engine degraded to the dense encoding (%s)",
                    exc,
                )
                engine = "xla"
                from quorum_intersection_tpu.backends.tpu.kernels import (
                    sweep_program_factory,
                )

                make_dispatch = sweep_program_factory(
                    circuit, lo_nodes, scc_mask, frozen, base_block,
                    circuit_d=circuit_d,
                )
        else:
            from quorum_intersection_tpu.backends.tpu.kernels import sweep_program_factory

            base_block = min(batch, max(lo_total, 1))
            # Device constants upload once; each ramp level only compiles.
            make_dispatch = sweep_program_factory(
                circuit, lo_nodes, scc_mask, frozen, base_block,
                circuit_d=circuit_d,
            )

        # Pipelined drive: keep up to MAX_INFLIGHT asynchronous device
        # programs queued and sync on the *oldest* (FIFO), so host↔device
        # round-trip latency — the measured bottleneck on a tunneled chip —
        # overlaps with device compute.  FIFO draining preserves determinism:
        # the first program containing a hit is processed first, and the
        # per-program scalar is the minimum hit index, so the witness is the
        # globally smallest hit candidate.  Program size ramps through
        # STEPS_RAMP as the sweep proves large (shape cache: one compile per
        # ramp level actually reached).
        steps = 0
        candidates = 0
        found = False
        first_hit = 0
        inflight: "deque" = deque()
        dispatchers = {}
        # Telemetry: windows dispatched/cancelled counters, one progress
        # event per drained window, and the (finally wired) Throughput
        # counter — fed drain-interval candidates/sec, so its rate excludes
        # setup and blocking compiles unlike the end-to-end stat.
        rec = get_run_record()
        throughput = Throughput()
        hi_cache = [-1, None]  # last built (hi value, mask row)
        # Instrumentation (VERDICT r2 §next-2): where does wall-clock go?
        # - compile_seconds: synchronous trace+compile of each program shape
        #   (the first dispatch call per steps_per_call blocks on it);
        # - per-level drain profile: candidates and elapsed time per ramp
        #   level, so steady-state device rate is separable from ramp-up.
        compile_seconds = 0.0
        t_first_dispatch = None
        drain_log: list = []  # (monotonic_time, coverage, steps_per_call)
        compile_log: list = []  # (end_time, duration) per synchronous compile

        def hi_row(hi: int):
            """Availability row for the high index bits (None when narrow)."""
            if not hi_nodes:
                return None
            if hi_cache[0] != hi:
                row = np.zeros(n, dtype=np.float32)
                for j, v in enumerate(hi_nodes):
                    if (hi >> j) & 1:
                        row[v] = 1.0
                hi_cache[0], hi_cache[1] = hi, row
            return hi_cache[1]

        def dispatch(lo: int, hi: int, steps_per_call: int):
            nonlocal compile_seconds, t_first_dispatch
            # Injectable device-dispatch boundary (utils/faults.py): `oom`
            # simulates RESOURCE_EXHAUSTED — the transient class the auto
            # router's ladder retries with backoff before degrading.
            fault_point("sweep.dispatch")
            if t_first_dispatch is None:
                t_first_dispatch = time.monotonic()
            fn = dispatchers.get(steps_per_call)
            if fn is None:
                # First call per shape blocks on trace+compile (subsequent
                # dispatches of the same shape are asynchronous); charge that
                # synchronous wall time to the compile bucket.
                fault_point("sweep.compile")
                fn = dispatchers[steps_per_call] = make_dispatch(steps_per_call)
                tc = time.monotonic()
                out = fn(lo, hi_row(hi))
                te = time.monotonic()
                compile_seconds += te - tc
                compile_log.append((te, te - tc))
                return out
            return fn(lo, hi_row(hi))

        trace = log.isEnabledFor(logging.DEBUG)  # cached for the hot loop

        def drain_one() -> bool:
            """Sync the oldest in-flight program; True iff it hit."""
            nonlocal steps, candidates, first_hit, found
            start, coverage, hi_base, spc, handle = inflight.popleft()
            hit = int(handle)
            steps += 1
            checked = min(coverage, total - start)
            candidates += checked
            now = time.monotonic()
            prev_t = drain_log[-1][0] if drain_log else (
                t_first_dispatch if t_first_dispatch is not None else now
            )
            interval = max(now - prev_t, 0.0)
            throughput.add(checked, interval)
            rec.add("sweep.candidates_checked", checked)
            # qi-cert coverage ledger (ISSUE 7): the exact enumerated-window
            # count, maintained at the drain — sums to `total` on a clean
            # full sweep, which is the checkable invariant behind every
            # `true` certificate (tools/check_cert.py).
            rec.add("cert.windows_enumerated", checked)
            rec.event(
                "sweep.window",
                start=start, candidates=checked, steps_per_call=spc,
                done=candidates, total=total, seconds=round(interval, 6),
                rate=round(checked / interval, 1) if interval > 0 else None,
            )
            drain_log.append((now, checked, spc))
            if trace:
                log.debug(
                    "sweep program %d: start=%d coverage=%d checked=%d/%d hit=%s",
                    steps, start, coverage, candidates, total, hit < int(INT32_MAX),
                )
            if hit < int(INT32_MAX):
                found = True
                # Chunk-tail programs may report an aliased (wrapped) index;
                # decode is periodic in 2^lo_bits, so masking recovers the
                # true in-chunk position.
                first_hit = (hi_base << lo_bits) | (hit & (lo_total - 1))
                return True
            if self.checkpoint is not None and not (
                self.cancel is not None and self.cancel.cancelled
            ):
                # The last program may overshoot `total` (ramped coverage is
                # not a divisor of it); clamp or resume_position would reject
                # the record and restart the whole sweep.  A cancelled sweep
                # stops recording: progress written by a RACE-losing sweep
                # would flip auto's resumable gate and skip the oracle on
                # every later run of the same problem (r1 review finding) —
                # the race driver additionally clears anything already
                # recorded before the cancel landed.
                self.checkpoint.record(min(start + coverage, total), total, fingerprint)
            return False

        async_compile = {"thread": None, "target": None, "seconds": 0.0}

        def start_async_compile(target: int) -> None:
            """Build + AOT-compile the target shape off-thread; the main
            loop keeps the device busy with current-level programs and only
            switches once the compiled program is ready (dispatchers[target]
            is assigned LAST, so the main thread never blocks on the lock)."""
            def work():
                tc = time.monotonic()
                try:
                    fn = make_dispatch(target)
                    precompile = getattr(fn, "precompile", None)
                    if precompile is None:
                        # Engine without AOT support (e.g. pallas): leave the
                        # dispatcher unregistered so the jump's inline
                        # compile is charged to compile_log, not silently
                        # folded into a drain interval.
                        return
                    precompile()
                    dispatchers[target] = fn
                # qi-lint: allow(degrade-via-ladder) — engine-internal retry
                except Exception as exc:  # noqa: BLE001 — fall back to sync
                    log.info("async ramp compile failed (%s); will compile inline", exc)
                finally:
                    async_compile["seconds"] += time.monotonic() - tc
            # Non-daemon: on an early-hit return the verdict is produced
            # immediately and only interpreter EXIT waits for the compile —
            # a daemon thread hard-killed inside native XLA compile aborts
            # the process ('FATAL: exception not rethrown').
            t = _thread_factory(target=work)
            async_compile["thread"] = t
            async_compile["target"] = target
            t.start()

        def check_cancel() -> None:
            """Cooperative cancel point (racing auto router): polled once
            per dispatched/drained program, so cancellation latency is
            bounded by one in-flight program's device time (~1 s at full
            ramp).  In-flight handles are simply dropped — the same
            bounded discard as an early hit.  Recording stops with the
            cancel (drain_one's guard); whether already-recorded progress
            survives is the CALLER's call — the race driver discards it
            when the oracle wins (it would mis-route later runs), while a
            caller cancelling a genuinely long sweep may keep it."""
            if self.cancel is not None and self.cancel.cancelled:
                rec.add("sweep.windows_cancelled", len(inflight))
                # qi-cert: everything not yet drained is CANCELLED coverage
                # — a later certificate must never claim these windows.
                # (The resumed prefix and the guard-pruned mass are already
                # claimed by their own ledger terms, never by this one.)
                rec.add(
                    "cert.windows_cancelled",
                    max(total - start0 - pruned_windows - candidates, 0),
                )
                rec.event(
                    "sweep.cancelled", start=start, total=total,
                    windows_dropped=len(inflight), drained=steps,
                )
                raise SearchCancelled(
                    f"sweep cancelled at candidate {start}/{total} "
                    f"({steps} programs dispatched)"
                )

        seg_ix = 0
        start = ranges[0][0] if ranges else total
        ramp_ix = 0
        since_ramp = 0  # dispatches since the last ramp change: the first
        # (small) program must run before the jump, so an early hit or crash
        # right at the start never has to sync/lose a maximum-size program.

        def remaining_work() -> int:
            """Surviving (un-pruned) windows not yet dispatched — the
            "remaining work" every ramp decision reads.  With pruning the
            space is no longer contiguous, so the raw index distance
            ``total - start`` would count pruned gaps as work."""
            if seg_ix >= len(ranges):
                return 0
            return range_suffix[seg_ix] - (start - ranges[seg_ix][0])

        def jump_worthwhile() -> bool:
            """Can the remaining work still fill the next ramp level?  The
            single source of truth for jump eligibility — the pre-loop
            compile start, the loop's jump branch, and the stale-marker
            clear must all agree or the depth cap / compiled big shape
            desynchronize from the actual jump decision."""
            return (
                ramp_ix + 1 < len(STEPS_RAMP)
                and remaining_work() >= STEPS_RAMP[ramp_ix + 1] * base_block * 2
            )

        if jump_worthwhile():
            # The jump target is already known before the first dispatch, so
            # its compile overlaps the level-1 compile instead of starting
            # only after it (the first dispatch blocks on level-1's compile;
            # serializing the two wastes the bigger compile's full latency).
            start_async_compile(STEPS_RAMP[
                _jump_target_ix(STEPS_RAMP, ramp_ix, base_block, remaining_work())
            ])
        # One span over the whole dispatch/drain drive (qi-trace): every
        # per-window sweep.window progress event lands inside it, so the
        # exported timeline shows the enumeration as one block with its
        # windows as instant marks on the same thread track.
        with rec.span(
            "sweep.drive", scc=s, total=total, resumed_from=start0,
            pruned=pruned_windows,
        ) as drive_span:
            while seg_ix < len(ranges):
                cur_hi = ranges[seg_ix][1]
                if start >= cur_hi:
                    # Range exhausted: hop over the pruned gap to the next
                    # surviving range (remaining work is non-contiguous now).
                    seg_ix += 1
                    if seg_ix < len(ranges):
                        start = ranges[seg_ix][0]
                    continue
                check_cancel()
                # Injectable window boundary: `preempt` simulates the scheduler
                # revoking the chip mid-enumeration (any recorded checkpoint
                # stays on disk, so the preempted run resumes — exactly the
                # contract checkpoints exist for).
                fault_point("sweep.window")
                # Grow the program only once the remaining work would fill at
                # least a couple of programs at the next size (never compile
                # shapes a small sweep won't use) — and then jump straight to
                # the largest such level, skipping the intermediate shapes.
                # The jump-target shape compiles in a background thread while
                # the current level keeps sweeping; the switch happens only when
                # the compiled program is ready (or inline if the thread died).
                if since_ramp >= RAMP_DISPATCHES and jump_worthwhile():
                    ct = async_compile["target"]
                    thread = async_compile["thread"]
                    if (
                        ct is not None
                        and ct in dispatchers
                        and remaining_work() >= ct * base_block
                    ):
                        # The in-flight compile landed and still fits: jump.
                        ramp_ix, since_ramp = STEPS_RAMP.index(ct), 0
                        async_compile["target"] = None
                    elif thread is None or not thread.is_alive():
                        target_ix = _jump_target_ix(
                            STEPS_RAMP, ramp_ix, base_block, remaining_work()
                        )
                        if target_ix == ramp_ix:
                            # No level above is worth compiling for the work
                            # that remains; drop any stale marker so the ramp
                            # depth cap lifts (and never "compile" the current
                            # level in a loop).
                            async_compile["target"] = None
                        elif ct == STEPS_RAMP[target_ix] and ct not in dispatchers:
                            # Thread finished without registering: compile
                            # failed; jump anyway, dispatch() compiles inline.
                            ramp_ix, since_ramp = target_ix, 0
                            async_compile["target"] = None
                        else:
                            start_async_compile(STEPS_RAMP[target_ix])
                    # else: a compile is still in flight — keep sweeping at the
                    # current level; the target is re-validated against the
                    # remaining work at jump time, never re-chosen mid-compile.
                elif async_compile["target"] is not None and not jump_worthwhile():
                    # The remaining work shrank below the jump guard while the
                    # compile was in flight: it will never be jumped to.  Clear
                    # the marker so the ramp depth cap lifts for the tail.
                    async_compile["target"] = None
                hi, lo = start >> lo_bits, start & (lo_total - 1)
                coverage = STEPS_RAMP[ramp_ix] * base_block
                spc = STEPS_RAMP[ramp_ix]
                boundary = min(lo_total - lo, cur_hi - start)
                if coverage > boundary:
                    # Segment tail — the decode chunk (two-level lo space) or
                    # the current surviving range, whichever ends first:
                    # dispatch the smallest program that covers the remainder,
                    # but ADVANCE/RECORD only to the boundary.  Chunk-tail
                    # overshoot decodes as aliases of this same chunk's prefix
                    # (harmless duplicates); range-tail overshoot sweeps into
                    # a guard-pruned gap, which by guard soundness holds no
                    # hit — either way the recorded position never claims
                    # windows beyond the boundary, so the enumerated count
                    # and any pruned ledger term stay disjoint.  This also
                    # makes checkpoint positions independent of batch/lo_bits
                    # choices across resumes.
                    rem = boundary
                    # Prefer the smallest ALREADY-COMPILED shape that covers the
                    # remainder (overshoot aliases are free duplicates): the
                    # jump skips intermediate levels, so a fresh `next(...)`
                    # pick here could stall the pipeline on a synchronous
                    # compile of a shape used exactly once per chunk tail.
                    compiled_ok = [
                        r for r in STEPS_RAMP
                        if r * base_block >= rem and r in dispatchers
                    ]
                    spc = (
                        min(compiled_ok) if compiled_ok
                        else next(r for r in STEPS_RAMP if r * base_block >= rem)
                    )
                    coverage = rem
                inflight.append((start, coverage, hi, spc, dispatch(lo, hi, spc)))
                rec.add("sweep.windows_dispatched")
                since_ramp += 1
                start += coverage
                # While a jump compile is pending AND the current level is the
                # first one, the queue holds only small RTT-bound programs; keep
                # it shallow (RAMP_INFLIGHT) so the post-jump drain backlog
                # stays bounded.  Above level 1 the queued programs are real
                # device work — capping them would idle the chip, and a pending
                # target that can no longer be jumped to is cleared above.
                depth = (
                    min(self.max_inflight, RAMP_INFLIGHT)
                    if async_compile["target"] is not None and ramp_ix == 0
                    else self.max_inflight
                )
                if len(inflight) >= max(depth, 1) and drain_one():
                    break
            while not found and inflight:
                check_cancel()
                if drain_one():
                    break
            drive_span.set(windows=steps, candidates=candidates,
                           found=found)

        # No join here: the compile thread is non-daemon, so an early-hit
        # verdict returns immediately and only interpreter exit waits for
        # any still-running compile (bounded by one compile; ~instant when
        # the persistent cache is warm).

        seconds = time.perf_counter() - t0
        stats = {
            "backend": self.name,
            "candidates_checked": candidates,
            "device_steps": steps,
            # The (n, units) shape the device programs actually ran —
            # post-restriction, post-padding — for shape-model work
            # accounting (macs_per_candidate_row; the packed bench row).
            "device_shape": [circuit.n, circuit.n_units],
            "enumeration_total": total,
            "seconds": seconds,
            "candidates_per_sec": candidates / seconds if seconds > 0 else 0.0,
            # Drain-interval rate from the wired Throughput counter: what
            # the device sustained between drains, setup/compile excluded
            # (the end-to-end candidates_per_sec includes them).
            "window_candidates_per_sec": round(throughput.per_second, 1),
            # qi-cert coverage ledger (cert.py ledger_entry): the window
            # categories whose sum the independent checker pins to the
            # window space on every `true` certificate.  Pruned-by-guard
            # carries the block-guard wins (ISSUE 10) as auditable mass —
            # each pruned block is a checkable (prefix, k, rule) claim the
            # checker re-verifies with its own fixpoint evaluator, never a
            # silent shrink of `windows_enumerated`.  A checkpoint-
            # resumed run did not re-drain the fingerprint-matched prefix,
            # so the prefix rides as its own term (the checker counts it
            # into the sum) rather than inflating `windows_enumerated`,
            # which stays "drained by THIS run" exactly.
            "cert": {
                "window_space": total,
                "windows_enumerated": candidates,
                "windows_pruned_guard": pruned_windows,
                "windows_skipped_pack_fill": 0,
                "windows_cancelled": 0,
                "windows_resumed_prefix": start0,
            },
        }
        # qi-cost/1 (ISSUE 17): an unfused solve occupied the whole device —
        # lanes = the padded lane axis, one window per candidate row.  A
        # wrong cost must become a dropped cost (cost.attribute degrade);
        # the total still counts so attributed_pct honestly reflects the gap.
        try:
            fault_point("cost.attribute")
            from quorum_intersection_tpu.cost import solo_cost
            stats["cost"] = solo_cost(
                circuit.n, candidates,
                macs_per_candidate_row(circuit.n, circuit.n_units,
                                       circuit.depth),
                seconds,
            )
            rec.add("cost.lane_windows_attributed",
                    int(stats["cost"]["lane_windows"]))
            rec.add("cost.lane_windows_total", circuit.n * candidates)
        except (FaultInjected, OSError) as exc:
            rec.add("cost.attribute_errors")
            rec.event("cost.degraded", site="sweep.solo", error=repr(exc))
            rec.add("cost.lane_windows_total", circuit.n * candidates)
        if plan is not None and plan.windows:
            # The checkable pruned-block ledger: enough for the stdlib
            # checker to rebuild every block's maximal candidate in graph
            # space and re-run its own greatest fixpoint on it.
            stats["cert"]["pruned_blocks"] = {
                "k": plan.block_bits,
                "rule": PRUNE_RULE_ID,
                "prefixes": list(plan.prefixes),
            }
            stats["cert"]["enumeration"] = {
                "fixed": graph.node_ids[nodes[0]],
                "bit_nodes": [graph.node_ids[v] for v in nodes[1:]],
            }
        if order_meta is not None:
            # Rank-order provenance: cert.py lifts this into
            # provenance.order on every certificate of this solve.
            stats["order"] = dict(order_meta)
        if engine == "bitset":
            # qi-sparse provenance (cert.py lifts to provenance.encoding):
            # stamped ONLY on the bitset path, so dense certs stay
            # byte-identical to every release before this encoding existed.
            stats["encoding"] = "bitset"
        rec.gauge("sweep.candidates_per_sec", round(throughput.per_second, 1))
        # Registry definition (docs/OBSERVABILITY.md): windows_enumerated /
        # window_space of a FULL sweep — 1.0 under pure brute force, driven
        # down only by real pruning wins.  Early-hit (false-verdict) and
        # checkpoint-resumed drives legitimately enumerate less than the
        # space for reasons that are not pruning, so they must not publish
        # a ratio the trend gate would read as a win.
        if total and not found and not start0:
            rec.gauge(
                "cert.enumeration_ratio",
                round(candidates / total, 6),
            )
        if start0:
            # Resume provenance: lets tooling prove a run actually skipped a
            # checkpointed prefix (tools/wide_run.py kill/resume ledger).
            stats["resumed_from"] = start0
        if padded_from is not None:
            # Warm-start provenance: the canonical shape this run compiled
            # under (and what it would have compiled without padding).
            stats["padded_from"] = list(padded_from)
            stats["padded_shape"] = [circuit.n, circuit.n_units]
        # The XLA-compile bucket alone (trace/lowering excluded): exactly
        # what the persistent compilation cache elides on a warm run — the
        # warm-start acceptance criterion pins warm <= 10% of cold on it.
        stats["xla_compile_seconds"] = round(
            sum(
                fn.xla_compile_seconds()
                for fn in dispatchers.values()
                if hasattr(fn, "xla_compile_seconds")
            ),
            4,
        )
        rec.gauge("sweep.xla_compile_seconds", stats["xla_compile_seconds"])
        stats.update(self._time_breakdown(
            t0_monotonic, t_first_dispatch, compile_seconds, drain_log, compile_log
        ))
        if async_compile["seconds"]:
            # Overlapped with device work — reported separately, never
            # subtracted from drain intervals like the blocking compiles.
            stats["async_compile_seconds"] = round(async_compile["seconds"], 3)
        if not found:
            if self.checkpoint is not None:
                self.checkpoint.clear()
            return SccCheckResult(intersects=True, stats=stats)

        # Decode the winning subset and rebuild the witness pair on the host.
        subset = [nodes[1 + j] for j in range(bits) if (first_hit >> j) & 1]
        q, disjoint = self._witness(graph, nodes, subset, scope_to_scc)
        if not q or not disjoint:
            # Defense in depth: the host recheck uses the exact reference
            # semantics, so an empty member here means the device decode lied
            # (e.g. an index-wrap bug) — fail loudly, never flip the verdict.
            raise RuntimeError(
                f"sweep decode error: device hit index {first_hit} failed the "
                f"host witness recheck (|q|={len(q)}, |disjoint|={len(disjoint)})"
            )
        if self.checkpoint is not None:
            self.checkpoint.clear()
        stats["hit_index"] = first_hit
        # Reference witness convention (cpp:372-373): q1 = the probe result,
        # q2 = the enumerated quorum.
        return SccCheckResult(intersects=False, q1=disjoint, q2=q, stats=stats)

    # ---- lane-packed multi-problem sweep (ISSUE 5 tentpole) -------------

    def _prepare_job(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        scope_to_scc: bool,
    ) -> _SweepJob:
        """Restrict one problem onto its SCC for packing.  Restriction runs
        UNCONDITIONALLY (even at circuit.n == |scc|): it guarantees the
        root-unit layout and scc-order lanes pack_circuits requires, and
        folds all outside availability into thresholds so the packed block
        needs no frozen row."""
        if circuit is None:
            raise ValueError("sweep backend requires the encoded circuit")
        scc = list(scc)
        order_meta: Optional[Dict[str, object]] = None
        if self._order_mode() == "rank" and len(scc) > 2:
            # Same rank-order permutation as the unpacked driver, applied
            # before restriction so the packed lanes inherit it.
            scc, order_meta = rank_order_nodes(graph, scc)
        s = len(scc)
        bits = s - 1
        if bits > self.max_bits:
            raise SccTooLargeError(
                f"|scc|={s} exceeds sweep width {self.max_bits}+1; use the frontier backend"
            )
        scoped_c, q6_c = restrict_circuit_pair(circuit, scc)
        return _SweepJob(
            graph=graph,
            nodes=list(scc),
            scope_to_scc=scope_to_scc,
            circuit=scoped_c,
            circuit_d=None if scope_to_scc else q6_c,
            bits=bits,
            total=1 << bits if bits > 0 else 1,
            order_meta=order_meta,
        )

    def check_sccs(
        self,
        jobs: Sequence[Tuple[TrustGraph, Optional[Circuit], List[int]]],
        *,
        scope_to_scc: bool = False,
        cancels: Optional[Sequence[Optional[CancelToken]]] = None,
        origins: Optional[Sequence[str]] = None,
    ) -> List[SccCheckResult]:
        """Batched multi-problem sweep with LANE PACKING: K independent
        problems fuse into one block-diagonal circuit whose padded lane
        tile they fill together (encode.pack_circuits), so one device
        program resolves up to K verdicts per matmul instead of wasting
        the XLA lane padding on one.

        Packs fill from the three sources the dispatch loop sees, in
        order: whole problems first (queued snapshot requests via
        pipeline.check_many, and multiple quorum-bearing SCCs of one
        snapshot, arrive here as separate jobs), then any spare lanes are
        filled with extra in-flight WINDOWS of the packed jobs' own
        enumerations (ascending contiguous ranges — _PackGroup).  Verdict,
        witness, and first-hit index are byte-identical to running
        :meth:`check_scc` per job (tests/test_lane_packing.py pins it).

        Jobs the packed path cannot serve stay on the plain sweep: wide
        (> 2^lo_bits) enumerations, and any run carrying a mesh or a
        checkpoint (packing has no sharded program and no multi-problem
        checkpoint format).  The ``sweep.pack`` fault point fires before
        any pack is built — injected failures surface here and the auto
        router's DegradationLadder degrades to the unpacked sweep.
        """
        jobs = list(jobs)
        results: List[Optional[SccCheckResult]] = [None] * len(jobs)
        prepared: Dict[int, _SweepJob] = {}
        if self.mesh is None and self.checkpoint is None:
            if self.cancel is not None and self.cancel.cancelled:
                raise SearchCancelled(
                    f"packed sweep cancelled before setup ({len(jobs)} jobs)"
                )
            packable: List[int] = []
            for i, (graph, circuit, scc) in enumerate(jobs):
                if len(scc) - 1 > min(self.lo_bits, LO_BITS):
                    continue  # wide two-level enumerations stay unpacked
                if (
                    cancels is not None and cancels[i] is not None
                    and cancels[i].cancelled
                ):
                    continue  # already dead: never let it occupy lanes
                prepared[i] = self._prepare_job(graph, circuit, scc, scope_to_scc)
                packable.append(i)
            if packable:
                # Injectable pack boundary (utils/faults.py sweep.pack):
                # `error` simulates a packing failure — routed through the
                # auto ladder this degrades to the unpacked per-problem
                # sweep with verdicts unchanged.
                fault_point("sweep.pack")
                from quorum_intersection_tpu.utils.compile_cache import (
                    enable_compilation_cache,
                )

                enable_compilation_cache()
                for pack_ixs in plan_packs(
                    [prepared[i].circuit.n for i in packable]
                ):
                    members = [prepared[packable[ix]] for ix in pack_ixs]
                    self._run_pack(
                        members,
                        cancels=(
                            [cancels[packable[ix]] for ix in pack_ixs]
                            if cancels is not None else None
                        ),
                        origins=(
                            [origins[packable[ix]] for ix in pack_ixs]
                            if origins is not None else None
                        ),
                    )
                    for ix in pack_ixs:
                        results[packable[ix]] = prepared[packable[ix]].result
        for i, (graph, circuit, scc) in enumerate(jobs):
            if results[i] is None:
                tok = cancels[i] if cancels is not None else None
                if tok is not None and tok.cancelled:
                    # The request behind this leftover job is already dead
                    # (deadline/client abort): never burn the NP-hard sweep
                    # on it.  Its whole window space is CANCELLED coverage.
                    results[i] = self._cancelled_result(scc)
                else:
                    results[i] = self.check_scc(
                        graph, circuit, scc, scope_to_scc=scope_to_scc
                    )
        return [res for res in results if res is not None]

    def _cancelled_result(self, scc: Sequence[int]) -> SccCheckResult:
        """A per-job-cancelled job's result: no verdict claim, the full
        window space booked as cancelled coverage (the ledger still sums
        exactly: enumerated 0 + pruned 0 + skipped 0 + cancelled = 2^bits)."""
        total = 1 << max(len(scc) - 1, 0)
        get_run_record().add("cert.windows_cancelled", total)
        return SccCheckResult(intersects=False, stats={
            "backend": self.name,
            "cancelled": True,
            "candidates_checked": 0,
            "enumeration_total": total,
            "cert": {
                "window_space": total,
                "windows_enumerated": 0,
                "windows_pruned_guard": 0,
                "windows_skipped_pack_fill": 0,
                "windows_cancelled": total,
            },
        })

    def _run_pack(
        self,
        jobs: List[_SweepJob],
        cancels: Optional[Sequence[Optional[CancelToken]]] = None,
        origins: Optional[Sequence[str]] = None,
    ) -> None:
        """Sweep one pack of jobs to verdicts (stored on each job).

        ``cancels``/``origins`` (qi-fuse) are job-aligned: a tripped
        per-job token retires THAT job's lane groups via the dead-lane
        machinery (the remainder lands on its ledger as cancelled
        coverage) without touching the co-packed jobs, and origins stamp
        pack provenance per lane group (fuse.* telemetry)."""
        t0 = time.perf_counter()
        rec = get_run_record()
        n_jobs = len(jobs)
        slot = ladder_up(max(j.circuit.n for j in jobs))
        capacity = max(1, LANE_TILE // slot)

        # Block-guard pruning per packed job (ISSUE 10): each member's guard
        # runs against its OWN restricted circuit (the packed block shares
        # no windows across groups), and any failure degrades the whole
        # pack to unpruned enumeration in place — same contract as the
        # unpacked driver's sweep.prune fault point.
        prune_plans: List[Optional[_PrunePlan]] = [None] * n_jobs
        if self._prune_enabled():
            try:
                for jix, job in enumerate(jobs):
                    # The guard speaks the drive's encoding (ISSUE 20): a
                    # bitset pack proves its blocks with the bitset guard
                    # (resolved per member circuit — a multi-edge member
                    # falls back to the dense guard; either guard's cert is
                    # checker-valid, the prune rule is encoding-agnostic).
                    guard_engine = "xla"
                    if self._engine_mode() == "bitset":
                        guard_engine = resolve_engine(
                            "bitset", mesh=False, wide=False,
                            restricted=False, circuit=job.circuit,
                        ).resolved
                    prune_plans[jix] = self._plan_pruning(
                        job.circuit,
                        np.arange(1, job.circuit.n, dtype=np.int64),
                        job.bits, job.total, 0, guard_engine,
                    )
            except SearchCancelled:
                raise
            # qi-lint: allow(degrade-via-ladder) — in-place optimization degrade
            except Exception as exc:  # noqa: BLE001
                prune_plans = [None] * n_jobs
                rec.add("sweep.prune_errors")
                rec.event("sweep.prune_degraded", cause=str(exc), packed=True)
                log.warning(
                    "packed sweep pruning degraded to unpruned (%s)", exc
                )
        self._emit_prune_telemetry(
            prune_plans, [j.total for j in jobs], packed=True
        )

        # Spare lanes become extra windows of the jobs with the largest
        # per-window enumerations (pack source (a): multiple in-flight
        # windows of the current SCC) — never split below ~two blocks per
        # window, or the extra lanes just re-sweep each other's overshoot.
        est_batch = self.batch if self.batch is not None else _auto_batch(
            capacity * slot
        )
        windows = [1] * n_jobs
        spare = capacity - n_jobs
        while spare > 0:
            j = max(range(n_jobs), key=lambda x: jobs[x].total / windows[x])
            if jobs[j].total / windows[j] < 2 * est_batch:
                break
            windows[j] += 1
            spare -= 1

        groups: List[_PackGroup] = []
        members: List[Tuple[Circuit, Optional[Circuit]]] = []
        for j, job in enumerate(jobs):
            w = windows[j]
            bounds = [job.total * t // w for t in range(w + 1)]
            for t in range(w):
                groups.append(_PackGroup(job=j, lo=bounds[t], hi=bounds[t + 1]))
                members.append((job.circuit, job.circuit_d))
        packed = pack_circuits(
            members,
            origins=(
                [origins[g.job] for g in groups] if origins is not None
                else None
            ),
        )
        pos, scc_mask, lane_group, group_ind = packed.decode_tables()
        k = packed.groups

        batch = self.batch if self.batch is not None else _auto_batch(packed.circuit.n)
        # Never dispatch blocks beyond the largest window's work (the
        # unpacked driver's min(batch, lo_total) discipline) — a small pack
        # must not burn a 2^19-row program on a 2^11 enumeration.
        batch = max(1, min(
            batch,
            max(g.hi - g.lo for g in groups),
        ))
        batch = clamp_batch_to_index_ceiling(batch, max(j.total for j in jobs))
        live_plans = [p for p in prune_plans if p is not None and p.windows]
        if live_plans:
            # Same base-program/prune-granularity alignment as the unpacked
            # drive: a surviving fragment must not burn a full-size program.
            batch = min(
                batch,
                max(1 << min(p.block_bits for p in live_plans), 512),
            )
        resolution = resolve_engine(
            self._engine_mode(), mesh=False, wide=False, restricted=False,
            circuit=packed.circuit,
        )
        _emit_engine_resolution(resolution, packed=True)
        pack_engine = resolution.resolved
        make_dispatch = None
        if pack_engine == "bitset":
            # Packed bitset drive runs the fused Pallas twin (same
            # per-group min-hit contract as the packed dense path).
            try:
                fault_point("sweep.bitset")
                from quorum_intersection_tpu.backends.tpu import pallas_sweep

                batch, _ = pallas_sweep.plan_batch(batch)
                make_dispatch = pallas_sweep.pallas_bitset_program_factory(
                    packed.circuit, packed.circuit_d, pos, scc_mask,
                    lane_group, group_ind, batch,
                )
            except SearchCancelled:
                raise
            # qi-lint: allow(degrade-via-ladder) — in-place encoding degrade
            except Exception as exc:  # noqa: BLE001
                rec.add("sweep.bitset_errors")
                rec.event("sweep.bitset_degraded", cause=str(exc), packed=True)
                log.warning(
                    "packed bitset sweep degraded to the dense encoding (%s)",
                    exc,
                )
                pack_engine = "xla"
        if make_dispatch is None and pack_engine == "pallas":
            from quorum_intersection_tpu.backends.tpu import pallas_sweep

            batch, _ = pallas_sweep.plan_batch(batch)
            make_dispatch = pallas_sweep.pallas_packed_program_factory(
                packed.circuit, packed.circuit_d, pos, scc_mask, lane_group,
                group_ind, batch,
            )
        elif make_dispatch is None:
            from quorum_intersection_tpu.backends.tpu.kernels import (
                packed_sweep_program_factory,
            )

            make_dispatch = packed_sweep_program_factory(
                packed.circuit, packed.circuit_d, pos, scc_mask, lane_group,
                group_ind, batch,
            )

        rec.add("sweep.packs_dispatched")
        rec.gauge("sweep.pack_fill_pct", round(packed.fill_pct, 2))
        rec.event(
            "sweep.packed",
            jobs=n_jobs, groups=k, slot=packed.slot, lanes=packed.circuit.n,
            fill_pct=round(packed.fill_pct, 2), engine=pack_engine,
        )
        if origins is not None:
            # qi-fuse provenance telemetry: how many verdict-bearing lanes
            # this pack carries, and how many of them share a tile with a
            # DIFFERENT request — the cross-request fusion meter.
            rec.add("fuse.packs_formed")
            rec.add("fuse.pack_lanes", sum(packed.sizes))
            rec.gauge("fuse.fill_pct", round(packed.fill_pct, 2))
            if packed.origin_count > 1:
                rec.add("fuse.cross_request_lanes", sum(packed.sizes))
        log.debug(
            "packed sweep: %d jobs in %d lane groups (slot %d, %d lanes, "
            "%.1f%% fill, engine %s)",
            n_jobs, k, packed.slot, packed.circuit.n, packed.fill_pct,
            pack_engine,
        )

        dispatchers: Dict[int, object] = {}

        def dispatch(starts: np.ndarray, spc: int):
            fault_point("sweep.dispatch")
            fn = dispatchers.get(spc)
            if fn is None:
                fault_point("sweep.compile")
                fn = dispatchers[spc] = make_dispatch(spc)
            return fn(starts)

        unresolved = set(range(n_jobs))
        nxt = [g.lo for g in groups]
        # Per-group drained high-water position (qi-cert): lets the skip
        # and cancel accounting compute exactly how much of a window was
        # never swept — minus any guard-pruned windows inside it, which the
        # pruned ledger term claims instead.
        pos = [g.lo for g in groups]

        def pruned_in(job_ix: int, lo: int, hi: int) -> int:
            p = prune_plans[job_ix]
            return p.overlap(lo, hi) if p is not None else 0

        for gix, g in enumerate(groups):
            p = prune_plans[g.job]
            if p is not None:
                nxt[gix] = p.skip(nxt[gix])
                if nxt[gix] >= g.hi:
                    # The whole window is guard-pruned: nothing to sweep.
                    g.done = True
        inflight: "deque" = deque()
        pack_rows = 0
        ramp = (1, 8, 64)
        spc_ix = 0
        depth_cap = max(1, min(self.max_inflight, 8))

        def check_cancel() -> None:
            if self.cancel is not None and self.cancel.cancelled:
                rec.add("sweep.windows_cancelled", len(inflight))
                # qi-cert: the unswept remainder of every live window is
                # CANCELLED coverage, exactly as in the unpacked drive.
                rec.add("cert.windows_cancelled", sum(
                    max(g.hi - pos[i], 0) - pruned_in(g.job, pos[i], g.hi)
                    for i, g in enumerate(groups) if not g.done
                ))
                rec.event(
                    "sweep.cancelled", packed=True,
                    windows_dropped=len(inflight),
                    jobs_unresolved=len(unresolved),
                )
                raise SearchCancelled(
                    f"packed sweep cancelled ({len(unresolved)} of "
                    f"{n_jobs} jobs unresolved)"
                )

        def retire_job(j: int) -> None:
            """qi-fuse: THIS job's request died (its own deadline/client
            abort) — freeze its lane groups via the dead-lane machinery and
            book the unswept remainder as CANCELLED coverage on its ledger
            alone.  Co-packed jobs keep sweeping; in-flight programs still
            carry the dead lanes, but pos[] never advances past what was
            actually drained, so the accounting stays exact."""
            dropped = 0
            for gix, g in enumerate(groups):
                if g.job != j or g.done:
                    continue
                dropped += max(g.hi - pos[gix], 0) - pruned_in(
                    j, pos[gix], g.hi
                )
                g.done = True
            jobs[j].cancelled = True
            jobs[j].cancelled_windows = dropped
            jobs[j].resolved = True
            unresolved.discard(j)
            rec.add("sweep.windows_cancelled", dropped)
            rec.add("cert.windows_cancelled", dropped)
            rec.event(
                "sweep.cancelled", packed=True,
                windows_dropped=dropped,
                jobs_unresolved=len(unresolved),
            )

        def check_job_cancels() -> None:
            if cancels is None:
                return
            for j in list(unresolved):
                tok = cancels[j]
                if tok is not None and tok.cancelled:
                    retire_job(j)

        def all_dispatched() -> bool:
            return all(
                g.done or nxt[i] >= g.hi for i, g in enumerate(groups)
            )

        def resolve_jobs() -> None:
            """Scan each job's ascending windows: its first hit is the hit
            of the lowest window whose every predecessor swept clean —
            the unpacked driver's FIFO first-hit order, group-wise."""
            for j in list(unresolved):
                wins = [g for g in groups if g.job == j]
                verdict: Optional[bool] = None
                for g in wins:
                    if g.hit is not None:
                        jobs[j].first_hit = g.hit
                        verdict = False
                        break
                    if not g.done:
                        break
                else:
                    verdict = True
                if verdict is None:
                    continue
                jobs[j].intersects = verdict
                jobs[j].resolved = True
                unresolved.discard(j)
                for g in wins:
                    g.done = True

        def drain_one() -> None:
            starts_snap, coverage, handle = inflight.popleft()
            hits = np.asarray(handle)
            drained = 0
            for gix, g in enumerate(groups):
                if g.done:
                    continue
                s0 = int(starts_snap[gix])
                if s0 >= g.hi:
                    continue  # frozen lane: nothing new covered
                top = min(s0 + coverage, g.hi)
                swept = (top - s0) - pruned_in(g.job, s0, top)
                jobs[g.job].candidates += swept
                pos[gix] = max(pos[gix], top)
                drained += swept
                h = int(hits[gix])
                if h < g.hi:
                    # In-range hit.  Overshoot rows (>= hi, aliased decode
                    # duplicates) are masked here on the host: the window's
                    # own range ends at hi, and whatever lies beyond belongs
                    # to the NEXT ascending window, which sweeps it itself.
                    g.hit = h
                    g.done = True
                    # Later windows of the same job can only yield LARGER
                    # indices: stop burning lanes on them.  Their unswept
                    # remainder is SKIPPED-BY-PACK-FILL coverage (qi-cert):
                    # windows that only existed because spare pack lanes
                    # split the enumeration, retired by a lower window's
                    # hit — counted exactly, per job, with any guard-pruned
                    # windows inside it staying on the pruned ledger term.
                    for g2ix, g2 in enumerate(groups):
                        if g2.job == g.job and g2.lo > g.lo and not g2.done:
                            skip = max(g2.hi - pos[g2ix], 0) - pruned_in(
                                g.job, pos[g2ix], g2.hi
                            )
                            jobs[g.job].skipped += max(skip, 0)
                            rec.add(
                                "cert.windows_skipped_pack_fill",
                                max(skip, 0),
                            )
                            g2.done = True
                elif top >= g.hi or pruned_in(g.job, top, g.hi) == g.hi - top:
                    # Fully drained — or everything left of this window is
                    # guard-pruned tail no program will ever be dispatched
                    # for (nxt skipped past it).
                    g.done = True
            rec.add("cert.windows_enumerated", drained)
            resolve_jobs()

        # A job whose every window was guard-pruned resolves before any
        # dispatch (its groups were marked done at init; pruned blocks hold
        # no hits, so "nothing left to sweep" IS the clean verdict).
        resolve_jobs()

        # The whole pack drive is one span (qi-trace), and the live
        # endpoint's /healthz reads the in-flight count from the gauge
        # bracketing it — a scrape mid-pack sees packs_in_flight=1.
        rec.gauge("sweep.packs_in_flight", 1)
        try:
            with rec.span(
                "sweep.pack", jobs=n_jobs, groups=k, slot=packed.slot,
                lanes=packed.circuit.n,
                fill_pct=round(packed.fill_pct, 2),
            ) as pack_span:
                while unresolved:
                    check_cancel()
                    check_job_cancels()
                    if not unresolved:
                        break
                    # Same injectable window boundary as the unpacked loop.
                    fault_point("sweep.window")
                    if not all_dispatched():
                        rem = max(
                            (g.hi - nxt[i] for i, g in enumerate(groups) if not g.done),
                            default=0,
                        )
                        while spc_ix + 1 < len(ramp) and rem >= ramp[spc_ix + 1] * batch * 2:
                            spc_ix += 1
                        spc = ramp[spc_ix]
                        if rem < spc * batch:
                            # Tail: the smallest program covering the remainder,
                            # preferring an already-compiled shape (the unpacked
                            # driver's chunk-tail discipline) — never burn a
                            # 64x-batch program on a few surviving rows.
                            fits = [r for r in ramp if r * batch >= rem]
                            compiled_ok = [r for r in fits if r in dispatchers]
                            spc = min(compiled_ok) if compiled_ok else min(fits)
                        coverage = spc * batch
                        snap = np.asarray(nxt, dtype=np.int32)
                        inflight.append((snap, coverage, dispatch(snap, spc)))
                        pack_rows += coverage
                        rec.add("sweep.pack_windows")
                        for i, g in enumerate(groups):
                            if not g.done and nxt[i] < g.hi:
                                nxt[i] += coverage
                                p = prune_plans[g.job]
                                if p is not None and nxt[i] < g.hi:
                                    # Hop the next dispatch over any guard-
                                    # pruned run ("remaining work" is no
                                    # longer contiguous under pruning).
                                    nxt[i] = p.skip(nxt[i])
                        if len(inflight) >= depth_cap:
                            drain_one()
                    elif inflight:
                        drain_one()
                    else:
                        # Defense in depth: every group drained yet a job is still
                        # unresolved would mean the accounting above lied — fail
                        # loudly, never spin.
                        raise RuntimeError(
                            f"packed sweep drained all lane groups with "
                            f"{len(unresolved)} job(s) unresolved"
                        )
                pack_span.set(rows_dispatched=pack_rows)
        finally:
            rec.gauge("sweep.packs_in_flight", 0)

        seconds = time.perf_counter() - t0
        xla_s = sum(
            fn.xla_compile_seconds()
            for fn in dispatchers.values()
            if hasattr(fn, "xla_compile_seconds")
        )
        pack_stats = {
            "packed": True,
            "pack_jobs": n_jobs,
            "pack_groups": k,
            "pack_slot": packed.slot,
            "pack_shape": [packed.circuit.n, packed.circuit.n_units],
            "pack_fill_pct": round(packed.fill_pct, 2),
            "pack_rows_dispatched": pack_rows,
            "pack_macs_per_candidate_row": macs_per_candidate_row(
                packed.circuit.n, packed.circuit.n_units, packed.circuit.depth
            ),
            "pack_engine": pack_engine,
            "pack_seconds": round(seconds, 4),
            "xla_compile_seconds": round(xla_s, 4),
        }
        if pack_engine == "bitset":
            # qi-sparse provenance, merged into every member job's stats
            # (cert.py lifts to provenance.encoding); dense packs stay
            # unstamped so their certs are byte-identical to prior releases.
            pack_stats["encoding"] = "bitset"
        # qi-cost/1 (ISSUE 17): book this pack's device work to its member
        # jobs by integer lane share (pad included).  The conserved quantity
        # is lane·windows: per-job attribution sums to the pack total
        # EXACTLY (asserted inside attribute_pack).  A cancelled job keeps
        # its lane groups (retire_job never reassigns ownership), so dead
        # lanes book to the request that died — and to nobody else.  A
        # wrong cost degrades to a dropped cost; only the total counter
        # moves then, so attributed_pct honestly shows the gap.
        pack_costs: Dict[object, Dict[str, object]] = {}
        pack_lane_windows = packed.circuit.n * pack_rows
        try:
            fault_point("cost.attribute")
            from quorum_intersection_tpu.cost import attribute_pack
            pack_costs = attribute_pack(
                [g.job for g in groups], packed.circuit.n, packed.slot,
                pack_rows, pack_stats["pack_macs_per_candidate_row"],
                seconds,
            )
            rec.add("cost.lane_windows_attributed", pack_lane_windows)
            rec.add("cost.lane_windows_total", pack_lane_windows)
        except (FaultInjected, OSError) as exc:
            pack_costs = {}
            rec.add("cost.attribute_errors")
            rec.event("cost.degraded", site="sweep.pack", error=repr(exc))
            rec.add("cost.lane_windows_total", pack_lane_windows)
        # Same registry rule as the unpacked drive: only full-coverage
        # (no-hit) jobs speak for brute-force enumeration; a hit job's
        # retired pack-fill windows are early-exit savings, not pruning.
        clean_jobs = [
            j for j in jobs if j.first_hit is None and not j.cancelled
        ]
        enum_all = sum(j.candidates for j in clean_jobs)
        total_all = sum(j.total for j in clean_jobs)
        if total_all:
            rec.gauge(
                "cert.enumeration_ratio", round(enum_all / total_all, 6)
            )
        for jix, job in enumerate(jobs):
            job_plan = prune_plans[jix]
            stats = {
                "backend": self.name,
                "candidates_checked": job.candidates,
                "enumeration_total": job.total,
                "seconds": seconds,
                # qi-cert ledger, per packed job: a clean (true-verdict)
                # job's windows partition its enumeration exactly, so
                # enumerated + pruned sums to the window space; a hit job's
                # skipped count is the pack-fill windows its hit retired.
                "cert": {
                    "window_space": job.total,
                    "windows_enumerated": job.candidates,
                    "windows_pruned_guard": (
                        job_plan.windows if job_plan is not None else 0
                    ),
                    "windows_skipped_pack_fill": job.skipped,
                    "windows_cancelled": 0,
                },
                **pack_stats,
            }
            if job_plan is not None and job_plan.windows:
                stats["cert"]["pruned_blocks"] = {
                    "k": job_plan.block_bits,
                    "rule": PRUNE_RULE_ID,
                    "prefixes": list(job_plan.prefixes),
                }
                stats["cert"]["enumeration"] = {
                    "fixed": job.graph.node_ids[job.nodes[0]],
                    "bit_nodes": [
                        job.graph.node_ids[v] for v in job.nodes[1:]
                    ],
                }
            if job.order_meta is not None:
                stats["order"] = dict(job.order_meta)
            job_cost = pack_costs.get(jix)
            if job_cost is not None:
                stats["cost"] = dict(job_cost)
            if origins is not None:
                stats["pack_origin"] = origins[jix]
            if job.cancelled:
                # qi-fuse: the request behind this job died mid-pack.  Its
                # ledger keeps the exact partition (enumerated before death
                # + pruned + skipped + cancelled == window space); no
                # verdict, no witness recheck.
                stats["cancelled"] = True
                stats["cert"]["windows_cancelled"] = job.cancelled_windows
                job.result = SccCheckResult(intersects=False, stats=stats)
                continue
            if job.first_hit is None:
                job.result = SccCheckResult(intersects=True, stats=stats)
                continue
            subset = [
                job.nodes[1 + b]
                for b in range(job.bits)
                if (job.first_hit >> b) & 1
            ]
            q, disjoint = self._witness(
                job.graph, job.nodes, subset, job.scope_to_scc
            )
            if not q or not disjoint:
                # Same defense in depth as the unpacked driver: the host
                # recheck uses the exact reference semantics — an empty
                # member means the packed decode lied; fail loudly.
                raise RuntimeError(
                    f"packed sweep decode error: hit index {job.first_hit} "
                    f"failed the host witness recheck "
                    f"(|q|={len(q)}, |disjoint|={len(disjoint)})"
                )
            stats["hit_index"] = job.first_hit
            job.result = SccCheckResult(
                intersects=False, q1=disjoint, q2=q, stats=stats
            )

    @staticmethod
    def _time_breakdown(t0, t_first_dispatch, compile_seconds, drain_log,
                        compile_log=()) -> dict:
        """Wall-clock decomposition for §next-2: setup (constants upload +
        program factory), synchronous compiles, and a per-ramp-level drain
        profile with the steady-state rate = throughput at the largest
        program size actually reached (drain-to-drain elapsed, so pipelined
        dispatch latency is inside, not hidden).  Compile time landing
        inside a drain interval is subtracted from that interval so it is
        never double-counted into a level's rate."""
        out = {"compile_seconds": round(compile_seconds, 3)}
        if t_first_dispatch is not None:
            out["setup_seconds"] = round(t_first_dispatch - t0, 3)
        if not drain_log:
            return out
        profile = {}
        prev_t = t_first_dispatch if t_first_dispatch is not None else drain_log[0][0]
        for t, cand, spc in drain_log:
            interval = t - prev_t
            interval -= sum(dur for te, dur in compile_log if prev_t < te <= t)
            cand_sum, sec_sum = profile.get(spc, (0, 0.0))
            profile[spc] = (cand_sum + cand, sec_sum + max(interval, 0.0))
            prev_t = t
        out["ramp_profile"] = {
            str(spc): {
                "candidates": cand,
                "seconds": round(sec, 3),
                "rate": round(cand / sec, 1) if sec > 0 else None,
            }
            for spc, (cand, sec) in sorted(profile.items())
        }
        top = max(profile)
        cand, sec = profile[top]
        if sec > 0:
            out["steady_rate"] = round(cand / sec, 1)
            out["steady_level"] = top
        return out

    # ---- sharded step ----------------------------------------------------

    def _build_sharded_step(self, circuit, bit_nodes, scc_mask, frozen, batch,
                            circuit_d=None):
        """Mesh-sharded sweep step: each device takes a contiguous sub-block
        (``steps_per_call`` of them per program), hit indices combine with one
        pmin collective.  Returns ``(base_block, make_dispatch)`` matching the
        single-device path's contract."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from quorum_intersection_tpu.backends.tpu.kernels import (
            sweep_constants,
            sweep_step,
        )
        from quorum_intersection_tpu.parallel.mesh import P, shard_map_fn

        mesh = self.mesh
        axis = mesh.axis_names[0]
        n_dev = mesh.devices.size
        per_dev = max(batch // n_dev, 1)
        base_block = per_dev * n_dev

        arrays, pos_j, scc_mask_j, frozen_j = sweep_constants(
            circuit, bit_nodes, scc_mask, frozen
        )
        from quorum_intersection_tpu.backends.tpu.kernels import CircuitArrays

        arrays_d = None if circuit_d is None else CircuitArrays(circuit_d)
        zeros_hi = jnp.zeros((circuit.n,), dtype=arrays.dtype)

        def make_dispatch(steps_per_call: int):
            def shard_fn(start, hi_mask):
                rank = lax.axis_index(axis)

                # Device r takes sub-block r of every consecutive block, so
                # the program's coverage stays one contiguous index range.
                def block_min_hit(block_start):
                    my_start = block_start + rank.astype(jnp.int32) * per_dev
                    hit, _ = sweep_step(
                        arrays, my_start, per_dev, pos_j, scc_mask_j, frozen_j,
                        hi_mask, arrays_d=arrays_d,
                    )
                    idx = my_start + jnp.arange(per_dev, dtype=jnp.int32)
                    return jnp.where(hit, idx, jnp.int32(INT32_MAX)).min()

                def body(i, best):
                    return jnp.minimum(best, block_min_hit(start + i * base_block))

                # Seed the carry from `rank` so it is manual-axis-varying —
                # a literal init would be replicated and trip the fori_loop
                # carry-type check under shard_map (cf. kernels.fixpoint).
                init = jnp.int32(INT32_MAX) + rank * jnp.int32(0)
                local = lax.fori_loop(0, steps_per_call, body, init)
                return lax.pmin(local, axis)

            sharded = jax.jit(
                shard_map_fn(shard_fn, mesh, in_specs=(P(), P()), out_specs=P())
            )

            # Same AOT ramp-jump hook as the single-device factory; dispatch
            # is asynchronous — the caller syncs via int(handle).
            from quorum_intersection_tpu.backends.tpu.kernels import make_aot_dispatch

            return make_aot_dispatch(sharded, zeros_hi, arrays.cast)

        return base_block, make_dispatch
