"""JAX/TPU backends: batched threshold-circuit kernels, exhaustive candidate
sweep, and the hybrid host-frontier search."""
