"""JAX/TPU backends: batched threshold-circuit kernels, exhaustive candidate
sweep, and the device-resident frontier search."""
