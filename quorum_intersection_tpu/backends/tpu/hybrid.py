"""Hybrid search: host branch-and-bound frontier + batched device fixpoints.

For SCCs too large to sweep exhaustively, the reference's pruned enumeration
is the only tractable strategy — but its call tree is serial, evaluating one
``containsQuorum`` fixpoint at a time (SURVEY.md §3.1 hot loops).  This
backend keeps the *same pruning logic* (every prune of cpp:252-400, see
``backends/python_oracle.py`` for the pinned spec) while turning every
fixpoint the search needs into a row of a batched device evaluation:

- the search is an explicit LIFO worklist of (toRemove, dontRemove) states
  (LIFO ≈ depth-first, keeping the frontier from ballooning the way a strict
  BFS would);
- each round pops up to ``batch`` pending fixpoint *requests* — branch
  feasibility checks, minimality probes (|Q|+1 per candidate, cpp:184-198),
  and disjointness probes (cpp:364-378, with the Q6 frozen mask) — pads them
  into one (B, n) matrix, and runs a single jitted batch fixpoint;
- results route back to per-state continuations on the host, which apply the
  prunes and push children.

Enumeration order differs from the serial recursion (branches interleave),
but the enumerated *set* of minimal quorums is identical — the recursion tree
is the same, only traversal order changes — so verdicts match the oracle;
on broken networks the witness pair found first may differ (any disjoint
pair is a valid witness, cpp's own witness already varies with its RNG).

Batch sizes are bucketed to powers of two so XLA compiles a handful of shapes.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from quorum_intersection_tpu.backends.base import SccCheckResult
from quorum_intersection_tpu.backends.python_oracle import find_best_node
from quorum_intersection_tpu.encode.circuit import Circuit
from quorum_intersection_tpu.fbas.graph import TrustGraph
from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("backends.tpu.hybrid")

DEFAULT_BATCH = 1024


@dataclass
class _State:
    """One node of the branch-and-bound tree."""

    to_remove: List[int]
    dont_remove: List[int]
    phase: str = "check_dont"  # check_dont → check_all → branch | minimality → probe
    fq_dont: Optional[List[int]] = None
    minimality_pending: int = 0
    minimality_failed: bool = False


@dataclass
class _Request:
    mask: np.ndarray  # (n,) float32 candidate availability
    frozen: Optional[np.ndarray]  # (n,) float32 or None
    state: _State
    kind: str  # "dont" | "all" | "minimal" | "probe"


class TpuHybridBackend:
    name = "tpu-hybrid"
    needs_circuit = True

    def __init__(self, batch: int = DEFAULT_BATCH) -> None:
        self.batch = batch

    def check_scc(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
    ) -> SccCheckResult:
        if circuit is None:
            raise ValueError("hybrid backend requires the encoded circuit")
        t0 = time.perf_counter()
        n = graph.n
        half = len(scc) // 2
        scc_mask = np.zeros(n, dtype=np.float32)
        scc_mask[scc] = 1.0
        frozen_probe = (
            np.zeros(n, dtype=np.float32) if scope_to_scc else 1.0 - scc_mask
        )

        stats = {"device_batches": 0, "fixpoints": 0, "bnb_states": 0, "minimal_quorums": 0}
        found: Dict[str, Optional[List[int]]] = {"q1": None, "q2": None}

        def mask_of(nodes: List[int]) -> np.ndarray:
            m = np.zeros(n, dtype=np.float32)
            m[nodes] = 1.0
            return m

        # LIFO worklist of pending device requests (LIFO ≈ depth-first).
        pending: List[_Request] = []

        def push_state(state: _State) -> None:
            # Prune 1 (size, cpp:386-391) and prune 2 (empty, cpp:266-268).
            if len(state.dont_remove) > half:
                return
            if not state.to_remove and not state.dont_remove:
                return
            stats["bnb_states"] += 1
            pending.append(
                _Request(mask_of(state.dont_remove), None, state, "dont")
            )

        root = _State(to_remove=list(scc), dont_remove=[])
        push_state(root)

        def handle(req: _Request, result: np.ndarray) -> None:
            """Route one fixpoint result back into the search."""
            state = req.state
            survivors = [v for v in np.nonzero(result)[0].tolist()]

            if req.kind == "dont":
                if survivors:
                    # dontRemove already contains a quorum (cpp:281-291):
                    # minimal iff every single-node removal kills it.
                    state.fq_dont = survivors
                    state.phase = "minimality"
                    members = state.dont_remove
                    state.minimality_pending = len(members)
                    state.minimality_failed = False
                    if not members:
                        return
                    for v in members:
                        m = mask_of(members)
                        m[v] = 0.0
                        pending.append(_Request(m, None, state, "minimal"))
                else:
                    state.phase = "check_all"
                    pending.append(
                        _Request(
                            mask_of(state.dont_remove + state.to_remove),
                            None,
                            state,
                            "all",
                        )
                    )
                return

            if req.kind == "minimal":
                state.minimality_pending -= 1
                if survivors:
                    state.minimality_failed = True
                if state.minimality_pending == 0 and not state.minimality_failed:
                    # Minimal quorum found → disjointness probe (cpp:357-384).
                    stats["minimal_quorums"] += 1
                    probe = np.clip(scc_mask - mask_of(state.dont_remove), 0.0, 1.0)
                    pending.append(_Request(probe, frozen_probe, state, "probe"))
                return

            if req.kind == "probe":
                if survivors:
                    found["q1"] = survivors
                    found["q2"] = list(state.dont_remove)
                return

            if req.kind == "all":
                # Prunes 4-6 then branch (cpp:301-345).
                if not survivors:
                    return
                quorum_set = set(survivors)
                if any(v not in quorum_set for v in state.dont_remove):
                    return
                best = find_best_node(survivors, state.dont_remove, graph, None)
                remaining = quorum_set - set(state.dont_remove)
                if not remaining:
                    return
                new_to_remove = sorted(v for v in remaining if v != best)
                # Include-branch pushed first so the LIFO explores the
                # exclude-branch first, like the serial order (cpp:336, :343).
                push_state(
                    _State(
                        to_remove=list(new_to_remove),
                        dont_remove=state.dont_remove + [best],
                    )
                )
                push_state(
                    _State(to_remove=list(new_to_remove), dont_remove=list(state.dont_remove))
                )
                return

        import jax

        from quorum_intersection_tpu.backends.tpu.kernels import CircuitArrays, fixpoint

        arrays = CircuitArrays(circuit)

        @jax.jit
        def run_jit(avail, frozen):
            return fixpoint(arrays, avail, frozen)

        zeros = np.zeros(n, dtype=np.float32)

        def launch():
            """Pop up to `batch` requests and dispatch them asynchronously."""
            take = pending[-self.batch :]
            del pending[-len(take) :]
            # Bucket the padded batch to powers of two: a handful of compiled
            # shapes instead of one per frontier size.
            b = 1
            while b < len(take):
                b *= 2
            masks = np.zeros((b, n), dtype=np.float32)
            frozens = np.zeros((b, n), dtype=np.float32)
            for i, req in enumerate(take):
                masks[i] = req.mask
                frozens[i] = req.frozen if req.frozen is not None else zeros
            # NB stats count DISPATCHED work: an early witness exit may leave
            # one inflight batch whose results are never drained.
            stats["device_batches"] += 1
            stats["fixpoints"] += len(take)
            log.debug(
                "hybrid batch %d: %d fixpoint rows (padded to %d), backlog %d, "
                "B&B states %d, minimal quorums %d",
                stats["device_batches"], len(take), b, len(pending),
                stats["bnb_states"], stats["minimal_quorums"],
            )
            return take, run_jit(arrays.cast(masks), arrays.cast(frozens))

        # Double-buffered drive: while one batch's results cross the (slow)
        # host↔device link, the next batch from the existing backlog is
        # already on the device.  Handling order across batches is
        # correctness-irrelevant: states' phase transitions are counted, not
        # ordered, and any disjoint pair is a valid witness.
        from collections import deque

        inflight: "deque" = deque()
        while (pending or inflight) and found["q1"] is None:
            while pending and len(inflight) < 2:
                inflight.append(launch())
            take, device_out = inflight.popleft()
            results = np.asarray(device_out) != 0  # sync point
            for i, req in enumerate(take):
                handle(req, results[i])
                if found["q1"] is not None:
                    break

        seconds = time.perf_counter() - t0
        stats.update({"backend": self.name, "seconds": seconds})
        if found["q1"] is not None:
            return SccCheckResult(
                intersects=False, q1=found["q1"], q2=found["q2"], stats=stats
            )
        return SccCheckResult(intersects=True, stats=stats)
