"""Hybrid search: host branch-and-bound frontier + batched device fixpoints.

For SCCs too large to sweep exhaustively, the reference's pruned enumeration
is the only tractable strategy — but its call tree is serial, evaluating one
``containsQuorum`` fixpoint at a time (SURVEY.md §3.1 hot loops).  This
backend keeps the *same pruning logic* (every prune of cpp:252-400, see
``backends/python_oracle.py`` for the pinned spec) while turning every
fixpoint the search needs into a row of a batched device evaluation:

- the search is an explicit LIFO worklist of (toRemove, dontRemove) states
  (LIFO ≈ depth-first, keeping the frontier from ballooning the way a strict
  BFS would);
- each round pops up to ``batch`` pending fixpoint *requests*, pads them
  into one (B, n) matrix, and runs a single jitted batch fixpoint; results
  route back to per-state continuations on the host, which apply the prunes
  and push children.

Three devices-hate-round-trips optimizations (r2, after VERDICT r1 flagged
the un-benchmarked frontier as too narrow to fill batches):

- **speculative dispatch**: a state's ``dont`` and ``all`` fixpoints launch
  together (the ``all`` result is needed whenever ``dont`` holds no quorum —
  the common case), and the disjointness probe launches alongside the
  minimality rows instead of after them; a state needs ~2 device rounds
  instead of ~4, and wasted rows are counted in ``stats["wasted_rows"]``;
- **fixpoint memoization**: the exclude-branch child shares its parent's
  ``dontRemove`` set, so its ``dont`` fixpoint is a guaranteed repeat; a
  host-side mask→result cache short-circuits those rows
  (``stats["cache_hits"]``);
- **deep dispatch pipeline**: several batches stay in flight so the
  host↔device round-trip latency overlaps with device compute (the same
  measured bottleneck the sweep pipeline hides, sweep.py MAX_INFLIGHT).

Enumeration order differs from the serial recursion (branches interleave),
but the enumerated *set* of minimal quorums is identical — the recursion tree
is the same, only traversal order changes — so verdicts match the oracle;
on broken networks the witness pair found first may differ (any disjoint
pair is a valid witness, cpp's own witness already varies with its RNG).

Batch shapes: on accelerators every batch pads to the fixed ``batch`` row
count — exactly one compiled program per problem (padding is free on the MXU
tile); the CPU emulation buckets to powers of two instead, since its cost is
per-row and its compiles are cheap.

Checkpoint/resume (r3): the worklist is explicit, so preemption survival is
a frontier snapshot — every unresolved state has at least one request in the
pending/in-flight queues (phase transitions are synchronous on the host), so
persisting those states' (toRemove, dontRemove) pairs and re-pushing them on
resume reproduces exactly the unfinished part of the search.  Same
fingerprint discipline as the sweep (utils/checkpoint.py).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from quorum_intersection_tpu.backends.base import SccCheckResult
from quorum_intersection_tpu.backends.python_oracle import find_best_node
from quorum_intersection_tpu.encode.circuit import Circuit
from quorum_intersection_tpu.fbas.graph import TrustGraph
from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("backends.tpu.hybrid")

DEFAULT_BATCH = None  # platform-adaptive: BATCH_TPU / BATCH_CPU at check time
# A real chip amortizes its fixed per-program dispatch cost best with big
# row blocks (the sweep's measured lesson, sweep.py module docs); the CPU
# emulation's per-row cost dominates instead, so smaller blocks keep
# latency-to-first-result low without hurting throughput.
BATCH_TPU = 32768
BATCH_CPU = 2048
MAX_INFLIGHT = 4
# Memoized fixpoint results are ~n bytes each; bound the cache so a
# pathological search cannot exhaust host memory.
CACHE_LIMIT = 1 << 17
# Seconds between checkpoint writes (when a checkpoint is attached): the
# frontier snapshot is O(states × n) JSON, so writes are rate-limited
# instead of per-batch.
CHECKPOINT_INTERVAL_S = 5.0


class HybridSearchInterrupted(RuntimeError):
    """Raised by the preemption-simulation hook after writing a checkpoint
    (``interrupt_after_batches``); production runs never see it."""


@dataclass
class _State:
    """One node of the branch-and-bound tree.

    Result routing is order-independent: ``dont``/``all`` results may land
    in either order (they are dispatched speculatively together), as may the
    minimality rows and the speculative disjointness probe.
    """

    to_remove: List[int]
    dont_remove: List[int]
    dont_done: bool = False
    dont_has_quorum: bool = False
    all_done: bool = False
    all_cached: bool = False
    all_survivors: Optional[List[int]] = None
    minimality_pending: int = 0
    minimality_failed: bool = False
    probe_done: bool = False
    probe_cached: bool = False
    probe_wasted: bool = False
    probe_survivors: List[int] = field(default_factory=list)


@dataclass
class _Request:
    mask: np.ndarray  # (n,) float32 candidate availability
    frozen: bool  # True: apply the Q6 frozen mask (disjointness probes)
    state: _State
    kind: str  # "dont" | "all" | "minimal" | "probe"
    cached: bool = False  # served from the memo, no device row occupied


class TpuHybridBackend:
    name = "tpu-hybrid"
    needs_circuit = True

    def __init__(
        self,
        batch: Optional[int] = DEFAULT_BATCH,
        seed: Optional[int] = None,
        randomized: bool = False,
        max_inflight: int = MAX_INFLIGHT,
        checkpoint=None,
        checkpoint_interval_s: Optional[float] = None,
        interrupt_after_batches: Optional[int] = None,
        mesh=None,
    ) -> None:
        self.batch = batch  # None ⇒ platform-adaptive at check time
        self.max_inflight = max_inflight
        # Optional jax.sharding.Mesh: fixpoint rows shard across devices
        # (embarrassingly parallel — no collective; results gather on host).
        self.mesh = mesh
        self.checkpoint = checkpoint  # utils.checkpoint.HybridCheckpoint or None
        if checkpoint_interval_s is None:
            # Env override for ops/tests (e.g. frequent writes under a
            # preemption-heavy scheduler, or a deterministic kill window).
            import os

            checkpoint_interval_s = float(
                os.environ.get("QI_HYBRID_CKPT_INTERVAL_S", CHECKPOINT_INTERVAL_S)
            )
        self.checkpoint_interval_s = checkpoint_interval_s
        # Preemption simulation for kill/resume tests: after draining this
        # many batches, force a checkpoint write and raise.
        self.interrupt_after_batches = interrupt_after_batches
        # Same contract as the host oracles: deterministic tie-break by
        # default, seeded-uniform over the same argmax set otherwise.
        self._rng = random.Random(seed) if (randomized or seed is not None) else None

    def check_scc(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
    ) -> SccCheckResult:
        if circuit is None:
            raise ValueError("hybrid backend requires the encoded circuit")
        from quorum_intersection_tpu.utils.platform import is_cpu_platform

        on_cpu = is_cpu_platform()
        batch = self.batch
        if batch is None:
            batch = BATCH_CPU if on_cpu else BATCH_TPU
        t0 = time.perf_counter()
        n = graph.n
        half = len(scc) // 2
        scc_mask = np.zeros(n, dtype=np.float32)
        scc_mask[scc] = 1.0
        frozen_probe = (
            np.zeros(n, dtype=np.float32) if scope_to_scc else 1.0 - scc_mask
        )

        stats = {
            "device_batches": 0,
            "fixpoints": 0,
            "bnb_states": 0,
            "minimal_quorums": 0,
            "cache_hits": 0,
            "wasted_rows": 0,
        }
        found: Dict[str, Optional[List[int]]] = {"q1": None, "q2": None}

        def mask_of(nodes: List[int]) -> np.ndarray:
            m = np.zeros(n, dtype=np.float32)
            m[nodes] = 1.0
            return m

        # LIFO worklist of pending device requests (LIFO ≈ depth-first).
        pending: List[_Request] = []
        # mask-bytes → survivor list; keyed separately for frozen probes.
        cache: Dict[bytes, List[int]] = {}

        def submit(req: _Request) -> None:
            """Dispatch a request, short-circuiting through the cache."""
            key = (b"f" if req.frozen else b"p") + req.mask.tobytes()
            hit = cache.get(key)
            if hit is not None:
                stats["cache_hits"] += 1
                req.cached = True
                handle(req, hit)
            else:
                pending.append(req)

        def push_state(state: _State) -> None:
            # Prune 1 (size, cpp:386-391) and prune 2 (empty, cpp:266-268).
            if len(state.dont_remove) > half:
                return
            if not state.to_remove and not state.dont_remove:
                return
            stats["bnb_states"] += 1
            # Speculative pair: `all` is consumed whenever `dont` holds no
            # quorum — the overwhelmingly common case in the tree interior.
            submit(_Request(mask_of(state.dont_remove), False, state, "dont"))
            submit(
                _Request(
                    mask_of(state.dont_remove + state.to_remove), False, state, "all"
                )
            )

        def branch(state: _State) -> None:
            """Prunes 4-6 then branch (cpp:301-345); needs dont (no quorum)
            AND the speculative `all` result."""
            survivors = state.all_survivors or []
            if not survivors:
                return
            quorum_set = set(survivors)
            if any(v not in quorum_set for v in state.dont_remove):
                return
            best = find_best_node(survivors, state.dont_remove, graph, self._rng)
            remaining = quorum_set - set(state.dont_remove)
            if not remaining:
                return
            new_to_remove = sorted(v for v in remaining if v != best)
            # Include-branch pushed first so the LIFO explores the
            # exclude-branch first, like the serial order (cpp:336, :343).
            push_state(
                _State(
                    to_remove=list(new_to_remove),
                    dont_remove=state.dont_remove + [best],
                )
            )
            push_state(
                _State(
                    to_remove=list(new_to_remove),
                    dont_remove=list(state.dont_remove),
                )
            )

        def minimal_confirmed(state: _State) -> None:
            stats["minimal_quorums"] += 1
            if state.probe_done:
                finish_probe(state)
            # else: the speculative probe result will arrive and route here.

        def waste_probe(state: _State) -> None:
            """Count a discarded speculative probe: once per state, device
            rows only (cache hits never occupied a row)."""
            if state.probe_done and not state.probe_wasted and not state.probe_cached:
                state.probe_wasted = True
                stats["wasted_rows"] += 1

        def finish_probe(state: _State) -> None:
            if state.probe_survivors:
                found["q1"] = state.probe_survivors
                found["q2"] = list(state.dont_remove)

        def handle(req: _Request, survivors: List[int]) -> None:
            """Route one fixpoint result back into the search."""
            state = req.state

            if req.kind == "dont":
                state.dont_done = True
                if survivors:
                    # dontRemove already contains a quorum (cpp:281-291):
                    # minimal iff every single-node removal kills it.  The
                    # speculative `all` row becomes dead weight (a wasted
                    # DEVICE row only if it wasn't served from the memo).
                    state.dont_has_quorum = True
                    if state.all_done and not state.all_cached:
                        stats["wasted_rows"] += 1
                    members = state.dont_remove
                    state.minimality_pending = len(members)
                    if not members:
                        return
                    for v in members:
                        m = mask_of(members)
                        m[v] = 0.0
                        submit(_Request(m, False, state, "minimal"))
                    # Speculative disjointness probe (cpp:357-384), valid
                    # only if minimality confirms; wasted otherwise.
                    probe = np.clip(scc_mask - mask_of(members), 0.0, 1.0)
                    submit(_Request(probe, True, state, "probe"))
                elif state.all_done:
                    branch(state)
                return

            if req.kind == "all":
                state.all_done = True
                state.all_cached = req.cached
                state.all_survivors = survivors
                if state.dont_done:
                    if state.dont_has_quorum:
                        if not req.cached:
                            stats["wasted_rows"] += 1
                    else:
                        branch(state)
                return

            if req.kind == "minimal":
                state.minimality_pending -= 1
                if survivors:
                    state.minimality_failed = True
                    waste_probe(state)
                elif state.minimality_pending == 0 and not state.minimality_failed:
                    minimal_confirmed(state)
                return

            if req.kind == "probe":
                state.probe_done = True
                state.probe_cached = req.cached
                state.probe_survivors = survivors
                if state.minimality_failed:
                    waste_probe(state)
                elif state.minimality_pending == 0:
                    # Minimality already confirmed; deliver the probe.
                    finish_probe(state)
                return

        fingerprint = None
        resumed = None
        if self.checkpoint is not None:
            from quorum_intersection_tpu.utils.checkpoint import sweep_fingerprint

            fingerprint = sweep_fingerprint(
                circuit.members, circuit.child, circuit.thresholds,
                np.asarray(scc, dtype=np.int32), scc_mask, frozen_probe,
            )
            resumed = self.checkpoint.resume_states(fingerprint)

        if resumed:
            # The saved frontier replaces the root: re-pushing exactly the
            # unresolved states reproduces the remainder of the search
            # (resolved states are not in the file and are never re-expanded).
            stats["resumed_states"] = len(resumed)
            for to_remove, dont_remove in resumed:
                push_state(_State(to_remove=list(to_remove), dont_remove=list(dont_remove)))
        else:
            push_state(_State(to_remove=list(scc), dont_remove=[]))

        import jax

        from quorum_intersection_tpu.backends.tpu.kernels import CircuitArrays, fixpoint

        arrays = CircuitArrays(circuit)
        frozen_row = arrays.cast(frozen_probe)

        def _fix(avail, frozen_flags):
            # Per-row frozen selection: probes get the Q6 mask, others zero.
            return fixpoint(arrays, avail, frozen_flags[:, None] * frozen_row)

        n_dev = 1
        if self.mesh is not None:
            # Row-sharded batch fixpoint: each device evaluates B/n_dev rows
            # independently; the closed-over circuit constants replicate.
            from quorum_intersection_tpu.parallel.mesh import P, shard_map_fn

            axis = self.mesh.axis_names[0]
            n_dev = self.mesh.devices.size
            run_jit = jax.jit(shard_map_fn(
                _fix, self.mesh,
                in_specs=(P(axis, None), P(axis)),
                out_specs=P(axis, None),
            ))
        else:
            run_jit = jax.jit(_fix)

        def launch():
            """Pop up to `batch` requests and dispatch them asynchronously."""
            take = pending[-batch:]
            del pending[-len(take) :]
            # Accelerators get ONE padded shape per problem: every batch
            # pads to `batch` rows, so exactly one program compiles (r3;
            # the r2 power-of-two bucketing compiled up to log2(batch)
            # shapes — each a multi-second stall through the tunnel) and the
            # padding waste is free on the MXU tile.  The CPU emulation
            # pays per-row compute instead of per-tile, so it keeps the
            # power-of-two bucketing (its compiles are sub-second).  A mesh
            # additionally needs the row axis divisible by (and at least)
            # the device count, which the rounding below preserves.
            if on_cpu:
                b = 1
                while b < len(take):
                    b *= 2
            else:
                b = batch
            b = max(b, n_dev)
            b = ((b + n_dev - 1) // n_dev) * n_dev
            masks = np.zeros((b, n), dtype=np.float32)
            flags = np.zeros((b,), dtype=np.float32)
            for i, req in enumerate(take):
                masks[i] = req.mask
                flags[i] = 1.0 if req.frozen else 0.0
            # NB stats count DISPATCHED work: an early witness exit may leave
            # inflight batches whose results are never drained.
            stats["device_batches"] += 1
            stats["fixpoints"] += len(take)
            log.debug(
                "hybrid batch %d: %d fixpoint rows (padded to %d), backlog %d, "
                "B&B states %d, minimal quorums %d, cache hits %d",
                stats["device_batches"], len(take), b, len(pending),
                stats["bnb_states"], stats["minimal_quorums"], stats["cache_hits"],
            )
            return take, run_jit(arrays.cast(masks), arrays.cast(flags))

        def record(take, results) -> None:
            for i, req in enumerate(take):
                survivors = np.nonzero(results[i])[0].tolist()
                key = (b"f" if req.frozen else b"p") + req.mask.tobytes()
                if len(cache) >= CACHE_LIMIT:
                    cache.clear()
                cache[key] = survivors
                handle(req, survivors)
                if found["q1"] is not None:
                    return

        # Pipelined drive: several batches in flight so the host↔device
        # round-trip overlaps with device compute.  Handling order across
        # batches is correctness-irrelevant: states' phase transitions are
        # counted, not ordered, and any disjoint pair is a valid witness.
        from collections import deque

        inflight: "deque" = deque()

        def frontier_snapshot() -> List:
            """(to_remove, dont_remove) of every state with unfinished work —
            exactly the states referenced by a pending or in-flight request
            (the invariant HybridCheckpoint documents)."""
            seen: Dict[int, _State] = {}
            for req in pending:
                seen[id(req.state)] = req.state
            for take, _ in inflight:
                for req in take:
                    seen[id(req.state)] = req.state
            return [
                [list(s.to_remove), list(s.dont_remove)] for s in seen.values()
            ]

        last_write = time.monotonic()
        drained = 0
        while (pending or inflight) and found["q1"] is None:
            while pending and len(inflight) < self.max_inflight:
                inflight.append(launch())
            take, device_out = inflight.popleft()
            record(take, np.asarray(device_out) != 0)  # sync point
            drained += 1
            # Never write once a witness is found: the witness-bearing state
            # is resolved and thus absent from the frontier snapshot, so a
            # post-witness write followed by a kill could resume into a
            # witness-free remainder and flip the verdict.
            if self.checkpoint is not None and found["q1"] is None:
                if (
                    self.interrupt_after_batches is not None
                    and drained >= self.interrupt_after_batches
                    and (pending or inflight)
                ):
                    self.checkpoint.record(frontier_snapshot(), fingerprint)
                    raise HybridSearchInterrupted(
                        f"simulated preemption after {drained} batches"
                    )
                if time.monotonic() - last_write >= self.checkpoint_interval_s:
                    self.checkpoint.record(frontier_snapshot(), fingerprint)
                    last_write = time.monotonic()

        seconds = time.perf_counter() - t0
        stats.update({"backend": self.name, "seconds": seconds})
        if self.checkpoint is not None:
            self.checkpoint.clear()  # either verdict: the search is complete
        if found["q1"] is not None:
            return SccCheckResult(
                intersects=False, q1=found["q1"], q2=found["q2"], stats=stats
            )
        return SccCheckResult(intersects=True, stats=stats)
