"""Fused Pallas sweep kernel — the whole candidate check in one device kernel
(the opt-in ``engine="pallas"`` of :class:`~...sweep.TpuSweepBackend`).

Design: the candidate block never exists in HBM at all — subset indices are
decoded *inside* the kernel (``(start + row) >> pos & 1``), both
greatest-fixpoint loops run on VMEM-resident ~1k-row grid blocks with
per-block early exit (each block stops when *its* rows converge, instead of
the XLA path's whole-batch convergence), and each block writes back exactly
one int32 (its min hit index).

Measured on v5e (2026-07, properly pipelined with ≥16 programs in flight;
r3 re-measured the XLA path's steady rate at 1.57-2.08G cand/s on the same
31-node circuit — bench_full_r3_onchip.json — widening this gap further):
the XLA path is **faster** — ~1.1G cand/s vs ~0.3G on a 31-node circuit
(Mosaic's per-grid-step overhead dominates at small widths and it does not
pipeline blocks across the grid the way XLA overlaps its fused loop), and
parity within noise (~130M cand/s) on a 256-node nested circuit where both
are MXU-bound.  The per-block early exit does not pay: convergence spread
across candidate blocks is small for real FBAS shapes.  The kernel is kept
as an alternative engine (``TpuSweepBackend(engine="pallas")``) — it is the
template for fusing further stages (e.g. in-kernel PRNG workloads) and the
regression baseline that keeps the XLA path honest.

Padding/layout: lanes want multiples of 128, so nodes pad ``n → Np`` and
units re-lay out as ``[node units 0..n) | pad | inner units @ Np..]`` with
``Up`` total — padded slots get an unsatisfiable threshold (2^30) so they
stay identically zero through every sweep and never affect real nodes.  The
int8 regime mirrors `kernels.CircuitArrays`: 0/1/count operands on the MXU's
8-bit path with exact int32 accumulation (gated on counts ≤ 127; rarer
circuits fall back to the XLA path).

Semantics are pinned to the XLA path bit-for-bit (`tests/test_pallas.py`
differential-tests both on CPU via interpret mode): same decode
(`kernels.bit_positions`), same Q4 self-availability, same Q6 frozen mask,
same hit definition, same min-hit-index per program.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from quorum_intersection_tpu.backends.base import INT32_MAX
from quorum_intersection_tpu.backends.tpu.kernels import _INT8_MAX_COUNT, bit_positions
from quorum_intersection_tpu.encode.circuit import Circuit

LANE = 128
DEFAULT_BLOCK = 1024  # candidates per grid block (per-block early exit scope)
_UNSAT = 1 << 30  # padded-unit threshold: never satisfiable, no int32 overflow
# int8 accumulate-in-int32 matmul: votes ≤ 127 each, ≤ Np ≤ 2^15 members ⇒ safe


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def plan_batch(batch: int, block: int = DEFAULT_BLOCK) -> Tuple[int, int]:
    """Resolve the caller's desired batch into ``(effective_batch, block)``:
    the grid block is int8-sublane aligned (multiple of 32) and the batch a
    multiple of it.  The sweep driver calls this too, so its coverage
    accounting matches the kernel's actual program size exactly."""
    if batch < block:
        block = _round_up(max(batch, 1), 32)
    return _round_up(batch, block), block


def pallas_supported(circuit: Circuit) -> bool:
    """int8 vote counts only (the common case; see module docs)."""
    return (
        int(circuit.members.max(initial=0)) <= _INT8_MAX_COUNT
        and int(circuit.child.max(initial=0)) <= _INT8_MAX_COUNT
    )


def pad_circuit(circuit: Circuit) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray, int, int]:
    """Re-lay out the circuit for lane-aligned tiles.

    Returns ``(members_t, child_t, thresholds, Np, Up)`` with node units at
    ``[0, n)`` and inner units moved to ``[Np, Np + U - n)`` so the kernel's
    ``sat[:, :Np]`` slice is exactly the (padded) node axis.  ``members_t``
    is (Np, Up) int8; ``child_t`` (Up, Up) int8 or None when the circuit has
    no inner sets; ``thresholds`` (1, Up) int32 with _UNSAT in padded slots.
    """
    n, u = circuit.n, circuit.n_units
    np_ = _round_up(max(n, 1), LANE)
    n_inner = u - n
    up = _round_up(np_ + n_inner, LANE)

    def unit_ix(j: int) -> int:
        return j if j < n else np_ + (j - n)

    umap = np.fromiter((unit_ix(j) for j in range(u)), dtype=np.int64, count=u)

    members_t = np.zeros((np_, up), dtype=np.int8)  # (node, unit) votes
    members_t[:n, umap] = circuit.members.T.astype(np.int8)

    thresholds = np.full((1, up), _UNSAT, dtype=np.int32)
    thresholds[0, umap] = circuit.thresholds.astype(np.int32)

    child_t = None
    if n_inner > 0:
        child_t = np.zeros((up, up), dtype=np.int8)  # (child unit, parent unit)
        child_t[np.ix_(umap, umap)] = circuit.child.T.astype(np.int8)
    return members_t, child_t, thresholds, np_, up


def _pad_row(row: Optional[np.ndarray], np_: int, fill, dtype) -> np.ndarray:
    out = np.full((1, np_), fill, dtype=dtype)
    if row is not None:
        out[0, : row.shape[0]] = row.astype(dtype)
    return out


def pallas_sweep_program_factory(
    circuit: Circuit,
    bit_nodes: np.ndarray,
    scc_mask: np.ndarray,
    frozen: Optional[np.ndarray],
    batch: int,
    block: int = DEFAULT_BLOCK,
    interpret: Optional[bool] = None,
) -> Callable[[int], Callable[[int], jnp.ndarray]]:
    """Drop-in replacement for `kernels.sweep_program_factory` built on the
    fused kernel.  Same contract: ``factory(steps_per_call)`` compiles a
    program covering ``batch × steps_per_call`` candidates and returns the
    min hit index (INT32_MAX ⇒ clean miss) as an async device scalar.
    """
    if not pallas_supported(circuit):
        raise ValueError("circuit vote counts exceed int8; use the XLA sweep path")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, block = plan_batch(batch, block)
    n_blocks = batch // block

    members_np, child_np, thr_np, np_, up = pad_circuit(circuit)
    depth = circuit.depth if child_np is not None else 0

    pos_np = _pad_row(bit_positions(bit_nodes, circuit.n), np_, 31, np.int32)
    scc_np = _pad_row(scc_mask, np_, 0, np.int8)
    frozen_np = _pad_row(frozen, np_, 0, np.int8)  # zeros when frozen is None

    members_j = jnp.asarray(members_np)
    thr_j = jnp.asarray(thr_np)
    pos_j = jnp.asarray(pos_np)
    scc_j = jnp.asarray(scc_np)
    frozen_j = jnp.asarray(frozen_np)
    child_j = jnp.asarray(child_np) if child_np is not None else None

    def kernel(start_ref, pos_ref, members_ref, thr_ref, scc_ref, frz_ref, *rest):
        child_ref, out_ref = (rest[0], rest[1]) if child_j is not None else (None, rest[0])
        start = start_ref[0, 0] + pl.program_id(0) * block
        row = lax.broadcasted_iota(jnp.int32, (block, np_), 0)
        avail0 = ((start + row) >> pos_ref[:] & 1).astype(jnp.int8)

        thr = thr_ref[:]  # (1, Up) int32

        def node_sat(total):
            base = jnp.dot(total, members_ref[:], preferred_element_type=jnp.int32)
            sat = (base >= thr).astype(jnp.int8)
            for _ in range(depth):
                sat = (
                    (base + jnp.dot(sat, child_ref[:], preferred_element_type=jnp.int32))
                    >= thr
                ).astype(jnp.int8)
            return jnp.bitwise_and(sat[:, :np_], total)

        def fixpoint(a0, frozen_row):
            def cond(c):
                return c[1]

            def body(c):
                a, _ = c
                # masks are 0/1: OR == max, and Mosaic has no int8 maxsi
                total = jnp.bitwise_or(a, frozen_row)
                nxt = jnp.bitwise_and(node_sat(total), a)
                # Arithmetic change detection: a wide i1 mask (nxt != a)
                # trips Mosaic's relayout on some shapes; masks are 0/1 and
                # the fixpoint only ever *removes* nodes, so the survivor
                # count strictly decreases until stable.
                changed = jnp.sum(a.astype(jnp.int32) - nxt.astype(jnp.int32)) > 0
                return nxt, changed

            out, _ = lax.while_loop(cond, body, (a0, jnp.bool_(True)))
            return out

        q = fixpoint(avail0, jnp.zeros((1, np_), dtype=jnp.int8))
        q_size = jnp.sum(q, axis=1, keepdims=True, dtype=jnp.int32)  # (B, 1)
        comp = jnp.clip(scc_ref[:].astype(jnp.int32) - q, 0, 1).astype(jnp.int8)
        d = fixpoint(comp, frz_ref[:])
        d_size = jnp.sum(d, axis=1, keepdims=True, dtype=jnp.int32)
        hit = jnp.logical_and(q_size > 0, d_size > 0)  # (B, 1)
        idx = start + lax.broadcasted_iota(jnp.int32, (block, 1), 0)
        # The output is one un-blocked (n_blocks, 1) SMEM buffer shared by
        # every grid step; each step owns exactly its program_id slot.
        out_ref[pl.program_id(0), 0] = jnp.min(
            jnp.where(hit, idx, jnp.int32(INT32_MAX))
        )

    const_spec = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)  # noqa: E731
    in_specs = [
        pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),  # start
        const_spec(),  # pos
        const_spec(),  # members
        const_spec(),  # thresholds
        const_spec(),  # scc mask
        const_spec(),  # frozen
    ]
    operands = [pos_j, members_j, thr_j, scc_j, frozen_j]
    if child_j is not None:
        in_specs.append(const_spec())
        operands.append(child_j)

    call = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((n_blocks, 1), jnp.int32),
        interpret=interpret,
    )

    def one_call(start):
        start2d = jnp.reshape(start, (1, 1)).astype(jnp.int32)
        return jnp.min(call(start2d, *operands))

    def factory(steps_per_call: int) -> Callable[..., jnp.ndarray]:
        @jax.jit
        def step(start0):
            if steps_per_call == 1:
                return one_call(start0)

            def body(i, best):
                return jnp.minimum(best, one_call(start0 + i * batch))

            return lax.fori_loop(0, steps_per_call, body, jnp.int32(INT32_MAX))

        def dispatch(start: int, hi_mask=None):
            # The sweep driver routes wide (two-level) enumerations to the
            # XLA engine; this kernel only serves the narrow case.
            assert hi_mask is None, "pallas engine does not take a hi mask"
            return step(jnp.int32(start))

        return dispatch

    return factory


def pallas_guard_factory(
    circuit: Circuit,
    block: int = 256,
    interpret: Optional[bool] = None,
) -> Callable[[np.ndarray], np.ndarray]:
    """Pallas twin of ``kernels.guard_program_factory`` (ISSUE 10): the
    block-guard Q-side fixpoint as a fused kernel — (B, n) 0/1
    maximal-candidate rows in, (B,) int32 survivor counts out (zero ⇒ the
    block's maximal candidate contains no quorum ⇒ the block prunes).
    Same padded layout and int8 regime as the sweep kernels; rows pad to
    the grid block and columns to the lane tile, both inert.
    """
    if not pallas_supported(circuit):
        raise ValueError("circuit vote counts exceed int8; use the XLA guard path")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block = _round_up(max(block, 1), 32)
    members_np, child_np, thr_np, np_, up = pad_circuit(circuit)
    depth = circuit.depth if child_np is not None else 0

    members_j = jnp.asarray(members_np)
    thr_j = jnp.asarray(thr_np)
    child_j = jnp.asarray(child_np) if child_np is not None else None

    def kernel(avail_ref, members_ref, thr_ref, *rest):
        child_ref, out_ref = (
            (rest[0], rest[1]) if child_j is not None else (None, rest[0])
        )
        thr = thr_ref[:]

        def node_sat(total):
            base = jnp.dot(total, members_ref[:], preferred_element_type=jnp.int32)
            sat = (base >= thr).astype(jnp.int8)
            for _ in range(depth):
                sat = (
                    (base + jnp.dot(sat, child_ref[:], preferred_element_type=jnp.int32))
                    >= thr
                ).astype(jnp.int8)
            return jnp.bitwise_and(sat[:, :np_], total)

        def cond(c):
            return c[1]

        def body(c):
            a, _ = c
            nxt = jnp.bitwise_and(node_sat(a), a)
            # Same arithmetic change detection as the sweep kernels.
            changed = jnp.sum(a.astype(jnp.int32) - nxt.astype(jnp.int32)) > 0
            return nxt, changed

        q, _ = lax.while_loop(cond, body, (avail_ref[...], jnp.bool_(True)))
        out_ref[...] = jnp.sum(q, axis=1, keepdims=True, dtype=jnp.int32)

    const_spec = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)  # noqa: E731
    in_specs = [
        pl.BlockSpec((block, np_), lambda i: (i, 0)),  # guard rows
        const_spec(),  # members
        const_spec(),  # thresholds
    ]
    operands = [members_j, thr_j]
    if child_j is not None:
        in_specs.append(const_spec())
        operands.append(child_j)

    def run(masks: np.ndarray) -> np.ndarray:
        rows = masks.shape[0]
        rows_pad = _round_up(max(rows, 1), block)
        padded = np.zeros((rows_pad, np_), dtype=np.int8)
        padded[:rows, : masks.shape[1]] = masks.astype(np.int8)
        call = pl.pallas_call(
            kernel,
            grid=(rows_pad // block,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((block, 1), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows_pad, 1), jnp.int32),
            interpret=interpret,
        )
        return np.asarray(call(jnp.asarray(padded), *operands))[:rows, 0]

    return run


def pallas_packed_program_factory(
    circuit: Circuit,
    circuit_d: Optional[Circuit],
    pos: np.ndarray,
    scc_mask: np.ndarray,
    lane_group: np.ndarray,
    group_ind: np.ndarray,
    batch: int,
    block: int = DEFAULT_BLOCK,
    interpret: Optional[bool] = None,
) -> Callable[[int], Callable]:
    """Lane-packed twin of :func:`pallas_sweep_program_factory` — the fused
    kernel over a block-diagonal ``encode.pack_circuits`` block with
    PER-GROUP first-hit reduction (same contract as
    ``kernels.packed_sweep_program_factory``: ``dispatch(starts)`` takes the
    (K,) per-group starts vector and returns the (K,) min hit indices).

    Per-group mechanics inside the kernel: each lane decodes against its
    OWN group's candidate index (a per-lane starts row replaces the scalar
    start), survivor counts reduce through one ``(B, Np) x (Np, Kp)``
    group-indicator matmul (lane-aligned, MXU-friendly — Mosaic has no
    cheap segment-sum), and each grid step writes its (1, Kp) min-hit row.
    ``circuit_d`` carries the packed Q6 thresholds (shares every other
    array with ``circuit``); members are SCC-restricted so no frozen row
    exists on the Q side and the D probe's fold is entirely in thresholds.
    """
    if not pallas_supported(circuit):
        raise ValueError("circuit vote counts exceed int8; use the XLA sweep path")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, block = plan_batch(batch, block)
    n_blocks = batch // block

    members_np, child_np, thr_np, np_, up = pad_circuit(circuit)
    depth = circuit.depth if child_np is not None else 0
    if circuit_d is not None:
        _, _, thr_d_np, np_d, up_d = pad_circuit(circuit_d)
        assert (np_d, up_d) == (np_, up), "packed Q6 twin must share shapes"
    else:
        thr_d_np = thr_np

    k = int(group_ind.shape[1])
    kp = _round_up(k, LANE)
    pos_row = _pad_row(pos, np_, 31, np.int32)
    scc_row = _pad_row(scc_mask, np_, 0, np.int8)
    gind = np.zeros((np_, kp), dtype=np.int8)
    gind[: group_ind.shape[0], :k] = group_ind.astype(np.int8)

    members_j = jnp.asarray(members_np)
    thr_j = jnp.asarray(thr_np)
    thr_d_j = jnp.asarray(thr_d_np)
    pos_j = jnp.asarray(pos_row)
    scc_j = jnp.asarray(scc_row)
    gind_j = jnp.asarray(gind)
    child_j = jnp.asarray(child_np) if child_np is not None else None
    lane_group_h = np.asarray(lane_group, dtype=np.int64)

    def kernel(sl_ref, sg_ref, pos_ref, members_ref, thr_ref, thr_d_ref,
               scc_ref, gind_ref, *rest):
        child_ref, out_ref = (rest[0], rest[1]) if child_j is not None else (None, rest[0])
        row0 = pl.program_id(0) * block
        row_n = row0 + lax.broadcasted_iota(jnp.int32, (block, np_), 0)
        avail0 = ((sl_ref[:] + row_n) >> pos_ref[:] & 1).astype(jnp.int8)

        def node_sat(total, thr):
            base = jnp.dot(total, members_ref[:], preferred_element_type=jnp.int32)
            sat = (base >= thr).astype(jnp.int8)
            for _ in range(depth):
                sat = (
                    (base + jnp.dot(sat, child_ref[:], preferred_element_type=jnp.int32))
                    >= thr
                ).astype(jnp.int8)
            return jnp.bitwise_and(sat[:, :np_], total)

        def fixpoint(a0, thr):
            def cond(c):
                return c[1]

            def body(c):
                a, _ = c
                nxt = jnp.bitwise_and(node_sat(a, thr), a)
                # Same arithmetic change detection as the unpacked kernel.
                changed = jnp.sum(a.astype(jnp.int32) - nxt.astype(jnp.int32)) > 0
                return nxt, changed

            out, _ = lax.while_loop(cond, body, (a0, jnp.bool_(True)))
            return out

        q = fixpoint(avail0, thr_ref[:])
        q_sizes = jnp.dot(q, gind_ref[:], preferred_element_type=jnp.int32)
        comp = jnp.clip(scc_ref[:].astype(jnp.int32) - q, 0, 1).astype(jnp.int8)
        d = fixpoint(comp, thr_d_ref[:])
        d_sizes = jnp.dot(d, gind_ref[:], preferred_element_type=jnp.int32)
        hit = jnp.logical_and(q_sizes > 0, d_sizes > 0)  # (B, Kp)
        row_k = row0 + lax.broadcasted_iota(jnp.int32, (block, kp), 0)
        idx = sg_ref[:] + row_k
        out_ref[...] = jnp.min(
            jnp.where(hit, idx, jnp.int32(INT32_MAX)), axis=0, keepdims=True
        )

    const_spec = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)  # noqa: E731
    in_specs = [
        const_spec(),  # starts per lane (1, Np)
        const_spec(),  # starts per group (1, Kp)
        const_spec(),  # pos
        const_spec(),  # members
        const_spec(),  # thresholds (Q side)
        const_spec(),  # thresholds (D probe)
        const_spec(),  # scc mask
        const_spec(),  # group indicator
    ]
    operands = [pos_j, members_j, thr_j, thr_d_j, scc_j, gind_j]
    if child_j is not None:
        in_specs.append(const_spec())
        operands.append(child_j)

    call = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, kp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_blocks, kp), jnp.int32),
        interpret=interpret,
    )

    def one_call(starts_lane, starts_grp):
        return jnp.min(call(starts_lane, starts_grp, *operands), axis=0)

    def factory(steps_per_call: int) -> Callable:
        @jax.jit
        def step(starts_lane, starts_grp):
            if steps_per_call == 1:
                return one_call(starts_lane, starts_grp)[:k]

            def body(i, best):
                off = i * batch
                return jnp.minimum(
                    best, one_call(starts_lane + off, starts_grp + off)
                )

            return lax.fori_loop(
                0, steps_per_call, body,
                jnp.full((kp,), INT32_MAX, dtype=jnp.int32),
            )[:k]

        def dispatch(starts):
            starts_h = np.asarray(starts, dtype=np.int32)
            sl = np.zeros((1, np_), dtype=np.int32)
            sl[0, : lane_group_h.shape[0]] = starts_h[lane_group_h]
            sg = np.zeros((1, kp), dtype=np.int32)
            sg[0, :k] = starts_h
            return step(jnp.asarray(sl), jnp.asarray(sg))

        return dispatch

    return factory


# ---------------------------------------------------------------------------
# Bitset twin (ISSUE 20 qi-sparse): the fused packed kernel over the
# intersect-and-popcount encoding.  Adjacency lives VMEM-resident as packed
# int32 words — (Np/32, Up) membership, (Up/32, Up) child links, (Np/32, Kp)
# group indicators — and every vote count is a word-unrolled AND + popcount
# (the Hacker's Delight bit-twiddle below: plain shifts/masks only, so the
# identical code lowers through Mosaic and interpret mode; no reliance on a
# native popcount instruction).  Same per-group min-hit output contract as
# pallas_packed_program_factory.  Word arrays are int32 (Mosaic's native
# 32-bit lane dtype); all ops below are pure bit manipulation, for which
# signedness is irrelevant.


def _shr32(v, k: int):
    """Logical right shift of int32 bit patterns: arithmetic ``>>`` then
    masking off the ``k`` sign-filled top bits."""
    return (v >> k) & ((1 << (32 - k)) - 1)


def _popcount32(v):
    """Per-lane population count of int32 words (bit-twiddling identity)."""
    v = v - (_shr32(v, 1) & 0x55555555)
    v = (v & 0x33333333) + (_shr32(v, 2) & 0x33333333)
    v = (v + _shr32(v, 4)) & 0x0F0F0F0F
    v = v + _shr32(v, 8)
    v = v + _shr32(v, 16)
    return v & 0x3F


def _pack_lanes32(bits):
    """Pack 0/1 int32 lanes ``(B, 32·W) → (B, W)`` words (LSB-first, the
    `encode.circuit.pack_mask_words` convention) via strided lane slices —
    2-D only, no reshape, so the op stays in Mosaic's comfort zone."""
    acc = bits[:, 0::32]
    for l in range(1, 32):
        acc = acc | (bits[:, l::32] << l)
    return acc


def _pack_words_host(mat: np.ndarray) -> np.ndarray:
    """Host-side word packing for kernel constants: ``(rows, cols)`` 0/1 →
    ``(rows/32, cols)`` int32 — bit ``r % 32`` of word ``r // 32`` is row
    *r* (rows must be a multiple of 32; lane-tile layouts always are)."""
    from quorum_intersection_tpu.encode.circuit import pack_mask_words

    rows = mat.shape[0]
    assert rows % 32 == 0, f"{rows} rows not word-aligned"
    packed = pack_mask_words(np.ascontiguousarray(mat.T), rows // 32)  # (cols, W)
    return np.ascontiguousarray(packed.T).view(np.int32)


def pallas_bitset_program_factory(
    circuit: Circuit,
    circuit_d: Optional[Circuit],
    pos: np.ndarray,
    scc_mask: np.ndarray,
    lane_group: np.ndarray,
    group_ind: np.ndarray,
    batch: int,
    block: int = DEFAULT_BLOCK,
    interpret: Optional[bool] = None,
) -> Callable[[int], Callable]:
    """Bitset twin of :func:`pallas_packed_program_factory` — identical
    contract (``dispatch(starts)``: (K,) per-group starts in, (K,) min hit
    indices out), identical decode and hit definition, with both fixpoints
    running over packed words: vote counts are per-word AND + popcount
    unrolls against the VMEM-resident word tables instead of MXU matmuls.
    Thresholds (Q and D folds) ride unchanged from the dense layout, so
    SCC-restriction and lane-packing semantics carry over verbatim."""
    from quorum_intersection_tpu.encode.circuit import bitset_supported

    if not bitset_supported(circuit):
        raise ValueError(
            "circuit has vote multiplicities > 1; the bitset kernel is "
            "0/1-vote only — use the dense engines"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, block = plan_batch(batch, block)
    n_blocks = batch // block

    members_np, child_np, thr_np, np_, up = pad_circuit(circuit)
    depth = circuit.depth if child_np is not None else 0
    if circuit_d is not None:
        _, _, thr_d_np, np_d, up_d = pad_circuit(circuit_d)
        assert (np_d, up_d) == (np_, up), "packed Q6 twin must share shapes"
    else:
        thr_d_np = thr_np

    w_n = np_ // 32  # availability words (node axis)
    k = int(group_ind.shape[1])
    kp = _round_up(k, LANE)
    pos_row = _pad_row(pos, np_, 31, np.int32)
    # Packed word constants: membership (W, Up), group indicator (W, Kp),
    # scc row (1, W) — child links (Up/32, Up) when inner units exist.
    members_w = _pack_words_host(members_np)
    gind_full = np.zeros((np_, kp), dtype=np.int8)
    gind_full[: group_ind.shape[0], :k] = group_ind.astype(np.int8)
    gmask_np = _pack_words_host(gind_full)
    scc_full = np.zeros((np_,), dtype=np.int8)
    scc_full[: scc_mask.shape[0]] = scc_mask.astype(np.int8)
    sccw_np = _pack_words_host(scc_full[:, None]).T  # (1, W)
    child_w = _pack_words_host(child_np) if child_np is not None else None

    members_j = jnp.asarray(members_w)
    thr_j = jnp.asarray(thr_np)
    thr_d_j = jnp.asarray(thr_d_np)
    pos_j = jnp.asarray(pos_row)
    sccw_j = jnp.asarray(np.ascontiguousarray(sccw_np))
    gmask_j = jnp.asarray(gmask_np)
    child_j = jnp.asarray(child_w) if child_w is not None else None
    lane_group_h = np.asarray(lane_group, dtype=np.int64)

    def kernel(sl_ref, sg_ref, pos_ref, members_ref, thr_ref, thr_d_ref,
               sccw_ref, gmask_ref, *rest):
        child_ref, out_ref = (rest[0], rest[1]) if child_j is not None else (None, rest[0])
        row0 = pl.program_id(0) * block
        row_n = row0 + lax.broadcasted_iota(jnp.int32, (block, np_), 0)
        bits0 = (sl_ref[:] + row_n) >> pos_ref[:] & 1  # (B, Np) int32 0/1
        avail0 = _pack_lanes32(bits0)  # (B, W)

        members_tbl = members_ref[:]  # (W, Up)
        child_tbl = child_ref[:] if child_ref is not None else None
        gmask_tbl = gmask_ref[:]  # (W, Kp)

        def votes(words, table):
            # (B, Wx) × (Wx, U'): Σ_w popcount(words[:, w] & table[w, :]) —
            # the bitset stand-in for the MXU vote matmul, unrolled over
            # the (static, small) word count.
            out = None
            for w in range(int(table.shape[0])):
                hits = _popcount32(words[:, w : w + 1] & table[w : w + 1, :])
                out = hits if out is None else out + hits
            return out

        def unit_sat(a_w, thr):
            base = votes(a_w, members_tbl)
            sat = (base >= thr).astype(jnp.int32)
            for _ in range(depth):
                extra = votes(_pack_lanes32(sat), child_tbl)
                sat = ((base + extra) >= thr).astype(jnp.int32)
            return sat

        def fixpoint(a0_w, thr):
            def cond(c):
                return c[1]

            def body(c):
                a, _ = c
                nxt = _pack_lanes32(unit_sat(a, thr)[:, :np_]) & a
                # Arithmetic change detection, word-flavored: the fixpoint
                # only ever clears bits, so a ^ nxt is exactly the removed
                # set and its popcount is the survivor-count decrease.
                changed = jnp.sum(_popcount32(a ^ nxt)) > 0
                return nxt, changed

            out, _ = lax.while_loop(cond, body, (a0_w, jnp.bool_(True)))
            return out

        q_w = fixpoint(avail0, thr_ref[:])
        q_sizes = votes(q_w, gmask_tbl)  # (B, Kp) per-group survivors
        comp = sccw_ref[:] & ~q_w
        d_w = fixpoint(comp, thr_d_ref[:])
        d_sizes = votes(d_w, gmask_tbl)
        hit = jnp.logical_and(q_sizes > 0, d_sizes > 0)  # (B, Kp)
        row_k = row0 + lax.broadcasted_iota(jnp.int32, (block, kp), 0)
        idx = sg_ref[:] + row_k
        out_ref[...] = jnp.min(
            jnp.where(hit, idx, jnp.int32(INT32_MAX)), axis=0, keepdims=True
        )

    const_spec = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)  # noqa: E731
    in_specs = [
        const_spec(),  # starts per lane (1, Np)
        const_spec(),  # starts per group (1, Kp)
        const_spec(),  # pos
        const_spec(),  # membership words (W, Up)
        const_spec(),  # thresholds (Q side)
        const_spec(),  # thresholds (D probe)
        const_spec(),  # scc words (1, W)
        const_spec(),  # group-indicator words (W, Kp)
    ]
    operands = [pos_j, members_j, thr_j, thr_d_j, sccw_j, gmask_j]
    if child_j is not None:
        in_specs.append(const_spec())
        operands.append(child_j)

    call = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, kp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_blocks, kp), jnp.int32),
        interpret=interpret,
    )

    def one_call(starts_lane, starts_grp):
        return jnp.min(call(starts_lane, starts_grp, *operands), axis=0)

    def factory(steps_per_call: int) -> Callable:
        @jax.jit
        def step(starts_lane, starts_grp):
            if steps_per_call == 1:
                return one_call(starts_lane, starts_grp)[:k]

            def body(i, best):
                off = i * batch
                return jnp.minimum(
                    best, one_call(starts_lane + off, starts_grp + off)
                )

            return lax.fori_loop(
                0, steps_per_call, body,
                jnp.full((kp,), INT32_MAX, dtype=jnp.int32),
            )[:k]

        def dispatch(starts):
            starts_h = np.asarray(starts, dtype=np.int32)
            sl = np.zeros((1, np_), dtype=np.int32)
            sl[0, : lane_group_h.shape[0]] = starts_h[lane_group_h]
            sg = np.zeros((1, kp), dtype=np.int32)
            sg[0, :k] = starts_h
            return step(jnp.asarray(sl), jnp.asarray(sg))

        return dispatch

    return factory
