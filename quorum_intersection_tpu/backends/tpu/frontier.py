"""Device-resident branch-and-bound — the frontier lives in HBM.

The r3 on-chip crossover (benchmarks/results/crossover_tpu_r3.txt) showed
WHY the round-trip hybrid loses to the native oracle everywhere: the B&B
frontier is host-sequential, so every batch of fixpoints pays a host↔device
round-trip (~65-100 ms through the tunneled chip) for a frontier that rarely
fills it.  This backend removes the round-trips entirely: the **worklist
itself is a device array** — a LIFO stack of (toRemove, dontRemove) bitmask
pairs in SCC-index space — and one jitted ``lax.while_loop`` pops a block of
states, evaluates their fixpoints as batched matmuls, applies the
reference's prunes (cpp:252-346; pinned spec `backends/python_oracle.py`),
and pushes children, thousands of states per device iteration with zero
host involvement.

Division of labor (verdict-equivalent to the serial oracle):

- **Device** handles the tree interior: the size prune (cpp:386-391 via the
  caller's half bound), the empty prune (cpp:266-268), the
  ``fixpoint(dontRemove)`` test (cpp:281), the full-candidate fixpoint +
  containment prunes (cpp:301-314), the branch-variable choice
  (max in-degree within the quorum, cpp:203-250) and the two-child
  expansion (cpp:336, :343-345).
- **Leaves**: states whose ``dontRemove`` already contains a quorum are
  *flagged* into a side buffer and never expanded (sound: the oracle
  prunes descent there either way, cpp:281-291).  Each flagged set then
  needs minimality (cpp:179-201) and the disjointness probe (cpp:357-384,
  Q6 availability).  Two engines, chosen by ``flag_check``:

  * ``"device"`` (default on accelerators): the leave-one-out minimality
    rows and the availability probe run as batched device fixpoints
    (:meth:`_build_flag_filter`) — necessary because flagged states are
    NOT rare on hierarchical networks (hier-7x4: 2.5 % of popped = 583k
    states; serial host checks would rival the native oracle's whole
    search).  A **negative** verdict (all quorums intersect) then rests
    on the device fixpoint — the same kernel the sweep backend's verdict
    rests on, differentially pinned against the host semantics
    (test_tpu_kernels.py, test_frontier.py count parity, tools/soak.py).
  * ``"host"`` (default on the CPU backend): the serial exact check per
    state through the native ``qi_max_quorum`` (parity-tested against
    the Python spec) when the library builds, else `fbas/semantics.py`.

  Either way a **positive witness** (verdict ``false``) never leaves this
  backend on device results alone: the device filter only *nominates* the
  first witness candidate, and the exact host semantics re-verify it
  before any verdict.

Deliberate deviation from cpp:221: when no quorum member has an edge into
``quorum ∖ dontRemove``, the reference falls back to ``quorum.front()`` —
which may lie in ``dontRemove``, making both children identical to their
parent (a latent non-termination in the reference).  This backend always
branches on a member of ``quorum ∖ dontRemove`` (lowest index when
in-degrees tie), which is the standard inclusion/exclusion branch variable
and keeps the enumeration complete AND strictly shrinking.

Scale-out of the worklist: the device arena is fixed-capacity; when it
nears overflow the chunk returns to the host, which spills the oldest half
of the stack to host memory and re-feeds it when the device runs dry —
LIFO across spills is not preserved, which affects only traversal order,
never the enumerated set.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from quorum_intersection_tpu.backends.base import SccCheckResult
from quorum_intersection_tpu.encode.circuit import Circuit
from quorum_intersection_tpu.fbas.graph import TrustGraph
from quorum_intersection_tpu.fbas.semantics import max_quorum
from quorum_intersection_tpu.utils.faults import fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record

log = get_logger("backends.tpu.frontier")

# Arena capacity (states).  A state is 2×s int8 (s = |scc| ≤ 64 for the
# sizes this backend targets): 2^18 states ≈ 32 MB of HBM at s=64.  DFS-ish
# LIFO keeps the live frontier far below this for every measured workload.
ARENA = 1 << 18
# States popped per device iteration.  Big enough that the two batched
# fixpoints fill the MXU, small enough that a shallow tree still saturates
# quickly (the frontier roughly doubles per iteration until it exceeds POP).
POP = 2048
# Exit the device loop once this many dontRemove-quorum states are flagged
# (the host then runs the exact minimality/witness checks).  Small enough
# to surface a broken network's witness fast, big enough to amortize the
# chunk round-trip on safe hierarchical networks that flag thousands.
# The exit threshold is a TRACED scalar: when a chunk exits flag-bound
# (safe-looking network, flags keep confirming non-witnesses) the host
# doubles it up to FLAG_EXIT_GROWTH× the initial value — fewer chunk
# round-trips on exactly the workloads that flag the most — without
# recompiling (the flag buffer is sized for the cap once).
FLAG_EXIT = 512
FLAG_EXIT_GROWTH = 16
# Device iterations per chunk: bounds time-to-host-visibility (stats,
# checkpoints, KeyboardInterrupt) without materially costing throughput.
CHUNK_ITERS = 512


class FrontierSearchInterrupted(RuntimeError):
    """Raised by the preemption-simulation hook after writing a checkpoint
    (``interrupt_after_chunks``); production runs never see it."""


class TpuFrontierBackend:
    """Device-resident B&B over the quorum-bearing SCC."""

    name = "tpu-frontier"
    needs_circuit = True

    def __init__(
        self,
        arena: int = ARENA,
        pop: int = POP,
        flag_exit: int = FLAG_EXIT,
        chunk_iters: int = CHUNK_ITERS,
        checkpoint=None,
        checkpoint_interval_s: Optional[float] = None,
        interrupt_after_chunks: Optional[int] = None,
        mesh=None,
        flag_check: str = "auto",
        pad_shapes: bool = True,
    ) -> None:
        if arena < 4:
            # Mirrors the mesh-path validation in check_scc: pop is clamped to
            # arena//4, and a zero pop block makes the chunk loop spin forever
            # (each chunk pops nothing) instead of failing.
            raise ValueError(f"arena={arena} too small (needs >= 4)")
        if flag_check not in ("auto", "device", "host"):
            raise ValueError(f"flag_check={flag_check!r} not in auto/device/host")
        self.arena = arena
        self.pop = min(pop, arena // 4)
        self.flag_exit = flag_exit
        self.chunk_iters = chunk_iters
        # Flagged-state checking strategy (measured at scc 28: 2.5% of
        # popped states flag — 583k serial host checks would dominate an
        # on-chip run).  "device": batched leave-one-out minimality +
        # disjointness-probe fixpoints on the accelerator, host only
        # re-verifies the rare witness candidate exactly.  "host": the
        # serial native/Python exact check per state.  "auto": device on
        # accelerators, host on the CPU backend (where the emulated batch
        # fixpoints lose to the native serial checks).
        self.flag_check = flag_check
        # Optional jax.sharding.Mesh: the popped block's fixpoint rows shard
        # across devices (all_gather reassembles); the arena and all control
        # flow replicate, so every device runs the identical expansion.
        self.mesh = mesh
        self.checkpoint = checkpoint  # utils.checkpoint.FrontierCheckpoint or None
        if checkpoint_interval_s is None:
            # Env override (QI_FRONTIER_CKPT_INTERVAL_S) exists for the real
            # process-death tests, which must shrink the write cadence of a
            # CLI child they cannot construct in-process.
            from quorum_intersection_tpu.utils.env import qi_env_float

            checkpoint_interval_s = qi_env_float("QI_FRONTIER_CKPT_INTERVAL_S")
        self.checkpoint_interval_s = checkpoint_interval_s
        # Preemption simulation for kill/resume tests (retired-hybrid
        # interrupt_after_batches contract): after this many chunks, force a
        # checkpoint write and raise.
        self.interrupt_after_chunks = interrupt_after_chunks
        # Canonical compile-shape bucketing (the sweep's warm-start
        # discipline): pad the SCC lane count AND the circuit's device axes
        # to the encode PAD_LADDER, so chunk_fn/filter_block compile once
        # per ladder bucket instead of once per exact |scc| (ROUND5_NOTES
        # flags 2-40 s per-shape compiles on small-SCC frontier rows).
        # False keeps exact shapes.
        self.pad_shapes = pad_shapes

    # ---- host-side exact checks (reference semantics) -------------------

    @staticmethod
    def _host_witness_check(
        graph: TrustGraph,
        scc: List[int],
        members: List[int],
        scope_to_scc: bool,
    ) -> Tuple[bool, Optional[Tuple[List[int], List[int]]]]:
        """Exact minimality + disjointness probe for one flagged set.

        Mirrors the oracle's visitor (python_oracle.py): returns
        ``(is_minimal, witness)`` where witness is ``(disjoint, members)``
        or None.  Runs `fbas/semantics.py` end-to-end, so device results
        never reach the verdict unchecked."""
        from quorum_intersection_tpu.backends.python_oracle import is_minimal_quorum

        if not is_minimal_quorum(members, graph):
            return False, None
        if scope_to_scc:
            avail = [False] * graph.n
            for v in scc:
                avail[v] = True
        else:
            avail = [True] * graph.n  # Q6 whole-graph availability (cpp:354)
        for v in members:
            avail[v] = False
        disjoint = max_quorum(graph, scc, avail)
        if disjoint:
            return True, (disjoint, list(members))
        return True, None

    def _make_host_checker(self, graph: TrustGraph, scc: List[int],
                           scope_to_scc: bool):
        """``check(members) -> (minimal, witness|None)`` with the fastest
        exact engine available: the native ``qi_max_quorum`` when the
        library builds (a safe hierarchical search host-checks thousands of
        flagged sets at |D|+2 fixpoints each — interpreted fixpoints would
        rival the device time), degrading to the Python semantics.  Both
        engines implement the same pinned spec (native scan parity is
        tested in test_cpp_backend.py)."""
        try:
            from quorum_intersection_tpu.backends.cpp import NativeMaxQuorum

            nmq = NativeMaxQuorum(graph)
        # qi-lint: allow(degrade-via-ladder) — engine-internal helper choice
        except Exception as exc:  # noqa: BLE001 — no g++ etc.
            log.info("native max-quorum unavailable (%s); host checks use "
                     "the Python semantics", exc)
            return lambda members: self._host_witness_check(
                graph, scc, members, scope_to_scc
            )

        scc_arr = np.asarray(scc, dtype=np.int32)
        avail = np.zeros(graph.n, dtype=np.uint8)  # reused across checks

        def check(members: List[int]) -> Tuple[bool, Optional[Tuple[List[int], List[int]]]]:
            m_arr = np.asarray(members, dtype=np.int32)
            avail[:] = 0
            avail[m_arr] = 1
            if not nmq.count(m_arr, avail):
                return False, None
            for v in members:
                avail[v] = 0
                if nmq.count(m_arr, avail):
                    return False, None
                avail[v] = 1
            if scope_to_scc:
                avail[:] = 0
                avail[scc_arr] = 1
            else:
                avail[:] = 1  # Q6 whole-graph availability (cpp:354)
            avail[m_arr] = 0
            disjoint = nmq(scc_arr, avail)
            if disjoint:
                return True, (disjoint, list(members))
            return True, None

        return check

    # ---- device flag filter ---------------------------------------------

    def _build_flag_filter(self, circuit: Circuit, scc: List[int],
                           scope_to_scc: bool, block: int,
                           probe_circuit: Optional[Circuit] = None):
        """Compile ``filter_block(flags, count) -> (minimal_count, widx)``:
        the flagged-state pipeline as batched device fixpoints.

        For each valid flagged set D (``dontRemove`` already contains a
        quorum, established by the chunk): D is a **minimal** quorum iff no
        single-member removal leaves a quorum inside it (cpp:179-201 — the
        leave-one-out rows run as ONE batch), and a minimal D is a
        **witness** iff the availability-probe fixpoint over ``scc ∖ D``
        (Q6 frozen helpers outside the SCC, cpp:357-384) survives.  Only
        ``widx`` — the first witness candidate, or ``block`` for none —
        ever returns to the host, which re-verifies it with the exact host
        semantics before any verdict: device results alone never decide.

        Measured necessity (hier-7x4, scc 28): 2.5% of popped states flag
        — 583k serial host checks at |D|+2 native fixpoints each rival the
        native oracle's whole search; batched on the accelerator they are
        a handful of matmul dispatches.
        """
        import jax
        import jax.numpy as jnp

        from quorum_intersection_tpu.backends.tpu.kernels import (
            CircuitArrays, fixpoint,
        )

        arrays = CircuitArrays(circuit)
        # Probe availability: with an SCC-restricted circuit the Q6 outside
        # contribution is FOLDED into ``probe_circuit``'s thresholds, so the
        # frozen row is all-zero; unrestricted, the single circuit serves
        # both sides and the frozen row carries the outside nodes.
        probe_arrays = arrays if probe_circuit is None else CircuitArrays(probe_circuit)
        s = len(scc)
        n = circuit.n
        scc_idx = jnp.asarray(np.asarray(scc, dtype=np.int32))
        scc_mask_n = jnp.zeros((n,), dtype=arrays.dtype).at[scc_idx].set(1)
        frozen = (
            jnp.zeros((n,), dtype=probe_arrays.dtype)
            if (scope_to_scc or probe_circuit is not None)
            else (1 - scc_mask_n).astype(probe_arrays.dtype)
        )
        eye_inv = (1 - jnp.eye(s, dtype=jnp.int8))

        @jax.jit
        def filter_block(flags_blk, count):
            valid = jnp.arange(block, dtype=jnp.int32) < count
            member = flags_blk > 0
            # Leave-one-out variants (B, s, s): row (i, j) = D_i ∖ {j}.
            loo = flags_blk[:, None, :] * eye_inv[None, :, :]
            loo_n = jnp.zeros((block * s, n), dtype=arrays.dtype).at[
                :, scc_idx
            ].set(loo.reshape(block * s, s).astype(arrays.dtype))
            q = fixpoint(arrays, loo_n)
            has_q = (q.sum(-1, dtype=jnp.int32) > 0).reshape(block, s)
            minimal = valid & ~jnp.any(has_q & member, axis=1)

            d_n = jnp.zeros((block, n), dtype=probe_arrays.dtype).at[:, scc_idx].set(
                flags_blk.astype(probe_arrays.dtype)
            )
            probe_avail = jnp.clip(
                scc_mask_n.astype(jnp.int32)[None, :] - d_n.astype(jnp.int32), 0, 1
            ).astype(probe_arrays.dtype)
            pq = fixpoint(probe_arrays, probe_avail, frozen)
            probe_hit = pq.sum(-1, dtype=jnp.int32) > 0
            wit = minimal & probe_hit
            widx = jnp.where(
                wit, jnp.arange(block, dtype=jnp.int32), jnp.int32(block)
            ).min()
            return minimal.sum(dtype=jnp.int32), widx

        return filter_block

    # ---- device chunk builder -------------------------------------------

    def _build_chunk(self, circuit: Circuit, scc: List[int], a_scc: np.ndarray,
                     half: int, K: int):
        """Compile ``run_chunk(T, D, top) -> (T, D, top, flags, fcount,
        iters, popped)`` — the device-resident expansion loop."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from quorum_intersection_tpu.backends.tpu.kernels import CircuitArrays, fixpoint

        arrays = CircuitArrays(circuit)
        s = len(scc)
        n = circuit.n
        C = self.arena
        # The loop exits once the (dynamic, host-grown) flag_exit threshold
        # is reached, and one more iteration can flag at most K more — this
        # capacity makes a dropped (lost) flag impossible at the threshold's
        # CAP, which matters for completeness.  Derived from the EFFECTIVE
        # (mesh-rounded) K, not self.pop.
        flag_cap = self.flag_exit * FLAG_EXIT_GROWTH + K

        if self.mesh is not None:
            axis = self.mesh.axis_names[0]
            n_dev = int(self.mesh.devices.size)
            rows = (2 * K) // n_dev  # K is pre-rounded so this is exact

            def batch_fixpoint(stacked):
                # Row-shard the double-height batch: each device evaluates
                # its contiguous block, one tiled all_gather reassembles.
                rank = lax.axis_index(axis)
                mine = lax.dynamic_slice(stacked, (rank * rows, 0), (rows, n))
                return lax.all_gather(
                    fixpoint(arrays, mine), axis, axis=0, tiled=True
                )
        else:
            def batch_fixpoint(stacked):
                return fixpoint(arrays, stacked)
        scc_idx = jnp.asarray(np.asarray(scc, dtype=np.int32))
        # In-degree counts within the SCC, with multiplicity (Q7): a_scc[u, w]
        # = #edges u→w.  Operand dtype follows the centralized CircuitArrays
        # policy: int8 only where that backend supports 8-bit dots (it
        # already encodes the CPU-backend mis-lowering exception,
        # kernels.py:77-80); accumulation stays int32 either way.
        a_dtype = (
            jnp.int8
            if arrays.dtype == jnp.int8 and int(a_scc.max(initial=0)) <= 127
            else jnp.int32
        )
        a_mat = jnp.asarray(a_scc).astype(a_dtype)

        def expand(T, D, top, flags, fcount, iters, popped):
            k = jnp.minimum(top, K)
            base = top - k
            blk_T = lax.dynamic_slice(T, (base, 0), (K, s))
            blk_D = lax.dynamic_slice(D, (base, 0), (K, s))
            valid = (jnp.arange(K, dtype=jnp.int32) < k)

            dsize = blk_D.sum(-1, dtype=jnp.int32)
            union = jnp.maximum(blk_T, blk_D)
            live = valid & (dsize <= half) & (union.sum(-1, dtype=jnp.int32) > 0)

            # Batched fixpoints in full-graph index space (the circuit is
            # n-wide); T, D ⊆ scc so survivors ⊆ scc and the gather back to
            # SCC space below is lossless.  The D-rows and union-rows run as
            # ONE double-height batch: one while_loop convergence instead of
            # two, and a taller matmul for the MXU.
            stacked = jnp.zeros((2 * K, n), dtype=arrays.dtype).at[:, scc_idx].set(
                jnp.concatenate([blk_D, union], axis=0).astype(arrays.dtype)
            )
            out = batch_fixpoint(stacked)[:, scc_idx]
            f1, f2 = out[:K], out[K:]

            d_has_q = live & (f1.sum(-1, dtype=jnp.int32) > 0)
            interior = live & ~d_has_q

            f2i = f2.astype(jnp.int8)
            contained = (blk_D.astype(jnp.int32) * (1 - f2i.astype(jnp.int32))).sum(-1) == 0
            nonempty = f2.sum(-1, dtype=jnp.int32) > 0
            eligible = f2i * (1 - blk_D)
            has_eligible = eligible.sum(-1, dtype=jnp.int32) > 0
            branchable = interior & nonempty & contained & has_eligible

            # Branch variable: max in-degree (from quorum members, with
            # multiplicity) within quorum ∖ dontRemove; argmax breaks ties
            # on the lowest index.  All-zero in-degrees fall through to the
            # lowest-index eligible node (deliberate cpp:221 deviation, see
            # module docstring).
            indeg = lax.dot(
                f2i.astype(a_mat.dtype), a_mat, preferred_element_type=jnp.int32
            )
            masked = jnp.where(eligible > 0, indeg, jnp.int32(-1))
            best = jnp.argmax(masked, axis=-1)
            best_oh = jax.nn.one_hot(best, s, dtype=jnp.int8)

            child_T = eligible * (1 - best_oh)
            incl_D = jnp.minimum(blk_D + best_oh, 1)
            # Pre-push prunes (identical to the entry prunes the children
            # would fail anyway — saves arena slots): the include child dies
            # on the size bound, either child dies when both sets are empty.
            excl_ok = branchable & (
                (child_T.sum(-1, dtype=jnp.int32) + dsize) > 0
            )
            incl_ok = branchable & (dsize + 1 <= half)

            # Compact writes: exclude children above include children so the
            # LIFO pops the exclude branch first (serial order, cpp:336).
            n_child = excl_ok.astype(jnp.int32) + incl_ok.astype(jnp.int32)
            off = jnp.cumsum(n_child) - n_child
            incl_pos = jnp.where(incl_ok, base + off, C)
            excl_pos = jnp.where(
                excl_ok, base + off + incl_ok.astype(jnp.int32), C
            )
            # One scatter per arena array (not one per child kind): both
            # children share T'; D differs (include adds best).
            pos = jnp.concatenate([incl_pos, excl_pos], axis=0)
            T = T.at[pos].set(
                jnp.concatenate([child_T, child_T], axis=0), mode="drop"
            )
            D = D.at[pos].set(
                jnp.concatenate([incl_D, blk_D], axis=0), mode="drop"
            )
            new_top = base + n_child.sum(dtype=jnp.int32)

            # Flag dontRemove-quorum states for the host's exact check.
            nf = d_has_q.astype(jnp.int32)
            fpos = jnp.where(d_has_q, fcount + jnp.cumsum(nf) - nf, flag_cap)
            flags = flags.at[fpos].set(blk_D, mode="drop")
            fcount = jnp.minimum(fcount + nf.sum(dtype=jnp.int32), flag_cap)

            return T, D, new_top, flags, fcount, iters + 1, popped + k

        chunk_iters = self.chunk_iters

        def chunk_fn(T, D, top, flag_exit):
            def cond(carry):
                T, D, top, flags, fcount, iters, popped = carry
                return (
                    (top > 0)
                    & (iters < chunk_iters)
                    & (fcount < flag_exit)
                    & (top <= C - 2 * K)  # overflow guard: host spills
                )

            flags = jnp.zeros((flag_cap, s), dtype=jnp.int8)
            carry = (T, D, top, flags, jnp.int32(0), jnp.int32(0), jnp.int32(0))
            if self.mesh is not None:
                # Seed every carry leaf's manual-axis varyingness from the
                # device rank (numerically a no-op): the loop body produces
                # varying values (all_gather output feeds the scatters), and
                # a replicated init would trip the while_loop carry-type
                # check under shard_map (cf. kernels.fixpoint, sweep.py).
                rank = lax.axis_index(self.mesh.axis_names[0])
                carry = tuple(
                    leaf + rank.astype(leaf.dtype) * 0 for leaf in carry
                )
            return lax.while_loop(cond, lambda c: expand(*c), carry)

        if self.mesh is not None:
            from quorum_intersection_tpu.parallel.mesh import P, shard_map_unchecked

            # Everything replicates in and out; the sharding happens inside
            # batch_fixpoint.  Control flow is identical on every device, so
            # the collective inside the loop always aligns.  The replication
            # check is disabled: the rank-seeded carries are varying-marked
            # but numerically replicated (deterministic identical
            # computation per device), a fact the static checker cannot
            # infer through the while_loop.
            return jax.jit(shard_map_unchecked(
                chunk_fn, self.mesh,
                in_specs=(P(), P(), P(), P()),
                out_specs=(P(), P(), P(), P(), P(), P(), P()),
            ))
        return jax.jit(chunk_fn)

    # ---- main entry ------------------------------------------------------

    def check_scc(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
    ) -> SccCheckResult:
        if circuit is None:
            raise ValueError("frontier backend requires the encoded circuit")
        from quorum_intersection_tpu.utils.compile_cache import enable_compilation_cache

        t0 = time.perf_counter()
        enable_compilation_cache()
        import jax.numpy as jnp

        s = len(scc)
        half = s // 2
        scc_pos = {v: i for i, v in enumerate(scc)}
        a_scc = np.zeros((s, s), dtype=np.int32)
        for u in scc:
            for w in graph.succ[u]:
                j = scc_pos.get(w)
                if j is not None:
                    a_scc[scc_pos[u], j] += 1

        # SCC restriction (encode.restrict_circuit_pair): on graphs wider
        # than the SCC, fold the constant outside-availability into the
        # thresholds and run every device fixpoint s-wide instead of
        # n-wide.  The scoped fold drives the interior (candidate-scoped
        # semantics, matching the oracle's avail construction); the Q6 fold
        # rides into the flag filter's disjointness probe.  Host-side
        # witness checks keep the ORIGINAL graph/scc (exact semantics are
        # never restricted).
        probe_circuit = None
        scc_local = scc
        if circuit.n > s:
            from quorum_intersection_tpu.encode.circuit import restrict_circuit_pair

            scoped_c, q6_c = restrict_circuit_pair(circuit, scc)
            log.debug(
                "frontier restricted to |scc|=%d: n %d->%d, units %d->%d",
                s, circuit.n, scoped_c.n, circuit.n_units, scoped_c.n_units,
            )
            circuit = scoped_c
            # Scoped searches need no separate probe fold (the filter's
            # all-zero frozen row over the scoped circuit IS the scoped
            # probe) — mirroring the sweep's circuit_d=None, and avoiding a
            # duplicate device upload of identical constants.
            probe_circuit = None if scope_to_scc else q6_c
            scc_local = list(range(s))

        # Canonical compile-shape bucketing (ISSUE 5 satellite — the
        # sweep's warm-start discipline applied to the frontier): round the
        # SCC lane count up the encode PAD_LADDER (s -> s_dev) and the
        # circuit's (n, units) axes to their canonical rungs, so the
        # chunk_fn/filter_block compile shapes — which key the persistent
        # XLA compile cache — collapse from "one per exact |scc|" into
        # ladder buckets.  Padded lanes map to inert padded circuit columns
        # (zero votes everywhere, Q2-unsatisfiable root units): they can
        # never enter a quorum, never branch, and never flag, so every
        # state/flag row keeps its support inside the real s lanes.  The
        # checkpoint fingerprint hashes the UNPADDED arrays (fp_circuit
        # below), so checkpoints recorded before this change keep resuming.
        fp_circuit, fp_probe = circuit, probe_circuit
        s_dev = s
        scc_dev = list(scc_local)
        padded_from = None
        if self.pad_shapes:
            from quorum_intersection_tpu.encode.circuit import (
                ladder_up,
                pad_circuit,
            )

            s_dev = ladder_up(s)
            n_to = ladder_up(max(circuit.n, s_dev))
            if circuit.n_units > circuit.n:
                # Preserve the strict inner-unit marker (n_units > n) that
                # pad_targets would collapse when the forced node axis
                # overtakes the unit count.
                units_to = ladder_up(max(circuit.n_units, n_to + 1))
            else:
                units_to = n_to
            if (n_to, units_to) != (circuit.n, circuit.n_units) or s_dev != s:
                padded_from = [s, circuit.n, circuit.n_units]
                pad_base = circuit.n  # padded lanes -> inert padded columns
                circuit = pad_circuit(circuit, n_to, units_to)
                if probe_circuit is not None:
                    probe_circuit = pad_circuit(probe_circuit, n_to, units_to)
                scc_dev += list(range(pad_base, pad_base + (s_dev - s)))
        if s_dev != s:
            a_pad = np.zeros((s_dev, s_dev), dtype=np.int32)
            a_pad[:s, :s] = a_scc
            a_scc = a_pad

        K = self.pop
        if self.mesh is not None:
            # The double-height fixpoint batch must split evenly across the
            # mesh: round the pop block up to a device-count multiple —
            # but never above arena//4, or the overflow-spill compaction's
            # `keep = top - C//2` could go negative (the device loop exits
            # at top > C - 2K, which must stay >= C//2).
            n_dev = int(self.mesh.devices.size)
            if self.arena < 4 * n_dev:
                raise ValueError(
                    f"arena={self.arena} too small for a {n_dev}-device mesh "
                    f"(needs >= {4 * n_dev})"
                )
            K = min(
                ((K + n_dev - 1) // n_dev) * n_dev,
                (self.arena // 4 // n_dev) * n_dev,
            )
        run_chunk = self._build_chunk(circuit, scc_dev, a_scc, half, K)
        # Built lazily on the first flagged batch: majority-style searches
        # flag nothing, and the native engine behind the checker may pay a
        # one-off g++ compile that a pure device run should never wait on.
        host_check = None

        stats = {
            "backend": self.name,
            "device_iters": 0,
            "device_chunks": 0,
            "states_popped": 0,
            "flagged": 0,
            "host_checks": 0,
            "device_flag_checks": 0,
            "minimal_quorums": 0,
            "spills": 0,
            # Dispatched-but-abandoned chunks (witness found / worklist
            # exhausted before the sync): their iters/popped/flagged never
            # reach the counters above, so flag-rate denominators derived
            # from device_chunks alone would overcount coverage.
            "discarded_chunks": 0,
        }
        if padded_from is not None:
            # Warm-start provenance, the sweep's discipline: the canonical
            # ladder shape this run compiled under (s_dev, n, units) and
            # the exact (s, n, units) it would have compiled without
            # bucketing — proves the compile-cache bucketing engaged.
            stats["padded_from"] = padded_from
            stats["padded_shape"] = [s_dev, circuit.n, circuit.n_units]

        C = self.arena  # K fixed above (mesh-rounded) — the host overflow
        # guard and the device loop's exit must use the same value or the
        # two can disagree and livelock.
        T = np.zeros((C, s_dev), dtype=np.int8)
        D = np.zeros((C, s_dev), dtype=np.int8)

        fingerprint = None
        resumed = None
        if self.checkpoint is not None:
            from quorum_intersection_tpu.utils.checkpoint import sweep_fingerprint

            # Masks live in the (possibly restricted) circuit's index space
            # — scc_local, NOT graph ids.  When restricted, the Q6/scoped
            # distinction moved into the probe thresholds, so the frozen
            # row is all-zero and the probe thresholds join the hash to
            # keep the two problems' fingerprints distinct (cf. the sweep's
            # fingerprint block).
            scc_mask = np.zeros(fp_circuit.n, dtype=np.float32)
            scc_mask[scc_local] = 1.0
            frozen = (
                np.zeros(fp_circuit.n, dtype=np.float32)
                if (scope_to_scc or fp_probe is not None)
                else 1.0 - scc_mask
            )
            fingerprint = sweep_fingerprint(
                fp_circuit.members, fp_circuit.child, fp_circuit.thresholds,
                np.asarray(scc, dtype=np.int32), scc_mask, frozen,
                None if fp_probe is None else fp_probe.thresholds,
            )
            resumed = self.checkpoint.resume_states(fingerprint)

        spill: List[Tuple[np.ndarray, np.ndarray]] = []  # host stack of blocks

        def encode_states(pairs) -> Tuple[np.ndarray, np.ndarray]:
            """(toRemove, dontRemove) node-list pairs → int8 bitmask blocks."""
            t_blk = np.zeros((len(pairs), s_dev), dtype=np.int8)
            d_blk = np.zeros((len(pairs), s_dev), dtype=np.int8)
            for r, (to_remove, dont_remove) in enumerate(pairs):
                for v in to_remove:
                    t_blk[r, scc_pos[v]] = 1
                for v in dont_remove:
                    d_blk[r, scc_pos[v]] = 1
            return t_blk, d_blk

        seed = resumed[: C // 2] if resumed else [(list(scc), [])]
        t_blk, d_blk = encode_states(seed)
        top = len(seed)
        T[:top], D[:top] = t_blk, d_blk
        if resumed:
            stats["resumed_states"] = len(resumed)
            # Excess resumed states go to the host spill in C//2-row blocks
            # (same granularity as overflow spills), so draining them later
            # is one chunk per block, not one per state.
            for i in range(C // 2, len(resumed), C // 2):
                spill.append(encode_states(resumed[i: i + C // 2]))

        if self.mesh is not None:
            # Replicated GLOBAL arrays: on a multi-host mesh, plain
            # jnp.asarray builds host-local arrays that a shard_map over the
            # global mesh rejects; an explicit replicated device_put is
            # correct on both single- and multi-host meshes (every process
            # computes identical values, so replication is consistent).
            import jax
            from jax.sharding import NamedSharding

            from quorum_intersection_tpu.parallel.mesh import P

            _sharding = NamedSharding(self.mesh, P())

            def to_dev(x):
                return jax.device_put(jnp.asarray(x), _sharding)
        else:
            def to_dev(x):
                return jnp.asarray(x)

        T_dev = to_dev(T)
        D_dev = to_dev(D)
        top_dev = to_dev(jnp.int32(top))
        witness: Optional[Tuple[List[int], List[int]]] = None
        last_ckpt = time.monotonic()
        first_chunk_s = 0.0
        chunk_s = 0.0  # steady-state chunks, unrounded until loop exit

        # Dynamic flag-exit threshold: starts at the configured value (fast
        # first witness on broken networks), doubles every time a chunk
        # exits flag-bound — safe networks that flag thousands converge to
        # ~one chunk round-trip per flag_cap instead of one per flag_exit.
        flag_exit_cur = self.flag_exit
        flag_exit_cap = self.flag_exit * FLAG_EXIT_GROWTH

        # Flagged sets awaiting their minimality/witness checks.  Processing
        # is deferred until AFTER the next chunk's dispatch, so the checks
        # overlap the device's async execution instead of idling it; every
        # conclusion point (verdict, checkpoint write) drains this first —
        # a pending state is already off the frontier, so a checkpoint
        # written before its check could lose the witness.
        from quorum_intersection_tpu.utils.platform import is_cpu_platform

        use_device_filter = self.flag_check == "device" or (
            self.flag_check == "auto" and not is_cpu_platform()
        )
        flag_block = self.flag_exit * FLAG_EXIT_GROWTH + K
        flag_filter = None  # compiled on the first flagged batch
        pending_flags: Optional[np.ndarray] = None

        def serial_check(rows: np.ndarray) -> bool:
            """Exact host check per row; True iff a witness was found."""
            nonlocal witness, host_check
            if host_check is None:
                host_check = self._make_host_checker(graph, scc, scope_to_scc)
            for row in rows:
                members = [scc[i] for i in np.nonzero(row)[0]]
                stats["host_checks"] += 1
                minimal, hit = host_check(members)
                if minimal:
                    stats["minimal_quorums"] += 1
                if hit is not None:
                    witness = hit
                    return True
            return False

        def process_pending() -> None:
            nonlocal witness, host_check, pending_flags, flag_filter
            rows = pending_flags
            pending_flags = None
            if rows is None or not len(rows):
                return
            if not use_device_filter:
                serial_check(rows)
                return
            if flag_filter is None:
                flag_filter = self._build_flag_filter(
                    circuit, scc_dev, scope_to_scc, flag_block,
                    probe_circuit=probe_circuit,
                )
            for start in range(0, len(rows), flag_block):
                blk = rows[start:start + flag_block]
                cnt = len(blk)
                if cnt < flag_block:
                    padded = np.zeros((flag_block, s_dev), dtype=np.int8)
                    padded[:cnt] = blk
                else:
                    padded = blk
                # qi-lint: allow(hygiene-recompile-hazard) — flag_block-shaped operands by construction: one compile per run
                mins, widx = flag_filter(jnp.asarray(padded), jnp.int32(cnt))
                stats["device_flag_checks"] += cnt
                # qi-lint: allow(hygiene-host-sync) — the worklist must branch on the filter verdict; one sync per flagged block
                widx_h = int(widx)
                if widx_h >= flag_block:
                    # qi-lint: allow(hygiene-host-sync) — same verdict readback; the filter result is already on host
                    stats["minimal_quorums"] += int(mins)
                    continue
                # Device claims a witness candidate: the exact host
                # semantics re-verify it before any verdict.
                if host_check is None:
                    host_check = self._make_host_checker(graph, scc, scope_to_scc)
                members = [scc[i] for i in np.nonzero(blk[widx_h])[0]]
                stats["host_checks"] += 1
                minimal, hit = host_check(members)
                if hit is not None:
                    # qi-lint: allow(hygiene-host-sync) — witness exit: the final ledger readback before returning
                    stats["minimal_quorums"] += int(mins)
                    witness = hit
                    return
                # Disagreement (fixpoint parity is differentially tested, so
                # this should be unreachable): exactness wins — count the
                # already-checked nominee, then redo the REST of the block
                # serially (re-checking the nominee would double-count
                # host_checks in the evidence ledger).
                log.warning(
                    "device flag filter disagreed with the exact host check; "
                    "serial fallback for %d flagged states", cnt,
                )
                if minimal:
                    stats["minimal_quorums"] += 1
                if serial_check(np.delete(blk[:cnt], widx_h, axis=0)):
                    return

        # The whole chunk pipeline is asynchronous: `inflight` holds the
        # dispatched-but-unsynced current chunk (with the flag threshold it
        # was dispatched under), and each loop turn chains a SPECULATIVE
        # next chunk onto its device-resident outputs before the host reads
        # anything.  Speculation is safe because the chunk's own entry
        # guards make it a no-op exactly when the host must intervene:
        # top == 0 (exhausted/refeed) and top > C - 2K (spill) both fail the
        # while_loop cond immediately, returning the carry unchanged — the
        # host then discards the no-op and dispatches a fresh chunk after
        # intervening.  Net effect: in the common path the device never
        # idles across the host's sync + flag handling (one tunnel RTT +
        # the host checks, both now overlapped).
        def dispatch(T_a, D_a, top_a):
            # The threshold scalar goes through to_dev like every other
            # shard_map input: on a multi-host mesh a host-local scalar
            # would be rejected against the P() in_spec.
            return (
                run_chunk(T_a, D_a, top_a, to_dev(jnp.int32(flag_exit_cur))),
                flag_exit_cur,
            )

        t_chunk = time.perf_counter()  # first interval includes trace+compile
        inflight, inflight_fe = dispatch(T_dev, D_dev, top_dev)
        while witness is None:
            # Injectable device-chunk boundary (utils/faults.py): `oom` /
            # `error` simulate the chip failing mid-search — routed through
            # the auto ladder this degrades to the host oracle; driven
            # directly it is a typed, loud failure, never a wrong verdict.
            fault_point("frontier.chunk")
            spec, spec_fe = dispatch(inflight[0], inflight[1], inflight[2])
            # Overlap: host-check the PREVIOUS chunk's flags while the
            # device crunches the current + speculative ones.
            process_pending()
            if witness is not None:
                # The completed-but-unread inflight chunk AND the
                # speculative chunk just dispatched are both abandoned:
                # their iters/popped/flagged never reach stats (syncing
                # here would stall a broken network's verdict by a chunk).
                # The marker keeps flag-rate denominators honest.
                stats["discarded_chunks"] += 2
                break
            T_dev, D_dev, top_dev, flags, fcount, iters, popped = inflight
            fcount_h = int(fcount)  # sync point: chunk fully drained here
            if stats["device_chunks"] == 0:
                # First call traces + compiles; keeping it separate makes
                # the on-chip ledger interpretable (compile through the
                # tunnel is seconds and high-variance).
                first_chunk_s = time.perf_counter() - t_chunk
            else:
                chunk_s += time.perf_counter() - t_chunk
            top_h = int(top_dev)
            stats["device_chunks"] += 1
            stats["device_iters"] += int(iters)
            stats["states_popped"] += int(popped)
            stats["flagged"] += fcount_h
            # qi-cert: the frontier's coverage unit is the drained chunk —
            # the count the certificate's ledger echoes for B&B engines.
            get_run_record().add("cert.frontier_chunks")
            log.debug(
                "frontier chunk %d: %d iters, %d popped, top=%d, %d flagged "
                "(exit at %d), %d spilled blocks",
                stats["device_chunks"], int(iters), int(popped), top_h,
                fcount_h, flag_exit_cur, len(spill),
            )

            if fcount_h:
                pending_flags = np.asarray(flags[:fcount_h], dtype=np.int8)
                # Grow against the threshold THIS chunk was dispatched with:
                # the speculative chunk always runs one threshold behind, so
                # comparing against the already-doubled current value would
                # stall growth to every other chunk.
                if fcount_h >= inflight_fe and flag_exit_cur < flag_exit_cap:
                    flag_exit_cur = min(
                        max(flag_exit_cur, inflight_fe) * 2, flag_exit_cap
                    )

            intervened = False
            if top_h > C - 2 * K:
                # Overflow: spill the OLDEST half of the stack (indices
                # [0, C//2)) to the host and compact the rest down.
                # np.array (not asarray): device buffers view as read-only.
                T_h = np.array(T_dev)
                D_h = np.array(D_dev)
                spill.append((T_h[: C // 2].copy(), D_h[: C // 2].copy()))
                keep = top_h - C // 2
                T_h[:keep] = T_h[C // 2: top_h]
                D_h[:keep] = D_h[C // 2: top_h]
                T_dev, D_dev, top_dev = (
                    to_dev(T_h), to_dev(D_h), to_dev(jnp.int32(keep))
                )
                top_h = keep
                stats["spills"] += 1
                intervened = True
            elif top_h == 0:
                if not spill:
                    # Worklist exhausted: drain any still-pending flags (the
                    # overlap defers them one chunk) before concluding that
                    # all quorums intersect.  The speculative chunk dispatched
                    # at the loop top is abandoned unread (it ran as a
                    # guarded no-op against the empty stack).
                    stats["discarded_chunks"] += 1
                    process_pending()
                    break
                T_blk, D_blk = spill.pop()
                # Re-feed a spilled block (valid rows are the nonempty ones —
                # spilled blocks are dense prefixes by construction).
                live = np.nonzero((T_blk | D_blk).any(axis=1))[0]
                T_h = np.zeros((C, s_dev), dtype=np.int8)
                D_h = np.zeros((C, s_dev), dtype=np.int8)
                T_h[: len(live)] = T_blk[live]
                D_h[: len(live)] = D_blk[live]
                T_dev, D_dev, top_dev = (
                    to_dev(T_h), to_dev(D_h), to_dev(jnp.int32(len(live)))
                )
                top_h = len(live)
                intervened = True

            if self.checkpoint is not None and witness is None:
                # Same post-witness write suppression as the retired hybrid: the
                # witness-bearing state is resolved and absent from the
                # frontier, so a write+kill after the witness could resume
                # into a witness-free remainder and flip the verdict.  Any
                # flags still pending from THIS chunk are part of "resolved"
                # — drain them (losing the overlap for this one chunk)
                # before writing, or a kill after the write could lose a
                # pending witness.
                due_interrupt = (
                    self.interrupt_after_chunks is not None
                    and stats["device_chunks"] >= self.interrupt_after_chunks
                    and (top_h > 0 or spill)
                )
                due_interval = (
                    time.monotonic() - last_ckpt >= self.checkpoint_interval_s
                )
                if due_interrupt or due_interval:
                    process_pending()
                    if witness is not None:
                        # The speculative chunk dispatched this turn is
                        # abandoned unread (cf. the loop-top break marker).
                        stats["discarded_chunks"] += 1
                        break
                if due_interrupt:
                    self._write_checkpoint(T_dev, D_dev, top_h, spill, scc, fingerprint)
                    raise FrontierSearchInterrupted(
                        f"simulated preemption after {stats['device_chunks']} chunks"
                    )
                if due_interval:
                    self._write_checkpoint(T_dev, D_dev, top_h, spill, scc, fingerprint)
                    last_ckpt = time.monotonic()

            if intervened:
                # The speculative chunk ran as a guarded no-op against the
                # pre-intervention state; drop it and dispatch fresh on the
                # spilled/re-fed arrays.
                stats["discarded_chunks"] += 1
                inflight, inflight_fe = dispatch(T_dev, D_dev, top_dev)
            else:
                inflight, inflight_fe = spec, spec_fe
            t_chunk = time.perf_counter()

        stats["seconds"] = time.perf_counter() - t0
        stats["first_chunk_seconds"] = round(first_chunk_s, 3)
        stats["chunk_seconds"] = round(chunk_s, 3)
        # qi-cert ledger (cert.py ledger_entry): the frontier's coverage
        # evidence is its worklist accounting — chunks drained, states
        # popped/flagged, and how many flagged sets passed the exact
        # minimality/host checks.  No window space: completeness rests on
        # the B&B invariant, which the differential suites pin.
        stats["cert"] = {
            "frontier_chunks_drained": stats["device_chunks"],
            "states_popped": stats["states_popped"],
            "flagged": stats["flagged"],
            "minimal_quorums": stats["minimal_quorums"],
            "host_checks": stats["host_checks"],
            "device_flag_checks": stats["device_flag_checks"],
        }
        if self.checkpoint is not None:
            self.checkpoint.clear()
        if witness is not None:
            q1, q2 = witness
            return SccCheckResult(intersects=False, q1=q1, q2=q2, stats=stats)
        return SccCheckResult(intersects=True, stats=stats)

    def _write_checkpoint(self, T_dev, D_dev, top, spill, scc, fingerprint) -> None:
        """Persist the full frontier (device stack + host spill) in the
        FrontierCheckpoint (toRemove, dontRemove) node-list format."""
        states = []

        def add_block(T_blk, D_blk):
            for t_row, d_row in zip(T_blk, D_blk):
                if not (t_row.any() or d_row.any()):
                    continue
                states.append([
                    [scc[i] for i in np.nonzero(t_row)[0]],
                    [scc[i] for i in np.nonzero(d_row)[0]],
                ])

        # Slice on device BEFORE the transfer: the arena is ~16 MB while the
        # live stack is usually a few rows, and this runs every few seconds.
        add_block(np.asarray(T_dev[:top]), np.asarray(D_dev[:top]))
        for T_blk, D_blk in spill:
            add_block(T_blk, D_blk)
        self.checkpoint.record(states, fingerprint)
