"""Batched quorum kernels on the threshold circuit — the TPU compute core.

The reference's hot leaves are ``containsQuorumSlice`` / ``containsQuorum``
(`/root/reference/quorum_intersection.cpp:90-177`) — per-node recursion with
early exits, evaluated one candidate set at a time.  The TPU-native
re-design evaluates **millions of candidate sets at once** as dense linear
algebra over the flattened threshold circuit (``encode/circuit.py``):

- slice satisfaction for a whole batch is ``avail @ membersᵀ`` (one MXU
  matmul) plus, for nested quorum sets, ``depth`` sweeps of ``sat @ childᵀ``
  (more matmuls) against the threshold vector;
- the greatest-fixpoint quorum (cpp:147's ``f(X) = {x ∈ X : slice(x) ⊆ X}``)
  is a ``lax.while_loop`` that runs until **every row** of the batch is
  stable — converged rows are idempotent under the update, so batch-wide
  convergence needs no per-row masking and terminates in ≤ n+1 sweeps;
- a ``frozen`` availability mask supports the reference's whole-graph
  availability semantics (Q6, cpp:354): frozen nodes satisfy slices but are
  never filtered by the fixpoint — exactly how ``containsQuorum`` never
  removes nodes outside its candidate list.

Two dtype regimes, chosen per circuit:

- **int8 operands, int32 accumulation** (the default): masks and vote-count
  matrices are 0/1/small-count int8, ``lax.dot(..,
  preferred_element_type=int32)`` rides the MXU's 8-bit path (2× bf16, ~4×
  f32 throughput on v5e) and is *exact* — int32 accumulation cannot lose
  counts for any n < 2^31;
- **float32 fallback** when a vote count exceeds int8 range (a validator or
  inner set repeated >127 times in one quorum set — pathological but legal):
  0/1 floats with counts far below 2^24 are equally exact.

Dispatch granularity matters as much as dtype on a tunneled single chip: a
device program has a fixed multi-ms overhead regardless of content (measured:
1 matmul ≈ 8 full sweeps per program), so :func:`sweep_program_factory` packs
``steps_per_call`` whole sweep blocks into ONE program via ``lax.fori_loop``,
reducing everything to a single scalar — the smallest hit index.  The sweep
driver (sweep.py) ramps ``steps_per_call`` up as the enumeration proves
large, amortizing the overhead to noise (measured ~40× end-to-end).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from quorum_intersection_tpu.backends.base import INT32_MAX
from quorum_intersection_tpu.encode.circuit import Circuit

# int8 operands hold vote counts ≤ 127; circuits with larger multiplicities
# (legal but pathological input) fall back to exact float32.
_INT8_MAX_COUNT = 127


class CircuitArrays:
    """Device-resident circuit constants, shared by all kernels.

    ``dtype`` is the operand dtype (int8 fast path / float32 fallback);
    ``acc`` the matmul accumulator dtype (int32 / float32); ``thresholds``
    live in ``acc`` so threshold compares need no casts.
    """

    def __init__(self, circuit: Circuit):
        self.n = circuit.n
        self.n_units = circuit.n_units
        self.depth = circuit.depth
        int8_ok = (
            int(circuit.members.max(initial=0)) <= _INT8_MAX_COUNT
            and int(circuit.child.max(initial=0)) <= _INT8_MAX_COUNT
        )
        if not int8_ok:
            self.dtype = self.acc = jnp.float32
        elif jax.default_backend() == "cpu":
            # XLA's CPU backend mis-lowers int8 dots with int32 accumulation
            # into mixed i32+i8 adds (LLVM verifier failure); int32 operands
            # keep the exact integer semantics without the 8-bit lowering.
            self.dtype = self.acc = jnp.int32
        else:
            self.dtype = jnp.int8
            self.acc = jnp.int32
        self.members_t = jnp.asarray(circuit.members.T, dtype=self.dtype)  # (n, U)
        self.thresholds = jnp.asarray(circuit.thresholds, dtype=self.acc)  # (U,)
        self.has_inner = circuit.n_units > circuit.n
        if self.has_inner:
            self.child_t = jnp.asarray(circuit.child.T, dtype=self.dtype)  # (U, U)
        else:
            self.child_t = None

    def dot(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """Matmul in the operands' dtype with exact accumulation."""
        return lax.dot(a, b, preferred_element_type=self.acc)

    def cast(self, x) -> jnp.ndarray:
        return jnp.asarray(x).astype(self.dtype)


def node_sat(arrays: CircuitArrays, avail: jnp.ndarray) -> jnp.ndarray:
    """Which nodes have a satisfied slice under ``avail``?

    ``avail``: (B, n) 0/1 in ``arrays.dtype``.  Returns (B, n) 0/1 same dtype.
    Self-availability (Q4) is the trailing elementwise product.
    """
    base = arrays.dot(avail, arrays.members_t)  # (B, U) direct-validator votes
    # First sweep: sat starts all-zero, so the child contribution is zero —
    # evaluate leaves directly instead of multiplying a zero matrix.  The
    # remaining `depth` sweeps propagate inner-set satisfaction up the DAG.
    sat = (base >= arrays.thresholds).astype(arrays.dtype)
    for _ in range(arrays.depth if arrays.has_inner else 0):
        sat = ((base + arrays.dot(sat, arrays.child_t)) >= arrays.thresholds).astype(
            arrays.dtype
        )
    return sat[..., : arrays.n] * avail


def fixpoint(
    arrays: CircuitArrays,
    avail: jnp.ndarray,
    frozen: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Greatest-fixpoint quorum per batch row (cpp:140-177 batched).

    ``avail``: (B, n) 0/1 candidate sets (any numeric dtype; cast to the
    circuit's operand dtype).  ``frozen``: optional (n,) 0/1 mask of nodes
    that remain available for slice satisfaction but are never filtered (Q6
    whole-graph availability; ``None`` ⇒ scoped).  Returns (B, n) 0/1 in
    ``arrays.dtype`` — the surviving quorum of each row (all-zero ⇒ no quorum
    inside that candidate set).
    """
    if frozen is None:
        frozen_row = jnp.zeros((arrays.n,), dtype=arrays.dtype)
    else:
        frozen_row = arrays.cast(frozen)

    def body(carry):
        a, _ = carry
        total = jnp.maximum(a, frozen_row)  # frozen helpers always available
        nxt = node_sat(arrays, total) * a  # only candidates can survive
        changed = jnp.any(nxt != a)
        return nxt, changed

    def cond(carry):
        return carry[1]

    a0 = arrays.cast(avail)
    # Derive the initial "changed" flag from the data (it is trivially True)
    # so the carry inherits the input's manual-axis varyingness under
    # shard_map — a literal jnp.bool_(True) would be replicated and trip the
    # while_loop carry-type check on sharded meshes.
    changed0 = jnp.any(a0 == a0)
    out, _ = lax.while_loop(cond, body, (a0, changed0))
    return out


def fixpoint_iters(
    arrays: CircuitArrays,
    avail: jnp.ndarray,
    frozen: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`fixpoint` plus the executed while_loop trip count.

    The trip count IS the batch's compute cost (every iteration re-evaluates
    the whole batch until the slowest row stabilizes), which is what the
    bench's roofline estimate needs: MACs/candidate = trips × per-iteration
    matmul cost (node_sat: n·U direct votes + depth·U² child propagation).
    Kept out of the hot sweep program — the counter is an extra carry."""
    if frozen is None:
        frozen_row = jnp.zeros((arrays.n,), dtype=arrays.dtype)
    else:
        frozen_row = arrays.cast(frozen)

    def body(carry):
        a, _, k = carry
        total = jnp.maximum(a, frozen_row)
        nxt = node_sat(arrays, total) * a
        return nxt, jnp.any(nxt != a), k + 1

    a0 = arrays.cast(avail)
    out, _, trips = lax.while_loop(
        lambda c: c[1], body, (a0, jnp.any(a0 == a0), jnp.int32(0))
    )
    return out, trips


def make_batch_fixpoint(
    circuit: Circuit,
) -> Callable[[np.ndarray, Optional[np.ndarray]], np.ndarray]:
    """Host-callable jitted batch fixpoint: (B, n) bool → (B, n) bool."""
    arrays = CircuitArrays(circuit)

    @jax.jit
    def run_jit(avail, frozen):
        return fixpoint(arrays, avail, frozen)

    def run(avail: np.ndarray, frozen: Optional[np.ndarray] = None) -> np.ndarray:
        a = arrays.cast(np.asarray(avail))
        f = (
            jnp.zeros((arrays.n,), dtype=arrays.dtype)
            if frozen is None
            else arrays.cast(np.asarray(frozen))
        )
        return np.asarray(run_jit(a, f)) != 0

    return run


def bit_positions(bit_nodes: np.ndarray, n: int) -> np.ndarray:
    """Per-node bit index for the subset decode: ``pos[bit_nodes[j]] = j``,
    every other node 31.  Shifting a non-negative int32 index right by 31
    yields bit 0, so non-enumerated nodes decode to "absent" with no masking.
    """
    pos = np.full((n,), 31, dtype=np.int32)
    for j, v in enumerate(np.asarray(bit_nodes, dtype=np.int32)):
        pos[int(v)] = j
    return pos


def decode_masks(start: jnp.ndarray, batch: int, pos: jnp.ndarray, dtype) -> jnp.ndarray:
    """Decode candidate indices ``start + [0, batch)`` into (batch, n) 0/1
    availability rows via per-node right-shifts (``pos`` from
    :func:`bit_positions`) — a dense vectorized op, no scatter.  Indices must
    stay below 2^31 (callers cap the enumeration width; SURVEY.md §7.3's
    uint32-lane note — JAX has no x64 by default).
    """
    idx = start + jnp.arange(batch, dtype=jnp.int32)  # (B,)
    return ((idx[:, None] >> pos[None, :]) & 1).astype(dtype)


def subset_masks(start: jnp.ndarray, batch: int, bit_nodes: jnp.ndarray, n: int) -> jnp.ndarray:
    """Decode candidate indices into (batch, n) float32 0/1 rows: bit *j* of
    the index toggles node ``bit_nodes[j]`` (test/reference surface; the
    compiled kernels use :func:`decode_masks` with a host-built ``pos``)."""
    pos = jnp.full((n,), 31, dtype=jnp.int32).at[bit_nodes].set(
        jnp.arange(bit_nodes.shape[0], dtype=jnp.int32)
    )
    return decode_masks(start, batch, pos, jnp.float32)


def sweep_step(
    arrays: CircuitArrays,
    start: jnp.ndarray,
    batch: int,
    pos: jnp.ndarray,
    scc_mask: jnp.ndarray,
    frozen: jnp.ndarray,
    hi_mask: Optional[jnp.ndarray] = None,
    arrays_d: Optional[CircuitArrays] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Evaluate one contiguous block of candidate subsets.

    For each candidate S (a subset of the enumeration nodes):
      Q = fixpoint(S); hit ⇔ Q ≠ ∅ ∧ fixpoint(scc ∖ Q) ≠ ∅
    — i.e. S exposes a disjoint quorum pair (see sweep.py for the
    verdict-equivalence argument).

    ``pos``: (n,) int32 from :func:`bit_positions`; ``scc_mask``/``frozen``:
    (n,) 0/1 in ``arrays.dtype``.  ``hi_mask``: optional (n,) 0/1 row of
    additionally-available nodes — the *high bits* of a wide (>2^31)
    enumeration, constant across the block (sweep.py two-level decode).
    Returns ``(hit, q_size)``: (B,) bool hit flags and (B,) int32 quorum
    sizes (diagnostics).  Witness reconstruction happens on the host from
    the first hit index.
    """
    # The Q fixpoint is scoped to the candidates; the D probe runs under the
    # availability the caller encodes in ``frozen`` — OR, when the circuit
    # was SCC-restricted (encode.restrict_circuit_pair), in ``arrays_d``'s
    # pre-folded thresholds with frozen all-zero.
    ad = arrays if arrays_d is None else arrays_d
    avail = decode_masks(start, batch, pos, arrays.dtype)
    if hi_mask is not None:
        avail = jnp.maximum(avail, hi_mask)
    q = fixpoint(arrays, avail)
    q_size = q.sum(axis=-1, dtype=jnp.int32)
    complement = jnp.clip(scc_mask - q, 0, 1).astype(ad.dtype)
    d = fixpoint(ad, complement, frozen)
    hit = jnp.logical_and(q_size > 0, d.sum(axis=-1, dtype=jnp.int32) > 0)
    return hit, q_size


def sweep_constants(
    circuit: Circuit,
    bit_nodes: np.ndarray,
    scc_mask: np.ndarray,
    frozen: Optional[np.ndarray],
) -> Tuple[CircuitArrays, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Upload the device-resident constants every sweep program closes over:
    ``(arrays, pos, scc_mask, frozen)`` — shared by the single-device
    program factory and the mesh-sharded step builder (sweep.py)."""
    arrays = CircuitArrays(circuit)
    pos_j = jnp.asarray(bit_positions(bit_nodes, circuit.n))
    scc_mask_j = arrays.cast(scc_mask)
    frozen_j = (
        jnp.zeros((circuit.n,), dtype=arrays.dtype)
        if frozen is None
        else arrays.cast(frozen)
    )
    return arrays, pos_j, scc_mask_j, frozen_j


def sweep_program_factory(
    circuit: Circuit,
    bit_nodes: np.ndarray,
    scc_mask: np.ndarray,
    frozen: Optional[np.ndarray],
    batch: int,
    circuit_d: Optional[Circuit] = None,
) -> Callable[[int], Callable[[int], jnp.ndarray]]:
    """Build sweep programs sharing one set of device-resident constants.

    ``factory(steps_per_call)`` compiles a program covering ``batch ×
    steps_per_call`` candidates, reduced to one device scalar: the smallest
    hit candidate index, or INT32_MAX for a clean miss.  The circuit arrays,
    bit-position table, and masks upload once and are closed over by every
    ramp level the driver compiles.

    ``steps_per_call`` sub-blocks run inside one program via ``fori_loop``,
    amortizing the fixed per-program dispatch overhead (module docs); the
    scalar result keeps the host↔device transfer at 4 bytes and — because the
    call is *asynchronous* — lets the sweep driver pipeline several programs
    in flight, hiding the tunneled chip's round-trip latency.
    """
    arrays, pos_j, scc_mask_j, frozen_j = sweep_constants(
        circuit, bit_nodes, scc_mask, frozen
    )
    arrays_d = None if circuit_d is None else CircuitArrays(circuit_d)
    zeros_hi = jnp.zeros((circuit.n,), dtype=arrays.dtype)

    def block_min_hit(start, hi_mask):
        hit, _ = sweep_step(
            arrays, start, batch, pos_j, scc_mask_j, frozen_j, hi_mask,
            arrays_d=arrays_d,
        )
        idx = start + jnp.arange(batch, dtype=jnp.int32)
        return jnp.where(hit, idx, jnp.int32(INT32_MAX)).min()

    def factory(steps_per_call: int) -> Callable[..., jnp.ndarray]:
        @jax.jit
        def step(start0, hi_mask):
            if steps_per_call == 1:
                return block_min_hit(start0, hi_mask)

            def body(i, best):
                return jnp.minimum(best, block_min_hit(start0 + i * batch, hi_mask))

            return lax.fori_loop(0, steps_per_call, body, jnp.int32(INT32_MAX))

        # hi_mask: (n,) 0/1 np row of high-bit nodes for wide sweeps (one
        # device upload per outer chunk; same compiled program).
        return make_aot_dispatch(step, zeros_hi, arrays.cast)

    return factory


def guard_program_factory(
    circuit: Circuit, batch: int
) -> Callable[[np.ndarray], np.ndarray]:
    """Block-guard fixpoint program (ISSUE 10 device-side pruning).

    Returns ``run(masks)``: (B, n) 0/1 maximal-candidate rows — one per
    window block, built by the sweep driver's prune planner — evaluated
    through the Q-side greatest fixpoint to (B,) int32 survivor counts.
    A zero count proves the block's maximal candidate contains NO quorum,
    so (by monotonicity of the greatest fixpoint in its candidate set) no
    window of the block can hit and the whole block is skippable.  Rows
    are chunked to a fixed ``batch`` shape (zero-padded tail) so the
    whole guard pass compiles exactly one program.
    """
    arrays = CircuitArrays(circuit)
    batch = max(int(batch), 1)

    @jax.jit
    def step(masks: jnp.ndarray) -> jnp.ndarray:
        return fixpoint(arrays, masks).sum(axis=-1, dtype=jnp.int32)

    def run(masks: np.ndarray) -> np.ndarray:
        rows = masks.shape[0]
        out = np.empty((rows,), dtype=np.int32)
        for lo in range(0, rows, batch):
            chunk = masks[lo : lo + batch]
            if chunk.shape[0] < batch:
                pad = np.zeros((batch, masks.shape[1]), dtype=masks.dtype)
                pad[: chunk.shape[0]] = chunk
                chunk = pad
            out[lo : lo + batch] = np.asarray(step(arrays.cast(chunk)))[
                : rows - lo
            ]
        return out

    return run


def decode_masks_packed(
    starts_lane: jnp.ndarray, batch: int, pos: jnp.ndarray, dtype
) -> jnp.ndarray:
    """Packed twin of :func:`decode_masks`: each lane decodes against its
    OWN group's candidate index.  ``starts_lane``: (n,) int32 — the owning
    group's current start index broadcast to that group's lanes
    (``starts[lane_group]``, see ``encode.PackedCircuit.decode_tables``).
    Row r of the block decodes candidate ``starts[g] + r`` for every group
    at once; padded lanes carry ``pos`` 31 and decode to 0 as usual.
    """
    idx = starts_lane[None, :] + jnp.arange(batch, dtype=jnp.int32)[:, None]
    return ((idx >> pos[None, :]) & 1).astype(dtype)


def packed_sweep_step(
    arrays: CircuitArrays,
    starts_lane: jnp.ndarray,
    batch: int,
    pos: jnp.ndarray,
    scc_mask: jnp.ndarray,
    group_ind: jnp.ndarray,
    arrays_d: Optional[CircuitArrays] = None,
    group_ind_d: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """One contiguous candidate block over a lane-packed circuit — the
    packed twin of :func:`sweep_step`, with PER-GROUP hit reduction.

    The packed circuit is block-diagonal (``encode.pack_circuits``), so the
    two fixpoints below compute every group's fixpoint independently in the
    same matmuls; the per-group survivor counts come out of one
    ``(B, n) x (n, K)`` indicator matmul instead of a lane-axis sum.
    Packed members are SCC-restricted, so all outside availability is
    folded into thresholds and no frozen row exists (``arrays_d`` carries
    the Q6 fold when any member probes under whole-graph availability).
    Returns ``hit``: (B, K) bool — group g's row r exposes a disjoint
    quorum pair for candidate ``starts[g] + r``.
    """
    ad = arrays if arrays_d is None else arrays_d
    gid = group_ind if group_ind_d is None else group_ind_d
    avail = decode_masks_packed(starts_lane, batch, pos, arrays.dtype)
    q = fixpoint(arrays, avail)
    q_sizes = arrays.dot(q, group_ind)  # (B, K) per-group survivor counts
    complement = jnp.clip(scc_mask - q, 0, 1).astype(ad.dtype)
    d = fixpoint(ad, complement)
    d_sizes = ad.dot(d, gid)
    return jnp.logical_and(q_sizes > 0, d_sizes > 0)


def packed_sweep_program_factory(
    circuit: Circuit,
    circuit_d: Optional[Circuit],
    pos: np.ndarray,
    scc_mask: np.ndarray,
    lane_group: np.ndarray,
    group_ind: np.ndarray,
    batch: int,
) -> Callable[[int], Callable]:
    """Packed twin of :func:`sweep_program_factory`.

    ``factory(steps_per_call)`` compiles a program covering ``batch ×
    steps_per_call`` candidates PER GROUP, reduced to one (K,) int32 vector:
    each group's smallest hit candidate index in the block, or INT32_MAX
    for that group's clean miss.  All groups advance in lockstep inside the
    program (``starts + i*batch``); the driver owns per-group ranges and
    masks overshoot on the host.
    """
    arrays = CircuitArrays(circuit)
    arrays_d = None if circuit_d is None else CircuitArrays(circuit_d)
    pos_j = jnp.asarray(pos)
    lane_group_j = jnp.asarray(lane_group)
    scc_j = arrays.cast(scc_mask)
    gi = arrays.cast(group_ind)
    gi_d = gi if arrays_d is None else arrays_d.cast(group_ind)
    k = int(group_ind.shape[1])

    def block_min_hit(starts):
        starts_lane = starts[lane_group_j]
        hit = packed_sweep_step(
            arrays, starts_lane, batch, pos_j, scc_j, gi,
            arrays_d=arrays_d, group_ind_d=gi_d,
        )
        idx = starts[None, :] + jnp.arange(batch, dtype=jnp.int32)[:, None]
        return jnp.where(hit, idx, jnp.int32(INT32_MAX)).min(axis=0)

    def factory(steps_per_call: int) -> Callable:
        @jax.jit
        def step(starts0):
            if steps_per_call == 1:
                return block_min_hit(starts0)

            def body(i, best):
                return jnp.minimum(best, block_min_hit(starts0 + i * batch))

            return lax.fori_loop(
                0, steps_per_call, body,
                jnp.full((k,), INT32_MAX, dtype=jnp.int32),
            )

        return make_packed_aot_dispatch(step, k)

    return factory


def make_packed_aot_dispatch(step, k: int) -> Callable:
    """:func:`make_aot_dispatch` for packed programs: the input is the
    (K,) per-group starts vector instead of a scalar + hi mask.  Same
    contract otherwise (``.precompile`` ramp hook, ``.xla_compile_seconds``
    warm-start stat, compile-once lock)."""
    state: dict = {}
    lock = threading.Lock()

    def precompile():
        with lock:
            if "compiled" not in state:
                lowered = step.lower(jax.ShapeDtypeStruct((k,), jnp.int32))
                tc = time.monotonic()
                state["compiled"] = lowered.compile()
                state["xla_seconds"] = time.monotonic() - tc
        return state["compiled"]

    def dispatch(starts):
        return precompile()(jnp.asarray(starts, dtype=jnp.int32))

    dispatch.precompile = precompile
    dispatch.xla_compile_seconds = lambda: state.get("xla_seconds", 0.0)
    return dispatch


# ---------------------------------------------------------------------------
# Bitset sweep kernels (ISSUE 20 qi-sparse): the same fixpoint semantics as
# the dense kernels above over the packed-uint32 encoding
# (``encode.circuit.BitsetCircuit``) — per-unit vote counts come from
# intersect-and-popcount over 32-node words instead of an (n, U) matmul.
# The dense dot streams the full vote matrix regardless of density; the
# bitset word loop does ``words = ceil(n/32)`` AND+popcount passes over a
# (B, U) tile each, a ~32× narrower operand stream that wins once n
# outgrows a few lane tiles (the calibration crossover row).  Differential
# parity with the dense path and the NumPy oracle is pinned by
# tests/test_qi_sparse.py; the fused Pallas twin lives in pallas_sweep.py.


class BitsetArrays:
    """Device-resident bitset-circuit constants (the `CircuitArrays` twin).

    Word tables upload TRANSPOSED — ``member_w`` is (words, U) so the vote
    loop broadcasts one (B, 1) word column against one (1, U) table row per
    word; ``child_w`` is (unit_words, U) likewise."""

    def __init__(self, bitset) -> None:
        self.n = bitset.n
        self.n_units = bitset.n_units
        self.depth = bitset.depth
        self.words = bitset.words
        self.unit_words = bitset.unit_words
        self.has_inner = bitset.n_units > bitset.n and bitset.child_words is not None
        self.member_w = jnp.asarray(np.ascontiguousarray(bitset.member_words.T))
        self.thresholds = jnp.asarray(bitset.thresholds.astype(np.int32))
        self.child_w = (
            jnp.asarray(np.ascontiguousarray(bitset.child_words.T))
            if self.has_inner
            else None
        )


def popcount_votes(avail_words: jnp.ndarray, table_w: jnp.ndarray) -> jnp.ndarray:
    """Per-unit vote counts: ``(B, W) uint32 × (W, U) uint32 → (B, U) int32``
    via ``Σ_w popcount(avail[:, w] & table[w, :])`` — the bitset twin of the
    dense ``avail @ membersᵀ`` dot.  The word loop is a static Python unroll
    (W ≤ 32 for ladder shapes), keeping peak intermediates at (B, U)."""
    votes = None
    for w in range(int(table_w.shape[0])):
        hits = lax.population_count(
            avail_words[..., w : w + 1] & table_w[w][None, :]
        ).astype(jnp.int32)
        votes = hits if votes is None else votes + hits
    return votes


def pack_bits(bits: jnp.ndarray, words: int) -> jnp.ndarray:
    """Pack 0/1 lanes ``(..., m)`` into uint32 words ``(..., words)`` on
    device (LSB-first, the `encode.circuit.pack_mask_words` convention).
    The shifted terms occupy disjoint bits, so the sum IS the bitwise OR."""
    m = int(bits.shape[-1])
    b = bits.astype(jnp.uint32)
    pad = words * 32 - m
    if pad > 0:
        b = jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, pad)])
    b = b.reshape(b.shape[:-1] + (words, 32))
    shifts = jnp.left_shift(jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(b * shifts, axis=-1, dtype=jnp.uint32)


def bitset_count(words_arr: jnp.ndarray) -> jnp.ndarray:
    """Population count over the word axis: ``(..., W) → (...,)`` int32."""
    return jnp.sum(
        lax.population_count(words_arr).astype(jnp.int32), axis=-1, dtype=jnp.int32
    )


def bitset_node_sat(ba: BitsetArrays, avail_words: jnp.ndarray) -> jnp.ndarray:
    """Bitset twin of :func:`node_sat`: ``(B, words)`` availability words →
    ``(B, words)`` satisfied-node words (Q4 self-availability included via
    the trailing AND, exactly the dense path's ``sat[..., :n] * avail``)."""
    base = popcount_votes(avail_words, ba.member_w)
    sat = (base >= ba.thresholds).astype(jnp.int32)
    for _ in range(ba.depth if ba.has_inner else 0):
        inner = popcount_votes(pack_bits(sat, ba.unit_words), ba.child_w)
        sat = ((base + inner) >= ba.thresholds).astype(jnp.int32)
    return pack_bits(sat[..., : ba.n], ba.words) & avail_words


def bitset_fixpoint(
    ba: BitsetArrays,
    avail_words: jnp.ndarray,
    frozen_words: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Greatest-fixpoint quorum per batch row over packed words — the
    :func:`fixpoint` twin: identical iteration structure and Q6 frozen
    semantics, with OR standing in for the dense max (masks are 0/1)."""
    if frozen_words is None:
        frozen_row = jnp.zeros((ba.words,), dtype=jnp.uint32)
    else:
        frozen_row = frozen_words

    def body(carry):
        a, _ = carry
        total = a | frozen_row  # frozen helpers always available
        nxt = bitset_node_sat(ba, total) & a  # only candidates survive
        return nxt, jnp.any(nxt != a)

    # Same data-derived initial flag as the dense fixpoint (shard_map
    # varyingness note there) — the bitset path never runs sharded today,
    # but the idiom costs nothing and keeps the twins line-for-line.
    changed0 = jnp.any(avail_words == avail_words)
    out, _ = lax.while_loop(lambda c: c[1], body, (avail_words, changed0))
    return out


def bitset_sweep_step(
    ba: BitsetArrays,
    start: jnp.ndarray,
    batch: int,
    pos: jnp.ndarray,
    scc_words: jnp.ndarray,
    frozen_words: jnp.ndarray,
    hi_words: Optional[jnp.ndarray] = None,
    ba_d: Optional[BitsetArrays] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Bitset twin of :func:`sweep_step`: one contiguous candidate block,
    identical hit definition (Q ≠ ∅ ∧ fixpoint(scc ∖ Q) ≠ ∅).

    Candidates decode through the SAME ``pos`` table as the dense path and
    pack on device; the complement is one AND-NOT (``scc & ~q``), and the
    wide-sweep ``hi_words`` row ORs in like the dense ``maximum`` — the
    bitset engine serves wide and SCC-restricted sweeps alike."""
    bd = ba if ba_d is None else ba_d
    avail = pack_bits(decode_masks(start, batch, pos, jnp.uint32), ba.words)
    if hi_words is not None:
        avail = avail | hi_words
    q = bitset_fixpoint(ba, avail)
    q_size = bitset_count(q)
    complement = scc_words & ~q
    d = bitset_fixpoint(bd, complement, frozen_words)
    hit = jnp.logical_and(q_size > 0, bitset_count(d) > 0)
    return hit, q_size


def bitset_sweep_program_factory(
    circuit: Circuit,
    bit_nodes: np.ndarray,
    scc_mask: np.ndarray,
    frozen: Optional[np.ndarray],
    batch: int,
    circuit_d: Optional[Circuit] = None,
) -> Callable[[int], Callable[[int], jnp.ndarray]]:
    """Drop-in replacement for :func:`sweep_program_factory` on the bitset
    encoding — same contract (``factory(steps_per_call)`` →
    ``make_aot_dispatch`` program: min hit index or INT32_MAX, async
    scalar, ``.precompile`` / ``.xla_compile_seconds`` hooks), so the sweep
    driver's ramp/pipeline/checkpoint machinery composes unchanged."""
    from quorum_intersection_tpu.encode.circuit import bitset_encode, pack_mask_words

    ba = BitsetArrays(bitset_encode(circuit))
    ba_d = None if circuit_d is None else BitsetArrays(bitset_encode(circuit_d))
    pos_j = jnp.asarray(bit_positions(bit_nodes, circuit.n))
    scc_words_j = jnp.asarray(pack_mask_words(np.asarray(scc_mask), ba.words))
    frozen_words_j = (
        jnp.zeros((ba.words,), dtype=jnp.uint32)
        if frozen is None
        else jnp.asarray(pack_mask_words(np.asarray(frozen), ba.words))
    )
    # The hi row crosses the dispatch boundary DENSE — (n,) 0/1, the same
    # row the dense engine takes — and packs inside the program, so the
    # driver's hi_row cache needs no bitset awareness.
    zeros_hi = jnp.zeros((circuit.n,), dtype=jnp.uint32)

    def block_min_hit(start, hi_words):
        hit, _ = bitset_sweep_step(
            ba, start, batch, pos_j, scc_words_j, frozen_words_j, hi_words,
            ba_d=ba_d,
        )
        idx = start + jnp.arange(batch, dtype=jnp.int32)
        return jnp.where(hit, idx, jnp.int32(INT32_MAX)).min()

    def factory(steps_per_call: int) -> Callable[..., jnp.ndarray]:
        @jax.jit
        def step(start0, hi_mask):
            hi_words = pack_bits(hi_mask, ba.words)
            if steps_per_call == 1:
                return block_min_hit(start0, hi_words)

            def body(i, best):
                return jnp.minimum(
                    best, block_min_hit(start0 + i * batch, hi_words)
                )

            return lax.fori_loop(0, steps_per_call, body, jnp.int32(INT32_MAX))

        return make_aot_dispatch(
            step, zeros_hi, lambda x: jnp.asarray(x).astype(jnp.uint32)
        )

    return factory


def bitset_guard_program_factory(
    circuit: Circuit, batch: int
) -> Callable[[np.ndarray], np.ndarray]:
    """Bitset twin of :func:`guard_program_factory` (block-guard pruning):
    (B, n) 0/1 maximal-candidate rows in, (B,) int32 survivor counts out.
    The guard's pruning claim is encoding-independent — a zero count proves
    the block's maximal candidate holds no quorum whichever representation
    evaluated the fixpoint, so guard certs stay checker-valid unchanged."""
    from quorum_intersection_tpu.encode.circuit import bitset_encode, pack_mask_words

    ba = BitsetArrays(bitset_encode(circuit))
    batch = max(int(batch), 1)

    @jax.jit
    def step(mask_words: jnp.ndarray) -> jnp.ndarray:
        return bitset_count(bitset_fixpoint(ba, mask_words))

    def run(masks: np.ndarray) -> np.ndarray:
        rows = masks.shape[0]
        packed = pack_mask_words(np.asarray(masks), ba.words)
        out = np.empty((rows,), dtype=np.int32)
        for lo in range(0, rows, batch):
            chunk = packed[lo : lo + batch]
            if chunk.shape[0] < batch:
                pad = np.zeros((batch, ba.words), dtype=np.uint32)
                pad[: chunk.shape[0]] = chunk
                chunk = pad
            out[lo : lo + batch] = np.asarray(step(jnp.asarray(chunk)))[: rows - lo]
        return out

    return run


def make_aot_dispatch(step, zeros_hi: jnp.ndarray, cast) -> Callable:
    """Wrap a jitted ``step(start, hi_mask)`` into a dispatch function that
    AOT-compiles once and calls the Compiled object.

    The ``.precompile`` attribute is the sweep driver's ramp-jump hook: the
    big shape compiles in a BACKGROUND thread while small programs keep the
    device busy (sweep.py), so the compile never idles the chip.  A lock
    makes a concurrent precompile + first dispatch compile exactly once.
    Shared by the single-device and mesh-sharded program factories.

    ``.xla_compile_seconds()`` reports the wall time of the ``.compile()``
    call alone — the bucket the persistent compilation cache elides (trace +
    lowering always run; sweep.py sums it into the warm-start stat the
    cache-hit acceptance test pins)."""
    state: dict = {}
    lock = threading.Lock()

    def precompile():
        with lock:
            if "compiled" not in state:
                lowered = step.lower(
                    jax.ShapeDtypeStruct((), jnp.int32),
                    jax.ShapeDtypeStruct(zeros_hi.shape, zeros_hi.dtype),
                )
                tc = time.monotonic()
                state["compiled"] = lowered.compile()
                state["xla_seconds"] = time.monotonic() - tc
        return state["compiled"]

    def dispatch(start: int, hi_mask=None):
        hi = zeros_hi if hi_mask is None else cast(hi_mask)
        return precompile()(jnp.int32(start), hi)

    dispatch.precompile = precompile
    dispatch.xla_compile_seconds = lambda: state.get("xla_seconds", 0.0)
    return dispatch

