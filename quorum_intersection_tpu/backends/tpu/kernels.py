"""Batched quorum kernels on the threshold circuit — the TPU compute core.

The reference's hot leaves are ``containsQuorumSlice`` / ``containsQuorum``
(`/root/reference/quorum_intersection.cpp:90-177`) — per-node recursion with
early exits, evaluated one candidate set at a time.  The TPU-native
re-design evaluates **thousands of candidate sets at once** as dense linear
algebra over the flattened threshold circuit (``encode/circuit.py``):

- slice satisfaction for a whole batch is ``avail @ membersᵀ`` (one MXU
  matmul) plus, for nested quorum sets, ``depth+1`` sweeps of
  ``sat @ childᵀ`` (more matmuls) against the threshold vector;
- the greatest-fixpoint quorum (cpp:147's ``f(X) = {x ∈ X : slice(x) ⊆ X}``)
  is a ``lax.while_loop`` that runs until **every row** of the batch is
  stable — converged rows are idempotent under the update, so batch-wide
  convergence needs no per-row masking and terminates in ≤ n+1 sweeps;
- a ``frozen`` availability mask supports the reference's whole-graph
  availability semantics (Q6, cpp:354): frozen nodes satisfy slices but are
  never filtered by the fixpoint — exactly how ``containsQuorum`` never
  removes nodes outside its candidate list.

Everything is float32 0/1 arithmetic: counts stay far below 2^24 so float32
matmuls are exact, and float matmuls are the MXU fast path (int8 quantization
would save bandwidth but caps vote counts; revisit if profiles demand it).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from quorum_intersection_tpu.backends.base import INT32_MAX
from quorum_intersection_tpu.encode.circuit import Circuit


class CircuitArrays:
    """Device-resident circuit constants, shared by all kernels."""

    def __init__(self, circuit: Circuit):
        self.n = circuit.n
        self.n_units = circuit.n_units
        self.depth = circuit.depth
        self.members_t = jnp.asarray(circuit.members.T, dtype=jnp.float32)  # (n, U)
        self.thresholds = jnp.asarray(circuit.thresholds, dtype=jnp.float32)  # (U,)
        self.has_inner = circuit.n_units > circuit.n
        if self.has_inner:
            self.child_t = jnp.asarray(circuit.child.T, dtype=jnp.float32)  # (U, U)
        else:
            self.child_t = None


def node_sat(arrays: CircuitArrays, avail: jnp.ndarray) -> jnp.ndarray:
    """Which nodes have a satisfied slice under ``avail``?

    ``avail``: (B, n) float32 0/1.  Returns (B, n) float32 0/1.
    Self-availability (Q4) is the trailing elementwise product.
    """
    base = avail @ arrays.members_t  # (B, U) vote counts from direct validators
    # First sweep: sat starts all-zero, so the child contribution is zero —
    # evaluate leaves directly instead of multiplying a zero matrix.  The
    # remaining `depth` sweeps propagate inner-set satisfaction up the DAG.
    sat = (base >= arrays.thresholds).astype(jnp.float32)
    if arrays.has_inner:
        for _ in range(arrays.depth):
            sat = ((base + sat @ arrays.child_t) >= arrays.thresholds).astype(jnp.float32)
    return sat[..., : arrays.n] * avail


def fixpoint(
    arrays: CircuitArrays,
    avail: jnp.ndarray,
    frozen: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Greatest-fixpoint quorum per batch row (cpp:140-177 batched).

    ``avail``: (B, n) float32 0/1 candidate sets.  ``frozen``: optional (n,)
    float32 0/1 mask of nodes that remain available for slice satisfaction but
    are never filtered (Q6 whole-graph availability; ``None`` ⇒ scoped).
    Returns (B, n) float32 0/1 — the surviving quorum of each row (all-zero ⇒
    no quorum inside that candidate set).
    """
    if frozen is None:
        frozen_row = jnp.zeros((arrays.n,), dtype=jnp.float32)
    else:
        frozen_row = frozen.astype(jnp.float32)

    def body(carry):
        a, _ = carry
        total = jnp.maximum(a, frozen_row)  # frozen helpers always available
        nxt = node_sat(arrays, total) * a  # only candidates can survive
        changed = jnp.any(nxt != a)
        return nxt, changed

    def cond(carry):
        return carry[1]

    a0 = avail.astype(jnp.float32)
    # Derive the initial "changed" flag from the data (it is trivially True)
    # so the carry inherits the input's manual-axis varyingness under
    # shard_map — a literal jnp.bool_(True) would be replicated and trip the
    # while_loop carry-type check on sharded meshes.
    changed0 = jnp.any(a0 >= 0.0)
    out, _ = lax.while_loop(cond, body, (a0, changed0))
    return out


def make_batch_fixpoint(
    circuit: Circuit,
) -> Callable[[np.ndarray, Optional[np.ndarray]], np.ndarray]:
    """Host-callable jitted batch fixpoint: (B, n) bool → (B, n) bool."""
    arrays = CircuitArrays(circuit)

    @jax.jit
    def run_jit(avail, frozen):
        return fixpoint(arrays, avail, frozen)

    def run(avail: np.ndarray, frozen: Optional[np.ndarray] = None) -> np.ndarray:
        a = jnp.asarray(avail, dtype=jnp.float32)
        f = (
            jnp.zeros((arrays.n,), dtype=jnp.float32)
            if frozen is None
            else jnp.asarray(frozen, dtype=jnp.float32)
        )
        return np.asarray(run_jit(a, f)) > 0.5

    return run


def subset_masks(start: jnp.ndarray, batch: int, bit_nodes: jnp.ndarray, n: int) -> jnp.ndarray:
    """Decode candidate indices ``start + [0, batch)`` into (batch, n) 0/1
    availability rows: bit *j* of the index toggles node ``bit_nodes[j]``.

    ``bit_nodes``: (s,) int32 vertex ids — the enumeration axis.  Indices must
    stay below 2^31 (callers cap the enumeration width; SURVEY.md §7.3's
    uint32-lane note — JAX has no x64 by default).
    """
    s = bit_nodes.shape[0]
    idx = start + jnp.arange(batch, dtype=jnp.int32)  # (B,)
    bits = ((idx[:, None] >> jnp.arange(s, dtype=jnp.int32)) & 1).astype(jnp.float32)
    rows = jnp.zeros((batch, n), dtype=jnp.float32)
    return rows.at[:, bit_nodes].set(bits)


def sweep_step(
    arrays: CircuitArrays,
    start: jnp.ndarray,
    batch: int,
    bit_nodes: jnp.ndarray,
    scc_mask: jnp.ndarray,
    frozen: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Evaluate one contiguous block of candidate subsets.

    For each candidate S (a subset of the enumeration nodes):
      Q = fixpoint(S); hit ⇔ Q ≠ ∅ ∧ fixpoint(scc ∖ Q) ≠ ∅
    — i.e. S exposes a disjoint quorum pair (see sweep.py for the
    verdict-equivalence argument).

    Returns ``(hit, q_size)``: (B,) bool hit flags and (B,) int32 quorum sizes
    (diagnostics).  Witness reconstruction happens on the host from the first
    hit index.
    """
    avail = subset_masks(start, batch, bit_nodes, arrays.n)
    q = fixpoint(arrays, avail)
    q_nonempty = q.sum(axis=-1) > 0
    complement = jnp.clip(scc_mask - q, 0.0, 1.0)
    d = fixpoint(arrays, complement, frozen)
    hit = jnp.logical_and(q_nonempty, d.sum(axis=-1) > 0)
    return hit, q.sum(axis=-1).astype(jnp.int32)


def make_sweep_first_hit(
    circuit: Circuit,
    bit_nodes: np.ndarray,
    scc_mask: np.ndarray,
    frozen: Optional[np.ndarray],
    batch: int,
) -> Callable[[int], jnp.ndarray]:
    """Compile a sweep step reduced to one device scalar: the smallest hit
    candidate index in the block, or INT32_MAX for a clean miss.

    Returning a scalar (instead of the (B,) hit vector) keeps the host↔device
    transfer per step at 4 bytes and — because the call is *asynchronous* —
    lets the sweep driver pipeline several blocks in flight, hiding dispatch
    latency (the measured bottleneck on a tunneled single chip).
    """
    arrays = CircuitArrays(circuit)
    bit_nodes_j = jnp.asarray(bit_nodes, dtype=jnp.int32)
    scc_mask_j = jnp.asarray(scc_mask, dtype=jnp.float32)
    frozen_j = (
        jnp.zeros((circuit.n,), dtype=jnp.float32)
        if frozen is None
        else jnp.asarray(frozen, dtype=jnp.float32)
    )

    @jax.jit
    def step(start):
        hit, _ = sweep_step(arrays, start, batch, bit_nodes_j, scc_mask_j, frozen_j)
        idx = start + jnp.arange(batch, dtype=jnp.int32)
        return jnp.where(hit, idx, jnp.int32(INT32_MAX)).min()

    return lambda start: step(jnp.int32(start))
