"""Automatic backend selection — latency-aware (VERDICT r2 §next-3).

Strategy, optimizing **time-to-verdict** (the BASELINE.json north-star
metric), not TPU-nativeness for its own sake:

- **small SCC** (≤ ``sweep_limit`` nodes — the static per-platform default,
  raised on accelerators by a MEASURED sweep-vs-native win window when a
  ``benchmarks/results/sweep_vs_native*_r*.txt`` artifact records the
  exhaustive sweep beating COMPLETED native runs, same extrapolation
  discipline as the frontier region: +4 headroom, device-kind match,
  capped at any measured loss — ``calibration.sweep_win_max_scc``):
  **RACE** the pruned host oracle against the sweep's spin-up.  The oracle
  runs on this thread with a B&B **call budget** equal to the estimated
  cost of the exhaustive sweep, while a background worker concurrently
  resolves the platform limit, AOT-compiles the sweep program
  (``kernels.make_aot_dispatch(...).precompile``) and starts dispatching
  windows.  First engine to a verdict wins; the loser is cancelled through
  a cooperative ``base.CancelToken`` threaded into the oracle's
  call-budget check and the sweep driver's window loop.  On real
  topologies the pruned search finishes in microseconds-to-milliseconds
  (the bundled snapshots need ~10 calls, SURVEY.md §6) and the sweep
  worker is cancelled before it dispatches anything; on pathological
  searches the sweep verdict lands at ~the direct-sweep cost instead of
  the sequential budget-burn-then-spin-up sum (measured 3.4× at scc 36,
  ``sweep_vs_native_tpu_r5.txt`` — VERDICT r5 weak-1).  Worst case ≈
  max(oracle budget, sweep) instead of their sum; typical case ≈ free.
  ``race=False`` (CLI ``--no-race``) restores the sequential chain:
  oracle first, sweep only after ``OracleBudgetExceeded``.
- **large SCC** (> ``sweep_limit``): the pruned search — native C++
  oracle, falling back to pure Python — unless a MEASURED on-chip win
  region says otherwise: when the newest ``crossover_tpu_r*.txt`` artifact
  records the device-resident frontier beating the native oracle from
  some |scc| upward (verdict + minimal-quorum-count parity on every
  qualifying row, config recorded), accelerator platforms route those
  SCCs to the frontier under the exact measured config
  (``calibration.frontier_win_min_scc``).  No artifact ⇒ host oracle
  everywhere — routing claims about the chip stay tied to recorded
  measurements.  (The round-trip hybrid engine was retired in r5 after
  losing 100-1000× at every measured size on chip and CPU alike,
  crossover artifacts r3-r5; the frontier carries its checkpoint and
  mesh capabilities.)

Every selection is logged; failures to import/compile an accelerator backend
degrade gracefully to the next option so the CLI always yields a verdict.
Since ISSUE 4 the degradation is an explicit :class:`DegradationLadder`
(tpu-sweep → tpu-frontier → native → python-oracle): bounded retries with
deterministic backoff for transient device errors, a watchdog + quarantine
for the in-process native call (``QI_NATIVE_WATCHDOG_S``), and a ``degrade``
telemetry event on every transition — exercised deterministically by
``utils/faults.py`` injection and the chaos soak (docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple, TypeVar

_T = TypeVar("_T")

from quorum_intersection_tpu.backends.base import (
    CancelToken,
    OracleBudgetExceeded,
    SccCheckResult,
    SearchBackend,
    SearchCancelled,
)
from quorum_intersection_tpu.encode.circuit import Circuit
from quorum_intersection_tpu.fbas.graph import TrustGraph
from quorum_intersection_tpu.utils.env import qi_env_float, qi_env_int
from quorum_intersection_tpu.utils.faults import TransientDeviceFault
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import (
    Span,
    dump_flight_recorder,
    get_run_record,
)

log = get_logger("backends.auto")

# Exhaustive-sweep cutoffs by platform: the sweep is exact and fastest while
# 2^(|scc|-1) stays cheap.  Measured:
# - v5e chip (r3, benchmarks/results/bench_full_r3_onchip.json): 626M
#   cand/s END-TO-END on the 2^33 wide sweep (steady 1.2-2.1G on device) —
#   2^34 ≈ 27 s at that measured rate, ~60 s under the variance-halved
#   SWEEP_RATE below: either way an acceptable exact fallback when the
#   oracle has already burned a comparably-sized budget ⇒ limit 35;
# - CPU emulation: ~0.45M cand/s (bench.py throughput phase) while the
#   native oracle runs ~0.7 µs/B&B-call (benchmarks/hybrid_crossover.py:
#   majority-18 = 185k calls = 0.13 s) — the oracle beats an exhaustive
#   2^(n-1) sweep at every measured size, so on CPU the sweep is only kept
#   where its worst case is sub-second: 2^17/0.45M ≈ 0.3 s ⇒ limit 18.
# The TPU value is the calibration module's SWEEP_WINDOW_FLOOR (single
# source: the measured sweep window exempts losses at or below the static
# limit, so the two constants must not drift) — imported below with
# CALIBRATION to keep the module's lazy-import discipline in one place.
from quorum_intersection_tpu.backends.calibration import (  # noqa: E402
    CALIBRATION,
    SWEEP_WINDOW_FLOOR,
)

SWEEP_LIMIT_TPU = SWEEP_WINDOW_FLOOR
SWEEP_LIMIT_CPU = 18
DEFAULT_SWEEP_LIMIT = None  # resolve by platform at check time
# The two-level decode's hard width: bits = |scc|-1 <= DEFAULT_MAX_BITS
# (sweep.py), i.e. |scc| <= DEFAULT_MAX_BITS + 1 — no measured window may
# raise the routing limit past it.  Derived from the sweep module itself
# (ADVICE r5 #3: the hand-duplicated literal 45 would silently rot if the
# decode ever widened); sweep.py is jax-free at import, so this stays
# within the module's lazy-device-import discipline.
from quorum_intersection_tpu.backends.tpu.sweep import (  # noqa: E402
    DEFAULT_MAX_BITS as _SWEEP_MAX_BITS,
    LO_BITS as _SWEEP_LO_BITS,
)

SWEEP_DECODE_CEILING = _SWEEP_MAX_BITS + 1
# How far past the largest MEASURED winning |scc| the sweep window
# extends: one sweep_vs_native grid step, the same extrapolation
# discipline as the frontier region below (and additionally capped at
# any measured LOSS above the window, calibration.sweep_win_cap_scc).
SWEEP_WIN_SCC_HEADROOM = 4

# Cost model for the oracle-first budget: DERIVED at import from the bench
# artifacts committed in this repo (backends/calibration.py — VERDICT r3
# §weak-3/§next-8: constants must track the hardware the suite last
# measured).  Each value's source file is in CALIBRATION.provenance; the
# r3 hand-measured constants remain the fallback when no artifact applies.
# The safety factors (accel halved for tunnel variance, CPU steady rate
# quartered for compile cost) live in the calibration module so the budget
# still errs toward giving the oracle MORE room, never less than
# MIN_ORACLE_BUDGET.  (CALIBRATION itself is imported above with the
# sweep-window floor.)
ORACLE_SECONDS_PER_CALL = CALIBRATION.oracle_seconds_per_call
SWEEP_RATE = CALIBRATION.sweep_rate
SWEEP_OVERHEAD_S = {"cpu": 1.0, "accel": 5.0}
MIN_ORACLE_BUDGET = 50_000

# How far past the largest MEASURED winning |scc| the frontier win region
# extends (see the routing comment in check_scc): one crossover-grid step.
FRONTIER_WIN_SCC_HEADROOM = 4

# Ceiling on how long the race driver waits for a CANCELLED losing engine
# to unwind before returning the winner's verdict.  Cancellation is
# cooperative: the sweep polls its token once per program (bounded by ~1 s
# of device work at full ramp) but cannot interrupt a jax import / platform
# probe / XLA compile already in flight, so the join is ADAPTIVE — about
# twice the winning oracle's runtime, capped here — keeping the cleanup
# wait proportional to the verdict it follows (a 5 ms verdict must not
# stall 5 s on a worker mid-import).  A still-unwinding loser finishes in
# the background (reported as `loser_joined: false` in the race stats) and
# interpreter exit waits for it — the thread is deliberately NON-daemon,
# the same choice sweep.py made for its compile threads after a daemon
# thread hard-killed inside native XLA compile aborted the process.
RACE_LOSER_JOIN_S = 5.0
RACE_LOSER_JOIN_MIN_S = 0.2


def _race_sync(point: str) -> None:
    """Deterministic-schedule hook (ISSUE 3): a no-op in production, replaced
    by ``tools/analyze/schedules.py`` to FORCE the race's nasty interleavings
    — sweep-wins-then-oracle-finishes, cancel-during-compile, both-finish-
    simultaneously — instead of hoping the wall clock finds them.  Points:

    - ``sweep.started``     — worker thread entered, before any device work
    - ``sweep.verdict``     — sweep result recorded, before cancelling the
      oracle
    - ``sweep.unwound``     — worker observed its cancel and is exiting
    - ``oracle.returned``   — main thread's oracle call completed (verdict,
      budget burn, or cancel), before the winner is decided

    The hook runs on the thread that reaches the point (monkeypatch the
    module attribute, as the harness and tests/test_race_schedules.py do); a
    replacement may block to serialize threads but MUST eventually return
    (the harness bounds every wait).  Keep call sites outside any lock.
    """


# ---------------------------------------------------------------------------
# Degradation ladder (ISSUE 4 tentpole): the explicit object behind every
# "engine unavailable; falling back" decision this router makes.  Before it,
# a dozen scattered `except Exception` sites each invented their own policy
# (no retries, no record of WHY a rung was skipped); now exactly one broad
# catch exists — inside DegradationLadder.attempt — and the qi-lint rule
# `degrade-via-ladder` keeps it that way.  Rung order (fastest exact engine
# first, always-available last):
#
#     tpu-sweep  →  tpu-frontier  →  native (C++)  →  python-oracle
#
# Transitions are never silent: each emits a `degrade` run-record event
# naming the failed rung, where control went, the cause, and the attempt
# count.  TRANSIENT device errors (RESOURCE_EXHAUSTED / OOM class — the
# errors a busy chip throws and then stops throwing) get a bounded retry
# budget (QI_RETRY_MAX) with exponential backoff and DETERMINISTIC jitter
# (hash-derived, not random.random — two runs of the same schedule back off
# identically, the same reproducibility discipline as tools/analyze's race
# schedules); everything else degrades on the first failure, exactly like
# the old ad-hoc sites.

RUNGS = ("tpu-sweep", "tpu-frontier", "native", "python-oracle")
# Base backoff for transient-device retries; attempt n sleeps
# RETRY_BACKOFF_S * 2^n * (1 + jitter), jitter ∈ [0, 0.25) derived from a
# hash of (rung, attempt) so it is deterministic per site yet decorrelated
# across rungs (thundering-herd protection that still replays exactly).
RETRY_BACKOFF_S = 0.05
# Watchdog poll granularity: how often the supervising thread re-checks the
# native call's liveness and forwards an outer (race) cancel inward.
WATCHDOG_POLL_S = 0.05

# Seam for tests: the ladder's backoff sleeps route through this module
# attribute so retry tests run in milliseconds (the analyze suite's "no
# sleeps in tests" discipline — patch the seam, don't wait the wall clock).
_retry_sleep: Callable[[float], None] = time.sleep

_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "OUT_OF_MEMORY",
    "out of memory",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
)


class RungFailed(RuntimeError):
    """One ladder rung failed (retry budget included); control falls to the
    next rung.  Typed — the router catches exactly this, never a bare
    ``Exception`` — and carries the rung, the root cause, and how many
    attempts were burned, so the fall-through sites stay diagnosable."""

    def __init__(self, rung: str, cause: BaseException, attempts: int) -> None:
        self.rung = rung
        self.cause = cause
        self.attempts = attempts
        super().__init__(
            f"ladder rung {rung!r} failed after {attempts} attempt(s): "
            f"{type(cause).__name__}: {cause}"
        )


class NativeWatchdogTimeout(RuntimeError):
    """The in-process native call blew its QI_NATIVE_WATCHDOG_S deadline.

    ``quarantine`` distinguishes the two severities: False — the call DID
    unwind once the watchdog tripped its CancelToken (slow, but the cancel
    path works; the rung stays available); True — the call ignored the
    cancel past the grace window (wedged inside native code; the rung is
    quarantined for the rest of the run so no later SCC can wedge on it).
    """

    def __init__(self, message: str, quarantine: bool) -> None:
        self.quarantine = quarantine
        super().__init__(message)


def _is_transient_device_error(exc: BaseException) -> bool:
    """Transient-device classification: the injected OOM class plus the
    real XLA/runtime markers (string match — jaxlib's XlaRuntimeError type
    is not importable on jax-free paths, and the markers are the stable
    part of those errors across versions)."""
    if isinstance(exc, TransientDeviceFault):
        return True
    text = str(exc)
    return any(marker in text for marker in _TRANSIENT_MARKERS)


def _backoff_delay(rung: str, attempt: int) -> float:
    """Exponential backoff with deterministic jitter for retry ``attempt``
    (0-based) at ``rung``.  sha256, not ``hash()``: Python randomizes the
    latter per process, and the whole point is that two runs of the same
    fault schedule sleep identically."""
    base = RETRY_BACKOFF_S * (2 ** attempt)
    digest = hashlib.sha256(f"{rung}:{attempt}".encode()).digest()
    return base * (1.0 + digest[0] / 1024.0)


class DegradationLadder:
    """Retry/degrade/quarantine policy for one run of the auto router.

    One instance per :class:`AutoBackend` — quarantine is scoped to the
    run, so a wedged native library poisons nothing beyond the process
    that observed it.  All fallthrough flows through :meth:`attempt`; the
    ``except Exception`` inside it is the only broad catch the
    ``degrade-via-ladder`` lint rule permits in ``backends/``.
    """

    def __init__(self, retry_max: Optional[int] = None) -> None:
        self.retry_max = (
            qi_env_int("QI_RETRY_MAX") if retry_max is None else retry_max
        )
        self._quarantined: Set[str] = set()

    def quarantined(self, rung: str) -> bool:
        return rung in self._quarantined

    def quarantine(self, rung: str, cause: object) -> None:
        """Mark ``rung`` unusable for the rest of this run (idempotent)."""
        if rung in self._quarantined:
            return
        self._quarantined.add(rung)
        rec = get_run_record()
        rec.add("ladder.quarantines")
        rec.event("ladder.quarantined", rung=rung, cause=str(cause))
        # Live health (/healthz) reads the quarantine picture from this
        # gauge; the flight recorder preserves the last-N context that led
        # to taking a rung out for the run.
        rec.gauge("ladder.quarantined_rungs", sorted(self._quarantined))
        dump_flight_recorder(f"quarantine:{rung}")
        log.warning(
            "ladder: rung %r quarantined for this run (%s)", rung, cause
        )

    def record_degrade(
        self,
        rung: str,
        to: str,
        cause: object,
        attempts: int = 1,
        transient: bool = False,
    ) -> None:
        """Emit the one-transition record every degradation must leave.

        ``rung``/``to`` must come from the canonical RUNGS vocabulary —
        consumers aggregate the degrade stream by these names, and three
        spellings of one destination would make the ladder picture
        unreadable.  Soft enforcement (warn, still emit): a junk name in
        telemetry must never cost the verdict it describes.
        """
        for field in (rung, to):
            if field not in RUNGS:
                log.warning(
                    "degrade event with non-canonical rung name %r "
                    "(expected one of %s)", field, RUNGS,
                )
        rec = get_run_record()
        rec.add("ladder.degrades")
        rec.event(
            "degrade", rung=rung, to=to, cause=str(cause),
            attempts=attempts, transient=transient,
        )
        # Every degrade event carries its last-N context out to disk (no-op
        # unless QI_FLIGHT_RECORDER is set — docs/OBSERVABILITY.md).
        dump_flight_recorder(f"degrade:{rung}->{to}")
        log.info("ladder: %s -> %s after %d attempt(s) (%s)",
                 rung, to, attempts, cause)

    def attempt(
        self,
        rung: str,
        fn: Callable[[], _T],
        fall_to: str,
    ) -> _T:
        """Run one rung with its retry budget.

        Verdict-path flow control (``OracleBudgetExceeded``,
        ``SearchCancelled``) passes straight through — those are scheduling
        signals, not failures.  Transient device errors retry up to
        ``retry_max`` times with deterministic backoff; anything else (or
        an exhausted budget) emits the ``degrade`` event and raises
        :class:`RungFailed` toward the caller's next rung.
        """
        if rung in self._quarantined:
            raise RungFailed(
                rung,
                RuntimeError("rung quarantined earlier in this run"),
                0,
            )
        attempts = 0
        while True:
            attempts += 1
            try:
                # One span per rung attempt (qi-trace): every rung and every
                # retry of one run appears in the timeline under the same
                # trace_id, so a degrade cascade reads as a cascade.
                with get_run_record().span(
                    "ladder.rung", rung=rung, attempt=attempts
                ):
                    return fn()
            except (OracleBudgetExceeded, SearchCancelled, RungFailed):
                raise
            except Exception as exc:  # noqa: BLE001 — the ladder's one broad catch
                transient = _is_transient_device_error(exc)
                if transient and attempts <= self.retry_max:
                    delay = _backoff_delay(rung, attempts - 1)
                    rec = get_run_record()
                    rec.add("ladder.retries")
                    rec.event(
                        "degrade.retry", rung=rung, attempt=attempts,
                        delay_s=round(delay, 4), cause=str(exc),
                    )
                    log.info(
                        "ladder: transient failure at %s (attempt %d/%d), "
                        "retrying in %.3fs: %s",
                        rung, attempts, self.retry_max + 1, delay, exc,
                    )
                    _retry_sleep(delay)
                    continue
                self.record_degrade(
                    rung, fall_to, exc, attempts=attempts, transient=transient
                )
                raise RungFailed(rung, exc, attempts) from exc


class _WatchedNativeOracle:
    """The ladder's native rung: the C++ oracle supervised by a watchdog,
    degrading to the Python oracle on ANY non-verdict failure.

    With ``QI_NATIVE_WATCHDOG_S`` unset (0, the default) the native call
    runs on the caller's thread exactly as before — zero new moving parts
    on the production path.  With a deadline set, the call runs on a
    supervised worker thread: past the deadline the watchdog trips the
    native CancelToken (rung stays available if the call unwinds — the
    cancel path works, it was just slow), and a call that ignores the
    cancel past the grace window quarantines the native rung for the run
    and falls to Python instead of wedging the router forever.  The worker
    is deliberately NON-daemon (the same choice the sweep's compile
    threads made): interpreter exit waits for a genuinely wedged call, but
    the router's verdict does not.
    """

    def __init__(
        self,
        ladder: DegradationLadder,
        native: SearchBackend,
        python_factory: Callable[[], SearchBackend],
        outer_cancel: Optional[CancelToken],
        native_cancel: Optional[CancelToken],
        watchdog_s: float,
    ) -> None:
        self._ladder = ladder
        self._native = native
        self._python_factory = python_factory
        # outer_cancel: the race driver's token (propagated verbatim when
        # it fires); native_cancel: the token the native search actually
        # polls — the watchdog's trip wire, distinct from the race's so a
        # deadline trip degrades to Python instead of masquerading as a
        # race cancellation.
        self._outer_cancel = outer_cancel
        self._native_cancel = native_cancel
        self._watchdog_s = watchdog_s
        self.name = "cpp"

    def check_scc(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
    ) -> SccCheckResult:
        try:
            return self._supervised(graph, circuit, scc, scope_to_scc)
        except (OracleBudgetExceeded, SearchCancelled):
            raise  # verdict-path flow control — the router handles these
        except NativeWatchdogTimeout as exc:
            if exc.quarantine:
                self._ladder.quarantine("native", exc)
            self._ladder.record_degrade("native", "python-oracle", exc)
        except Exception as exc:  # noqa: BLE001 — the ladder's native→python transition
            self._ladder.record_degrade("native", "python-oracle", exc)
        self.name = "python"
        return self._python_factory().check_scc(
            graph, circuit, scc, scope_to_scc=scope_to_scc
        )

    def _supervised(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        scope_to_scc: bool,
    ) -> SccCheckResult:
        if self._watchdog_s <= 0:
            return self._native.check_scc(
                graph, circuit, scc, scope_to_scc=scope_to_scc
            )
        holder: Dict[str, object] = {}

        def work() -> None:
            try:
                holder["res"] = self._native.check_scc(
                    graph, circuit, scc, scope_to_scc=scope_to_scc
                )
            # Thread-boundary marshal, re-raised verbatim on the supervisor:
            # qi-lint: allow(degrade-via-ladder) — nothing is swallowed here
            except BaseException as exc:  # noqa: BLE001
                holder["exc"] = exc

        # daemon=False is EXPLICIT, not the default-by-inheritance: the
        # fused serve drain calls this rung from daemon worker threads,
        # and a daemon watchdog hard-killed mid-native-call at interpreter
        # exit aborts the process.
        worker = threading.Thread(
            target=work, name="qi-native-watchdog", daemon=False
        )
        worker.start()
        deadline = time.monotonic() + self._watchdog_s
        grace_deadline: Optional[float] = None
        while worker.is_alive():
            if (
                self._outer_cancel is not None
                and self._outer_cancel.cancelled
                and self._native_cancel is not None
            ):
                # The race decided elsewhere: forward the cancel inward.
                self._native_cancel.cancel()
            now = time.monotonic()
            if grace_deadline is None and now >= deadline:
                get_run_record().event(
                    "native.watchdog_cancel",
                    deadline_s=self._watchdog_s, scc=len(scc),
                )
                dump_flight_recorder("watchdog:native")
                log.warning(
                    "native call exceeded %.2fs watchdog deadline; "
                    "tripping its cancel token", self._watchdog_s,
                )
                if self._native_cancel is not None:
                    self._native_cancel.cancel()
                grace_deadline = now + min(1.0, self._watchdog_s)
            elif grace_deadline is not None and now >= grace_deadline:
                # Ignored the cancel: abandon the worker (non-daemon; see
                # class docstring) and quarantine the rung.
                raise NativeWatchdogTimeout(
                    f"native call ignored its cancel for "
                    f"{min(1.0, self._watchdog_s):.2f}s past the "
                    f"{self._watchdog_s:.2f}s deadline (|scc|={len(scc)})",
                    quarantine=True,
                )
            worker.join(WATCHDOG_POLL_S)
        exc = holder.get("exc")
        if exc is not None:
            if (
                isinstance(exc, SearchCancelled)
                and grace_deadline is not None
                and not (
                    self._outer_cancel is not None
                    and self._outer_cancel.cancelled
                )
            ):
                # OUR trip unwound it, not the race's: a slow call, not a
                # cancelled one — degrade to Python, keep the rung usable.
                raise NativeWatchdogTimeout(
                    f"native call hit the {self._watchdog_s:.2f}s watchdog "
                    f"deadline and unwound on cancel (|scc|={len(scc)})",
                    quarantine=False,
                ) from exc
            raise exc  # type: ignore[misc]
        return holder["res"]  # type: ignore[return-value]


def _measured_sweep_raise() -> Optional[int]:
    """The artifact-backed accelerator sweep limit, BEFORE the device-kind
    gate: largest measured winning |scc| + headroom, capped at any
    measured loss above the window and at the decode ceiling.  None when
    no sweep_vs_native artifact recorded a win.  Deliberately touches no
    device — callers that must stay probe-free (the optimistic bound in
    check_scc) use it directly."""
    win = CALIBRATION.sweep_win_max_scc
    if win is None:
        return None
    raised = min(win + SWEEP_WIN_SCC_HEADROOM, SWEEP_DECODE_CEILING)
    if CALIBRATION.sweep_win_cap_scc is not None:
        raised = min(raised, CALIBRATION.sweep_win_cap_scc)
    return raised


# Resolved per-platform sweep limit, cached after the first device probe
# (ADVICE r5 / ISSUE 2 satellite): the optimistic oracle-first bound in
# check_scc is deliberately probe-free, so on its FIRST pass it must use the
# ungated _measured_sweep_raise() — which on a foreign device (GPU box with a
# TPU-measured artifact) over-shoots, burns the oracle budget, and restarts
# the oracle unbudgeted.  Once any budget burn / race worker has paid the
# probe, the true gated limit is cached here and every later solve in the
# process uses it for the optimistic bound too — the pathological path is
# paid at most once per process, not once per resume.
_resolved_platform_limit: Optional[int] = None


def _platform_sweep_limit() -> int:
    global _resolved_platform_limit
    from quorum_intersection_tpu.utils.platform import (
        backend_kind, is_cpu_platform,
    )

    if is_cpu_platform():
        limit = SWEEP_LIMIT_CPU
    else:
        limit = SWEEP_LIMIT_TPU
        raised = _measured_sweep_raise()
        if raised is not None:
            kind = backend_kind()
            if kind == CALIBRATION.sweep_win_device:
                limit = max(limit, raised)
            elif _resolved_platform_limit is None:
                # The artifact was measured on different hardware: ignore it,
                # loudly — routing claims stay tied to the device they were
                # measured on, and the record says so.  First resolution
                # only: this limit is re-resolved once per SCC, and the
                # identical event per SCC would just spam the stream.
                get_run_record().event(
                    "calibration.foreign_artifact_ignored",
                    artifact_device=CALIBRATION.sweep_win_device,
                    live_device=kind,
                    raised_limit=raised,
                )
                log.info(
                    "sweep-window artifact measured on %r ignored on %r",
                    CALIBRATION.sweep_win_device, kind,
                )
    _resolved_platform_limit = limit
    return limit


class AutoBackend:
    name = "auto"
    # qi-fuse: the batch entry accepts per-job cancel tokens and origins —
    # the fused serve drain hands work from different requests to one
    # check_sccs call, each job retiring on its own request's deadline.
    supports_job_cancels = True

    def __init__(
        self,
        prefer_tpu: bool = False,
        sweep_limit: Optional[int] = DEFAULT_SWEEP_LIMIT,
        seed: Optional[int] = None,
        randomized: bool = False,
        checkpoint: Optional[object] = None,
        mesh: Optional[object] = None,
        race: bool = True,
        pack: Optional[bool] = None,
        cancel: Optional[CancelToken] = None,
    ) -> None:
        # prefer_tpu (`--backend tpu`) is routing-neutral since the r3
        # on-chip crossover: large SCCs go to the host oracle everywhere
        # (it only changes a log line); kept for CLI compatibility.
        self.prefer_tpu = prefer_tpu
        self.sweep_limit = sweep_limit
        self.checkpoint = checkpoint  # forwarded to the sweep backend
        self.mesh = mesh  # forwarded to the sweep backend
        # race=False (`--no-race`) restores the sequential oracle-then-sweep
        # chain: the budgeted oracle runs alone and only a budget burn
        # touches the device.  The escape hatch exists for single-core
        # boxes (the racing sweep competes for the oracle's CPU) and for
        # debugging — verdicts are identical either way.
        self.race = race
        # External cooperative cancellation (ISSUE 8, the serving layer's
        # deadline supervisor): a base.CancelToken threaded into every
        # engine this router sequentially runs — the budgeted oracle's
        # call-budget check, the sweep's window loop, the native search's
        # poll.  A deadline-supervised solve runs the SEQUENTIAL chain:
        # the racing orchestrator mints its own per-arm tokens (one-shot,
        # unmergeable with an outer one), so an external token disables
        # the race rather than silently not reaching one arm.  Verdicts
        # are identical either way (--no-race contract).
        self.cancel = cancel
        if cancel is not None and race:
            self.race = False
            log.debug(
                "auto: external cancel token supplied; racing orchestrator "
                "disabled for this router (sequential chain, deadline-"
                "cancellable)"
            )
        # Lane packing for the batch entry (check_sccs): None (default)
        # engages only behind a MEASURED packed-vs-unpacked win on the live
        # device kind (calibration.pack_win_max_scc — the same recorded-
        # measurement discipline as every other routing claim here); True
        # forces packing (tests, benchmarks); False never packs.
        self.pack = pack
        self._oracle_options = {"seed": seed, "randomized": randomized} if (randomized or seed is not None) else {}
        # One ladder per router instance: retry budgets and quarantine are
        # scoped to the run (the CLI builds one AutoBackend per solve).
        self._ladder = DegradationLadder()

    def _sweep(
        self,
        cancel: Optional[CancelToken] = None,
        engine: Optional[str] = None,
    ) -> SearchBackend:
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend

        return TpuSweepBackend(
            checkpoint=self.checkpoint, mesh=self.mesh,
            cancel=cancel if cancel is not None else self.cancel,
            engine=engine,
        )

    def _bitset_hint(self, graph: TrustGraph, scc: List[int]) -> Optional[str]:
        """Density-routed encoding hint for a sweep-bound solve (qi-sparse):
        ``"bitset"`` when the measured win region covers this SCC, else
        None (the sweep backend's own default resolution applies).

        Every clause is the recorded-measurement discipline the other
        routing gates follow: the region comes from committed --bitset
        bench rows (calibration._bitset_win), the live device kind must
        match the kind the win was measured on, |scc| must reach the
        smallest measured winning size (extrapolation goes UP the scc
        axis only — more windows amortize fixed costs further), and the
        SCC's qset density must stay within the densest measured win
        (denser qsets erode exactly the sparsity the encoding streams).
        An explicit ``QI_SWEEP_ENGINE`` always wins: the ctor argument
        this hint feeds would override the env knob inside the backend,
        so a user-pinned engine must short-circuit the hint here."""
        from quorum_intersection_tpu.utils.env import qi_env

        if qi_env("QI_SWEEP_ENGINE").strip():
            return None
        win = CALIBRATION.bitset_win_min_scc
        dmax = CALIBRATION.bitset_win_max_density
        if win is None or dmax is None or len(scc) < win:
            return None
        from quorum_intersection_tpu.utils.platform import backend_kind

        if backend_kind() != CALIBRATION.bitset_win_device:
            return None
        from quorum_intersection_tpu.fbas.synth import scc_qset_density

        density = scc_qset_density(graph, scc)
        if density > dmax:
            return None
        get_run_record().event(
            "route.encoding", engine="bitset", scc=len(scc),
            density=round(density, 4),
            reason=(
                f"measured win region: |scc| >= {win}, "
                f"qset density <= {dmax:.4g} "
                f"on {CALIBRATION.bitset_win_device}"
            ),
        )
        return "bitset"

    def _cpu_oracle(
        self,
        budget_s: Optional[float] = None,
        cancel: Optional[CancelToken] = None,
    ) -> SearchBackend:
        """The host-oracle rungs of the degradation ladder: native C++
        (watchdog-supervised, quarantinable — see _WatchedNativeOracle),
        degrading to pure Python.  With ``budget_s``, the returned backend
        carries a B&B call budget sized per engine speed; with ``cancel``,
        a base.CancelToken the search polls (racing mode)."""

        def python_oracle() -> SearchBackend:
            from quorum_intersection_tpu.backends.python_oracle import (
                PythonOracleBackend,
            )

            options = dict(self._oracle_options)
            if budget_s is not None:
                options["budget_calls"] = max(
                    int(budget_s / ORACLE_SECONDS_PER_CALL["python"]),
                    MIN_ORACLE_BUDGET,
                )
            if cancel is not None:
                options["cancel"] = cancel
            return PythonOracleBackend(**options)

        watchdog_s = qi_env_float("QI_NATIVE_WATCHDOG_S", 0.0)

        def native_oracle() -> SearchBackend:
            from quorum_intersection_tpu.backends.cpp import CppOracleBackend

            options = dict(self._oracle_options)
            if budget_s is not None:
                options["budget_calls"] = max(
                    int(budget_s / ORACLE_SECONDS_PER_CALL["cpp"]),
                    MIN_ORACLE_BUDGET,
                )
            # Under a watchdog the native search polls its OWN token (the
            # watchdog's trip wire); the race's token is forwarded inward
            # by the supervisor.  Without one, the race token goes straight
            # through, exactly as before.
            native_cancel = CancelToken() if watchdog_s > 0 else cancel
            if native_cancel is not None:
                options["cancel"] = native_cancel
            backend = CppOracleBackend(**options)
            backend.ensure_built()
            return _WatchedNativeOracle(
                self._ladder, backend, python_oracle,
                outer_cancel=cancel, native_cancel=native_cancel,
                watchdog_s=watchdog_s,
            )

        try:
            return self._ladder.attempt(
                "native", native_oracle, fall_to="python-oracle"
            )
        except RungFailed as fail:
            log.info(
                "native C++ oracle unavailable (%s); using Python oracle",
                fail.cause,
            )
        return python_oracle()

    def _estimated_sweep_seconds(self, s: int) -> float:
        """Probe-free budget: the MIN of the per-platform sweep estimates.

        Deliberately platform-blind — probing would touch the device backend
        (utils/platform.py: a hung tunnel blocks there), and the happy path
        (oracle finishes under budget) should never contact a device at all.
        min() keeps the budget honest on both platforms: at small |scc| the
        CPU estimate dominates the bound; at large |scc| the accelerator
        estimate stops a pathological oracle within ~the on-chip sweep cost.

        The accelerator overhead term shrinks when an auto_race artifact
        measured a HOT persistent compile cache (calibration.
        sweep_warm_ratio: warm XLA-compile seconds / cold): per-shape
        compile — the dominant fixed cost at snapshot scale — is mostly
        cache hits then, so the budget stops a pathological oracle sooner
        and routing prefers the chip exactly when the chip is cheap.
        Like the accel RATE term above it, the chip-measured ratio applies
        without a device-kind match — this estimate must stay probe-free —
        and the leak onto a CPU-only box is bounded: the overhead floor is
        SWEEP_OVERHEAD_S['cpu'], so the budget under-shoots by at most
        (accel - cpu) overhead seconds, and the sizes where that matters
        (> SWEEP_LIMIT_CPU) fall back to the unbudgeted oracle, never to a
        CPU-emulated sweep.
        """
        space = float(1 << max(s - 1, 0))
        accel_overhead = SWEEP_OVERHEAD_S["accel"]
        warm = CALIBRATION.sweep_warm_ratio
        if warm is not None:
            accel_overhead = max(
                SWEEP_OVERHEAD_S["cpu"], accel_overhead * warm
            )
        return min(
            SWEEP_OVERHEAD_S["cpu"] + space / SWEEP_RATE["cpu"],
            accel_overhead + space / SWEEP_RATE["accel"],
        )

    def _budgeted_oracle(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        scope_to_scc: bool,
        budget_s: float,
    ) -> Optional[SccCheckResult]:
        """Sequential oracle-first attempt (``--no-race``): returns a
        result, or None meaning 'fall back to the sweep' (budget burned)."""
        backend = self._cpu_oracle(budget_s=budget_s, cancel=self.cancel)
        try:
            log.debug(
                "auto: oracle-first (%s) for |scc|=%d, budget ~%.1fs of calls",
                backend.name, len(scc), budget_s,
            )
            return backend.check_scc(graph, circuit, scc, scope_to_scc=scope_to_scc)
        except OracleBudgetExceeded as exc:
            get_run_record().add("oracle.budget_burns")
            log.info("oracle budget burned (%s); switching to the exhaustive sweep", exc)
            return None

    def _race(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        scope_to_scc: bool,
        budget_s: float,
    ) -> Optional[SccCheckResult]:
        """Racing orchestrator: budgeted host oracle vs concurrent sweep
        spin-up; first verdict wins, the loser is cooperatively cancelled.

        The sequential chain measured its worst case at scc 36 as 3.4x the
        direct sweep (benchmarks/results/sweep_vs_native_tpu_r5.txt: 174 s
        of serial budget burn BEFORE the sweep's compile+dispatch even
        started).  Racing overlaps the two: a background worker resolves
        the platform sweep limit (the device probe moves OFF the verdict
        path — a hung tunnel strands only the worker), builds the sweep,
        and starts dispatching windows, while this thread runs the budgeted
        B&B exactly as before.  Whichever engine reaches a verdict first
        cancels the other through a base.CancelToken threaded into the
        oracle's call-budget check and the sweep driver's window loop.

        Verdicts cannot change: both engines implement the same pinned
        spec and a cancelled engine raises (SearchCancelled) instead of
        answering — the race alters scheduling only.  When BOTH finish,
        the oracle's result is preferred, so witness output is identical
        to the sequential path whenever the oracle finishes under budget.

        Returns the winning result, or None when neither engine produced a
        verdict (budget burned AND sweep ineligible/unavailable) — the
        caller then falls through to the same sequential fallbacks as a
        ``--no-race`` budget burn.
        """
        with get_run_record().span("race", scc=len(scc)) as race_span:
            return self._race_inner(
                graph, circuit, scc, scope_to_scc, budget_s, race_span
            )

    def _race_inner(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        scope_to_scc: bool,
        budget_s: float,
        race_span: Span,
    ) -> Optional[SccCheckResult]:
        rec = get_run_record()
        oracle_cancel = CancelToken()
        sweep_cancel = CancelToken()
        outcome: Dict[str, object] = {}
        t0 = time.monotonic()

        def sweep_worker() -> None:
            # The worker's whole arm is one span, explicitly parented under
            # the race span (cross-THREAD trace propagation — a thread's
            # spans are otherwise roots), so the LOSING arm appears in the
            # same timeline as the verdict that beat it.
            with rec.span(
                "race.sweep", parent_id=race_span.span_id, scc=len(scc)
            ) as arm_span:
                self._sweep_arm(
                    arm_span, graph, circuit, scc, scope_to_scc,
                    oracle_cancel, sweep_cancel, outcome, t0,
                )

        # Non-daemon (see RACE_LOSER_JOIN_S): the verdict itself never
        # waits on this thread beyond the adaptive join, but interpreter
        # EXIT does — a daemon thread hard-killed inside native XLA
        # compile/init aborts the process (the failure sweep.py's compile
        # threads hit), which is worse than a bounded exit wait.  On a
        # HUNG tunnel the probe can strand the worker and exit blocks;
        # that environment already hangs the sequential router's post-burn
        # probe on the MAIN thread — `--no-race` (or JAX_PLATFORMS=cpu,
        # utils/platform.py) is the documented way out either way.
        # daemon=False must be EXPLICIT: Thread daemonness is inherited
        # from the spawning thread, and the fused serve drain races from
        # daemon worker threads — an inherited-daemon sweep hard-killed
        # inside XLA at exit is exactly the abort described above.
        worker = threading.Thread(
            target=sweep_worker, name="qi-race-sweep", daemon=False
        )
        worker.start()

        oracle_res = None
        oracle_state = "verdict"
        backend = self._cpu_oracle(budget_s=budget_s, cancel=oracle_cancel)
        log.debug(
            "auto: racing %s (budget ~%.1fs of calls) against sweep "
            "spin-up for |scc|=%d", backend.name, budget_s, len(scc),
        )
        t_oracle = time.monotonic()
        # The oracle arm mirrors the sweep arm's span (same trace, same
        # parent) so the timeline shows BOTH racers side by side.
        with rec.span("race.oracle", budget_s=round(budget_s, 3)) as ora_span:
            try:
                oracle_res = backend.check_scc(
                    graph, circuit, scc, scope_to_scc=scope_to_scc
                )
            except OracleBudgetExceeded as exc:
                oracle_state = "budget_exceeded"
                rec.add("oracle.budget_burns")
                log.info(
                    "race: oracle budget burned (%s); awaiting the sweep", exc
                )
            except SearchCancelled:
                oracle_state = "cancelled"
            ora_span.set(outcome=oracle_state)
        oracle_seconds = time.monotonic() - t_oracle
        _race_sync("oracle.returned")

        def race_stats(winner: str, joined: bool,
                       loser_join_s: Optional[float] = None,
                       winner_wait_s: Optional[float] = None) -> dict:
            rs = {
                "winner": winner,
                "budget_s": round(budget_s, 3),
                "oracle_seconds": round(oracle_seconds, 4),
                "oracle_outcome": oracle_state,
                "loser_joined": joined,
            }
            if loser_join_s is not None:
                rs["loser_join_seconds"] = round(loser_join_s, 4)
            if winner_wait_s is not None:
                # Sweep-wins path: the join waited for the WINNER's verdict,
                # not a loser's unwind (the losing oracle already finished
                # on this thread) — a separate key, so loser_join_seconds
                # stays a pure unwind-latency metric.
                rs["winner_wait_seconds"] = round(winner_wait_s, 4)
            if "sweep_seconds" in outcome:
                rs["sweep_seconds"] = round(outcome["sweep_seconds"], 4)
            for key in ("sweep_ineligible", "sweep_error"):
                if key in outcome:
                    rs[key] = outcome[key]
            # One schema everywhere: the race verdict lands in the span's
            # attributes AND as a standalone event, so both a JSONL stream
            # and the in-memory record answer "who won, how long did the
            # loser take to unwind" without digging into res.stats.
            race_span.set(**rs)
            rec.event("race", **rs)
            if loser_join_s is not None:
                rec.gauge("race.loser_join_seconds", round(loser_join_s, 4))
            return rs

        if oracle_res is not None:
            # Host oracle reached the verdict (the overwhelmingly common
            # path on real topologies): cancel the sweep and give it a
            # bounded window to unwind its in-flight work.
            sweep_cancel.cancel()
            t_join = time.monotonic()
            worker.join(timeout=min(
                RACE_LOSER_JOIN_S,
                max(RACE_LOSER_JOIN_MIN_S, 2.0 * oracle_seconds),
            ))
            loser_join_s = time.monotonic() - t_join
            joined = not worker.is_alive()
            if not joined:
                log.info(
                    "race: cancelled sweep still unwinding (finishes in "
                    "the background; verdict is already final)"
                )
            if self.checkpoint is not None and joined:
                # Discard any progress the LOSING sweep recorded before the
                # cancel landed: the race only runs when the checkpoint held
                # no progress (the resumable gate), so everything in it now
                # is this race's residue — left on disk it would flip that
                # gate and skip the oracle on every later run of the same
                # problem, turning a milliseconds verdict into a full sweep
                # (r1 review finding).  Joined-only: a still-running worker
                # could otherwise re-record after this clear (TOCTOU); the
                # unjoined case is covered by the worker's OWN clear in its
                # SearchCancelled handler, which runs strictly after its
                # engine's last possible record.
                try:
                    self.checkpoint.clear()
                # qi-lint: allow(degrade-via-ladder) — cleanup, not routing
                except Exception:  # noqa: BLE001 — cleanup must not cost the verdict
                    pass
            oracle_res.stats["race"] = race_stats("oracle", joined, loser_join_s)
            return oracle_res

        # Budget burned (or the sweep already won and cancelled us): the
        # sweep IS the verdict path now — wait for it like the sequential
        # fallback would, minus the spin-up time it already overlapped.
        t_join = time.monotonic()
        worker.join()
        winner_wait_s = time.monotonic() - t_join
        res = outcome.get("sweep_result")
        if res is not None:
            res.stats["race"] = race_stats(
                "sweep", True, winner_wait_s=winner_wait_s
            )
            return res
        race_stats("none", True, winner_wait_s=winner_wait_s)
        return None

    def _sweep_arm(
        self,
        arm_span: Span,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        scope_to_scc: bool,
        oracle_cancel: CancelToken,
        sweep_cancel: CancelToken,
        outcome: Dict[str, object],
        t0: float,
    ) -> None:
        """The race's sweep arm (worker-thread body of :meth:`_race_inner`):
        resolve the platform limit, spin up the sweep, record the outcome.
        Runs inside the ``race.sweep`` span the worker opened."""
        try:
            _race_sync("sweep.started")
            if sweep_cancel.cancelled:
                arm_span.set(outcome="cancelled")
                return
            # The race's ONE device contact, off the verdict path.
            limit = (
                self.sweep_limit if self.sweep_limit is not None
                else _platform_sweep_limit()
            )
            if len(scc) > limit:
                outcome["sweep_ineligible"] = (
                    f"|scc|={len(scc)} > platform sweep limit {limit}"
                )
                arm_span.set(outcome="ineligible")
                return
            if sweep_cancel.cancelled:
                arm_span.set(outcome="cancelled")
                return
            res = self._ladder.attempt(
                "tpu-sweep",
                lambda: self._sweep(
                    cancel=sweep_cancel,
                    engine=self._bitset_hint(graph, scc),
                ).check_scc(
                    graph, circuit, scc, scope_to_scc=scope_to_scc
                ),
                fall_to="native",
            )
            outcome["sweep_result"] = res
            outcome["sweep_seconds"] = time.monotonic() - t0
            arm_span.set(outcome="verdict")
            _race_sync("sweep.verdict")
            oracle_cancel.cancel()
        except SearchCancelled:
            outcome["sweep_cancelled"] = True
            arm_span.set(outcome="cancelled")
            _race_sync("sweep.unwound")
            if self.checkpoint is not None:
                # Discard this losing sweep's recorded progress FROM THE
                # WORKER THREAD, after its engine has raised: the worker
                # is the checkpoint's only writer, so no record can land
                # after this clear (the driver-side clear below covers
                # non-cancel exits, but only once the worker is joined —
                # clearing while the worker might still write would
                # re-create the residue it removes).
                try:
                    self.checkpoint.clear()
                # qi-lint: allow(degrade-via-ladder) — cleanup, not routing
                except Exception:  # noqa: BLE001 — cleanup is best-effort
                    pass
        except RungFailed as fail:
            # The ladder burned the sweep rung's retries (degrade event
            # already on the record); the racing oracle IS the fallback.
            outcome["sweep_error"] = str(fail.cause)
            arm_span.set(outcome="error")
            log.info("race: sweep engine unavailable (%s)", fail.cause)

    def _has_recorded_progress(self, scc: List[int]) -> bool:
        """Does the attached checkpoint hold progress plausibly belonging to
        THIS problem?  Delegated to the checkpoint class (which owns the
        on-disk format) — the full fingerprint check stays inside the
        backends, which ignore foreign files anyway; a false positive here
        merely skips oracle-first once."""
        probe = getattr(self.checkpoint, "has_progress", None)
        if probe is None:
            return False
        try:
            return bool(probe(1 << max(len(scc) - 1, 0)))
        # qi-lint: allow(degrade-via-ladder) — probe, not an engine rung
        except Exception:  # noqa: BLE001 — a broken probe must not block solves
            return False

    def check_scc(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
        _budget_burned: bool = False,
    ) -> SccCheckResult:
        # The routing decision is a span of its own ("route"): nested under
        # the pipeline's phase.search span, wrapping the race span when one
        # runs, and stamped with the engine that actually answered — the
        # record shows WHERE the verdict came from, not just how long.
        # ``_budget_burned`` (private; check_sccs fallback) records that
        # this problem's oracle budget ALREADY burned in the batch entry,
        # so the route skips straight to the post-burn engines instead of
        # re-burning the same budget.
        if self.cancel is not None and self.cancel.cancelled:
            # Pre-cancelled (a serving deadline expired before routing even
            # started): abort before touching any engine — cancellation is
            # an abort signal about scheduling, never a verdict.
            raise SearchCancelled(
                f"auto router cancelled before routing (|scc|={len(scc)})"
            )
        with get_run_record().span(
            "route", scc=len(scc), race_enabled=self.race
        ) as route_span:
            res = self._route(
                graph, circuit, scc, scope_to_scc=scope_to_scc,
                budget_burned=_budget_burned,
            )
            route_span.set(backend=res.stats.get("backend", "?"))
            # The live endpoint's "which rung is serving" answer: the
            # engine that produced the most recent verdict.
            get_run_record().gauge(
                "ladder.rung", res.stats.get("backend", "?")
            )
            return res

    # ---- batch entry (ISSUE 5): lane-packed multi-problem routing --------

    def _pack_bound(self, sizes: List[int]) -> Optional[int]:
        """Largest |scc| the batch entry may fuse into lane packs, or None
        when packing must not engage at all — PROBE-FREE (no device
        contact; the device-kind half of the auto gate is checked in
        check_sccs only after every budgeted oracle has answered, so a
        hung tunnel can never starve the verdict path).

        pack=True forces engagement (bounded only by the platform sweep
        limit applied later); pack=False (or a mesh/checkpoint, which the
        packed path does not serve) forbids it; pack=None engages only
        behind a MEASURED packed win (calibration.pack_win_max_scc), and —
        unlike mere engagement — the returned bound (win + one grid step
        of headroom) also CAPS which jobs may pack, so a batch that
        engages off two small measured jobs cannot sneak an unmeasured
        size into the pack.  Auto-gating additionally needs two jobs that
        could actually share a pack.
        """
        if self.pack is False or self.mesh is not None or self.checkpoint is not None:
            return None
        if self.pack is True:
            return SWEEP_DECODE_CEILING
        win = CALIBRATION.pack_win_max_scc
        if win is None:
            return None
        bound = win + SWEEP_WIN_SCC_HEADROOM
        eligible = [s for s in sizes if s <= bound]
        return bound if len(eligible) >= 2 else None

    def check_sccs(
        self,
        jobs: Sequence[Tuple[TrustGraph, Optional[Circuit], List[int]]],
        *,
        scope_to_scc: bool = False,
        cancels: Optional[Sequence[Optional[CancelToken]]] = None,
        origins: Optional[Sequence[str]] = None,
    ) -> List[SccCheckResult]:
        """Batch entry (``pipeline.check_many``): route many SCC problems
        at once, fusing sweep-sized ones into lane packs.

        The packed engine is LADDER-VISIBLE: the packed attempt runs as
        the ``tpu-sweep`` rung, so any failure — including an injected
        ``sweep.pack`` fault — emits a ``degrade`` event and falls back to
        the unpacked per-problem router with verdicts unchanged.  With
        ``pack=None`` (auto-gated), each packable job first gets the
        budgeted host oracle exactly as the sequential single-problem path
        would — real topologies resolve there in microseconds and only the
        budget-burners pay for a pack; ``pack=True`` (tests, benchmarks)
        skips the oracle for a deterministic packed run.  Jobs outside the
        pack window route per-job through :meth:`check_scc` (race,
        frontier region, host oracle) unchanged.
        """
        jobs = list(jobs)
        results: List[Optional[SccCheckResult]] = [None] * len(jobs)
        rec = get_run_record()
        packable: List[int] = []
        burned: Set[int] = set()
        pack_cap = self._pack_bound([len(scc) for _, _, scc in jobs])
        if pack_cap is not None:
            # Probe-free optimistic limit, exactly as _route's oracle-first
            # bound: the budgeted oracles below must answer without any
            # device contact (a hung tunnel blocks in the probe).
            if self.sweep_limit is not None:
                optimistic = self.sweep_limit
            elif _resolved_platform_limit is not None:
                optimistic = _resolved_platform_limit
            else:
                optimistic = max(SWEEP_LIMIT_TPU, _measured_sweep_raise() or 0)
            for i, (graph, circuit, scc) in enumerate(jobs):
                if (
                    len(scc) > min(optimistic, pack_cap)
                    or len(scc) - 1 > _SWEEP_LO_BITS
                ):
                    continue
                if self.pack is None:
                    res = self._budgeted_oracle(
                        graph, circuit, scc, scope_to_scc,
                        self._estimated_sweep_seconds(len(scc)),
                    )
                    if res is not None:
                        results[i] = res
                        continue
                    burned.add(i)
                packable.append(i)
        if packable and self.pack is None:
            # Every oracle has answered; the survivors head for the device
            # anyway, so the gated platform limit and the device-kind half
            # of the calibration gate (a TPU-measured pack win must not
            # engage elsewhere) are checked HERE, off the verdict path.
            from quorum_intersection_tpu.utils.platform import backend_kind

            limit = (
                self.sweep_limit if self.sweep_limit is not None
                else _platform_sweep_limit()
            )
            if backend_kind() != CALIBRATION.pack_win_device:
                packable = []
            else:
                packable = [i for i in packable if len(jobs[i][2]) <= limit]
        elif packable:
            limit = (
                self.sweep_limit if self.sweep_limit is not None
                else _platform_sweep_limit()
            )
            packable = [i for i in packable if len(jobs[i][2]) <= limit]
        if packable:
            def run_packed() -> List[SccCheckResult]:
                # Encoding hint for the PACK: one fused drive serves every
                # member, so the bitset twin engages only when every packed
                # job's SCC sits inside the measured win region — one dense-
                # friendly member routes the whole pack dense (the honest
                # default; per-job engines would defeat the fusion).
                hint = None
                if all(
                    self._bitset_hint(jobs[i][0], jobs[i][2]) == "bitset"
                    for i in packable
                ):
                    hint = "bitset"
                sweep = self._sweep(engine=hint)
                rec.event(
                    "route.decision", engine="tpu-sweep",
                    scc=max(len(jobs[i][2]) for i in packable),
                    reason=f"lane-packed batch of {len(packable)} jobs",
                )
                return sweep.check_sccs(
                    [jobs[i] for i in packable], scope_to_scc=scope_to_scc,
                    cancels=(
                        [cancels[i] for i in packable]
                        if cancels is not None else None
                    ),
                    origins=(
                        [origins[i] for i in packable]
                        if origins is not None else None
                    ),
                )

            try:
                packed = self._ladder.attempt(
                    "tpu-sweep", run_packed, fall_to="tpu-sweep"
                )
                for i, res in zip(packable, packed):
                    results[i] = res
            except RungFailed as fail:
                log.info(
                    "packed sweep unavailable (%s); falling back to the "
                    "unpacked per-problem router", fail.cause,
                )
        for i, (graph, circuit, scc) in enumerate(jobs):
            if results[i] is None:
                tok = cancels[i] if cancels is not None else None
                if tok is not None and tok.cancelled:
                    # qi-fuse: the request behind this leftover job is
                    # already dead — book its whole window space as
                    # cancelled coverage instead of burning an engine on a
                    # verdict nobody will read.
                    total = 1 << max(len(scc) - 1, 0)
                    rec.add("cert.windows_cancelled", total)
                    results[i] = SccCheckResult(intersects=False, stats={
                        "backend": self.name, "cancelled": True,
                        "candidates_checked": 0, "enumeration_total": total,
                        "cert": {
                            "window_space": total,
                            "windows_enumerated": 0,
                            "windows_pruned_guard": 0,
                            "windows_skipped_pack_fill": 0,
                            "windows_cancelled": total,
                        },
                    })
                    continue
                # A job whose budget already burned above must not re-burn
                # it in the per-problem route (gate-dropped or packed-rung
                # failure): _budget_burned skips straight to the post-burn
                # engines, the same place a --no-race burn lands.
                results[i] = self.check_scc(
                    graph, circuit, scc, scope_to_scc=scope_to_scc,
                    _budget_burned=i in burned,
                )
        return [res for res in results if res is not None]

    def _route(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
        budget_burned: bool = False,
    ) -> SccCheckResult:
        # Optimistic limit first (no device probe on THIS thread): the
        # oracle-vs-sweep window applies to every SCC a sweep could
        # possibly handle on any platform.  Racing mode (default) overlaps
        # the two engines — the platform probe and sweep spin-up happen in
        # a background worker while the budgeted oracle runs here, so the
        # worst case is ~max(engines) instead of the sequential
        # budget-burn-THEN-spin-up sum (measured 3.4x the direct sweep at
        # scc 36, sweep_vs_native_tpu_r5.txt).  --no-race restores the
        # sequential chain, whose happy path touches no device at all; if
        # its post-burn probe rules the sweep out (CPU platform mid-range
        # SCC, or no jax), the burned budget is lost and the unbudgeted
        # oracle restarts — the documented worst case, paid only on
        # pathological inputs.  A checkpoint file WITH recorded progress
        # skips the oracle entirely: re-burning the budget on every resume
        # of a preempted sweep would tax exactly the long runs checkpoints
        # exist for.
        resumable = self._has_recorded_progress(scc)
        if self.sweep_limit is not None:
            optimistic = self.sweep_limit
        elif _resolved_platform_limit is not None:
            # A prior burn/race already paid the device probe: the true
            # gated limit replaces the ungated optimistic guess, so a
            # foreign-device artifact cannot re-burn the budget on resume
            # (ADVICE r5 auto.py:251).
            optimistic = _resolved_platform_limit
        else:
            optimistic = max(SWEEP_LIMIT_TPU, _measured_sweep_raise() or 0)
        if len(scc) <= optimistic:
            if not resumable and not budget_burned:
                budget_s = self._estimated_sweep_seconds(len(scc))
                attempt = self._race if self.race else self._budgeted_oracle
                res = attempt(graph, circuit, scc, scope_to_scc, budget_s)
                if res is not None:
                    # The common path (race winner / oracle under budget)
                    # gets a routing record too, not just the fallbacks.
                    get_run_record().event(
                        "route.decision",
                        engine=res.stats.get("backend", "?"), scc=len(scc),
                        reason=(
                            f"race winner "
                            f"({res.stats.get('race', {}).get('winner', '?')})"
                            if self.race else
                            f"oracle finished under ~{budget_s:.1f}s budget"
                        ),
                    )
                    return res
            limit = (
                self.sweep_limit if self.sweep_limit is not None
                else _platform_sweep_limit()
            )
            if len(scc) <= limit:
                def run_sweep() -> SccCheckResult:
                    # Construct FIRST: the route.decision event fires only
                    # for a sweep that actually exists (a jax-free box must
                    # not record engine=tpu-sweep for a host-oracle verdict).
                    backend = self._sweep(
                        engine=self._bitset_hint(graph, scc)
                    )
                    log.debug("auto: sweep backend for |scc|=%d", len(scc))
                    get_run_record().event(
                        "route.decision", engine="tpu-sweep", scc=len(scc),
                        reason=(
                            "checkpoint has recorded progress" if resumable
                            else f"|scc| <= platform sweep limit {limit}"
                        ),
                    )
                    return backend.check_scc(
                        graph, circuit, scc, scope_to_scc=scope_to_scc
                    )

                try:
                    return self._ladder.attempt(
                        "tpu-sweep", run_sweep, fall_to="native"
                    )
                except RungFailed as fail:
                    log.info(
                        "sweep backend unavailable (%s); falling back",
                        fail.cause,
                    )
        # Large SCC: the device-resident frontier takes it ONLY inside a
        # MEASURED on-chip win region (CALIBRATION.frontier_win_min_scc,
        # derived from the newest crossover_tpu_r*.txt artifact with
        # verdict+count parity on every qualifying row) — routing claims
        # about the chip stay tied to recorded measurements, exactly like
        # the sweep-rate constants above.  Two bounds keep that honest
        # (ADVICE r4 medium): the live device kind must MATCH the kind the
        # win was measured on (a TPU win says nothing about a GPU), and
        # |scc| may exceed the largest MEASURED winning size by at most
        # FRONTIER_WIN_SCC_HEADROOM — one +4-org step, the granularity of
        # the crossover grid, justified by the ratio improving
        # monotonically with |scc| in every recorded artifact; beyond that
        # the config is untested extrapolation and the host oracle keeps
        # the SCC.  No artifact, or a CPU platform (where the native
        # oracle wins every measured size): host oracle.
        from quorum_intersection_tpu.utils.platform import backend_kind

        win = CALIBRATION.frontier_win_min_scc
        hi = CALIBRATION.frontier_win_max_scc
        in_region = (
            win is not None
            and win <= len(scc) <= (hi or win) + FRONTIER_WIN_SCC_HEADROOM
            and backend_kind() == CALIBRATION.frontier_win_device
        )
        if in_region:
            def run_frontier() -> SccCheckResult:
                from quorum_intersection_tpu.backends.tpu.frontier import (
                    TpuFrontierBackend,
                )

                # The CLI hands auto a SweepCheckpoint (it cannot know the
                # routing outcome); the frontier needs the (toRemove,
                # dontRemove) state format — convert at the same path, the
                # way the CLI does for an explicit --backend tpu-frontier.
                # Without this the frontier's resume_states call raises and
                # the degrade path silently drops BOTH the device engine
                # and the user's checkpointing (r5 review finding).
                ckpt = self.checkpoint
                if ckpt is not None and not hasattr(ckpt, "resume_states"):
                    from quorum_intersection_tpu.utils.checkpoint import (
                        FrontierCheckpoint,
                    )

                    ckpt = FrontierCheckpoint(ckpt.path)
                # The kwargs the win was MEASURED under ride along — a win
                # recorded at pop=4096 must not route to a default-pop
                # frontier (unknown keys raise and fall through to the
                # host oracle, so a rotten artifact degrades, not crashes).
                backend = TpuFrontierBackend(
                    checkpoint=ckpt, mesh=self.mesh,
                    **CALIBRATION.frontier_config,
                )
                log.info(
                    "auto: device frontier for |scc|=%d (measured win region: %s)",
                    len(scc), CALIBRATION.provenance.get("frontier"),
                )
                get_run_record().event(
                    "route.decision", engine="tpu-frontier", scc=len(scc),
                    reason=(
                        f"measured win region [{win}, "
                        f"{(hi or win) + FRONTIER_WIN_SCC_HEADROOM}] on "
                        f"{CALIBRATION.frontier_win_device}"
                    ),
                    provenance=CALIBRATION.provenance.get("frontier"),
                )
                return backend.check_scc(
                    graph, circuit, scc, scope_to_scc=scope_to_scc
                )

            try:
                return self._ladder.attempt(
                    "tpu-frontier", run_frontier, fall_to="native"
                )
            except RungFailed as fail:
                log.info("frontier unavailable (%s); falling back", fail.cause)
        if self.prefer_tpu:
            # `--backend tpu` is honest about where large SCCs outside the
            # measured win regions actually go — see the module docstring.
            log.info(
                "device engines skipped for |scc|=%d (outside every "
                "measured win region); using host oracle", len(scc),
            )
        if self.checkpoint is not None:
            # Host oracles are all-or-nothing; honor the user's expectation
            # loudly instead of silently dropping progress recording.
            log.warning(
                "checkpoint not honored: |scc|=%d routed to a host oracle "
                "(no progress will be recorded)", len(scc),
            )
        # Deliberately NOT an unconditional cancel=self.cancel: with no
        # external token this call stays zero-arg, the stable signature
        # callers (and test spies) may replace _cpu_oracle with.
        backend = (
            self._cpu_oracle(cancel=self.cancel)
            if self.cancel is not None else self._cpu_oracle()
        )
        log.debug("auto: %s backend for |scc|=%d", backend.name, len(scc))
        get_run_record().event(
            "route.decision", engine=backend.name, scc=len(scc),
            reason="host oracle (outside every measured device win region)",
        )
        return backend.check_scc(graph, circuit, scc, scope_to_scc=scope_to_scc)
