"""Automatic backend selection.

Strategy (SURVEY.md §7.2 step 4 rationale):

- **small SCC** (≤ ``sweep_limit`` nodes): the TPU exhaustive subset sweep is
  exact, embarrassingly parallel, and fastest — candidate space 2^|scc| is
  bounded;
- **large SCC**: the pruned search is the only tractable option — prefer the
  native C++ oracle, falling back to the pure-Python oracle; the TPU hybrid
  (host frontier + batched device fixpoints) is selected with
  ``prefer_tpu=True`` **and only on accelerator platforms** — the measured
  crossover (benchmarks/hybrid_crossover.py, README table) shows the native
  oracle winning at every tractable size on the CPU emulation.

Every selection is logged; failures to import/compile an accelerator backend
degrade gracefully to the next option so the CLI always yields a verdict.
"""

from __future__ import annotations

from typing import List, Optional

from quorum_intersection_tpu.backends.base import SccCheckResult
from quorum_intersection_tpu.encode.circuit import Circuit
from quorum_intersection_tpu.fbas.graph import TrustGraph
from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("backends.auto")

# Exhaustive-sweep cutoffs by platform: the sweep is exact and fastest while
# 2^(|scc|-1) stays cheap.  Measured:
# - v5e chip: ~0.5-1G cand/s → 2^32 ≈ a few seconds ⇒ limit 33;
# - CPU emulation: ~0.45M cand/s (bench.py throughput phase) while the
#   native oracle runs ~0.7 µs/B&B-call (benchmarks/hybrid_crossover.py:
#   majority-18 = 185k calls = 0.13 s) — the oracle beats an exhaustive
#   2^(n-1) sweep at every measured size, so on CPU the sweep is only kept
#   where its worst case is sub-second: 2^17/0.45M ≈ 0.3 s ⇒ limit 18.
SWEEP_LIMIT_TPU = 33
SWEEP_LIMIT_CPU = 18
DEFAULT_SWEEP_LIMIT = None  # resolve by platform at check time


def _platform_sweep_limit() -> int:
    from quorum_intersection_tpu.utils.platform import is_cpu_platform

    return SWEEP_LIMIT_CPU if is_cpu_platform() else SWEEP_LIMIT_TPU


class AutoBackend:
    name = "auto"

    def __init__(
        self,
        prefer_tpu: bool = False,
        sweep_limit: Optional[int] = DEFAULT_SWEEP_LIMIT,
        seed: Optional[int] = None,
        randomized: bool = False,
        checkpoint=None,
    ) -> None:
        self.prefer_tpu = prefer_tpu
        self.sweep_limit = sweep_limit
        self.checkpoint = checkpoint  # forwarded to the sweep/hybrid backends
        self._oracle_options = {"seed": seed, "randomized": randomized} if (randomized or seed is not None) else {}

    def _sweep(self):
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend

        return TpuSweepBackend(checkpoint=self.checkpoint)

    def _hybrid(self):
        from quorum_intersection_tpu.backends.tpu.hybrid import TpuHybridBackend

        # Same seeded/randomized tie-break contract as the host oracles.
        options = dict(self._oracle_options)
        if self.checkpoint is not None:
            # The user handed a sweep-format checkpoint (path-per-problem);
            # the hybrid stores its frontier at the same path in its own
            # format — the fingerprints keep the two from cross-resuming.
            from quorum_intersection_tpu.utils.checkpoint import HybridCheckpoint

            options["checkpoint"] = HybridCheckpoint(self.checkpoint.path)
        return TpuHybridBackend(**options)

    def _cpu_oracle(self):
        try:
            from quorum_intersection_tpu.backends.cpp import CppOracleBackend

            backend = CppOracleBackend(**self._oracle_options)
            backend.ensure_built()
            return backend
        except Exception as exc:  # noqa: BLE001 — degrade to pure Python
            log.info("native C++ oracle unavailable (%s); using Python oracle", exc)
            from quorum_intersection_tpu.backends.python_oracle import PythonOracleBackend

            return PythonOracleBackend(**self._oracle_options)

    def check_scc(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
    ) -> SccCheckResult:
        limit = self.sweep_limit if self.sweep_limit is not None else _platform_sweep_limit()
        if len(scc) <= limit:
            try:
                backend = self._sweep()
                log.debug("auto: sweep backend for |scc|=%d", len(scc))
                return backend.check_scc(graph, circuit, scc, scope_to_scc=scope_to_scc)
            except Exception as exc:  # noqa: BLE001
                log.info("sweep backend unavailable (%s); falling back", exc)
        if self.prefer_tpu:
            # Measured (benchmarks/hybrid_crossover.py): on the CPU
            # emulation the hybrid's per-row cost is ~100× the native
            # oracle's per-fixpoint cost, so it loses at every tractable
            # size — only route to it when a real accelerator is attached.
            from quorum_intersection_tpu.utils.platform import is_cpu_platform

            if is_cpu_platform():
                log.info(
                    "hybrid skipped on CPU platform (native oracle measured "
                    "faster at every tractable size); using host oracle"
                )
            else:
                try:
                    backend = self._hybrid()
                    log.debug("auto: hybrid backend for |scc|=%d", len(scc))
                    return backend.check_scc(graph, circuit, scc, scope_to_scc=scope_to_scc)
                except Exception as exc:  # noqa: BLE001
                    log.info("hybrid backend unavailable (%s); falling back", exc)
        if self.checkpoint is not None:
            # Host oracles are all-or-nothing; honor the user's expectation
            # loudly instead of silently dropping progress recording.
            log.warning(
                "checkpoint not honored: |scc|=%d routed to a host oracle "
                "(no progress will be recorded)", len(scc),
            )
        backend = self._cpu_oracle()
        log.debug("auto: %s backend for |scc|=%d", backend.name, len(scc))
        return backend.check_scc(graph, circuit, scc, scope_to_scc=scope_to_scc)
