"""Pure-Python branch-and-bound oracle — the portable correctness anchor.

Re-implements the reference's exponential search with the same pruning logic
(`/root/reference/quorum_intersection.cpp:252-400`), written fresh against the
pinned spec in SURVEY.md §2.1 C6-C9:

- :func:`find_best_node`        — branching heuristic: max in-degree within the
  current quorum excluding the restriction set (cpp:203-250).  The reference
  tie-breaks uniformly at random (its only nondeterminism; verdict-independent,
  SURVEY.md C7 [verified]); default here is deterministic (lowest vertex index
  among the argmax set), with an optional seeded RNG mode that is
  distributionally equivalent (uniform over the same argmax set).
- :func:`is_minimal_quorum`     — quorum whose every single-node removal kills
  all quorums inside it (cpp:179-201).
- :func:`iterate_minimal_quorums` — inclusion/exclusion enumeration of minimal
  quorums over (toRemove, dontRemove) with the reference's four prunes
  (cpp:261, :266-268, :281-291, :303-314, :325-328).
- :class:`PythonOracleBackend.check_scc` — the disjointness driver: for each
  minimal quorum Q, search for a quorum disjoint from Q; candidates larger than
  ⌊|scc|/2⌋ are pruned since two disjoint quorums cannot both exceed half
  (cpp:386-391).
"""

from __future__ import annotations

import logging
import random
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence

from quorum_intersection_tpu.backends.base import SccCheckResult
from quorum_intersection_tpu.encode.circuit import Circuit
from quorum_intersection_tpu.fbas.graph import TrustGraph
from quorum_intersection_tpu.fbas.semantics import max_quorum
from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("backends.python")


def find_best_node(
    quorum: Sequence[int],
    restriction: Sequence[int],
    graph: TrustGraph,
    rng: Optional[random.Random] = None,
) -> int:
    """Next branch variable: a max-in-degree node within ``quorum`` minus
    ``restriction`` (cpp:203-250).

    The reference's reservoir-style randomized tie-break lands on a uniform
    member of the final argmax set; we pick the lowest index (deterministic)
    or ``rng.choice`` over the same set.  Parallel edges and self-loops count
    with multiplicity (Q7, cpp:224-229).
    """
    eligible = set(quorum) - set(restriction)
    indeg: Dict[int, int] = {}
    for node in quorum:
        for w in graph.succ[node]:
            if w in eligible:
                indeg[w] = indeg.get(w, 0) + 1
    if not indeg:
        return quorum[0]  # bestNode initialization fallback (cpp:221)
    max_deg = max(indeg.values())
    candidates = sorted(w for w, d in indeg.items() if d == max_deg)
    if rng is not None:
        return rng.choice(candidates)
    return candidates[0]


def is_minimal_quorum(nodes: Sequence[int], graph: TrustGraph) -> bool:
    """``nodes`` contains a quorum AND removing any single node kills all
    quorums inside it (cpp:179-201)."""
    avail = [False] * graph.n
    for v in nodes:
        avail[v] = True
    if not max_quorum(graph, nodes, avail):
        return False
    for v in nodes:
        avail[v] = False
        if max_quorum(graph, nodes, avail):
            return False
        avail[v] = True
    return True


class _SearchState:
    """Mutable search bookkeeping shared across the recursion.

    ``trace`` mirrors the reference's per-call trace spew (its static call
    counter + BOOST_LOG_TRIVIAL(trace) narration, cpp:258-259): captured once
    so the hot recursion pays a single attribute check when tracing is off.
    """

    __slots__ = ("bnb_calls", "minimal_quorums", "fixpoint_calls", "trace",
                 "budget_calls", "budget_exceeded", "best_node_fallback",
                 "cancel", "cancelled")

    def __init__(self, budget_calls: int = 0, cancel=None) -> None:
        self.bnb_calls = 0
        self.minimal_quorums = 0
        self.fixpoint_calls = 0
        # Times the cpp:221 bestNode initialization fallback fired with a node
        # already in dontRemove (PARITY.md D15) — the one branch where the
        # frontier's enumeration legitimately diverges from this oracle's.
        self.best_node_fallback = 0
        self.trace = log.isEnabledFor(logging.DEBUG)
        # 0 = unlimited; otherwise the search aborts (budget_exceeded) once
        # bnb_calls passes the budget — see base.OracleBudgetExceeded.
        self.budget_calls = budget_calls
        self.budget_exceeded = False
        # Optional base.CancelToken, polled alongside the budget check so a
        # racing caller can stop this search from another thread — see
        # base.SearchCancelled.
        self.cancel = cancel
        self.cancelled = False


def iterate_minimal_quorums(
    to_remove: List[int],
    dont_remove: List[int],
    graph: TrustGraph,
    visitor: Callable[[List[int]], bool],
    current_visitor: Callable[[List[int]], bool],
    state: _SearchState,
    rng: Optional[random.Random],
    dont_known_no_quorum: bool = False,
) -> bool:
    """Branch-and-bound enumeration of minimal quorums (cpp:252-346).

    Invariant: every minimal quorum ⊆ toRemove ∪ dontRemove that contains all
    of dontRemove is eventually visited (or the search stops once ``visitor``
    returns True).  Prunes, in order:

    1. ``current_visitor(dontRemove)`` — caller-supplied size prune (cpp:261);
    2. both sets empty (cpp:266-268);
    3. dontRemove already contains a quorum → report iff dontRemove *is* a
       minimal quorum, then stop descending either way (cpp:281-291: any
       proper superset cannot be minimal);
    4. no quorum in toRemove ∪ dontRemove (cpp:303-306);
    5. the max quorum does not contain all of dontRemove (cpp:308-314);
    6. nothing outside dontRemove left to branch on (cpp:325-328).

    Then branch on ``bestNode``: excluded first (cpp:336), included second
    (cpp:343-345).
    """
    state.bnb_calls += 1
    if state.budget_calls and state.bnb_calls > state.budget_calls:
        # Abort the whole recursion (True unwinds like a hit); the caller
        # distinguishes via budget_exceeded, never via the verdict.
        state.budget_exceeded = True
        return True
    if state.cancel is not None and state.cancel.cancelled:
        # Same unwind as the budget abort; distinguished via `cancelled`,
        # never via the verdict.
        state.cancelled = True
        return True
    if state.trace:
        log.debug(
            "B&B call %d: |toRemove|=%d |dontRemove|=%d",
            state.bnb_calls, len(to_remove), len(dont_remove),
        )
    if current_visitor(dont_remove):
        if state.trace:
            log.debug("prune: |dontRemove|=%d exceeds size bound", len(dont_remove))
        return False
    if not to_remove and not dont_remove:
        return False

    avail = [False] * graph.n
    for v in dont_remove:
        avail[v] = True

    # The exclude-branch child shares its parent's dontRemove set, whose
    # fixpoint the parent just computed to be empty — skip the guaranteed
    # repeat (mirrors the native oracle exactly for stats lockstep).
    dont_has_quorum = False
    if not dont_known_no_quorum:
        state.fixpoint_calls += 1
        dont_has_quorum = bool(max_quorum(graph, dont_remove, avail))
    if dont_has_quorum:
        if is_minimal_quorum(dont_remove, graph):
            state.minimal_quorums += 1
            if state.trace:
                log.debug(
                    "minimal quorum #%d found (size %d): %s",
                    state.minimal_quorums, len(dont_remove), dont_remove,
                )
            return visitor(list(dont_remove))
        if state.trace:
            log.debug("prune: dontRemove contains a non-minimal quorum")
        return False

    for v in to_remove:
        avail[v] = True
    state.fixpoint_calls += 1
    quorum = max_quorum(graph, dont_remove + to_remove, avail)
    if not quorum:
        return False

    quorum_set = set(quorum)
    for v in dont_remove:
        if v not in quorum_set:
            return False

    best = find_best_node(quorum, dont_remove, graph, rng)

    remaining = quorum_set - set(dont_remove)
    if best not in remaining:
        # Only the cpp:221 fallback can pick a dontRemove member (the normal
        # argmax is over quorum ∖ restriction) — record it so differential
        # tests can tell D15 divergence apart from a frontier bug.
        state.best_node_fallback += 1
    if not remaining:
        return False

    new_to_remove = sorted(v for v in remaining if v != best)
    if iterate_minimal_quorums(
        new_to_remove, dont_remove, graph, visitor, current_visitor, state, rng,
        dont_known_no_quorum=True,  # same dontRemove: fixpoint is a repeat
    ):
        return True
    return iterate_minimal_quorums(
        new_to_remove, dont_remove + [best], graph, visitor, current_visitor, state, rng
    )


class PythonOracleBackend:
    """Reference-faithful disjointness search on the host."""

    name = "python"
    needs_circuit = False  # works on TrustGraph set semantics directly

    def __init__(
        self,
        seed: Optional[int] = None,
        randomized: bool = False,
        budget_calls: Optional[int] = None,
        cancel=None,
    ) -> None:
        self._rng = random.Random(seed) if (randomized or seed is not None) else None
        self._budget_calls = 0 if budget_calls is None else int(budget_calls)
        self._cancel = cancel  # base.CancelToken or None (racing auto router)

    def check_scc(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
    ) -> SccCheckResult:
        t0 = time.perf_counter()
        state = _SearchState(budget_calls=self._budget_calls, cancel=self._cancel)

        if scope_to_scc:
            avail = [False] * graph.n
            for v in scc:
                avail[v] = True
        else:
            # Reference semantics: the whole graph starts available (Q6,
            # cpp:354) — sound for a sink SCC, whose slices cannot reference
            # outside nodes.
            avail = [True] * graph.n

        outcome: Dict[str, object] = {"intersects": True, "q1": None, "q2": None}

        def visitor(quorum: List[int]) -> bool:
            # Mark Q unavailable, search the SCC for a disjoint quorum
            # (cpp:357-384).
            for v in quorum:
                avail[v] = False
            state.fixpoint_calls += 1
            disjoint = max_quorum(graph, scc, avail)
            if disjoint:
                if state.trace:
                    log.debug(
                        "disjointness probe: FOUND disjoint quorum (size %d) — stopping",
                        len(disjoint),
                    )
                outcome["intersects"] = False
                outcome["q1"] = disjoint
                outcome["q2"] = list(quorum)
                return True
            if state.trace:
                log.debug("disjointness probe: no disjoint quorum; continuing")
            for v in quorum:
                avail[v] = True
            return False

        half = len(scc) // 2

        def current_visitor(candidate: List[int]) -> bool:
            # Two disjoint quorums cannot both exceed ⌊|scc|/2⌋ (cpp:386-391).
            return len(candidate) > half

        # The B&B recursion is ~2 frames per level of |scc|; lift the limit
        # for large components.
        needed = 4 * len(scc) + 1000
        old_limit = sys.getrecursionlimit()
        if needed > old_limit:
            sys.setrecursionlimit(needed)
        try:
            iterate_minimal_quorums(
                list(scc), [], graph, visitor, current_visitor, state, self._rng
            )
        finally:
            if needed > old_limit:
                sys.setrecursionlimit(old_limit)

        seconds = time.perf_counter() - t0
        if state.budget_exceeded:
            from quorum_intersection_tpu.backends.base import OracleBudgetExceeded

            raise OracleBudgetExceeded(
                f"python oracle exceeded {self._budget_calls} B&B calls "
                f"on |scc|={len(scc)} after {seconds:.2f}s"
            )
        if state.cancelled:
            from quorum_intersection_tpu.backends.base import SearchCancelled

            raise SearchCancelled(
                f"python oracle cancelled on |scc|={len(scc)} after "
                f"{seconds:.2f}s ({state.bnb_calls} B&B calls)"
            )
        if state.trace:
            log.debug(
                "search done: %d B&B calls, %d minimal quorums, %d fixpoints in %.3fs",
                state.bnb_calls, state.minimal_quorums, state.fixpoint_calls, seconds,
            )
        return SccCheckResult(
            intersects=bool(outcome["intersects"]),
            q1=outcome["q1"],
            q2=outcome["q2"],
            stats={
                "backend": self.name,
                "bnb_calls": state.bnb_calls,
                "minimal_quorums": state.minimal_quorums,
                "fixpoint_calls": state.fixpoint_calls,
                "best_node_fallback": state.best_node_fallback,
                "seconds": seconds,
                # qi-cert ledger: a B&B engine's coverage evidence is its
                # node counts — echoed into the verdict certificate so
                # "exhaustively searched" carries its search size.
                "cert": {
                    "bnb_calls": state.bnb_calls,
                    "minimal_quorums": state.minimal_quorums,
                    "fixpoint_calls": state.fixpoint_calls,
                },
            },
        )
