// Native CPU oracle for the quorum-intersection framework.
//
// Re-implements the exponential core of the reference solver
// (/root/reference/quorum_intersection.cpp:90-400) as a standalone C++17
// shared library with a C ABI, written fresh against the pinned semantics in
// SURVEY.md §2.1/§2.3 and kept in exact lockstep (verdicts AND search
// statistics) with the pure-Python oracle in backends/python_oracle.py:
//
//   - slice_unit / slice_satisfied  ~ containsQuorumSlice (cpp:90-138),
//     with quirks Q2 (null qset never satisfiable), Q3 (threshold <= 0 or
//     threshold > members normalized to never-satisfiable) and Q4
//     (self-availability required) pinned as in fbas/semantics.py.
//   - max_quorum                    ~ containsQuorum greatest fixpoint
//     (cpp:140-177), including the availability restore on exit.
//   - is_minimal_quorum             ~ isMinimalQuorum (cpp:179-201).
//   - find_best_node                ~ findBestNode (cpp:203-250); default
//     tie-break is deterministic lowest-index over the argmax set, optional
//     seeded RNG mode is uniform over the same set (verdict-independent,
//     SURVEY.md C7 [verified]).
//   - Search::iterate               ~ iterateMinimalQuorums (cpp:252-346)
//     with all four prunes in the reference order.
//   - qi_check_scc                  ~ checkMinimalQuorums (cpp:348-400):
//     per minimal quorum Q, probe the SCC for a quorum disjoint from Q; the
//     half-size prune (two disjoint quorums cannot both exceed |scc|/2,
//     cpp:386-391) is the current_visitor.
//
// Data comes in pre-flattened from Python (see backends/cpp/__init__.py):
// the trust graph as CSR successor lists and every quorum-set tree as a pool
// of "units" (threshold, member span, inner-unit span).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <random>
#include <vector>

namespace {

struct Graph {
  int32_t n;
  const int32_t* succ_off;  // CSR offsets, length n+1
  const int32_t* succ_tgt;  // CSR targets (with multiplicity, quirk Q7)
  const int32_t* roots;     // per-node root unit index; -1 == null qset (Q2)
  const int32_t* units;     // 5 ints per unit: threshold, mb, me, ib, ie
  const int32_t* mem;       // member pool (node indices)
  const int32_t* inner;     // inner pool (unit indices)
};

// Threshold test for one (sub-)unit against the availability vector, with the
// reference's dual early-exit counters (fail = members - threshold + 1).
bool slice_unit(const Graph& g, int32_t u, const uint8_t* avail) {
  const int32_t* U = g.units + 5 * u;
  int64_t t = U[0];
  const int32_t mb = U[1], me = U[2], ib = U[3], ie = U[4];
  if (t <= 0) return false;  // Q3: degenerate threshold, never satisfiable
  int64_t fail = (me - mb) + (ie - ib) - t + 1;
  if (fail <= 0) return false;  // Q3: threshold > members
  for (int32_t i = mb; i < me; ++i) {
    if (avail[g.mem[i]]) {
      if (--t == 0) return true;
    } else if (--fail == 0) {
      return false;
    }
  }
  for (int32_t i = ib; i < ie; ++i) {
    if (slice_unit(g, g.inner[i], avail)) {
      if (--t == 0) return true;
    } else if (--fail == 0) {
      return false;
    }
  }
  return false;
}

bool slice_satisfied(const Graph& g, int32_t owner, const uint8_t* avail) {
  const int32_t root = g.roots[owner];
  if (root < 0) return false;       // Q2: null quorumSet
  if (!avail[owner]) return false;  // Q4: self must be available
  return slice_unit(g, root, avail);
}

// Greatest fixpoint of f(X) = {x in X : slice(x) satisfied by X}, in place:
// `nodes` is compacted to the surviving quorum (keeping relative order) and
// `avail` is narrowed during iteration but restored before returning, so
// callers can reuse their availability vector (cpp:171-173).
// `removed_scratch` is caller-provided so the hot search allocates nothing.
void max_quorum_inplace(const Graph& g, std::vector<int32_t>& nodes,
                        uint8_t* avail, std::vector<int32_t>& removed_scratch) {
  removed_scratch.clear();
  for (;;) {
    const size_t before = nodes.size();
    size_t w = 0;
    for (size_t i = 0; i < before; ++i) {
      const int32_t v = nodes[i];
      if (slice_satisfied(g, v, avail)) {
        nodes[w++] = v;
      } else if (avail[v]) {
        avail[v] = 0;
        removed_scratch.push_back(v);
      }
    }
    nodes.resize(w);
    if (nodes.size() == before) break;
  }
  for (const int32_t v : removed_scratch) avail[v] = 1;
}

// Value-returning convenience wrapper (cold paths: SCC scan, candidate
// check, bindings).
std::vector<int32_t> max_quorum(const Graph& g, std::vector<int32_t> nodes,
                                uint8_t* avail) {
  std::vector<int32_t> removed;
  max_quorum_inplace(g, nodes, avail, removed);
  return nodes;
}



struct Search {
  const Graph& g;
  uint8_t* avail;  // disjointness availability, shared across visitor calls
  std::vector<int32_t> scc;
  int32_t half;
  std::mt19937_64* rng;
  // Per-call trace narration to stderr — the native analog of the
  // reference's BOOST_LOG_TRIVIAL(trace) spew + static call counter
  // (cpp:258-259); message content matches backends/python_oracle.py so
  // both CLIs show the same search trajectory under -t.
  bool trace = false;
  int64_t bnb_calls = 0;
  int64_t minimal_quorums = 0;
  int64_t fixpoint_calls = 0;
  // Optional call budget (0 = unlimited): lets a caller race this pruned
  // search against an exhaustive engine without threads or processes — the
  // search aborts deterministically once it has proven more expensive than
  // the alternative (backends/auto.py latency-aware routing).
  int64_t budget_calls = 0;
  bool budget_exceeded = false;
  // Optional cooperative cancel flag (nullptr = never cancelled): polled
  // alongside the budget check, so a racing caller (backends/auto.py) can
  // stop this search from another thread the moment a concurrent engine
  // reaches a verdict.  The pointer targets a caller-owned int32 written
  // from Python while this call runs without the GIL; a plain volatile
  // read is sufficient — the flag only ever transitions 0 -> 1 and a
  // one-call-delayed observation is harmless.
  const volatile int32_t* cancel_flag = nullptr;
  bool cancelled = false;
  bool found = false;
  std::vector<int32_t> q1, q2;
  // Collect mode (top-tier analytics): instead of probing each minimal
  // quorum for a disjoint partner, accumulate the UNION of their members
  // and keep enumerating.  The caller must disable the half-size prune —
  // it is sound for the disjointness search only (two disjoint quorums
  // cannot both exceed |scc|/2), not for full enumeration.
  bool collect = false;
  std::vector<uint8_t> union_mark;

  // Reusable per-frame scratch (hot-path allocation elimination, r3): every
  // buffer is fully consumed BEFORE the recursive calls in iterate(), so
  // sharing one set across the whole recursion is safe.  ~10 heap
  // allocations per B&B call become zero; O(n) clears remain (cheap).
  std::vector<uint8_t> s_local;       // availability for dont/cand fixpoints
  std::vector<uint8_t> s_avail_min;   // is_minimal_quorum availability
  std::vector<uint8_t> s_mark;        // in_quorum / eligible marks
  std::vector<int32_t> s_removed;     // max_quorum_inplace restore list
  std::vector<int32_t> s_nodes;       // dont-check fixpoint workspace
  std::vector<int32_t> s_quorum;      // cand fixpoint workspace
  std::vector<int32_t> s_min_nodes;   // is_minimal fixpoint workspace
  std::vector<int32_t> s_probe;       // disjointness-probe workspace
  std::vector<int32_t> s_indeg;       // find_best_node in-degrees
  std::vector<int32_t> s_candidates;  // find_best_node argmax set

  void init_scratch() {
    s_local.assign(g.n, 0);
    s_avail_min.assign(g.n, 0);
    s_mark.assign(g.n, 0);
    s_indeg.assign(g.n, 0);
  }

  // is_minimal_quorum (cpp:179-201) on scratch: candidate is a quorum AND
  // removing any single node kills all quorums inside it.
  bool minimal_on_scratch(const std::vector<int32_t>& nodes) {
    std::fill(s_avail_min.begin(), s_avail_min.end(), 0);
    for (const int32_t v : nodes) s_avail_min[v] = 1;
    s_min_nodes.assign(nodes.begin(), nodes.end());
    max_quorum_inplace(g, s_min_nodes, s_avail_min.data(), s_removed);
    if (s_min_nodes.empty()) return false;
    for (const int32_t v : nodes) {
      s_avail_min[v] = 0;
      s_min_nodes.assign(nodes.begin(), nodes.end());
      max_quorum_inplace(g, s_min_nodes, s_avail_min.data(), s_removed);
      if (!s_min_nodes.empty()) return false;
      s_avail_min[v] = 1;
    }
    return true;
  }

  // find_best_node (cpp:203-250) on scratch; semantics identical to the
  // free function (max in-degree with multiplicity, lowest-index or
  // seeded-uniform tie-break).
  int32_t best_on_scratch(const std::vector<int32_t>& quorum,
                          const std::vector<int32_t>& restriction) {
    std::fill(s_mark.begin(), s_mark.end(), 0);
    for (const int32_t v : quorum) s_mark[v] = 1;
    for (const int32_t v : restriction) s_mark[v] = 0;
    std::fill(s_indeg.begin(), s_indeg.end(), 0);
    bool any_edge = false;
    for (const int32_t v : quorum) {
      for (int32_t e = g.succ_off[v]; e < g.succ_off[v + 1]; ++e) {
        const int32_t w = g.succ_tgt[e];
        if (s_mark[w]) {
          ++s_indeg[w];
          any_edge = true;
        }
      }
    }
    if (!any_edge) return quorum[0];  // bestNode init fallback (cpp:221)
    int32_t max_deg = 0;
    for (const int32_t v : quorum) max_deg = std::max(max_deg, s_indeg[v]);
    s_candidates.clear();
    for (const int32_t v : quorum) {
      if (s_mark[v] && s_indeg[v] == max_deg) s_candidates.push_back(v);
    }
    std::sort(s_candidates.begin(), s_candidates.end());
    s_candidates.erase(
        std::unique(s_candidates.begin(), s_candidates.end()),
        s_candidates.end());
    if (rng != nullptr) {
      std::uniform_int_distribution<size_t> pick(0, s_candidates.size() - 1);
      return s_candidates[pick(*rng)];
    }
    return s_candidates.front();
  }

  // checkMinimalQuorums' visitor (cpp:357-384): mark Q unavailable, probe the
  // SCC for a disjoint quorum; restore on miss.
  bool visit(const std::vector<int32_t>& quorum) {
    for (const int32_t v : quorum) avail[v] = 0;
    ++fixpoint_calls;
    s_probe.assign(scc.begin(), scc.end());
    max_quorum_inplace(g, s_probe, avail, s_removed);
    std::vector<int32_t>& disjoint = s_probe;
    if (!disjoint.empty()) {
      if (trace) {
        std::fprintf(stderr,
                     "trace: disjointness probe: FOUND disjoint quorum "
                     "(size %zu) — stopping\n",
                     disjoint.size());
      }
      found = true;
      q1.assign(disjoint.begin(), disjoint.end());
      q2 = quorum;
      return true;
    }
    if (trace) {
      std::fprintf(stderr,
                   "trace: disjointness probe: no disjoint quorum; continuing\n");
    }
    for (const int32_t v : quorum) avail[v] = 1;
    return false;
  }

  // `dont_known_no_quorum`: the exclude-branch child shares its parent's
  // dontRemove set, whose fixpoint the parent just computed to be empty —
  // recomputing it is a guaranteed repeat (the host-side analog of the
  // hybrid's mask→result memo), so the parent passes the knowledge down
  // and the child skips that fixpoint.  Exact: the same set against the
  // same graph has the same greatest fixpoint.
  bool iterate(const std::vector<int32_t>& to_remove,
               std::vector<int32_t>& dont_remove,
               bool dont_known_no_quorum = false) {
    ++bnb_calls;
    if (budget_calls > 0 && bnb_calls > budget_calls) {
      // Abort the whole recursion (true unwinds like a hit); the caller
      // distinguishes via budget_exceeded, never via the verdict.
      budget_exceeded = true;
      return true;
    }
    if (cancel_flag != nullptr && *cancel_flag != 0) {
      // Same unwind as the budget abort; the caller distinguishes via the
      // -3 return, never via the verdict.
      cancelled = true;
      return true;
    }
    if (trace) {
      std::fprintf(stderr, "trace: B&B call %lld: |toRemove|=%zu |dontRemove|=%zu\n",
                   static_cast<long long>(bnb_calls), to_remove.size(),
                   dont_remove.size());
    }
    // Size prune (cpp:261 via :386-391): two disjoint quorums cannot both
    // exceed half the SCC.
    if (static_cast<int32_t>(dont_remove.size()) > half) {
      if (trace) {
        std::fprintf(stderr, "trace: prune: |dontRemove|=%zu exceeds size bound\n",
                     dont_remove.size());
      }
      return false;
    }
    if (to_remove.empty() && dont_remove.empty()) return false;

    std::fill(s_local.begin(), s_local.end(), 0);
    uint8_t* local = s_local.data();
    for (const int32_t v : dont_remove) local[v] = 1;

    bool dont_has_quorum = false;
    if (!dont_known_no_quorum) {
      ++fixpoint_calls;
      s_nodes.assign(dont_remove.begin(), dont_remove.end());
      max_quorum_inplace(g, s_nodes, local, s_removed);
      dont_has_quorum = !s_nodes.empty();
    }
    if (dont_has_quorum) {
      // dontRemove already contains a quorum: report iff it IS a minimal
      // quorum; either way stop descending (cpp:281-291).
      if (minimal_on_scratch(dont_remove)) {
        ++minimal_quorums;
        if (trace) {
          std::fprintf(stderr, "trace: minimal quorum #%lld found (size %zu)\n",
                       static_cast<long long>(minimal_quorums),
                       dont_remove.size());
        }
        if (collect) {
          for (const int32_t v : dont_remove) union_mark[v] = 1;
          return false;  // keep enumerating
        }
        return visit(dont_remove);
      }
      if (trace) {
        std::fprintf(stderr,
                     "trace: prune: dontRemove contains a non-minimal quorum\n");
      }
      return false;
    }

    for (const int32_t v : to_remove) local[v] = 1;
    s_quorum.assign(dont_remove.begin(), dont_remove.end());
    s_quorum.insert(s_quorum.end(), to_remove.begin(), to_remove.end());
    ++fixpoint_calls;
    max_quorum_inplace(g, s_quorum, local, s_removed);
    const std::vector<int32_t>& quorum = s_quorum;
    if (quorum.empty()) return false;  // prune (cpp:303-306)

    std::fill(s_mark.begin(), s_mark.end(), 0);
    for (const int32_t v : quorum) s_mark[v] = 1;
    for (const int32_t v : dont_remove) {
      if (!s_mark[v]) return false;  // prune (cpp:308-314)
    }

    const int32_t best = best_on_scratch(quorum, dont_remove);

    // remaining = quorum \ dontRemove; nothing left to branch on is a prune
    // (cpp:325-328).  `quorum` has unique elements (it is a fixpoint of the
    // unique candidate list), so no dedup is needed.  `new_to_remove` is a
    // REAL per-frame vector: it must survive across the first recursive
    // call for the second — the only allocation left in the hot path.
    std::fill(s_mark.begin(), s_mark.end(), 0);
    for (const int32_t v : dont_remove) s_mark[v] = 1;
    std::vector<int32_t> new_to_remove;
    new_to_remove.reserve(quorum.size());
    bool any_remaining = false;
    for (const int32_t v : quorum) {
      if (!s_mark[v]) {
        any_remaining = true;
        if (v != best) new_to_remove.push_back(v);
      }
    }
    if (!any_remaining) return false;
    std::sort(new_to_remove.begin(), new_to_remove.end());

    // Branch: exclude best first (cpp:336), then include it (cpp:343-345).
    // Exclude child inherits this frame's dontRemove unchanged — its dont
    // fixpoint is a guaranteed repeat of the empty one computed above.
    if (iterate(new_to_remove, dont_remove, /*dont_known_no_quorum=*/true)) {
      return true;
    }
    dont_remove.push_back(best);
    const bool hit = iterate(new_to_remove, dont_remove);
    dont_remove.pop_back();
    return hit;
  }
};

}  // namespace

extern "C" {

// Disjoint-quorum search within one SCC.  Returns 1 iff all quorums
// intersect; on 0, q1/q2 (buffers of capacity n) receive the witness pair;
// -2 iff `budget_calls` > 0 and the search exceeded it (verdict unknown —
// the caller falls back to another engine; backends/auto.py); -3 iff
// `cancel_flag` became nonzero (a racing caller's concurrent engine won).
// stats_out[0..2] = {bnb_calls, minimal_quorums, fixpoint_calls}.
// `trace` != 0 narrates every B&B call / prune / probe to stderr (the
// reference's -t trace spew, cpp:258-259).
int32_t qi_check_scc_cancel(int32_t n, const int32_t* succ_off,
                            const int32_t* succ_tgt, const int32_t* roots,
                            const int32_t* units, const int32_t* mem,
                            const int32_t* inner, const int32_t* scc,
                            int32_t scc_len, int32_t scope_to_scc,
                            int32_t use_rng, uint64_t seed, int32_t trace,
                            int64_t budget_calls,
                            const volatile int32_t* cancel_flag,
                            int32_t* q1_out,
                            int32_t* q1_len, int32_t* q2_out, int32_t* q2_len,
                            int64_t* stats_out) {
  Graph g{n, succ_off, succ_tgt, roots, units, mem, inner};
  // Reference semantics (Q6, cpp:354): the whole graph starts available —
  // sound for a sink SCC; scope_to_scc narrows availability to the SCC.
  std::vector<uint8_t> avail(n, scope_to_scc ? 0 : 1);
  std::vector<int32_t> scc_vec(scc, scc + scc_len);
  if (scope_to_scc) {
    for (const int32_t v : scc_vec) avail[v] = 1;
  }

  std::mt19937_64 rng_engine(seed);
  Search search{g, avail.data(), scc_vec, scc_len / 2,
                use_rng ? &rng_engine : nullptr, trace != 0};
  search.budget_calls = budget_calls;
  search.cancel_flag = cancel_flag;
  search.init_scratch();
  std::vector<int32_t> dont;
  search.iterate(scc_vec, dont);

  if (trace != 0) {
    std::fprintf(stderr,
                 "trace: search done: %lld B&B calls, %lld minimal quorums, "
                 "%lld fixpoints\n",
                 static_cast<long long>(search.bnb_calls),
                 static_cast<long long>(search.minimal_quorums),
                 static_cast<long long>(search.fixpoint_calls));
  }
  stats_out[0] = search.bnb_calls;
  stats_out[1] = search.minimal_quorums;
  stats_out[2] = search.fixpoint_calls;
  if (search.budget_exceeded || search.cancelled) {
    *q1_len = 0;
    *q2_len = 0;
    return search.cancelled ? -3 : -2;
  }
  if (search.found) {
    *q1_len = static_cast<int32_t>(search.q1.size());
    std::copy(search.q1.begin(), search.q1.end(), q1_out);
    *q2_len = static_cast<int32_t>(search.q2.size());
    std::copy(search.q2.begin(), search.q2.end(), q2_out);
    return 0;
  }
  *q1_len = 0;
  *q2_len = 0;
  return 1;
}

// Budgeted-but-uncancellable entry point (pre-race ABI): kept for any
// binding built against it; forwards with no cancel flag.
int32_t qi_check_scc_budget(int32_t n, const int32_t* succ_off,
                            const int32_t* succ_tgt, const int32_t* roots,
                            const int32_t* units, const int32_t* mem,
                            const int32_t* inner, const int32_t* scc,
                            int32_t scc_len, int32_t scope_to_scc,
                            int32_t use_rng, uint64_t seed, int32_t trace,
                            int64_t budget_calls, int32_t* q1_out,
                            int32_t* q1_len, int32_t* q2_out, int32_t* q2_len,
                            int64_t* stats_out) {
  return qi_check_scc_cancel(n, succ_off, succ_tgt, roots, units, mem, inner,
                             scc, scc_len, scope_to_scc, use_rng, seed, trace,
                             budget_calls, nullptr, q1_out, q1_len, q2_out,
                             q2_len, stats_out);
}

// Top-tier enumeration: the union of ALL minimal quorums' members inside
// the SCC (SCC-scoped availability), via the same branch-and-bound with
// the half-size prune disabled (that prune is sound only for the
// disjointness search) and a collecting visitor.  Writes the union as a
// 0/1 bitmap into `union_out` (caller buffer of n bytes).  Returns the
// minimal-quorum count, or -2 if `budget_calls` > 0 was exceeded (the
// bitmap then holds a partial union; stats_out is still filled).
int64_t qi_top_tier(int32_t n, const int32_t* succ_off,
                    const int32_t* succ_tgt, const int32_t* roots,
                    const int32_t* units, const int32_t* mem,
                    const int32_t* inner, const int32_t* scc,
                    int32_t scc_len, int64_t budget_calls,
                    uint8_t* union_out, int64_t* stats_out) {
  Graph g{n, succ_off, succ_tgt, roots, units, mem, inner};
  std::vector<uint8_t> avail(n, 0);
  std::vector<int32_t> scc_vec(scc, scc + scc_len);
  for (const int32_t v : scc_vec) avail[v] = 1;  // scoped availability

  // half = scc_len disables the size prune (dont_remove can never exceed
  // the whole SCC); deterministic tie-break — the enumerated SET is
  // order-independent anyway.
  Search search{g, avail.data(), scc_vec, scc_len, nullptr, false};
  search.collect = true;
  search.union_mark.assign(n, 0);
  search.budget_calls = budget_calls;
  search.init_scratch();
  std::vector<int32_t> dont;
  search.iterate(scc_vec, dont);

  std::copy(search.union_mark.begin(), search.union_mark.end(), union_out);
  stats_out[0] = search.bnb_calls;
  stats_out[1] = search.minimal_quorums;
  stats_out[2] = search.fixpoint_calls;
  if (search.budget_exceeded) return -2;
  return search.minimal_quorums;
}

// Unbudgeted entry point (original ABI): kept for the native CLI and any
// binding that predates the budgeted variant.
int32_t qi_check_scc(int32_t n, const int32_t* succ_off,
                     const int32_t* succ_tgt, const int32_t* roots,
                     const int32_t* units, const int32_t* mem,
                     const int32_t* inner, const int32_t* scc,
                     int32_t scc_len, int32_t scope_to_scc, int32_t use_rng,
                     uint64_t seed, int32_t trace, int32_t* q1_out,
                     int32_t* q1_len, int32_t* q2_out, int32_t* q2_len,
                     int64_t* stats_out) {
  return qi_check_scc_budget(n, succ_off, succ_tgt, roots, units, mem, inner,
                             scc, scc_len, scope_to_scc, use_rng, seed, trace,
                             0, q1_out, q1_len, q2_out, q2_len, stats_out);
}

// Greatest-fixpoint quorum over `nodes` given an availability vector
// (restored on return).  Exposed for the native CLI's per-SCC quorum scan
// (pipeline parity with cpp:645-672) and for bindings that need the bare
// fixpoint.  Returns the surviving-quorum length written to `out`.
int32_t qi_max_quorum(int32_t n, const int32_t* roots, const int32_t* units,
                      const int32_t* mem, const int32_t* inner,
                      const int32_t* nodes, int32_t nodes_len, uint8_t* avail,
                      int32_t* out) {
  Graph g{n, nullptr, nullptr, roots, units, mem, inner};
  std::vector<int32_t> vec(nodes, nodes + nodes_len);
  std::vector<int32_t> q = max_quorum(g, std::move(vec), avail);
  std::copy(q.begin(), q.end(), out);
  return static_cast<int32_t>(q.size());
}

// Benchmark unit of work: for each availability mask (row of `masks`,
// batch x n, row-major uint8), run the is-quorum greatest fixpoint and the
// complement disjointness probe — the same per-candidate check the TPU sweep
// performs.  Returns the number of rows where both probes found a quorum
// (consumed so the work cannot be optimized away).
int64_t qi_candidate_check(int32_t n, const int32_t* roots,
                           const int32_t* units, const int32_t* mem,
                           const int32_t* inner, const uint8_t* masks,
                           int32_t batch) {
  Graph g{n, nullptr, nullptr, roots, units, mem, inner};
  int64_t hits = 0;
  std::vector<uint8_t> avail(n);
  std::vector<uint8_t> in_q(n);
  std::vector<int32_t> work, removed;  // loop-invariant scratch: zero
  work.reserve(n);                     // allocations in the per-row loop
  removed.reserve(n);
  for (int32_t b = 0; b < batch; ++b) {
    const uint8_t* row = masks + static_cast<int64_t>(b) * n;
    std::copy(row, row + n, avail.begin());
    work.clear();
    for (int32_t v = 0; v < n; ++v) {
      if (avail[v]) work.push_back(v);
    }
    max_quorum_inplace(g, work, avail.data(), removed);
    const bool q_nonempty = !work.empty();
    std::fill(in_q.begin(), in_q.end(), 0);
    for (const int32_t v : work) in_q[v] = 1;
    work.clear();
    for (int32_t v = 0; v < n; ++v) {
      avail[v] = in_q[v] ? 0 : 1;
      if (avail[v]) work.push_back(v);
    }
    max_quorum_inplace(g, work, avail.data(), removed);
    if (q_nonempty && !work.empty()) ++hits;
  }
  return hits;
}

}  // extern "C"
