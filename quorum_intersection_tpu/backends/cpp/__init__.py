"""Native C++ CPU oracle backend — bindings, build, and marshalling.

The exponential search core lives in ``qi_oracle.cpp`` (same directory), a
fresh C++17 implementation of the reference's solver semantics
(`/root/reference/quorum_intersection.cpp:90-400`; see the pinned spec in
SURVEY.md §2.1 C4-C9).  It is compiled on demand with ``g++`` into a shared
library cached under ``_build/`` (keyed by a source hash, so edits trigger a
rebuild) and loaded through :mod:`ctypes` — no pybind11 dependency.

Marshalling: the :class:`~quorum_intersection_tpu.fbas.graph.TrustGraph` is
flattened once per call into plain int32 arrays — CSR successor lists plus a
"unit pool" for the recursive quorum-set trees (one unit = threshold, a span
of direct members, a span of inner units).  ``threshold is None`` (null qset,
quirk Q2) is encoded as root index -1.

The backend is verdict- AND statistics-identical to the pure-Python oracle
(:mod:`quorum_intersection_tpu.backends.python_oracle`) in deterministic
mode; ``tests/test_cpp_backend.py`` pins both.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from quorum_intersection_tpu.backends.base import SccCheckResult
from quorum_intersection_tpu.encode.circuit import Circuit
from quorum_intersection_tpu.fbas.graph import IndexedQSet, TrustGraph
from quorum_intersection_tpu.utils.env import qi_env
from quorum_intersection_tpu.utils.faults import fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import Span, get_run_record

log = get_logger("backends.cpp")

_SRC = Path(__file__).with_name("qi_oracle.cpp")
_BUILD_DIR = Path(__file__).with_name("_build")

# Hard ceiling on one g++ invocation (ISSUE 4 satellite): the oracle builds
# in ~2 s and the sanitized CLI in ~10 s on the slowest measured box, so ten
# minutes means a wedged compiler (NFS stall, fork bomb, OOM thrash) — fail
# loudly with whatever stderr the compiler produced instead of hanging the
# solve that triggered the on-demand build forever.
BUILD_TIMEOUT_S = 600

_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_u8p = ctypes.POINTER(ctypes.c_uint8)

_lib: Optional[ctypes.CDLL] = None


def _so_path() -> Path:
    digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    return _BUILD_DIR / f"qi_oracle-{digest}.so"


def _run_gxx(cmd: Sequence[str], what: str) -> "subprocess.CompletedProcess":
    """One g++ invocation under the build timeout.  A wedged compiler
    surfaces whatever stderr it produced — a silent timeout is
    undebuggable, and the degradation ladder's log line would otherwise
    just read "TimeoutExpired"."""
    try:
        return subprocess.run(
            cmd, capture_output=True, text=True, timeout=BUILD_TIMEOUT_S
        )
    except subprocess.TimeoutExpired as exc:
        stderr = exc.stderr or b""
        if isinstance(stderr, bytes):
            stderr = stderr.decode("utf-8", "replace")
        raise RuntimeError(
            f"{what} timed out after {BUILD_TIMEOUT_S}s "
            f"(`{' '.join(cmd)}`):\n{stderr.strip()}"
        ) from exc


def _compile(out: Path, sources: Sequence[Path], flags: Sequence[str],
             what: str, force: bool) -> Path:
    """Shared g++ driver: idempotent content-hashed artifact, tmp-file +
    atomic rename (concurrent builders use distinct tmp names)."""
    if out.exists() and not force:
        return out
    _BUILD_DIR.mkdir(exist_ok=True)
    tmp = out.with_name(out.name + f".tmp{os.getpid()}")
    cmd = ["g++", "-std=c++17", *flags, "-o", str(tmp), *map(str, sources)]
    log.info("building %s: %s", what, " ".join(cmd))
    fault_point("native.build")
    proc = _run_gxx(cmd, f"{what} build")
    if proc.returncode != 0:
        raise RuntimeError(f"{what} build failed (exit {proc.returncode}):\n{proc.stderr}")
    tmp.replace(out)
    return out


def build_library(force: bool = False) -> Path:
    """Compile ``qi_oracle.cpp`` → a content-hashed ``.so`` (idempotent)."""
    return _compile(
        _so_path(), [_SRC], ["-O3", "-fPIC", "-shared"], "native oracle", force
    )


_CLI_SRC = Path(__file__).with_name("qi_native.cpp")

# Instrumented build catalog (ISSUE 3): binary-name tag → g++ flags.  "asan"
# is the UB-hygiene check the reference never had (its own uninitialized-
# threshold read, SURVEY §2.3-Q2, would trip MSan); "tsan" exists for the
# threaded callers the racing auto router added — the native search itself
# is single-threaded, but `qi_check_scc_cancel` polls a cancel flag another
# thread flips, and TSAN is the tool that vets that access pattern once
# multi-threaded drivers reach the native layer.
_SANITIZER_FLAGS: Dict[str, List[str]] = {
    "asan": ["-O1", "-g", "-fsanitize=address,undefined",
             "-fno-sanitize-recover=all"],
    "tsan": ["-O1", "-g", "-fsanitize=thread"],
}


def sanitizer_mode() -> str:
    """The sanitizer the instrumented build uses: ``QI_SANITIZER`` ∈
    {asan, tsan, none} (registry: utils/env.py), default asan."""
    mode = qi_env("QI_SANITIZER").strip().lower() or "asan"
    if mode not in ("asan", "tsan", "none"):
        raise ValueError(
            f"QI_SANITIZER={mode!r} not in {{asan, tsan, none}}"
        )
    return mode


def _probe_sanitizer_runtime(mode: str) -> None:
    """Compile-and-link a 2-line probe under the requested sanitizer so a
    toolchain without the runtime fails HERE with a clear message — never by
    silently handing callers the unsanitized binary (ISSUE 3 satellite: the
    old behavior degraded to a plain build path on any failure, so a 'green'
    sanitizer run could mean 'nothing was instrumented')."""
    with tempfile.TemporaryDirectory(prefix="qi-sanprobe-") as tmp:
        src = Path(tmp) / "probe.cpp"
        src.write_text("int main() { return 0; }\n")
        cmd = ["g++", "-std=c++17", *_SANITIZER_FLAGS[mode],
               "-o", str(Path(tmp) / "probe"), str(src)]
        proc = _run_gxx(cmd, f"{mode} sanitizer probe")
    if proc.returncode != 0:
        raise RuntimeError(
            f"toolchain lacks the {mode} sanitizer runtime "
            f"(probe `{' '.join(cmd)}` failed):\n{proc.stderr.strip()}\n"
            f"Install the lib{mode} runtime or set QI_SANITIZER=none."
        )


def build_native_cli(
    force: bool = False, sanitize: Union[bool, str] = False
) -> Path:
    """Compile the standalone native CLI (``qi_native.cpp`` + the oracle) →
    a content-hashed binary, the framework's equivalent of the reference's
    single-binary deployment (`/root/reference/quorum_intersection.cpp`
    main, C21).  Idempotent; returns the binary path.

    ``sanitize`` selects an instrumented build (separate digest-keyed cache
    entry per sanitizer, ``qi_native-{asan,tsan}-<digest>``): ``True`` uses
    the mode ``QI_SANITIZER`` names (default asan), or pass ``"asan"`` /
    ``"tsan"`` explicitly.  ``QI_SANITIZER=none`` (or ``sanitize="none"``)
    REFUSES the instrumented build with a clear error instead of silently
    returning the plain binary — callers asked for instrumentation, and a
    passing run must mean the instrumentation actually ran.  A toolchain
    missing the sanitizer runtime fails the same way (probe first, so the
    error names the missing runtime, not a linker soup)."""
    digest = hashlib.sha256(_CLI_SRC.read_bytes() + _SRC.read_bytes()).hexdigest()[:16]
    if sanitize:
        mode = sanitizer_mode() if sanitize is True else str(sanitize).lower()
        if mode == "none":
            raise RuntimeError(
                "sanitized build requested but QI_SANITIZER=none — unset it "
                "(or pick asan/tsan) to build an instrumented binary"
            )
        if mode not in _SANITIZER_FLAGS:
            raise ValueError(
                f"unknown sanitizer {mode!r}; expected one of "
                f"{sorted(_SANITIZER_FLAGS)} or 'none'"
            )
        exe = _BUILD_DIR / f"qi_native-{mode}-{digest}"
        if not exe.exists() or force:
            _probe_sanitizer_runtime(mode)
        return _compile(
            exe, [_CLI_SRC, _SRC], _SANITIZER_FLAGS[mode],
            f"{mode}-sanitized native CLI", force,
        )
    exe = _BUILD_DIR / f"qi_native-{digest}"
    return _compile(exe, [_CLI_SRC, _SRC], ["-O2"], "native CLI", force)


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(str(build_library()))
    lib.qi_check_scc_cancel.restype = ctypes.c_int32
    lib.qi_check_scc_cancel.argtypes = [
        ctypes.c_int32,  # n
        _i32p, _i32p,  # succ_off, succ_tgt
        _i32p, _i32p, _i32p, _i32p,  # roots, units, mem, inner
        _i32p, ctypes.c_int32,  # scc, scc_len
        ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64,  # scope, use_rng, seed
        ctypes.c_int32,  # trace (per-call stderr narration)
        ctypes.c_int64,  # budget_calls (0 = unlimited; -2 return on overrun)
        _i32p,  # cancel_flag (NULL = uncancellable; -3 return on cancel)
        _i32p, _i32p, _i32p, _i32p,  # q1_out, q1_len, q2_out, q2_len
        _i64p,  # stats_out[3]
    ]
    lib.qi_top_tier.restype = ctypes.c_int64
    lib.qi_top_tier.argtypes = [
        ctypes.c_int32,  # n
        _i32p, _i32p,  # succ_off, succ_tgt
        _i32p, _i32p, _i32p, _i32p,  # roots, units, mem, inner
        _i32p, ctypes.c_int32,  # scc, scc_len
        ctypes.c_int64,  # budget_calls
        _u8p,  # union_out (n bytes)
        _i64p,  # stats_out[3]
    ]
    lib.qi_max_quorum.restype = ctypes.c_int32
    lib.qi_max_quorum.argtypes = [
        ctypes.c_int32,  # n
        _i32p, _i32p, _i32p, _i32p,  # roots, units, mem, inner
        _i32p, ctypes.c_int32,  # nodes, nodes_len
        _u8p, _i32p,  # avail (restored on return), out
    ]
    lib.qi_candidate_check.restype = ctypes.c_int64
    lib.qi_candidate_check.argtypes = [
        ctypes.c_int32,  # n
        _i32p, _i32p, _i32p, _i32p,  # roots, units, mem, inner
        _u8p, ctypes.c_int32,  # masks, batch
    ]
    _lib = lib
    return lib


class FlatGraph:
    """Int32 flattening of a :class:`TrustGraph` for the C ABI.

    Unit layout (5 ints per unit): ``threshold, member_begin, member_end,
    inner_begin, inner_end`` — spans into the ``mem`` (node-index) and
    ``inner`` (unit-index) pools.  A null qset flattens to root ``-1``;
    every stored threshold is Q3-normalized (degenerate ``<= 0`` —
    including a ``None``-threshold *inner* set — or unreachable
    ``> member count`` becomes the never-satisfiable sentinel
    ``m_count + 1``), matching
    :func:`~quorum_intersection_tpu.fbas.semantics.slice_satisfied` and
    keeping arbitrary-precision JSON thresholds exact in int32.
    """

    def __init__(self, graph: TrustGraph) -> None:
        units: List[Tuple[int, int, int, int, int]] = []
        mem: List[int] = []
        inner: List[int] = []

        from quorum_intersection_tpu.fbas.schema import MAX_QSET_DEPTH

        def add_unit(q: IndexedQSet, depth: int = 0) -> int:
            if depth > MAX_QSET_DEPTH:
                # Graphs from parse_fbas are pre-capped; this guards
                # programmatic construction like encode/circuit.py does.
                raise ValueError(
                    f"quorumSet nesting exceeds depth {MAX_QSET_DEPTH}"
                )
            uid = len(units)
            units.append((0, 0, 0, 0, 0))  # placeholder; children first
            mb = len(mem)
            mem.extend(q.members)
            me = len(mem)
            child_ids = [add_unit(iq, depth + 1) for iq in q.inner]
            ib = len(inner)
            inner.extend(child_ids)
            ie = len(inner)
            t = 0 if q.threshold is None else q.threshold
            # Q3 normalization, exactly as qi_native.cpp flatten_qset: a
            # degenerate (<= 0) or unreachable (> member count) threshold
            # becomes the never-satisfiable sentinel m_count + 1.  Beyond
            # matching fbas/semantics.py, this keeps arbitrary-precision
            # JSON thresholds EXACT in the int32 unit table — a raw store
            # raised OverflowError on out-of-int32 values (caught by
            # tools/fuzz_python.py; the schema deliberately accepts any
            # integer, and the verdict must not depend on its magnitude).
            m_count = (me - mb) + (ie - ib)
            if t <= 0 or t > m_count:
                t = m_count + 1
            units[uid] = (t, mb, me, ib, ie)
            return uid

        roots: List[int] = []
        for q in graph.qsets:
            roots.append(-1 if q.threshold is None else add_unit(q))

        succ_off = np.zeros(graph.n + 1, dtype=np.int32)
        for v, targets in enumerate(graph.succ):
            succ_off[v + 1] = succ_off[v] + len(targets)
        succ_tgt = np.fromiter(
            (w for targets in graph.succ for w in targets),
            dtype=np.int32,
            count=int(succ_off[-1]),
        )

        self.n = graph.n
        self.succ_off = np.ascontiguousarray(succ_off)
        self.succ_tgt = np.ascontiguousarray(succ_tgt)
        self.roots = np.asarray(roots, dtype=np.int32)
        self.units = np.asarray(
            [x for unit in units for x in unit] or [0], dtype=np.int32
        )
        self.mem = np.asarray(mem or [0], dtype=np.int32)
        self.inner = np.asarray(inner or [0], dtype=np.int32)

    def _ptr(self, arr: np.ndarray):
        return arr.ctypes.data_as(_i32p)


class CppOracleBackend:
    """Branch-and-bound disjointness search in native code (C++17 via ctypes)."""

    name = "cpp"
    needs_circuit = False  # searches on host set semantics, like the Python oracle

    def __init__(
        self,
        seed: Optional[int] = None,
        randomized: bool = False,
        budget_calls: Optional[int] = None,
        cancel=None,
    ) -> None:
        self._use_rng = bool(randomized or seed is not None)
        # randomized without an explicit seed means *actual* nondeterminism
        # (matching the python backend's random.Random(None) and the
        # reference's random_device-seeded engine, cpp:207).
        self._seed = (
            int.from_bytes(os.urandom(8), "little") if seed is None else int(seed)
        )
        # Optional B&B call budget: check_scc raises OracleBudgetExceeded
        # instead of running an unbounded exponential search (the auto
        # router's latency-aware oracle-first strategy).
        self._budget_calls = 0 if budget_calls is None else int(budget_calls)
        # Optional base.CancelToken: the native search polls its int32 flag
        # alongside the budget check and check_scc raises SearchCancelled —
        # the racing auto router stops this engine from another thread when
        # a concurrent engine reaches the verdict first.
        self._cancel = cancel

    def ensure_built(self) -> None:
        _load()

    def check_scc(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
    ) -> SccCheckResult:
        # One span per native entry (qi-trace): its span_id doubles as the
        # CALL ID, echoed back beside the B&B counters and in the result
        # stats, so a JSONL stream ties each native counter increment to
        # the exact call (and thread) that produced it.
        rec = get_run_record()
        with rec.span("native.call", scc=len(scc)) as call_span:
            return self._check_scc_traced(
                call_span, graph, circuit, scc, scope_to_scc
            )

    def _check_scc_traced(
        self,
        call_span: Span,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        scope_to_scc: bool,
    ) -> SccCheckResult:
        call_id = call_span.span_id
        # Injectable native-entry boundary (utils/faults.py): `error`
        # simulates a crashed call, `hang` a wedged one — the auto router's
        # watchdog/quarantine hardening is exercised exactly here.
        fault_point("native.call")
        lib = _load()
        flat = FlatGraph(graph)
        scc_arr = np.asarray(scc, dtype=np.int32)
        q1 = np.zeros(graph.n, dtype=np.int32)
        q2 = np.zeros(graph.n, dtype=np.int32)
        q1_len = ctypes.c_int32(0)
        q2_len = ctypes.c_int32(0)
        stats = np.zeros(3, dtype=np.int64)

        cancel_ptr = (
            _i32p() if self._cancel is None
            else self._cancel.flag.ctypes.data_as(_i32p)
        )
        t0 = time.perf_counter()
        intersects = lib.qi_check_scc_cancel(
            flat.n,
            flat._ptr(flat.succ_off),
            flat._ptr(flat.succ_tgt),
            flat._ptr(flat.roots),
            flat._ptr(flat.units),
            flat._ptr(flat.mem),
            flat._ptr(flat.inner),
            scc_arr.ctypes.data_as(_i32p),
            len(scc),
            int(scope_to_scc),
            int(self._use_rng),
            self._seed,
            int(log.isEnabledFor(logging.DEBUG)),  # -t routes here via set_trace
            self._budget_calls,
            cancel_ptr,
            q1.ctypes.data_as(_i32p),
            ctypes.byref(q1_len),
            q2.ctypes.data_as(_i32p),
            ctypes.byref(q2_len),
            stats.ctypes.data_as(_i64p),
        )
        seconds = time.perf_counter() - t0

        # Native-call accounting (ISSUE 2): every entry into the C++ search
        # core lands in the run record — call count, wall time, and the B&B
        # calls actually executed (also counted on budget/cancel exits,
        # where no SccCheckResult carries them).  The call id rides on the
        # span beside the same counters (ISSUE 6).
        rec = get_run_record()
        rec.add("native.check_scc_calls")
        rec.add("native.check_scc_seconds", round(seconds, 6))
        rec.add("native.bnb_calls", int(stats[0]))
        call_span.set(
            call_id=call_id, bnb_calls=int(stats[0]),
            seconds=round(seconds, 6),
        )

        if intersects == -2:
            from quorum_intersection_tpu.backends.base import OracleBudgetExceeded

            rec.add("oracle.budget_calls_consumed", self._budget_calls)
            raise OracleBudgetExceeded(
                f"native oracle exceeded {self._budget_calls} B&B calls "
                f"on |scc|={len(scc)} after {seconds:.2f}s"
            )
        if intersects == -3:
            from quorum_intersection_tpu.backends.base import SearchCancelled

            raise SearchCancelled(
                f"native oracle cancelled on |scc|={len(scc)} after "
                f"{seconds:.2f}s ({int(stats[0])} B&B calls)"
            )

        return SccCheckResult(
            intersects=bool(intersects),
            q1=q1[: q1_len.value].tolist() if not intersects else None,
            q2=q2[: q2_len.value].tolist() if not intersects else None,
            stats={
                "backend": self.name,
                "bnb_calls": int(stats[0]),
                "minimal_quorums": int(stats[1]),
                "fixpoint_calls": int(stats[2]),
                "seconds": seconds,
                # The span id of this exact native entry (qi-trace): joins
                # the result back to its native.call span and counters.
                "native_call_id": call_id,
                # qi-cert ledger: the native oracle's B&B node counts,
                # echoed beside the call id so the certificate's coverage
                # evidence joins back to the exact native.call span.
                "cert": {
                    "bnb_calls": int(stats[0]),
                    "minimal_quorums": int(stats[1]),
                    "fixpoint_calls": int(stats[2]),
                    "native_call_id": call_id,
                },
            },
        )


class NativeMaxQuorum:
    """Reusable native greatest-fixpoint evaluator over one graph.

    Call signature mirrors :func:`fbas.semantics.max_quorum`:
    ``nmq(candidates, avail) -> surviving quorum members``.  ``avail`` is a
    WRITABLE uint8 row the caller owns exclusively for the duration of the
    call: the native fixpoint narrows it in place and restores it before
    returning (qi_oracle.cpp), so it must not be read-only or shared with a
    concurrent reader.  Built once per graph — the flattening and library
    load amortize over many calls, which is what the frontier backend's
    flagged-state checks need (thousands of minimality fixpoints per safe
    hierarchical search).  ``candidates`` may be a pre-built int32 array to
    skip per-call conversion; :meth:`count` returns only the survivor count
    (no Python list materialization) for callers that truth-test.
    """

    def __init__(self, graph: TrustGraph) -> None:
        self._lib = _load()
        self._flat = FlatGraph(graph)
        self._out = np.zeros(graph.n, dtype=np.int32)

    def count(self, candidates, avail: np.ndarray) -> int:
        flat = self._flat
        arr = np.asarray(candidates, dtype=np.int32)
        return self._lib.qi_max_quorum(
            flat.n,
            flat._ptr(flat.roots),
            flat._ptr(flat.units),
            flat._ptr(flat.mem),
            flat._ptr(flat.inner),
            arr.ctypes.data_as(_i32p),
            len(arr),
            avail.ctypes.data_as(_u8p),
            self._out.ctypes.data_as(_i32p),
        )

    def __call__(self, candidates, avail: np.ndarray) -> List[int]:
        return self._out[: self.count(candidates, avail)].tolist()


def native_scc_scan(graph: TrustGraph, sccs: List[List[int]]) -> List[List[int]]:
    """Per-SCC max-quorum scan via ``qi_max_quorum`` — the native analog of
    the pipeline's quorum-bearing-SCC detection (cpp:645-672), used for big
    snapshots where N interpreted-Python fixpoints dominate the solve
    (VERDICT r1 §weak-7).  Returns one (possibly empty) quorum per SCC, in
    the same member order as the Python scan."""
    t0 = time.perf_counter()
    nmq = NativeMaxQuorum(graph)
    avail = np.zeros(graph.n, dtype=np.uint8)
    quorums: List[List[int]] = []
    for members in sccs:
        arr = np.asarray(members, dtype=np.int32)
        avail[arr] = 1
        quorums.append(nmq(arr, avail))
        avail[arr] = 0
    rec = get_run_record()
    rec.add("native.scan_fixpoints", len(sccs))
    rec.add("native.scan_seconds", round(time.perf_counter() - t0, 6))
    return quorums


def native_top_tier(
    graph: TrustGraph, scc: List[int], budget_calls: int = 0
) -> Tuple[Optional[List[int]], int]:
    """Union of all minimal quorums' members in the SCC via the native
    enumeration.  Returns ``(members, minimal_quorum_count)``; members is
    None when the call budget was exceeded (partial enumeration)."""
    lib = _load()
    flat = FlatGraph(graph)
    scc_arr = np.asarray(scc, dtype=np.int32)
    union = np.zeros(graph.n, dtype=np.uint8)
    stats = np.zeros(3, dtype=np.int64)
    count = lib.qi_top_tier(
        flat.n,
        flat._ptr(flat.succ_off),
        flat._ptr(flat.succ_tgt),
        flat._ptr(flat.roots),
        flat._ptr(flat.units),
        flat._ptr(flat.mem),
        flat._ptr(flat.inner),
        scc_arr.ctypes.data_as(_i32p),
        len(scc),
        int(budget_calls),
        union.ctypes.data_as(_u8p),
        stats.ctypes.data_as(_i64p),
    )
    if count == -2:
        return None, int(stats[1])
    return np.nonzero(union)[0].tolist(), int(count)


def native_candidate_check(graph: TrustGraph, masks: np.ndarray) -> Tuple[int, float]:
    """Run the per-candidate check (fixpoint + complement probe) over a batch
    of availability masks in native code.  Returns ``(hits, seconds)``."""
    lib = _load()
    flat = FlatGraph(graph)
    m = np.ascontiguousarray(masks.astype(np.uint8))
    batch = m.shape[0]
    t0 = time.perf_counter()
    hits = lib.qi_candidate_check(
        flat.n,
        flat._ptr(flat.roots),
        flat._ptr(flat.units),
        flat._ptr(flat.mem),
        flat._ptr(flat.inner),
        m.ctypes.data_as(_u8p),
        batch,
    )
    return int(hits), time.perf_counter() - t0


def native_candidate_rate(graph: TrustGraph, masks: np.ndarray) -> float:
    """Single-core candidates/sec baseline for ``bench.py``."""
    _, seconds = native_candidate_check(graph, masks)
    return masks.shape[0] / seconds if seconds > 0 else float("inf")
