// qi_native — standalone native CLI for the quorum-intersection framework.
//
// The reference ships as a single C++ binary (CLI C21, frontend C10-C12,
// analytics C14-C16, pipeline C17-C19 of SURVEY.md §2.1; see
// /root/reference/quorum_intersection.cpp:402-800).  This translation unit is
// the framework's native equivalent: a fresh C++17 implementation of the
// full stdin→stdout pipeline — hand-rolled JSON parser (no Boost), trust
// graph with explicit dangling policy (Q1), iterative Tarjan SCC with the
// sink-first numbering contract, per-SCC quorum scan, the branch-and-bound
// disjointness search (linked from qi_oracle.cpp), PageRank with the
// reference's pinned deviations (C15), and SCC-colored Graphviz (C14).
//
// Flag surface and exit-code contract match the reference CLI
// (quorum_intersection.cpp:744-800): `-h` usage/exit 0, bad flag
// "Invalid option!"+usage/exit 1, `-p` PageRank/exit 0, default mode prints
// true/false and exits 0 iff intersecting.  Superset flags mirror the Python
// CLI: --dangling-policy, --scc-select, --scope-scc, --compat, --seed,
// --randomized.
//
// Build (done on demand by backends/cpp/__init__.py:build_native_cli):
//   g++ -O2 -std=c++17 qi_native.cpp qi_oracle.cpp -o qi_native

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <limits>
#include <memory>
#include <random>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

// ---- solver core (qi_oracle.cpp) -----------------------------------------

extern "C" {
int32_t qi_check_scc(int32_t n, const int32_t* succ_off,
                     const int32_t* succ_tgt, const int32_t* roots,
                     const int32_t* units, const int32_t* mem,
                     const int32_t* inner, const int32_t* scc,
                     int32_t scc_len, int32_t scope_to_scc, int32_t use_rng,
                     uint64_t seed, int32_t trace, int32_t* q1_out,
                     int32_t* q1_len, int32_t* q2_out, int32_t* q2_len,
                     int64_t* stats_out);
int32_t qi_max_quorum(int32_t n, const int32_t* roots, const int32_t* units,
                      const int32_t* mem, const int32_t* inner,
                      const int32_t* nodes, int32_t nodes_len, uint8_t* avail,
                      int32_t* out);
}

namespace {

// ---- minimal JSON ---------------------------------------------------------
// Just enough for stellarbeat /nodes/raw snapshots: objects, arrays, strings
// (with escapes incl. \uXXXX → UTF-8), numbers, true/false/null.

struct JValue;
using JPtr = std::unique_ptr<JValue>;

struct JValue {
  enum Kind { Null, Bool, Num, Str, Arr, Obj } kind = Null;
  bool b = false;
  bool is_int = false;  // number token had no fraction/exponent
  double num = 0;
  std::string str;
  std::vector<JPtr> arr;
  std::vector<std::pair<std::string, JPtr>> obj;  // order-preserving

  const JValue* get(const std::string& key) const {
    // Last occurrence wins on duplicate keys, like Python's json.loads.
    for (auto it = obj.rbegin(); it != obj.rend(); ++it) {
      if (it->first == key) return it->second.get();
    }
    return nullptr;
  }
};

// Hostile-input hardening: caps keep recursive descent (JSON values, quorum
// sets) inside the native stack instead of overflowing on crafted input.
// kMaxQSetDepth matches schema.py MAX_QSET_DEPTH so both CLIs reject the
// same snapshots with the same clean diagnostic.
constexpr int kMaxJsonDepth = 512;
constexpr int kMaxQSetDepth = 128;

struct JsonParser {
  const char* p;
  const char* end;
  int depth = 0;
  explicit JsonParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  [[noreturn]] void fail(const std::string& why) {
    throw std::runtime_error("JSON parse error: " + why);
  }
  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }
  char peek() {
    skip_ws();
    if (p >= end) fail("unexpected end of input");
    return *p;
  }
  void expect(char c) {
    if (peek() != c) fail(std::string("expected '") + c + "'");
    ++p;
  }

  JPtr parse() {
    JPtr v = parse_value();
    skip_ws();
    if (p != end) fail("trailing data after top-level value");
    return v;
  }

  JPtr parse_value() {
    switch (peek()) {
      case '{': return parse_object();
      case '[': return parse_array();
      case '"': {
        auto v = std::make_unique<JValue>();
        v->kind = JValue::Str;
        v->str = parse_string();
        return v;
      }
      case 't': return parse_lit("true", true);
      case 'f': return parse_lit("false", false);
      case 'n': {
        check_lit("null");
        return std::make_unique<JValue>();
      }
      default: return parse_number();
    }
  }

  void check_lit(const char* lit) {
    const size_t len = std::strlen(lit);
    if (static_cast<size_t>(end - p) < len || std::strncmp(p, lit, len) != 0) {
      fail(std::string("bad literal, expected ") + lit);
    }
    p += len;
  }
  JPtr parse_lit(const char* lit, bool val) {
    check_lit(lit);
    auto v = std::make_unique<JValue>();
    v->kind = JValue::Bool;
    v->b = val;
    return v;
  }

  JPtr parse_number() {
    // Strict JSON grammar: -? (0 | [1-9][0-9]*) frac? exp? — so malformed
    // inputs the Python CLI rejects (json.loads) are rejected here too.
    const char* start = p;
    if (p < end && *p == '-') ++p;
    if (p >= end || *p < '0' || *p > '9') fail("bad number");
    if (*p == '0') {
      ++p;
    } else {
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    bool integral = true;
    if (p < end && *p == '.') {
      integral = false;
      ++p;
      if (p >= end || *p < '0' || *p > '9') fail("bad number fraction");
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      integral = false;
      ++p;
      if (p < end && (*p == '-' || *p == '+')) ++p;
      if (p >= end || *p < '0' || *p > '9') fail("bad number exponent");
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    auto v = std::make_unique<JValue>();
    v->kind = JValue::Num;
    v->is_int = integral;
    v->num = std::strtod(std::string(start, p).c_str(), nullptr);
    return v;
  }

  unsigned parse_hex4() {
    if (end - p < 4) fail("bad \\u escape");
    unsigned code = 0;
    for (int i = 0; i < 4; ++i) {
      const char h = *p++;
      code <<= 4;
      if (h >= '0' && h <= '9') code |= h - '0';
      else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
      else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
      else fail("bad \\u escape");
    }
    return code;
  }

  static void encode_utf8(std::string& out, unsigned code) {
    if (code < 0x80) {
      out.push_back(static_cast<char>(code));
    } else if (code < 0x800) {
      out.push_back(static_cast<char>(0xC0 | (code >> 6)));
      out.push_back(static_cast<char>(0x80 | (code & 0x3F)));
    } else if (code < 0x10000) {
      out.push_back(static_cast<char>(0xE0 | (code >> 12)));
      out.push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (code & 0x3F)));
    } else {
      out.push_back(static_cast<char>(0xF0 | (code >> 18)));
      out.push_back(static_cast<char>(0x80 | ((code >> 12) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (code & 0x3F)));
    }
  }

  std::string parse_string() {
    expect('"');
    std::string out;
    while (p < end && *p != '"') {
      char c = *p++;
      if (static_cast<unsigned char>(c) < 0x20) {
        fail("raw control character in string");  // json.loads parity
      }
      if (c != '\\') {
        out.push_back(c);
        continue;
      }
      if (p >= end) fail("dangling escape");
      char e = *p++;
      switch (e) {
        case '"': out.push_back('"'); break;
        case '\\': out.push_back('\\'); break;
        case '/': out.push_back('/'); break;
        case 'b': out.push_back('\b'); break;
        case 'f': out.push_back('\f'); break;
        case 'n': out.push_back('\n'); break;
        case 'r': out.push_back('\r'); break;
        case 't': out.push_back('\t'); break;
        case 'u': {
          // Surrogate pairs combine into one code point (matching Python's
          // json.loads); a LONE surrogate folds to U+FFFD — Python would
          // keep the unpaired surrogate and then crash encoding it to
          // stdout, so there is no valid byte-identical behavior to mirror.
          unsigned code = parse_hex4();
          if (code >= 0xDC00 && code <= 0xDFFF) {
            code = 0xFFFD;  // lone low surrogate
          } else if (code >= 0xD800 && code <= 0xDBFF) {
            const unsigned hi = code;
            code = 0xFFFD;  // unless a low surrogate follows:
            if (end - p >= 6 && p[0] == '\\' && p[1] == 'u') {
              const char* save = p;
              p += 2;
              const unsigned lo = parse_hex4();
              if (lo >= 0xDC00 && lo <= 0xDFFF) {
                code = 0x10000 + ((hi - 0xD800) << 10) + (lo - 0xDC00);
              } else {
                p = save;  // not a pair: re-process the escape next round
              }
            }
          }
          encode_utf8(out, code);
          break;
        }
        default: fail("unknown escape");
      }
    }
    if (p >= end) fail("unterminated string");
    ++p;  // closing quote
    return out;
  }

  JPtr parse_array() {
    if (++depth > kMaxJsonDepth) fail("nesting too deep");
    expect('[');
    auto v = std::make_unique<JValue>();
    v->kind = JValue::Arr;
    if (peek() == ']') {
      ++p;
      --depth;
      return v;
    }
    for (;;) {
      v->arr.push_back(parse_value());
      char c = peek();
      if (c == ',') {
        ++p;
        continue;
      }
      if (c == ']') {
        ++p;
        --depth;
        return v;
      }
      fail("expected ',' or ']'");
    }
  }

  JPtr parse_object() {
    if (++depth > kMaxJsonDepth) fail("nesting too deep");
    expect('{');
    auto v = std::make_unique<JValue>();
    v->kind = JValue::Obj;
    if (peek() == '}') {
      ++p;
      --depth;
      return v;
    }
    for (;;) {
      std::string key = parse_string();
      expect(':');
      v->obj.emplace_back(std::move(key), parse_value());
      char c = peek();
      if (c == ',') {
        ++p;
        continue;
      }
      if (c == '}') {
        ++p;
        --depth;
        return v;
      }
      fail("expected ',' or '}'");
    }
  }
};

// ---- schema (C10-C11) -----------------------------------------------------

struct QSet {
  bool null = true;  // null/empty quorumSet ⇒ never satisfiable (Q2)
  int64_t threshold = 0;
  std::vector<std::string> validators;
  std::vector<QSet> inner;
};

struct Node {
  std::string public_key;
  std::string name;
  QSet qset;
};

// Same validation rules as fbas/schema.py:_parse_qset — the native binary
// must reject exactly what the Python CLI rejects, or verdicts diverge on
// malformed snapshots.
QSet parse_qset(const JValue* v, const std::string& where, int depth = 0) {
  if (depth > kMaxQSetDepth) {
    throw std::runtime_error(where + ": quorumSet nesting exceeds depth " +
                             std::to_string(kMaxQSetDepth));
  }
  QSet q;
  if (v == nullptr || v->kind == JValue::Null) return q;
  if (v->kind != JValue::Obj) {
    throw std::runtime_error(where + ": quorumSet must be an object or null");
  }
  if (v->obj.empty()) return q;  // {} ≡ null (Q2)
  q.null = false;
  const JValue* t = v->get("threshold");
  if (t == nullptr) {
    throw std::runtime_error(where + ": non-empty quorumSet missing 'threshold'");
  }
  if (t->kind == JValue::Num && t->is_int) {
    q.threshold = static_cast<int64_t>(t->num);
  } else if (t->kind == JValue::Str) {
    // boost::property_tree compatibility: accept numeric strings
    // (schema.py accepts int("...") — full-string, optional sign).
    const std::string& s = t->str;
    size_t pos = 0;
    try {
      q.threshold = std::stoll(s, &pos);
    } catch (const std::out_of_range&) {
      // Python's arbitrary-precision int() accepts magnitudes beyond int64;
      // any such threshold is unsatisfiable either way (non-positive hits
      // the Q3 sentinel, huge positive exceeds every member count), so
      // clamp to the matching int64 extreme instead of rejecting the
      // snapshot — keeps stdout parity with the Python CLI.
      size_t i = 0;
      while (i < s.size() && std::isspace(static_cast<unsigned char>(s[i]))) ++i;
      const bool neg = i < s.size() && s[i] == '-';
      if (i < s.size() && (s[i] == '-' || s[i] == '+')) ++i;
      const size_t digits_start = i;
      while (i < s.size() && s[i] >= '0' && s[i] <= '9') ++i;
      pos = (i > digits_start) ? i : std::string::npos;
      q.threshold = neg ? std::numeric_limits<int64_t>::min()
                        : std::numeric_limits<int64_t>::max();
    } catch (...) {
      pos = std::string::npos;
    }
    // Python's int() also tolerates surrounding whitespace.
    while (pos != std::string::npos && pos < s.size() &&
           std::isspace(static_cast<unsigned char>(s[pos]))) {
      ++pos;
    }
    if (pos != s.size()) {
      throw std::runtime_error(where + ": threshold '" + s + "' is not an integer");
    }
  } else {
    throw std::runtime_error(where + ": threshold must be an integer");
  }
  if (const JValue* vals = v->get("validators"); vals != nullptr) {
    if (vals->kind == JValue::Null) {
      // absent/null → empty (schema.py validators=None path)
    } else if (vals->kind != JValue::Arr) {
      throw std::runtime_error(where + ": validators must be an array");
    } else {
      for (const auto& s : vals->arr) {
        if (s->kind != JValue::Str) {
          throw std::runtime_error(where + ": validator entries must be strings");
        }
        q.validators.push_back(s->str);
      }
    }
  }
  if (const JValue* in = v->get("innerQuorumSets"); in != nullptr) {
    if (in->kind == JValue::Null) {
      // absent/null → empty
    } else if (in->kind != JValue::Arr) {
      throw std::runtime_error(where + ": innerQuorumSets must be an array");
    } else {
      for (size_t i = 0; i < in->arr.size(); ++i) {
        q.inner.push_back(parse_qset(
            in->arr[i].get(),
            where + ".innerQuorumSets[" + std::to_string(i) + "]", depth + 1));
      }
    }
  }
  return q;
}

std::vector<Node> parse_fbas(const std::string& text) {
  JsonParser parser(text);
  JPtr root = parser.parse();
  if (root->kind != JValue::Arr) {
    throw std::runtime_error("top-level JSON must be an array of nodes");
  }
  std::vector<Node> nodes;
  nodes.reserve(root->arr.size());
  std::unordered_map<std::string, size_t> seen;  // duplicate publicKey guard
  for (size_t i = 0; i < root->arr.size(); ++i) {
    const JValue* nv = root->arr[i].get();
    if (nv->kind != JValue::Obj) {
      throw std::runtime_error("node " + std::to_string(i) + " is not an object");
    }
    Node node;
    const JValue* pk = nv->get("publicKey");
    if (pk == nullptr || pk->kind != JValue::Str) {
      throw std::runtime_error("node " + std::to_string(i) + " missing publicKey");
    }
    node.public_key = pk->str;
    if (!seen.emplace(node.public_key, i).second) {
      // schema.py Fbas.__post_init__: silently aliased vertices are a
      // foot-gun; reject like the Python CLI does.
      throw std::runtime_error("duplicate publicKey: '" + node.public_key + "'");
    }
    if (const JValue* nm = nv->get("name"); nm != nullptr && nm->kind == JValue::Str) {
      node.name = nm->str;
    }
    // quorumSet required, like the reference's get_child (cpp:430)
    bool has_qs = false;
    for (const auto& kv : nv->obj) {
      if (kv.first == "quorumSet") has_qs = true;
    }
    if (!has_qs) {
      throw std::runtime_error("node " + std::to_string(i) + " missing quorumSet");
    }
    node.qset = parse_qset(nv->get("quorumSet"), node.public_key);
    nodes.push_back(std::move(node));
  }
  return nodes;
}

// ---- graph build + flattened solver tables (C12 + oracle marshalling) -----

struct FlatGraph {
  int32_t n = 0;
  std::vector<int32_t> succ_off, succ_tgt;   // CSR with multiplicity (Q7)
  std::vector<int32_t> roots;                // -1 ⇒ null qset (Q2)
  std::vector<int32_t> units;                // 5 ints/unit
  std::vector<int32_t> mem, inner;
  std::vector<std::string> ids, names;
  int64_t dangling = 0;

  const std::string& label(int32_t v) const {
    return names[v].empty() ? ids[v] : names[v];
  }
};

int32_t flatten_qset(const QSet& q, FlatGraph& g,
                     const std::unordered_map<std::string, int32_t>& index,
                     bool alias0, std::vector<int32_t>& out_edges,
                     int depth = 0) {
  // Parsed qsets are already capped at kMaxQSetDepth; this guards
  // programmatic construction the same way encode/circuit.py does.
  if (depth > kMaxQSetDepth) {
    throw std::runtime_error("quorumSet nesting exceeds depth " +
                             std::to_string(kMaxQSetDepth));
  }
  // Root-level null/{} (Q2): the caller stores -1 and the solver skips
  // the node's slice entirely.  An INNER null must NOT get the sentinel —
  // it still occupies a voting slot that can never be satisfied
  // (fbas/semantics.py counts it in the fail budget; the Python-side
  // FlatGraph Q3-clamps it to the never-satisfiable sentinel m_count+1,
  // exactly like the normalization below).  Returning -1 at inner depths leaked
  // the root sentinel into the inner pool, where slice_unit dereferenced
  // units[-1] — a heap-buffer-overflow found by tools/fuzz_native.py on
  // `"innerQuorumSets": [{}]` inputs.  Falling through is sufficient: a
  // null qset has threshold 0 and no members, so the general path's Q3
  // normalization below emits the never-satisfiable unit {1,0,0,0,0}.
  if (q.null && depth == 0) return -1;
  const int32_t unit = static_cast<int32_t>(g.units.size() / 5);
  g.units.insert(g.units.end(), {0, 0, 0, 0, 0});  // placeholder
  std::vector<int32_t> members;
  for (const std::string& key : q.validators) {
    auto it = index.find(key);
    int32_t v;
    if (it == index.end()) {
      ++g.dangling;
      if (!alias0) continue;  // strict: never-available ≡ dropped (Q1)
      v = 0;                  // reference aliasing (cpp:456)
    } else {
      v = it->second;
    }
    members.push_back(v);
    out_edges.push_back(v);
  }
  std::vector<int32_t> inner_units;
  for (const QSet& iq : q.inner) {
    inner_units.push_back(flatten_qset(iq, g, index, alias0, out_edges, depth + 1));
  }
  const int32_t mb = static_cast<int32_t>(g.mem.size());
  g.mem.insert(g.mem.end(), members.begin(), members.end());
  const int32_t me = static_cast<int32_t>(g.mem.size());
  const int32_t ib = static_cast<int32_t>(g.inner.size());
  g.inner.insert(g.inner.end(), inner_units.begin(), inner_units.end());
  const int32_t ie = static_cast<int32_t>(g.inner.size());
  int32_t* U = g.units.data() + 5 * unit;
  // Q3 normalization (fbas/semantics.py contract): threshold <= 0 ⇒ never
  // satisfiable (members + inners + 1 can never be reached).  Thresholds
  // above the member count are equally unsatisfiable — clamping them to the
  // same sentinel also keeps huge int64 values exact in the int32 unit
  // table (a raw cast would truncate and could flip the verdict).
  const int64_t m_count = (me - mb) + (ie - ib);
  const int64_t t = q.threshold;
  U[0] = static_cast<int32_t>((t <= 0 || t > m_count) ? m_count + 1 : t);
  U[1] = mb;
  U[2] = me;
  U[3] = ib;
  U[4] = ie;
  return unit;
}

FlatGraph build_graph(const std::vector<Node>& nodes, bool alias0) {
  FlatGraph g;
  g.n = static_cast<int32_t>(nodes.size());
  std::unordered_map<std::string, int32_t> index;
  for (int32_t i = 0; i < g.n; ++i) {
    index.emplace(nodes[i].public_key, i);
    g.ids.push_back(nodes[i].public_key);
    g.names.push_back(nodes[i].name);
  }
  std::vector<std::vector<int32_t>> succ(g.n);
  g.roots.resize(g.n);
  for (int32_t i = 0; i < g.n; ++i) {
    g.roots[i] = flatten_qset(nodes[i].qset, g, index, alias0, succ[i]);
  }
  g.succ_off.push_back(0);
  for (int32_t i = 0; i < g.n; ++i) {
    g.succ_tgt.insert(g.succ_tgt.end(), succ[i].begin(), succ[i].end());
    g.succ_off.push_back(static_cast<int32_t>(g.succ_tgt.size()));
  }
  return g;
}

// ---- Tarjan SCC (sink-first numbering, matching fbas/graph.py) ------------

std::vector<std::vector<int32_t>> tarjan_sccs(const FlatGraph& g) {
  const int32_t n = g.n;
  std::vector<int32_t> comp(n, -1), low(n, 0), disc(n, 0);
  std::vector<uint8_t> on_stack(n, 0);
  std::vector<int32_t> stack;
  int32_t timer = 0, count = 0;
  std::vector<std::pair<int32_t, int32_t>> work;  // (vertex, edge cursor)

  for (int32_t root = 0; root < n; ++root) {
    if (disc[root]) continue;
    work.emplace_back(root, g.succ_off[root]);
    while (!work.empty()) {
      auto& [v, cursor] = work.back();
      if (cursor == g.succ_off[v]) {
        disc[v] = low[v] = ++timer;
        stack.push_back(v);
        on_stack[v] = 1;
      }
      bool advanced = false;
      while (cursor < g.succ_off[v + 1]) {
        const int32_t w = g.succ_tgt[cursor++];
        if (!disc[w]) {
          work.emplace_back(w, g.succ_off[w]);
          advanced = true;
          break;
        }
        if (on_stack[w]) low[v] = std::min(low[v], disc[w]);
      }
      if (advanced) continue;
      const int32_t done = v;
      work.pop_back();
      if (low[done] == disc[done]) {
        for (;;) {
          const int32_t w = stack.back();
          stack.pop_back();
          on_stack[w] = 0;
          comp[w] = count;
          if (w == done) break;
        }
        ++count;
      }
      if (!work.empty()) {
        low[work.back().first] = std::min(low[work.back().first], low[done]);
      }
    }
  }
  std::vector<std::vector<int32_t>> sccs(count);
  for (int32_t v = 0; v < n; ++v) sccs[comp[v]].push_back(v);
  return sccs;
}

// ---- verbose narration (pipeline parity, cpp:475-490 print shape) ---------

void print_quorum(const FlatGraph& g, const std::vector<int32_t>& quorum) {
  for (const int32_t v : quorum) {
    std::string names;
    const int32_t root = g.roots[v];
    std::string threshold = "null";
    if (root >= 0) {
      const int32_t* U = g.units.data() + 5 * root;
      threshold = std::to_string(U[0]);
      for (int32_t i = U[1]; i < U[2]; ++i) {
        names += g.ids[g.mem[i]];
        names += ' ';
      }
    }
    std::cout << g.names[v] << ' ' << g.ids[v] << "\n( quorumslice: threshold = "
              << threshold << ' ' << names << ") \n\n";
  }
  std::cout << "\n";
}

// ---- PageRank (C15 pinned semantics) + printer (C16) ----------------------

void page_rank(const FlatGraph& g, double m, double convergence,
               uint64_t max_iterations) {
  const int32_t n = g.n;
  if (n == 0) {
    std::cout << "PageRank:\n";
    return;
  }
  std::vector<double> rank(n, 0.0);
  rank[0] = 1.0;  // all mass on vertex 0 (cpp:543)
  std::vector<int32_t> outdeg(n);
  for (int32_t v = 0; v < n; ++v) outdeg[v] = g.succ_off[v + 1] - g.succ_off[v];
  for (uint64_t it = 0; it < max_iterations; ++it) {
    std::vector<double> next(n, m / n);  // base mass every iteration (cpp:555-557)
    for (int32_t v = 0; v < n; ++v) {
      if (outdeg[v] == 0) continue;  // dangling vertices leak their mass
      const double send = (1.0 - m) / outdeg[v] * rank[v];
      for (int32_t e = g.succ_off[v]; e < g.succ_off[v + 1]; ++e) {
        next[g.succ_tgt[e]] += send;  // multiplicity counts (Q7)
      }
    }
    double diff = 0.0, sum = 0.0;
    for (int32_t v = 0; v < n; ++v) {
      diff += std::abs(next[v] - rank[v]);  // un-normalized L1 (cpp:573-575)
      sum += next[v];
    }
    for (int32_t v = 0; v < n; ++v) rank[v] = next[v] / sum;
    if (diff <= convergence) break;
  }
  // sort desc by rank, ties asc by label (cpp:585-613)
  std::vector<int32_t> order(n);
  for (int32_t v = 0; v < n; ++v) order[v] = v;
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    if (rank[a] != rank[b]) return rank[a] > rank[b];
    return g.label(a) < g.label(b);
  });
  std::cout << "PageRank:\n";
  char buf[64];
  for (const int32_t v : order) {
    std::snprintf(buf, sizeof(buf), "%g", rank[v]);
    std::cout << g.label(v) << ": " << buf << "\n";
  }
}

// ---- Graphviz (C14: fill color (0xFFFFFF / sccCount) * sccIndex) ----------

void graphviz(const FlatGraph& g, const std::vector<std::vector<int32_t>>& sccs) {
  std::vector<int32_t> comp(g.n);
  for (size_t s = 0; s < sccs.size(); ++s) {
    for (const int32_t v : sccs[s]) comp[v] = static_cast<int32_t>(s);
  }
  // Same print shape as analytics/graphviz.py (which mirrors Boost
  // write_graphviz + the reference's NodeWriter, cpp:492-530).
  const int64_t step = sccs.empty() ? 0 : 0xFFFFFF / static_cast<int64_t>(sccs.size());
  std::cout << "digraph G {\n";
  char color[16];
  for (int32_t v = 0; v < g.n; ++v) {
    std::snprintf(color, sizeof(color), "#%06llx",
                  static_cast<unsigned long long>(step * comp[v]) & 0xFFFFFF);
    std::string label;  // dot-escape like graphviz.py:_escape
    for (const char c : g.label(v)) {
      if (c == '\\' || c == '"') label.push_back('\\');
      label.push_back(c);
    }
    std::cout << v << "[style=filled color=\"" << color << "\" label=\""
              << label << "\" fontcolor=\"white\"];\n";
  }
  for (int32_t v = 0; v < g.n; ++v) {
    for (int32_t e = g.succ_off[v]; e < g.succ_off[v + 1]; ++e) {
      std::cout << v << "->" << g.succ_tgt[e] << " ;\n";
    }
  }
  std::cout << "}\n";
}

// ---- CLI ------------------------------------------------------------------

void usage(std::ostream& os) {
  os << "usage: qi_native [options] < nodes.json\n"
        "Decide the quorum-intersection property of a Stellar FBAS\n"
        "(stellarbeat /nodes/raw JSON on stdin).\n\n"
        "  -h, --help             produce help message\n"
        "  -v, --verbose          print info about the analyzed configuration\n"
        "  -g, --graph            print graphviz representation\n"
        "  -t, --trace            trace-level search narration on stderr\n"
        "  -p, --pagerank         compute PageRank instead\n"
        "  -i, --max_iterations N PageRank iteration cap (default 100000)\n"
        "  -m, --dangling_factor F  PageRank dangling factor (default 0.0001)\n"
        "  -c, --convergence F    PageRank convergence (default 0.0001)\n"
        "      --dangling-policy {strict|alias0}   unknown validator refs\n"
        "      --scc-select {quorum-bearing|front} which SCC to search\n"
        "      --scope-scc        scope availability to the searched SCC\n"
        "      --compat           reference-bug-compatible: alias0 + front\n"
        "      --seed N           randomized branching tie-break seed\n"
        "      --randomized       randomized tie-break (random seed)\n";
}

struct Options {
  bool verbose = false, graph = false, pagerank = false, scope_scc = false;
  bool trace = false;
  bool alias0 = false, front = false, randomized = false;
  uint64_t max_iterations = 100000, seed = 0;
  bool has_seed = false;
  double dangling_factor = 0.0001, convergence = 0.0001;
};

int invalid_option() {
  std::cout << "Invalid option!\n";
  usage(std::cout);
  return 1;
}

}  // namespace

int main(int argc, char** argv) {
  Options opt;
  bool flag_error = false;
  for (int i = 1; i < argc; ++i) {
    const std::string a = argv[i];
    auto next = [&](const char* what) -> const char* {
      if (i + 1 >= argc) {
        // Same surface as argparse's missing-value error (stdout, usage,
        // exit 1 — _RefCompatParser contract).
        (void)what;
        std::exit(invalid_option());
      }
      return argv[++i];
    };
    // Strict numeric flag values: garbage is a usage error (exit-code
    // parity with argparse's type=int/float rejection), not a silent 0.
    auto next_u64 = [&](const char* what) -> uint64_t {
      const char* s = next(what);
      char* endp = nullptr;
      const uint64_t v = std::strtoull(s, &endp, 10);
      if (endp == s || *endp != '\0') flag_error = true;
      return v;
    };
    auto next_f64 = [&](const char* what) -> double {
      const char* s = next(what);
      char* endp = nullptr;
      const double v = std::strtod(s, &endp);
      if (endp == s || *endp != '\0') flag_error = true;
      return v;
    };
    if (a == "-h" || a == "--help") {
      usage(std::cout);
      return 0;
    } else if (a == "-v" || a == "--verbose") {
      opt.verbose = true;
    } else if (a == "-g" || a == "--graph") {
      opt.graph = true;
    } else if (a == "-t" || a == "--trace") {
      opt.trace = true;
    } else if (a == "-p" || a == "--pagerank") {
      opt.pagerank = true;
    } else if (a == "-i" || a == "--max_iterations") {
      opt.max_iterations = next_u64("max_iterations");
    } else if (a == "-m" || a == "--dangling_factor") {
      opt.dangling_factor = next_f64("dangling_factor");
    } else if (a == "-c" || a == "--convergence") {
      opt.convergence = next_f64("convergence");
    } else if (a == "--dangling-policy") {
      const std::string v = next("dangling-policy");
      if (v == "alias0") opt.alias0 = true;
      else if (v == "strict") opt.alias0 = false;
      else return invalid_option();
    } else if (a == "--scc-select") {
      const std::string v = next("scc-select");
      if (v == "front") opt.front = true;
      else if (v == "quorum-bearing") opt.front = false;
      else return invalid_option();
    } else if (a == "--scope-scc") {
      opt.scope_scc = true;
    } else if (a == "--compat") {
      opt.alias0 = true;
      opt.front = true;
    } else if (a == "--seed") {
      opt.seed = next_u64("seed");
      opt.has_seed = true;
      opt.randomized = true;
    } else if (a == "--randomized") {
      opt.randomized = true;
    } else {
      return invalid_option();
    }
    if (flag_error) return invalid_option();
  }

  std::ostringstream ss;
  ss << std::cin.rdbuf();
  FlatGraph g;
  try {
    g = build_graph(parse_fbas(ss.str()), opt.alias0);
  } catch (const std::exception& e) {
    std::cerr << "invalid FBAS configuration: " << e.what() << "\n";
    return 1;
  }

  if (opt.pagerank) {
    page_rank(g, opt.dangling_factor, opt.convergence, opt.max_iterations);
    return 0;  // PageRank mode always exits 0 (cpp:787)
  }

  const std::vector<std::vector<int32_t>> sccs = tarjan_sccs(g);
  if (opt.graph) graphviz(g, sccs);
  if (opt.verbose) {
    std::cout << "total number of strongly connected components: " << sccs.size()
              << "\n";
  }

  // Per-SCC quorum scan (cpp:645-672).
  if (opt.trace) {
    std::fprintf(stderr, "trace: %zu strongly connected components; scanning for quorums\n",
                 sccs.size());
  }
  std::vector<int32_t> quorum_sccs;
  std::vector<uint8_t> avail(g.n, 0);
  std::vector<int32_t> qbuf(g.n);
  for (size_t s = 0; s < sccs.size(); ++s) {
    for (const int32_t v : sccs[s]) avail[v] = 1;
    const int32_t qlen =
        qi_max_quorum(g.n, g.roots.data(), g.units.data(), g.mem.data(),
                      g.inner.data(), sccs[s].data(),
                      static_cast<int32_t>(sccs[s].size()), avail.data(),
                      qbuf.data());
    for (const int32_t v : sccs[s]) avail[v] = 0;
    if (qlen > 0) {
      quorum_sccs.push_back(static_cast<int32_t>(s));
      if (opt.trace) {
        std::fprintf(stderr, "trace: scc %zu (size %zu) contains a quorum (size %d)\n",
                     s, sccs[s].size(), qlen);
      }
      if (opt.verbose) {
        std::cout << "found quorum inside of a strongly connected component:\n";
        print_quorum(g, std::vector<int32_t>(qbuf.begin(), qbuf.begin() + qlen));
      }
    }
  }

  static const std::vector<int32_t> kEmpty;
  const std::vector<int32_t>& main_scc =
      (opt.front || quorum_sccs.empty())
          ? (sccs.empty() ? kEmpty : sccs.front())
          : sccs[quorum_sccs.front()];
  if (opt.verbose) {
    std::cout << "number of strongly connected components containing some quorum: "
              << quorum_sccs.size() << "\n";
    std::cout << "size of the main strongly connected component: "
              << main_scc.size() << "\n";
    std::cout << "main strongly connected component (all minimal quorums are "
                 "included in it; small size means small resilience of the "
                 "network):\n";
    print_quorum(g, main_scc);
  }

  bool intersects;
  std::vector<int32_t> q1, q2;
  if (quorum_sccs.size() != 1) {
    // Guard (cpp:681-688).
    intersects = false;
    if (opt.verbose) {
      std::cout << "network's configuration is broken - more than one strongly "
                   "connected component contains a quorum - "
                << quorum_sccs.size() << "\n";
    }
  } else {
    std::vector<int32_t> q1b(g.n), q2b(g.n);
    int32_t q1l = 0, q2l = 0;
    int64_t stats[3] = {0, 0, 0};
    const int32_t ok = qi_check_scc(
        g.n, g.succ_off.data(), g.succ_tgt.data(), g.roots.data(),
        g.units.data(), g.mem.data(), g.inner.data(), main_scc.data(),
        static_cast<int32_t>(main_scc.size()), opt.scope_scc ? 1 : 0,
        opt.randomized ? 1 : 0,
        opt.has_seed ? opt.seed : std::random_device{}(), opt.trace ? 1 : 0,
        q1b.data(), &q1l, q2b.data(), &q2l, stats);
    intersects = ok == 1;
    q1.assign(q1b.begin(), q1b.begin() + q1l);
    q2.assign(q2b.begin(), q2b.begin() + q2l);
    if (opt.verbose) {
      if (!intersects) {
        std::cout << "found two non-intersecting quorums\nfirst quorum:\n";
        print_quorum(g, q1);
        std::cout << "second quorum:\n";
        print_quorum(g, q2);
      } else {
        std::cout << "all quorums are intersecting\n";
      }
    }
  }

  std::cout << (intersects ? "true" : "false") << "\n";
  return intersects ? 0 : 1;
}
