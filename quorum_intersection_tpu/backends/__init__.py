"""Pluggable quorum-disjointness search backends.

The polynomial phases (parse, graph, SCC reduction, per-SCC quorum scan) are
shared host code in :mod:`quorum_intersection_tpu.pipeline`; a *backend* owns
only the NP-hard part — deciding whether the quorum-bearing SCC contains two
disjoint quorums — mirroring how the BASELINE.json north star splits the
reference into a frontend + pluggable QuorumChecker.

Backends:

- ``python``     — pure-Python branch-and-bound, reference-faithful (the
                   portable correctness oracle)
- ``cpp``        — native C++ branch-and-bound over the flattened threshold
                   circuit (the fast CPU oracle)
- ``tpu-sweep``  — JAX exhaustive batched subset sweep (small SCCs; verdict-
                   equivalent by the half-size argument, exact by construction)
- ``tpu-hybrid`` — host frontier + batched device fixpoint evaluation
- ``auto``       — picks per-SCC-size: sweep for tiny, hybrid/cpp beyond
"""

from quorum_intersection_tpu.backends.base import SccCheckResult, SearchBackend, get_backend

__all__ = ["SccCheckResult", "SearchBackend", "get_backend"]
