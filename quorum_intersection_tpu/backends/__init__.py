"""Pluggable quorum-disjointness search backends.

The polynomial phases (parse, graph, SCC reduction, per-SCC quorum scan) are
shared host code in :mod:`quorum_intersection_tpu.pipeline`; a *backend* owns
only the NP-hard part — deciding whether the quorum-bearing SCC contains two
disjoint quorums — mirroring how the BASELINE.json north star splits the
reference into a frontend + pluggable QuorumChecker.

Backends:

- ``python``     — pure-Python branch-and-bound, reference-faithful (the
                   portable correctness oracle)
- ``cpp``        — native C++ branch-and-bound over the flattened threshold
                   circuit (the fast CPU oracle)
- ``tpu-sweep``  — JAX exhaustive batched subset sweep (small SCCs; verdict-
                   equivalent by the half-size argument, exact by construction)
- ``tpu-hybrid`` — host frontier + batched device fixpoint evaluation
- ``tpu-frontier`` — device-resident B&B: the worklist lives in HBM and
                   expands inside one lax.while_loop (zero round-trips in
                   the tree interior; rare leaves host-checked exactly)
- ``auto``       — latency-aware: budgeted oracle first, sweep fallback for
                   small SCCs; host oracle beyond (measured crossover)
"""

from quorum_intersection_tpu.backends.base import SccCheckResult, SearchBackend, get_backend

__all__ = ["SccCheckResult", "SearchBackend", "get_backend"]
