"""Pluggable quorum-disjointness search backends.

The polynomial phases (parse, graph, SCC reduction, per-SCC quorum scan) are
shared host code in :mod:`quorum_intersection_tpu.pipeline`; a *backend* owns
only the NP-hard part — deciding whether the quorum-bearing SCC contains two
disjoint quorums — mirroring how the BASELINE.json north star splits the
reference into a frontend + pluggable QuorumChecker.

Backends:

- ``python``     — pure-Python branch-and-bound, reference-faithful (the
                   portable correctness oracle)
- ``cpp``        — native C++ branch-and-bound over the flattened threshold
                   circuit (the fast CPU oracle)
- ``tpu-sweep``  — JAX exhaustive batched subset sweep (small SCCs; verdict-
                   equivalent by the half-size argument, exact by construction)
- ``tpu-frontier`` — device-resident B&B: the worklist lives in HBM and
                   expands inside one lax.while_loop (zero round-trips in
                   the tree interior; rare leaves host-checked exactly).
                   Beats the native oracle at scc 32 on chip
                   (crossover_tpu_r5.txt, 1.16x with count parity)
- ``auto``       — latency-aware: budgeted oracle first, sweep fallback for
                   small SCCs; host oracle beyond, except inside measured
                   frontier/sweep win regions (backends/calibration.py)

The round-trip ``tpu-hybrid`` engine (host frontier + batched device
fixpoint evaluation) was retired in r5: measured 100-1000x slower than
the native oracle at every size on chip and CPU alike (crossover
artifacts r3-r5), with both of its unique capabilities — checkpoint and
mesh sharding — carried by the frontier.
"""

from quorum_intersection_tpu.backends.base import SccCheckResult, SearchBackend, get_backend

__all__ = ["SccCheckResult", "SearchBackend", "get_backend"]
