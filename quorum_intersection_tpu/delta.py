"""qi-delta/1 — incremental re-analysis (ISSUE 9 tentpole).

The serving layer answers a *stream* of stellarbeat snapshots, and PR 8's
verdict cache is all-or-nothing per snapshot fingerprint: one threshold
wobble in one SCC forces a full re-solve of every SCC, even though the
NP-hard work decomposes per-SCC (arXiv:1902.06493) and "Read-Write Quorum
Systems Made Practical" (arXiv:2104.04102) treats quorum analysis as a
continuously re-queried service artifact.  This module closes the gap:

- :class:`SccVerdictStore` — an LRU store (``QI_DELTA_CACHE_MAX``) keyed
  by the SCC-local fingerprints of ``fbas/diff.py``: per-SCC **scan**
  results (the polynomial max-quorum fixpoint, re-run for every SCC of
  every snapshot today) and per-SCC **search verdicts** (the exponential
  disjointness search plus its qi-cert ledger/witness fragment), both in
  SCC-local coordinates so they project onto any snapshot whose component
  is structurally identical.  Concurrent misses on one fingerprint are
  **single-flight**: one leader solves, followers wait and reuse
  (``tools/analyze/schedules.py`` forces the orderings).  Since ISSUE 11
  the store is a **two-level tier**: an attached :class:`SharedSccStore`
  (fingerprint-keyed files, atomic writes — the fleet workers' shared
  directory) is read through on every local miss and written through on
  every bank, so identical SCC fragments are solved once per *fleet*, not
  once per process; a dead shared tier degrades to local-LRU-only through
  the ``fleet.store`` fault point, loudly, never to a wrong verdict.
- :class:`DeltaEngine` — the delta-aware twin of
  :func:`pipeline.check_many`: per snapshot it re-runs only the cheap
  structural prefix (parse → graph → Tarjan), serves every fingerprint-
  unchanged SCC's scan and the target SCC's verdict from the store, and
  sends **only dirty/new SCCs** to a backend.  A ``churn_trace`` step that
  wobbles one watcher SCC therefore re-solves *zero* SCCs; a step that
  dirties the quorum-bearing core re-solves exactly that one.
- **Composed certificates**: a store hit stitches the cached SCC
  ledger/witness fragment into a fresh ``qi-cert/1`` built against the
  *new* snapshot (guard counts, node ids, and witness evidence recomputed
  — only the structural verdict and its coverage arithmetic are reused),
  stamped ``provenance.delta`` with reused vs re-solved SCC counts, and
  still checkable by the unmodified stdlib ``tools/check_cert.py``.

The diff/fingerprint path is a declared fault point (``delta.diff``,
docs/ROBUSTNESS.md): an injected or real failure there degrades to the
full re-solve chain (``pipeline.check_many``) — incremental re-analysis is
an optimization, never a precondition for a verdict.  Telemetry
(``qi-telemetry/1``): ``delta.*`` spans/events/counters plus the
``delta.scc_reuse_pct`` / ``delta.store_size`` gauges ``/healthz`` and
``/metrics`` expose (docs/OBSERVABILITY.md registry).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import socket
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple, Union

from quorum_intersection_tpu.backends.base import (
    CancelToken,
    SearchBackend,
    SearchCancelled,
)
from quorum_intersection_tpu.cert import build_certificate
from quorum_intersection_tpu.fbas.diff import (
    diff_snapshots,
    localize,
    project,
    scc_fingerprint,
)
from quorum_intersection_tpu.fbas.graph import TrustGraph, build_graph
from quorum_intersection_tpu.fbas.schema import Fbas, parse_fbas
from quorum_intersection_tpu.pipeline import (
    SolveResult,
    _classify_sccs,
    check_many,
    scan_scc_quorums,
)
from quorum_intersection_tpu.utils.env import qi_env, qi_env_float, qi_env_int
from quorum_intersection_tpu.utils.faults import FaultInjected, fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record
from quorum_intersection_tpu.utils.timers import PhaseTimers

log = get_logger("delta")

DELTA_SCHEMA = "qi-delta/1"

# Deterministic-interleaving hook (tools/analyze/schedules.py): a no-op in
# production; the schedule harness swaps in a SyncController to FORCE the
# store's single-flight orderings (follower-waits-for-leader,
# leader-fails-follower-takes-over) the wall clock almost never produces.
_delta_sync: Callable[[str], None] = lambda point: None

# Bound on one single-flight wait: a follower whose leader died without
# publishing takes the lease over instead of wedging the drain forever.
LEASE_WAIT_S = 60.0

# Stats keys that describe the ORIGINAL solve's run, not the verdict: they
# are dropped from stored fragments so a composed result never claims a
# stale race outcome (or the original run's rank-order provenance — the
# composed result never ran a sweep) as its own (native/bnb counters stay
# — they ARE the coverage evidence the composed ledger re-serves).
_VOLATILE_STATS = ("race", "order")

_StoreKey = Tuple[str, str, str]


def _localize_pruned_evidence(
    stats: Dict[str, object], graph: TrustGraph, members: List[int]
) -> Optional[Dict[str, object]]:
    """Rewrite a fragment's pruned-block evidence (ISSUE 10) into SCC-local
    coordinates before banking: the cert ledger's ``enumeration`` block
    names graph-space publicKeys, and a fingerprint-matched SCC in a LATER
    snapshot may carry different keys (the same rank map the witness
    localization rides).  The ``pruned_blocks`` claims are pure block
    arithmetic over the bit order, so only the bit→node map needs the
    coordinate change.  ``None`` when an enumeration id fails to localize
    (a claim that escaped the SCC — the same unsoundness the witness
    localization refuses to cache): the caller must not bank the
    fragment, because a composed certificate could never re-verify it."""
    cert = stats.get("cert")
    if not isinstance(cert, dict) or "enumeration" not in cert:
        return stats
    enum = cert.get("enumeration") or {}
    rank: Dict[str, int] = {
        graph.node_ids[v]: i for i, v in enumerate(members)
    }
    try:
        local = {
            "fixed": rank[enum["fixed"]],
            "bit_nodes": [rank[pk] for pk in enum["bit_nodes"]],
        }
    except (KeyError, TypeError):
        return None
    stats = dict(stats)
    cert = dict(cert)
    del cert["enumeration"]
    cert["enumeration_local"] = local
    stats["cert"] = cert
    return stats


def _project_pruned_evidence(
    stats: Dict[str, object], graph: TrustGraph, members: List[int]
) -> Dict[str, object]:
    """Inverse of :func:`_localize_pruned_evidence` at compose time: rebuild
    the ``enumeration`` bit→node map against THIS snapshot's graph, so the
    composed certificate's pruned blocks re-verify under the new ids."""
    cert = stats.get("cert")
    if not isinstance(cert, dict) or "enumeration_local" not in cert:
        return stats
    local = cert["enumeration_local"]
    stats = dict(stats)
    cert = dict(cert)
    del cert["enumeration_local"]
    cert["enumeration"] = {
        "fixed": graph.node_ids[members[local["fixed"]]],
        "bit_nodes": [
            graph.node_ids[members[r]] for r in local["bit_nodes"]
        ],
    }
    stats["cert"] = cert
    return stats


@dataclass
class SccScan:
    """Cached per-SCC quorum-scan result, SCC-local coordinates."""

    quorum_local: Tuple[int, ...]  # () = no quorum inside this SCC


@dataclass
class SccVerdict:
    """Cached per-SCC search verdict + its certificate fragment."""

    intersects: bool
    q1_local: Optional[List[int]]
    q2_local: Optional[List[int]]
    stats: Dict[str, object] = field(default_factory=dict)


STORE_SCHEMA = "qi-store/1"


def _mesh_token_digest() -> str:
    """SHA-256 digest of ``QI_FLEET_TOKEN`` (empty token ⇒ empty digest)
    — the store gateway's session auth; the wire never sees the raw
    token.  Kept wire-identical to serve_transport.fleet_token_digest
    (importing it here would cycle delta ← serve ← serve_transport)."""
    token = qi_env("QI_FLEET_TOKEN")
    if not token:
        return ""
    return hashlib.sha256(token.encode("utf-8")).hexdigest()


class RemoteStoreClient:
    """qi-store/1 — SCC fragments fetched and published over the mesh
    wire (qi-mesh, ISSUE 19).

    One persistent token-authenticated JSONL connection to the fleet
    front door's store gateway (fleet.py ``StoreGateway``); a socket
    worker with no shared filesystem reads through to it on every local
    miss (fetch-on-miss) and writes every banked fragment back
    (publish-on-solve).  **Safe by construction**: a fetched payload
    passes the same strict shape validation as a local file and the
    composed certificate re-verifies through the checker — a torn,
    corrupt or forged shipped fragment is just a miss, never trusted.

    Every round trip sits behind the ``store.fetch`` fault point with a
    deadline (socket timeout) and bounded retry with backoff+jitter;
    exhausted retries degrade to a LOCAL SOLVE (``store.fetch_errors``
    counter + ``store.fetch_degraded`` event, loud) — fleet-wide reuse
    is lost, the verdict is not.
    """

    def __init__(self, host: str, port: int, *, timeout_s: float = 2.0,
                 retries: int = 2) -> None:
        self.host = host
        self.port = int(port)
        self.timeout_s = max(float(timeout_s), 0.05)
        self.retries = max(int(retries), 1)
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._reader = None
        self._writer = None

    # ---- wire ------------------------------------------------------------

    def _connect_locked(self) -> None:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s,
        )
        sock.settimeout(self.timeout_s)
        reader = sock.makefile("r", encoding="utf-8")
        writer = sock.makefile("w", encoding="utf-8")
        writer.write(json.dumps({"store_hello": {
            "schema": STORE_SCHEMA, "token": _mesh_token_digest(),
        }}) + "\n")
        writer.flush()
        resp = json.loads(reader.readline() or "null")
        if not (isinstance(resp, dict) and resp.get("ok")):
            raise OSError(
                f"store gateway rejected the session: {resp!r}"
            )
        self._sock, self._reader, self._writer = sock, reader, writer

    def _close_locked(self) -> None:
        for closer in (self._reader, self._writer, self._sock):
            try:
                if closer is not None:
                    closer.close()
            except OSError:
                pass
        self._sock = self._reader = self._writer = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _request(self, op: Dict[str, object]) -> Optional[Dict[str, object]]:
        """One authenticated round trip: deadline + bounded retry with
        backoff+jitter behind ``store.fetch``; ``None`` = degraded (the
        caller solves locally)."""
        rec = get_run_record()
        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                # Bounded backoff+jitter before each retry: a partitioned
                # gateway gets breathing room, a blip retries quickly.
                time.sleep(
                    min(0.05 * (2 ** (attempt - 1)), 0.5)
                    * (1.0 + random.random())
                )
            try:
                fault_point("store.fetch")
                with self._lock:
                    if self._sock is None:
                        self._connect_locked()
                    assert self._writer is not None
                    assert self._reader is not None
                    self._writer.write(json.dumps(op, default=str) + "\n")
                    self._writer.flush()
                    line = self._reader.readline()
                resp = json.loads(line or "null")
                if not (isinstance(resp, dict) and resp.get("ok") is True):
                    raise ValueError(f"store gateway answered {resp!r}")
                return resp
            except (FaultInjected, OSError, ValueError, TypeError) as exc:
                last = exc
                with self._lock:
                    self._close_locked()
        rec.add("store.fetch_errors")
        rec.event("store.fetch_degraded", op=str(op.get("op")),
                  error=str(last))
        log.warning(
            "remote store %s failed after %d attempt(s) (%s); degrading "
            "to local solve", op.get("op"), self.retries + 1, last,
        )
        return None

    # ---- operations ------------------------------------------------------

    def fetch(self, kind: str, fp: str,
              scope: str = "") -> Optional[Dict[str, object]]:
        """One fragment payload from the gateway, or ``None`` (miss or
        degraded — indistinguishable on purpose: both solve locally)."""
        get_run_record().add("store.fetches")
        resp = self._request(
            {"op": "get", "kind": kind, "fp": fp, "scope": scope},
        )
        if resp is None:
            return None
        payload = resp.get("payload")
        return payload if isinstance(payload, dict) else None

    def publish(self, kind: str, fp: str, payload: Dict[str, object],
                scope: str = "") -> bool:
        """Publish one banked fragment; ``False`` (never an exception) on
        a degraded wire — the fragment stays local, loudly."""
        get_run_record().add("store.publishes")
        resp = self._request({
            "op": "put", "kind": kind, "fp": fp, "scope": scope,
            "payload": payload,
        })
        return resp is not None


class SharedSccStore:
    """Fingerprint-keyed shared fragment tier (qi-fleet, ISSUE 11).

    The second level under :class:`SccVerdictStore`: one file per fragment
    under ``root``, named by entry kind + SCC-local fingerprint + scoping
    bit, written atomically (tmp + rename) so concurrent fleet workers
    never read a torn fragment.  Fragments are stored in SCC-local
    coordinates — deliberately coordinate-free (PR 10 proved transplant
    across key spaces), which is exactly what makes a fragment solved by
    worker A composable into worker B's certificate, with the composed
    cert still passing the unmodified ``tools/check_cert.py``.

    Every operation sits behind the ``fleet.store`` fault point
    (docs/ROBUSTNESS.md) and **degrades to local-LRU-only**: a read error,
    a full disk, an unparseable fragment, or an injected fault costs
    fleet-wide reuse (``fleet.store_errors`` counter, loud), never a
    verdict and never a wrong fragment — a fragment that fails shape
    validation is treated as a miss, not trusted.
    """

    def __init__(self, root: Union[str, Path],
                 max_mb: Optional[float] = None) -> None:
        self.root = Path(root)
        # Compaction budget (ROADMAP follow-up: the fragment directory
        # grows without bound).  <= 0 keeps the pre-GC unbounded behavior.
        self.max_bytes = int(
            (max_mb if max_mb is not None
             else qi_env_float("QI_FLEET_STORE_MAX_MB", 0.0)) * 1024 * 1024
        )
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        # Optional third tier (qi-mesh, ISSUE 19): a RemoteStoreClient to
        # the fleet front door's store gateway.  Attached post-hoc on a
        # socket worker (serve.ServeEngine.attach_remote_store); when set,
        # a local-file miss reads through to the wire and every local bank
        # is published back — same safety story as the file tier, since a
        # fetched fragment still passes shape validation and the composed
        # cert re-verifies through the checker.
        self.remote: Optional["RemoteStoreClient"] = None

    def _path(self, kind: str, fp: str, scope: str) -> Path:
        return self.root / f"{kind}-{scope or 'g'}-{fp}.json"

    def _note(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self._hits += 1
            else:
                self._misses += 1
            hits, misses = self._hits, self._misses
        rec = get_run_record()
        rec.add("fleet.store_hits" if hit else "fleet.store_misses")
        rec.gauge(
            "fleet.store_hit_pct",
            round(100.0 * hits / (hits + misses), 2) if hits + misses else 0.0,
        )

    def get(self, kind: str, fp: str, scope: str = "") -> Optional[Dict[str, object]]:
        """One fragment payload, or ``None`` (miss or degraded)."""
        rec = get_run_record()
        try:
            fault_point("fleet.store")
            raw = self._path(kind, fp, scope).read_text(encoding="utf-8")
            payload = json.loads(raw)
            if not isinstance(payload, dict):
                raise ValueError("shared fragment is not a JSON object")
        except FileNotFoundError:
            fetched = self._fetch_remote(kind, fp, scope)
            if fetched is not None:
                self._note(True)
                return fetched
            self._note(False)
            return None
        except (OSError, ValueError, FaultInjected) as exc:
            rec.add("fleet.store_errors")
            rec.event("fleet.store_error", op="get", kind=kind, error=str(exc))
            log.warning(
                "shared store read failed (%s); degrading to local LRU only "
                "for this lookup", exc,
            )
            return None
        self._note(True)
        return payload

    def put(self, kind: str, fp: str, payload: Dict[str, object],
            scope: str = "") -> bool:
        """Bank one fragment; ``False`` (never an exception) on failure."""
        rec = get_run_record()
        path = self._path(kind, fp, scope)
        tmp = path.with_name(f"{path.name}.tmp-{os.getpid()}")
        try:
            fault_point("fleet.store")
            self.root.mkdir(parents=True, exist_ok=True)
            tmp.write_text(
                json.dumps(payload, separators=(",", ":")), encoding="utf-8",
            )
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError, FaultInjected) as exc:
            rec.add("fleet.store_errors")
            rec.event("fleet.store_error", op="put", kind=kind, error=str(exc))
            log.warning(
                "shared store write failed (%s); fragment stays local-only",
                exc,
            )
            try:
                tmp.unlink()
            except OSError:
                pass
            return False
        self._maybe_gc()
        if self.remote is not None:
            # Publish-on-solve: best effort — the client degrades loudly
            # on its own (store.fetch_errors), the local bank stands.
            self.remote.publish(kind, fp, payload, scope)
        return True

    def _fetch_remote(self, kind: str, fp: str,
                      scope: str) -> Optional[Dict[str, object]]:
        """Fetch-on-miss through the mesh gateway and bank the fragment
        locally (atomic tmp+rename, same as :meth:`put`) so the next miss
        is a plain file hit.  ``None`` on no-remote, remote-miss, or a
        degraded wire — all just a local miss."""
        if self.remote is None:
            return None
        payload = self.remote.fetch(kind, fp, scope)
        if payload is None:
            return None
        path = self._path(kind, fp, scope)
        tmp = path.with_name(f"{path.name}.tmp-{os.getpid()}")
        try:
            self.root.mkdir(parents=True, exist_ok=True)
            tmp.write_text(
                json.dumps(payload, separators=(",", ":")), encoding="utf-8",
            )
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError):
            try:
                tmp.unlink()
            except OSError:
                pass
        return payload

    def _maybe_gc(self) -> None:
        """LRU-by-mtime sweep on publish (``QI_FLEET_STORE_MAX_MB``):
        while the fragment directory exceeds its size budget the stalest
        fragments are deleted — LOUD (``delta.store_evictions`` counter +
        ``delta.store_gc`` event), and an evicted fragment costs a future
        re-solve on a miss, never a verdict: a concurrent reader of a
        just-deleted file sees FileNotFoundError, which is already a
        plain miss."""
        if self.max_bytes <= 0:
            return
        try:
            files = sorted(
                (p.stat().st_mtime, p.stat().st_size, str(p), p)
                for p in self.root.glob("*.json")
            )
        except OSError:
            return
        total = sum(size for _, size, _, _ in files)
        if total <= self.max_bytes:
            return
        evicted = 0
        for _, size, _, path in files:
            try:
                path.unlink()
            except OSError:
                continue
            total -= size
            evicted += 1
            if total <= self.max_bytes:
                break
        if evicted:
            rec = get_run_record()
            rec.add("delta.store_evictions", evicted)
            rec.event(
                "delta.store_gc", evicted=evicted,
                remaining_bytes=max(total, 0),
                budget_bytes=self.max_bytes,
            )
            log.warning(
                "shared store over its %d-byte budget; %d stalest "
                "fragment(s) evicted (they re-solve on next miss)",
                self.max_bytes, evicted,
            )


def _encode_verdict(verdict: SccVerdict) -> Dict[str, object]:
    return {
        "intersects": bool(verdict.intersects),
        "q1_local": verdict.q1_local,
        "q2_local": verdict.q2_local,
        "stats": verdict.stats,
    }


def _decode_verdict(payload: Dict[str, object]) -> Optional[SccVerdict]:
    """Strict shape validation: a forged/torn shared fragment becomes a
    miss, never a trusted verdict."""
    intersects = payload.get("intersects")
    q1 = payload.get("q1_local")
    q2 = payload.get("q2_local")
    stats = payload.get("stats")
    if not isinstance(intersects, bool) or not isinstance(stats, dict):
        return None
    for q in (q1, q2):
        if q is not None and not (
            isinstance(q, list) and all(isinstance(v, int) for v in q)
        ):
            return None
    return SccVerdict(intersects=intersects, q1_local=q1, q2_local=q2,
                      stats=stats)


def _decode_scan(payload: Dict[str, object]) -> Optional[SccScan]:
    quorum = payload.get("quorum_local")
    if not (isinstance(quorum, list)
            and all(isinstance(v, int) for v in quorum)):
        return None
    return SccScan(quorum_local=tuple(quorum))


class SccVerdictStore:
    """LRU-bounded, single-flight store of per-SCC scans and verdicts.

    One LRU budget (``QI_DELTA_CACHE_MAX``) covers both entry kinds — scan
    entries are tiny next to verdict fragments, but a shared bound keeps
    the occupancy gauge honest.  Thread-safe; telemetry is emitted outside
    the lock (lock-discipline: never emit while holding one).

    **Two-level tier** (qi-fleet, ISSUE 11): with ``shared`` attached the
    local LRU reads through to a fingerprint-keyed
    :class:`SharedSccStore` — a local scan/verdict miss probes the shared
    tier before solving (a shared hit is banked locally and counted as a
    reuse), and every banked fragment is written through, so N fleet
    workers solve each structurally distinct SCC once fleet-wide instead
    of once per process.  ``shared=None`` (the default) is byte-for-byte
    the PR 9 per-process behavior.
    """

    def __init__(self, max_entries: Optional[int] = None,
                 shared: Optional[SharedSccStore] = None) -> None:
        self.max_entries = max(
            max_entries if max_entries is not None
            else qi_env_int("QI_DELTA_CACHE_MAX", 4096),
            1,
        )
        self.shared = shared
        self._lock = threading.Lock()
        self._entries: "OrderedDict[_StoreKey, object]" = OrderedDict()
        self._pending: Dict[_StoreKey, threading.Event] = {}
        self._scc_hits = 0
        self._scc_misses = 0

    @staticmethod
    def _vkey(fp: str, scope_to_scc: bool) -> _StoreKey:
        return ("verdict", fp, str(int(scope_to_scc)))

    # ---- internal ---------------------------------------------------------

    def _put(self, key: _StoreKey, value: object) -> None:
        evicted = 0
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                evicted += 1
            size = len(self._entries)
        rec = get_run_record()
        if evicted:
            rec.add("delta.store_evictions", evicted)
        rec.gauge("delta.store_size", size)

    def _note_verdict_lookup(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self._scc_hits += 1
            else:
                self._scc_misses += 1
            hits, misses = self._scc_hits, self._scc_misses
        rec = get_run_record()
        rec.add("delta.scc_hits" if hit else "delta.scc_misses")
        rec.gauge(
            "delta.scc_reuse_pct",
            round(100.0 * hits / (hits + misses), 2) if hits + misses else 0.0,
        )

    # ---- scans ------------------------------------------------------------

    def get_scan(self, fp: str) -> Optional[SccScan]:
        key = ("scan", fp, "")
        with self._lock:
            scan = self._entries.get(key)
            if scan is not None:
                self._entries.move_to_end(key)
        if scan is None and self.shared is not None:
            payload = self.shared.get("scan", fp)
            if payload is not None:
                scan = _decode_scan(payload)
                if scan is not None:
                    self._put(key, scan)
        rec = get_run_record()
        rec.add("delta.scan_hits" if scan is not None else "delta.scan_misses")
        return scan  # type: ignore[return-value]

    def put_scan(self, fp: str, scan: SccScan) -> None:
        self._put(("scan", fp, ""), scan)
        if self.shared is not None:
            self.shared.put("scan", fp, {"quorum_local": list(scan.quorum_local)})

    # ---- verdicts (single-flight) -----------------------------------------

    def _shared_verdict(
        self, fp: str, scope_to_scc: bool
    ) -> Optional[SccVerdict]:
        """Shared-tier verdict probe: a validated hit is banked locally."""
        if self.shared is None:
            return None
        payload = self.shared.get("verdict", fp, scope=f"s{int(scope_to_scc)}")
        if payload is None:
            return None
        verdict = _decode_verdict(payload)
        if verdict is not None:
            self._put(self._vkey(fp, scope_to_scc), verdict)
        return verdict

    def peek_verdict(
        self, fp: str, scope_to_scc: bool
    ) -> Optional[SccVerdict]:
        """Plain lookup, no lease, no hit/miss accounting — the intra-batch
        follower probe after its leader's batch solved."""
        key = self._vkey(fp, scope_to_scc)
        with self._lock:
            cached = self._entries.get(key)
            if cached is not None:
                self._entries.move_to_end(key)
        if cached is None:
            cached = self._shared_verdict(fp, scope_to_scc)
        return cached  # type: ignore[return-value]

    def lease_verdict(
        self, fp: str, scope_to_scc: bool
    ) -> Tuple[str, Optional[SccVerdict]]:
        """``("hit", verdict)`` or ``("leader", None)``.

        A concurrent leader already solving this fingerprint parks the
        caller until :meth:`publish_verdict` fires, then re-probes: the
        published verdict is a hit; a leader that failed (published
        ``None``) hands the lease over — the caller becomes the new
        leader.  Bounded by :data:`LEASE_WAIT_S` so a dead leader can
        never wedge a drain.
        """
        key = self._vkey(fp, scope_to_scc)
        while True:
            wait_ev: Optional[threading.Event] = None
            cached: Optional[object] = None
            with self._lock:
                cached = self._entries.get(key)
                if cached is not None:
                    self._entries.move_to_end(key)
                elif key in self._pending:
                    wait_ev = self._pending[key]
                else:
                    self._pending[key] = threading.Event()
            if cached is not None:
                self._note_verdict_lookup(True)
                return "hit", cached  # type: ignore[return-value]
            if wait_ev is None:
                # Read-through before solving (qi-fleet): another worker
                # may already have banked this fragment in the shared
                # tier.  A hit releases the just-taken lease — followers
                # re-probe and find the banked local entry.
                shared_hit = self._shared_verdict(fp, scope_to_scc)
                if shared_hit is not None:
                    with self._lock:
                        ev = self._pending.pop(key, None)
                    if ev is not None:
                        ev.set()
                    self._note_verdict_lookup(True)
                    return "hit", shared_hit
                self._note_verdict_lookup(False)
                _delta_sync("store.leader")
                return "leader", None
            _delta_sync("store.wait")
            if not wait_ev.wait(LEASE_WAIT_S):
                # Leader died without publishing: exactly ONE timed-out
                # waiter takes the lease over — it swaps in a fresh event
                # so later arrivals (and the other timed-out waiters, who
                # loop) park on the new leader instead of all becoming
                # leaders and re-solving the same fingerprint N times.
                # Should the presumed-dead leader publish after all, its
                # publish pops the fresh event and wakes those waiters to
                # re-probe — correctness is unaffected either way.
                with self._lock:
                    if self._pending.get(key) is not wait_ev:
                        continue  # published or already taken over: re-probe
                    self._pending[key] = threading.Event()
                self._note_verdict_lookup(False)
                _delta_sync("store.leader")
                return "leader", None

    def publish_verdict(
        self, fp: str, scope_to_scc: bool, verdict: Optional[SccVerdict]
    ) -> None:
        """Resolve a lease: store ``verdict`` (``None`` = the leader's
        solve failed or was uncacheable; waiting followers re-contend for
        the lease) and wake every waiter."""
        key = self._vkey(fp, scope_to_scc)
        if verdict is not None:
            self._put(key, verdict)
            if self.shared is not None:
                # Write-through: the fragment is SCC-local (coordinate-
                # free), so any fleet worker can compose it.
                self.shared.put(
                    "verdict", fp, _encode_verdict(verdict),
                    scope=f"s{int(scope_to_scc)}",
                )
        with self._lock:
            ev = self._pending.pop(key, None)
        if ev is not None:
            ev.set()
        _delta_sync("store.publish")

    # ---- introspection ----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def reuse_pct(self) -> float:
        with self._lock:
            total = self._scc_hits + self._scc_misses
            return 100.0 * self._scc_hits / total if total else 0.0


@dataclass
class _SourceState:
    """Per-source bookkeeping across the classify → compose/solve phases."""

    ix: int
    fbas: Fbas
    graph: TrustGraph
    n_sccs: int = 0
    quorum_scc_ids: List[int] = field(default_factory=list)
    scc_quorums: Dict[int, List[int]] = field(default_factory=dict)
    main_scc: List[int] = field(default_factory=list)
    target_scc: List[int] = field(default_factory=list)
    target_index: int = 0
    target_fp: str = ""
    cacheable: bool = False
    scan_reused: int = 0
    scan_fresh: int = 0
    ev0: int = 0
    timers: Dict[str, float] = field(default_factory=dict)


class DeltaEngine:
    """Delta-aware batch solver (see module docstring).

    One engine per serving configuration: all snapshots it sees share the
    front-end options (dangling policy, SCC selection, scoping), which is
    what makes per-SCC fragments interchangeable across them.  The engine
    remembers the previous snapshot's graph and emits a
    ``delta.classified`` event per snapshot with the
    :func:`fbas.diff.diff_snapshots` summary — the observable that tells
    "cosmetic churn" from "core restructure" in a live ``/metrics``
    scrape.
    """

    def __init__(
        self,
        store: Optional[SccVerdictStore] = None,
        *,
        dangling: str = "strict",
        scc_select: str = "quorum-bearing",
        scope_to_scc: bool = False,
        track_diff: bool = True,
    ) -> None:
        self.store = store if store is not None else SccVerdictStore()
        self.dangling = dangling
        self.scc_select = scc_select
        self.scope_to_scc = scope_to_scc
        self.track_diff = track_diff
        # Previous snapshot's (graph, partition, fingerprints) — kept so
        # the per-snapshot delta.classified diff costs only overlap
        # bookkeeping, never a second Tarjan/fingerprint pass.
        self._prev: Optional[
            Tuple[TrustGraph, List[List[int]], List[Tuple[str, bool]]]
        ] = None

    # ---- entry point ------------------------------------------------------

    def check_many(
        self,
        sources: List[object],
        *,
        backend: Union[str, SearchBackend] = "auto",
        pack: Optional[bool] = None,
        cancels: Optional[Sequence[Optional[CancelToken]]] = None,
        origins: Optional[Sequence[str]] = None,
    ) -> List[SolveResult]:
        """Batch verdicts for ``sources``, reusing per-SCC work.

        Semantics contract: result verdicts, witnesses and certificates
        are interchangeable with :func:`pipeline.check_many`'s for the
        same sources (the differential suite in ``tests/test_qi_delta.py``);
        only *which engine re-derives them* changes.  Degrades to the full
        re-solve chain on an injected/real ``delta.diff`` failure — and on
        ANY unexpected error in the incremental body itself (fingerprint,
        diff, store, compose): incremental re-analysis is an optimization,
        never a precondition for a verdict.  Only cooperative cancellation
        (``SearchCancelled``, the serve deadline path) propagates.
        """
        rec = get_run_record()
        try:
            fault_point("delta.diff")
        except (FaultInjected, OSError) as exc:
            rec.add("delta.diff_faults")
            return self._degrade(sources, backend, pack, exc, cancels, origins)
        try:
            return self._check_many_incremental(
                sources, backend, pack, cancels, origins
            )
        except SearchCancelled:
            raise
        except Exception as exc:  # noqa: BLE001 — any differ/store failure
            # degrades to the full chain (docs/ROBUSTNESS.md contract);
            # the verdict must never depend on the optimization working.
            rec.add("delta.errors")
            return self._degrade(sources, backend, pack, exc, cancels, origins)

    def _degrade(
        self,
        sources: List[object],
        backend: Union[str, SearchBackend],
        pack: Optional[bool],
        exc: BaseException,
        cancels: Optional[Sequence[Optional[CancelToken]]] = None,
        origins: Optional[Sequence[str]] = None,
    ) -> List[SolveResult]:
        rec = get_run_record()
        rec.event("delta.degraded", error=str(exc))
        log.warning(
            "delta path failed (%s); degrading to full re-solve", exc,
        )
        return check_many(
            sources, backend=backend, dangling=self.dangling,
            scc_select=self.scc_select, scope_to_scc=self.scope_to_scc,
            pack=pack, cancels=cancels, origins=origins,
        )

    def _check_many_incremental(
        self,
        sources: List[object],
        backend: Union[str, SearchBackend],
        pack: Optional[bool],
        cancels: Optional[Sequence[Optional[CancelToken]]] = None,
        origins: Optional[Sequence[str]] = None,
    ) -> List[SolveResult]:
        rec = get_run_record()
        allow_native = backend_name(backend) != "python"
        results: List[Optional[SolveResult]] = [None] * len(sources)
        misses: List[_SourceState] = []
        followers: List[_SourceState] = []
        # Fingerprints THIS call holds the lease for: an identical snapshot
        # later in the same batch must not wait on its own batch's lease
        # (single-thread deadlock) — it becomes an intra-batch follower and
        # composes from the store after the leader's batch solve lands.
        held: Set[str] = set()
        reused = 0
        with rec.span("delta.check", sources=len(sources)):
            try:
                for ix, source in enumerate(sources):
                    st = self._classify(ix, source, allow_native)
                    if len(st.quorum_scc_ids) != 1:
                        results[ix] = self._guard_result(st)
                        continue
                    if not st.cacheable:
                        get_run_record().add("delta.uncacheable")
                        misses.append(st)
                        continue
                    if st.target_fp in held:
                        followers.append(st)
                        continue
                    outcome, cached = self.store.lease_verdict(
                        st.target_fp, self.scope_to_scc
                    )
                    if outcome == "hit":
                        assert cached is not None
                        results[ix] = self._compose(st, cached)
                        reused += 1
                    else:
                        held.add(st.target_fp)
                        misses.append(st)
                if misses:
                    self._solve_misses(
                        misses, results, backend, pack, held,
                        cancels=cancels, origins=origins,
                    )
                for st in followers:
                    cached = self.store.peek_verdict(
                        st.target_fp, self.scope_to_scc
                    )
                    # Intra-batch followers count toward the reuse gauge
                    # too: a composition IS a reuse, whichever flight path
                    # (lease wait vs same-batch peek) delivered the
                    # fragment — and a straggler that must re-solve is a
                    # miss the gauge must not hide.
                    self.store._note_verdict_lookup(cached is not None)
                    if cached is not None:
                        results[st.ix] = self._compose(st, cached)
                        reused += 1
                if any(
                    results[st.ix] is None for st in followers
                ):
                    # The leader's fragment never landed (failed solve /
                    # witness escaped the SCC): solve the stragglers
                    # directly — correctness over reuse.
                    strag = [st for st in followers if results[st.ix] is None]
                    self._solve_misses(
                        strag, results, backend, pack, set(),
                        cancels=cancels, origins=origins,
                    )
            finally:
                # Any lease still held here (an exception mid-batch, a
                # deadline cancel inside the backend solve) is released as
                # a failure so concurrent followers re-contend instead of
                # wedging until the lease timeout.
                for fp in held:
                    self.store.publish_verdict(fp, self.scope_to_scc, None)
        if reused:
            rec.add("delta.compositions", reused)
        return [r for r in results if r is not None]

    # ---- classification ---------------------------------------------------

    def _classify(
        self, ix: int, source: object, allow_native: bool
    ) -> _SourceState:
        """The structural prefix: parse → graph → the SAME
        ``pipeline._classify_sccs`` guard/selection logic the one-shot
        entry points share (so incremental guard verdicts cannot drift),
        with a store-aware scan provider that serves every
        fingerprint-matched SCC's scan from cache (the polynomial half of
        incremental re-analysis)."""
        rec = get_run_record()
        timers = PhaseTimers()
        with timers.phase("parse"):
            fbas = source if isinstance(source, Fbas) else parse_fbas(source)
        with timers.phase("graph"):
            graph = build_graph(fbas, dangling=self.dangling)
        st = _SourceState(ix=ix, fbas=fbas, graph=graph)
        st.ev0 = rec.event_count()

        fps: List[Tuple[str, bool]] = []
        parts: List[List[int]] = []

        def store_scan(
            g: TrustGraph, sccs: List[List[int]], *, allow_native: bool
        ) -> List[Optional[List[int]]]:
            parts.extend(sccs)
            quorums, scc_fps, reused, fresh = self._serve_scans(
                g, sccs, allow_native
            )
            fps.extend(scc_fps)
            st.scan_reused += reused
            st.scan_fresh += fresh
            return quorums

        count, sccs, quorum_scc_ids, scc_quorums, main_scc = _classify_sccs(
            graph, allow_native=allow_native, scc_select=self.scc_select,
            timers=timers, scan=store_scan,
        )
        st.n_sccs = count
        st.quorum_scc_ids = quorum_scc_ids
        st.scc_quorums = scc_quorums
        st.main_scc = main_scc
        if self.track_diff:
            # The diff summary costs only overlap bookkeeping: both
            # snapshots' partitions and fingerprints are already in hand
            # (this one's from the scan above, the previous one's kept).
            if self._prev is not None:
                prev_graph, prev_parts, prev_fps = self._prev
                diff = diff_snapshots(
                    prev_graph, graph,
                    old_parts=prev_parts, old_fps_list=prev_fps,
                    new_parts=sccs, new_fps_list=fps,
                )
                rec.event("delta.classified", **diff.summary())
            self._prev = (graph, sccs, fps)
        if len(st.quorum_scc_ids) == 1:
            st.target_index = (
                0 if self.scc_select == "front" else st.quorum_scc_ids[0]
            )
            st.target_scc = sccs[st.target_index]
            st.target_fp, closed = fps[st.target_index]
            # Soundness gate (fbas/diff.py module docstring): under the
            # reference's whole-graph availability, a stored verdict is
            # only reusable when the component cannot see outside itself.
            st.cacheable = closed or self.scope_to_scc
        st.timers = dict(timers.totals)
        return st

    def _serve_scans(
        self, graph: TrustGraph, sccs: List[List[int]], allow_native: bool
    ) -> Tuple[
        List[Optional[List[int]]], List[Tuple[str, bool]], int, int
    ]:
        """Per-SCC quorum scans with the store in front: every
        fingerprint-matched SCC's scan comes from cache, misses run the
        real :func:`pipeline.scan_scc_quorums` and are banked.  Returns
        ``(quorums, fingerprints, reused, fresh)``.  Shared by the
        classification prefix AND the re-solve leg (via ``check_many``'s
        ``scan`` hook), so a dirty snapshot's unchanged SCCs never re-run
        their fixpoints either."""
        fps = [scc_fingerprint(graph, members) for members in sccs]
        quorums: List[Optional[List[int]]] = [None] * len(sccs)
        miss_ids: List[int] = []
        reused = 0
        for sid, members in enumerate(sccs):
            scan = self.store.get_scan(fps[sid][0])
            if scan is None:
                miss_ids.append(sid)
            else:
                quorums[sid] = project(list(scan.quorum_local), members)
                reused += 1
        if miss_ids:
            fresh = scan_scc_quorums(
                graph, [sccs[sid] for sid in miss_ids],
                allow_native=allow_native,
            )
            for sid, quorum in zip(miss_ids, fresh):
                quorums[sid] = quorum
                local = localize(quorum, sccs[sid])
                if local is not None:
                    self.store.put_scan(
                        fps[sid][0], SccScan(quorum_local=tuple(local))
                    )
        return quorums, fps, reused, len(miss_ids)

    # ---- composition ------------------------------------------------------

    def _compose(self, st: _SourceState, cached: SccVerdict) -> SolveResult:
        """Stitch one cached fragment into a full result + certificate
        against THIS snapshot's graph (guard, node ids and witness
        evidence all rebuilt fresh — see module docstring)."""
        rec = get_run_record()
        t0 = time.perf_counter()
        q1 = project(cached.q1_local, st.target_scc)
        q2 = project(cached.q2_local, st.target_scc)
        stats: Dict[str, object] = _project_pruned_evidence(
            dict(cached.stats), st.graph, st.target_scc
        )
        stats["delta"] = {
            "reused": True,
            "solved_seconds": stats.get("seconds"),
        }
        # qi-cost/1 (ISSUE 17): a reused SCC did zero new device work — its
        # cost is a reuse CREDIT (the lane·windows the cached solve booked,
        # avoided here), replacing the cached stats' own cost so reuse is
        # never double-billed.  Degrades to no cost, never a wrong verdict.
        try:
            fault_point("cost.attribute")
            from quorum_intersection_tpu.cost import reuse_credit
            cached_cost = stats.get("cost")
            stats["cost"] = reuse_credit(
                cached_cost if isinstance(cached_cost, dict) else None
            )
        except (FaultInjected, OSError) as exc:
            stats.pop("cost", None)
            rec.add("cost.attribute_errors")
            rec.event("cost.degraded", site="delta.compose", error=repr(exc))
        delta_stamp = {
            "schema": DELTA_SCHEMA,
            "reused_sccs": 1,
            "resolved_sccs": 0,
            "scan_reused": st.scan_reused,
            "scan_fresh": st.scan_fresh,
        }
        timers = dict(st.timers)
        timers["search"] = time.perf_counter() - t0
        stats["seconds"] = timers["search"]
        res = SolveResult(
            intersects=cached.intersects,
            n_sccs=st.n_sccs,
            quorum_scc_ids=list(st.quorum_scc_ids),
            main_scc=st.main_scc,
            q1=q1,
            q2=q2,
            stats=stats,
            timers=timers,
            cert=build_certificate(
                st.graph, intersects=cached.intersects, reason="search",
                n_sccs=st.n_sccs, quorum_bearing=len(st.quorum_scc_ids),
                scc_select=self.scc_select, scope_to_scc=self.scope_to_scc,
                stats=stats, q1=q1, q2=q2,
                target_scc=st.target_scc, target_scc_index=st.target_index,
                events=rec.events_since(st.ev0), batched=True,
                delta=delta_stamp,
            ),
        )
        rec.event(
            "delta.composed", fingerprint=st.target_fp,
            verdict=cached.intersects, backend=stats.get("backend"),
        )
        return res

    def _guard_result(self, st: _SourceState) -> SolveResult:
        """Guard-decided snapshot (zero or >= 2 quorum-bearing SCCs) —
        exactly :func:`pipeline.check_many`'s guard path, with the scans
        possibly served from the store."""
        rec = get_run_record()
        q1 = q2 = None
        if len(st.quorum_scc_ids) >= 2:
            q1 = st.scc_quorums[st.quorum_scc_ids[0]]
            q2 = st.scc_quorums[st.quorum_scc_ids[1]]
        delta_stamp = {
            "schema": DELTA_SCHEMA,
            "reused_sccs": 0,
            "resolved_sccs": 0,
            "scan_reused": st.scan_reused,
            "scan_fresh": st.scan_fresh,
        }
        return SolveResult(
            intersects=False, n_sccs=st.n_sccs,
            quorum_scc_ids=list(st.quorum_scc_ids), main_scc=st.main_scc,
            q1=q1, q2=q2, stats={"reason": "scc_guard"},
            timers=dict(st.timers),
            cert=build_certificate(
                st.graph, intersects=False, reason="scc_guard",
                n_sccs=st.n_sccs, quorum_bearing=len(st.quorum_scc_ids),
                scc_select=self.scc_select, scope_to_scc=self.scope_to_scc,
                stats={"reason": "scc_guard"}, q1=q1, q2=q2,
                events=rec.events_since(st.ev0), batched=True,
                delta=delta_stamp,
            ),
        )

    # ---- backend solves ---------------------------------------------------

    def _solve_misses(
        self,
        misses: List[_SourceState],
        results: List[Optional[SolveResult]],
        backend: Union[str, SearchBackend],
        pack: Optional[bool],
        held: Set[str],
        cancels: Optional[Sequence[Optional[CancelToken]]] = None,
        origins: Optional[Sequence[str]] = None,
    ) -> None:
        """Send the dirty/new target SCCs to the real backend (one batched
        ``check_many`` call — lane packing and the ladder apply as ever),
        then bank each solved fragment and release its lease.

        ``cancels``/``origins`` (qi-fuse) are SOURCE-aligned on the outer
        batch; only the miss subset rides along (``st.ix``) — which is the
        fusion win: delta-reused SCCs never occupy lanes."""
        rec = get_run_record()
        rec.add("delta.solves", len(misses))
        # The classification prefix already scanned every one of these
        # snapshots; check_many re-derives the same partition from the
        # same Fbas deterministically, so the re-solve leg re-serves the
        # prefix's per-SCC quorums verbatim (non-quorum SCCs scanned
        # empty) — no fixpoint, fingerprint, or store work re-runs, and
        # the delta.scan_* counters count each SCC exactly once.
        seq = iter(misses)

        def store_scan(
            g: TrustGraph, sccs: List[List[int]], *, allow_native: bool
        ) -> List[Optional[List[int]]]:
            st = next(seq)
            return [
                st.scc_quorums.get(sid, []) for sid in range(len(sccs))
            ]

        solved = check_many(
            [st.fbas for st in misses], backend=backend,
            dangling=self.dangling, scc_select=self.scc_select,
            scope_to_scc=self.scope_to_scc, pack=pack,
            delta={
                "schema": DELTA_SCHEMA,
                "reused_sccs": 0,
                "resolved_sccs": 1,
            },
            scan=store_scan,
            cancels=(
                [cancels[st.ix] for st in misses]
                if cancels is not None else None
            ),
            origins=(
                [origins[st.ix] for st in misses]
                if origins is not None else None
            ),
        )
        for st, res in zip(misses, solved):
            results[st.ix] = res
            self._bank(st, res, held)

    def _bank(
        self, st: _SourceState, res: SolveResult, held: Set[str]
    ) -> None:
        """Store one freshly solved fragment and publish its lease.

        Publishes a failed lease (followers re-contend) whenever the
        fragment could not faithfully re-serve: an un-closed SCC under
        whole-graph availability, a guard flip mid-flight, or a witness
        that escaped the component."""
        publishable: Optional[SccVerdict] = None
        if res.stats.get("cancelled"):
            # qi-fuse: a retired lane's partial coverage is NOT a verdict —
            # banking it would serve a non-answer to every future match.
            if st.target_fp in held:
                held.discard(st.target_fp)
                self.store.publish_verdict(
                    st.target_fp, self.scope_to_scc, None
                )
            return
        if st.cacheable and res.stats.get("reason") != "scc_guard":
            q1_local = localize(res.q1, st.target_scc)
            q2_local = localize(res.q2, st.target_scc)
            witness_ok = res.intersects or (
                q1_local is not None and q2_local is not None
            )
            if witness_ok:
                stats = _localize_pruned_evidence(
                    {
                        k: v for k, v in res.stats.items()
                        if k not in _VOLATILE_STATS
                    },
                    st.graph, st.target_scc,
                )
                if stats is not None:
                    publishable = SccVerdict(
                        intersects=bool(res.intersects),
                        q1_local=q1_local, q2_local=q2_local, stats=stats,
                    )
        if st.target_fp in held:
            held.discard(st.target_fp)
            self.store.publish_verdict(
                st.target_fp, self.scope_to_scc, publishable
            )
        elif publishable is not None:
            # An intra-batch straggler re-solved after its leader's
            # fragment failed to land: bank the fresh fragment directly
            # (publishable is only ever built for a cacheable state).
            self.store.publish_verdict(
                st.target_fp, self.scope_to_scc, publishable
            )


def backend_name(backend: Union[str, SearchBackend, None]) -> str:
    """Best-effort backend name for routing decisions (scan path)."""
    if backend is None:
        return "auto"
    if isinstance(backend, str):
        return backend
    return getattr(backend, "name", "auto")
