"""Multi-host (multi-process) execution — the DCN-scale half of the
communication backend.

The reference is a single process with no distributed backend at all
(SURVEY.md §5); its NCCL/MPI-shaped obligation maps here to JAX's runtime
collectives over a global device mesh:

- **within a slice** the candidate-sweep's one collective (a scalar
  ``pmin`` of first-hit indices per program, ``backends/tpu/sweep.py``)
  rides ICI;
- **across slices/hosts** the same collective crosses DCN — it is one int32
  per device program, so DCN latency is irrelevant to throughput; candidate
  blocks themselves never move between hosts (each device decodes its own
  indices locally — zero-byte sharding of the enumeration axis).

Multi-host SPMD contract of the sweep driver (why it is safe to reuse
unchanged): every process runs the identical deterministic dispatch loop
(same block schedule, same ramp), all processes enqueue the same programs in
the same order, and each program's result is a *replicated* scalar
(``out_specs=P()``), addressable by every process — so the host-side
``int(handle)`` sync and the FIFO drain agree everywhere without any extra
host-level coordination.

Usage on a TPU pod/multi-slice job (one process per host)::

    from quorum_intersection_tpu.parallel import distributed
    distributed.initialize()            # env-driven on TPU pods
    mesh = distributed.global_candidate_mesh()
    backend = TpuSweepBackend(mesh=mesh, batch=1 << 20)

Single-process runs (including the CPU host-platform emulation used in
tests) are the degenerate case: ``initialize`` is a no-op and the global
mesh equals the local one.
"""

from __future__ import annotations

import os
import time

from typing import Optional, Sequence

import numpy as np

from quorum_intersection_tpu.parallel.mesh import CANDIDATE_AXIS, candidate_mesh
from quorum_intersection_tpu.utils.env import qi_env_float
from quorum_intersection_tpu.utils.faults import fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record

log = get_logger("parallel.distributed")

_initialized = False

# First-retry backoff for coordinator-join failures; doubles per attempt,
# capped below so the bounded window (QI_DIST_INIT_TIMEOUT_S) is spent on
# retries rather than one long sleep.  A transient coordinator (restarting
# pod, DNS lag) usually answers within a few doublings; a dead one burns
# the window and degrades loudly to single-process.
_INIT_BACKOFF_S = 0.5
_INIT_BACKOFF_CAP_S = 5.0

# Seam for tests (mirrors backends/auto._retry_sleep): retry backoff
# sleeps route through this attribute so the bounded-retry path runs in
# milliseconds under test.
_retry_sleep = time.sleep

# RuntimeError markers that mean the failure is UNRECOVERABLE in this
# process — the XLA backend was already touched before init (jax's
# "must be called before any JAX computations" / "already initialized"
# family).  Retrying cannot help (the backend stays touched), so these
# degrade immediately instead of burning the whole retry window asleep;
# everything else (dead/slow coordinator) gets the bounded retries.
_UNRECOVERABLE_INIT_MARKERS = (
    "before any JAX computations",
    "already initialized",
    "backend and platform",
)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> None:
    """Join the multi-process JAX runtime (idempotent).

    With no arguments, relies on the TPU pod environment (JAX autodetects
    coordinator/process topology on Cloud TPU); arguments override for
    manual GPU/CPU multi-process setups.  A second call, or a call in a
    plainly single-process environment, is a no-op — so library code can
    call this unconditionally.

    Coordinator-join failures (dead/slow coordinator, the injected
    ``distributed.init`` fault) retry with exponential backoff under the
    ``QI_DIST_INIT_TIMEOUT_S`` budget before degrading to single-process —
    and the degrade is LOUD: a warning plus a ``distributed.init_degraded``
    run-record event naming the cause and attempt count, because a 256-chip
    job silently running on one host is the expensive kind of "working".
    """
    global _initialized
    if _initialized:
        return
    import jax

    # Probe whether a launcher already brought the distributed runtime up
    # WITHOUT touching the XLA backend: jax.process_count() would initialize
    # backends and then guarantee jax.distributed.initialize() below raises.
    # Public API (jax ≥ 0.4.15); older jaxes fall through to the try/except
    # around initialize below, which degrades loudly rather than silently.
    if getattr(jax.distributed, "is_initialized", None) is not None:
        if jax.distributed.is_initialized():
            _initialized = True
            return
    if coordinator_address is None and num_processes is None:
        # No explicit topology and no multi-host pod environment ⇒ single
        # process.  TPU_WORKER_HOSTNAMES counts only with >1 entry (tunneled
        # single-chip images export it as "localhost").
        workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        multihost_env = len([w for w in workers.split(",") if w.strip()]) > 1 or any(
            k in os.environ
            for k in ("MEGASCALE_COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS")
        )
        if not multihost_env:
            log.debug("single-process environment; distributed init skipped")
            _initialized = True
            return
    deadline = time.monotonic() + qi_env_float("QI_DIST_INIT_TIMEOUT_S", 20.0)
    attempt = 0
    rec = get_run_record()
    # One span over the whole join (qi-trace): every retry and the degrade
    # land inside it, and the worker's RunRecord has already adopted the
    # launcher's trace_id when QI_TRACE_CONTEXT rides the job environment —
    # a pod's worth of workers stitches into one timeline.
    with rec.span("distributed.init") as init_span:
        while True:
            attempt += 1
            try:
                fault_point("distributed.init")
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    local_device_ids=local_device_ids,
                )
                if attempt > 1:
                    log.info(
                        "distributed init succeeded on attempt %d", attempt
                    )
                init_span.set(outcome="joined")
                break
            except RuntimeError as exc:
                # Two causes share this exception: the XLA backend was
                # already touched before init (unrecoverable — degrade NOW,
                # retrying only wastes the window), and a coordinator that
                # is down or still coming up (recoverable — the case the
                # bounded retry exists for).
                unrecoverable = any(
                    marker in str(exc)
                    for marker in _UNRECOVERABLE_INIT_MARKERS
                )
                delay = min(
                    _INIT_BACKOFF_S * (2 ** (attempt - 1)),
                    _INIT_BACKOFF_CAP_S,
                )
                if not unrecoverable and time.monotonic() + delay < deadline:
                    log.info(
                        "distributed init failed (attempt %d: %s); retrying "
                        "in %.1fs", attempt, exc, delay,
                    )
                    _retry_sleep(delay)
                    continue
                # Budget burned: proceeding single-process is the only
                # option left; make it loud AND machine-readable.
                log.warning(
                    "distributed init unavailable after %d attempt(s) (%s); "
                    "continuing single-process", attempt, exc,
                )
                rec.event(
                    "distributed.init_degraded", cause=str(exc),
                    attempts=attempt,
                )
                init_span.set(outcome="degraded")
                break
        init_span.set(attempts=attempt)
    _initialized = True
    log.info(
        "distributed runtime up: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), len(jax.devices()),
    )


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1


def global_candidate_mesh(axis_name: str = CANDIDATE_AXIS):
    """1-D mesh over ALL global devices, ordered host-major.

    Host-major order (`jax.devices()` is already process-grouped) keeps each
    host's contiguous run of candidate blocks on its own local devices — the
    index→device mapping never makes DCN carry anything except the final
    scalar reduction.
    """
    import jax

    return candidate_mesh(devices=list(jax.devices()), axis_name=axis_name)


def hybrid_candidate_mesh(axis_name: str = CANDIDATE_AXIS):
    """Like :func:`global_candidate_mesh` but orders devices via
    ``mesh_utils.create_hybrid_device_mesh`` (ICI-adjacent within a slice,
    DCN across slices) before flattening into the single candidate axis.
    Falls back to the plain global mesh when topology metadata is
    unavailable (CPU emulation, single slice)."""
    import jax

    try:
        from jax.experimental import mesh_utils

        devs = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(len(jax.local_devices()),),
            dcn_mesh_shape=(jax.process_count(),),
            devices=jax.devices(),
        )
        from jax.sharding import Mesh

        return Mesh(np.asarray(devs).reshape(-1), axis_names=(axis_name,))
    except Exception as exc:  # noqa: BLE001 - topology metadata absent
        log.debug("hybrid mesh unavailable (%s); using global mesh", exc)
        return global_candidate_mesh(axis_name)
