"""Device-mesh / sharding helpers for the candidate-sweep axis."""

from quorum_intersection_tpu.parallel.mesh import candidate_mesh, shard_map_fn

__all__ = ["candidate_mesh", "shard_map_fn"]
