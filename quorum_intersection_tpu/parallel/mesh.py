"""Mesh construction and sharding helpers.

The reference has no distributed backend at all (SURVEY.md §5) — its scaling
axis is pruning.  The TPU-native equivalent of a distributed communication
backend is a ``jax.sharding.Mesh`` over the **candidate-subset axis** (the
2^n space of node subsets): each chip evaluates a contiguous block of
candidate indices, and the only cross-chip communication is an OR/min
reduction over per-shard hit flags — one scalar collective per sweep step,
riding ICI (or DCN across slices) via ``shard_map`` + ``lax.pmin``.

All helpers work identically on real TPU meshes and on the CPU host-platform
emulation used in tests (``--xla_force_host_platform_device_count=8``).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

try:  # JAX ≥ 0.4.31 exports shard_map at the top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore

P = PartitionSpec

CANDIDATE_AXIS = "candidates"


def candidate_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    axis_name: str = CANDIDATE_AXIS,
) -> Mesh:
    """1-D mesh over the candidate axis.

    Uses all visible devices by default; ``n_devices`` takes a prefix (handy
    for tests that want a mesh smaller than the emulated device count).
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), axis_names=(axis_name,))


def shard_map_fn(
    fn: Callable,
    mesh: Mesh,
    in_specs,
    out_specs,
    checked: bool = True,
) -> Callable:
    """Thin wrapper over ``jax.shard_map`` pinned to our mesh conventions.

    ``checked=False`` disables the static replication check — for programs
    whose outputs are numerically replicated but varying-MARKED (e.g.
    rank-seeded while_loop carries, frontier.py), which the checker cannot
    infer through the loop.  The disabling kwarg is feature-detected
    (``check_vma`` on current JAX, ``check_rep`` on older releases, absent
    on the oldest) so this module's version fallback keeps working across
    the unversioned jax dependency."""
    kwargs = {}
    if not checked:
        import inspect

        try:
            params = inspect.signature(shard_map).parameters
        except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
            params = {}
        if "check_vma" in params:
            kwargs = {"check_vma": False}
        elif "check_rep" in params:
            kwargs = {"check_rep": False}
        # else: very old jax — no check to disable
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def shard_map_unchecked(fn: Callable, mesh: Mesh, in_specs, out_specs) -> Callable:
    """Back-compat alias for ``shard_map_fn(..., checked=False)``."""
    return shard_map_fn(fn, mesh, in_specs, out_specs, checked=False)
