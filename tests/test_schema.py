"""Frontend schema tests: parsing, validation errors, fixture ground truth.

Fixture structural numbers come from SURVEY.md §4.1 (verified during the
survey session against the reference fixtures).
"""

import json

import pytest

from quorum_intersection_tpu.fbas.schema import (
    FbasSchemaError,
    NULL_QSET,
    QSet,
    parse_fbas,
)


def test_parse_minimal():
    fbas = parse_fbas(
        '[{"publicKey": "A", "name": "alice", '
        '"quorumSet": {"threshold": 1, "validators": ["A"], "innerQuorumSets": []}}]'
    )
    assert len(fbas) == 1
    assert fbas[0].public_key == "A"
    assert fbas[0].name == "alice"
    assert fbas[0].qset == QSet(threshold=1, validators=("A",))
    assert fbas.label(0) == "alice"


def test_name_optional_defaults_empty():
    fbas = parse_fbas('[{"publicKey": "A", "quorumSet": null}]')
    assert fbas[0].name == ""
    assert fbas.label(0) == "A"  # label falls back to publicKey (cpp:507)


def test_null_and_empty_qset_are_null():
    fbas = parse_fbas(
        '[{"publicKey": "A", "quorumSet": null},'
        ' {"publicKey": "B", "quorumSet": {}}]'
    )
    assert fbas[0].qset is NULL_QSET
    assert fbas[1].qset is NULL_QSET
    assert fbas[0].qset.is_null


def test_nested_inner_sets():
    fbas = parse_fbas(
        json.dumps(
            [
                {
                    "publicKey": "A",
                    "quorumSet": {
                        "threshold": 2,
                        "validators": ["A"],
                        "innerQuorumSets": [
                            {
                                "threshold": 1,
                                "validators": ["B"],
                                "innerQuorumSets": [
                                    {"threshold": 1, "validators": ["C"]}
                                ],
                            }
                        ],
                    },
                }
            ]
        )
    )
    q = fbas[0].qset
    assert q.max_depth() == 2
    assert list(q.all_validator_refs()) == ["A", "B", "C"]
    assert q.member_count() == 2


def test_ignored_extra_keys():
    fbas = parse_fbas(
        '[{"publicKey": "A", "updatedAt": "2020-01-01", '
        '"quorumSet": {"threshold": 1, "validators": ["A"], "hashKey": "zzz"}}]'
    )
    assert fbas[0].qset.threshold == 1


def test_falsy_wrong_typed_fields_rejected():
    # Regression: `x or ()` used to coerce falsy wrong types (0, false, "") to
    # the empty list instead of raising.
    with pytest.raises(FbasSchemaError, match="validators"):
        parse_fbas('[{"publicKey": "A", "quorumSet": {"threshold": 1, "validators": 0}}]')
    with pytest.raises(FbasSchemaError, match="innerQuorumSets"):
        parse_fbas('[{"publicKey": "A", "quorumSet": {"threshold": 1, "innerQuorumSets": false}}]')


def test_numeric_string_threshold_accepted():
    # boost::property_tree stores scalars as strings; keep input compat.
    fbas = parse_fbas('[{"publicKey": "A", "quorumSet": {"threshold": "2", "validators": []}}]')
    assert fbas[0].qset.threshold == 2


@pytest.mark.parametrize(
    "doc,msg",
    [
        ('{"publicKey": "A"}', "array"),
        ('[{"name": "x", "quorumSet": null}]', "publicKey"),
        ('[{"publicKey": "A"}]', "quorumSet"),
        ('[{"publicKey": "A", "quorumSet": {"validators": ["A"]}}]', "threshold"),
        ('[{"publicKey": "A", "quorumSet": {"threshold": 1, "validators": "A"}}]', "validators"),
        ('[{"publicKey": "A", "quorumSet": null}, {"publicKey": "A", "quorumSet": null}]', "duplicate"),
    ],
)
def test_schema_errors(doc, msg):
    with pytest.raises(FbasSchemaError, match=msg):
        parse_fbas(doc)


def test_reference_fixture_counts(ref_fixture):
    """Node and null-qset counts match SURVEY.md §4.1 [verified] numbers."""
    expectations = {
        "correct_trivial.json": (3, 0),
        "broken_trivial.json": (3, 0),
        "correct.json": (74, 26),
        "broken.json": (78, 28),
    }
    for name, (n_nodes, n_null) in expectations.items():
        with open(ref_fixture(name)) as f:
            fbas = parse_fbas(f)
        assert len(fbas) == n_nodes
        assert sum(1 for node in fbas if node.qset.is_null) == n_null
