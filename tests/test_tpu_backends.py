"""TPU backend verdict tests: golden fixtures, differential vs the Python
oracle on synthetic networks, witnesses, checkpointing, size limits."""

import numpy as np
import pytest

from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
from quorum_intersection_tpu.backends.tpu.sweep import SccTooLargeError, TpuSweepBackend
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.semantics import is_quorum
from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas, random_fbas
from quorum_intersection_tpu.pipeline import solve


@pytest.fixture(params=["tpu-sweep", "tpu-frontier"])
def tpu_backend(request):
    if request.param == "tpu-sweep":
        return TpuSweepBackend(batch=512)
    return TpuFrontierBackend(arena=4096, pop=128)


def make_recording_ckpt(path):
    """SweepCheckpoint that records every record() payload and the
    fingerprints resume_position() sees — lets tests learn the true problem
    fingerprint (cleared files don't survive completion) and forge mid-run
    preemptions.  Built lazily because SweepCheckpoint is a dataclass."""
    from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

    class RecordingCkpt(SweepCheckpoint):
        def __post_init__(self):
            super().__post_init__()
            self.history = []
            self.fps = []

        def record(self, position, total, fingerprint=None):
            self.history.append((position, total, fingerprint))
            super().record(position, total, fingerprint)

        def resume_position(self, total, fingerprint=None, **kw):
            self.fps.append(fingerprint)
            return super().resume_position(total, fingerprint, **kw)

    return RecordingCkpt(path)


class TestGoldenFixtures:
    @pytest.mark.parametrize(
        "name,expected",
        [
            ("correct_trivial.json", True),
            ("broken_trivial.json", False),
            ("correct.json", True),
            ("broken.json", False),
        ],
    )
    def test_verdicts(self, ref_fixture, tpu_backend, name, expected):
        with open(ref_fixture(name)) as f:
            res = solve(f.read(), backend=tpu_backend)
        assert res.intersects is expected

    def test_broken_witness_is_valid(self, ref_fixture, tpu_backend):
        with open(ref_fixture("broken.json")) as f:
            data = f.read()
        res = solve(data, backend=tpu_backend)
        assert not res.intersects
        g = build_graph(parse_fbas(data))
        assert res.q1 and res.q2
        assert not (set(res.q1) & set(res.q2))
        assert is_quorum(g, res.q1)
        assert is_quorum(g, res.q2)


class TestDifferentialVsOracle:
    """CPU-vs-TPU differential on synthetic random FBAS — the test strategy
    the reference never had (SURVEY.md §4.3 item 2)."""

    @pytest.mark.parametrize("seed", range(8))
    def test_random_fbas_verdict_parity(self, seed, tpu_backend):
        data = random_fbas(
            14, seed=seed, nested_prob=0.3, null_prob=0.1, dangling_prob=0.1
        )
        want = solve(data, backend="python")
        got = solve(data, backend=tpu_backend)
        assert got.intersects is want.intersects, f"seed={seed}"

    @pytest.mark.parametrize("n", [4, 7, 10, 13])
    def test_majority_pairs(self, n, tpu_backend):
        assert solve(majority_fbas(n), backend=tpu_backend).intersects is True
        assert solve(majority_fbas(n, broken=True), backend=tpu_backend).intersects is False

    def test_hierarchical_pairs(self, tpu_backend):
        assert solve(hierarchical_fbas(3, 3), backend=tpu_backend).intersects is True
        assert (
            solve(hierarchical_fbas(3, 3, broken=True), backend=tpu_backend).intersects
            is False
        )

    @pytest.mark.parametrize("scope", [False, True])
    def test_scoping_parity(self, scope, tpu_backend):
        for seed in (2, 5):
            data = random_fbas(12, seed=seed, null_prob=0.2)
            want = solve(data, backend="python", scope_to_scc=scope)
            got = solve(data, backend=tpu_backend, scope_to_scc=scope)
            assert got.intersects is want.intersects


class TestSweepSpecifics:
    def test_scc_too_large_raises(self):
        backend = TpuSweepBackend(max_bits=4)
        data = majority_fbas(8)
        with pytest.raises(SccTooLargeError):
            solve(data, backend=backend)

    def test_auto_falls_back_beyond_sweep_limit(self):
        from quorum_intersection_tpu.backends.auto import AutoBackend

        backend = AutoBackend(sweep_limit=4)
        res = solve(majority_fbas(9), backend=backend)
        assert res.intersects is True
        assert res.stats["backend"] in ("python", "cpp")

    def test_checkpoint_resume(self, tmp_path):
        ckpt = make_recording_ckpt(tmp_path / "sweep.json")
        # Small batches force multiple steps on a safe network so the
        # checkpoint records progress (broken ones exit on the first hit).
        backend = TpuSweepBackend(batch=16, checkpoint=ckpt)
        data = majority_fbas(9)
        res = solve(data, backend=backend)
        assert res.intersects
        assert ckpt.history
        # finished runs clear their checkpoint
        assert ckpt.resume_position(1 << 8) == 0

        # simulate a preempted run: re-record a midpoint with the true
        # fingerprint; the resumed sweep skips the prefix
        total = 1 << 8
        fingerprint = ckpt.history[-1][2]
        ckpt.record(128, total, fingerprint)
        backend2 = TpuSweepBackend(batch=16, checkpoint=ckpt)
        res2 = solve(data, backend=backend2)
        assert res2.intersects
        assert res2.stats["candidates_checked"] <= total - 128 + 16

        # a checkpoint from a DIFFERENT problem with the same enumeration
        # size must be ignored — resuming it could skip the witness
        ckpt.record(128, total, "bogus-fingerprint")
        backend3 = TpuSweepBackend(batch=16, checkpoint=ckpt)
        res3 = solve(data, backend=backend3)
        assert res3.intersects
        assert res3.stats["candidates_checked"] >= total

    def test_checkpoint_total_mismatch_ignored(self, tmp_path):
        from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

        ckpt = SweepCheckpoint(tmp_path / "sweep.json")
        ckpt.record(100, 999)
        assert ckpt.resume_position(256) == 0

    def test_checkpoint_fingerprint_mismatch_ignored(self, tmp_path):
        from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

        ckpt = SweepCheckpoint(tmp_path / "sweep.json")
        ckpt.record(100, 256, "aaaa")
        assert ckpt.resume_position(256, "bbbb") == 0
        assert ckpt.resume_position(256, "aaaa") == 100
        # legacy/fingerprint-free lookups still work
        assert ckpt.resume_position(256) == 100

    def test_checkpoint_legacy_fingerprint_accepted(self, tmp_path):
        # A file written under an older hash format resumes when the caller
        # names that hash as an accepted alternate (ADVICE r4: format
        # widening must not discard long-run progress).
        from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

        ckpt = SweepCheckpoint(tmp_path / "sweep.json")
        ckpt.record(100, 256, "old-format-hash")
        assert ckpt.resume_position(256, "new", alt_fingerprints=("other",)) == 0
        assert ckpt.resume_position(
            256, "new", alt_fingerprints=("old-format-hash",)
        ) == 100

    def test_sweep_resumes_pre_r4_checkpoint(self, tmp_path, monkeypatch):
        # End-to-end: forge the checkpoint a pre-r4 build would have left
        # (6-array fingerprint, no D-thresholds field) and verify today's
        # sweep resumes from it instead of restarting at zero.
        import quorum_intersection_tpu.utils.checkpoint as ckpt_mod

        ckpt = make_recording_ckpt(tmp_path / "sweep.json")
        data = majority_fbas(9)
        orig = ckpt_mod.sweep_fingerprint
        seen = []
        monkeypatch.setattr(
            ckpt_mod, "sweep_fingerprint",
            lambda *arrays: seen.append(arrays) or orig(*arrays),
        )
        res = solve(data, backend=TpuSweepBackend(batch=16, checkpoint=ckpt))
        assert res.intersects
        full = [a for a in seen if len(a) == 7]
        assert full, "sweep no longer hashes the 7-field fingerprint"
        legacy_fp = orig(*full[-1][:6])  # what a pre-r4 build wrote
        total = 1 << 8
        ckpt.record(128, total, legacy_fp)
        res2 = solve(data, backend=TpuSweepBackend(batch=16, checkpoint=ckpt))
        assert res2.intersects
        assert res2.stats["candidates_checked"] <= total - 128 + 16

    def test_single_node_scc(self):
        data = [{"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["A"]}}]
        res = solve(data, backend=TpuSweepBackend())
        assert res.intersects is True

    def test_throughput_stats_present(self):
        res = solve(majority_fbas(8), backend=TpuSweepBackend(batch=64))
        for key in ("candidates_checked", "device_steps", "candidates_per_sec", "seconds"):
            assert key in res.stats


class TestHybridRetirement:
    """The round-trip hybrid engine was retired in r5 (lost 100-1000x at
    every measured size, crossover artifacts r3-r5).  Its name must fail
    LOUDLY with the successor spelled out — not silently re-route."""

    def test_get_backend_names_the_successor(self):
        from quorum_intersection_tpu.backends.base import get_backend

        with pytest.raises(ValueError, match="tpu-frontier"):
            get_backend("tpu-hybrid")

    def test_cli_rejects_retired_backend(self, ref_fixture):
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, "-m", "quorum_intersection_tpu",
             "--backend", "tpu-hybrid"],
            input=ref_fixture("correct_trivial.json").read_text(),
            capture_output=True, text=True, timeout=60,
        )
        # Reference error contract (cli.py): "Invalid option!" + usage on
        # stdout, exit 1 — and the usage line shows the surviving choices.
        assert proc.returncode == 1
        assert "Invalid option!" in proc.stdout
        assert "tpu-frontier" in proc.stdout
        assert "tpu-hybrid" not in proc.stdout


class TestWideSweep:
    """Two-level (hi|lo) decode: enumeration wider than the on-device int32
    index space, exercised at tiny widths via lo_bits override."""

    @pytest.mark.parametrize("broken", [False, True])
    def test_verdict_parity_narrow_vs_wide(self, broken):
        data = majority_fbas(12, broken=broken)
        narrow = solve(data, backend=TpuSweepBackend(batch=64))
        wide = solve(data, backend=TpuSweepBackend(batch=64, lo_bits=6))
        assert narrow.intersects == wide.intersects == (not broken)
        if broken:
            # identical global index order ⇒ identical first-hit witness
            assert wide.q1 == narrow.q1
            assert wide.q2 == narrow.q2
            assert not set(wide.q1) & set(wide.q2)

    def test_wide_hierarchical_safe(self):
        # nested inner sets through the two-level decode
        data = hierarchical_fbas(4, 3)
        res = solve(data, backend=TpuSweepBackend(batch=32, lo_bits=5))
        assert res.intersects is True

    def test_wide_in_scc_witness(self):
        # majority break keeps the disjoint pair inside one SCC, so the
        # wide search itself (not the SCC guard) must produce the witness
        data = majority_fbas(14, broken=True)
        res = solve(data, backend=TpuSweepBackend(batch=64, lo_bits=7))
        assert res.intersects is False
        assert res.q1 and res.q2 and not set(res.q1) & set(res.q2)

    def test_wide_safe_counts_every_candidate(self):
        data = majority_fbas(11)
        res = solve(data, backend=TpuSweepBackend(batch=64, lo_bits=4))
        assert res.intersects is True
        assert res.stats["enumeration_total"] == 1 << 10
        assert res.stats["candidates_checked"] >= 1 << 10

    def test_wide_checkpoint_roundtrip(self, tmp_path):
        from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

        ckpt = SweepCheckpoint(tmp_path / "wide.json")
        data = majority_fbas(11)
        res = solve(data, backend=TpuSweepBackend(batch=16, lo_bits=4, checkpoint=ckpt))
        assert res.intersects is True
        assert not ckpt.path.exists()  # cleared on completion

    def test_wide_sharded_mesh(self):
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs multi-device mesh")
        from quorum_intersection_tpu.parallel.mesh import candidate_mesh

        mesh = candidate_mesh(2)
        data = majority_fbas(11, broken=True)
        res = solve(data, backend=TpuSweepBackend(batch=32, lo_bits=5, mesh=mesh))
        assert res.intersects is False
        assert res.q1 and res.q2


class TestIndexCeilingGuards:
    """int32 decode-ceiling hardening (advisor finding): user-supplied
    batch/lo_bits must never let device indices wrap past 2^31."""

    def test_lo_bits_over_ceiling_rejected(self):
        with pytest.raises(ValueError, match="int32 decode ceiling"):
            TpuSweepBackend(lo_bits=31)

    def test_batch_clamp_arithmetic(self):
        from quorum_intersection_tpu.backends.tpu.sweep import (
            STEPS_RAMP,
            clamp_batch_to_index_ceiling,
        )

        lo_total = 1 << 30
        clamped = clamp_batch_to_index_ceiling(1 << 22, lo_total)
        # largest possible program must stay below 2^31
        assert lo_total + STEPS_RAMP[-1] * clamped <= 1 << 31
        # in-range batches pass through untouched
        assert clamp_batch_to_index_ceiling(1 << 19, lo_total) == 1 << 19
        assert clamp_batch_to_index_ceiling(64, 1 << 11) == 64

    def test_oversized_batch_still_correct(self):
        # A batch beyond the ceiling is clamped, not wrapped: verdict and
        # witness stay correct.
        data = majority_fbas(12, broken=True)
        res = solve(data, backend=TpuSweepBackend(batch=1 << 22))
        assert res.intersects is False
        assert res.q1 and res.q2 and not set(res.q1) & set(res.q2)


class TestPreferTpuRouting:
    """`--backend tpu` stays routing-honest: large SCCs outside every
    measured win region go to the host oracle on all platforms."""

    def test_prefer_tpu_on_cpu_routes_to_host_oracle(self, monkeypatch):
        import quorum_intersection_tpu.utils.platform as plat
        from quorum_intersection_tpu.backends.auto import AutoBackend

        monkeypatch.setattr(plat, "is_cpu_platform", lambda: True)
        auto = AutoBackend(prefer_tpu=True, sweep_limit=4)
        called = []
        orig = auto._cpu_oracle

        def spy():
            called.append(True)
            return orig()

        monkeypatch.setattr(auto, "_cpu_oracle", spy)
        res = solve(majority_fbas(9), backend=auto)
        assert res.intersects is True
        assert called  # host oracle used, no device engine


class TestLatencyAwareRouting:
    """Oracle-first auto routing (VERDICT r2 §next-3): small SCCs get the
    pruned oracle with a sweep-cost call budget; budget burns fall back to
    the exhaustive sweep; verdicts never change, only latency."""

    def test_small_scc_routes_to_oracle_first(self):
        from quorum_intersection_tpu.backends.auto import AutoBackend

        res = solve(majority_fbas(9), backend=AutoBackend())
        assert res.intersects is True
        assert res.stats["backend"] in ("cpp", "python")

    def test_snapshot_time_to_verdict_is_oracle_fast(self):
        import time

        from quorum_intersection_tpu.backends.auto import AutoBackend
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        data = stellar_like_fbas()  # ~150 validators, 21-node core SCC
        t0 = time.perf_counter()
        res = solve(data, backend=AutoBackend())
        seconds = time.perf_counter() - t0
        assert res.intersects is True
        assert res.stats["backend"] in ("cpp", "python")
        # The whole point: no sweep compile/dispatch on the verdict path.
        assert seconds < 5, f"snapshot verdict took {seconds:.1f}s"

    def test_budget_burn_falls_back_to_sweep(self, monkeypatch):
        import quorum_intersection_tpu.backends.auto as auto_mod

        monkeypatch.setattr(auto_mod, "MIN_ORACLE_BUDGET", 1)
        monkeypatch.setattr(
            auto_mod.AutoBackend, "_estimated_sweep_seconds", lambda self, s: 0.0
        )
        backend = auto_mod.AutoBackend()
        res = solve(majority_fbas(9), backend=backend)
        assert res.intersects is True
        assert res.stats["backend"] == "tpu-sweep"
        res = solve(majority_fbas(9, broken=True), backend=backend)
        assert res.intersects is False
        assert res.q1 and res.q2 and not set(res.q1) & set(res.q2)

    def test_budgeted_oracle_verdict_identical_under_budget(self):
        # A generous budget must not perturb the search at all: stats
        # lockstep with the unbudgeted oracle.
        from quorum_intersection_tpu.backends.python_oracle import PythonOracleBackend

        data = majority_fbas(10)
        plain = solve(data, backend=PythonOracleBackend())
        budgeted = solve(data, backend=PythonOracleBackend(budget_calls=10**9))
        assert plain.intersects is budgeted.intersects is True
        assert plain.stats["bnb_calls"] == budgeted.stats["bnb_calls"]

    def test_python_oracle_budget_exceeded_raises(self):
        from quorum_intersection_tpu.backends.base import OracleBudgetExceeded
        from quorum_intersection_tpu.backends.python_oracle import PythonOracleBackend

        with pytest.raises(OracleBudgetExceeded):
            solve(majority_fbas(12), backend=PythonOracleBackend(budget_calls=5))

    def test_cpp_oracle_budget_exceeded_raises(self):
        from quorum_intersection_tpu.backends.base import OracleBudgetExceeded
        from quorum_intersection_tpu.backends.cpp import CppOracleBackend

        backend = CppOracleBackend(budget_calls=5)
        try:
            backend.ensure_built()
        except Exception as exc:  # noqa: BLE001
            pytest.skip(f"native oracle unavailable: {exc}")
        with pytest.raises(OracleBudgetExceeded):
            solve(majority_fbas(12), backend=backend)

    def test_existing_checkpoint_skips_oracle_first(self, tmp_path):
        # A preempted sweep's progress must resume directly — re-burning
        # the oracle budget on every restart would tax exactly the long
        # runs checkpoints exist for.
        from quorum_intersection_tpu.backends.auto import AutoBackend
        from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

        data = majority_fbas(9)
        ck = SweepCheckpoint(tmp_path / "sweep.ckpt")
        ck.record(16, 1 << 8)  # recorded progress for this enumeration size
        res = solve(data, backend=AutoBackend(checkpoint=ck))
        assert res.intersects is True
        assert res.stats["backend"] == "tpu-sweep"  # not the oracle

    def test_malformed_frontier_checkpoint_ignored(self, tmp_path):
        import json as _json

        from quorum_intersection_tpu.backends.tpu.frontier import (
            FrontierSearchInterrupted,
        )
        from quorum_intersection_tpu.utils.checkpoint import FrontierCheckpoint

        data = majority_fbas(12)
        ck = FrontierCheckpoint(tmp_path / "frontier.ckpt")
        with pytest.raises(FrontierSearchInterrupted):
            solve(data, backend=TpuFrontierBackend(
                arena=2048, pop=32, checkpoint=ck,
                interrupt_after_chunks=1, chunk_iters=2))
        # Corrupt the states while keeping the fingerprint valid: the file
        # must be ignored (fresh search), never crash the run.
        payload = _json.loads(ck.path.read_text())
        payload["states"] = [["not-a-pair"]]
        ck.path.write_text(_json.dumps(payload))
        res = solve(data, backend=TpuFrontierBackend(
            arena=2048, pop=32, checkpoint=ck))
        assert res.intersects is True
        assert "resumed_states" not in res.stats


class TestRampJump:
    """Deterministic coverage for the async ramp-jump state machine
    (sweep.py): inline and dead fake threads replace the compile thread so
    every branch — jump-on-landed, failed-compile inline fallback — runs
    without timing races."""

    class _InlineThread:
        """start() runs the work synchronously; the next loop iteration
        sees the registered dispatcher and jumps."""

        def __init__(self, *a, **k):
            self._target = k.get("target")

        def start(self):
            self._target()

        def is_alive(self):
            return False

    class _DeadThread:
        """Never runs the work: simulates a failed async compile — the
        driver must jump anyway and compile inline."""

        def __init__(self, *a, **k):
            pass

        def start(self):
            pass

        def is_alive(self):
            return False

    def test_jump_engages_with_verdict_parity(self, monkeypatch):
        import quorum_intersection_tpu.backends.tpu.sweep as sweep_mod

        monkeypatch.setattr(sweep_mod, "_thread_factory", self._InlineThread)
        res = solve(majority_fbas(15), backend=TpuSweepBackend(batch=64))
        assert res.intersects is True
        assert res.stats["steady_level"] > 1
        assert res.stats["candidates_checked"] >= res.stats["enumeration_total"]

    def test_jump_broken_network_witness(self, monkeypatch):
        import quorum_intersection_tpu.backends.tpu.sweep as sweep_mod

        monkeypatch.setattr(sweep_mod, "_thread_factory", self._InlineThread)
        data = majority_fbas(15, broken=True)
        single = solve(data, backend=TpuSweepBackend(batch=64))
        assert single.intersects is False
        assert single.q1 and single.q2 and not set(single.q1) & set(single.q2)

    def test_failed_async_compile_jumps_inline(self, monkeypatch):
        import quorum_intersection_tpu.backends.tpu.sweep as sweep_mod

        monkeypatch.setattr(sweep_mod, "_thread_factory", self._DeadThread)
        res = solve(majority_fbas(15), backend=TpuSweepBackend(batch=64))
        assert res.intersects is True
        assert res.stats["steady_level"] > 1  # sync jump still happened
        assert res.stats["candidates_checked"] >= res.stats["enumeration_total"]

    def test_wide_chunked_sweep_with_jump_and_tails(self):
        """Two-level decode with many outer chunks (small lo_bits) through
        the new jump/tail-shape selection: verdict parity on both twins and
        vs the oracle on random nets."""
        from quorum_intersection_tpu.fbas.synth import random_fbas as _rf

        for data, want in (
            (majority_fbas(13), True),
            (majority_fbas(13, broken=True), False),
        ):
            res = solve(data, backend=TpuSweepBackend(batch=16, lo_bits=6))
            assert res.intersects is want
        for seed in (2, 11):
            data = _rf(12, seed=seed, nested_prob=0.4)
            a = solve(data, backend="python").intersects
            b = solve(data, backend=TpuSweepBackend(batch=16, lo_bits=5)).intersects
            assert a is b


def test_frontier_real_sigkill_resume(tmp_path):
    """True process-death resume: SIGKILL the CLI mid-search once the
    checkpoint file appears on disk, then resume in a fresh process —
    verdict parity and recorded-progress reuse (stats: resumed_states)."""
    import json as _json
    import os
    import signal
    import subprocess
    import sys
    import time as _time

    ck = tmp_path / "frontier.ckpt"
    env = dict(os.environ, QI_FRONTIER_CKPT_INTERVAL_S="0.05")
    data = _json.dumps(majority_fbas(16))
    cmd = [sys.executable, "-m", "quorum_intersection_tpu",
           "--backend", "tpu-frontier", "--checkpoint", str(ck), "--timing"]
    proc = subprocess.Popen(
        cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, env=env,
    )
    try:
        proc.stdin.write(data)
        proc.stdin.close()
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline:
            if ck.exists():
                break
            if proc.poll() is not None:
                break
            _time.sleep(0.05)
        if proc.poll() is None:
            assert ck.exists(), "no checkpoint appeared within the window"
            proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()  # never orphan the solver on an assert/timeout path
            proc.wait()
    if proc.returncode == 0:
        # Completed before the kill landed (fast machine): the checkpoint is
        # already cleared, so there is nothing to resume — verdict parity is
        # all this run can assert.
        assert proc.stdout.read().strip() == "true"
        return

    resumed = subprocess.run(
        cmd, input=data, capture_output=True, text=True, env=env, timeout=600,
    )
    assert resumed.stdout.strip() == "true"
    assert resumed.returncode == 0
    assert "resumed_states" in resumed.stderr  # [stats] line: progress reused
    assert not ck.exists()  # cleared on completion


class TestWideResumeInvariance:
    """VERDICT r4 item 6 (regression half): checkpoint positions are
    ABSOLUTE candidate indices — a sweep preempted under one geometry
    (batch, lo_bits) must resume correctly under another, at hi-bits > 4,
    without skipping or double-claiming candidates (sweep.py chunk-boundary
    recording)."""

    def test_safe_resume_across_geometry_change(self, tmp_path):
        # 13 nodes -> 12 enumeration bits; lo_bits=5 leaves 7 hi bits on the
        # first run, lo_bits=7 leaves 5 on the resume — both > 4, and 2064
        # is a chunk boundary of the OLD geometry only (2064 % 128 != 0).
        data = majority_fbas(13)
        total = 1 << 12
        ck = make_recording_ckpt(tmp_path / "wide.json")
        res = solve(data, backend=TpuSweepBackend(batch=16, lo_bits=5, checkpoint=ck))
        assert res.intersects is True
        fp = ck.history[-1][2]
        pos = 2064
        ck.record(pos, total, fp)
        res2 = solve(data, backend=TpuSweepBackend(batch=32, lo_bits=7, checkpoint=ck))
        assert res2.intersects is True
        # Exactly the unclaimed suffix is swept (small slack for a tail
        # program's alias overshoot).
        assert total - pos <= res2.stats["candidates_checked"] <= total - pos + 64

    def test_broken_resume_geometry_change_finds_same_witness(self, tmp_path):
        # Knob on node 0 puts the first hit at absolute index 127 (measured,
        # deterministic: tarjan order + enumeration order are fixed);
        # resuming past a clean prefix (112 < 127) under a DIFFERENT
        # geometry must find the SAME first hit.
        data = majority_fbas(13)
        data[0]["quorumSet"]["threshold"] = 1
        total = 1 << 12
        ck = make_recording_ckpt(tmp_path / "wide_broken.json")
        base = solve(data, backend=TpuSweepBackend(batch=16, lo_bits=5, checkpoint=ck))
        assert base.intersects is False
        hit = base.stats["hit_index"]
        assert hit == 127  # construction guard: late enough to resume past 112
        ck.record(112, total, ck.fps[-1])
        res = solve(data, backend=TpuSweepBackend(batch=32, lo_bits=7, checkpoint=ck))
        assert res.intersects is False
        assert res.stats["hit_index"] == hit
        assert res.q1 and res.q2 and not set(res.q1) & set(res.q2)


class TestSccRestriction:
    """Device searches on graphs wider than the SCC run on the restricted
    circuit (encode.restrict_circuit_pair) — verdicts, witnesses, and
    minimal-quorum counts must be indistinguishable from the host oracle."""

    def test_sweep_safe_broken_and_wide(self):
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
        from quorum_intersection_tpu.fbas.synth import benchmark_fbas

        safe = benchmark_fbas(48, 12, seed=3)
        broken = benchmark_fbas(48, 12, broken=True, seed=3)
        assert solve(safe, backend=TpuSweepBackend(batch=256)).intersects is True
        res = solve(broken, backend=TpuSweepBackend(batch=256))
        assert res.intersects is False
        assert res.q1 and res.q2 and not set(res.q1) & set(res.q2)
        assert is_quorum(build_graph(parse_fbas(broken)), res.q1)
        # hi-bits path through the restricted decode
        wide = solve(
            benchmark_fbas(48, 14, seed=7),
            backend=TpuSweepBackend(batch=32, lo_bits=6),
        )
        assert wide.intersects is True

    @pytest.mark.parametrize("scope", [False, True])
    def test_sweep_scoping_parity(self, scope):
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
        from quorum_intersection_tpu.fbas.synth import benchmark_fbas

        data = benchmark_fbas(48, 12, seed=3)
        want = solve(data, backend="python", scope_to_scc=scope)
        got = solve(data, backend=TpuSweepBackend(batch=256), scope_to_scc=scope)
        assert got.intersects is want.intersects

    @pytest.mark.parametrize("fc", ["host", "device"])
    def test_frontier_count_parity_restricted(self, fc):
        from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
        from quorum_intersection_tpu.fbas.synth import benchmark_fbas

        data = benchmark_fbas(64, 14, seed=1)
        po = solve(data, backend="python")
        fr = solve(data, backend=TpuFrontierBackend(arena=4096, pop=128, flag_check=fc))
        assert po.intersects is fr.intersects is True
        # A majority core confirms ZERO minimal quorums (the half-size
        # prune fires first) — equality is the completeness assertion.
        assert fr.stats["minimal_quorums"] == po.stats["minimal_quorums"]

        broken = benchmark_fbas(64, 14, broken=True, seed=1)
        fb = solve(broken, backend=TpuFrontierBackend(arena=4096, pop=128, flag_check=fc))
        assert fb.intersects is False
        assert fb.q1 and fb.q2 and not set(fb.q1) & set(fb.q2)

    def test_restricted_sweep_checkpoint_resume(self, tmp_path):
        # Fingerprints over the RESTRICTED arrays: a resume must skip
        # exactly the recorded prefix on the same problem and reject a
        # different one.
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
        from quorum_intersection_tpu.fbas.synth import benchmark_fbas

        data = benchmark_fbas(40, 10, seed=2)
        total = 1 << 9
        ck = make_recording_ckpt(tmp_path / "restricted.json")
        res = solve(data, backend=TpuSweepBackend(batch=16, checkpoint=ck))
        assert res.intersects is True
        fp = ck.history[-1][2]
        ck.record(256, total, fp)
        res2 = solve(data, backend=TpuSweepBackend(batch=16, checkpoint=ck))
        assert res2.intersects is True
        assert res2.stats["candidates_checked"] <= total - 256 + 16
        assert res2.stats.get("resumed_from") == 256
