"""Worker half of the two-process `jax.distributed` test (not a test module;
launched as a subprocess by tests/test_distributed.py).

Each of the two processes brings up 4 emulated CPU devices, joins the
distributed runtime through a localhost coordinator, builds the 8-device
global candidate mesh, and runs the sharded sweep on a safe and a broken
majority FBAS.  Results print as one JSON line for the parent to compare —
across processes and against a single-process solve.
"""

import json
import os
import sys


def main() -> int:
    port, pid = sys.argv[1], int(sys.argv[2])
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")  # axon sitecustomize repin

    from quorum_intersection_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2, jax.process_count()
    assert distributed.is_multihost()

    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    mesh = distributed.global_candidate_mesh()
    out = {
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "global_devices": int(mesh.devices.size),
    }
    for broken in (False, True):
        res = solve(
            majority_fbas(11, broken=broken),
            backend=TpuSweepBackend(batch=64, mesh=mesh),
        )
        out["broken" if broken else "safe"] = {
            "intersects": res.intersects,
            "q1": res.q1,
            "q2": res.q2,
            "candidates_checked": res.stats.get("candidates_checked"),
        }

    # Device-resident frontier across the SAME two-process mesh: its
    # all_gather runs INSIDE the device while_loop, so iteration counts
    # must align across processes (they do: identical replicated inputs).
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas

    fr = solve(
        hierarchical_fbas(4, 3),
        backend=TpuFrontierBackend(arena=1024, pop=8 * mesh.devices.size, mesh=mesh),
    )
    out["frontier"] = {
        "intersects": fr.intersects,
        "minimal_quorums": fr.stats.get("minimal_quorums"),
        "states_popped": fr.stats.get("states_popped"),
    }
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
