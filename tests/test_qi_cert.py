"""qi-cert differential suite (ISSUE 7): certificate parity across all four
backend rungs, ledger arithmetic, packed ``check_many`` certificates, the
mid-sweep cancel accounting, the independent checker's accept/reject
pinning, the ``cert.write`` fault downgrade, and ``--timing`` byte
compatibility with certificates enabled."""

import copy
import json
import os
import subprocess
import sys

import pytest

from quorum_intersection_tpu.backends.base import SearchCancelled
from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
from quorum_intersection_tpu.cert import CERT_SCHEMA
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import majority_fbas
from quorum_intersection_tpu.pipeline import check_many, solve
from quorum_intersection_tpu.utils import telemetry
from tools.check_cert import CheckFailure, check_certificate
from tools.check_cert import main as checker_main

from tests.conftest import VENDORED_DIR

CLI = [sys.executable, "-m", "quorum_intersection_tpu"]

BACKENDS = ("python", "cpp", "tpu-sweep", "tpu-frontier")


def make_backend(name):
    if name == "tpu-sweep":
        return TpuSweepBackend(batch=512)
    if name == "tpu-frontier":
        return TpuFrontierBackend(arena=4096, pop=128)
    return name


def fixture_nodes(name):
    return json.loads((VENDORED_DIR / f"{name}.json").read_text())


@pytest.fixture
def fresh_record():
    rec = telemetry.reset_run_record()
    yield rec
    telemetry.reset_run_record()


def _env(**extra):
    env = {k: v for k, v in os.environ.items() if not k.startswith("QI_")}
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


def pair_of(witness):
    """A witness as an unordered pair of quorum sets — 'same pair up to
    the reference convention' (docs/PARITY.md §Certificate invariants)."""
    return {frozenset(witness["q1"]), frozenset(witness["q2"])}


class TestDifferentialParity:
    """All four rungs emit equivalent, independently-checkable certs."""

    @pytest.mark.parametrize("backend", BACKENDS)
    @pytest.mark.parametrize(
        "fixture,verdict",
        [
            ("trivial_correct", True),
            ("trivial_broken", False),
            ("nested_correct", True),
            ("nested_broken", False),
        ],
    )
    def test_rung_certificates_validate(self, backend, fixture, verdict):
        nodes = fixture_nodes(fixture)
        res = solve(json.dumps(nodes), backend=make_backend(backend))
        assert res.intersects is verdict
        cert = res.cert
        assert cert["schema"] == CERT_SCHEMA
        assert cert["verdict"] is verdict
        # The independent checker accepts every rung's certificate.
        notes = check_certificate(cert, nodes)
        assert notes

    @pytest.mark.parametrize("fixture", ["trivial_broken", "nested_broken"])
    def test_witness_pair_parity_across_rungs(self, fixture):
        nodes = fixture_nodes(fixture)
        pairs = {}
        for backend in BACKENDS:
            res = solve(json.dumps(nodes), backend=make_backend(backend))
            assert not res.intersects
            pairs[backend] = pair_of(res.cert["witness"])
        assert len(set(map(frozenset, pairs.values()))) == 1, pairs

    @pytest.mark.parametrize("fixture", ["trivial_correct", "nested_correct"])
    def test_sweep_ledger_sums_to_window_space(self, fixture):
        nodes = fixture_nodes(fixture)
        res = solve(json.dumps(nodes), backend=TpuSweepBackend(batch=512))
        entry = res.cert["coverage"]["sccs"][0]
        space = 1 << (entry["size"] - 1)
        assert entry["window_space"] == space
        assert (
            entry["windows_enumerated"]
            + entry["windows_pruned_guard"]
            + entry["windows_skipped_pack_fill"]
            + entry["windows_cancelled"]
        ) == space
        assert entry["windows_cancelled"] == 0

    def test_oracle_and_frontier_ledgers(self):
        nodes = fixture_nodes("nested_correct")
        res_py = solve(json.dumps(nodes), backend="python")
        entry = res_py.cert["coverage"]["sccs"][0]
        assert entry["bnb_calls"] >= 1
        res_fr = solve(
            json.dumps(nodes), backend=TpuFrontierBackend(arena=4096, pop=128)
        )
        entry = res_fr.cert["coverage"]["sccs"][0]
        assert entry["frontier_chunks_drained"] >= 1
        assert entry["states_popped"] >= 1

    def test_provenance_stamps_backend_and_trace(self, fresh_record):
        res = solve(
            json.dumps(fixture_nodes("nested_correct")),
            backend=TpuSweepBackend(batch=512),
        )
        prov = res.cert["provenance"]
        assert prov["backend"] == "tpu-sweep"
        assert prov["trace_id"] == fresh_record.trace_id
        assert prov["sanitize"]["dangling_policy"] == "strict"
        assert prov["events_truncated"] is False
        names = {ev["name"] for ev in prov["events"]}
        assert "sweep.engine_resolved" in names

    def test_event_overflow_marks_provenance_truncated(
        self, fresh_record, monkeypatch
    ):
        # Once MAX_EVENTS overflows, a later solve's events_since slice is
        # empty — the cert must say "audit trail clipped", not pass off the
        # empty list as "no routing/degrade events happened".
        monkeypatch.setattr(telemetry, "MAX_EVENTS", 8)
        for _ in range(12):
            fresh_record.event("noise")
        res = solve(json.dumps(fixture_nodes("trivial_correct")),
                    backend="python")
        assert res.cert["provenance"]["events_truncated"] is True
        assert res.cert["provenance"]["events"] == []

    def test_enumeration_ratio_gauge_full_coverage_sweeps_only(
        self, fresh_record
    ):
        # Registry rule (docs/OBSERVABILITY.md): the gauge is the brute-
        # force baseline only a real pruning win may drive below 1.0 — an
        # early-hit (false-verdict) sweep enumerates less than the space
        # for a different reason and must not publish it.
        solve(json.dumps(fixture_nodes("trivial_broken")),
              backend=TpuSweepBackend(batch=512))
        _, gauges = fresh_record.snapshot()
        assert "cert.enumeration_ratio" not in gauges
        solve(json.dumps(fixture_nodes("nested_correct")),
              backend=TpuSweepBackend(batch=512))
        _, gauges = fresh_record.snapshot()
        assert gauges.get("cert.enumeration_ratio") == 1.0


class TestPackedCheckMany:
    def test_packed_batch_certificates(self, fresh_record):
        sources = [
            majority_fbas(8),
            majority_fbas(9),
            majority_fbas(8, broken=True),
        ]
        results = check_many(sources, backend="auto", pack=True)
        assert [r.intersects for r in results] == [True, True, False]
        for src, res in zip(sources, results):
            cert = res.cert
            assert cert["provenance"]["batched"] is True
            check_certificate(cert, src)
        # Both true verdicts ran packed, and their ledgers still sum.
        for res in results[:2]:
            entry = res.cert["coverage"]["sccs"][0]
            assert entry.get("packed") is True
            assert entry["windows_enumerated"] == entry["window_space"]
        # The packed drive maintained the cert counters as it drained.
        counters, _ = fresh_record.snapshot()
        assert counters.get("cert.windows_enumerated", 0) >= 128 + 256

    def test_guard_decided_sources_get_certs_too(self):
        nodes = fixture_nodes("nested_broken")  # guard-decided (2 QB SCCs)
        [res] = check_many([nodes], backend="python")
        assert not res.intersects
        assert res.cert["guard"]["reason"] == "scc_guard"
        check_certificate(res.cert, nodes)


class _TrippingCancel:
    """CancelToken stand-in that trips after N polls (the sweep only reads
    ``.cancelled`` on its window/drain cancel points)."""

    def __init__(self, after):
        self.after = after
        self.polls = 0

    @property
    def cancelled(self):
        self.polls += 1
        return self.polls > self.after


class TestMidSweepCancel:
    def test_cancel_counts_unswept_windows_and_yields_no_cert(
        self, fresh_record
    ):
        data = majority_fbas(16)  # 2^15 windows, several programs at batch=512
        graph = build_graph(parse_fbas(data))
        from quorum_intersection_tpu.encode.circuit import encode_circuit

        circuit = encode_circuit(graph)
        backend = TpuSweepBackend(
            batch=512, max_inflight=2, cancel=_TrippingCancel(3)
        )
        with pytest.raises(SearchCancelled):
            backend.check_scc(graph, circuit, list(range(graph.n)))
        counters, _ = fresh_record.snapshot()
        cancelled = counters.get("cert.windows_cancelled", 0)
        enumerated = counters.get("cert.windows_enumerated", 0)
        assert cancelled > 0
        # Everything is accounted: nothing both enumerated and cancelled,
        # and no full-coverage claim is possible from this run.
        assert enumerated + cancelled >= 1 << 15
        assert enumerated < 1 << 15


class TestChecker:
    def test_checker_cli_accepts_fixture_pair(self, tmp_path):
        for fx in ("trivial_correct", "trivial_broken"):
            nodes = fixture_nodes(fx)
            res = solve(json.dumps(nodes), backend="python")
            cert_path = tmp_path / f"{fx}.cert.json"
            cert_path.write_text(json.dumps(res.cert))
            rc = checker_main([str(cert_path), str(VENDORED_DIR / f"{fx}.json")])
            assert rc == 0

    def test_corrupted_witness_exits_1(self, tmp_path):
        nodes = fixture_nodes("trivial_broken")
        res = solve(json.dumps(nodes), backend="python")
        bad = copy.deepcopy(res.cert)
        # Forge an overlap: the witness pair is no longer disjoint.
        bad["witness"]["q1"] = bad["witness"]["q1"] + [bad["witness"]["q2"][0]]
        cert_path = tmp_path / "bad.cert.json"
        cert_path.write_text(json.dumps(bad))
        rc = checker_main(
            [str(cert_path), str(VENDORED_DIR / "trivial_broken.json")]
        )
        assert rc == 1

    def test_short_summed_ledger_exits_1(self, tmp_path):
        nodes = fixture_nodes("nested_correct")
        res = solve(json.dumps(nodes), backend=TpuSweepBackend(batch=512))
        bad = copy.deepcopy(res.cert)
        bad["coverage"]["sccs"][0]["windows_enumerated"] -= 1
        cert_path = tmp_path / "short.cert.json"
        cert_path.write_text(json.dumps(bad))
        rc = checker_main(
            [str(cert_path), str(VENDORED_DIR / "nested_correct.json")]
        )
        assert rc == 1

    def test_cancelled_windows_cannot_back_a_true_verdict(self):
        nodes = fixture_nodes("nested_correct")
        res = solve(json.dumps(nodes), backend=TpuSweepBackend(batch=512))
        bad = copy.deepcopy(res.cert)
        entry = bad["coverage"]["sccs"][0]
        entry["windows_enumerated"] -= 5
        entry["windows_cancelled"] += 5  # sums, but rests on cancelled work
        with pytest.raises(CheckFailure, match="cancelled"):
            check_certificate(bad, nodes)

    def test_unverifiable_pruned_mass_is_unsound(self):
        # Since ISSUE 10 pruning exists, but every pruned window must be
        # backed by a re-checkable `pruned_blocks` ledger — a ledger
        # booking unswept windows as "pruned" with no block claims sums
        # to the space yet asserts coverage nothing verified, and the
        # checker rejects it (tests/test_qi_prune.py pins the accept
        # side and the forged-block rejection).
        nodes = fixture_nodes("nested_correct")
        res = solve(json.dumps(nodes), backend=TpuSweepBackend(batch=512))
        bad = copy.deepcopy(res.cert)
        entry = bad["coverage"]["sccs"][0]
        entry["windows_enumerated"] -= 7
        entry["windows_pruned_guard"] += 7  # sums, but nothing pruned it
        with pytest.raises(CheckFailure, match="unverifiable"):
            check_certificate(bad, nodes)

    def test_wrong_guard_count_is_unsound(self):
        nodes = fixture_nodes("nested_broken")
        res = solve(json.dumps(nodes), backend="python")
        bad = copy.deepcopy(res.cert)
        bad["guard"]["quorum_bearing_sccs"] = 1
        with pytest.raises(CheckFailure, match="quorum-bearing"):
            check_certificate(bad, nodes)

    def test_unsatisfied_evidence_is_unsound(self):
        nodes = fixture_nodes("trivial_broken")
        res = solve(json.dumps(nodes), backend="python")
        bad = copy.deepcopy(res.cert)
        bad["witness"]["evidence"]["q1"][0]["satisfied"] = False
        with pytest.raises(CheckFailure, match="unsatisfied"):
            check_certificate(bad, nodes)

    def test_resumed_prefix_counts_toward_the_window_space(self):
        nodes = fixture_nodes("nested_correct")
        res = solve(json.dumps(nodes), backend=TpuSweepBackend(batch=512))
        cert = copy.deepcopy(res.cert)
        entry = cert["coverage"]["sccs"][0]
        # Recast part of the enumeration as a checkpoint-resumed prefix:
        # the sum still covers the space, so the cert stays sound.
        entry["windows_enumerated"] -= 512
        entry["windows_resumed_prefix"] = 512
        notes = check_certificate(cert, nodes)
        assert any("checkpoint-resumed" in n for n in notes)
        # ...but the prefix cannot conjure coverage beyond the space.
        entry["windows_resumed_prefix"] += 1
        with pytest.raises(CheckFailure, match="ledger arithmetic"):
            check_certificate(cert, nodes)

    def test_malformed_evidence_rows_are_unsound_not_a_crash(self):
        nodes = fixture_nodes("trivial_broken")
        res = solve(json.dumps(nodes), backend="python")
        bad = copy.deepcopy(res.cert)
        bad["witness"]["evidence"]["q1"] = ["not-an-object"]
        with pytest.raises(CheckFailure, match="not objects"):
            check_certificate(bad, nodes)
        bad2 = copy.deepcopy(res.cert)
        del bad2["witness"]["evidence"]["q1"][0]["id"]
        with pytest.raises(CheckFailure, match="do not cover"):
            check_certificate(bad2, nodes)

    def test_non_object_ledger_entry_is_unsound_not_a_crash(self):
        nodes = fixture_nodes("trivial_correct")
        res = solve(json.dumps(nodes), backend="python")
        bad = copy.deepcopy(res.cert)
        bad["coverage"]["sccs"] = ["bogus"]
        with pytest.raises(CheckFailure, match="not an object"):
            check_certificate(bad, nodes)

    def test_hostile_structure_exits_2_never_a_traceback(self, tmp_path):
        nodes = fixture_nodes("trivial_broken")
        res = solve(json.dumps(nodes), backend="python")
        bad = copy.deepcopy(res.cert)
        bad["witness"] = ["hostile"]  # .get on a list inside the checker
        cert_path = tmp_path / "hostile.cert.json"
        cert_path.write_text(json.dumps(bad))
        rc = checker_main(
            [str(cert_path), str(VENDORED_DIR / "trivial_broken.json")]
        )
        assert rc == 2


class TestResumedSweep:
    def test_checkpoint_resumed_cert_passes_the_checker(
        self, tmp_path, fresh_record
    ):
        from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

        nodes = fixture_nodes("nested_correct")
        ck = SweepCheckpoint(tmp_path / "sweep.ckpt")
        first = TpuSweepBackend(
            batch=512, max_inflight=2, checkpoint=ck,
            cancel=_TrippingCancel(8),
        )
        with pytest.raises(SearchCancelled):
            solve(json.dumps(nodes), backend=first)
        res = solve(
            json.dumps(nodes),
            backend=TpuSweepBackend(batch=512, checkpoint=ck),
        )
        assert res.intersects is True
        entry = res.cert["coverage"]["sccs"][0]
        # The first (cancelled) run recorded block-aligned progress; the
        # resumed run's ledger carries that prefix as its own term and the
        # independent checker accepts the sum.
        assert entry["windows_resumed_prefix"] > 0
        assert (
            entry["windows_enumerated"] + entry["windows_resumed_prefix"]
            == entry["window_space"]
        )
        notes = check_certificate(res.cert, nodes)
        assert any("checkpoint-resumed" in n for n in notes)


class TestCliAndFaults:
    def test_cert_out_writes_validating_certificate(self, tmp_path):
        cert_path = tmp_path / "cli.cert.json"
        proc = subprocess.run(
            CLI + ["--backend", "python", "--cert-out", str(cert_path)],
            input=(VENDORED_DIR / "nested_broken.json").read_text(),
            capture_output=True, text=True, timeout=120, env=_env(),
        )
        assert proc.returncode == 1  # false verdict
        assert proc.stdout.strip() == "false"
        cert = json.loads(cert_path.read_text())
        check_certificate(cert, fixture_nodes("nested_broken"))

    def test_cert_write_fault_downgrades_not_flips(self, tmp_path):
        cert_path = tmp_path / "never.cert.json"
        metrics = tmp_path / "m.jsonl"
        proc = subprocess.run(
            CLI + ["--backend", "python", "--cert-out", str(cert_path),
                   "--metrics-json", str(metrics)],
            input=(VENDORED_DIR / "trivial_correct.json").read_text(),
            capture_output=True, text=True, timeout=120,
            env=_env(QI_FAULTS="cert.write=oserror@1"),
        )
        assert proc.returncode == 0, proc.stderr  # verdict unaffected
        assert proc.stdout.strip() == "true"
        assert not cert_path.exists()
        lines = [json.loads(l) for l in metrics.read_text().splitlines()]
        counters = {
            l["name"]: l["value"] for l in lines if l["kind"] == "counter"
        }
        assert counters.get("cert.write_errors") == 1
        assert counters.get("faults.injected") == 1

    def test_cert_out_rejected_in_analytics_modes(self, tmp_path):
        # Analytics modes never reach the solve that builds a certificate;
        # accepting --cert-out and exiting 0 with the file never written
        # would strand a CI consumer on ENOENT with nothing to diagnose.
        cert_path = tmp_path / "c.json"
        for flag in ("--pagerank", "--top-tier", "--splitting-set",
                     "--blocking-set"):
            proc = subprocess.run(
                CLI + [flag, "--cert-out", str(cert_path)],
                input=(VENDORED_DIR / "trivial_correct.json").read_text(),
                capture_output=True, text=True, timeout=120, env=_env(),
            )
            assert proc.returncode == 1, (flag, proc.stderr)
            assert "--cert-out" in proc.stderr
            assert not cert_path.exists()

    def test_timing_byte_compatible_with_certificates(self, tmp_path):
        """--timing's [timing]/[stats] line KEYS are identical with and
        without --cert-out, and the deterministic [stats] sequence keeps
        its order.  ([timing] lines are duration-sorted by
        PhaseTimers.summary(), so their relative order legitimately varies
        run to run — compare them as a multiset, not a sequence.)"""
        def run(extra):
            proc = subprocess.run(
                CLI + ["--timing", "--backend", "python", *extra],
                input=(VENDORED_DIR / "trivial_correct.json").read_text(),
                capture_output=True, text=True, timeout=120, env=_env(),
            )
            assert proc.returncode == 0
            return [
                line.split(":", 1)[0]
                for line in proc.stderr.splitlines()
                if line.startswith(("[timing]", "[stats]"))
            ]

        plain = run([])
        with_cert = run(["--cert-out", str(tmp_path / "c.json")])
        assert sorted(plain) == sorted(with_cert)
        assert [k for k in plain if k.startswith("[stats]")] == [
            k for k in with_cert if k.startswith("[stats]")
        ]
        # Legacy lines still precede any [timing]/[stats] reordering of the
        # cert payload: the [timing] block stays contiguous and first.
        assert plain[0].startswith("[timing]") and with_cert[0].startswith(
            "[timing]"
        )


class TestSplittingReuse:
    def test_is_splitting_validated_by_witness_evidence(self):
        from quorum_intersection_tpu.analytics.splitting import is_splitting

        nodes = fixture_nodes("trivial_broken")
        # Already split: the empty deletion is witnessed by the cert's
        # evidence (the splitting analytics now consume qi-cert evidence
        # instead of a bare q1-is-not-None).
        assert is_splitting(nodes, []) is True
        correct = fixture_nodes("trivial_correct")
        assert is_splitting(correct, []) is False
