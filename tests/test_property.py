"""Property-based differential testing (hypothesis).

The seeded differential loops elsewhere in the suite check fixed samples;
these properties let hypothesis search the FBAS space for divergence
between the engines and for metamorphic invariants the reference pins:

- python oracle ⇔ exhaustive sweep verdict equality (the sweep's
  verdict-equivalence proof, sweep.py module docstring, exercised on
  adversarial instances rather than seeds);
- witness validity: a False verdict always carries two disjoint quorums
  (each a fixpoint-verified quorum, cpp:351-352 out-param contract);
- sanitizer idempotence (fix_quorum_configurations.py:11-15 analog);
- verdict monotonicity under the one-knob methodology (SURVEY.md §4.1):
  raising one node's top-level threshold never creates a *new* disjoint
  pair on a previously-safe symmetric network.
"""

import pytest

pytest.importorskip("hypothesis")

from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.semantics import is_quorum
from quorum_intersection_tpu.fbas.synth import random_fbas
from quorum_intersection_tpu.pipeline import solve

# Device-touching properties keep example counts small: each example runs
# two full solves (one jit-compiled); the value is the SEARCH, not volume.
COMMON = dict(
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)

fbas_params = st.fixed_dictionaries(
    {
        "n": st.integers(min_value=3, max_value=12),
        "seed": st.integers(min_value=0, max_value=10**6),
        "nested_prob": st.sampled_from([0.0, 0.3, 0.7]),
        "null_prob": st.sampled_from([0.0, 0.2]),
        "dangling_prob": st.sampled_from([0.0, 0.2]),
    }
)


@settings(max_examples=25, **COMMON)
@given(params=fbas_params)
def test_oracle_and_sweep_verdicts_agree(params):
    data = random_fbas(**params)
    oracle = solve(data, backend="python")
    sweep = solve(data, backend=TpuSweepBackend(batch=256))
    assert oracle.intersects is sweep.intersects


@settings(max_examples=25, **COMMON)
@given(params=fbas_params)
def test_false_verdict_carries_valid_disjoint_witness(params):
    data = random_fbas(**params)
    res = solve(data, backend="python")
    if res.intersects:
        return
    if res.stats.get("reason") == "scc_guard" and len(res.quorum_scc_ids) == 0:
        # No quorum exists anywhere — no witness pair is possible.
        assert res.q1 is None and res.q2 is None
        return
    graph = build_graph(parse_fbas(data))
    assert res.q1 and res.q2
    assert not set(res.q1) & set(res.q2)
    assert is_quorum(graph, res.q1)
    assert is_quorum(graph, res.q2)


@settings(max_examples=50, **COMMON)
@given(params=fbas_params)
def test_sanitizer_idempotent_and_parse_clean(params):
    from quorum_intersection_tpu.fbas.sanitize import sanitize

    data = random_fbas(**params)
    once = sanitize(data)
    twice = sanitize(once)
    assert once == twice
    parse_fbas(once)  # sanitized output must always parse


@settings(max_examples=20, **COMMON)
@given(
    n=st.integers(min_value=3, max_value=9),
    bump=st.integers(min_value=0, max_value=3),
    victim=st.integers(min_value=0, max_value=8),
)
def test_raising_a_threshold_never_breaks_a_safe_majority(n, bump, victim):
    """One-knob metamorphic property: on a safe symmetric majority network,
    RAISING any single node's threshold (more agreement required) cannot
    create a disjoint quorum pair — only lowering can (the broken twins'
    knob, `broken_trivial.json:20` lowers 2→1)."""
    from quorum_intersection_tpu.fbas.synth import majority_fbas

    data = majority_fbas(n)
    victim %= n
    q = data[victim]["quorumSet"]
    q["threshold"] = min(q["threshold"] + bump, n)
    res = solve(data, backend="python")
    assert res.intersects is True


@settings(max_examples=15, **COMMON)
@given(params=fbas_params)
def test_oracle_and_frontier_agree_with_count_parity(params):
    # The device-resident frontier must match the oracle's verdict on
    # hypothesis-searched instances AND, on safe single-SCC verdicts, its
    # confirmed-minimal-quorum count (enumeration completeness — a frontier
    # that drops states could still luck into the right verdict).
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend

    data = random_fbas(**params)
    oracle = solve(data, backend="python")
    frontier = solve(data, backend=TpuFrontierBackend(arena=2048, pop=128))
    assert oracle.intersects is frontier.intersects
    if (
        oracle.intersects
        and oracle.stats.get("reason") != "scc_guard"
        # PARITY.md D15: when the oracle's cpp:221 bestNode fallback fires it
        # branches on a dontRemove member (duplicating it), while the frontier
        # uses an always-eligible branch variable — counts may then differ
        # legitimately, so only assert parity on fallback-free searches.
        and oracle.stats.get("best_node_fallback", 0) == 0
    ):
        assert (
            frontier.stats["minimal_quorums"] == oracle.stats["minimal_quorums"]
        )
    if not frontier.intersects and frontier.q1 is not None:
        assert frontier.q2 is not None
        assert not set(frontier.q1) & set(frontier.q2)
