"""The benchmark contract: `python bench.py` must ALWAYS end its stdout with
a parseable headline JSON line (driver contract — BENCH_r01.json died with
rc=124/parsed:null; the r2 bench is built to make that impossible).  Run
CPU-pinned so the test never touches the hang-prone tunnel."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

BENCH = Path(__file__).parent.parent / "bench.py"


@pytest.fixture(scope="module")
def quick_run():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(BENCH), "--quick", "--budget-seconds", "420"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(BENCH.parent),
    )
    return proc


def test_exits_zero(quick_run):
    assert quick_run.returncode == 0


def test_every_stdout_line_is_a_full_headline(quick_run):
    lines = [ln for ln in quick_run.stdout.strip().splitlines() if ln.strip()]
    assert lines, "no output at all"
    for ln in lines:
        d = json.loads(ln)  # every emitted line must parse
        assert d["metric"] == "candidate_quorums_checked_per_sec_per_chip"
        assert "unit" in d and "vs_baseline" in d and "phases" in d


def test_final_line_has_real_number_and_parity(quick_run):
    d = json.loads(quick_run.stdout.strip().splitlines()[-1])
    assert d["value"] > 0
    # Reference corpus when /root/reference is present, plus the always-on
    # vendored corpus (fixtures/MANIFEST.json).
    assert d["parity"].endswith("6/6 vendored")
    assert d["baseline_value"] > 0
    assert d["phases"].get("throughput") == "ok"


def test_final_line_r4_fields(quick_run):
    # r4 additions: per-phase device stamps, the north-star verdict
    # comparisons, and the sweep roofline diagnostics.
    d = json.loads(quick_run.stdout.strip().splitlines()[-1])
    assert d["phase_devices"].get("throughput") == "cpu"
    for key in ("verdict_256", "verdict_1024"):
        assert d["phases"].get(key) == "ok"
        vd = d[key]
        assert vd["verdict_ok"] is True
        assert vd["auto_seconds"] >= 0
        assert vd["native_rate"] > 0
        # Quick cores complete natively, so the ratio must be measured.
        assert vd["native_completed"] is True and "ratio" in vd
    assert d.get("sweep_fixpoint_trips"), "roofline trips missing"
    assert d.get("sweep_macs_per_candidate", 0) > 0


def test_timeout_salvage_keeps_partial_phase_output(monkeypatch):
    # A phase child that emits incrementally (the frontier rows) and
    # then hangs past its timeout must leave its completed rows on the
    # record with a partial_error marker; a crash after emitting rows is
    # salvaged the same way (with a trailing corrupt line skipped); strict
    # phases keep the plain error contract.
    import subprocess
    import sys
    import textwrap

    import bench

    class FakeDeadline:
        def remaining(self):
            return 1e9

    monkeypatch.setattr(bench, "MIN_CHILD_TIMEOUT", 0.5)
    real_popen = subprocess.Popen

    def fake_child(script):
        def fake_popen(cmd, **kw):
            return real_popen([sys.executable, "-c", script], **kw)
        return fake_popen

    # The child timeout must cover interpreter startup, which this image's
    # sitecustomize makes expensive (it imports jax into EVERY python
    # process — measured >3 s on a busy 1-core box).  Measure it once and
    # give the crash child 3x that; the hang children then cost the same
    # bounded wait instead of a hard-coded guess that flakes under load.
    import time as _time

    t0 = _time.monotonic()
    subprocess.run([sys.executable, "-c", "pass"], check=True)
    child_timeout = max(3.0, 3.0 * (_time.monotonic() - t0))

    hang = textwrap.dedent(
        """
        import json, time
        print(json.dumps({"frontier_row1": 1}), flush=True)
        time.sleep(600)
        """
    )
    monkeypatch.setattr(subprocess, "Popen", fake_child(hang))
    res = bench.run_child("frontier", FakeDeadline(), child_timeout, salvage=True)
    assert res.get("frontier_row1") == 1
    assert "partial_error" in res and "error" not in res
    strict = bench.run_child("sweep", FakeDeadline(), child_timeout)
    assert strict == {"error": f"timeout after {child_timeout:.0f}s"}

    crash = textwrap.dedent(
        """
        import json, sys
        print(json.dumps({"frontier_row1": 2}), flush=True)
        sys.stdout.write("{corrupt trailing line")
        sys.stdout.flush()
        sys.exit(11)
        """
    )
    monkeypatch.setattr(subprocess, "Popen", fake_child(crash))
    res = bench.run_child("frontier", FakeDeadline(), child_timeout, salvage=True)
    assert res.get("frontier_row1") == 2  # reverse scan skipped the corrupt tail
    assert res["partial_error"].startswith("exit 11")
