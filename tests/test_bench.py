"""The benchmark contract: `python bench.py` must ALWAYS end its stdout with
a parseable headline JSON line (driver contract — BENCH_r01.json died with
rc=124/parsed:null; the r2 bench is built to make that impossible).  Run
CPU-pinned so the test never touches the hang-prone tunnel."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

BENCH = Path(__file__).parent.parent / "bench.py"


@pytest.fixture(scope="module")
def quick_run():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(BENCH), "--quick", "--budget-seconds", "420"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(BENCH.parent),
    )
    return proc


def test_exits_zero(quick_run):
    assert quick_run.returncode == 0


def test_every_stdout_line_is_a_full_headline(quick_run):
    lines = [ln for ln in quick_run.stdout.strip().splitlines() if ln.strip()]
    assert lines, "no output at all"
    for ln in lines:
        d = json.loads(ln)  # every emitted line must parse
        assert d["metric"] == "candidate_quorums_checked_per_sec_per_chip"
        assert "unit" in d and "vs_baseline" in d and "phases" in d


def test_final_line_has_real_number_and_parity(quick_run):
    d = json.loads(quick_run.stdout.strip().splitlines()[-1])
    assert d["value"] > 0
    # Reference corpus when /root/reference is present, plus the always-on
    # vendored corpus (fixtures/MANIFEST.json).
    assert d["parity"].endswith("6/6 vendored")
    assert d["baseline_value"] > 0
    assert d["phases"].get("throughput") == "ok"
