"""qi-fleet suite (ISSUE 11): consistent-hash ring determinism + bounded
rebalance, the two-level SCC verdict store (cross-store reuse through the
shared tier, degraded-tier behavior, forged-fragment rejection), the
fleet-vs-single-worker differential on the vendored fixture pairs with
checker-validated certs including a cross-worker composed fragment, the
kill-one-of-N journal-inheritance matrix (pending/done/corrupt/torn
inherited by a peer), every ``fleet.*`` fault point typed-or-oracle-equal,
the forced routing/failover interleavings, the socket transport of the
serve split, the zipfian churn skew, and the fleet-aware /healthz +
/readyz."""

import json
import socket

import pytest

from quorum_intersection_tpu.delta import (
    SccScan,
    SccVerdict,
    SccVerdictStore,
    SharedSccStore,
)
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import (
    churn_trace,
    churn_trace_steps,
    majority_fbas,
)
from quorum_intersection_tpu.fleet import FleetEngine, HashRing
from quorum_intersection_tpu.pipeline import solve
from quorum_intersection_tpu.serve import (
    RequestJournal,
    ServeEngine,
    ServeError,
    snapshot_fingerprint,
)
from quorum_intersection_tpu.serve_transport import SocketServeServer
from quorum_intersection_tpu.utils import faults, telemetry
from quorum_intersection_tpu.utils.metrics_server import (
    healthz_payload,
    readyz_payload,
)
from tools.check_cert import check_certificate

from tests.conftest import VENDORED_DIR

FIXTURE_PAIRS = [
    ("trivial_correct", True),
    ("trivial_broken", False),
    ("nested_correct", True),
    ("nested_broken", False),
]


def fixture_nodes(name):
    return json.loads((VENDORED_DIR / f"{name}.json").read_text())


def fingerprint_of(nodes):
    return snapshot_fingerprint(build_graph(parse_fbas(nodes)))


@pytest.fixture
def rec():
    record = telemetry.reset_run_record()
    faults.clear_plan()
    yield record
    faults.clear_plan()
    telemetry.reset_run_record()


class _Fleet:
    """Context-managed local-worker fleet with test-friendly defaults."""

    def __init__(self, tmp_path, n=2, **kwargs):
        kwargs.setdefault("backend", "python")
        kwargs.setdefault("worker_mode", "local")
        kwargs.setdefault("journal_dir", tmp_path / "fleet")
        kwargs.setdefault("probe_interval_s", 30.0)  # probes only on demand
        self.engine = FleetEngine(n, **kwargs)

    def __enter__(self):
        self.engine.start()
        return self.engine

    def __exit__(self, *exc):
        self.engine.stop(drain=True, timeout=60.0)
        return False


def _wait_counter(record, name, want, timeout=20.0):
    """Poll the run record until counter ``name`` reaches ``want``."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        counters, _ = record.snapshot()
        if counters.get(name, 0) >= want:
            return counters.get(name, 0)
        time.sleep(0.02)
    counters, _ = record.snapshot()
    return counters.get(name, 0)


# ---------------------------------------------------------------------------
# consistent-hash ring


class TestHashRing:
    def test_deterministic_across_instances(self):
        a, b = HashRing(vnodes=32), HashRing(vnodes=32)
        for ring in (a, b):
            for w in ("w0", "w1", "w2", "w3"):
                ring.add(w)
        keys = [f"fp-{i:04d}" for i in range(200)]
        assert [a.route(k) for k in keys] == [b.route(k) for k in keys]

    def test_every_worker_owns_keys(self):
        ring = HashRing(vnodes=32)
        for w in ("w0", "w1", "w2", "w3"):
            ring.add(w)
        owners = {ring.route(f"fp-{i:04d}") for i in range(400)}
        assert owners == {"w0", "w1", "w2", "w3"}

    def test_bounded_rebalance_on_leave(self):
        ring = HashRing(vnodes=32)
        for w in ("w0", "w1", "w2", "w3"):
            ring.add(w)
        keys = [f"fp-{i:04d}" for i in range(400)]
        before = {k: ring.route(k) for k in keys}
        ring.remove("w1")
        moved = [k for k in keys if ring.route(k) != before[k]]
        # ONLY the departed worker's keys move — everything else is pinned.
        assert moved and all(before[k] == "w1" for k in moved)
        assert len(moved) == sum(1 for v in before.values() if v == "w1")

    def test_bounded_rebalance_on_join(self):
        ring = HashRing(vnodes=32)
        for w in ("w0", "w1", "w2", "w3"):
            ring.add(w)
        keys = [f"fp-{i:04d}" for i in range(400)]
        before = {k: ring.route(k) for k in keys}
        ring.add("w4")
        moved = [k for k in keys if ring.route(k) != before[k]]
        # Every moved key moves TO the joiner, and only ~1/N of the space
        # moves (vnode variance bounded well under half).
        assert moved and all(ring.route(k) == "w4" for k in moved)
        assert len(moved) < len(keys) / 2

    def test_empty_ring_raises(self):
        with pytest.raises(LookupError):
            HashRing().route("fp")


# ---------------------------------------------------------------------------
# two-level SCC verdict store


class TestSharedStore:
    def test_cross_store_verdict_reuse(self, rec, tmp_path):
        shared = SharedSccStore(tmp_path / "store")
        a = SccVerdictStore(64, shared=shared)
        outcome, _ = a.lease_verdict("fp-1", False)
        assert outcome == "leader"
        a.publish_verdict("fp-1", False, SccVerdict(
            intersects=True, q1_local=None, q2_local=None,
            stats={"backend": "python"},
        ))
        # A DIFFERENT store (another worker) reads the banked fragment
        # through the shared tier instead of solving.
        b = SccVerdictStore(64, shared=SharedSccStore(tmp_path / "store"))
        outcome, verdict = b.lease_verdict("fp-1", False)
        assert outcome == "hit"
        assert verdict.intersects is True
        assert verdict.stats["backend"] == "python"
        counters, _ = rec.snapshot()
        assert counters.get("fleet.store_hits", 0) >= 1

    def test_cross_store_scan_reuse(self, rec, tmp_path):
        a = SccVerdictStore(64, shared=SharedSccStore(tmp_path / "s"))
        a.put_scan("scan-fp", SccScan(quorum_local=(0, 2, 3)))
        b = SccVerdictStore(64, shared=SharedSccStore(tmp_path / "s"))
        scan = b.get_scan("scan-fp")
        assert scan is not None and scan.quorum_local == (0, 2, 3)

    def test_scope_bit_partitions_fragments(self, tmp_path):
        shared = SharedSccStore(tmp_path / "store")
        a = SccVerdictStore(64, shared=shared)
        a.lease_verdict("fp-s", True)
        a.publish_verdict("fp-s", True, SccVerdict(
            intersects=False, q1_local=[0], q2_local=[1], stats={},
        ))
        b = SccVerdictStore(64, shared=SharedSccStore(tmp_path / "store"))
        outcome, _ = b.lease_verdict("fp-s", False)  # other scoping: miss
        assert outcome == "leader"
        b.publish_verdict("fp-s", False, None)

    def test_store_fault_degrades_to_local(self, rec, tmp_path):
        faults.install_plan(faults.parse_faults("fleet.store=error@1+"))
        store = SccVerdictStore(64, shared=SharedSccStore(tmp_path / "s"))
        store.put_scan("fp-x", SccScan(quorum_local=(1,)))  # shared write fails
        scan = store.get_scan("fp-x")  # local LRU still serves it
        assert scan is not None and scan.quorum_local == (1,)
        counters, _ = rec.snapshot()
        assert counters.get("fleet.store_errors", 0) >= 1
        faults.clear_plan()
        # The shared file was never written while the tier was down.
        fresh = SccVerdictStore(64, shared=SharedSccStore(tmp_path / "s"))
        assert fresh.get_scan("fp-x") is None

    def test_forged_fragment_is_a_miss_never_trusted(self, rec, tmp_path):
        root = tmp_path / "store"
        shared = SharedSccStore(root)
        root.mkdir(parents=True)
        (root / "verdict-s0-forged.json").write_text("{not json", "utf-8")
        (root / "verdict-s0-shape.json").write_text(
            json.dumps({"intersects": "yes", "stats": {}}), "utf-8",
        )
        store = SccVerdictStore(64, shared=shared)
        for fp in ("forged", "shape"):
            outcome, verdict = store.lease_verdict(fp, False)
            assert outcome == "leader" and verdict is None
            store.publish_verdict(fp, False, None)


# ---------------------------------------------------------------------------
# fleet-vs-single differential


class TestFleetDifferential:
    @pytest.mark.parametrize("fixture,verdict", FIXTURE_PAIRS)
    def test_fleet_equals_single_engine(self, rec, tmp_path, fixture,
                                        verdict):
        nodes = fixture_nodes(fixture)
        single = ServeEngine(backend="python")
        single.start()
        try:
            ref = single.submit(nodes).result(timeout=60.0)
        finally:
            single.stop(drain=True, timeout=30.0)
        with _Fleet(tmp_path, n=2) as fleet:
            resp = fleet.submit(nodes).result(timeout=60.0)
        assert resp.intersects is verdict is ref.intersects
        assert resp.cert is not None
        assert resp.cert["verdict"] is verdict
        if not verdict:
            assert (resp.cert["witness"]["q1"], resp.cert["witness"]["q2"]) \
                == (ref.cert["witness"]["q1"], ref.cert["witness"]["q2"])
        check_certificate(resp.cert, nodes)

    def test_fleet_n4_differential(self, rec, tmp_path):
        nodes = fixture_nodes("nested_broken")
        with _Fleet(tmp_path, n=4) as fleet:
            resp = fleet.submit(nodes).result(timeout=60.0)
        assert resp.intersects is False
        check_certificate(resp.cert, nodes)

    def test_cross_worker_composed_fragment(self, rec, tmp_path):
        """A fragment solved on one worker composes into a cert answered
        by the OTHER worker: the SCC-local fingerprint ignores publicKeys
        (PR 10 transplant), so two key-renamed twins share a fragment
        while their snapshot fingerprints route to different workers —
        and the composed cert still passes the unmodified checker."""
        with _Fleet(tmp_path, n=2, store_dir=tmp_path / "store") as fleet:
            base_nodes = majority_fbas(7, prefix="CWAAA")
            base_w = fleet._ring.route(fingerprint_of(base_nodes))
            other_nodes = None
            for tag in ("CWBBB", "CWCCC", "CWDDD", "CWEEE", "CWFFF"):
                cand = majority_fbas(7, prefix=tag)
                if fleet._ring.route(fingerprint_of(cand)) != base_w:
                    other_nodes = cand
                    break
            assert other_nodes is not None, "no prefix routed differently"
            first = fleet.submit(base_nodes).result(timeout=60.0)
            assert first.intersects is True
            second = fleet.submit(other_nodes).result(timeout=60.0)
        assert second.intersects is True
        delta_stamp = second.cert["provenance"]["delta"]
        # Composed from the shared tier: the other worker never re-solved.
        assert delta_stamp["reused_sccs"] == 1
        assert delta_stamp["resolved_sccs"] == 0
        check_certificate(second.cert, other_nodes)
        counters, _ = rec.snapshot()
        assert counters.get("fleet.store_hits", 0) >= 1

    def test_duplicate_request_id_resolves_both(self, rec, tmp_path):
        """A client reusing a request_id must not orphan the earlier
        ticket (the serve contract answers every submission): both
        tickets resolve, each under the client's own id."""
        nodes = majority_fbas(5, prefix="DUP")
        with _Fleet(tmp_path, n=2) as fleet:
            t1 = fleet.submit(nodes, request_id="same-id")
            t2 = fleet.submit(nodes, request_id="same-id")
            r1 = t1.result(timeout=60.0)
            r2 = t2.result(timeout=60.0)
        assert r1.intersects is True and r2.intersects is True
        assert r1.request_id == r2.request_id == "same-id"
        counters, _ = rec.snapshot()
        assert (counters.get("fleet.verdicts", 0)
                + counters.get("fleet.errors", 0)) == 2

    def test_zipfian_stream_parity(self, rec, tmp_path):
        trace = churn_trace(majority_fbas(7, prefix="ZPF"), 14, seed=2,
                            skew=1.1)
        expected = {}
        for snap in trace:
            key = json.dumps(snap, sort_keys=True)
            if key not in expected:
                expected[key] = solve(snap, backend="python").intersects
        with _Fleet(tmp_path, n=2) as fleet:
            tickets = [(snap, fleet.submit(snap)) for snap in trace]
            for snap, ticket in tickets:
                got = ticket.result(timeout=60.0).intersects
                assert got is expected[json.dumps(snap, sort_keys=True)]


# ---------------------------------------------------------------------------
# failover


class TestFailover:
    def _journal_with_matrix(self, tmp_path, pending_nodes, done_nodes):
        """A dead worker's journal: two pending reqs, one done pair, one
        mid-file corrupt line, one torn tail."""
        path = tmp_path / "dead.journal"
        journal = RequestJournal(path)
        journal.append_request(
            "pend-a", fingerprint_of(pending_nodes[0]), pending_nodes[0],
            None,
        )
        journal.append_request(
            "done-b", fingerprint_of(done_nodes), done_nodes, None,
        )
        journal.append_done("done-b", fingerprint_of(done_nodes),
                            "verdict", True)
        journal.append_request(
            "pend-c", fingerprint_of(pending_nodes[1]), pending_nodes[1],
            None,
        )
        journal.close()
        with open(path, "a", encoding="utf-8") as fh:
            fh.write('{"kind": "req", "request_id": "torn-tail", "nod\n')
        return path

    def test_journal_inheritance_matrix(self, rec, tmp_path):
        """Pending entries re-solve on a peer exactly once; done entries
        never replay (zero duplicated); the torn tail is tolerated."""
        pend = [majority_fbas(5, prefix="INH0"),
                majority_fbas(5, broken=True, prefix="INH1")]
        done = majority_fbas(5, prefix="INH2")
        path = self._journal_with_matrix(tmp_path, pend, done)
        with _Fleet(tmp_path, n=2) as fleet:
            replayed = fleet.adopt_journal(path)
            assert replayed == 2  # pend-a + pend-c; done-b skipped
            got = _wait_counter(rec, "fleet.replayed_verdicts", 2)
            assert got == 2
        counters, _ = rec.snapshot()
        assert counters.get("fleet.replays", 0) == 2

    def test_kill_one_local_rerouted(self, rec, tmp_path):
        """In-flight requests of a killed worker re-route to the survivor
        and every ticket still resolves with the oracle verdict."""
        snaps = [majority_fbas(n, broken=b, prefix="KLL")
                 for n in (5, 7, 9) for b in (False, True)]
        expected = [solve(s, backend="python").intersects for s in snaps]
        with _Fleet(tmp_path, n=2, batch_max=2) as fleet:
            tickets = [fleet.submit(s) for s in snaps]
            fleet.kill_worker(fleet.worker_ids()[0], evict=True)
            got = [t.result(timeout=60.0).intersects for t in tickets]
        assert got == expected
        counters, _ = rec.snapshot()
        assert counters.get("fleet.evictions", 0) == 1

    @pytest.mark.slow
    def test_kill_one_subprocess_sigkill(self, rec, tmp_path):
        """The real thing: subprocess workers, a mid-stream SIGKILL, the
        dead worker's journal inherited by its peer — zero lost, every
        verdict oracle-equal."""
        trace = churn_trace(majority_fbas(9, prefix="SGK"), 9, seed=4)
        expected = [solve(s, backend="python").intersects for s in trace]
        fleet = FleetEngine(
            2, backend="python", worker_mode="subprocess",
            journal_dir=tmp_path / "proc", probe_interval_s=0.2,
        )
        fleet.start()
        try:
            tickets = [fleet.submit(s) for s in trace[:6]]
            fleet.kill_worker(fleet.worker_ids()[0])  # real SIGKILL
            tickets += [fleet.submit(s) for s in trace[6:]]
            got = [t.result(timeout=120.0).intersects for t in tickets]
        finally:
            fleet.stop(drain=True, timeout=60.0)
        assert got == expected
        counters, _ = rec.snapshot()
        assert counters.get("fleet.evictions", 0) == 1


# ---------------------------------------------------------------------------
# fault points: typed or oracle-equal


class TestFleetFaultPoints:
    def _stream_parity(self, fleet, snaps, expected):
        outcomes = []
        for snap in snaps:
            try:
                ticket = fleet.submit(snap)
            except (ServeError, faults.FaultInjected) as exc:
                outcomes.append(("typed", type(exc).__name__))
                continue
            try:
                outcomes.append(("ok", ticket.result(timeout=60.0).intersects))
            except (ServeError, faults.FaultInjected) as exc:
                outcomes.append(("typed", type(exc).__name__))
        for (kind, value), want in zip(outcomes, expected):
            if kind == "ok":
                assert value is want
        return outcomes

    def _snaps(self):
        snaps = [majority_fbas(n, broken=b, prefix="FLT")
                 for n in (5, 7) for b in (False, True)]
        return snaps, [solve(s, backend="python").intersects for s in snaps]

    def test_route_fault_degrades_to_first_live(self, rec, tmp_path):
        snaps, expected = self._snaps()
        faults.install_plan(faults.parse_faults("fleet.route=error@1+"))
        with _Fleet(tmp_path, n=2) as fleet:
            outcomes = self._stream_parity(fleet, snaps, expected)
        assert all(kind == "ok" for kind, _ in outcomes)
        counters, _ = rec.snapshot()
        assert counters.get("fleet.route_errors", 0) >= len(snaps)

    def test_store_fault_degrades_to_local_lru(self, rec, tmp_path):
        snaps, expected = self._snaps()
        faults.install_plan(faults.parse_faults("fleet.store=error@1+"))
        with _Fleet(tmp_path, n=2, store_dir=tmp_path / "store") as fleet:
            outcomes = self._stream_parity(fleet, snaps, expected)
        assert all(kind == "ok" for kind, _ in outcomes)
        counters, _ = rec.snapshot()
        assert counters.get("fleet.store_errors", 0) >= 1

    def test_probe_fault_never_evicts(self, rec, tmp_path):
        snaps, expected = self._snaps()
        faults.install_plan(faults.parse_faults("fleet.probe=error@1+"))
        with _Fleet(tmp_path, n=2, probe_interval_s=0.05) as fleet:
            _wait_counter(rec, "fleet.probe_errors", 2, timeout=5.0)
            outcomes = self._stream_parity(fleet, snaps, expected)
            assert len(fleet.worker_ids()) == 2  # nobody spuriously evicted
        assert all(kind == "ok" for kind, _ in outcomes)
        counters, _ = rec.snapshot()
        assert counters.get("fleet.probe_errors", 0) >= 2
        assert counters.get("fleet.evictions", 0) == 0

    def test_replay_fault_degrades_to_inflight_reroute(self, rec, tmp_path):
        """An unreadable dead journal costs the journal-only orphans, not
        the in-flight tickets: clients still resolve oracle-equal."""
        pend = majority_fbas(5, prefix="RPL")
        journal = RequestJournal(tmp_path / "dead.journal")
        journal.append_request("orphan", fingerprint_of(pend), pend, None)
        journal.close()
        snaps, expected = self._snaps()
        faults.install_plan(faults.parse_faults("fleet.replay=error@1"))
        with _Fleet(tmp_path, n=2) as fleet:
            replayed = fleet.adopt_journal(journal.path)
            assert replayed == 0  # degraded: journal skipped, loudly
            outcomes = self._stream_parity(fleet, snaps, expected)
        assert all(kind == "ok" for kind, _ in outcomes)
        counters, _ = rec.snapshot()
        assert counters.get("fleet.replay_errors", 0) == 1


# ---------------------------------------------------------------------------
# forced interleavings


class TestFleetSchedules:
    def test_forced_interleavings_clean(self, rec):
        from tools.analyze.schedules import run_fleet_schedules

        results = run_fleet_schedules()
        # 5 schedules (route-during-eviction, replay-races-new-request,
        # respawn-restores-ring since ISSUE 12, hedge-races-primary-response
        # and scale-down-races-dispatch since ISSUE 19) × both topologies.
        assert len(results) == 10
        for r in results:
            assert r.ok, f"{r.schedule} on {r.topology}: {r.error}"


# ---------------------------------------------------------------------------
# transport split


class TestTransports:
    def test_socket_roundtrip_and_ping(self, rec):
        nodes = majority_fbas(5, prefix="SCK")
        engine = ServeEngine(backend="python")
        engine.start()
        server = SocketServeServer(engine, port=0)
        try:
            with socket.create_connection(("127.0.0.1", server.port),
                                          timeout=10.0) as conn:
                fh = conn.makefile("rw", encoding="utf-8")
                fh.write(json.dumps({"ping": "t1"}) + "\n")
                fh.flush()
                pong = json.loads(fh.readline())
                assert pong["pong"] == "t1" and pong["ready"] is True
                fh.write(json.dumps(
                    {"request_id": "sock-1", "nodes": nodes}
                ) + "\n")
                fh.flush()
                resp = json.loads(fh.readline())
                assert resp["request_id"] == "sock-1"
                assert resp["verdict"] is True
                assert "cert" not in resp  # emit_certs off by default
        finally:
            server.stop()
            engine.stop(drain=True, timeout=30.0)

    def test_fleet_cli_smoke_local_workers(self, rec, tmp_path):
        """The `fleet` subcommand over in-process workers: same JSONL
        contract as serve (module-level, no subprocess spawn)."""
        import io
        import sys

        from quorum_intersection_tpu.fleet import fleet_main

        lines = [json.dumps({"request_id": f"r{i}",
                             "nodes": majority_fbas(5, prefix="CLI")})
                 for i in range(3)]
        old_in, old_out = sys.stdin, sys.stdout
        sys.stdin = io.StringIO("\n".join(lines) + "\n")
        sys.stdout = io.StringIO()
        try:
            rc = fleet_main([
                "-n", "2", "--backend", "python", "--local-workers",
                "--journal-dir", str(tmp_path / "cli"),
            ])
            out = sys.stdout.getvalue()
        finally:
            sys.stdin, sys.stdout = old_in, old_out
        assert rc == 0
        responses = [json.loads(ln) for ln in out.splitlines()]
        assert responses[0]["kind"] == "fleet"
        verdicts = {r["request_id"]: r["verdict"]
                    for r in responses if "verdict" in r}
        assert verdicts == {"r0": True, "r1": True, "r2": True}


# ---------------------------------------------------------------------------
# zipfian churn skew (fbas/synth.py satellite)


class TestChurnSkew:
    def test_default_skew_is_byte_identical(self):
        base = majority_fbas(7, prefix="SKW")
        a = churn_trace(base, 10, seed=3)
        b = churn_trace(base, 10, seed=3, skew=0.0)
        assert json.dumps(a) == json.dumps(b)

    def test_skew_deterministic_with_revisits(self):
        base = majority_fbas(7, prefix="SKW")
        a = churn_trace(base, 30, seed=3, skew=1.1)
        b = churn_trace(base, 30, seed=3, skew=1.1)
        assert json.dumps(a) == json.dumps(b)
        assert len(a) == 31
        dumps = [json.dumps(s) for s in a]
        assert len(set(dumps)) < len(dumps)  # hot keys actually repeat

    def test_revisit_metas_point_at_identical_snapshots(self):
        base = majority_fbas(7, prefix="SKW")
        trace, metas = churn_trace_steps(base, 20, seed=5, skew=1.2)
        revisits = [m for m in metas if "revisit_of" in m]
        assert revisits, "skew=1.2 over 20 steps produced no revisit"
        for meta in revisits:
            assert meta["mutations"] == []
            assert meta["affected_scc_ids"] == []
            assert json.dumps(trace[meta["step"]]) \
                == json.dumps(trace[meta["revisit_of"]])

    def test_negative_skew_rejected(self):
        with pytest.raises(ValueError):
            churn_trace(majority_fbas(5), 2, skew=-0.5)


# ---------------------------------------------------------------------------
# fleet-aware health endpoints


class TestFleetHealth:
    def test_healthz_carries_fleet_gauges(self, rec, tmp_path):
        with _Fleet(tmp_path, n=2, probe_interval_s=0.05,
                    store_dir=tmp_path / "store") as fleet:
            fleet.submit(majority_fbas(5, prefix="HLZ")).result(timeout=60.0)
            _wait_counter(rec, "fleet.routed", 1)
            import time

            time.sleep(0.2)  # a probe cycle refreshes the aggregates
            payload = healthz_payload()
            assert payload["fleet_workers_live"] == 2
            assert payload["fleet_ring_size"] == 2

    def test_readyz_503_while_fleet_replays(self, rec):
        rec.gauge("fleet.replay_complete", 0)
        payload, status = readyz_payload()
        assert status == 503 and payload["status"] == "replaying"
        assert payload["fleet_replay_complete"] is False
        rec.gauge("fleet.replay_complete", 1)
        payload, status = readyz_payload()
        assert status == 200 and payload["fleet_replay_complete"] is True
