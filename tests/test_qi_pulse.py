"""qi-pulse suite (ISSUE 15): the mergeable histogram primitive
(unit/merge/property/Prometheus/JSONL), per-request wire trace
propagation front-door→worker→response→journal-replay, the pong-carried
aggregation plane (merged /metrics histogram == bucket-wise sum of the
worker scrapes; ``pulse.aggregate`` fault degrade parity), slow-request
exemplars (fire exactly for slow requests, never flip a verdict), and
the metrics_report cross-process graft + Chrome ``--merge`` exporter
with a pre-pulse-stream regression pin."""

import json
import random
import sys
import time

import pytest

from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import majority_fbas
from quorum_intersection_tpu.fleet import FleetEngine
from quorum_intersection_tpu.pipeline import solve
from quorum_intersection_tpu.serve import (
    RequestJournal,
    ServeEngine,
    snapshot_fingerprint,
)
from quorum_intersection_tpu.serve_transport import pong_payload
from quorum_intersection_tpu.utils import faults, telemetry
from quorum_intersection_tpu.utils.faults import FaultPlan, FaultRule
from quorum_intersection_tpu.utils.metrics_server import healthz_payload
from quorum_intersection_tpu.utils.telemetry import (
    DEFAULT_HIST_BOUNDS_MS,
    Histogram,
    TraceContext,
    hist_bounds,
    percentile,
    prom_lines,
)
from tools.metrics_report import (
    export_chrome,
    load_stream,
    render,
    span_table,
)


@pytest.fixture
def rec():
    record = telemetry.reset_run_record()
    faults.clear_plan()
    yield record
    faults.clear_plan()
    telemetry.reset_run_record()


class _Engine:
    """Context manager: a started ServeEngine that always stops."""

    def __init__(self, **kw):
        self.engine = ServeEngine(**kw)

    def __enter__(self):
        self.engine.start()
        return self.engine

    def __exit__(self, *exc):
        self.engine.stop(drain=True, timeout=30.0)
        return False


# ---------------------------------------------------------------------------
# the histogram primitive


class TestHistogram:
    def test_exact_count_and_sum(self):
        h = Histogram("t")
        for v in (0.1, 3.0, 700.0, 100000.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["count"] == 4
        assert abs(snap["sum"] - 100703.1) < 1e-6
        # one overflow bucket beyond the bounded edges
        assert len(snap["counts"]) == len(snap["bounds"]) + 1
        assert snap["counts"][-1] == 1  # the 100 s outlier

    def test_bucket_edges_are_inclusive(self):
        h = Histogram("t", bounds=(1.0, 2.0, 4.0))
        h.observe(2.0)  # exactly an upper edge: belongs to that bucket
        assert h.snapshot()["counts"] == [0, 1, 0, 0]

    def test_merge_equals_histogram_of_union(self):
        # The mergeability law the whole aggregation plane rests on:
        # merge(h(A), h(B)) == h(A + B), bucket-exact, over random data.
        rng = random.Random(7)
        a = [rng.uniform(0.01, 90000.0) for _ in range(700)]
        b = [rng.expovariate(1 / 50.0) for _ in range(400)]
        ha, hb, hu = Histogram("x"), Histogram("x"), Histogram("x")
        for v in a:
            ha.observe(v)
        for v in b:
            hb.observe(v)
        for v in a + b:
            hu.observe(v)
        merged = Histogram.merge_wire([ha.snapshot(), hb.snapshot()])
        union = hu.snapshot()
        assert merged["counts"] == union["counts"]
        assert merged["count"] == union["count"]
        assert abs(merged["sum"] - union["sum"]) < 1e-3

    def test_merge_refuses_mismatched_bounds(self):
        a = Histogram("a", bounds=(1.0, 2.0)).snapshot()
        b = Histogram("b", bounds=(1.0, 3.0)).snapshot()
        with pytest.raises(ValueError):
            Histogram.merge_wire([a, b])

    def test_set_from_wire_refuses_mismatched_bounds(self):
        h = Histogram("t", bounds=(1.0, 2.0))
        with pytest.raises(ValueError):
            h.set_from_wire(Histogram("o", bounds=(1.0, 4.0)).snapshot())

    def test_bucket_override_env(self, monkeypatch):
        monkeypatch.setenv("QI_PULSE_BUCKETS", "1, 2,4")
        assert hist_bounds() == (1.0, 2.0, 4.0)
        monkeypatch.setenv("QI_PULSE_BUCKETS", "4,2,nope")
        assert hist_bounds() == DEFAULT_HIST_BOUNDS_MS  # malformed: fallback
        # Duplicate edges would render duplicate Prometheus le labels
        # (the whole scrape would be rejected): strictly ascending only.
        monkeypatch.setenv("QI_PULSE_BUCKETS", "1,1,2")
        assert hist_bounds() == DEFAULT_HIST_BOUNDS_MS
        monkeypatch.delenv("QI_PULSE_BUCKETS")
        assert hist_bounds() == DEFAULT_HIST_BOUNDS_MS

    def test_window_percentile_is_the_legacy_estimator(self):
        h = Histogram("t")
        samples = [float(i) for i in range(1, 101)]
        for v in samples:
            h.observe(v)
        assert h.window_percentile(99.0) == percentile(samples, 99.0) == 99.0
        assert h.window_percentile(50.0) == percentile(samples, 50.0)

    def test_quantile_ms_is_bucket_upper_bound(self):
        h = Histogram("t", bounds=(1.0, 10.0, 100.0))
        for v in (0.5, 5.0, 5.0, 50.0):
            h.observe(v)
        assert h.quantile_ms(50.0) == 10.0  # rank 2 lands in the ≤10 bucket
        assert h.quantile_ms(100.0) == 100.0
        assert Histogram("e").quantile_ms(99.0) == 0.0

    def test_prometheus_rendering(self, rec):
        h = rec.histogram("pulse.e2e_ms")
        h.observe(0.1)
        h.observe(3.0)
        h.observe(10 ** 9)  # overflow bucket
        lines = prom_lines(rec)
        assert "# TYPE qi_pulse_e2e_ms histogram" in lines
        # Cumulative le convention; +Inf equals the exact count.
        assert 'qi_pulse_e2e_ms_bucket{le="+Inf"} 3' in lines
        assert 'qi_pulse_e2e_ms_bucket{le="4"} 2' in lines
        assert any(line.startswith("qi_pulse_e2e_ms_sum ") for line in lines)
        assert "qi_pulse_e2e_ms_count 3" in lines
        # Deterministic: two renders are byte-identical.
        assert lines == prom_lines(rec)

    def test_jsonl_final_lines(self, rec, tmp_path):
        rec.histogram("pulse.e2e_ms").observe(5.0)
        rec.histogram("pulse.untouched_ms")  # no samples: stays silent
        lines = rec.final_lines()
        hist_lines = [ln for ln in lines if ln["kind"] == "histogram"]
        assert [ln["name"] for ln in hist_lines] == ["pulse.e2e_ms"]
        assert hist_lines[0]["count"] == 1


# ---------------------------------------------------------------------------
# the reporter: cross-process graft, histogram section, chrome export


def _write_stream(path, lines):
    path.write_text("".join(json.dumps(ln) + "\n" for ln in lines))
    return str(path)


def _old_style_stream():
    """A PR-6-era two-process stream: colliding span ids, NO remote-parent
    fields — the pid-scoped lookup must keep the processes apart."""
    return [
        {"kind": "meta", "schema": "qi-telemetry/1", "pid": 100,
         "argv0": "a", "t_wall": 1000.0, "trace_id": "aaaa"},
        {"kind": "meta", "schema": "qi-telemetry/1", "pid": 200,
         "argv0": "b", "t_wall": 1000.5, "trace_id": "aaaa"},
        {"kind": "span", "name": "parent", "span_id": 1, "parent_id": None,
         "start_s": 0.0, "seconds": 1.0, "trace_id": "aaaa", "pid": 100,
         "tid": 1, "attrs": {}},
        {"kind": "span", "name": "child", "span_id": 2, "parent_id": 1,
         "start_s": 0.1, "seconds": 0.5, "trace_id": "aaaa", "pid": 100,
         "tid": 1, "attrs": {}},
        # Same ids in ANOTHER pid: must not graft under pid 100's parent.
        {"kind": "span", "name": "other_root", "span_id": 2, "parent_id": None,
         "start_s": 0.2, "seconds": 0.3, "trace_id": "aaaa", "pid": 200,
         "tid": 2, "attrs": {}},
        {"kind": "counter", "name": "c", "value": 3},
    ]


class TestReporter:
    def test_pre_pulse_stream_renders_unchanged(self, tmp_path):
        # The regression pin: no remote-parent fields ⇒ the tree is
        # exactly the old pid-scoped one (cross-pid spans stay roots) and
        # no histogram section appears.
        path = _write_stream(tmp_path / "old.jsonl", _old_style_stream())
        out = render(path)
        table = span_table(load_stream(path)["spans"])
        assert "  child" in table.splitlines()[3] or any(
            ln.startswith("  child") for ln in table.splitlines()
        )
        assert any(ln.startswith("other_root") for ln in table.splitlines())
        assert "latency histograms" not in out

    def test_remote_parent_grafts_across_pids(self, tmp_path):
        lines = _old_style_stream()
        # A qi-pulse worker span: thread root + wire-carried remote parent
        # pointing at pid 100's span 1 — must graft under it.
        lines.append({
            "kind": "span", "name": "serve.solve", "span_id": 9,
            "parent_id": None, "start_s": 0.3, "seconds": 0.4,
            "trace_id": "aaaa", "pid": 200, "tid": 2, "attrs": {},
            "remote_parent_span": 1, "remote_parent_pid": 100,
        })
        path = _write_stream(tmp_path / "graft.jsonl", lines)
        table = span_table(load_stream(path)["spans"])
        assert any(ln.startswith("  serve.solve")
                   for ln in table.splitlines())

    def test_histogram_lines_aggregate_bucketwise(self, tmp_path):
        bounds = [1.0, 10.0]
        lines = [
            {"kind": "histogram", "name": "pulse.e2e_ms", "bounds": bounds,
             "counts": [1, 2, 0], "count": 3, "sum": 12.0},
            {"kind": "histogram", "name": "pulse.e2e_ms", "bounds": bounds,
             "counts": [0, 1, 1], "count": 2, "sum": 105.0},
        ]
        path = _write_stream(tmp_path / "h.jsonl", lines)
        data = load_stream(path)
        agg = data["histograms"]["pulse.e2e_ms"]
        assert agg["counts"] == [1, 3, 1] and agg["count"] == 5
        assert abs(agg["sum"] - 117.0) < 1e-9
        assert "latency histograms" in render(path)

    def test_chrome_export_merge_flows(self, tmp_path):
        lines = _old_style_stream()
        lines.append({
            "kind": "span", "name": "serve.solve", "span_id": 9,
            "parent_id": None, "start_s": 0.3, "seconds": 0.4,
            "trace_id": "aaaa", "pid": 200, "tid": 2, "attrs": {},
            "remote_parent_span": 1, "remote_parent_pid": 100,
        })
        path = _write_stream(tmp_path / "c.jsonl", lines)
        plain = tmp_path / "plain.json"
        merged = tmp_path / "merged.json"
        export_chrome(load_stream(path), str(plain), merge=False)
        export_chrome(load_stream(path), str(merged), merge=True)
        plain_events = json.loads(plain.read_text())
        merged_events = json.loads(merged.read_text())
        assert not [e for e in plain_events if e["ph"] in ("s", "f")]
        flows = [e for e in merged_events if e["ph"] in ("s", "f")]
        assert {e["ph"] for e in flows} == {"s", "f"}
        start = next(e for e in flows if e["ph"] == "s")
        finish = next(e for e in flows if e["ph"] == "f")
        assert start["id"] == finish["id"]
        assert start["pid"] == 100 and finish["pid"] == 200


# ---------------------------------------------------------------------------
# serve: stage histograms, trace adoption, journal replay, exemplars


class TestServePulse:
    def test_stage_histograms_and_byte_compatible_gauges(self, rec):
        with _Engine(backend="python") as engine:
            for n in (3, 4, 3):  # one repeat ⇒ one cache hit
                engine.submit(majority_fbas(n)).result(timeout=60.0)
        hists = rec.histograms_snapshot()
        for name in ("pulse.queue_wait_ms", "pulse.cache_ms",
                     "pulse.solve_ms", "pulse.respond_ms", "pulse.e2e_ms"):
            assert hists[name]["count"] > 0, name
        _, gauges = rec.snapshot()
        h = rec.histogram("pulse.e2e_ms")
        assert gauges["serve.p50_ms"] == round(h.window_percentile(50.0), 3)
        assert gauges["serve.p99_ms"] == round(h.window_percentile(99.0), 3)

    def test_trace_adoption_and_response_echo(self, rec):
        wire = "feedbeef12345678:7:4242"
        with _Engine(backend="python") as engine:
            resp = engine.submit(
                majority_fbas(3), request_id="r0", trace=wire,
            ).result(timeout=60.0)
        assert resp.trace == wire
        admit = [sp for sp in rec.spans if sp.name == "serve.admit"]
        assert admit and admit[0].trace_id == "feedbeef12345678"
        assert admit[0].remote_parent_span == 7
        assert admit[0].remote_parent_pid == 4242
        solve_spans = [sp for sp in rec.spans if sp.name == "serve.solve"]
        assert solve_spans and all(
            sp.trace_id == "feedbeef12345678" for sp in solve_spans
        )
        # Spans the solve opens UNDER the adopted scope (the pipeline's
        # check_many span) carry the adopted trace too — the chain the
        # acceptance criterion pins: request span is an ancestor.
        inner = [sp for sp in rec.spans
                 if sp.trace_id == "feedbeef12345678"
                 and sp.name not in ("serve.admit", "serve.solve")]
        assert inner, [sp.name for sp in rec.spans]

    def test_coalesced_waiter_echoes_its_own_trace(self, rec):
        # Two clients, one fingerprint, two different wire traces: the
        # coalescer's response must echo ITS context, not the leader's.
        faults.install_plan(FaultPlan([
            FaultRule("serve.drain", "hang", first=1, every=False,
                      seconds=0.4),
        ]))
        try:
            with _Engine(backend="python") as engine:
                lead = engine.submit(majority_fbas(5), request_id="lead",
                                     trace="aaaa111100000000:1:10")
                time.sleep(0.1)  # lands inside the hung drain cycle
                coal = engine.submit(majority_fbas(5), request_id="coal",
                                     trace="bbbb222200000000:2:20")
                r1 = lead.result(timeout=60.0)
                r2 = coal.result(timeout=60.0)
        finally:
            faults.clear_plan()
        counters, _ = rec.snapshot()
        assert counters.get("serve.coalesced") == 1
        assert r1.trace == "aaaa111100000000:1:10"
        assert r2.trace == "bbbb222200000000:2:20"

    def test_traceless_requests_stay_pre_pulse(self, rec):
        with _Engine(backend="python") as engine:
            resp = engine.submit(majority_fbas(3)).result(timeout=60.0)
        assert resp.trace is None
        assert all(sp.trace_id == rec.trace_id for sp in rec.spans)
        assert all(sp.remote_parent_span is None for sp in rec.spans)

    def test_journal_carries_trace_and_replay_adopts(self, rec, tmp_path):
        nodes = majority_fbas(3)
        fp = snapshot_fingerprint(build_graph(parse_fbas(nodes)))
        wire = "cafe0123deadbeef:9:77"
        journal = RequestJournal(tmp_path / "j.journal")
        assert journal.append_request("lost-1", fp, nodes, None, trace=wire)
        journal.close()
        raw = (tmp_path / "j.journal").read_text()
        assert json.loads(raw.splitlines()[1])["trace"] == wire
        with _Engine(backend="python", journal=tmp_path / "j.journal",
                     batch_max=1) as engine:
            report = engine._replay_report
            assert report["verdicts"] == {"lost-1": True}
        replayed = [sp for sp in rec.spans
                    if sp.trace_id == "cafe0123deadbeef"]
        assert replayed, "replay did not re-adopt the journaled trace"
        roots = [sp for sp in replayed if sp.remote_parent_span is not None]
        assert roots and roots[0].remote_parent_span == 9
        assert roots[0].remote_parent_pid == 77

    def test_exemplar_fires_exactly_for_slow_requests(
            self, rec, tmp_path, monkeypatch):
        flight = tmp_path / "flight.json"
        monkeypatch.setenv("QI_PULSE_SLOW_MS", "60")
        monkeypatch.setenv("QI_FLIGHT_RECORDER", str(flight))
        # Hang the SECOND drain cycle only: request 1 serves fast (no
        # exemplar), request 2 crosses the threshold (one exemplar).
        faults.install_plan(FaultPlan([
            FaultRule("serve.drain", "hang", first=2, every=True,
                      seconds=0.25),
        ]))
        with _Engine(backend="python") as engine:
            fast = engine.submit(majority_fbas(3)).result(timeout=60.0)
            slow = engine.submit(majority_fbas(4)).result(timeout=60.0)
        faults.clear_plan()
        assert fast.intersects is True and slow.intersects is True
        counters, _ = rec.snapshot()
        assert counters.get("pulse.exemplars") == 1
        exemplar = json.loads((tmp_path / "flight.json.exemplar").read_text())
        assert exemplar["schema"] == "qi-exemplar/1"
        assert exemplar["reason"] == "slow-request"
        assert exemplar["e2e_ms"] > 60
        assert exemplar["stages"]["e2e_ms"] == exemplar["e2e_ms"]
        assert "queue_wait_ms" in exemplar["stages"]
        assert isinstance(exemplar["tail"], list) and exemplar["tail"]

    def test_exemplars_off_by_default(self, rec):
        with _Engine(backend="python") as engine:
            engine.submit(majority_fbas(3)).result(timeout=60.0)
        counters, _ = rec.snapshot()
        assert counters.get("pulse.exemplars", 0) == 0

    def test_pong_carries_pulse_snapshots(self, rec):
        with _Engine(backend="python") as engine:
            engine.submit(majority_fbas(3)).result(timeout=60.0)
        pong = pong_payload("tok")
        assert pong["pulse"]["pulse.e2e_ms"]["count"] >= 1
        assert "fleet.pulse.e2e_ms" not in pong["pulse"]


# ---------------------------------------------------------------------------
# fleet: request span, merged histograms, aggregate fault degrade


class _Fleet:
    def __init__(self, n=2, **kw):
        kw.setdefault("worker_mode", "local")
        kw.setdefault("backend", "python")
        kw.setdefault("probe_interval_s", 0.05)
        self.engine = FleetEngine(n, **kw)

    def __enter__(self):
        self.engine.start()
        return self.engine

    def __exit__(self, *exc):
        self.engine.stop(drain=True)
        return False


def _wait_for_merge(rec, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        snap = rec.histograms_snapshot().get("fleet.pulse.e2e_ms")
        if snap and snap["count"] > 0:
            return snap
        time.sleep(0.05)
    raise AssertionError("aggregation plane never merged worker pulses")


class TestFleetPulse:
    def test_end_to_end_trace_identity_local(self, rec):
        with _Fleet(2) as fleet:
            resp = fleet.submit(majority_fbas(3), request_id="q1").result(
                timeout=60.0,
            )
        assert resp.intersects is True
        ctx = TraceContext.from_env(resp.trace)
        assert ctx is not None and ctx.trace_id == rec.trace_id
        req_spans = [sp for sp in rec.spans if sp.name == "fleet.request"]
        assert req_spans and ctx.span_id in {sp.span_id for sp in req_spans}
        # The worker's admission span grafts under the front door's
        # request span: same trace, remote parent == fleet.request.
        admits = [sp for sp in rec.spans if sp.name == "serve.admit"
                  and sp.remote_parent_span == ctx.span_id]
        assert admits and admits[0].trace_id == rec.trace_id
        hists = rec.histograms_snapshot()
        assert hists["pulse.route_ms"]["count"] >= 1
        assert hists["pulse.fleet_e2e_ms"]["count"] >= 1

    def test_merged_metrics_equal_sum_of_worker_scrapes(self, rec):
        with _Fleet(2) as fleet:
            for n in (3, 4, 5, 3):
                fleet.submit(majority_fbas(n)).result(timeout=60.0)
            merged = _wait_for_merge(rec)
            health = fleet.healthz()
        # One snapshot per distinct worker PROCESS (local workers share
        # one record, so their pongs alias the same histogram — summing
        # them would double-count; the plane dedupes by pid).
        by_pid = {
            w.get("pid"): w["pulse"]["pulse.e2e_ms"]
            for w in health["workers"].values()
            if isinstance(w.get("pulse"), dict) and "pulse.e2e_ms" in w["pulse"]
        }
        assert by_pid, health
        expected = Histogram.merge_wire(list(by_pid.values()))
        assert merged["counts"] == expected["counts"]
        assert merged["count"] == expected["count"]
        assert abs(merged["sum"] - expected["sum"]) < 1e-6
        _, gauges = rec.snapshot()
        assert gauges["fleet.e2e_p99_ms"] > 0
        assert healthz_payload()["fleet_e2e_p99_ms"] == \
            gauges["fleet.e2e_p99_ms"]

    def test_pulse_aggregate_fault_degrades_not_verdicts(self, rec):
        faults.install_plan(FaultPlan([
            FaultRule("pulse.aggregate", "error", first=1, every=True),
        ]))
        try:
            with _Fleet(2) as fleet:
                verdicts = [
                    fleet.submit(majority_fbas(n)).result(timeout=60.0)
                    for n in (3, 4)
                ]
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    counters, _ = rec.snapshot()
                    if counters.get("pulse.agg_errors", 0) > 0:
                        break
                    time.sleep(0.05)
        finally:
            faults.clear_plan()
        assert [r.intersects for r in verdicts] == [True, True]
        counters, _ = rec.snapshot()
        assert counters.get("pulse.agg_errors", 0) > 0
        # Per-worker metrics stayed; the merged view never formed.
        hists = rec.histograms_snapshot()
        assert "fleet.pulse.e2e_ms" not in hists
        assert hists["pulse.e2e_ms"]["count"] > 0

    def test_pulse_agg_off_switch(self, rec, monkeypatch):
        monkeypatch.setenv("QI_PULSE_AGG", "0")
        with _Fleet(2) as fleet:
            fleet.submit(majority_fbas(3)).result(timeout=60.0)
            time.sleep(0.3)  # several probe cycles
        assert "fleet.pulse.e2e_ms" not in rec.histograms_snapshot()


@pytest.mark.slow
class TestSubprocessDifferential:
    """The real cross-process pin: one subprocess worker, front-door
    trace_id in the worker's OWN telemetry stream, echoed on the wire."""

    def test_trace_crosses_the_pipe(self, rec, tmp_path, monkeypatch):
        stream = tmp_path / "worker.jsonl"
        monkeypatch.setenv("QI_METRICS_JSON", str(stream))
        engine = FleetEngine(
            1, worker_mode="subprocess", backend="python",
            journal_dir=tmp_path / "fleet",
        )
        engine.start()
        try:
            resp = engine.submit(
                majority_fbas(3), request_id="x1",
            ).result(timeout=120.0)
        finally:
            engine.stop(drain=True)
        assert resp.intersects is True
        ctx = TraceContext.from_env(resp.trace)
        assert ctx is not None and ctx.trace_id == rec.trace_id
        lines = [json.loads(ln) for ln in stream.read_text().splitlines()]
        worker_spans = [
            ln for ln in lines
            if ln.get("kind") == "span" and ln.get("pid") != rec.pid
            and ln.get("trace_id") == rec.trace_id
        ]
        assert worker_spans, "no worker span joined the front door's trace"
        assert any(ln.get("remote_parent_pid") == rec.pid
                   for ln in worker_spans)
        oracle = solve(majority_fbas(3), backend="python")
        assert resp.intersects == oracle.intersects
