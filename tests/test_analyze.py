"""qi-analyze (ISSUE 3 tentpole): lint rules, typing gate, CLI contract.

Per-rule fixture pairs live in tests/analyze_fixtures/ — the bad file must
yield EXACTLY one finding (for its rule, and under the full rule set), the
good twin zero.  The fixtures are parsed, never imported, so deliberately
broken code costs nothing at runtime.  The repo itself must scan clean:
`python -m tools.analyze` exiting 0 at HEAD is the acceptance criterion the
analyze job in CI enforces forever after.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.analyze.lint import (
    DEFAULT_SCAN,
    RULES,
    FileContext,
    lint_file,
    run_lint,
)
from tools.analyze.typing_gate import (
    TYPING_TARGETS,
    annotation_coverage,
    run_typing_gate,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = REPO_ROOT / "tests" / "analyze_fixtures"

RULE_FIXTURES = {
    "jax-tracer-leak": "tracer_leak",
    "span-balance": "span_balance",
    "lock-discipline": "lock_discipline",
    "cancel-token-plumbed": "cancel_token",
    "no-bare-env-read": "env_read",
    "import-at-top": "import_at_top",
    # Path-gated rule: its fixture pair lives under a backends/ subdir so
    # the relative path matches the gate (the rule is scoped to engines).
    "degrade-via-ladder": "backends/degrade_via_ladder",
    # ISSUE 13: telemetry/fault names must stay statically extractable so
    # the qi-surface registry drift gate sees every emission.
    "telemetry-name-literal": "telemetry_name_literal",
}


def fixture_path(kind, stem):
    """``bad``/``good`` fixture path for a stem that may carry a subdir."""
    rel = Path(stem)
    return FIXTURES / rel.parent / f"{kind}_{rel.name}.py"


class TestRuleFixtures:
    @pytest.mark.parametrize("rule,stem", sorted(RULE_FIXTURES.items()))
    def test_bad_fixture_yields_exactly_one_finding(self, rule, stem):
        path = fixture_path("bad", stem)
        findings = lint_file(path, root=REPO_ROOT, rules=[rule])
        assert len(findings) == 1, findings
        assert findings[0].rule == rule
        # The flagged line is the one the fixture marks BAD.
        marked = [
            i + 1 for i, line in enumerate(path.read_text().splitlines())
            if "BAD" in line
        ]
        assert findings[0].line in marked
        # No OTHER rule fires on the fixture either: one bad file isolates
        # one failure mode.
        assert lint_file(path, root=REPO_ROOT) == findings

    @pytest.mark.parametrize("rule,stem", sorted(RULE_FIXTURES.items()))
    def test_good_fixture_is_clean(self, rule, stem):
        path = fixture_path("good", stem)
        assert lint_file(path, root=REPO_ROOT) == []

    def test_every_rule_has_a_fixture_pair(self):
        assert set(RULE_FIXTURES) == set(RULES)
        for stem in RULE_FIXTURES.values():
            assert fixture_path("bad", stem).is_file()
            assert fixture_path("good", stem).is_file()


class TestSuppression:
    def test_inline_allow_suppresses_only_named_rule(self, tmp_path):
        src = (
            "def f():\n"
            "    # qi-lint: allow(import-at-top) — justified here\n"
            "    import threading\n"
            "    return threading.Event()\n"
        )
        p = tmp_path / "suppressed.py"
        p.write_text(src)
        assert lint_file(p) == []
        # A different rule's allow() does not mask the finding.
        p.write_text(src.replace("import-at-top", "span-balance"))
        findings = lint_file(p)
        assert [f.rule for f in findings] == ["import-at-top"]


class TestRepoClean:
    """The acceptance criterion: the repo at HEAD has zero findings."""

    def test_lint_clean(self):
        findings = run_lint(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_typing_gate_clean(self):
        findings, _notes = run_typing_gate(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_typing_targets_fully_annotated(self):
        # Stronger than the ratchet (which only forbids regression): the
        # PR that introduced the gate left every target at 100%.
        for entry in TYPING_TARGETS:
            p = REPO_ROOT / entry
            files = [p] if p.is_file() else sorted(p.rglob("*.py"))
            for f in files:
                coverage, total = annotation_coverage(f)
                assert coverage == 1.0, (f, coverage, total)

    def test_fixtures_outside_default_scan(self):
        # The deliberately-bad fixtures must never leak into the repo scan.
        from tools.analyze.lint import iter_python_files

        scanned = {str(p) for p in iter_python_files(REPO_ROOT, DEFAULT_SCAN)}
        assert not any("analyze_fixtures" in s for s in scanned)

    def test_surface_clean_and_inventory_current(self):
        # The whole-program drift gate (ISSUE 13): the registries agree
        # with the code, and the COMMITTED inventory matches a fresh
        # extraction (regenerating it in CI must produce no diff).
        from tools.analyze.surface import run_surface

        findings, _notes = run_surface(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_locks_clean(self):
        from tools.analyze.locks import run_locks

        findings, _notes = run_locks(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_wire_clean(self):
        from tools.analyze.wire import run_wire

        findings, _notes = run_wire(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_hygiene_clean(self):
        # ISSUE 18: the hot paths carry no unsanctioned host syncs,
        # recompile hazards, or in-loop transfers at HEAD.
        from tools.analyze.hygiene import run_hygiene

        findings, _notes = run_hygiene(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_conserve_clean_and_doc_table_current(self):
        # ISSUE 18: every declared conservation obligation proves on all
        # exit paths AND the docs mirror matches the frozen table.
        from tools.analyze.conserve import run_conserve

        findings, _notes = run_conserve(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)


class TestTypingRatchet:
    def test_regression_is_a_finding(self, tmp_path, monkeypatch):
        import tools.analyze.typing_gate as tg

        mod = tmp_path / "mod.py"
        mod.write_text("def f(x: int) -> int:\n    return x\n")
        ratchet = tmp_path / "ratchet.json"
        monkeypatch.setattr(tg, "TYPING_TARGETS", ("mod.py",))
        monkeypatch.setattr(tg, "RATCHET_PATH", ratchet)

        findings, _ = tg.run_typing_gate(tmp_path, update_ratchet=True)
        assert findings == []
        assert json.loads(ratchet.read_text())["annotation_coverage"] == {
            "mod.py": 1.0
        }
        # Drop an unannotated function in: coverage falls, the gate fails.
        mod.write_text(
            "def f(x: int) -> int:\n    return x\n\n\ndef g(y):\n    return y\n"
        )
        findings, _ = tg.run_typing_gate(tmp_path)
        assert len(findings) == 1
        assert "regressed" in findings[0].message

    def test_new_module_must_enter_fully_annotated(self, tmp_path, monkeypatch):
        import tools.analyze.typing_gate as tg

        (tmp_path / "newmod.py").write_text("def g(y):\n    return y\n")
        monkeypatch.setattr(tg, "TYPING_TARGETS", ("newmod.py",))
        monkeypatch.setattr(tg, "RATCHET_PATH", tmp_path / "ratchet.json")
        findings, _ = tg.run_typing_gate(tmp_path)
        assert len(findings) == 1
        assert "full annotation coverage" in findings[0].message


class TestAnalyzeCli:
    """The one entry point: exit codes and the qi-telemetry/1 findings
    stream tools/metrics_report.py renders."""

    def test_lint_and_typing_pass_exit_zero(self, tmp_path):
        out = tmp_path / "findings.jsonl"
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", "lint", "typing",
             "--jsonl", str(out)],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "CLEAN" in proc.stdout

        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert lines[0]["kind"] == "meta"
        assert lines[0]["schema"] == "qi-telemetry/1"
        counters = {
            l["name"]: l["value"] for l in lines if l["kind"] == "counter"
        }
        assert counters["analyze.findings"] == 0
        assert counters["analyze.lint_findings"] == 0

        # The stream parses through the standard report renderer.
        sys.path.insert(0, str(REPO_ROOT / "tools"))
        try:
            from metrics_report import load_stream, render

            data = load_stream(str(out))
            assert data["bad_lines"] == 0
            assert "qi-telemetry report" in render(str(out))
        finally:
            sys.path.pop(0)

    def test_findings_exit_nonzero_and_land_in_stream(self, tmp_path, monkeypatch):
        # Point the scan at a directory containing one bad fixture.
        bad_dir = tmp_path / "scan"
        bad_dir.mkdir()
        (bad_dir / "leak.py").write_text(
            (FIXTURES / "bad_import_at_top.py").read_text()
        )
        import tools.analyze.__main__ as main_mod
        import tools.analyze.lint as lint_mod

        monkeypatch.setattr(lint_mod, "DEFAULT_SCAN", ("scan",))
        monkeypatch.setattr(main_mod, "REPO_ROOT", tmp_path)
        out = tmp_path / "findings.jsonl"
        rc = main_mod.main(["lint", "--jsonl", str(out)])
        assert rc == 1
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        events = [l for l in lines if l["kind"] == "event"]
        assert len(events) == 1
        attrs = events[0]["attrs"]
        assert events[0]["name"] == "analyze.finding"
        assert attrs["rule"] == "import-at-top"
        assert attrs["file"] == "scan/leak.py"
        assert attrs["pass"] == "lint"

    def test_unknown_pass_is_a_usage_error(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", "nonsense"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 2
        assert "unknown pass" in proc.stderr


class TestEnvRegistry:
    """The runtime twin of no-bare-env-read (utils/env.py)."""

    def test_undeclared_name_raises(self):
        from quorum_intersection_tpu.utils.env import qi_env

        with pytest.raises(KeyError, match="QI_NOT_A_THING"):
            qi_env("QI_NOT_A_THING")

    def test_defaults_and_overrides(self, monkeypatch):
        from quorum_intersection_tpu.utils.env import (
            qi_env,
            qi_env_flag,
            qi_env_float,
        )

        monkeypatch.delenv("QI_SANITIZER", raising=False)
        assert qi_env("QI_SANITIZER") == "asan"
        monkeypatch.setenv("QI_SANITIZER", "tsan")
        assert qi_env("QI_SANITIZER") == "tsan"
        monkeypatch.delenv("QI_LOG_JSON", raising=False)
        assert qi_env_flag("QI_LOG_JSON") is False
        monkeypatch.setenv("QI_LOG_JSON", "1")
        assert qi_env_flag("QI_LOG_JSON") is True
        monkeypatch.setenv("QI_FRONTIER_CKPT_INTERVAL_S", "0.25")
        assert qi_env_float("QI_FRONTIER_CKPT_INTERVAL_S") == 0.25
        monkeypatch.setenv("QI_FRONTIER_CKPT_INTERVAL_S", "bogus")
        assert qi_env_float("QI_FRONTIER_CKPT_INTERVAL_S") == 5.0  # default

    def test_registry_documents_every_declared_var(self):
        from quorum_intersection_tpu.utils.env import registry

        names = [v.name for v in registry()]
        assert len(names) == len(set(names))
        for var in registry():
            assert var.name.startswith("QI_")
            assert len(var.description) > 20  # a real contract, not a stub


class TestLockDisciplineSubRules:
    """The two sub-rules the fixture pair doesn't isolate: nested lock
    acquisition and emit-under-lock."""

    def _findings(self, src, tmp_path):
        p = tmp_path / "sample.py"
        p.write_text(src)
        return lint_file(p, rules=["lock-discipline"])

    def test_nested_lock_acquisition_flagged(self, tmp_path):
        src = (
            "import threading\n\n"
            "lock_a = threading.Lock()\n"
            "lock_b = threading.Lock()\n\n"
            "def f():\n"
            "    with lock_a:\n"
            "        with lock_b:\n"
            "            pass\n"
        )
        findings = self._findings(src, tmp_path)
        assert len(findings) == 1
        assert "nested lock" in findings[0].message
        assert findings[0].line == 8  # the INNER acquisition

    def test_sequential_locks_not_flagged(self, tmp_path):
        src = (
            "import threading\n\n"
            "lock_a = threading.Lock()\n\n"
            "def f():\n"
            "    with lock_a:\n"
            "        pass\n"
            "    with lock_a:\n"
            "        pass\n"
        )
        assert self._findings(src, tmp_path) == []

    def test_emit_under_lock_flagged(self, tmp_path):
        src = (
            "import threading\n\n"
            "lock = threading.Lock()\n\n"
            "def f(sink, line):\n"
            "    with lock:\n"
            "        sink.emit(line)\n"
        )
        findings = self._findings(src, tmp_path)
        assert len(findings) == 1
        assert "emit" in findings[0].message


class TestScheduleDegenerationIsLoud:
    """r.ok must be False when the forced ordering did not actually happen,
    even if the verdict matches (code-review finding: auto.py's worker
    swallows engine exceptions into sweep_error)."""

    def test_sweep_error_fails_the_schedule(self):
        from tools.analyze.schedules import ScheduleResult

        r = ScheduleResult(
            schedule="cancel_during_compile", topology="majority9",
            verdict=True, expected=True, winner="oracle",
            oracle_outcome="verdict", trace=["oracle.returned"],
            error="sweep_error: ScheduleError('gate held past 30s')",
        )
        assert not r.ok

    def test_missing_sync_point_is_detected(self, monkeypatch):
        # Break the ordering deliberately: a sweep engine that errors out
        # instead of parking in compile leaves sweep.unwound unreached and
        # sweep_error set — _run_one must report the schedule degenerate.
        import tools.analyze.schedules as sched
        from quorum_intersection_tpu.fbas.synth import majority_fbas

        class ExplodingSweep:
            name = "tpu-sweep"

            def __init__(self, cancel=None, compiling=None, **kw):
                self.cancel = cancel
                self.compiling = compiling

            def check_scc(self, *a, **k):
                if self.compiling is not None:
                    self.compiling.set()  # release the oracle's gate first
                raise RuntimeError("engine exploded in compile")

        monkeypatch.setattr(sched, "FakeSweep", ExplodingSweep)
        r = sched._run_one(
            "cancel_during_compile", majority_fbas(9), True, "majority9"
        )
        assert r.verdict is True  # the oracle still answered correctly...
        assert not r.ok  # ...but the harness refuses to call it clean
        assert r.error is not None and "sweep_error" in r.error


class TestSurfacePass:
    """qi-surface (ISSUE 13 tentpole): extraction, wildcard matching, every
    drift direction, inventory determinism + staleness."""

    FAULTS = {"fixture.point", "fixture.unfired"}
    ENV = {"QI_FIXTURE", "QI_UNREAD"}

    def _fixture_root(self, tmp_path, with_bad):
        import shutil

        root = tmp_path / "repo"
        shutil.copytree(FIXTURES / "surface" / "docs", root / "docs")
        shutil.copytree(FIXTURES / "surface" / "pkg", root / "pkg")
        if not with_bad:
            (root / "pkg" / "bad_emits.py").unlink()
        return root

    def _run(self, root, tmp_path, **kw):
        from tools.analyze.surface import run_surface

        kw.setdefault("inventory_path", tmp_path / "inv.json")
        return run_surface(
            root, scan=("pkg",), declared_faults=self.FAULTS,
            declared_env=self.ENV, **kw,
        )

    def test_planted_drift_directions_fire_exactly(self, tmp_path):
        # The GOOD emission file against registries with planted drift:
        # one finding per planted direction, nothing else.
        root = self._fixture_root(tmp_path, with_bad=False)
        findings, _ = self._run(root, tmp_path, update_inventory=True)
        by_rule = {}
        for f in findings:
            by_rule.setdefault(f.rule, []).append(f)
        assert sorted(by_rule) == [
            "surface-env-doc-stale",       # QI_GHOST row, undeclared
            "surface-env-unread",          # QI_UNREAD declared, never read
            "surface-fault-doc-stale",     # fixture.ghost row, undeclared
            "surface-fault-undocumented",  # fixture.unfired missing its row
            "surface-fault-unfired",       # fixture.unfired never fires
            "surface-registry-stale",      # fixture.stale row, never emitted
        ]
        assert all(len(v) == 1 for v in by_rule.values()), by_rule
        assert "fixture.stale" in by_rule["surface-registry-stale"][0].message

    def test_emission_side_drift_and_inventory_staleness(self, tmp_path):
        root = self._fixture_root(tmp_path, with_bad=False)
        self._run(root, tmp_path, update_inventory=True)  # bank the inventory
        # Adding the bad file changes the surface: unregistered counter,
        # undeclared fault point + env read, AND a stale inventory.
        import shutil

        shutil.copy(FIXTURES / "surface" / "pkg" / "bad_emits.py",
                    root / "pkg")
        findings, _ = self._run(root, tmp_path)
        rules = {f.rule for f in findings}
        assert "surface-telemetry-unregistered" in rules
        assert "surface-fault-undeclared" in rules
        assert "surface-env-undeclared" in rules
        assert "surface-inventory-stale" in rules
        bad = [f for f in findings
               if f.rule == "surface-telemetry-unregistered"]
        assert bad[0].path.endswith("bad_emits.py")
        marked = [
            i + 1 for i, line in enumerate(
                (root / "pkg" / "bad_emits.py").read_text().splitlines())
            if "BAD" in line
        ]
        assert bad[0].line in marked

    def test_registered_good_surface_is_clean(self, tmp_path):
        # With the planted-drift registry rows honored (unfired/unread
        # entries removed from the declared sets), the good file is CLEAN.
        root = self._fixture_root(tmp_path, with_bad=False)
        obs = (root / "docs" / "OBSERVABILITY.md").read_text()
        (root / "docs" / "OBSERVABILITY.md").write_text(
            "\n".join(l for l in obs.splitlines()
                      if "fixture.stale" not in l) + "\n")
        rob = (root / "docs" / "ROBUSTNESS.md").read_text()
        (root / "docs" / "ROBUSTNESS.md").write_text(
            "\n".join(l for l in rob.splitlines()
                      if "ghost" not in l and "GHOST" not in l) + "\n")
        from tools.analyze.surface import run_surface

        findings, _ = run_surface(
            root, scan=("pkg",), inventory_path=tmp_path / "inv.json",
            declared_faults={"fixture.point"}, declared_env={"QI_FIXTURE"},
            update_inventory=True,
        )
        assert findings == [], "\n".join(f.render() for f in findings)
        # ... and a second run against the banked inventory stays clean.
        findings, _ = run_surface(
            root, scan=("pkg",), inventory_path=tmp_path / "inv.json",
            declared_faults={"fixture.point"}, declared_env={"QI_FIXTURE"},
        )
        assert findings == []

    def test_inventory_deterministic_across_runs(self):
        import json

        from tools.analyze.surface import extract_surface

        a = json.dumps(extract_surface(REPO_ROOT).to_inventory(),
                       sort_keys=True)
        b = json.dumps(extract_surface(REPO_ROOT).to_inventory(),
                       sort_keys=True)
        assert a == b

    def test_committed_inventory_matches_fresh_extraction(self):
        import json

        from tools.analyze.surface import INVENTORY_PATH, extract_surface

        committed = json.loads(INVENTORY_PATH.read_text())
        assert committed == extract_surface(REPO_ROOT).to_inventory()
        assert committed["schema"] == "qi-surface/1"
        # The journal field-stability slice the wire pass banks here.
        assert "kind" in committed["wire"]["serve.journal"]["producer"]
        assert "fingerprint" in committed["wire"]["serve.journal"]["consumer"]

    def test_wildcard_matching(self):
        from tools.analyze.surface import _covered

        assert _covered("phase.parse", {"phase.*"})
        assert _covered("phase.*", {"phase.parse"})   # wildcard vs exact row
        assert _covered("bench.*", {"bench.*"})
        assert not _covered("serve.batch", {"phase.*"})
        assert not _covered("phaseparse", {"phase.*"})
        # Mid-name placeholders (`serve.<op>.latency` rows) must match the
        # concrete emission (code-review finding).
        assert _covered("serve.drain.latency", {"serve.*.latency"})
        assert not _covered("serve.drain.count", {"serve.*.latency"})

    def test_keyword_name_argument_is_extracted(self, tmp_path):
        # rec.add(name="...") / fault_point(name="...") are legal call
        # shapes and must not bypass extraction (code-review finding).
        from tools.analyze.surface import Surface, _extract_file
        from tools.analyze.lint import FileContext

        src = (
            "def f(rec):\n"
            "    rec.add(name='kw.counter')\n"
            "    fault_point(name='kw.point')\n"
            "    qi_env(name='QI_KW')\n"
        )
        p = tmp_path / "m.py"
        p.write_text(src)
        surface = Surface()
        _extract_file(FileContext(p, "m.py", src), surface)
        assert "kw.counter" in surface.names("counter")
        assert {e.name for e in surface.fault_fires} == {"kw.point"}
        assert {e.name for e in surface.env_reads} == {"QI_KW"}

    def test_code_side_findings_honor_allow_suppression(self, tmp_path):
        # The qi-lint suppression discipline applies to surface findings
        # at the emitting call site (doc-side rows have no code line).
        import shutil

        root = self._fixture_root(tmp_path, with_bad=False)
        (root / "pkg" / "suppressed.py").write_text(
            "from quorum_intersection_tpu.utils.telemetry import "
            "get_run_record\n\n\n"
            "def emit() -> None:\n"
            "    rec = get_run_record()\n"
            "    # qi-lint: allow(surface-telemetry-unregistered) — "
            "fixture reason\n"
            "    rec.add('fixture.suppressed_counter')\n"
        )
        findings, _ = self._run(root, tmp_path, update_inventory=True)
        assert "surface-telemetry-unregistered" not in {
            f.rule for f in findings
        }, findings

    def test_placeholderless_fstring_is_exact_not_wildcard(self, tmp_path):
        from tools.analyze.lint import FileContext, resolve_name_arg
        import ast as ast_mod

        src = "def f(rec):\n    rec.add(f'serve.hits')\n"
        p = tmp_path / "m.py"
        p.write_text(src)
        ctx = FileContext(p, "m.py", src)
        call = next(n for n in ast_mod.walk(ctx.tree)
                    if isinstance(n, ast_mod.Call))
        assert resolve_name_arg(ctx, call.args[0]) == "serve.hits"

    def test_conditional_and_fstring_names_extract(self, tmp_path):
        from tools.analyze.lint import FileContext, resolve_name_args

        src = (
            "K = 'mod.const'\n"
            "def f(rec, flag, kind):\n"
            "    rec.add('a.hits' if flag else 'a.misses')\n"
            "    rec.event(f'q.{kind}')\n"
            "    rec.gauge(K, 1)\n"
            "    rec.add('x' + kind)\n"
        )
        p = tmp_path / "m.py"
        p.write_text(src)
        ctx = FileContext(p, "m.py", src)
        import ast as ast_mod

        calls = [n for n in ast_mod.walk(ctx.tree)
                 if isinstance(n, ast_mod.Call)]
        resolved = [resolve_name_args(ctx, c.args[0]) for c in calls]
        assert ["a.hits", "a.misses"] in resolved
        assert ["q.*"] in resolved
        assert ["mod.const"] in resolved
        assert [] in resolved  # concatenation: unextractable


class TestLocksPass:
    """qi-locks (ISSUE 13 tentpole): one fixture pair per finding kind."""

    PAIRS = {
        "lock-order-cycle": "locks/lock_order",
        "lock-blocking": "locks/lock_blocking",
        "lock-guardian": "locks/lock_guardian",
    }

    @pytest.mark.parametrize("rule,stem", sorted(PAIRS.items()))
    def test_bad_fixture_yields_exactly_one_finding(self, rule, stem):
        from tools.analyze.locks import run_locks

        rel = str(Path("tests/analyze_fixtures") / f"{Path(stem).parent}" /
                  f"bad_{Path(stem).name}.py")
        findings, _ = run_locks(REPO_ROOT, targets=[rel])
        assert [f.rule for f in findings] == [rule], findings
        marked = [
            i + 1 for i, line in enumerate(
                (REPO_ROOT / rel).read_text().splitlines())
            if "BAD" in line
        ]
        assert findings[0].line in marked

    @pytest.mark.parametrize("rule,stem", sorted(PAIRS.items()))
    def test_good_fixture_is_clean(self, rule, stem):
        from tools.analyze.locks import run_locks

        rel = str(Path("tests/analyze_fixtures") / f"{Path(stem).parent}" /
                  f"good_{Path(stem).name}.py")
        findings, _ = run_locks(REPO_ROOT, targets=[rel])
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_suppression_applies(self, tmp_path):
        from tools.analyze.locks import run_locks

        src = (REPO_ROOT / "tests/analyze_fixtures/locks/"
               "bad_lock_blocking.py").read_text()
        src = src.replace(
            "            subprocess.run",
            "            # qi-lint: allow(lock-blocking) — fixture reason\n"
            "            subprocess.run",
        )
        (tmp_path / "suppressed.py").write_text(src)
        findings, _ = run_locks(tmp_path, targets=["suppressed.py"])
        assert findings == []

    def test_rlock_reentry_is_not_a_cycle(self, tmp_path):
        # RLocks exist to re-enter: a re-acquisition through a call edge
        # must not be reported as a deadlock (code-review finding).
        from tools.analyze.locks import run_locks

        (tmp_path / "reentrant.py").write_text(
            "import threading\n\n\n"
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            self.inner()\n\n"
            "    def inner(self):\n"
            "        with self._lock:\n"
            "            pass\n"
        )
        findings, _ = run_locks(tmp_path, targets=["reentrant.py"])
        assert findings == [], findings
        # The plain-Lock twin IS a re-entry deadlock.
        (tmp_path / "plain.py").write_text(
            (tmp_path / "reentrant.py").read_text().replace("RLock", "Lock")
        )
        findings, _ = run_locks(tmp_path, targets=["plain.py"])
        assert [f.rule for f in findings] == ["lock-order-cycle"]
        assert findings[0].message.count("R._lock") >= 2  # the self-cycle

    def test_blocking_in_locked_helper_is_interprocedural(self, tmp_path):
        # A *_locked helper's sleep inherits the caller's lock via
        # entry_held and must still be a finding (code-review finding).
        from tools.analyze.locks import run_locks

        (tmp_path / "helper.py").write_text(
            "import threading\n"
            "import time\n\n\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n\n"
            "    def slow(self):\n"
            "        with self._lock:\n"
            "            self._slow_locked()\n\n"
            "    def _slow_locked(self):\n"
            "        time.sleep(5)\n"
        )
        findings, _ = run_locks(tmp_path, targets=["helper.py"])
        assert [f.rule for f in findings] == ["lock-blocking"], findings
        assert "time.sleep" in findings[0].message

    def test_thread_target_entry_resets_entry_held(self, tmp_path):
        # A function used BOTH as a thread target and as a callee under a
        # lock starts lock-free on the thread side: its lock-free mutation
        # must stay a guardian finding (code-review finding).
        from tools.analyze.locks import run_locks

        (tmp_path / "dual.py").write_text(
            "import threading\n\n\n"
            "class D:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.items = []\n"
            "        self.t = threading.Thread(target=self._work)\n\n"
            "    def inline(self):\n"
            "        with self._lock:\n"
            "            self._work()\n\n"
            "    def _work(self):\n"
            "        self.items.append(1)\n"
        )
        findings, _ = run_locks(tmp_path, targets=["dual.py"])
        assert "lock-guardian" in {f.rule for f in findings}, findings

    def test_condition_alias_is_one_lock(self):
        # Condition(self._lock) aliases to _lock: the sanctioned wait in
        # the good blocking fixture must resolve to the SAME lock id.
        from tools.analyze.locks import build_model

        model = build_model(
            REPO_ROOT,
            ["tests/analyze_fixtures/locks/good_lock_blocking.py"],
        )
        cls = next(iter(model.classes.values()))
        assert cls.lock_id("_done") == cls.lock_id("_lock")


class TestWirePass:
    """qi-wire (ISSUE 13 tentpole): producer ⊇ consumer per channel, site
    integrity, and the real protocol's extraction shape."""

    def _patched(self, monkeypatch, specs):
        import tools.analyze.wire as wire_mod

        monkeypatch.setattr(wire_mod, "CHANNEL_SPECS", specs)
        return wire_mod

    def test_unproduced_consumer_field_is_a_finding(self, monkeypatch):
        wire_mod = self._patched(monkeypatch, (
            ("fixture",
             (("tests/analyze_fixtures/wire/bad_channel.py", "produce"),),
             (("tests/analyze_fixtures/wire/bad_channel.py", "consume",
               ("obj",)),)),
        ))
        findings, _ = wire_mod.run_wire(REPO_ROOT)
        assert [f.rule for f in findings] == ["wire-consumer-unproduced"]
        assert "'missing'" in findings[0].message
        marked = [
            i + 1 for i, line in enumerate(
                (REPO_ROOT / "tests/analyze_fixtures/wire/bad_channel.py"
                 ).read_text().splitlines())
            if "BAD" in line
        ]
        assert findings[0].line in marked

    def test_matched_channel_is_clean(self, monkeypatch):
        wire_mod = self._patched(monkeypatch, (
            ("fixture",
             (("tests/analyze_fixtures/wire/good_channel.py", "produce"),),
             (("tests/analyze_fixtures/wire/good_channel.py", "consume",
               ("obj",)),)),
        ))
        findings, _ = wire_mod.run_wire(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_missing_site_is_loud(self, monkeypatch):
        # A refactor that moves a spec'd function must fail the gate, not
        # silently stop checking the protocol.
        wire_mod = self._patched(monkeypatch, (
            ("fixture",
             (("tests/analyze_fixtures/wire/good_channel.py", "vanished"),),
             (("tests/analyze_fixtures/wire/good_channel.py", "consume",
               ("obj",)),)),
        ))
        findings, _ = wire_mod.run_wire(REPO_ROOT)
        assert "wire-site-missing" in {f.rule for f in findings}

    def test_real_channels_extract_the_protocol(self):
        from tools.analyze.wire import extract_channels

        channels = {c.name: c for c in extract_channels(REPO_ROOT)}
        assert not any(c.findings for c in channels.values())
        req = channels["serve.request"]
        assert {"request_id", "nodes", "deadline_s", "query", "ping"} \
            <= set(req.consumer_fields)
        assert set(req.consumer_fields) <= set(req.producer_fields)
        journal = channels["serve.journal"]
        assert {"kind", "request_id", "fingerprint", "nodes", "query"} \
            <= set(journal.consumer_fields)
        resp = channels["serve.response"]
        assert {"verdict", "cached", "error", "code", "message", "cert",
                "stats", "result", "pong"} <= set(resp.consumer_fields)
        for ch in channels.values():
            missing = set(ch.consumer_fields) - set(ch.producer_fields)
            assert not missing, (ch.name, missing)


class TestTracerLeakPrecision:
    """The rule must track taint, not pattern-match: static closure config
    stays branchable, lax callbacks inherit taint."""

    def _findings(self, src, tmp_path):
        p = tmp_path / "sample.py"
        p.write_text(src)
        return lint_file(p, rules=["jax-tracer-leak"])

    def test_lax_callback_params_are_tainted(self, tmp_path):
        src = (
            "import jax\n"
            "from jax import lax\n\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    def body(i, best):\n"
            "        if best > 0:\n"
            "            return best\n"
            "        return best + i\n"
            "    return lax.fori_loop(0, 4, body, x)\n"
        )
        findings = self._findings(src, tmp_path)
        assert [f.rule for f in findings] == ["jax-tracer-leak"]

    def test_static_closure_branch_not_flagged(self, tmp_path):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n\n"
            "def factory(steps):\n"
            "    @jax.jit\n"
            "    def step(x):\n"
            "        if steps == 1:\n"
            "            return jnp.sum(x)\n"
            "        return jnp.sum(x) * steps\n"
            "    return step\n"
        )
        assert self._findings(src, tmp_path) == []

    def test_jit_wrapped_local_function_is_traced(self, tmp_path):
        src = (
            "import jax\n\n"
            "def build():\n"
            "    def shard_fn(start):\n"
            "        if start > 0:\n"
            "            return start\n"
            "        return -start\n"
            "    return jax.jit(shard_fn)\n"
        )
        findings = self._findings(src, tmp_path)
        assert [f.rule for f in findings] == ["jax-tracer-leak"]


class TestHygienePass:
    """qi-hygiene (ISSUE 18 tentpole): one fixture pair per finding kind,
    hot-region seeding from the span inventory, and the witness chain."""

    PAIRS = {
        "hygiene-host-sync": "hygiene/host_sync",
        "hygiene-recompile-hazard": "hygiene/recompile_hazard",
        "hygiene-transfer-in-loop": "hygiene/transfer_in_loop",
    }

    @pytest.mark.parametrize("rule,stem", sorted(PAIRS.items()))
    def test_bad_fixture_yields_exactly_one_finding(self, rule, stem):
        from tools.analyze.hygiene import run_hygiene

        rel = str(Path("tests/analyze_fixtures") / f"{Path(stem).parent}" /
                  f"bad_{Path(stem).name}.py")
        findings, _ = run_hygiene(REPO_ROOT, targets=[rel])
        assert [f.rule for f in findings] == [rule], findings
        marked = [
            i + 1 for i, line in enumerate(
                (REPO_ROOT / rel).read_text().splitlines())
            if "BAD" in line
        ]
        assert findings[0].line in marked

    @pytest.mark.parametrize("rule,stem", sorted(PAIRS.items()))
    def test_good_fixture_is_clean(self, rule, stem):
        from tools.analyze.hygiene import run_hygiene

        rel = str(Path("tests/analyze_fixtures") / f"{Path(stem).parent}" /
                  f"good_{Path(stem).name}.py")
        findings, _ = run_hygiene(REPO_ROOT, targets=[rel])
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_finding_carries_hot_path_witness(self):
        from tools.analyze.hygiene import run_hygiene

        rel = "tests/analyze_fixtures/hygiene/bad_host_sync.py"
        findings, _ = run_hygiene(REPO_ROOT, targets=[rel])
        assert "[hot via span sweep.drive: drive]" in findings[0].message

    def test_suppression_applies(self, tmp_path):
        from tools.analyze.hygiene import run_hygiene

        src = (REPO_ROOT / "tests/analyze_fixtures/hygiene/"
               "bad_host_sync.py").read_text()
        src = src.replace(
            "            total += float(y)",
            "            # qi-lint: allow(hygiene-host-sync) — fixture\n"
            "            total += float(y)",
        )
        (tmp_path / "suppressed.py").write_text(src)
        findings, _ = run_hygiene(tmp_path, targets=["suppressed.py"])
        assert findings == [], findings

    def test_hot_region_seeded_from_span_inventory(self, tmp_path):
        # The seeding contract: a seed span missing from the qi-surface
        # inventory silently disables nothing — the function simply is
        # not hot, so renaming a drive span shows up as the inventory
        # diff (a reviewed contract change), not a stale hardcode.
        from tools.analyze.hygiene import run_hygiene

        rel = "tests/analyze_fixtures/hygiene/bad_host_sync.py"
        inv = tmp_path / "inventory.json"
        inv.write_text(json.dumps({"telemetry": {"span": []}}))
        findings, _ = run_hygiene(REPO_ROOT, targets=[rel],
                                  inventory_path=inv)
        assert findings == []
        inv.write_text(json.dumps({"telemetry": {"span": ["sweep.drive"]}}))
        findings, _ = run_hygiene(REPO_ROOT, targets=[rel],
                                  inventory_path=inv)
        assert [f.rule for f in findings] == ["hygiene-host-sync"]

    def test_cold_function_is_not_scanned(self, tmp_path):
        # The same sink outside any hot region must not be a finding:
        # the pass polices hot paths, not the whole package.
        from tools.analyze.hygiene import run_hygiene

        src = (REPO_ROOT / "tests/analyze_fixtures/hygiene/"
               "bad_host_sync.py").read_text()
        src = src.replace('rec.span("sweep.drive")', 'rec.span("cold.path")')
        (tmp_path / "cold.py").write_text(src)
        findings, _ = run_hygiene(tmp_path, targets=["cold.py"])
        assert findings == [], findings

    def test_injected_violation_fails_the_analyzer(self, tmp_path, monkeypatch):
        # Acceptance: dropping a fixture violation into package code makes
        # `python -m tools.analyze` exit nonzero.
        import tools.analyze.__main__ as main_mod

        pkg = tmp_path / "quorum_intersection_tpu"
        pkg.mkdir()
        (pkg / "hot.py").write_text(
            (REPO_ROOT / "tests/analyze_fixtures/hygiene/"
             "bad_host_sync.py").read_text()
        )
        monkeypatch.setattr(main_mod, "REPO_ROOT", tmp_path)
        out = tmp_path / "findings.jsonl"
        rc = main_mod.main(["hygiene", "--jsonl", str(out)])
        assert rc == 1
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        events = [l for l in lines if l["kind"] == "event"]
        assert len(events) == 1
        assert events[0]["attrs"]["rule"] == "hygiene-host-sync"
        assert events[0]["attrs"]["pass"] == "hygiene"


class TestConservePass:
    """qi-conserve (ISSUE 18 tentpole): fixture pairs for both obligation
    modes, suppression, region/table gates, and the injection acceptance."""

    @staticmethod
    def _leg_table(kind):
        return ((
            "fixture-cancel",
            f"tests/analyze_fixtures/conserve/{kind}_leg_missing.py:drain",
            "paired", "all",
            "sweep.windows_cancelled;cert.windows_cancelled", "fixture"),)

    @staticmethod
    def _exit_table(kind):
        return ((
            "fixture-closure",
            f"tests/analyze_fixtures/conserve/{kind}_exit_closure.py:resolve",
            "exit", "all", "serve.verdicts|serve.errors", "fixture"),)

    @pytest.mark.parametrize("stem,table_of", [
        ("leg_missing", "_leg_table"), ("exit_closure", "_exit_table"),
    ])
    def test_bad_fixture_yields_exactly_one_finding(self, stem, table_of):
        from tools.analyze.conserve import run_conserve

        rel = f"tests/analyze_fixtures/conserve/bad_{stem}.py"
        findings, _ = run_conserve(
            REPO_ROOT, targets=[rel], table=getattr(self, table_of)("bad"),
            check_docs=False)
        assert [f.rule for f in findings] == ["conserve-leg-missing"], findings
        marked = [
            i + 1 for i, line in enumerate(
                (REPO_ROOT / rel).read_text().splitlines())
            if "BAD" in line
        ]
        assert findings[0].line in marked

    @pytest.mark.parametrize("stem,table_of", [
        ("leg_missing", "_leg_table"), ("exit_closure", "_exit_table"),
    ])
    def test_good_fixture_is_clean(self, stem, table_of):
        from tools.analyze.conserve import run_conserve

        rel = f"tests/analyze_fixtures/conserve/good_{stem}.py"
        findings, _ = run_conserve(
            REPO_ROOT, targets=[rel], table=getattr(self, table_of)("good"),
            check_docs=False)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_suppression_applies(self, tmp_path):
        from tools.analyze.conserve import run_conserve

        src = (REPO_ROOT / "tests/analyze_fixtures/conserve/"
               "bad_leg_missing.py").read_text()
        src = src.replace(
            "            return done  # BAD",
            "            # qi-lint: allow(conserve-leg-missing) — fixture\n"
            "            return done  # BAD",
        )
        (tmp_path / "suppressed.py").write_text(src)
        table = (("fixture-cancel", "suppressed.py:drain", "paired", "all",
                  "sweep.windows_cancelled;cert.windows_cancelled", "f"),)
        findings, _ = run_conserve(tmp_path, targets=["suppressed.py"],
                                   table=table, check_docs=False)
        assert findings == [], findings

    def test_vanished_region_is_loud(self, tmp_path):
        from tools.analyze.conserve import run_conserve

        (tmp_path / "empty.py").write_text("x = 1\n")
        table = (("fixture-gone", "empty.py:drain", "paired", "all",
                  "sweep.windows_cancelled;cert.windows_cancelled", "f"),)
        findings, _ = run_conserve(tmp_path, targets=["empty.py"],
                                   table=table, check_docs=False)
        assert [f.rule for f in findings] == ["conserve-region-missing"]

    def test_region_that_books_nothing_is_loud(self, tmp_path):
        # A paired region that stopped booking ANY declared leg means the
        # invariant moved out from under the table — as loud as a break.
        from tools.analyze.conserve import run_conserve

        (tmp_path / "hollow.py").write_text(
            "def drain(rec, jobs):\n"
            "    for job in jobs:\n"
            "        job.run()\n"
            "    return len(jobs)\n"
        )
        table = (("fixture-hollow", "hollow.py:drain", "paired", "all",
                  "sweep.windows_cancelled;cert.windows_cancelled", "f"),)
        findings, _ = run_conserve(tmp_path, targets=["hollow.py"],
                                   table=table, check_docs=False)
        assert [f.rule for f in findings] == ["conserve-region-missing"]

    def test_raise_filter_ignores_return_paths(self, tmp_path):
        # exits="raise" scopes the obligation to abnormal exits only —
        # the shape the serve admission gate needs (normal admissions
        # close later, via the resolve regions).
        from tools.analyze.conserve import run_conserve

        (tmp_path / "admit.py").write_text(
            "def admit(rec, q, entry):\n"
            "    if q.full():\n"
            "        rec.add(\"serve.errors\", 1)\n"
            "        raise RuntimeError(\"shed\")\n"
            "    q.put_nowait(entry)\n"
            "    return \"queued\"\n"
        )
        table = (("fixture-admit", "admit.py:admit", "exit", "raise",
                  "serve.errors", "f"),)
        findings, _ = run_conserve(tmp_path, targets=["admit.py"],
                                   table=table, check_docs=False)
        assert findings == [], findings

    def test_doc_table_round_trips(self):
        from tools.analyze.conserve import (
            CONSERVATION_TABLE,
            doc_table_rows,
            render_table,
        )

        expected = [(r[0], r[1], r[2], r[3], r[4])
                    for r in CONSERVATION_TABLE]
        assert doc_table_rows(render_table()) == expected

    def test_missing_doc_mirror_is_drift(self, tmp_path):
        from tools.analyze.conserve import run_conserve

        (tmp_path / "empty.py").write_text("x = 1\n")
        findings, _ = run_conserve(tmp_path, targets=["empty.py"], table=(),
                                   check_docs=True)
        assert [f.rule for f in findings] == ["conserve-table-drift"]

    def test_injected_leg_drop_fails_the_analyzer(self, tmp_path):
        # Acceptance: re-introducing the pre-existing retire_job violation
        # (the operational cancel leg dropped) into a package copy makes
        # the conserve pass report it against the real table.
        import shutil

        from tools.analyze.conserve import run_conserve

        shutil.copytree(REPO_ROOT / "quorum_intersection_tpu",
                        tmp_path / "quorum_intersection_tpu")
        sweep = (tmp_path / "quorum_intersection_tpu" / "backends" / "tpu"
                 / "sweep.py")
        src = sweep.read_text()
        needle = (
            '            rec.add("sweep.windows_cancelled", dropped)\n'
            '            rec.add("cert.windows_cancelled", dropped)\n'
        )
        assert needle in src
        sweep.write_text(src.replace(
            needle, '            rec.add("cert.windows_cancelled", dropped)\n'))
        findings, _ = run_conserve(tmp_path, check_docs=False)
        assert any(
            f.rule == "conserve-leg-missing" and "sweep-retire-pack"
            in f.message for f in findings
        ), "\n".join(f.render() for f in findings)
