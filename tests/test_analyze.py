"""qi-analyze (ISSUE 3 tentpole): lint rules, typing gate, CLI contract.

Per-rule fixture pairs live in tests/analyze_fixtures/ — the bad file must
yield EXACTLY one finding (for its rule, and under the full rule set), the
good twin zero.  The fixtures are parsed, never imported, so deliberately
broken code costs nothing at runtime.  The repo itself must scan clean:
`python -m tools.analyze` exiting 0 at HEAD is the acceptance criterion the
analyze job in CI enforces forever after.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.analyze.lint import (
    DEFAULT_SCAN,
    RULES,
    FileContext,
    lint_file,
    run_lint,
)
from tools.analyze.typing_gate import (
    TYPING_TARGETS,
    annotation_coverage,
    run_typing_gate,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = REPO_ROOT / "tests" / "analyze_fixtures"

RULE_FIXTURES = {
    "jax-tracer-leak": "tracer_leak",
    "span-balance": "span_balance",
    "lock-discipline": "lock_discipline",
    "cancel-token-plumbed": "cancel_token",
    "no-bare-env-read": "env_read",
    "import-at-top": "import_at_top",
    # Path-gated rule: its fixture pair lives under a backends/ subdir so
    # the relative path matches the gate (the rule is scoped to engines).
    "degrade-via-ladder": "backends/degrade_via_ladder",
}


def fixture_path(kind, stem):
    """``bad``/``good`` fixture path for a stem that may carry a subdir."""
    rel = Path(stem)
    return FIXTURES / rel.parent / f"{kind}_{rel.name}.py"


class TestRuleFixtures:
    @pytest.mark.parametrize("rule,stem", sorted(RULE_FIXTURES.items()))
    def test_bad_fixture_yields_exactly_one_finding(self, rule, stem):
        path = fixture_path("bad", stem)
        findings = lint_file(path, root=REPO_ROOT, rules=[rule])
        assert len(findings) == 1, findings
        assert findings[0].rule == rule
        # The flagged line is the one the fixture marks BAD.
        marked = [
            i + 1 for i, line in enumerate(path.read_text().splitlines())
            if "BAD" in line
        ]
        assert findings[0].line in marked
        # No OTHER rule fires on the fixture either: one bad file isolates
        # one failure mode.
        assert lint_file(path, root=REPO_ROOT) == findings

    @pytest.mark.parametrize("rule,stem", sorted(RULE_FIXTURES.items()))
    def test_good_fixture_is_clean(self, rule, stem):
        path = fixture_path("good", stem)
        assert lint_file(path, root=REPO_ROOT) == []

    def test_every_rule_has_a_fixture_pair(self):
        assert set(RULE_FIXTURES) == set(RULES)
        for stem in RULE_FIXTURES.values():
            assert fixture_path("bad", stem).is_file()
            assert fixture_path("good", stem).is_file()


class TestSuppression:
    def test_inline_allow_suppresses_only_named_rule(self, tmp_path):
        src = (
            "def f():\n"
            "    # qi-lint: allow(import-at-top) — justified here\n"
            "    import threading\n"
            "    return threading.Event()\n"
        )
        p = tmp_path / "suppressed.py"
        p.write_text(src)
        assert lint_file(p) == []
        # A different rule's allow() does not mask the finding.
        p.write_text(src.replace("import-at-top", "span-balance"))
        findings = lint_file(p)
        assert [f.rule for f in findings] == ["import-at-top"]


class TestRepoClean:
    """The acceptance criterion: the repo at HEAD has zero findings."""

    def test_lint_clean(self):
        findings = run_lint(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_typing_gate_clean(self):
        findings, _notes = run_typing_gate(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_typing_targets_fully_annotated(self):
        # Stronger than the ratchet (which only forbids regression): the
        # PR that introduced the gate left every target at 100%.
        for entry in TYPING_TARGETS:
            p = REPO_ROOT / entry
            files = [p] if p.is_file() else sorted(p.rglob("*.py"))
            for f in files:
                coverage, total = annotation_coverage(f)
                assert coverage == 1.0, (f, coverage, total)

    def test_fixtures_outside_default_scan(self):
        # The deliberately-bad fixtures must never leak into the repo scan.
        from tools.analyze.lint import iter_python_files

        scanned = {str(p) for p in iter_python_files(REPO_ROOT, DEFAULT_SCAN)}
        assert not any("analyze_fixtures" in s for s in scanned)


class TestTypingRatchet:
    def test_regression_is_a_finding(self, tmp_path, monkeypatch):
        import tools.analyze.typing_gate as tg

        mod = tmp_path / "mod.py"
        mod.write_text("def f(x: int) -> int:\n    return x\n")
        ratchet = tmp_path / "ratchet.json"
        monkeypatch.setattr(tg, "TYPING_TARGETS", ("mod.py",))
        monkeypatch.setattr(tg, "RATCHET_PATH", ratchet)

        findings, _ = tg.run_typing_gate(tmp_path, update_ratchet=True)
        assert findings == []
        assert json.loads(ratchet.read_text())["annotation_coverage"] == {
            "mod.py": 1.0
        }
        # Drop an unannotated function in: coverage falls, the gate fails.
        mod.write_text(
            "def f(x: int) -> int:\n    return x\n\n\ndef g(y):\n    return y\n"
        )
        findings, _ = tg.run_typing_gate(tmp_path)
        assert len(findings) == 1
        assert "regressed" in findings[0].message

    def test_new_module_must_enter_fully_annotated(self, tmp_path, monkeypatch):
        import tools.analyze.typing_gate as tg

        (tmp_path / "newmod.py").write_text("def g(y):\n    return y\n")
        monkeypatch.setattr(tg, "TYPING_TARGETS", ("newmod.py",))
        monkeypatch.setattr(tg, "RATCHET_PATH", tmp_path / "ratchet.json")
        findings, _ = tg.run_typing_gate(tmp_path)
        assert len(findings) == 1
        assert "full annotation coverage" in findings[0].message


class TestAnalyzeCli:
    """The one entry point: exit codes and the qi-telemetry/1 findings
    stream tools/metrics_report.py renders."""

    def test_lint_and_typing_pass_exit_zero(self, tmp_path):
        out = tmp_path / "findings.jsonl"
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", "lint", "typing",
             "--jsonl", str(out)],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "CLEAN" in proc.stdout

        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert lines[0]["kind"] == "meta"
        assert lines[0]["schema"] == "qi-telemetry/1"
        counters = {
            l["name"]: l["value"] for l in lines if l["kind"] == "counter"
        }
        assert counters["analyze.findings"] == 0
        assert counters["analyze.lint_findings"] == 0

        # The stream parses through the standard report renderer.
        sys.path.insert(0, str(REPO_ROOT / "tools"))
        try:
            from metrics_report import load_stream, render

            data = load_stream(str(out))
            assert data["bad_lines"] == 0
            assert "qi-telemetry report" in render(str(out))
        finally:
            sys.path.pop(0)

    def test_findings_exit_nonzero_and_land_in_stream(self, tmp_path, monkeypatch):
        # Point the scan at a directory containing one bad fixture.
        bad_dir = tmp_path / "scan"
        bad_dir.mkdir()
        (bad_dir / "leak.py").write_text(
            (FIXTURES / "bad_import_at_top.py").read_text()
        )
        import tools.analyze.__main__ as main_mod
        import tools.analyze.lint as lint_mod

        monkeypatch.setattr(lint_mod, "DEFAULT_SCAN", ("scan",))
        monkeypatch.setattr(main_mod, "REPO_ROOT", tmp_path)
        out = tmp_path / "findings.jsonl"
        rc = main_mod.main(["lint", "--jsonl", str(out)])
        assert rc == 1
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        events = [l for l in lines if l["kind"] == "event"]
        assert len(events) == 1
        attrs = events[0]["attrs"]
        assert events[0]["name"] == "analyze.finding"
        assert attrs["rule"] == "import-at-top"
        assert attrs["file"] == "scan/leak.py"
        assert attrs["pass"] == "lint"

    def test_unknown_pass_is_a_usage_error(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", "nonsense"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 2
        assert "unknown pass" in proc.stderr


class TestEnvRegistry:
    """The runtime twin of no-bare-env-read (utils/env.py)."""

    def test_undeclared_name_raises(self):
        from quorum_intersection_tpu.utils.env import qi_env

        with pytest.raises(KeyError, match="QI_NOT_A_THING"):
            qi_env("QI_NOT_A_THING")

    def test_defaults_and_overrides(self, monkeypatch):
        from quorum_intersection_tpu.utils.env import (
            qi_env,
            qi_env_flag,
            qi_env_float,
        )

        monkeypatch.delenv("QI_SANITIZER", raising=False)
        assert qi_env("QI_SANITIZER") == "asan"
        monkeypatch.setenv("QI_SANITIZER", "tsan")
        assert qi_env("QI_SANITIZER") == "tsan"
        monkeypatch.delenv("QI_LOG_JSON", raising=False)
        assert qi_env_flag("QI_LOG_JSON") is False
        monkeypatch.setenv("QI_LOG_JSON", "1")
        assert qi_env_flag("QI_LOG_JSON") is True
        monkeypatch.setenv("QI_FRONTIER_CKPT_INTERVAL_S", "0.25")
        assert qi_env_float("QI_FRONTIER_CKPT_INTERVAL_S") == 0.25
        monkeypatch.setenv("QI_FRONTIER_CKPT_INTERVAL_S", "bogus")
        assert qi_env_float("QI_FRONTIER_CKPT_INTERVAL_S") == 5.0  # default

    def test_registry_documents_every_declared_var(self):
        from quorum_intersection_tpu.utils.env import registry

        names = [v.name for v in registry()]
        assert len(names) == len(set(names))
        for var in registry():
            assert var.name.startswith("QI_")
            assert len(var.description) > 20  # a real contract, not a stub


class TestLockDisciplineSubRules:
    """The two sub-rules the fixture pair doesn't isolate: nested lock
    acquisition and emit-under-lock."""

    def _findings(self, src, tmp_path):
        p = tmp_path / "sample.py"
        p.write_text(src)
        return lint_file(p, rules=["lock-discipline"])

    def test_nested_lock_acquisition_flagged(self, tmp_path):
        src = (
            "import threading\n\n"
            "lock_a = threading.Lock()\n"
            "lock_b = threading.Lock()\n\n"
            "def f():\n"
            "    with lock_a:\n"
            "        with lock_b:\n"
            "            pass\n"
        )
        findings = self._findings(src, tmp_path)
        assert len(findings) == 1
        assert "nested lock" in findings[0].message
        assert findings[0].line == 8  # the INNER acquisition

    def test_sequential_locks_not_flagged(self, tmp_path):
        src = (
            "import threading\n\n"
            "lock_a = threading.Lock()\n\n"
            "def f():\n"
            "    with lock_a:\n"
            "        pass\n"
            "    with lock_a:\n"
            "        pass\n"
        )
        assert self._findings(src, tmp_path) == []

    def test_emit_under_lock_flagged(self, tmp_path):
        src = (
            "import threading\n\n"
            "lock = threading.Lock()\n\n"
            "def f(sink, line):\n"
            "    with lock:\n"
            "        sink.emit(line)\n"
        )
        findings = self._findings(src, tmp_path)
        assert len(findings) == 1
        assert "emit" in findings[0].message


class TestScheduleDegenerationIsLoud:
    """r.ok must be False when the forced ordering did not actually happen,
    even if the verdict matches (code-review finding: auto.py's worker
    swallows engine exceptions into sweep_error)."""

    def test_sweep_error_fails_the_schedule(self):
        from tools.analyze.schedules import ScheduleResult

        r = ScheduleResult(
            schedule="cancel_during_compile", topology="majority9",
            verdict=True, expected=True, winner="oracle",
            oracle_outcome="verdict", trace=["oracle.returned"],
            error="sweep_error: ScheduleError('gate held past 30s')",
        )
        assert not r.ok

    def test_missing_sync_point_is_detected(self, monkeypatch):
        # Break the ordering deliberately: a sweep engine that errors out
        # instead of parking in compile leaves sweep.unwound unreached and
        # sweep_error set — _run_one must report the schedule degenerate.
        import tools.analyze.schedules as sched
        from quorum_intersection_tpu.fbas.synth import majority_fbas

        class ExplodingSweep:
            name = "tpu-sweep"

            def __init__(self, cancel=None, compiling=None, **kw):
                self.cancel = cancel
                self.compiling = compiling

            def check_scc(self, *a, **k):
                if self.compiling is not None:
                    self.compiling.set()  # release the oracle's gate first
                raise RuntimeError("engine exploded in compile")

        monkeypatch.setattr(sched, "FakeSweep", ExplodingSweep)
        r = sched._run_one(
            "cancel_during_compile", majority_fbas(9), True, "majority9"
        )
        assert r.verdict is True  # the oracle still answered correctly...
        assert not r.ok  # ...but the harness refuses to call it clean
        assert r.error is not None and "sweep_error" in r.error


class TestTracerLeakPrecision:
    """The rule must track taint, not pattern-match: static closure config
    stays branchable, lax callbacks inherit taint."""

    def _findings(self, src, tmp_path):
        p = tmp_path / "sample.py"
        p.write_text(src)
        return lint_file(p, rules=["jax-tracer-leak"])

    def test_lax_callback_params_are_tainted(self, tmp_path):
        src = (
            "import jax\n"
            "from jax import lax\n\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    def body(i, best):\n"
            "        if best > 0:\n"
            "            return best\n"
            "        return best + i\n"
            "    return lax.fori_loop(0, 4, body, x)\n"
        )
        findings = self._findings(src, tmp_path)
        assert [f.rule for f in findings] == ["jax-tracer-leak"]

    def test_static_closure_branch_not_flagged(self, tmp_path):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n\n"
            "def factory(steps):\n"
            "    @jax.jit\n"
            "    def step(x):\n"
            "        if steps == 1:\n"
            "            return jnp.sum(x)\n"
            "        return jnp.sum(x) * steps\n"
            "    return step\n"
        )
        assert self._findings(src, tmp_path) == []

    def test_jit_wrapped_local_function_is_traced(self, tmp_path):
        src = (
            "import jax\n\n"
            "def build():\n"
            "    def shard_fn(start):\n"
            "        if start > 0:\n"
            "            return start\n"
            "        return -start\n"
            "    return jax.jit(shard_fn)\n"
        )
        findings = self._findings(src, tmp_path)
        assert [f.rule for f in findings] == ["jax-tracer-leak"]
