"""qi-prune differential suite (ISSUE 10): rank-ordered windows +
device-side block-guard pruning.

Pins: ordered/pruned vs natural/unpruned vs the python oracle on the
correct/broken ``near_disjoint_cores`` pair (verdicts identical
everywhere; pruning byte-identical ON TOP of a fixed ordering — same
witness pair, same hit index), packed parity, witness-pair validity
through the permutation (independent checker), mid-sweep cancel and
checkpoint-resume accounting with pruned blocks, the ``sweep.prune``
fault degrading to the unpruned enumeration with the verdict unchanged,
and the checker's accept/reject pinning for pruned-block ledgers
(including a forged pruned block).
"""

import copy
import json
from functools import lru_cache

import pytest

from quorum_intersection_tpu.backends.base import SearchCancelled
from quorum_intersection_tpu.backends.tpu.sweep import (
    PRUNE_RULE_ID,
    TpuSweepBackend,
)
from quorum_intersection_tpu.encode.circuit import (
    encode_circuit,
    rank_order_nodes,
)
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import near_disjoint_cores
from quorum_intersection_tpu.pipeline import quorum_bearing_sccs, solve
from quorum_intersection_tpu.utils import telemetry
from tools.check_cert import CheckFailure, check_certificate

CORRECT = near_disjoint_cores(6, 1)
BROKEN = near_disjoint_cores(6, 1, broken=True)
FIXTURES = {"correct": (CORRECT, True), "broken": (BROKEN, False)}


def sweep(order, prune, **kw):
    kw.setdefault("batch", 256)
    return TpuSweepBackend(order=order, prune=prune, **kw)


@lru_cache(maxsize=None)
def sweep_solve(fixture, order, prune, engine="xla"):
    data, _ = FIXTURES[fixture]
    return solve(
        json.dumps(data),
        backend=sweep(order, prune, engine=engine),
    )


@lru_cache(maxsize=None)
def oracle_solve(fixture):
    data, _ = FIXTURES[fixture]
    return solve(json.dumps(data), backend="python")


def make_job(data):
    graph = build_graph(parse_fbas(data))
    circuit = encode_circuit(graph)
    [(_sid, scc)] = quorum_bearing_sccs(graph, allow_native=False)
    return graph, circuit, scc


@pytest.fixture
def fresh_record():
    rec = telemetry.reset_run_record()
    yield rec
    telemetry.reset_run_record()


class TestPreset:
    """near_disjoint_cores is a one-knob pair with ONE SCC both ways."""

    def test_pair_verdicts_and_structure(self):
        for fixture, (data, verdict) in FIXTURES.items():
            res = oracle_solve(fixture)
            assert res.intersects is verdict, fixture
            assert res.n_sccs == 1  # the knob never changes the partition
            assert len(res.main_scc) == len(data)

    def test_deterministic_bytes(self):
        assert near_disjoint_cores(6, 1, seed=3) == near_disjoint_cores(
            6, 1, seed=3
        )
        assert near_disjoint_cores(6, 1, seed=3) != near_disjoint_cores(
            6, 1, seed=4
        )


class TestDifferential:
    @pytest.mark.parametrize("order", ["natural", "rank"])
    @pytest.mark.parametrize("fixture", ["correct", "broken"])
    def test_pruned_matches_unpruned_and_oracle(self, order, fixture):
        _, verdict = FIXTURES[fixture]
        unpruned = sweep_solve(fixture, order, False)
        pruned = sweep_solve(fixture, order, True)
        assert oracle_solve(fixture).intersects is verdict
        assert unpruned.intersects is verdict
        assert pruned.intersects is verdict
        # Pruning is byte-identical ON TOP of the ordering: pruned blocks
        # hold no hits, so the first-hit window and witness pair are the
        # unpruned sweep's exactly.
        assert (pruned.q1, pruned.q2) == (unpruned.q1, unpruned.q2)
        assert pruned.stats.get("hit_index") == unpruned.stats.get("hit_index")

    @pytest.mark.parametrize("order", ["natural", "rank"])
    def test_true_cert_has_verifiable_pruned_mass(self, order):
        data, _ = FIXTURES["correct"]
        res = sweep_solve("correct", order, True)
        led = res.stats["cert"]
        assert led["windows_pruned_guard"] > 0
        # sweep_enumeration_ratio < 1.0: real pruning, exact arithmetic.
        assert led["windows_enumerated"] < led["window_space"]
        assert (
            led["windows_enumerated"] + led["windows_pruned_guard"]
            == led["window_space"]
        )
        assert led["pruned_blocks"]["rule"] == PRUNE_RULE_ID
        notes = check_certificate(res.cert, data)
        assert any("guard-pruned" in n for n in notes)

    def test_witness_valid_through_the_permutation(self):
        # The ordered witness decodes through the permuted graph-space id
        # list; the independent checker confirms it is a disjoint quorum
        # pair of the RAW snapshot.
        data, _ = FIXTURES["broken"]
        res = sweep_solve("broken", "rank", True)
        check_certificate(res.cert, data)

    def test_pallas_engine_parity(self):
        data, _ = FIXTURES["correct"]
        res = sweep_solve("correct", "rank", True, engine="pallas")
        assert res.intersects is True
        assert res.stats["cert"]["windows_pruned_guard"] > 0
        check_certificate(res.cert, data)

    def test_rank_order_is_a_permutation_with_provenance(self):
        graph = build_graph(parse_fbas(BROKEN))
        [(_sid, scc)] = quorum_bearing_sccs(graph, allow_native=False)
        ordered, meta = rank_order_nodes(graph, scc)
        assert sorted(ordered) == sorted(scc)
        assert meta["mode"] == "rank"
        assert meta["fixed"] == graph.node_ids[ordered[0]]
        # The FULL permutation rides the meta, so any ordered cert lets a
        # consumer reconstruct the enumeration (bit j = bit_nodes[j]).
        assert meta["bit_nodes"] == [graph.node_ids[v] for v in ordered[1:]]
        rank_cert = sweep_solve("correct", "rank", True).cert
        prov = rank_cert["provenance"]["order"]
        assert prov["mode"] == "rank"
        assert len(prov["bit_nodes"]) == len(CORRECT) - 1
        assert prov["fixed"] not in prov["bit_nodes"]
        natural_cert = sweep_solve("correct", "natural", True).cert
        assert "order" not in natural_cert["provenance"]


class TestPacked:
    def test_packed_pruned_matches_unpacked(self):
        datas = [CORRECT, near_disjoint_cores(6, 1, seed=1), BROKEN]
        jobs = [make_job(d) for d in datas]
        unpacked = [
            sweep("rank", True).check_scc(g, c, s) for g, c, s in jobs
        ]
        packed = sweep("rank", True).check_sccs(jobs)
        for u, p in zip(unpacked, packed):
            assert u.intersects == p.intersects
            assert (u.q1, u.q2) == (p.q1, p.q2)
        for p in packed:
            if not p.intersects:
                continue
            led = p.stats["cert"]
            assert led["windows_pruned_guard"] > 0
            assert (
                led["windows_enumerated"]
                + led["windows_pruned_guard"]
                + led["windows_skipped_pack_fill"]
                == led["window_space"]
            )


class _TrippingCancel:
    def __init__(self, after):
        self.after = after
        self.polls = 0

    @property
    def cancelled(self):
        self.polls += 1
        return self.polls > self.after


class TestCancelAndResume:
    def test_cancel_accounting_with_pruned_blocks(self, fresh_record):
        data = near_disjoint_cores(7, 1)  # 2^14 windows, heavy pruning
        graph, circuit, scc = make_job(data)
        backend = sweep(
            "natural", True, max_inflight=2, cancel=_TrippingCancel(6)
        )
        with pytest.raises(SearchCancelled):
            backend.check_scc(graph, circuit, scc)
        counters, _ = fresh_record.snapshot()
        space = 1 << (len(scc) - 1)
        pruned = counters.get("cert.windows_pruned_guard", 0)
        enumerated = counters.get("cert.windows_enumerated", 0)
        cancelled = counters.get("cert.windows_cancelled", 0)
        assert pruned > 0 and cancelled > 0
        # Exact partition: every window is enumerated, pruned, or
        # cancelled — never two of those at once.
        assert enumerated + pruned + cancelled == space
        assert enumerated < space

    def test_checkpoint_resume_accounting_with_pruned_blocks(
        self, tmp_path, fresh_record
    ):
        from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

        data = near_disjoint_cores(7, 1)
        ck = SweepCheckpoint(tmp_path / "sweep.ckpt")
        first = sweep(
            "natural", True, max_inflight=2, checkpoint=ck,
            cancel=_TrippingCancel(10),
        )
        with pytest.raises(SearchCancelled):
            solve(json.dumps(data), backend=first)
        res = solve(
            json.dumps(data),
            backend=sweep("natural", True, checkpoint=ck),
        )
        assert res.intersects is True
        entry = res.cert["coverage"]["sccs"][0]
        assert entry["windows_resumed_prefix"] > 0
        assert entry["windows_pruned_guard"] > 0
        # The resumed prefix and the pruned mass never overlap: the plan
        # only prunes blocks fully at or above the resume cut.
        assert (
            entry["windows_enumerated"]
            + entry["windows_pruned_guard"]
            + entry["windows_resumed_prefix"]
            == entry["window_space"]
        )
        notes = check_certificate(res.cert, data)
        assert any("checkpoint-resumed" in n for n in notes)


class TestFaultDegrade:
    def test_prune_fault_degrades_to_unpruned_same_verdict(
        self, monkeypatch, fresh_record
    ):
        monkeypatch.setenv("QI_FAULTS", "sweep.prune=error")
        res = solve(json.dumps(CORRECT), backend=sweep("natural", True))
        assert res.intersects is True
        led = res.stats["cert"]
        assert led["windows_pruned_guard"] == 0
        assert led["windows_enumerated"] == led["window_space"]
        counters, _ = fresh_record.snapshot()
        assert counters.get("sweep.prune_errors", 0) >= 1
        assert counters.get("faults.injected", 0) >= 1
        assert any(
            e.get("name") == "sweep.prune_degraded"
            for e in fresh_record.events
        )
        check_certificate(res.cert, CORRECT)

    def test_prune_fault_degrades_packed_pack(self, monkeypatch):
        monkeypatch.setenv("QI_FAULTS", "sweep.prune=error")
        jobs = [make_job(CORRECT), make_job(BROKEN)]
        results = sweep("natural", True).check_sccs(jobs)
        assert [r.intersects for r in results] == [True, False]
        assert results[0].stats["cert"]["windows_pruned_guard"] == 0


class TestChecker:
    def _pruned_cert(self):
        return copy.deepcopy(sweep_solve("correct", "natural", True).cert)

    def test_forged_pruned_block_rejected(self):
        bad = self._pruned_cert()
        blocks = bad["coverage"]["sccs"][0]["pruned_blocks"]
        size = bad["coverage"]["sccs"][0]["size"]
        block_space = 1 << (size - 1 - blocks["k"])
        # The all-ones prefix's maximal candidate is (nearly) the whole
        # SCC, which certainly contains a quorum — booking it as pruned
        # claims coverage nothing verified.  Replacing (not adding) keeps
        # the ledger arithmetic intact, so only the re-verification can
        # catch it.
        forged = block_space - 1
        assert forged not in blocks["prefixes"]
        blocks["prefixes"][-1] = forged
        with pytest.raises(CheckFailure, match="maximal candidate contains"):
            check_certificate(bad, CORRECT)

    def test_pruned_mass_without_block_ledger_rejected(self):
        bad = self._pruned_cert()
        del bad["coverage"]["sccs"][0]["pruned_blocks"]
        with pytest.raises(CheckFailure, match="unverifiable"):
            check_certificate(bad, CORRECT)

    def test_block_count_mismatch_rejected(self):
        bad = self._pruned_cert()
        bad["coverage"]["sccs"][0]["pruned_blocks"]["prefixes"].pop()
        with pytest.raises(CheckFailure, match="blocks \\* 2"):
            check_certificate(bad, CORRECT)

    def test_unknown_rule_rejected(self):
        bad = self._pruned_cert()
        bad["coverage"]["sccs"][0]["pruned_blocks"]["rule"] = "trust-me"
        with pytest.raises(CheckFailure, match="unknown prune rule"):
            check_certificate(bad, CORRECT)

    def test_enumeration_must_be_a_permutation(self):
        bad = self._pruned_cert()
        enum = bad["coverage"]["sccs"][0]["enumeration"]
        enum["bit_nodes"][0] = enum["bit_nodes"][1]
        with pytest.raises(CheckFailure, match="not a permutation"):
            check_certificate(bad, CORRECT)

    def test_pruned_block_overlapping_resumed_prefix_rejected(self):
        # A forged cert could recast enumerated windows as a resumed
        # prefix while keeping pruned blocks BELOW the cut: every block
        # still re-verifies and the sum still covers the space, but the
        # overlapped windows are claimed by two ledger terms at once.
        bad = self._pruned_cert()
        entry = bad["coverage"]["sccs"][0]
        blocks = entry["pruned_blocks"]
        lowest = min(blocks["prefixes"]) << blocks["k"]
        resumed = lowest + (1 << blocks["k"])  # covers the lowest block
        entry["windows_resumed_prefix"] = resumed
        entry["windows_enumerated"] -= resumed
        with pytest.raises(CheckFailure, match="resumed prefix"):
            check_certificate(bad, CORRECT)

    def test_sampled_verification_accepts(self):
        cert = self._pruned_cert()
        notes = check_certificate(cert, CORRECT, sample=3)
        assert any("sampled" in n for n in notes)


class TestDeltaComposition:
    def test_composed_cert_projects_pruned_evidence_across_keys(self):
        # qi-delta fragments transplant across fingerprint-matched SCCs
        # even when every publicKey differs (fbas/diff.py hashes structure,
        # not identity).  A pruned fragment's enumeration map is banked in
        # SCC-local ranks and rebuilt against the NEW snapshot's ids at
        # compose time, so the composed certificate's pruned blocks still
        # re-verify under the stdlib checker.
        from quorum_intersection_tpu.delta import DeltaEngine, SccVerdictStore

        twin = near_disjoint_cores(6, 1, prefix="XYZ")  # same structure,
        # disjoint key space
        engine = DeltaEngine(SccVerdictStore())
        [first] = engine.check_many(
            [CORRECT], backend=sweep("rank", True)
        )
        assert first.intersects is True
        assert first.cert["coverage"]["sccs"][0]["windows_pruned_guard"] > 0
        [composed] = engine.check_many(
            [twin], backend=sweep("rank", True)
        )
        assert composed.intersects is True
        assert composed.cert["provenance"]["delta"]["reused_sccs"] == 1
        entry = composed.cert["coverage"]["sccs"][0]
        assert entry["windows_pruned_guard"] > 0
        # Every enumeration id is a NEW-snapshot key, and the checker
        # re-proves every transplanted pruned block against the raw twin.
        assert all(
            pk.startswith("XYZ") for pk in entry["enumeration"]["bit_nodes"]
        )
        notes = check_certificate(composed.cert, twin)
        assert any("pruned blocks re-verified" in n for n in notes)


class TestKnobs:
    def test_env_defaults_off(self, monkeypatch):
        monkeypatch.delenv("QI_SWEEP_ORDER", raising=False)
        monkeypatch.delenv("QI_SWEEP_PRUNE", raising=False)
        backend = TpuSweepBackend()
        assert backend._order_mode() == "natural"
        assert backend._prune_enabled() is False

    def test_env_knobs_engage(self, monkeypatch):
        monkeypatch.setenv("QI_SWEEP_ORDER", "rank")
        monkeypatch.setenv("QI_SWEEP_PRUNE", "1")
        backend = TpuSweepBackend()
        assert backend._order_mode() == "rank"
        assert backend._prune_enabled() is True
        monkeypatch.setenv("QI_SWEEP_PRUNE", "0")
        assert backend._prune_enabled() is False

    def test_constructor_overrides_env(self, monkeypatch):
        monkeypatch.setenv("QI_SWEEP_ORDER", "rank")
        monkeypatch.setenv("QI_SWEEP_PRUNE", "1")
        backend = TpuSweepBackend(order="natural", prune=False)
        assert backend._order_mode() == "natural"
        assert backend._prune_enabled() is False

    def test_unknown_order_rejected(self):
        with pytest.raises(ValueError, match="unknown sweep order"):
            TpuSweepBackend(order="chaotic")
