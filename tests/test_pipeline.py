"""End-to-end pipeline tests: golden fixture verdicts, witness pairs, guard
paths, policy/selection knobs, synthetic pass/fail pairs."""

import io

import pytest

from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas, trivial_pair
from quorum_intersection_tpu.pipeline import solve

BACKEND = "python"


def _solve(source, **kw):
    kw.setdefault("backend", BACKEND)
    return solve(source, **kw)


class TestGoldenFixtures:
    """Verdict parity with the reference on its own fixtures (SURVEY.md §4.1),
    under both dangling policies and both SCC-selection rules."""

    @pytest.mark.parametrize("dangling", ["strict", "alias0"])
    @pytest.mark.parametrize("scc_select", ["quorum-bearing", "front"])
    @pytest.mark.parametrize(
        "name,expected",
        [
            ("correct_trivial.json", True),
            ("broken_trivial.json", False),
            ("correct.json", True),
            ("broken.json", False),
        ],
    )
    def test_verdicts(self, ref_fixture, name, expected, dangling, scc_select):
        with open(ref_fixture(name)) as f:
            res = _solve(f.read(), dangling=dangling, scc_select=scc_select)
        assert res.intersects is expected

    def test_broken_witness_pair(self, ref_fixture):
        with open(ref_fixture("broken.json")) as f:
            res = _solve(f.read())
        assert not res.intersects
        # Known disjoint pair: {Eno, SDF1} vs {SDF2, SDF3} (BASELINE.md).
        assert res.q1 and res.q2
        assert set(res.q1) & set(res.q2) == set()

    def test_correct_structure(self, ref_fixture):
        with open(ref_fixture("correct.json")) as f:
            res = _solve(f.read())
        assert res.intersects
        assert res.n_sccs == 49
        assert len(res.quorum_scc_ids) == 1
        assert len(res.main_scc) == 4  # the SDF+Eno sink
        assert res.stats["bnb_calls"] == 11  # SURVEY.md §6 [verified]

    def test_trivial_bnb_calls(self, ref_fixture):
        with open(ref_fixture("correct_trivial.json")) as f:
            res = _solve(f.read())
        assert res.stats["bnb_calls"] == 11  # SURVEY.md §6 [verified]


class TestSyntheticPairs:
    @pytest.mark.parametrize("n", [3, 5, 8, 11])
    def test_majority_pair(self, n):
        assert _solve(majority_fbas(n)).intersects is True
        assert _solve(majority_fbas(n, broken=True)).intersects is False

    def test_hierarchical_pair(self):
        assert _solve(hierarchical_fbas(3, 3)).intersects is True
        assert _solve(hierarchical_fbas(3, 3, broken=True)).intersects is False

    def test_trivial_pair_generator(self):
        pair = trivial_pair()
        assert _solve(pair["correct"]).intersects is True
        assert _solve(pair["broken"]).intersects is False

    def test_witness_is_disjoint_quorum_pair(self):
        from quorum_intersection_tpu.fbas.graph import build_graph
        from quorum_intersection_tpu.fbas.schema import parse_fbas
        from quorum_intersection_tpu.fbas.semantics import is_quorum

        data = majority_fbas(7, broken=True)
        res = _solve(data)
        assert not res.intersects
        g = build_graph(parse_fbas(data))
        assert is_quorum(g, res.q1)
        assert is_quorum(g, res.q2)
        assert not (set(res.q1) & set(res.q2))


class TestGuardPaths:
    def test_no_quorum_anywhere_is_broken(self):
        # Every node has an unsatisfiable slice → zero quorum-bearing SCCs.
        data = [
            {"publicKey": "A", "quorumSet": None},
            {"publicKey": "B", "quorumSet": None},
        ]
        res = _solve(data)
        assert not res.intersects
        assert res.stats.get("reason") == "scc_guard"
        assert res.quorum_scc_ids == []

    def test_two_independent_quorums_is_broken(self):
        # Two disconnected self-trusting islands → two quorum-bearing SCCs.
        data = majority_fbas(3, prefix="LEFT") + majority_fbas(3, prefix="RIGHT")
        res = _solve(data)
        assert not res.intersects
        assert res.stats.get("reason") == "scc_guard"
        assert len(res.quorum_scc_ids) == 2

    def test_non_sink_component_has_no_quorum_when_depending_down(self):
        # A 3-majority core plus a tail node trusting the core: 2 SCCs, only
        # the core bears a quorum; tail can never be in a minimal quorum.
        data = majority_fbas(3) + [
            {
                "publicKey": "TAIL",
                "quorumSet": {"threshold": 2, "validators": ["NODE0000", "NODE0001"]},
            }
        ]
        res = _solve(data)
        assert res.intersects
        assert res.n_sccs == 2
        assert len(res.quorum_scc_ids) == 1


class TestKnobs:
    def test_randomized_tiebreak_same_verdicts(self, ref_fixture):
        # The reference's RNG tie-break is verdict-independent (SURVEY.md C7);
        # so is ours, across seeds.
        from quorum_intersection_tpu.backends.python_oracle import PythonOracleBackend

        for seed in (0, 1, 7):
            for name, expected in (("correct.json", True), ("broken.json", False)):
                with open(ref_fixture(name)) as f:
                    res = solve(f.read(), backend=PythonOracleBackend(seed=seed))
                assert res.intersects is expected

    def test_scope_to_scc_same_verdict_on_sink(self, ref_fixture):
        # Q6: whole-graph availability is only sound because the searched SCC
        # is a sink; scoping must not change the verdict there.
        for name, expected in (("correct.json", True), ("broken.json", False)):
            with open(ref_fixture(name)) as f:
                res = _solve(f.read(), scope_to_scc=True)
            assert res.intersects is expected

    def test_verbose_narration(self):
        buf = io.StringIO()
        res = _solve(majority_fbas(3), verbose=True, out=buf)
        text = buf.getvalue()
        assert "total number of strongly connected components: 1" in text
        assert "all quorums are intersecting" in text
        assert res.intersects

    def test_verbose_broken_narration(self):
        buf = io.StringIO()
        res = _solve(majority_fbas(5, broken=True), verbose=True, out=buf)
        text = buf.getvalue()
        assert "found two non-intersecting quorums" in text
        assert "first quorum:" in text and "second quorum:" in text
        assert not res.intersects


class TestStellarLike:
    """Snapshot-shaped workload (BASELINE north star: time-to-verdict on a
    ~150-validator stellarbeat snapshot)."""

    def test_structure(self):
        from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
        from quorum_intersection_tpu.fbas.schema import parse_fbas
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        g = build_graph(parse_fbas(stellar_like_fbas()))
        assert g.n == 149  # 7*3 core + 100 watchers + 28 null
        assert g.dangling_refs == 7
        count, comp = tarjan_scc(g.n, g.succ)
        sccs = group_sccs(g.n, comp, count)
        assert max(len(s) for s in sccs) == 21  # the core

    def test_pair_verdicts_oracle(self):
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        assert solve(stellar_like_fbas(), backend="python").intersects is True
        res = solve(stellar_like_fbas(broken=True), backend="python")
        assert res.intersects is False
        # broken by an in-SCC disjoint pair, not the SCC guard
        assert res.q1 and res.q2
        assert not set(res.q1) & set(res.q2)

    def test_pair_verdicts_auto_small(self):
        # auto backend on the bench's quick-size snapshot (15-node core —
        # a 2^14 sweep keeps this fast on the CPU test platform; the full
        # 21-node core runs on real TPU via bench.py)
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        small = dict(n_core_orgs=5, n_watchers=30)
        assert solve(stellar_like_fbas(**small), backend="auto").intersects is True
        res = solve(stellar_like_fbas(broken=True, **small), backend="auto")
        assert res.intersects is False
        assert res.q1 and res.q2 and not set(res.q1) & set(res.q2)


class TestSccScan:
    """Native vs Python per-SCC quorum scan: identical quorums, and the big
    snapshot routes to the native path (VERDICT r1 §weak-7)."""

    def test_native_scan_matches_python(self):
        from quorum_intersection_tpu.backends.cpp import native_scc_scan
        from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
        from quorum_intersection_tpu.fbas.schema import parse_fbas
        from quorum_intersection_tpu.fbas.semantics import max_quorum
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        g = build_graph(parse_fbas(stellar_like_fbas(n_watchers=300)))
        count, comp = tarjan_scc(g.n, g.succ)
        sccs = group_sccs(g.n, comp, count)
        try:
            native = native_scc_scan(g, sccs)
        except Exception as exc:  # pragma: no cover - g++ missing
            pytest.skip(f"native oracle unavailable: {exc}")
        for members, nq in zip(sccs, native):
            avail = [False] * g.n
            for v in members:
                avail[v] = True
            assert nq == max_quorum(g, members, avail)

    def test_big_snapshot_scan_fast_and_correct(self):
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        try:
            from quorum_intersection_tpu.backends.cpp import build_library

            build_library()  # outside the timed phase: compile ≠ scan time
        except Exception as exc:  # pragma: no cover - g++ missing
            pytest.skip(f"native oracle unavailable: {exc}")
        data = stellar_like_fbas(n_watchers=1500)
        res = solve(data, backend="cpp")
        assert res.intersects is True
        # ~1500 singleton SCCs: the native scan keeps this well under a second
        assert res.timers["scc_scan"] < 2.0

    def test_explicit_python_backend_stays_interpreted(self, monkeypatch):
        # --backend python is a no-native-code promise: the scan must not
        # compile or call the C++ oracle even on large graphs.
        import quorum_intersection_tpu.pipeline as pl
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        def boom(*a, **k):  # pragma: no cover - called means failure
            raise AssertionError("native scan used under --backend python")

        monkeypatch.setattr(
            "quorum_intersection_tpu.backends.cpp.native_scc_scan", boom
        )
        monkeypatch.setattr(pl, "NATIVE_SCAN_LIMIT", 8)
        res = solve(stellar_like_fbas(n_core_orgs=3, n_watchers=10), backend="python")
        assert res.intersects is True


def test_scc_guard_two_quorum_sccs_yields_witness_pair():
    """With >= 2 quorum-bearing SCCs the guard verdict (cpp:681-688) now
    also surfaces a witness pair via the API: one per-SCC quorum each,
    disjoint by construction (the reference only narrates here)."""
    from quorum_intersection_tpu.fbas.semantics import is_quorum as _isq
    from quorum_intersection_tpu.fbas.synth import majority_fbas

    from quorum_intersection_tpu.fbas.graph import build_graph as _bg
    from quorum_intersection_tpu.fbas.schema import parse_fbas as _pf

    data = majority_fbas(3, prefix="ISLA") + majority_fbas(3, prefix="ISLB")
    res = solve(data, backend="python")
    assert res.intersects is False
    assert res.stats["reason"] == "scc_guard"
    assert len(res.quorum_scc_ids) == 2
    g = _bg(_pf(data))
    assert res.q1 and res.q2 and not set(res.q1) & set(res.q2)
    assert _isq(g, res.q1) and _isq(g, res.q2)


def test_scc_guard_no_quorum_anywhere_has_no_witness():
    # All nodes null-qset: zero quorum-bearing SCCs — broken, no witness.
    data = [{"publicKey": f"N{i}", "name": "", "quorumSet": None} for i in range(3)]
    res = solve(data, backend="python")
    assert res.intersects is False
    assert res.quorum_scc_ids == []
    assert res.q1 is None and res.q2 is None


class TestBenchmarkFbas:
    """The north-star verdict-benchmark generator (synth.benchmark_fbas,
    BASELINE.json configs[3..4]): the k-of-n core must be the unique
    quorum-bearing sink SCC and the one-knob broken twin must flip the
    verdict — on the oracle AND the device sweep."""

    def test_safe_and_broken_twins_differential(self):
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
        from quorum_intersection_tpu.fbas.synth import benchmark_fbas

        data = benchmark_fbas(28, 9, seed=3)
        broken = benchmark_fbas(28, 9, broken=True, seed=3)
        for backend in ("python", TpuSweepBackend()):
            assert solve(data, backend=backend).intersects is True
            assert solve(broken, backend=backend).intersects is False

    def test_nested_watchers_core_is_unique_quorum_scc(self):
        from quorum_intersection_tpu.fbas.graph import build_graph
        from quorum_intersection_tpu.fbas.schema import parse_fbas
        from quorum_intersection_tpu.fbas.synth import benchmark_fbas

        data = benchmark_fbas(40, 11, nested_watchers=True, seed=1)
        assert len(data) == 40
        # At least one watcher actually carries an inner set (depth 2).
        assert any(
            n["quorumSet"] and n["quorumSet"]["innerQuorumSets"]
            and not n["publicKey"].startswith("CORE")
            for n in data
        )
        res = solve(data, backend="python")
        assert res.intersects is True
        assert len(res.quorum_scc_ids) == 1
        assert len(res.main_scc) == 11
        g = build_graph(parse_fbas(data))
        core = {i for i in range(g.n) if g.node_ids[i].startswith("CORE")}
        assert set(res.main_scc) == core

    def test_degenerate_args_rejected(self):
        from quorum_intersection_tpu.fbas.synth import benchmark_fbas

        for n_total, core in ((10, 2), (10, 11)):
            with pytest.raises(ValueError):
                benchmark_fbas(n_total, core)
