"""Threshold-circuit encoder tests: normalization, interning, multiplicity."""

import numpy as np
import pytest

from quorum_intersection_tpu.encode.circuit import encode_circuit, node_sat_np
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import hierarchical_fbas


def _circuit(data):
    g = build_graph(parse_fbas(data))
    return g, encode_circuit(g)


def test_roots_are_first_n_units():
    g, c = _circuit(hierarchical_fbas(3, 3))
    assert c.n == 9
    assert c.n_units >= c.n


def test_interning_shares_identical_inner_sets():
    # 16 orgs × 16 validators: every node carries the same 16 org inner sets;
    # interning keeps the circuit at n + 16 units instead of n + 16n.
    g, c = _circuit(hierarchical_fbas(16, 16))
    assert c.n == 256
    assert c.n_units == 256 + 16
    assert c.depth == 1


def test_normalization_null_zero_negative_thresholds():
    data = [
        {"publicKey": "A", "quorumSet": None},
        {"publicKey": "B", "quorumSet": {"threshold": 0, "validators": ["A", "B"]}},
        {"publicKey": "C", "quorumSet": {"threshold": -3, "validators": ["A"]}},
        {"publicKey": "D", "quorumSet": {"threshold": 1, "validators": ["D"]}},
    ]
    _, c = _circuit(data)
    avail = np.ones((1, 4), dtype=bool)
    sat = node_sat_np(c, avail)
    # A (null), B (t=0), C (t<0) never satisfiable; D self-satisfied.
    assert sat[0].tolist() == [False, False, False, True]


def test_duplicate_validator_votes():
    # B listed twice: two votes, so threshold 2 is met by B alone.
    data = [
        {"publicKey": "A", "quorumSet": {"threshold": 2, "validators": ["B", "B"]}},
        {"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["B"]}},
    ]
    _, c = _circuit(data)
    avail = np.array([[True, True]])
    assert node_sat_np(c, avail)[0].tolist() == [True, True]


def test_duplicate_inner_set_votes_after_interning():
    # The same inner set twice → interned to one unit with child count 2,
    # so threshold 2 is met when the single shared inner set is satisfied.
    inner = {"threshold": 1, "validators": ["B"], "innerQuorumSets": []}
    data = [
        {
            "publicKey": "A",
            "quorumSet": {"threshold": 2, "validators": [], "innerQuorumSets": [inner, dict(inner)]},
        },
        {"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["B"]}},
    ]
    _, c = _circuit(data)
    assert c.n_units == 3  # two roots + ONE interned inner unit
    avail = np.array([[True, True]])
    assert node_sat_np(c, avail)[0].tolist() == [True, True]


def test_overflow_raises_not_wraps():
    inner = {"threshold": 1, "validators": ["B"], "innerQuorumSets": []}
    data = [
        {
            "publicKey": "A",
            "quorumSet": {
                "threshold": 1,
                "validators": [],
                "innerQuorumSets": [dict(inner) for _ in range(256)],
            },
        },
        {"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["B"]}},
    ]
    g = build_graph(parse_fbas(data))
    with pytest.raises(ValueError, match="repeated"):
        encode_circuit(g)
    dup_validators = [
        {"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["B"] * 256}},
        {"publicKey": "B", "quorumSet": None},
    ]
    g = build_graph(parse_fbas(dup_validators))
    with pytest.raises(ValueError, match="255"):
        encode_circuit(g)
