"""Threshold-circuit encoder tests: normalization, interning, multiplicity."""

import numpy as np
import pytest

from quorum_intersection_tpu.encode.circuit import encode_circuit, node_sat_np
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import hierarchical_fbas


def _circuit(data):
    g = build_graph(parse_fbas(data))
    return g, encode_circuit(g)


def test_roots_are_first_n_units():
    g, c = _circuit(hierarchical_fbas(3, 3))
    assert c.n == 9
    assert c.n_units >= c.n


def test_interning_shares_identical_inner_sets():
    # 16 orgs × 16 validators: every node carries the same 16 org inner sets;
    # interning keeps the circuit at n + 16 units instead of n + 16n.
    g, c = _circuit(hierarchical_fbas(16, 16))
    assert c.n == 256
    assert c.n_units == 256 + 16
    assert c.depth == 1


def test_normalization_null_zero_negative_thresholds():
    data = [
        {"publicKey": "A", "quorumSet": None},
        {"publicKey": "B", "quorumSet": {"threshold": 0, "validators": ["A", "B"]}},
        {"publicKey": "C", "quorumSet": {"threshold": -3, "validators": ["A"]}},
        {"publicKey": "D", "quorumSet": {"threshold": 1, "validators": ["D"]}},
    ]
    _, c = _circuit(data)
    avail = np.ones((1, 4), dtype=bool)
    sat = node_sat_np(c, avail)
    # A (null), B (t=0), C (t<0) never satisfiable; D self-satisfied.
    assert sat[0].tolist() == [False, False, False, True]


def test_duplicate_validator_votes():
    # B listed twice: two votes, so threshold 2 is met by B alone.
    data = [
        {"publicKey": "A", "quorumSet": {"threshold": 2, "validators": ["B", "B"]}},
        {"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["B"]}},
    ]
    _, c = _circuit(data)
    avail = np.array([[True, True]])
    assert node_sat_np(c, avail)[0].tolist() == [True, True]


def test_duplicate_inner_set_votes_after_interning():
    # The same inner set twice → interned to one unit with child count 2,
    # so threshold 2 is met when the single shared inner set is satisfied.
    inner = {"threshold": 1, "validators": ["B"], "innerQuorumSets": []}
    data = [
        {
            "publicKey": "A",
            "quorumSet": {"threshold": 2, "validators": [], "innerQuorumSets": [inner, dict(inner)]},
        },
        {"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["B"]}},
    ]
    _, c = _circuit(data)
    assert c.n_units == 3  # two roots + ONE interned inner unit
    avail = np.array([[True, True]])
    assert node_sat_np(c, avail)[0].tolist() == [True, True]


def test_overflow_raises_not_wraps():
    inner = {"threshold": 1, "validators": ["B"], "innerQuorumSets": []}
    data = [
        {
            "publicKey": "A",
            "quorumSet": {
                "threshold": 1,
                "validators": [],
                "innerQuorumSets": [dict(inner) for _ in range(256)],
            },
        },
        {"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["B"]}},
    ]
    g = build_graph(parse_fbas(data))
    with pytest.raises(ValueError, match="repeated"):
        encode_circuit(g)
    dup_validators = [
        {"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["B"] * 256}},
        {"publicKey": "B", "quorumSet": None},
    ]
    g = build_graph(parse_fbas(dup_validators))
    with pytest.raises(ValueError, match="255"):
        encode_circuit(g)


class TestRestrictCircuit:
    """SCC restriction (encode.restrict_circuit_pair): folding constant
    outside-availability into thresholds must be EXACTLY equivalent to the
    full-width fixpoint with a frozen row, for rows supported inside the
    SCC — both folds (scoped Q-side, Q6 D-side)."""

    def _cases(self):
        from quorum_intersection_tpu.fbas.synth import (
            benchmark_fbas, random_fbas, stellar_like_fbas,
        )

        return [
            benchmark_fbas(64, 12, nested_watchers=True, seed=3),
            stellar_like_fbas(n_core_orgs=4, per_org=3, n_watchers=20,
                              n_null=5, n_dangling=2),
            random_fbas(24, seed=5, nested_prob=0.4, null_prob=0.15,
                        dangling_prob=0.2),
        ]

    def test_fixpoint_equivalence_both_folds(self):
        import jax.numpy as jnp

        from quorum_intersection_tpu.backends.tpu.kernels import (
            CircuitArrays, fixpoint,
        )
        from quorum_intersection_tpu.encode.circuit import restrict_circuit_pair
        from quorum_intersection_tpu.fbas.graph import group_sccs, tarjan_scc
        from quorum_intersection_tpu.pipeline import scan_scc_quorums

        rng = np.random.default_rng(0)
        for data in self._cases():
            g = build_graph(parse_fbas(data))
            circuit = encode_circuit(g)
            count, comp = tarjan_scc(g.n, g.succ)
            sccs = group_sccs(g.n, comp, count)
            scc = next(
                (s for s, q in zip(sccs, scan_scc_quorums(g, sccs)) if q),
                sccs[0],
            )
            s = len(scc)
            scoped_c, q6_c = restrict_circuit_pair(circuit, scc)
            assert scoped_c.n == q6_c.n == s
            assert scoped_c.n_units < circuit.n_units or circuit.n == s
            fa = CircuitArrays(circuit)
            rows_s = (rng.random((48, s)) < 0.5).astype(np.float32)
            rows_n = np.zeros((48, g.n), np.float32)
            rows_n[:, scc] = rows_s
            frozen = np.ones(g.n, np.float32)
            frozen[scc] = 0.0
            for rc, froz in ((scoped_c, None), (q6_c, frozen)):
                full = np.asarray(fixpoint(
                    fa, jnp.asarray(rows_n),
                    None if froz is None else jnp.asarray(froz),
                ))[:, scc]
                rest = np.asarray(fixpoint(CircuitArrays(rc), jnp.asarray(rows_s)))
                np.testing.assert_array_equal(full != 0, rest != 0)

    def test_root_layout_and_frozen_helper_fold(self):
        # The Q4/frozen-helper scenario (test_fixpoint_frozen_mask_q6): A's
        # slice needs frozen T — the Q6 fold must satisfy it with A alone,
        # while the scoped fold must not.
        import jax.numpy as jnp

        from quorum_intersection_tpu.backends.tpu.kernels import (
            CircuitArrays, fixpoint,
        )
        from quorum_intersection_tpu.encode.circuit import restrict_circuit_pair

        data = [
            {"publicKey": "A", "quorumSet": {"threshold": 2, "validators": ["A", "T"]}},
            {"publicKey": "T", "quorumSet": None},
        ]
        g = build_graph(parse_fbas(data))
        circuit = encode_circuit(g)
        scoped_c, q6_c = restrict_circuit_pair(circuit, [0])  # S = {A}
        row = jnp.ones((1, 1), jnp.float32)
        assert int(fixpoint(CircuitArrays(q6_c), row).sum()) == 1
        assert int(fixpoint(CircuitArrays(scoped_c), row).sum()) == 0


class TestCanonicalPadding:
    """Warm-start pad ladder (encode.pad_circuit): padding is semantically
    inert for any availability row supported on the original nodes, and it
    preserves the structural invariants the device kernels read off the
    array shapes."""

    def _random_circuit(self, seed, n=11):
        from quorum_intersection_tpu.fbas.synth import random_fbas

        data = random_fbas(n, seed=seed, nested_prob=0.4, null_prob=0.1)
        return encode_circuit(build_graph(parse_fbas(data)))

    def test_pad_targets_ladder_and_invariants(self):
        from quorum_intersection_tpu.encode.circuit import pad_targets

        assert pad_targets(5, 5) == (8, 8)
        assert pad_targets(9, 9) == (16, 16)
        assert pad_targets(36, 36) == (48, 48)
        assert pad_targets(2000, 2000) == (2000, 2000)  # beyond the ladder
        # Inner-unit circuits keep the STRICT n_units > n marker even when
        # both would round to the same rung.
        n_pad, u_pad = pad_targets(30, 31)
        assert n_pad == 32 and u_pad > n_pad
        # No inner units: padded shape stays square.
        assert pad_targets(16, 16) == (16, 16)

    @pytest.mark.parametrize("seed", range(6))
    def test_node_sat_equivalence(self, seed):
        from quorum_intersection_tpu.encode.circuit import (
            max_quorum_np,
            pad_circuit,
            pad_targets,
        )

        circuit = self._random_circuit(seed)
        n_to, u_to = pad_targets(circuit.n, circuit.n_units)
        padded = pad_circuit(circuit, n_to, u_to)
        rng = np.random.default_rng(seed)
        avail = rng.integers(0, 2, size=(16, circuit.n)).astype(bool)
        avail_pad = np.zeros((16, padded.n), dtype=bool)
        avail_pad[:, : circuit.n] = avail

        sat = node_sat_np(circuit, avail)
        sat_pad = node_sat_np(padded, avail_pad)
        np.testing.assert_array_equal(sat_pad[:, : circuit.n], sat)
        assert not sat_pad[:, circuit.n :].any()  # padded nodes are inert

        mq = max_quorum_np(circuit, avail)
        mq_pad = max_quorum_np(padded, avail_pad)
        np.testing.assert_array_equal(mq_pad[:, : circuit.n], mq)
        assert not mq_pad[:, circuit.n :].any()

    def test_pad_identity_and_guards(self):
        from quorum_intersection_tpu.encode.circuit import (
            pad_circuit,
            pad_targets,
        )

        circuit = encode_circuit(
            build_graph(parse_fbas(hierarchical_fbas(4, 2)))
        )
        n_to, u_to = pad_targets(circuit.n, circuit.n_units)
        assert pad_circuit(circuit, circuit.n, circuit.n_units) is circuit
        with pytest.raises(ValueError, match="below circuit shape"):
            pad_circuit(circuit, circuit.n - 1, u_to)
        if circuit.n_units > circuit.n:
            # A square pad target large enough to hold the units would
            # collapse the strict n_units > n inner-unit marker.
            square = max(n_to, u_to)
            with pytest.raises(ValueError, match="inner-unit marker"):
                pad_circuit(circuit, square, square)

    def test_sweep_uses_canonical_shape_with_verdict_parity(self):
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
        from quorum_intersection_tpu.fbas.synth import majority_fbas
        from quorum_intersection_tpu.pipeline import solve

        for broken in (False, True):
            data = majority_fbas(12, broken=broken)
            padded = solve(data, backend=TpuSweepBackend(batch=64))
            exact = solve(
                data, backend=TpuSweepBackend(batch=64, pad_shapes=False)
            )
            assert padded.intersects is exact.intersects is (not broken)
            assert padded.stats["padded_shape"] == [16, 16]
            assert "padded_shape" not in exact.stats
            if broken:
                # Identical enumeration order => identical first hit.
                assert padded.stats["hit_index"] == exact.stats["hit_index"]
                assert padded.q1 == exact.q1 and padded.q2 == exact.q2
