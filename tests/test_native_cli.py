"""Standalone native CLI (`backends/cpp/qi_native.cpp`) — golden fixtures,
exit-code contract, and byte-level differential against the Python CLI
(which the rest of the suite pins to the reference contract, C21/C14-C16)."""

import json
import subprocess
import sys

import pytest

from quorum_intersection_tpu.backends.cpp import build_native_cli
from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas


@pytest.fixture(scope="module")
def native():
    try:
        return str(build_native_cli())
    except Exception as exc:  # pragma: no cover - g++ missing
        pytest.skip(f"native CLI unavailable: {exc}")


def run_native(native, args, stdin_data=""):
    return subprocess.run(
        [native] + args, input=stdin_data, capture_output=True, text=True
    )


def run_python(args, stdin_data=""):
    return subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "--backend", "python"]
        + args,
        input=stdin_data,
        capture_output=True,
        text=True,
    )


GOLDEN = [
    ("correct_trivial.json", "true", 0),
    ("broken_trivial.json", "false", 1),
    ("correct.json", "true", 0),
    ("broken.json", "false", 1),
]


@pytest.mark.parametrize("name,expected_out,expected_code", GOLDEN)
def test_golden_fixtures(native, ref_fixture, name, expected_out, expected_code):
    data = ref_fixture(name).read_text()
    proc = run_native(native, [], data)
    assert proc.stdout.strip() == expected_out
    assert proc.returncode == expected_code


def test_exit_code_contract(native):
    assert run_native(native, ["-h"]).returncode == 0
    bad = run_native(native, ["--definitely-not-a-flag"])
    assert bad.returncode == 1
    assert "Invalid option!" in bad.stdout
    assert run_native(native, [], "not json").returncode == 1
    # PageRank mode always exits 0
    assert (
        run_native(native, ["-p"], json.dumps(majority_fbas(3, broken=True))).returncode
        == 0
    )


@pytest.mark.parametrize("name", [n for n, _, _ in GOLDEN])
def test_verbose_matches_python_cli(native, ref_fixture, name):
    data = ref_fixture(name).read_text()
    n = run_native(native, ["-v"], data)
    p = run_python(["-v"], data)
    assert n.stdout == p.stdout
    assert n.returncode == p.returncode


def test_compat_mode_matches_python_cli(native, ref_fixture):
    data = ref_fixture("correct.json").read_text()
    n = run_native(native, ["-v", "--compat"], data)
    p = run_python(["-v", "--compat"], data)
    assert n.stdout == p.stdout


def test_graphviz_matches_python_cli(native, ref_fixture):
    data = ref_fixture("correct_trivial.json").read_text()
    n = run_native(native, ["-g"], data)
    p = run_python(["-g"], data)
    assert n.stdout == p.stdout


def test_pagerank_matches_python_numerically(native, ref_fixture):
    data = ref_fixture("correct.json").read_text()
    n = run_native(native, ["-p"], data)
    p = run_python(["-p"], data)

    def parse(out):
        ranks = {}
        for line in out.splitlines()[1:]:
            label, _, value = line.rpartition(": ")
            ranks[label] = float(value)
        return ranks

    rn, rp = parse(n.stdout), parse(p.stdout)
    assert rn.keys() == rp.keys()
    for k in rn:
        assert rn[k] == pytest.approx(rp[k], rel=1e-4, abs=1e-7)


@pytest.mark.parametrize(
    "data,expected",
    [
        (majority_fbas(7), "true"),
        (majority_fbas(7, broken=True), "false"),
        (hierarchical_fbas(3, 3), "true"),
        (hierarchical_fbas(3, 3, broken=True), "false"),
    ],
    ids=["maj-safe", "maj-broken", "hier-safe", "hier-broken"],
)
def test_synthetic_verdicts(native, data, expected):
    proc = run_native(native, [], json.dumps(data))
    assert proc.stdout.strip() == expected


def test_randomized_tiebreak_verdict_stable(native, ref_fixture):
    data = ref_fixture("broken.json").read_text()
    for seed in (0, 1, 12345):
        proc = run_native(native, ["--seed", str(seed)], data)
        assert proc.stdout.strip() == "false"
        assert proc.returncode == 1


@pytest.mark.parametrize(
    "payload",
    [
        '[]true',  # trailing garbage
        json.dumps([{"publicKey": "A", "quorumSet": {"validators": ["A"]}}]),  # missing threshold
        json.dumps([{"publicKey": "A", "quorumSet": {"threshold": "x", "validators": ["A"]}}]),
        json.dumps([{"publicKey": "A", "quorumSet": {"threshold": 1.5, "validators": ["A"]}}]),
        json.dumps([{"publicKey": "A", "quorumSet": {"threshold": 1, "validators": [3]}}]),
        json.dumps(
            [
                {"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["A"]}},
                {"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["A"]}},
            ]
        ),  # duplicate publicKey
    ],
    ids=["trailing", "no-threshold", "str-threshold", "float-threshold",
         "nonstr-validator", "dup-key"],
)
def test_rejects_what_python_rejects(native, payload):
    n = run_native(native, [], payload)
    p = run_python([], payload)
    assert n.returncode == 1
    assert p.returncode == 1
    assert "invalid FBAS configuration" in n.stderr
    assert "invalid FBAS configuration" in p.stderr


def test_accepts_numeric_string_threshold_like_python(native):
    payload = json.dumps(
        [{"publicKey": "A", "quorumSet": {"threshold": "1", "validators": ["A"]}}]
    )
    n = run_native(native, [], payload)
    p = run_python([], payload)
    assert (n.stdout, n.returncode) == (p.stdout, p.returncode) == ("true\n", 0)


def test_graphviz_escapes_label(native):
    payload = json.dumps(
        [{"publicKey": "A", "name": 'say "hi"',
          "quorumSet": {"threshold": 1, "validators": ["A"]}}]
    )
    n = run_native(native, ["-g"], payload)
    p = run_python(["-g"], payload)
    assert n.stdout == p.stdout
    assert '\\"hi\\"' in n.stdout


def test_bad_numeric_flag_is_usage_error(native):
    proc = run_native(native, ["-p", "-i", "abc"], "[]")
    assert proc.returncode == 1
    assert "Invalid option!" in proc.stdout


def test_huge_threshold_parity(native):
    # int64 thresholds must not truncate into satisfiability
    payload = json.dumps(
        [{"publicKey": "A", "quorumSet": {"threshold": 4294967297, "validators": ["A"]}}]
    )
    n = run_native(native, [], payload)
    p = run_python([], payload)
    assert (n.stdout, n.returncode) == (p.stdout, p.returncode) == ("false\n", 1)


def test_control_char_rejected_like_python(native):
    payload = '[{"publicKey": "A\tB", "quorumSet": {"threshold": 1, "validators": ["A\tB"]}}]'
    n = run_native(native, [], payload)
    p = run_python([], payload)
    assert n.returncode == p.returncode == 1


def test_duplicate_json_key_last_wins(native):
    # json.loads keeps the LAST occurrence of a duplicate object key
    payload = (
        '[{"publicKey": "A", '
        '"quorumSet": {"threshold": 99, "validators": ["A"]}, '
        '"quorumSet": {"threshold": 1, "validators": ["A"]}}]'
    )
    n = run_native(native, [], payload)
    p = run_python([], payload)
    assert (n.stdout, n.returncode) == (p.stdout, p.returncode) == ("true\n", 0)


def test_missing_flag_value_is_usage_error(native):
    proc = run_native(native, ["-i"], "[]")
    assert proc.returncode == 1
    assert "Invalid option!" in proc.stdout
    assert proc.stderr == ""


def test_huge_threshold_matches_python(native):
    # Thresholds beyond int64 range: Python's arbitrary-precision int()
    # accepts them (an absurdly large threshold is just unsatisfiable);
    # the native CLI clamps to the int64 extremes instead of rejecting.
    huge = "9" * 30
    payload = (
        f'[{{"publicKey": "A", "quorumSet": {{"threshold": "{huge}", '
        '"validators": ["A"]}}, '
        '{"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["B"]}}]'
    )
    n = run_native(native, [], payload)
    p = run_python([], payload)
    assert (n.stdout, n.returncode) == (p.stdout, p.returncode)

    neg = f'[{{"publicKey": "A", "quorumSet": {{"threshold": "-{huge}", "validators": ["A"]}}}}]'
    n = run_native(native, [], neg)
    p = run_python([], neg)
    assert (n.stdout, n.returncode) == (p.stdout, p.returncode)

    junk = f'[{{"publicKey": "A", "quorumSet": {{"threshold": "{huge}x", "validators": ["A"]}}}}]'
    n = run_native(native, [], junk)
    p = run_python([], junk)
    assert n.returncode == p.returncode == 1


def test_whitespace_padded_huge_threshold_matches_python(native):
    # \v-prefixed over-int64 threshold: std::stoll skips \v per isspace and
    # throws out_of_range; the clamp handler must skip the same whitespace
    # set or the two CLIs diverge (Python int() accepts it).
    huge = "9" * 30
    payload = (
        f'[{{"publicKey": "A", "quorumSet": {{"threshold": "\\u000b{huge} ", '
        '"validators": ["A"]}}]'
    )
    n = run_native(native, [], payload)
    p = run_python([], payload)
    assert (n.stdout, n.returncode) == (p.stdout, p.returncode)


def test_duplicate_publickey_rejected_both_clis(native):
    # Deviation D1 (docs/PARITY.md): the reference silently aliases edge
    # targets to the last duplicate (cpp:445); both CLIs here reject.
    payload = (
        '[{"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["A"]}}, '
        '{"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["A"]}}]'
    )
    n = run_native(native, [], payload)
    p = run_python([], payload)
    assert n.returncode == p.returncode == 1
    assert "duplicate publicKey" in n.stderr
    assert "duplicate publicKey" in p.stderr
