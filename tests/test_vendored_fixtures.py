"""Self-contained golden corpus (`fixtures/`, VERDICT r2 §missing-1).

These tests never touch `/root/reference`: the vendored pass/fail pairs
(frozen from the deterministic synthetic generators by
`tools/make_fixtures.py`) carry their own golden verdicts and structural
stats in `fixtures/MANIFEST.json`, so verdict parity stays a *running*
gate — not a skip — when the reference checkout is absent.
"""

import json
import subprocess
import sys

import pytest

from tests.conftest import vendored_fixture_text, vendored_manifest
from quorum_intersection_tpu.fbas import synth
from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.pipeline import solve

MANIFEST = vendored_manifest()
SMALL = [n for n in MANIFEST if not n.endswith(".gz")]


@pytest.mark.parametrize("name", SMALL)
def test_python_oracle_matches_manifest(name):
    res = solve(vendored_fixture_text(name), backend="python")
    assert res.intersects is MANIFEST[name]["verdict"]


@pytest.mark.parametrize("name", SMALL)
def test_sweep_backend_matches_manifest(name):
    res = solve(vendored_fixture_text(name), backend="tpu-sweep")
    assert res.intersects is MANIFEST[name]["verdict"]


@pytest.mark.parametrize("name", SMALL)
def test_cpp_oracle_matches_manifest(name):
    pytest.importorskip("ctypes")
    try:
        from quorum_intersection_tpu.backends.cpp import CppOracleBackend

        CppOracleBackend().ensure_built()
    except Exception as exc:  # noqa: BLE001 — no g++ in this env
        pytest.skip(f"native oracle unavailable: {exc}")
    res = solve(vendored_fixture_text(name), backend="cpp")
    assert res.intersects is MANIFEST[name]["verdict"]


@pytest.mark.parametrize("name", list(MANIFEST))
def test_structure_matches_manifest(name):
    """The frozen stats pin the generators: any drift in synth.py or the
    frontend shows up as a manifest mismatch, the same way the reference
    pair methodology pins one knob (SURVEY.md §4.1)."""
    want = MANIFEST[name]
    graph = build_graph(parse_fbas(vendored_fixture_text(name)), dangling="strict")
    count, comp = tarjan_scc(graph.n, graph.succ)
    sccs = group_sccs(graph.n, comp, count)
    assert graph.n == want["nodes"]
    assert count == want["n_sccs"]
    assert max(len(s) for s in sccs) == want["largest_scc"]
    assert sum(1 for q in graph.qsets if q.threshold is None) == want["null_qsets"]
    assert graph.dangling_refs == want["dangling_refs"]


def test_generators_reproduce_frozen_trivial_pair():
    """`tools/make_fixtures.py` is deterministic — spot-check that the
    committed bytes match a fresh generation for the trivial pair."""
    frozen = json.loads(vendored_fixture_text("trivial_correct.json"))
    assert frozen == synth.majority_fbas(3, prefix="TRIV")
    frozen = json.loads(vendored_fixture_text("trivial_broken.json"))
    assert frozen == synth.majority_fbas(3, broken=True, prefix="TRIV")


@pytest.mark.parametrize(
    "name,expected_out,expected_code",
    [
        ("trivial_correct.json", "true", 0),
        ("trivial_broken.json", "false", 1),
        ("snapshot_correct.json", "true", 0),
        ("snapshot_broken.json", "false", 1),
    ],
)
def test_cli_contract_on_vendored_corpus(name, expected_out, expected_code):
    proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "--backend", "python"],
        input=vendored_fixture_text(name),
        capture_output=True,
        text=True,
    )
    assert proc.stdout.strip() == expected_out
    assert proc.returncode == expected_code
