"""Splitting-set (safety margin, byzantine-deletion semantics) tests."""

import json
import subprocess
import sys

from quorum_intersection_tpu.analytics.splitting import (
    delete_nodes,
    is_splitting,
    minimum_splitting_set,
)
from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas


def test_majority_splitting_number():
    # Classic k-of-n result under byzantine deletion: a splitting set needs
    # 2k - n members (the survivors' reduced thresholds then admit two
    # disjoint quorums).  n=4, k=3 → 2;  n=3, k=2 → 1;  n=7, k=4 → 1.
    for n, expect in ((4, 2), (3, 1), (7, 1)):
        data = majority_fbas(n)
        split = minimum_splitting_set(data, max_k=2)
        assert split is not None and len(split) == expect, (n, split)


def test_supermajority_resists_small_splits():
    # 6-of-7: 2k - n = 5 > 2 → nothing within max_k=2 splits.
    data = [
        {"publicKey": f"K{i}", "name": f"k{i}",
         "quorumSet": {"threshold": 6, "validators": [f"K{j}" for j in range(7)],
                       "innerQuorumSets": []}}
        for i in range(7)
    ]
    assert minimum_splitting_set(data, max_k=2) is None


def test_broken_network_splits_with_empty_set():
    data = majority_fbas(4, broken=True)
    assert minimum_splitting_set(data) == []


def test_halting_deletion_is_not_a_split():
    # Deleting the whole validator list of everyone leaves trivial slices —
    # but deleting nodes that merely REMOVE all quorums (halt) must not
    # count as splitting.  A 2-node network 2-of-2: deleting one node makes
    # the survivor's slice 1-of-1 over itself → single quorum, intersecting.
    data = [
        {"publicKey": "A", "name": "a",
         "quorumSet": {"threshold": 2, "validators": ["A", "B"], "innerQuorumSets": []}},
        {"publicKey": "B", "name": "b",
         "quorumSet": {"threshold": 2, "validators": ["A", "B"], "innerQuorumSets": []}},
    ]
    assert not is_splitting(data, ["A"])


def test_delete_reduces_thresholds_and_propagates_trivial_inner():
    # 2-of-3 inner set fully deleted (2 of its members) → trivially
    # satisfied → parent threshold drops by one.
    data = [{"publicKey": "P", "name": "p", "quorumSet": {
        "threshold": 2,
        "validators": ["X"],
        "innerQuorumSets": [
            {"threshold": 2, "validators": ["A", "B", "C"], "innerQuorumSets": []}
        ],
    }}]
    out = delete_nodes(data, ["A", "B"])
    q = out[0]["quorumSet"]
    assert q["threshold"] == 1  # inner became trivial: 2 - 1
    assert q["validators"] == ["X"]
    assert q["innerQuorumSets"] == []


def test_hierarchical_splitting():
    # 5 orgs × 3 validators (3-of-5 orgs, 2-of-3 inner): ONE byzantine
    # validator suffices — its org's inner set drops to 1-of-2, so the org
    # satisfies BOTH sides via different surviving members, and each side
    # completes its 3-of-5 with two further disjoint org-majorities.
    data = hierarchical_fbas(5, 3)
    split = minimum_splitting_set(data, max_k=2)
    assert split is not None and len(split) == 1


def test_cli_splitting_set_mode():
    proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "--splitting-set"],
        input=json.dumps(majority_fbas(4)),
        capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0
    assert "minimum splitting set (2 nodes):" in proc.stdout


def test_cli_splitting_set_none_within_k():
    data = [
        {"publicKey": f"K{i}", "name": f"k{i}",
         "quorumSet": {"threshold": 6, "validators": [f"K{j}" for j in range(7)],
                       "innerQuorumSets": []}}
        for i in range(7)
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "--splitting-set"],
        input=json.dumps(data), capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0
    assert "no splitting set" in proc.stdout


def test_string_thresholds_scrub_like_ints():
    # ptree compat: the schema accepts numeric-string thresholds; deletion
    # must too, or byzantine analysis silently degrades to crash semantics.
    data = majority_fbas(3)
    for node in data:
        node["quorumSet"]["threshold"] = str(node["quorumSet"]["threshold"])
    split = minimum_splitting_set(data, max_k=2)
    assert split is not None and len(split) == 1


def test_preexisting_zero_threshold_keeps_q3_semantics():
    # A threshold<=0 qset is never satisfiable (Q3) — deletion of ZERO
    # nodes must not flip it to trivially-true and fabricate a split.
    data = majority_fbas(3) + [
        {"publicKey": "ZZ", "name": "zz",
         "quorumSet": {"threshold": 0, "validators": [], "innerQuorumSets": []}}
    ]
    assert not is_splitting(data, [])


def test_splitting_probes_skip_certificate_assembly():
    # is_splitting sits in minimum_splitting_set's combinatorial loop: its
    # internal solves run with with_cert=False, so the loop never pays
    # per-candidate certificate assembly or floods the run record with
    # cert.* events (which would saturate the in-memory event cap real
    # certificates' provenance slices read from).
    from quorum_intersection_tpu.utils import telemetry

    rec = telemetry.reset_run_record()
    try:
        data = majority_fbas(5)
        assert minimum_splitting_set(data, max_k=2) is not None
        counters, _ = rec.snapshot()
        assert counters.get("cert.certificates", 0) == 0
    finally:
        telemetry.reset_run_record()
