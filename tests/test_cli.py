"""CLI contract tests — flag surface, exit codes, output modes
(SURVEY.md §2.2, C21)."""

import json
import subprocess
import sys

import pytest

from quorum_intersection_tpu.fbas.synth import majority_fbas

CLI = [sys.executable, "-m", "quorum_intersection_tpu"]


def run_cli(args, stdin_data=""):
    return subprocess.run(
        CLI + args, input=stdin_data, capture_output=True, text=True
    )


def _json(data):
    return json.dumps(data)


def test_true_verdict_exit_0():
    proc = run_cli(["--backend", "python"], _json(majority_fbas(3)))
    assert proc.stdout.strip() == "true"
    assert proc.returncode == 0


def test_false_verdict_exit_1():
    proc = run_cli(["--backend", "python"], _json(majority_fbas(3, broken=True)))
    assert proc.stdout.strip() == "false"
    assert proc.returncode == 1


def test_help_exit_0():
    proc = run_cli(["-h"])
    assert proc.returncode == 0
    assert "usage" in proc.stdout.lower()


def test_invalid_option_message_and_exit_1():
    # cpp:771-775: "Invalid option!" + usage to *stdout*, exit 1.
    proc = run_cli(["--definitely-not-a-flag"])
    assert proc.returncode == 1
    assert "Invalid option!" in proc.stdout
    assert "usage" in proc.stdout.lower()


def test_verbose_narration():
    proc = run_cli(["-v", "--backend", "python"], _json(majority_fbas(3)))
    assert "total number of strongly connected components" in proc.stdout
    assert proc.stdout.rstrip().endswith("true")


def test_graphviz_before_verdict():
    # cpp:635-637: dot dump precedes the verdict line, which still prints.
    proc = run_cli(["-g", "--backend", "python"], _json(majority_fbas(3)))
    assert proc.stdout.startswith("digraph G {")
    assert proc.stdout.rstrip().endswith("true")
    assert proc.returncode == 0


def test_pagerank_mode_exit_0():
    proc = run_cli(["-p"], _json(majority_fbas(3)))
    assert proc.returncode == 0
    assert proc.stdout.startswith("PageRank:")
    assert len(proc.stdout.strip().splitlines()) == 4  # header + 3 nodes


def test_pagerank_flags_accepted():
    proc = run_cli(["-p", "-i", "10", "-m", "0.15", "-c", "0.001"], _json(majority_fbas(3)))
    assert proc.returncode == 0


def test_compat_mode():
    proc = run_cli(["--compat", "--backend", "python"], _json(majority_fbas(3)))
    assert proc.stdout.strip() == "true"


def test_schema_error_reported_cleanly():
    proc = run_cli(["--backend", "python"], '[{"name": "no-key"}]')
    assert proc.returncode == 1
    assert "invalid FBAS configuration" in proc.stderr


def test_timing_flag():
    proc = run_cli(["--timing", "--backend", "python"], _json(majority_fbas(3)))
    assert proc.returncode == 0
    assert "[timing]" in proc.stderr
    assert "[stats]" in proc.stderr


@pytest.mark.parametrize(
    "name,expected_out,expected_code",
    [
        ("correct_trivial.json", "true", 0),
        ("broken_trivial.json", "false", 1),
        ("correct.json", "true", 0),
        ("broken.json", "false", 1),
    ],
)
def test_golden_fixture_cli_contract(ref_fixture, name, expected_out, expected_code):
    with open(ref_fixture(name)) as f:
        data = f.read()
    proc = run_cli(["--backend", "python"], data)
    assert proc.stdout.strip() == expected_out
    assert proc.returncode == expected_code


def test_checkpoint_flag(tmp_path):
    # A completed sweep clears its checkpoint; the flag must round-trip
    # through backend construction without disturbing the verdict.
    ck = tmp_path / "sweep.ckpt"
    proc = run_cli(
        ["--backend", "tpu-sweep", "--checkpoint", str(ck)],
        _json(majority_fbas(5)),
    )
    assert proc.stdout.strip() == "true"
    assert proc.returncode == 0
    assert not ck.exists()  # cleared on completion


def test_checkpoint_flag_requires_sweep_backend(tmp_path):
    proc = run_cli(
        ["--backend", "python", "--checkpoint", str(tmp_path / "x")],
        _json(majority_fbas(3)),
    )
    assert proc.returncode == 1
    assert "checkpoint-capable" in proc.stderr


def test_profile_dir_flag(tmp_path):
    trace = tmp_path / "trace"
    proc = run_cli(
        ["--backend", "tpu-sweep", "--profile-dir", str(trace)],
        _json(majority_fbas(5)),
    )
    assert proc.stdout.strip() == "true"
    assert proc.returncode == 0
    # jax writes plugins/profile/<ts>/*.xplane.pb under the trace dir
    assert any(trace.rglob("*.xplane.pb"))


def test_mesh_flag_sharded_sweep():
    # conftest pins JAX_PLATFORMS=cpu with 8 emulated devices; the child
    # CLI inherits that env, so --mesh 2 builds a real 2-device mesh.
    proc = run_cli(
        ["--backend", "tpu-sweep", "--mesh", "2"], _json(majority_fbas(9))
    )
    assert proc.stdout.strip() == "true"
    assert proc.returncode == 0


def test_mesh_flag_all_devices_broken_network():
    proc = run_cli(
        ["--backend", "tpu-sweep", "--mesh", "all"],
        _json(majority_fbas(9, broken=True)),
    )
    assert proc.stdout.strip() == "false"
    assert proc.returncode == 1


def test_mesh_flag_requires_device_backend():
    proc = run_cli(
        ["--backend", "python", "--mesh", "2"], _json(majority_fbas(3))
    )
    assert proc.returncode == 1
    assert "--mesh requires a device backend" in proc.stderr


def test_mesh_flag_bad_values():
    proc = run_cli(
        ["--backend", "tpu-sweep", "--mesh", "lots"], _json(majority_fbas(3))
    )
    assert proc.returncode == 1
    assert "device count or 'all'" in proc.stderr
    proc = run_cli(
        ["--backend", "tpu-sweep", "--mesh", "999"], _json(majority_fbas(3))
    )
    assert proc.returncode == 1


def test_mesh_flag_rejects_nonpositive():
    for value in ("0", "-2"):
        proc = run_cli(
            ["--backend", "tpu-sweep", "--mesh", value], _json(majority_fbas(3))
        )
        assert proc.returncode == 1
        assert "positive device count" in proc.stderr
