"""Native C++ oracle backend — build, parity, and differential tests.

The C++ oracle (backends/cpp/qi_oracle.cpp) must be *verdict- and
statistics-identical* to the pure-Python oracle in deterministic mode: both
implement the same pinned search (SURVEY.md §2.1 C4-C9), so their
branch-and-bound call counts, minimal-quorum counts, and fixpoint counts must
match exactly — any drift means the native port diverged from the spec.
"""

from __future__ import annotations

import shutil

import numpy as np
import pytest

from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import (
    hierarchical_fbas,
    majority_fbas,
    random_fbas,
)
from quorum_intersection_tpu.pipeline import solve

if shutil.which("g++") is None:
    pytest.skip("g++ not available", allow_module_level=True)

pytest.importorskip("quorum_intersection_tpu.backends.cpp")

from quorum_intersection_tpu.backends.cpp import (
    CppOracleBackend,
    native_candidate_check,
)


STATS_KEYS = ("bnb_calls", "minimal_quorums", "fixpoint_calls")


def _both(source, **solve_kwargs):
    rp = solve(source, backend="python", **solve_kwargs)
    rc = solve(source, backend="cpp", **solve_kwargs)
    return rp, rc


def _assert_lockstep(rp, rc):
    assert rc.intersects == rp.intersects
    assert rc.q1 == rp.q1
    assert rc.q2 == rp.q2
    for key in STATS_KEYS:
        if key in rp.stats:
            assert rc.stats[key] == rp.stats[key], key


class TestGoldenFixtures:
    @pytest.mark.parametrize(
        "name,want",
        [
            ("correct_trivial.json", True),
            ("broken_trivial.json", False),
            ("correct.json", True),
            ("broken.json", False),
        ],
    )
    def test_verdict_and_stats_lockstep(self, ref_fixture, name, want):
        source = ref_fixture(name).read_text()
        rp, rc = _both(source)
        assert rc.intersects is want
        _assert_lockstep(rp, rc)

    def test_alias0_compat_mode(self, ref_fixture):
        # Reference dangling semantics (Q1) must also agree across backends.
        source = ref_fixture("broken.json").read_text()
        rp, rc = _both(source, dangling="alias0")
        assert rc.intersects is False
        _assert_lockstep(rp, rc)


class TestSynthetic:
    @pytest.mark.parametrize("n", [4, 7, 10, 13])
    @pytest.mark.parametrize("broken", [False, True])
    def test_majority(self, n, broken):
        rp, rc = _both(majority_fbas(n, broken=broken))
        assert rc.intersects is (not broken)
        _assert_lockstep(rp, rc)

    @pytest.mark.parametrize("broken", [False, True])
    def test_hierarchical(self, broken):
        rp, rc = _both(hierarchical_fbas(4, 3, broken=broken))
        assert rc.intersects is (not broken)
        _assert_lockstep(rp, rc)

    @pytest.mark.parametrize("seed", range(8))
    def test_random_differential(self, seed):
        fbas = random_fbas(
            16, seed=seed, nested_prob=0.3, null_prob=0.1, dangling_prob=0.2
        )
        rp, rc = _both(fbas)
        _assert_lockstep(rp, rc)

    def test_scoped_availability(self):
        rp, rc = _both(majority_fbas(9, broken=True), scope_to_scc=True)
        assert rc.intersects is False
        _assert_lockstep(rp, rc)


class TestRandomizedTieBreak:
    """The randomized branching heuristic is the reference's only
    nondeterminism; verdicts must be seed-independent (SURVEY.md C7)."""

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_verdict_seed_independent(self, seed):
        for broken in (False, True):
            fbas = majority_fbas(8, broken=broken)
            det = solve(fbas, backend="cpp").intersects
            rnd = solve(
                fbas, backend=CppOracleBackend(seed=seed, randomized=True)
            ).intersects
            assert det == rnd == (not broken)


class TestNativeCandidateCheck:
    def test_hit_count_matches_host_semantics(self):
        from quorum_intersection_tpu.fbas.semantics import max_quorum

        graph = build_graph(parse_fbas(hierarchical_fbas(3, 3)))
        rng = np.random.default_rng(0)
        masks = rng.random((64, graph.n)) < 0.5

        hits, seconds = native_candidate_check(graph, masks)
        assert seconds >= 0

        expected = 0
        for row in masks:
            avail = row.tolist()
            cand = [v for v in range(graph.n) if avail[v]]
            q = max_quorum(graph, cand, avail)
            qset = set(q)
            comp_avail = [v not in qset for v in range(graph.n)]
            comp = [v for v in range(graph.n) if comp_avail[v]]
            d = max_quorum(graph, comp, comp_avail)
            if q and d:
                expected += 1
        assert hits == expected


class TestThresholdExtremes:
    """Arbitrary-precision JSON thresholds must not crash or skew any
    engine: the ctypes FlatGraph raised OverflowError on out-of-int32
    values until the Q3 clamp matched qi_native.cpp's (found by
    tools/fuzz_python.py; the schema deliberately accepts any integer)."""

    @pytest.mark.parametrize("extreme", [
        9999999999999999999999999,   # far beyond int64
        2**31,                       # first value past int32
        -(2**31) - 1,                # first value below int32
        -1,
    ])
    def test_engines_agree_with_extreme_threshold_node(self, extreme):
        import json

        from quorum_intersection_tpu.pipeline import solve

        payload = json.dumps([
            {"publicKey": "A",
             "quorumSet": {"threshold": 2, "validators": ["A", "B"]}},
            {"publicKey": "B",
             "quorumSet": {"threshold": 2, "validators": ["A", "B"]}},
            # The extreme-threshold node is OUTSIDE the quorum-bearing SCC
            # but inside the flattened graph — exactly the shape that
            # reached FlatGraph's int32 table and crashed.
            {"publicKey": "C",
             "quorumSet": {"threshold": extreme,
                           "validators": ["A", "B", "C"]}},
        ])
        verdicts = {
            solve(payload, backend=b).intersects
            for b in ("python", "cpp", "tpu-sweep")
        }
        assert verdicts == {True}
