"""Multi-device sharding tests on the emulated 8-device CPU mesh —
the fake-backend analog for TPU pods (SURVEY.md §4.3 item 5)."""

import jax
import numpy as np
import pytest

from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.semantics import is_quorum
from quorum_intersection_tpu.fbas.synth import (
    hierarchical_fbas,
    majority_fbas,
    random_fbas,
)
from quorum_intersection_tpu.parallel.mesh import candidate_mesh
from quorum_intersection_tpu.pipeline import solve

needs_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (emulated) devices"
)


def test_candidate_mesh_uses_all_devices():
    mesh = candidate_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == ("candidates",)


def test_candidate_mesh_prefix():
    mesh = candidate_mesh(2)
    assert mesh.devices.size == 2
    with pytest.raises(ValueError):
        candidate_mesh(10_000)


@needs_8_devices
@pytest.mark.parametrize("n_dev", [2, 8])
def test_sharded_sweep_verdict_parity(n_dev):
    mesh = candidate_mesh(n_dev)
    for data, expected in (
        (majority_fbas(11), True),
        (majority_fbas(11, broken=True), False),
    ):
        res = solve(data, backend=TpuSweepBackend(batch=64 * n_dev, mesh=mesh))
        assert res.intersects is expected


@needs_8_devices
def test_sharded_witness_is_valid_quorum_pair():
    mesh = candidate_mesh(8)
    data = majority_fbas(12, broken=True)
    res = solve(data, backend=TpuSweepBackend(batch=256, mesh=mesh))
    assert not res.intersects
    g = build_graph(parse_fbas(data))
    assert is_quorum(g, res.q1) and is_quorum(g, res.q2)
    assert not (set(res.q1) & set(res.q2))


@needs_8_devices
def test_sharded_matches_unsharded_on_random_fbas():
    mesh = candidate_mesh(8)
    for seed in (0, 3, 9):
        data = random_fbas(13, seed=seed, nested_prob=0.3, null_prob=0.1)
        single = solve(data, backend=TpuSweepBackend(batch=256))
        sharded = solve(data, backend=TpuSweepBackend(batch=256, mesh=mesh))
        assert single.intersects is sharded.intersects


@needs_8_devices
def test_graft_dryrun_multichip():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)

    fn, args = mod.entry()
    hit, q_size = jax.jit(fn)(*args)
    assert hit.shape == (256,)
    assert q_size.shape == (256,)
    assert not bool(np.asarray(hit).any())  # flagship problem is a safe network


class TestDistributed:
    """Single-process degenerate behavior of the multi-host helpers (the
    multi-process paths need a real pod; these pin the contracts that hold
    everywhere)."""

    def test_initialize_noop_single_process(self):
        from quorum_intersection_tpu.parallel import distributed

        distributed.initialize()  # must not raise or block
        assert distributed.is_multihost() is False
        distributed.initialize()  # idempotent

    def test_global_mesh_covers_all_devices(self):
        from quorum_intersection_tpu.parallel import distributed

        mesh = distributed.global_candidate_mesh()
        assert mesh.devices.size == len(jax.devices())
        assert mesh.axis_names == ("candidates",)

    @needs_8_devices
    def test_hybrid_topology_mesh_falls_back_cleanly(self):
        # "hybrid" here is mesh TOPOLOGY (ICI within a slice, DCN across
        # hosts — mesh_utils.create_hybrid_device_mesh), unrelated to the
        # retired hybrid search engine.
        from quorum_intersection_tpu.parallel import distributed

        mesh = distributed.hybrid_candidate_mesh()
        assert mesh.devices.size == len(jax.devices())

    @needs_8_devices
    def test_sweep_on_global_mesh(self):
        from quorum_intersection_tpu.parallel import distributed

        backend = TpuSweepBackend(batch=64, mesh=distributed.global_candidate_mesh())
        assert solve(majority_fbas(9), backend=backend).intersects is True
        backend = TpuSweepBackend(batch=64, mesh=distributed.global_candidate_mesh())
        res = solve(majority_fbas(9, broken=True), backend=backend)
        assert res.intersects is False


class TestShardedCoverage:
    """Full-coverage evidence for the sharded sweep: (1) a safe sweep checks
    exactly the whole enumeration; (2) the sharded witness is the globally
    smallest hit index — identical to the unsharded run — which could not
    hold if any device skipped its sub-blocks."""

    @needs_8_devices
    def test_safe_sweep_counts_whole_enumeration(self):
        mesh = candidate_mesh(8)
        res = solve(majority_fbas(13), backend=TpuSweepBackend(batch=256, mesh=mesh))
        assert res.intersects is True
        assert res.stats["candidates_checked"] >= res.stats["enumeration_total"]

    @needs_8_devices
    def test_sharded_hit_index_matches_unsharded(self):
        mesh = candidate_mesh(8)
        data = majority_fbas(12, broken=True)
        single = solve(data, backend=TpuSweepBackend(batch=256))
        sharded = solve(data, backend=TpuSweepBackend(batch=256, mesh=mesh))
        assert single.intersects is sharded.intersects is False
        assert single.stats["hit_index"] == sharded.stats["hit_index"]


@needs_8_devices
def test_mesh_scaling_benchmark_smoke(tmp_path):
    """The weak-scaling benchmark script must run all widths with verdict
    parity and write its results table (small workload for CI budget)."""
    import subprocess
    import sys

    out = tmp_path / "scaling.txt"
    proc = subprocess.run(
        [sys.executable, "benchmarks/mesh_scaling.py", "--nodes", "13",
         "--out", str(out)],
        capture_output=True, text=True, timeout=300,
        cwd=str(__import__("pathlib").Path(__file__).parent.parent),
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    table = out.read_text()
    for n_dev in (1, 2, 4, 8):
        assert f"\n{n_dev:>5}  " in table
    assert "speedup 8-dev vs 1-dev" in table


@needs_8_devices
def test_mesh_sweep_ramp_jump(monkeypatch):
    """The sharded factory's precompile hook: the jump engages on a mesh
    (deterministic inline fake thread) with verdict parity."""
    import quorum_intersection_tpu.backends.tpu.sweep as sweep_mod
    from tests.test_tpu_backends import TestRampJump

    monkeypatch.setattr(sweep_mod, "_thread_factory", TestRampJump._InlineThread)
    mesh = candidate_mesh(8)
    res = solve(majority_fbas(15), backend=TpuSweepBackend(batch=64, mesh=mesh))
    assert res.intersects is True
    assert res.stats["steady_level"] > 1
    assert res.stats["candidates_checked"] >= res.stats["enumeration_total"]


@needs_8_devices
def test_frontier_mesh_count_parity():
    # The mesh-sharded frontier must enumerate EXACTLY the oracle's set of
    # minimal quorums (count parity = completeness through the sharded
    # fixpoint + all_gather path), and find witnesses on broken networks.
    from quorum_intersection_tpu.backends.python_oracle import PythonOracleBackend
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend

    mesh = candidate_mesh(8)
    po = solve(hierarchical_fbas(4, 3), backend=PythonOracleBackend())
    fr = solve(
        hierarchical_fbas(4, 3),
        backend=TpuFrontierBackend(arena=4096, pop=250, mesh=mesh),
    )
    assert fr.intersects is True
    assert fr.stats["minimal_quorums"] == po.stats["minimal_quorums"] > 0

    br = solve(
        majority_fbas(12, broken=True),
        backend=TpuFrontierBackend(arena=4096, pop=256, mesh=mesh),
    )
    assert br.intersects is False
    assert br.q1 and br.q2 and not set(br.q1) & set(br.q2)


@needs_8_devices
def test_sharded_sweep_on_restricted_wide_graph():
    # Mesh sharding composes with the SCC-restricted circuit (the sharded
    # step builder threads the Q6 fold as arrays_d): verdict + witness
    # parity on a 48-node graph with a 12-node k-of-n core.
    from quorum_intersection_tpu.fbas.synth import benchmark_fbas

    mesh = candidate_mesh(8)
    for broken in (False, True):
        data = benchmark_fbas(48, 12, broken=broken, seed=3)
        want = solve(data, backend="python")
        got = solve(data, backend=TpuSweepBackend(batch=64, mesh=mesh))
        assert got.intersects is want.intersects is (not broken)
        if not got.intersects:
            assert got.q1 and got.q2 and not set(got.q1) & set(got.q2)


@needs_8_devices
def test_frontier_mesh_with_device_flag_filter():
    # Mesh sharding composes with the batched device flag pipeline (the
    # filter runs replicated outside the shard_mapped chunk): count parity
    # on a flag-heavy safe network, exact witness on a broken one, zero
    # serial host checks on the safe path.
    from quorum_intersection_tpu.backends.python_oracle import PythonOracleBackend
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
    from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

    mesh = candidate_mesh(8)
    po = solve(hierarchical_fbas(5, 3), backend=PythonOracleBackend())
    fr = solve(
        hierarchical_fbas(5, 3),
        backend=TpuFrontierBackend(arena=8192, pop=256, mesh=mesh,
                                   flag_check="device"),
    )
    assert fr.intersects is True
    assert fr.stats["minimal_quorums"] == po.stats["minimal_quorums"] > 0
    assert fr.stats["host_checks"] == 0

    br = solve(
        stellar_like_fbas(n_core_orgs=4, per_org=3, n_watchers=10, broken=True),
        backend=TpuFrontierBackend(arena=8192, pop=256, mesh=mesh,
                                   flag_check="device"),
    )
    assert br.intersects is False
    assert br.q1 and br.q2 and not set(br.q1) & set(br.q2)


@needs_8_devices
def test_frontier_mesh_nondividing_device_count():
    # A device count that does not divide arena//4 must clamp the rounded
    # pop block so the overflow-spill compaction can never go negative
    # (regression: 3-device mesh, pop=512, arena=2048 crashed mid-spill),
    # and the flag capacity must follow the EFFECTIVE block size.
    from quorum_intersection_tpu.backends.python_oracle import PythonOracleBackend
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend

    mesh = candidate_mesh(3)
    po = solve(hierarchical_fbas(4, 3), backend=PythonOracleBackend())
    fr = solve(
        hierarchical_fbas(4, 3),
        backend=TpuFrontierBackend(arena=2048, pop=512, mesh=mesh),
    )
    assert fr.intersects is True
    assert fr.stats["minimal_quorums"] == po.stats["minimal_quorums"]

    with pytest.raises(ValueError, match="too small"):
        solve(
            majority_fbas(9),
            backend=TpuFrontierBackend(arena=8, pop=4, mesh=mesh),
        )


@needs_8_devices
def test_auto_backend_forwards_mesh():
    from quorum_intersection_tpu.backends.auto import AutoBackend

    mesh = candidate_mesh(4)
    auto = AutoBackend(mesh=mesh)
    assert auto._sweep().mesh is mesh
    # Frontier mesh plumbing rides auto's win-region route AND the CLI;
    # direct construction covers the attribute contract.
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend

    assert TpuFrontierBackend(mesh=mesh).mesh is mesh
