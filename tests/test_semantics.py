"""Reference-faithful set-semantics tests: slice satisfaction quirks Q2-Q4,
fixpoint behavior, hand-computed cases (SURVEY.md §4.3 item 4)."""

from quorum_intersection_tpu.fbas.graph import IndexedQSet, build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.semantics import is_quorum, max_quorum, slice_satisfied
from quorum_intersection_tpu.fbas.synth import majority_fbas


def _graph(data):
    return build_graph(parse_fbas(data))


def q(t, members=(), inner=()):
    return IndexedQSet(threshold=t, members=tuple(members), inner=tuple(inner))


class TestSliceSatisfied:
    def test_simple_threshold(self):
        qs = q(2, [0, 1, 2])
        assert slice_satisfied(0, qs, [True, True, False])
        assert not slice_satisfied(0, qs, [True, False, False])

    def test_q4_self_availability_required(self):
        # Owner 3 not in its own validator list — still must be available (cpp:95-98).
        qs = q(1, [0])
        assert not slice_satisfied(3, qs, [True, True, True, False])
        assert slice_satisfied(3, qs, [True, True, True, True])

    def test_q2_null_qset_never_satisfiable(self):
        assert not slice_satisfied(0, IndexedQSet(threshold=None), [True])

    def test_q3_zero_threshold_never_satisfiable(self):
        assert not slice_satisfied(0, q(0, [0, 1]), [True, True])
        assert not slice_satisfied(0, q(0), [True])

    def test_q3_threshold_above_members_never_satisfiable(self):
        assert not slice_satisfied(0, q(3, [0, 1]), [True, True])

    def test_inner_sets_count_as_one_vote(self):
        # 2 votes needed: validator 0 + satisfied inner {1 or 2}.
        qs = q(2, [0], [q(1, [1, 2])])
        assert slice_satisfied(0, qs, [True, False, True])
        assert not slice_satisfied(0, qs, [True, False, False])

    def test_nested_depth_two(self):
        deep = q(1, [], [q(1, [], [q(1, [2])])])
        assert slice_satisfied(0, deep, [True, False, True])
        assert not slice_satisfied(0, deep, [True, False, False])

    def test_inner_self_availability_uses_owner(self):
        # Inner recursion passes the *owner*, not the inner members (cpp:121).
        qs = q(1, [], [q(1, [1])])
        assert not slice_satisfied(0, qs, [False, True])


class TestMaxQuorum:
    def test_majority_is_quorum(self):
        g = _graph(majority_fbas(5))
        avail = [True] * 5
        assert sorted(max_quorum(g, range(5), avail)) == [0, 1, 2, 3, 4]
        # 3-of-5 subset is also a quorum (k = 3)
        avail = [True, True, True, False, False]
        assert sorted(max_quorum(g, [0, 1, 2], avail)) == [0, 1, 2]
        # ...but a 2-node subset is not
        avail = [True, True, False, False, False]
        assert max_quorum(g, [0, 1], avail) == []

    def test_avail_restored_after_call(self):
        g = _graph(majority_fbas(5))
        avail = [True, True, False, False, False]
        max_quorum(g, [0, 1], avail)
        assert avail == [True, True, False, False, False]  # cpp:171-173

    def test_cascade_removal(self):
        # 0 needs 1, 1 needs 2, 2 needs itself only; removing 2 cascades.
        data = [
            {"publicKey": "A", "quorumSet": {"threshold": 2, "validators": ["A", "B"]}},
            {"publicKey": "B", "quorumSet": {"threshold": 2, "validators": ["B", "C"]}},
            {"publicKey": "C", "quorumSet": {"threshold": 1, "validators": ["C"]}},
        ]
        g = _graph(data)
        avail = [True, True, True]
        assert sorted(max_quorum(g, range(3), avail)) == [0, 1, 2]
        avail = [True, True, False]
        assert max_quorum(g, [0, 1], avail) == []

    def test_null_qset_nodes_never_in_quorum(self):
        data = majority_fbas(3) + [{"publicKey": "NULL1", "quorumSet": None}]
        g = _graph(data)
        avail = [True] * 4
        assert sorted(max_quorum(g, range(4), avail)) == [0, 1, 2]

    def test_is_quorum(self):
        g = _graph(majority_fbas(5))
        assert is_quorum(g, [0, 1, 2])
        assert not is_quorum(g, [0, 1])
