"""qi-cost suite (ISSUE 17): attribution must be conserved and invisible.

Acceptance, per ISSUE 17:

- the conservation invariant, property-style: for every (lane tile, slot,
  group count, window count) shape the sum of attributed lane·windows
  equals the pack total *exactly* — including a mid-pack cancel (dead
  lanes bill to the request that retired them) and the delta reuse
  credit (zero new work, credit == the cached solve's lane·windows);
- fused-vs-unfused cost parity modulo pad amortization: identical
  topologies co-packed book the same per-request lane·windows as their
  solo dispatches (zero pad), and the two live counters agree;
- the SLO plane's multiwindow burn discipline: ``slo.burn`` fires exactly
  once on a synthetic sustained breach, never on a lone spike, never on
  recovery;
- the ``cost.attribute`` fault point degrades to a *dropped* cost —
  verdict and cert byte-identical with attribution off;
- the adaptive fuse-window controller's decision table is pinned, and the
  forced ``cost_window_decision_races_late_admit`` interleaving
  (tools/analyze/schedules.py) passes on both topologies.
"""

import copy
import json
import threading

import pytest

from quorum_intersection_tpu.backends.base import CancelToken
from quorum_intersection_tpu.cost import (
    AUTO_WINDOW_BURN_CAP_MS,
    AUTO_WINDOW_CAP_MS,
    AUTO_WINDOW_FLOOR_MS,
    SloPlane,
    TenantTable,
    attribute_pack,
    choose_fuse_window,
    fleet_tenant_table,
    merge_costs,
    merge_tenant_snapshots,
    pack_lane_shares,
    parse_slo,
    reset_cost_state,
    reuse_credit,
    solo_cost,
    tenant_table,
)
from quorum_intersection_tpu.fbas.synth import (
    churn_trace,
    majority_fbas,
    stellar_like_fbas,
)
from quorum_intersection_tpu.pipeline import check_many, solve
from quorum_intersection_tpu.serve import ServeEngine
from quorum_intersection_tpu.utils import faults, telemetry
import quorum_intersection_tpu.backends.tpu.sweep as sweep_mod
import quorum_intersection_tpu.cost as cost_mod
from tools.check_cert import check_certificate

from tests.conftest import VENDORED_DIR

FIXTURE_PAIRS = [
    ("trivial_correct", True),
    ("trivial_broken", False),
    ("nested_correct", True),
    ("nested_broken", False),
]


def fixture_nodes(name):
    return json.loads((VENDORED_DIR / f"{name}.json").read_text())


@pytest.fixture
def rec():
    record = telemetry.reset_run_record()
    faults.clear_plan()
    reset_cost_state()
    yield record
    faults.clear_plan()
    reset_cost_state()
    telemetry.reset_run_record()


def serve_one(nodes, **kw):
    engine = ServeEngine(backend=kw.pop("backend", "auto"), **kw)
    try:
        engine.start()
        return engine.submit(nodes).result(timeout=120.0)
    finally:
        engine.stop(drain=True, timeout=30.0)


def normalized(cert):
    """A cert with the run-volatile provenance block dropped: what must
    be byte-identical with attribution degraded."""
    out = copy.deepcopy(cert)
    out.pop("provenance", None)
    return out


class TestConservation:
    """sum(attributed lane·windows) == pack total, exactly, always."""

    SHAPES = [
        (n_lanes, slot, k)
        for n_lanes in (8, 16, 32, 64, 128)
        for slot in (1, 2, 4, 8, 16)
        for k in (1, 2, 3, 5, 7)
        if k * slot <= n_lanes
    ]

    def test_lane_shares_conserve_every_shape(self):
        for n_lanes, slot, k in self.SHAPES:
            shares = pack_lane_shares(n_lanes, slot, k)
            assert sum(shares) == n_lanes
            assert len(shares) == k
            assert all(s >= slot for s in shares)
            # Pad splits as evenly as integers allow.
            assert max(shares) - min(shares) <= 1

    def test_lane_shares_rejects_bad_shapes(self):
        with pytest.raises(ValueError):
            pack_lane_shares(8, 4, 0)
        with pytest.raises(ValueError):
            pack_lane_shares(8, 8, 2)  # n_lanes < k*slot

    def test_attribute_pack_conserves_every_shape(self):
        for n_lanes, slot, k in self.SHAPES:
            for pack_rows in (1, 7, 256):
                # Duplicate origins (one request, many groups) merge.
                origins = [f"job-{gix % max(1, k - 1)}" for gix in range(k)]
                costs = attribute_pack(
                    origins, n_lanes, slot, pack_rows,
                    macs_per_row=n_lanes * 64, seconds=0.25,
                )
                total = sum(int(c["lane_windows"]) for c in costs.values())
                assert total == n_lanes * pack_rows, (n_lanes, slot, k)
                assert sum(int(c["lanes"]) for c in costs.values()) == n_lanes
                assert sum(int(c["groups"]) for c in costs.values()) == k
                # Pro-rated wall clock re-sums to the dispatch wall.
                assert sum(float(c["device_s"]) for c in costs.values()) == \
                    pytest.approx(0.25, abs=1e-6)

    def test_cancelled_group_keeps_its_origin(self):
        """A retired group's lanes bill to the canceller — ownership is
        never reassigned mid-pack, so conservation needs no special
        case for dead lanes."""
        costs = attribute_pack(
            ["req-dead", "req-live", "req-live"], 32, 8, 100,
            macs_per_row=2048, seconds=0.1,
        )
        assert set(costs) == {"req-dead", "req-live"}
        dead, live = costs["req-dead"], costs["req-live"]
        assert int(dead["lane_windows"]) > 0
        assert int(dead["lane_windows"]) + int(live["lane_windows"]) == \
            32 * 100

    def test_reuse_credit_is_zero_work_plus_credit(self):
        cached = solo_cost(16, 256, macs_per_row=4096, seconds=0.5)
        credit = reuse_credit(cached)
        assert credit["reused"] is True
        assert credit["lane_windows"] == 0
        assert credit["macs"] == 0
        assert credit["device_s"] == 0.0
        assert credit["credit_lane_windows"] == cached["lane_windows"]
        # A cost-less cached solve (python oracle) credits nothing.
        assert reuse_credit(None)["credit_lane_windows"] == 0

    def test_merge_costs_conserves_sums_and_credit(self):
        parts = [
            solo_cost(16, 256, macs_per_row=4096, seconds=0.5),
            attribute_pack(["a"], 32, 16, 64,
                           macs_per_row=2048, seconds=0.1)["a"],
            reuse_credit(solo_cost(8, 32, macs_per_row=512, seconds=0.2)),
        ]
        merged = merge_costs(parts)
        assert merged["lane_windows"] == \
            sum(int(p["lane_windows"]) for p in parts)
        assert merged["macs"] == sum(int(p["macs"]) for p in parts)
        assert merged["fused"] is True
        assert merged["reused"] is True
        assert merged["credit_lane_windows"] == 8 * 32

    def test_pack_counters_conserve_end_to_end(self, rec):
        """Through the real sweep pack drain: attributed == total."""
        streams = [majority_fbas(9, prefix=f"P{i}") for i in range(3)]
        results = check_many(streams, backend="auto", pack=True,
                             origins=["a", "b", "c"])
        for res in results:
            assert res.intersects is True
            cost = res.stats.get("cost")
            assert cost is not None and cost["fused"] is True
            assert int(cost["lane_windows"]) > 0
        counters, _ = rec.snapshot()
        assert counters.get("cost.lane_windows_attributed", 0) > 0
        assert counters["cost.lane_windows_attributed"] == \
            counters["cost.lane_windows_total"]

    def test_mid_pack_cancel_conserves_and_bills_canceller(self, rec):
        """A token cancelled DURING the first sweep window retires its
        lanes mid-pack; the dead request is still billed its full group
        share and the live counters stay equal."""
        tok = CancelToken()
        real = sweep_mod.fault_point
        state = {"hits": 0}

        def cancel_mid(point):
            if point == "sweep.window":
                state["hits"] += 1
                if state["hits"] == 1:
                    tok.cancel()
            return real(point)

        sweep_mod.fault_point = cancel_mid
        try:
            dead, live = check_many(
                [majority_fbas(13), majority_fbas(11)], backend="auto",
                pack=True, cancels=[tok, None],
                origins=["req-dead", "req-live"],
            )
        finally:
            sweep_mod.fault_point = real
        assert dead.stats.get("cancelled") is True
        assert live.intersects is True
        dead_cost = dead.stats.get("cost")
        assert dead_cost is not None and int(dead_cost["lane_windows"]) > 0
        counters, _ = rec.snapshot()
        assert counters["cost.lane_windows_attributed"] == \
            counters["cost.lane_windows_total"]


class TestCostParity:
    """Fused and unfused book the same work, modulo pad amortization."""

    def test_identical_topologies_pad_free_parity(self, rec):
        """Three identical-shape requests co-pack with zero pad, so each
        fused share equals its solo dispatch's lane·windows exactly."""
        streams = [majority_fbas(9, prefix=f"P{i}") for i in range(3)]
        solo = solve(streams[0], backend="tpu-sweep").stats["cost"]
        assert solo["fused"] is False
        fused = check_many(streams, backend="auto", pack=True,
                           origins=["a", "b", "c"])
        for res in fused:
            cost = res.stats["cost"]
            assert cost["fused"] is True
            assert cost["windows"] == solo["windows"]
            assert cost["lane_windows"] == solo["lane_windows"]

    def test_mixed_pack_amortizes_only_pad(self, rec):
        """Different-size requests: each share is at least its ladder
        slot and the excess over all slots is exactly the pack pad."""
        streams = [majority_fbas(n) for n in (7, 9, 11)]
        results = check_many(streams, backend="auto", pack=True,
                             origins=list("abc"))
        costs = [r.stats["cost"] for r in results]
        windows = {int(c["windows"]) for c in costs}
        assert len(windows) == 1  # one pack, one window count
        lanes = [int(c["lanes"]) for c in costs]
        slot = min(lanes)
        n_lanes = sum(lanes)
        assert sum(int(c["lane_windows"]) for c in costs) == \
            n_lanes * windows.pop()
        assert all(lane >= slot for lane in lanes)
        assert max(lanes) - slot <= (n_lanes - 3 * slot) + 1


class TestTenantTable:
    def test_lru_bound_and_eviction_counter(self, rec):
        table = TenantTable(capacity=3)
        for i in range(5):
            table.book(f"client-{i}",
                       solo_cost(8, 4, macs_per_row=64, seconds=0.01))
        assert len(table) == 3
        snap = table.snapshot()
        assert set(snap) == {"client-2", "client-3", "client-4"}
        counters, _ = rec.snapshot()
        assert counters.get("cost.tenants_evicted", 0) == 2

    def test_booking_touches_lru_order(self, rec):
        table = TenantTable(capacity=2)
        table.book("a", None)
        table.book("b", None)
        table.book("a", None)  # touch: a is now most recent
        table.book("c", None)  # evicts b, not a
        assert set(table.snapshot()) == {"a", "c"}

    def test_top_ranks_by_lane_windows_then_requests(self):
        table = TenantTable(capacity=8)
        table.book("small", solo_cost(1, 4, macs_per_row=1, seconds=0.0))
        table.book("big", solo_cost(64, 64, macs_per_row=1, seconds=0.0))
        table.book("chatty", None)
        table.book("chatty", None)
        ranked = [client for client, _ in table.top(2)]
        assert ranked == ["big", "small"]

    def test_merge_then_replace_never_double_counts(self):
        part = {"t": {"requests": 2, "lane_windows": 100, "macs": 5,
                      "credit_lane_windows": 0, "device_s": 0.5}}
        merged = merge_tenant_snapshots([part, part])
        assert merged["t"]["lane_windows"] == 200
        fleet = TenantTable(capacity=8)
        fleet.replace(merged)
        fleet.replace(merged)  # cumulative snapshots: replace, not add
        assert fleet.snapshot()["t"]["lane_windows"] == 200

    def test_serve_books_clients_cache_hit_costless(self, rec):
        """Alice's solve books real lane·windows; Bob's identical request
        is a cache hit — the request books, zero new device work."""
        nodes = majority_fbas(9)
        engine = ServeEngine(backend="tpu-sweep")
        try:
            engine.start()
            first = engine.submit(nodes, client="alice").result(timeout=120.0)
            second = engine.submit(nodes, client="bob").result(timeout=120.0)
        finally:
            engine.stop(drain=True, timeout=30.0)
        assert first.intersects is second.intersects is True
        assert second.cached is True and second.cost is None
        assert first.cost is not None
        snap = tenant_table().snapshot()
        assert snap["alice"]["requests"] == 1
        assert snap["alice"]["lane_windows"] == first.cost["lane_windows"]
        assert snap["bob"] == {"requests": 1, "lane_windows": 0, "macs": 0,
                               "credit_lane_windows": 0, "device_s": 0.0}
        assert first.cert["provenance"]["cost"] == first.cost

    def test_serve_churn_books_delta_credit(self, rec):
        """Delta-reused SCCs ride the wire as credits and aggregate into
        the tenant's credit_lane_windows — never its lane_windows."""
        base = stellar_like_fbas(n_core_orgs=3, per_org=2, n_watchers=12,
                                 seed=7)
        trace = churn_trace(base, 4, seed=3)
        engine = ServeEngine(backend="tpu-sweep")
        try:
            engine.start()
            responses = [
                engine.submit(snap, client="churner").result(timeout=120.0)
                for snap in trace
            ]
        finally:
            engine.stop(drain=True, timeout=30.0)
        assert all(r.intersects for r in responses)
        reused = [r.cost for r in responses
                  if r.cost is not None and r.cost.get("reused")]
        assert reused, "churn never exercised delta reuse"
        assert all(r["lane_windows"] == 0 for r in reused)
        assert all(int(r["credit_lane_windows"]) > 0 for r in reused)
        row = tenant_table().snapshot()["churner"]
        assert row["requests"] == len(trace)
        assert int(row["credit_lane_windows"]) >= len(reused)

    def test_final_lines_carry_tenant_table(self, rec):
        """The finish-time JSONL stream exports the table — and stays
        byte-identical when nothing was booked."""
        kinds = [line.get("kind") for line in rec.final_lines()]
        assert "tenants" not in kinds
        tenant_table().book("alice",
                            solo_cost(8, 4, macs_per_row=64, seconds=0.01))
        lines = [line for line in rec.final_lines()
                 if line.get("kind") == "tenants"]
        assert len(lines) == 1
        assert lines[0]["schema"] == "qi-cost/1"
        assert lines[0]["tenants"]["alice"]["requests"] == 1


class TestSloPlane:
    SPEC = "serve_e2e_p99_ms<500"

    def test_parse_slo_clauses(self):
        targets = parse_slo("serve_e2e_p99_ms<500, pack_fill_pct>60")
        assert [(t.metric, t.op, t.bound) for t in targets] == [
            ("serve_e2e_p99_ms", "<", 500.0),
            ("pack_fill_pct", ">", 60.0),
        ]
        assert targets[0].violated(700.0) and not targets[0].violated(100.0)
        assert targets[1].violated(50.0) and not targets[1].violated(80.0)
        # Malformed clauses skip loudly, never raise.
        assert parse_slo("nonsense,p99<abc,,") == []
        assert parse_slo("") == [] and SloPlane(spec="").enabled is False

    def _drive(self, plane, rec, value, start, n, step=60.0):
        rec.gauge("serve.p99_ms", value)
        t = start
        for _ in range(n):
            status = plane.evaluate(now=t)
            t += step
        return t, status

    def test_burn_fires_once_on_breach_never_on_recovery(self, rec):
        plane = SloPlane(spec=self.SPEC, fast_s=300.0, slow_s=3600.0)
        t, status = self._drive(plane, rec, 120.0, 1000.0, 20)
        assert status["burning"] == 0

        # A lone spike inside a healthy fast window must NOT page.
        t, _ = self._drive(plane, rec, 900.0, t, 1)
        t, status = self._drive(plane, rec, 120.0, t, 5)
        assert status["burning"] == 0
        assert not [e for e in rec.events if e["name"] == "slo.burn"]

        # A sustained breach pages exactly once.
        t, status = self._drive(plane, rec, 900.0, t, 10)
        assert status["burning"] == 1
        (target,) = status["targets"]
        assert target["burning"] is True
        assert target["fast_ratio"] >= 0.5
        burns = [e for e in rec.events if e["name"] == "slo.burn"]
        assert len(burns) == 1
        assert burns[0]["attrs"]["metric"] == "serve_e2e_p99_ms"
        _, gauges = rec.snapshot()
        assert gauges.get("slo.burning") == 1

        # Recovery clears the gauge silently — no recovery event, no
        # re-fire while the fast window drains the bad samples out.
        t, status = self._drive(plane, rec, 120.0, t, 10)
        assert status["burning"] == 0
        assert plane.burning_count() == 0
        assert len([e for e in rec.events if e["name"] == "slo.burn"]) == 1
        _, gauges = rec.snapshot()
        assert gauges.get("slo.burning") == 0

    def test_evaluate_degrades_through_fault_point(self, rec):
        plane = SloPlane(spec=self.SPEC, fast_s=300.0, slow_s=3600.0)
        faults.install_plan(faults.parse_faults("cost.attribute=error@1+"))
        status = plane.evaluate(now=1.0)
        assert status["degraded"] is True
        counters, _ = rec.snapshot()
        assert counters.get("cost.attribute_errors", 0) == 1
        degr = [e for e in rec.events if e["name"] == "cost.degraded"]
        assert degr and degr[0]["attrs"]["site"] == "slo.evaluate"

    def test_sloz_payload_carries_tenants(self, rec, monkeypatch):
        monkeypatch.setenv("QI_SLO", self.SPEC)
        reset_cost_state()
        tenant_table().book("alice",
                            solo_cost(8, 4, macs_per_row=64, seconds=0.01))
        fleet_tenant_table().replace({"bob": {
            "requests": 3, "lane_windows": 64, "macs": 9,
            "credit_lane_windows": 0, "device_s": 0.1,
        }})
        from quorum_intersection_tpu.utils.metrics_server import (
            healthz_payload, sloz_payload,
        )
        payload = sloz_payload()
        assert payload["schema"] == "qi-slo/1"
        assert payload["enabled"] is True
        assert payload["tenants"]["local"][0]["client"] == "alice"
        assert payload["tenants"]["fleet"][0]["client"] == "bob"
        health = healthz_payload()
        assert "slo_burning" in health
        assert "cost_attribute_errors" in health


class TestCostFaultPoint:
    """cost.attribute=error: dropped cost, byte-identical everything."""

    def test_degrade_leaves_verdict_and_cert_byte_identical(self, rec):
        clean = {}
        for fixture, verdict in FIXTURE_PAIRS:
            res = solve(fixture_nodes(fixture), backend="tpu-sweep")
            assert res.intersects is verdict
            clean[fixture] = res
        # Guard-short-circuited fixtures never dispatch a sweep; at least
        # the swept ones must have stamped provenance.cost when healthy.
        assert any("cost" in r.cert.get("provenance", {})
                   for r in clean.values())
        faults.clear_plan()
        telemetry.reset_run_record()
        rec = telemetry.get_run_record()
        faults.install_plan(faults.parse_faults("cost.attribute=error@1+"))
        for fixture, verdict in FIXTURE_PAIRS:
            res = solve(fixture_nodes(fixture), backend="tpu-sweep")
            assert res.intersects is verdict
            assert res.stats.get("cost") is None
            assert "cost" not in res.cert.get("provenance", {})
            assert json.dumps(normalized(res.cert), sort_keys=True) == \
                json.dumps(normalized(clean[fixture].cert), sort_keys=True)
            check_certificate(res.cert, fixture_nodes(fixture))
        n_swept = sum(1 for r in clean.values()
                      if "cost" in r.cert.get("provenance", {}))
        counters, _ = rec.snapshot()
        assert counters.get("cost.attribute_errors", 0) >= n_swept
        assert counters.get("cost.lane_windows_attributed", 0) == 0
        # The degraded total still counts the device work that happened.
        assert counters.get("cost.lane_windows_total", 0) > 0
        sites = {e["attrs"]["site"] for e in rec.events
                 if e["name"] == "cost.degraded"}
        assert "sweep.solo" in sites

    def test_serve_degrade_books_nothing_answers_everything(self, rec):
        faults.install_plan(faults.parse_faults("cost.attribute=error@1+"))
        resp = serve_one(majority_fbas(9), backend="tpu-sweep")
        assert resp.intersects is True
        assert resp.cost is None
        assert "cost" not in resp.cert.get("provenance", {})
        check_certificate(resp.cert, majority_fbas(9))
        assert len(tenant_table()) == 0
        counters, _ = rec.snapshot()
        assert counters.get("cost.attribute_errors", 0) >= 1


class TestAutoWindow:
    """The closed loop's decision table, pinned."""

    @pytest.mark.parametrize("depth,p99,burning,expect", [
        (0, 100.0, False, 0.0),     # sparse: never wait on nothing
        (0, 100.0, True, 0.0),
        (5, 100.0, False, AUTO_WINDOW_CAP_MS),
        (4, 60.0, False, 15.0),     # p99/4 inside [floor, cap]
        (3, 2.0, False, AUTO_WINDOW_FLOOR_MS),
        (5, 100.0, True, AUTO_WINDOW_BURN_CAP_MS),
        (3, 2.0, True, AUTO_WINDOW_FLOOR_MS),  # floor already under cap
    ])
    def test_decision_table(self, depth, p99, burning, expect):
        assert choose_fuse_window(depth, p99, burning) == expect

    def test_decision_bounds_hold_everywhere(self):
        for depth in (0, 1, 3, 17):
            for p99 in (0.0, 1.0, 40.0, 10_000.0):
                for burning in (False, True):
                    w = choose_fuse_window(depth, p99, burning)
                    assert 0.0 <= w <= AUTO_WINDOW_CAP_MS
                    if depth <= 0:
                        assert w == 0.0
                    else:
                        assert w >= min(AUTO_WINDOW_FLOOR_MS,
                                        AUTO_WINDOW_CAP_MS)
                        if burning:
                            assert w <= AUTO_WINDOW_BURN_CAP_MS

    def test_engine_accepts_auto_and_decides_per_flush(self, rec):
        """End-to-end: an 'auto' engine answers correctly and logs a
        serve.fuse_window decision for its drain cycle."""
        engine = ServeEngine(backend="python", fuse_window_ms="auto")
        try:
            engine.start()
            resp = engine.submit(majority_fbas(9)).result(timeout=120.0)
        finally:
            engine.stop(drain=True, timeout=30.0)
        assert resp.intersects is True
        decisions = [e for e in rec.events
                     if e["name"] == "serve.fuse_window"]
        assert decisions
        for d in decisions:
            assert 0.0 <= d["attrs"]["window_ms"] <= AUTO_WINDOW_CAP_MS
        _, gauges = rec.snapshot()
        assert "serve.fuse_window_ms" in gauges


class TestForcedCostSchedules:
    """The window-decision-vs-late-admit interleaving, forced every run
    (the same harness `python -m tools.analyze race` executes in CI)."""

    @pytest.fixture(scope="class")
    def results(self):
        from tools.analyze.schedules import run_cost_schedules

        return run_cost_schedules()

    def test_all_schedules_pass_both_topologies(self, results):
        from tools.analyze.schedules import COST_SCHEDULES

        assert "cost_window_decision_races_late_admit" in COST_SCHEDULES
        assert len(results) == len(COST_SCHEDULES) * 2
        bad = [r for r in results if not r.ok]
        assert not bad, bad

    def test_late_admit_gets_its_own_decision(self, results):
        for r in results:
            assert r.trace.count("cost.window.decide") >= 2

    def test_hook_restored_and_no_leaked_drains(self, results):
        assert cost_mod._cost_sync is None
        assert not [
            t for t in threading.enumerate()
            if t.name.startswith("qi-serve-drain")
        ]
