"""Blocking-set (liveness resilience) analytics tests."""

import pytest

from quorum_intersection_tpu.analytics.resilience import (
    is_blocking,
    minimal_blocking_set,
    minimum_blocking_size,
)
from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.semantics import max_quorum
from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas


def _scc_of(data):
    graph = build_graph(parse_fbas(data))
    count, comp = tarjan_scc(graph.n, graph.succ)
    sccs = group_sccs(graph.n, comp, count)
    for members in sccs:
        avail = [v in set(members) for v in range(graph.n)]
        if max_quorum(graph, members, avail):
            return graph, members
    return graph, sccs[0]


def test_majority_blocking_number():
    # k-of-n majority (k = n//2 + 1): any n - k + 1 failures block every
    # quorum; fewer cannot (the survivors still hold a k-majority).
    for n in (3, 5, 7):
        graph, scc = _scc_of(majority_fbas(n))
        k = n // 2 + 1
        expect = n - k + 1
        assert minimum_blocking_size(graph, scc) == expect
        minimal = minimal_blocking_set(graph, scc)
        assert is_blocking(graph, scc, minimal)
        # inclusion-minimality: no single member can be dropped
        for v in minimal:
            assert not is_blocking(graph, scc, [w for w in minimal if w != v])


def test_hierarchical_blocking_set():
    # 5 orgs x 3 validators, 3-of-5 orgs with 2-of-3 inner sets: killing 2
    # validators in each of 3 orgs (6 nodes) blocks; the minimum is 6.
    graph, scc = _scc_of(hierarchical_fbas(5, 3))
    assert len(scc) == 15
    minimal = minimal_blocking_set(graph, scc)
    assert is_blocking(graph, scc, minimal)
    assert minimum_blocking_size(graph, scc) == 6


def test_no_quorum_scc_blocked_by_nothing():
    data = [{"publicKey": f"N{i}", "name": "", "quorumSet": None} for i in range(3)]
    graph = build_graph(parse_fbas(data))
    assert minimal_blocking_set(graph, [0, 1, 2]) == []
    assert minimum_blocking_size(graph, [0, 1, 2]) == 0


def test_exact_search_cap():
    graph, scc = _scc_of(majority_fbas(5))
    assert minimum_blocking_size(graph, scc, limit=3) is None  # |scc|=5 > 3


def test_cli_blocking_set_mode(ref_fixture):
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "--blocking-set"],
        input=ref_fixture("correct.json").read_text(),
        capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0
    assert proc.stdout.startswith("minimal blocking set (2 nodes):")
    assert "minimum blocking size: 2" in proc.stdout


def test_cli_blocking_set_no_quorum():
    import json
    import subprocess
    import sys

    data = json.dumps(
        [{"publicKey": f"N{i}", "name": "", "quorumSet": None} for i in range(3)]
    )
    proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "--blocking-set"],
        input=data, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    assert "none needed" in proc.stdout


def test_cli_blocking_set_covers_every_quorum_scc():
    """Two independent quorum-bearing SCCs: halting the network requires
    blocking both — the union set and the summed minimum."""
    import json
    import subprocess
    import sys

    data = json.dumps(
        majority_fbas(3, prefix="ISLA") + majority_fbas(3, prefix="ISLB")
    )
    proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "--blocking-set"],
        input=data, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    # 2-of-3 majority per island: 2 failures block each, 4 total.
    assert "minimal blocking set (4 nodes):" in proc.stdout
    assert "minimum blocking size: 4" in proc.stdout
