"""Auto-routing cost-model calibration (backends/calibration.py): derived
values must be traceable to a named artifact, clamped against artifact rot,
and fall back to the r3 constants when nothing applies."""

import json

from quorum_intersection_tpu.backends.calibration import (
    DEFAULT_ORACLE_SPC,
    DEFAULT_SWEEP_RATE,
    calibrate,
)


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return p


def test_no_artifacts_yields_defaults():
    cal = calibrate(paths=[])
    assert cal.sweep_rate == DEFAULT_SWEEP_RATE
    assert cal.oracle_seconds_per_call == DEFAULT_ORACLE_SPC
    assert all(v == "default" for v in cal.provenance.values())


def test_derives_from_tpu_record_with_provenance(tmp_path):
    p = _write(tmp_path, "BENCH_r09.json", {
        "device": "TPU v5 lite",
        "wide_sweep_device_cand_per_sec": 8e8,
        "verdict_256": {"native_engine": "cpp", "native_rate": 2e6},
    })
    cal = calibrate(paths=[p])
    assert cal.sweep_rate["accel"] == 4e8  # halved for tunnel variance
    assert cal.oracle_seconds_per_call["cpp"] == 1 / 2e6
    assert "BENCH_r09.json" in cal.provenance["accel"]
    assert "native_rate" in cal.provenance["cpp"]
    # No CPU record: cpu rate stays at the default.
    assert cal.sweep_rate["cpu"] == DEFAULT_SWEEP_RATE["cpu"]


def test_cpu_record_and_newest_round_wins(tmp_path):
    a = _write(tmp_path, "BENCH_r05.json", {
        "device": "cpu-fallback", "sweep_steady_rate": 2e6,
    })
    b = _write(tmp_path, "BENCH_r06.json", {
        "device": "TPU v5 lite", "wide_sweep_device_cand_per_sec": 6e8,
    })
    c = _write(tmp_path, "unnumbered.json", {
        "device": "TPU v5 lite", "wide_sweep_device_cand_per_sec": 4e8,
    })
    cal = calibrate(paths=[a, b, c])
    assert cal.sweep_rate["cpu"] == 2e6 / 4
    assert cal.sweep_rate["accel"] == 3e8  # r06 outranks the unnumbered file
    assert "BENCH_r06.json" in cal.provenance["accel"]

    # A NEWER round that measured slower must lower the estimate — the
    # model tracks the hardware last measured, not the fastest ever seen.
    d = _write(tmp_path, "BENCH_r07.json", {
        "device": "TPU v5 lite", "wide_sweep_device_cand_per_sec": 1.2e8,
    })
    cal = calibrate(paths=[a, b, c, d])
    assert cal.sweep_rate["accel"] == 0.6e8
    assert "BENCH_r07.json" in cal.provenance["accel"]

    # A file whose name embeds a big number OUTSIDE the r<N> round
    # convention must not outrank real rounds.
    e = _write(tmp_path, "verdict_1024.json", {
        "device": "TPU v5 lite", "wide_sweep_device_cand_per_sec": 9e8,
    })
    cal = calibrate(paths=[a, b, c, d, e])
    assert "BENCH_r07.json" in cal.provenance["accel"]


def test_out_of_window_and_corrupt_artifacts_ignored(tmp_path):
    bad_rate = _write(tmp_path, "BENCH_r07.json", {
        "device": "TPU v5 lite",
        "wide_sweep_device_cand_per_sec": 1e15,  # unit bug: above window
        "verdict_256": {"native_engine": "python", "native_rate": 4e4},
    })
    corrupt = tmp_path / "BENCH_r08.json"
    corrupt.write_text("{not json")
    engineless = _write(tmp_path, "BENCH_r09.json", {
        "device": "cpu-fallback",
        "verdict_1024": {"native_rate": 5e4},  # no native_engine label
    })
    cal = calibrate(paths=[bad_rate, corrupt, engineless])
    assert cal.sweep_rate["accel"] == DEFAULT_SWEEP_RATE["accel"]
    # python-engine AND unlabeled native_rate must not calibrate the cpp
    # oracle (either would shrink its budget ~50x in the unsafe direction).
    assert cal.oracle_seconds_per_call["cpp"] == DEFAULT_ORACLE_SPC["cpp"]


def test_driver_wrapper_tail_shape(tmp_path):
    # The driver's BENCH_r*.json wraps the headline in a "tail" text blob
    # whose last parseable line is the record.
    p = _write(tmp_path, "BENCH_r04.json", {
        "rc": 0,
        "tail": "noise\n" + json.dumps({
            "device": "TPU v5 lite", "sweep_device_cand_per_sec": 3.2e8,
        }),
    })
    cal = calibrate(paths=[p])
    assert cal.sweep_rate["accel"] == 1.6e8
    assert "sweep_device_cand_per_sec" in cal.provenance["accel"]


def test_repo_artifacts_actually_calibrate():
    # This repo carries the r3 on-chip record: the import-time calibration
    # must be traceable to SOME named artifact, not all-defaults.
    from quorum_intersection_tpu.backends import auto
    from quorum_intersection_tpu.backends.calibration import CALIBRATION

    assert CALIBRATION.provenance["accel"] != "default"
    assert ".json" in CALIBRATION.provenance["accel"]
    # auto.py consumes the calibrated dicts (identity, not a copy).
    assert auto.SWEEP_RATE is CALIBRATION.sweep_rate
    assert auto.ORACLE_SECONDS_PER_CALL is CALIBRATION.oracle_seconds_per_call


class TestFrontierWinRegion:
    """Measured-crossover routing: auto sends large SCCs to the frontier
    ONLY inside a win region recorded by an on-chip crossover artifact."""

    def _txt(self, tmp_path, name, rows):
        lines = ["| header |"]
        for row in rows:
            scc, speed, dev, ok = row[:4]
            rec = {
                "workload": f"w{scc}", "scc": scc, "device": dev,
                "frontier_speedup_vs_cpp": speed, "verdict_ok": ok,
                "counts_ok": True,
                # Machine-readable config is an ELIGIBILITY requirement
                # (config-less rows never gate routing).
                "frontier_kw": row[4] if len(row) > 4 else {},
            }
            lines.append(json.dumps(rec))
        p = tmp_path / name
        p.write_text("\n".join(lines))
        return p

    def test_configless_or_countless_rows_never_qualify(self, tmp_path):
        p = tmp_path / "crossover_tpu_r9.txt"
        p.write_text("\n".join([
            # no frontier_kw: the bench's standard loop / hand-made rows
            json.dumps({"scc": 28, "device": "TPU v5 lite",
                        "frontier_speedup_vs_cpp": 5.0, "verdict_ok": True,
                        "counts_ok": True}),
            # no counts_ok: enumeration completeness never measured
            json.dumps({"scc": 32, "device": "TPU v5 lite",
                        "frontier_speedup_vs_cpp": 5.0, "verdict_ok": True,
                        "frontier_kw": {}}),
        ]))
        assert calibrate(paths=[], crossover_paths=[p]).frontier_win_min_scc is None

    def test_win_region_from_artifact(self, tmp_path):
        p = self._txt(tmp_path, "crossover_tpu_r9.txt", [
            (24, 0.8, "TPU v5 lite", True),
            (28, 1.3, "TPU v5 lite", True),
            (32, 2.5, "TPU v5 lite", True),
        ])
        cal = calibrate(paths=[], crossover_paths=[p])
        assert cal.frontier_win_min_scc == 28
        assert cal.frontier_win_max_scc == 32  # largest MEASURED winning size
        assert cal.frontier_win_device == "tpu"
        assert "crossover_tpu_r9.txt" in cal.provenance["frontier"]

    def test_losing_or_unparitied_row_kills_region_above(self, tmp_path):
        p = self._txt(tmp_path, "crossover_tpu_r9.txt", [
            (24, 1.5, "TPU v5 lite", True),   # win below a later loss: ignored
            (28, 0.9, "TPU v5 lite", True),
            (32, 2.5, "TPU v5 lite", False),  # no verdict parity: counts as loss
            (36, 2.5, "TPU v5 lite", True),
        ])
        cal = calibrate(paths=[], crossover_paths=[p])
        assert cal.frontier_win_min_scc == 36

    def test_same_scc_win_and_loss_kills_region_there(self, tmp_path):
        # Two rows at the same scc IN THE SAME CONFIG: the minimum gates.
        p = self._txt(tmp_path, "crossover_tpu_r9.txt", [
            (28, 0.9, "TPU v5 lite", True),
            (28, 1.2, "TPU v5 lite", True),
            (32, 1.5, "TPU v5 lite", True),
        ])
        cal = calibrate(paths=[], crossover_paths=[p])
        assert cal.frontier_win_min_scc == 32

    def test_win_config_carried_and_grouped(self, tmp_path):
        # A loss under defaults must not kill a win region measured under a
        # different config — and the winning config rides into routing.
        p = self._txt(tmp_path, "crossover_tpu_r9.txt", [
            (28, 0.9, "TPU v5 lite", True, {}),
            (28, 1.3, "TPU v5 lite", True, {"pop": 4096}),
            (32, 2.0, "TPU v5 lite", True, {"pop": 4096}),
        ])
        cal = calibrate(paths=[], crossover_paths=[p])
        assert cal.frontier_win_min_scc == 28
        assert cal.frontier_config == {"pop": 4096}
        assert "pop" in cal.provenance["frontier"]

    def test_threshold_tie_prefers_faster_config(self, tmp_path):
        # r5 measured two configs winning from the same scc: defaults at
        # 1.16x and pop=2048 at 1.31x — routing must carry the faster one.
        p = self._txt(tmp_path, "crossover_tpu_r9.txt", [
            (32, 1.16, "TPU v5 lite", True, {"flag_check": "auto"}),
            (32, 1.31, "TPU v5 lite", True, {"pop": 2048}),
        ])
        cal = calibrate(paths=[], crossover_paths=[p])
        assert cal.frontier_win_min_scc == 32
        assert cal.frontier_config == {"pop": 2048}

    def test_cpu_rows_and_missing_artifacts_yield_none(self, tmp_path):
        p = self._txt(tmp_path, "crossover_tpu_r9.txt", [
            (28, 5.0, "cpu", True),  # emulation rows must not gate chip routing
        ])
        assert calibrate(paths=[], crossover_paths=[p]).frontier_win_min_scc is None
        assert calibrate(paths=[], crossover_paths=[]).frontier_win_min_scc is None

    def test_newest_round_artifact_wins(self, tmp_path):
        old = self._txt(tmp_path, "crossover_tpu_r4.txt",
                        [(24, 1.5, "TPU v5 lite", True)])
        new = self._txt(tmp_path, "crossover_tpu_r5.txt",
                        [(24, 0.5, "TPU v5 lite", True),
                         (30, 1.5, "TPU v5 lite", True)])
        cal = calibrate(paths=[], crossover_paths=[old, new])
        assert cal.frontier_win_min_scc == 30

    def test_auto_routes_into_measured_win_region(self, tmp_path, monkeypatch):
        from quorum_intersection_tpu.backends import auto
        from quorum_intersection_tpu.fbas.synth import majority_fbas
        from quorum_intersection_tpu.pipeline import solve
        from quorum_intersection_tpu.utils import platform as plat

        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_min_scc", 8)
        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_max_scc", 12)
        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_device", "tpu")
        monkeypatch.setattr(plat, "backend_kind", lambda: "tpu")
        res = solve(majority_fbas(9), backend=auto.AutoBackend(sweep_limit=4))
        assert res.intersects is True
        assert res.stats["backend"] == "tpu-frontier"

    def test_frontier_route_converts_sweep_checkpoint(self, tmp_path, monkeypatch):
        # The CLI hands auto a SweepCheckpoint; the frontier route must
        # convert it (same path, frontier format) instead of letting
        # resume_states AttributeError silently degrade to the host oracle
        # with no checkpointing (r5 review finding).
        from quorum_intersection_tpu.backends import auto
        from quorum_intersection_tpu.fbas.synth import majority_fbas
        from quorum_intersection_tpu.pipeline import solve
        from quorum_intersection_tpu.utils import platform as plat
        from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_min_scc", 8)
        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_max_scc", 12)
        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_device", "tpu")
        monkeypatch.setattr(plat, "backend_kind", lambda: "tpu")
        ck = SweepCheckpoint(tmp_path / "auto.ckpt")
        res = solve(
            majority_fbas(9),
            backend=auto.AutoBackend(sweep_limit=4, checkpoint=ck),
        )
        assert res.intersects is True
        assert res.stats["backend"] == "tpu-frontier"

    def test_auto_caps_extrapolation_above_measured_max(self, monkeypatch):
        # |scc|=9 with a win measured only at scc 4: 9 > 4 + headroom(4),
        # so routing must NOT extrapolate the region (ADVICE r4 medium).
        from quorum_intersection_tpu.backends import auto
        from quorum_intersection_tpu.fbas.synth import majority_fbas
        from quorum_intersection_tpu.pipeline import solve
        from quorum_intersection_tpu.utils import platform as plat

        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_min_scc", 4)
        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_max_scc", 4)
        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_device", "tpu")
        monkeypatch.setattr(plat, "backend_kind", lambda: "tpu")
        res = solve(majority_fbas(9), backend=auto.AutoBackend(sweep_limit=4))
        assert res.stats["backend"] in ("python", "cpp")

    def test_auto_requires_matching_device_kind(self, monkeypatch):
        # A TPU-measured win must not route a different accelerator kind.
        from quorum_intersection_tpu.backends import auto
        from quorum_intersection_tpu.fbas.synth import majority_fbas
        from quorum_intersection_tpu.pipeline import solve
        from quorum_intersection_tpu.utils import platform as plat

        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_min_scc", 8)
        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_max_scc", 12)
        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_device", "tpu")
        monkeypatch.setattr(plat, "backend_kind", lambda: "gpu")
        res = solve(majority_fbas(9), backend=auto.AutoBackend(sweep_limit=4))
        assert res.stats["backend"] in ("python", "cpp")

    def test_auto_stays_on_host_without_artifact(self, monkeypatch):
        from quorum_intersection_tpu.backends import auto
        from quorum_intersection_tpu.fbas.synth import majority_fbas
        from quorum_intersection_tpu.pipeline import solve
        from quorum_intersection_tpu.utils import platform as plat

        monkeypatch.setattr(auto.CALIBRATION, "frontier_win_min_scc", None)
        monkeypatch.setattr(plat, "is_cpu_platform", lambda: False)
        res = solve(majority_fbas(9), backend=auto.AutoBackend(sweep_limit=4))
        assert res.stats["backend"] in ("python", "cpp")


class TestSweepWindow:
    """Measured sweep-vs-native routing window: auto's accelerator sweep
    limit rises above the static default ONLY when an artifact records the
    exhaustive sweep beating COMPLETED native-oracle runs on the chip."""

    def _txt(self, tmp_path, name, rows):
        lines = ["| header |"]
        for scc, speed, dev, ok, completed in rows:
            lines.append(json.dumps({
                "scc": scc, "device": dev, "sweep_speedup_vs_native": speed,
                "verdict_ok": ok, "native_completed": completed,
            }))
        p = tmp_path / name
        p.write_text("\n".join(lines))
        return p

    def test_window_from_artifact(self, tmp_path):
        p = self._txt(tmp_path, "sweep_vs_native_tpu_r5.txt", [
            (28, 5.8, "TPU v5 lite", True, True),
            (32, 9.1, "TPU v5 lite", True, True),
            (36, 6.0, "TPU v5 lite", True, True),
        ])
        cal = calibrate(paths=[], sweep_window_paths=[p])
        assert cal.sweep_win_max_scc == 36
        assert cal.sweep_win_cap_scc is None
        assert cal.sweep_win_device == "tpu"
        assert "sweep_vs_native_tpu_r5.txt" in cal.provenance["sweep_window"]

    def test_incomplete_native_or_cpu_rows_never_qualify(self, tmp_path):
        p = self._txt(tmp_path, "sweep_vs_native_tpu_r5.txt", [
            (36, 9.0, "TPU v5 lite", True, False),  # estimated total: a floor
            (32, 9.0, "cpu", True, True),           # emulation row
            (28, 9.0, "TPU v5 lite", False, True),  # no verdict parity
        ])
        assert calibrate(
            paths=[], sweep_window_paths=[p]
        ).sweep_win_max_scc is None
        assert calibrate(
            paths=[], sweep_window_paths=[]
        ).sweep_win_max_scc is None

    def test_loss_above_window_caps_extrapolation(self, tmp_path):
        p = self._txt(tmp_path, "sweep_vs_native_tpu_r5.txt", [
            (32, 2.0, "TPU v5 lite", True, True),
            (36, 0.8, "TPU v5 lite", True, True),
        ])
        cal = calibrate(paths=[], sweep_window_paths=[p])
        assert cal.sweep_win_max_scc == 32
        assert cal.sweep_win_cap_scc == 35  # headroom may not reach the loss

    def test_loss_disqualifies_wins_above_it(self, tmp_path):
        # A "win" beyond a measured loss (noise; the trend is monotone) must
        # not leapfrog the loss: the limit routes EVERY size up to it.
        p = self._txt(tmp_path, "sweep_vs_native_tpu_r5.txt", [
            (36, 0.8, "TPU v5 lite", True, True),
            (40, 1.2, "TPU v5 lite", True, True),
        ])
        assert calibrate(
            paths=[], sweep_window_paths=[p]
        ).sweep_win_max_scc is None

    def test_platform_limit_raised_only_with_matching_device(self, monkeypatch):
        from quorum_intersection_tpu.backends import auto
        from quorum_intersection_tpu.utils import platform as plat

        monkeypatch.setattr(plat, "is_cpu_platform", lambda: False)
        monkeypatch.setattr(plat, "backend_kind", lambda: "tpu")
        monkeypatch.setattr(auto.CALIBRATION, "sweep_win_max_scc", 36)
        monkeypatch.setattr(auto.CALIBRATION, "sweep_win_cap_scc", None)
        monkeypatch.setattr(auto.CALIBRATION, "sweep_win_device", "tpu")
        assert auto._platform_sweep_limit() == 40  # 36 + headroom 4

        monkeypatch.setattr(plat, "backend_kind", lambda: "gpu")
        assert auto._platform_sweep_limit() == auto.SWEEP_LIMIT_TPU

        # The raise respects a measured-loss cap and the decode ceiling.
        monkeypatch.setattr(plat, "backend_kind", lambda: "tpu")
        monkeypatch.setattr(auto.CALIBRATION, "sweep_win_cap_scc", 37)
        assert auto._platform_sweep_limit() == 37
        monkeypatch.setattr(auto.CALIBRATION, "sweep_win_cap_scc", None)
        monkeypatch.setattr(auto.CALIBRATION, "sweep_win_max_scc", 44)
        assert auto._platform_sweep_limit() == auto.SWEEP_DECODE_CEILING

        # CPU platform: the window never applies.
        monkeypatch.setattr(plat, "is_cpu_platform", lambda: True)
        assert auto._platform_sweep_limit() == auto.SWEEP_LIMIT_CPU

    def test_window_never_lowers_the_static_limit(self, monkeypatch):
        from quorum_intersection_tpu.backends import auto
        from quorum_intersection_tpu.utils import platform as plat

        monkeypatch.setattr(plat, "is_cpu_platform", lambda: False)
        monkeypatch.setattr(plat, "backend_kind", lambda: "tpu")
        monkeypatch.setattr(auto.CALIBRATION, "sweep_win_max_scc", 20)
        monkeypatch.setattr(auto.CALIBRATION, "sweep_win_cap_scc", None)
        monkeypatch.setattr(auto.CALIBRATION, "sweep_win_device", "tpu")
        assert auto._platform_sweep_limit() == auto.SWEEP_LIMIT_TPU

    def test_estimate_only_row_does_not_cap_a_completed_win(self, tmp_path):
        # r5 shape: the first run's scc-36 row was estimate-only (native
        # hit the cap); a later completed-native run APPENDED to the same
        # round artifact must be able to extend the window — absence of a
        # measured ratio is not a loss.
        p = self._txt(tmp_path, "sweep_vs_native_tpu_r5.txt", [
            (32, 24.7, "TPU v5 lite", True, True),
            (36, 10.7, "TPU v5 lite", True, False),   # estimate-only: skip
        ])
        cal = calibrate(paths=[], sweep_window_paths=[p])
        assert cal.sweep_win_max_scc == 32
        assert cal.sweep_win_cap_scc is None  # NOT capped at 35
        with p.open("a") as f:
            f.write("\n" + json.dumps({
                "scc": 36, "device": "TPU v5 lite",
                "sweep_speedup_vs_native": 9.3,
                "verdict_ok": True, "native_completed": True,
            }))
        cal = calibrate(paths=[], sweep_window_paths=[p])
        assert cal.sweep_win_max_scc == 36

    def test_loss_at_or_below_static_floor_is_exempt(self, tmp_path):
        # Small-scc rows lose to compile overhead by construction; sizes at
        # or below the static limit route to the sweep regardless of this
        # window, so such losses must not veto the raise.
        p = self._txt(tmp_path, "sweep_vs_native_tpu_r5.txt", [
            (24, 0.1, "TPU v5 lite", True, True),   # compile-bound loss
            (28, 4.8, "TPU v5 lite", True, True),
            (32, 24.7, "TPU v5 lite", True, True),
        ])
        cal = calibrate(paths=[], sweep_window_paths=[p])
        assert cal.sweep_win_max_scc == 32
        assert cal.sweep_win_cap_scc is None


class TestVerdictVeto:
    """ADVICE r5 #2 regression: a verdict_ok=false row is CORRECTNESS
    evidence and must disqualify the sweep-window raise at EVERY |scc| —
    before the fix it was coerced to v=0.0 and, at sizes at or below the
    static floor, slipped under the floor-loss exemption."""

    def _txt(self, tmp_path, name, rows):
        lines = ["| header |"]
        for scc, speed, dev, ok, completed in rows:
            lines.append(json.dumps({
                "scc": scc, "device": dev, "sweep_speedup_vs_native": speed,
                "verdict_ok": ok, "native_completed": completed,
            }))
        p = tmp_path / name
        p.write_text("\n".join(lines))
        return p

    def test_mismatch_below_floor_vetoes_whole_window(self, tmp_path):
        # The exact hole: scc 24 <= SWEEP_WINDOW_FLOOR(35) used to be
        # exempt as a "loss"; as a verdict mismatch it must veto the raise.
        p = self._txt(tmp_path, "sweep_vs_native_tpu_r9.txt", [
            (24, 9.0, "TPU v5 lite", False, True),
            (28, 4.8, "TPU v5 lite", True, True),
            (32, 24.7, "TPU v5 lite", True, True),
        ])
        assert calibrate(
            paths=[], sweep_window_paths=[p]
        ).sweep_win_max_scc is None

    def test_mismatch_above_floor_still_vetoes(self, tmp_path):
        p = self._txt(tmp_path, "sweep_vs_native_tpu_r9.txt", [
            (36, 2.0, "TPU v5 lite", False, True),
            (40, 9.0, "TPU v5 lite", True, True),
        ])
        assert calibrate(
            paths=[], sweep_window_paths=[p]
        ).sweep_win_max_scc is None

    def test_veto_logged_as_correctness(self, tmp_path):
        # The package logger sets propagate=False, so capture with a
        # handler attached directly instead of caplog.
        import logging

        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        p = self._txt(tmp_path, "sweep_vs_native_tpu_r9.txt", [
            (24, 9.0, "TPU v5 lite", False, True),
            (32, 24.7, "TPU v5 lite", True, True),
        ])
        logger = logging.getLogger("quorum_intersection_tpu.backends.calibration")
        handler = _Capture(level=logging.WARNING)
        logger.addHandler(handler)
        try:
            calibrate(paths=[], sweep_window_paths=[p])
        finally:
            logger.removeHandler(handler)
        assert any("vetoed" in m and "verdict_ok=false" in m for m in records)

    def test_perf_loss_below_floor_is_still_exempt(self, tmp_path):
        # The exemption the veto must NOT swallow: a genuine performance
        # loss (verdict parity held) at or below the floor keeps the raise.
        p = self._txt(tmp_path, "sweep_vs_native_tpu_r9.txt", [
            (24, 0.1, "TPU v5 lite", True, True),
            (32, 24.7, "TPU v5 lite", True, True),
        ])
        cal = calibrate(paths=[], sweep_window_paths=[p])
        assert cal.sweep_win_max_scc == 32


class TestWarmStartRatio:
    """Warm/cold compile ratio (benchmarks/auto_race.py artifacts) feeding
    auto's budget estimate once the persistent compile cache is known-hot."""

    def _txt(self, tmp_path, name, rows):
        lines = []
        for dev, cold, warm in rows:
            lines.append(json.dumps({
                "mode": "real", "device": dev,
                "sweep_cold_xla_compile_s": cold,
                "sweep_warm_xla_compile_s": warm,
            }))
        p = tmp_path / name
        p.write_text("\n".join(lines))
        return p

    def test_ratio_from_artifact_worst_row_gates(self, tmp_path):
        p = self._txt(tmp_path, "auto_race_tpu_r9.txt", [
            ("TPU v5 lite", 20.0, 0.5),   # 0.025
            ("TPU v5 lite", 10.0, 1.0),   # 0.1 — worst row wins
        ])
        cal = calibrate(paths=[], auto_race_paths=[p])
        assert cal.sweep_warm_ratio == 0.1
        assert "auto_race_tpu_r9.txt" in cal.provenance["warm_start"]

    def test_tiny_cold_cpu_and_rotten_rows_ignored(self, tmp_path):
        p = self._txt(tmp_path, "auto_race_tpu_r9.txt", [
            ("TPU v5 lite", 0.05, 0.0),   # cold too small to measure
            ("cpu", 20.0, 0.1),           # emulation row
        ])
        cal = calibrate(paths=[], auto_race_paths=[p])
        assert cal.sweep_warm_ratio is None
        # warm > cold clamps to 1.0 (artifact rot, not physics)
        p2 = self._txt(tmp_path, "auto_race_tpu_r10.txt", [
            ("TPU v5 lite", 2.0, 5.0),
        ])
        cal = calibrate(paths=[], auto_race_paths=[p2])
        assert cal.sweep_warm_ratio == 1.0

    def test_hermetic_and_default(self, tmp_path):
        assert calibrate(paths=[]).sweep_warm_ratio is None

    def test_warm_ratio_shrinks_auto_budget(self, monkeypatch):
        from quorum_intersection_tpu.backends import auto

        backend = auto.AutoBackend()
        monkeypatch.setattr(auto.CALIBRATION, "sweep_warm_ratio", None)
        cold_budget = backend._estimated_sweep_seconds(34)
        monkeypatch.setattr(auto.CALIBRATION, "sweep_warm_ratio", 0.05)
        warm_budget = backend._estimated_sweep_seconds(34)
        assert warm_budget < cold_budget  # routing prefers the chip sooner
