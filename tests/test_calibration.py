"""Auto-routing cost-model calibration (backends/calibration.py): derived
values must be traceable to a named artifact, clamped against artifact rot,
and fall back to the r3 constants when nothing applies."""

import json

from quorum_intersection_tpu.backends.calibration import (
    DEFAULT_ORACLE_SPC,
    DEFAULT_SWEEP_RATE,
    calibrate,
)


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return p


def test_no_artifacts_yields_defaults():
    cal = calibrate(paths=[])
    assert cal.sweep_rate == DEFAULT_SWEEP_RATE
    assert cal.oracle_seconds_per_call == DEFAULT_ORACLE_SPC
    assert all(v == "default" for v in cal.provenance.values())


def test_derives_from_tpu_record_with_provenance(tmp_path):
    p = _write(tmp_path, "BENCH_r09.json", {
        "device": "TPU v5 lite",
        "wide_sweep_device_cand_per_sec": 8e8,
        "verdict_256": {"native_engine": "cpp", "native_rate": 2e6},
    })
    cal = calibrate(paths=[p])
    assert cal.sweep_rate["accel"] == 4e8  # halved for tunnel variance
    assert cal.oracle_seconds_per_call["cpp"] == 1 / 2e6
    assert "BENCH_r09.json" in cal.provenance["accel"]
    assert "native_rate" in cal.provenance["cpp"]
    # No CPU record: cpu rate stays at the default.
    assert cal.sweep_rate["cpu"] == DEFAULT_SWEEP_RATE["cpu"]


def test_cpu_record_and_newest_round_wins(tmp_path):
    a = _write(tmp_path, "BENCH_r05.json", {
        "device": "cpu-fallback", "sweep_steady_rate": 2e6,
    })
    b = _write(tmp_path, "BENCH_r06.json", {
        "device": "TPU v5 lite", "wide_sweep_device_cand_per_sec": 6e8,
    })
    c = _write(tmp_path, "unnumbered.json", {
        "device": "TPU v5 lite", "wide_sweep_device_cand_per_sec": 4e8,
    })
    cal = calibrate(paths=[a, b, c])
    assert cal.sweep_rate["cpu"] == 2e6 / 4
    assert cal.sweep_rate["accel"] == 3e8  # r06 outranks the unnumbered file
    assert "BENCH_r06.json" in cal.provenance["accel"]

    # A NEWER round that measured slower must lower the estimate — the
    # model tracks the hardware last measured, not the fastest ever seen.
    d = _write(tmp_path, "BENCH_r07.json", {
        "device": "TPU v5 lite", "wide_sweep_device_cand_per_sec": 1.2e8,
    })
    cal = calibrate(paths=[a, b, c, d])
    assert cal.sweep_rate["accel"] == 0.6e8
    assert "BENCH_r07.json" in cal.provenance["accel"]

    # A file whose name embeds a big number OUTSIDE the r<N> round
    # convention must not outrank real rounds.
    e = _write(tmp_path, "verdict_1024.json", {
        "device": "TPU v5 lite", "wide_sweep_device_cand_per_sec": 9e8,
    })
    cal = calibrate(paths=[a, b, c, d, e])
    assert "BENCH_r07.json" in cal.provenance["accel"]


def test_out_of_window_and_corrupt_artifacts_ignored(tmp_path):
    bad_rate = _write(tmp_path, "BENCH_r07.json", {
        "device": "TPU v5 lite",
        "wide_sweep_device_cand_per_sec": 1e15,  # unit bug: above window
        "verdict_256": {"native_engine": "python", "native_rate": 4e4},
    })
    corrupt = tmp_path / "BENCH_r08.json"
    corrupt.write_text("{not json")
    engineless = _write(tmp_path, "BENCH_r09.json", {
        "device": "cpu-fallback",
        "verdict_1024": {"native_rate": 5e4},  # no native_engine label
    })
    cal = calibrate(paths=[bad_rate, corrupt, engineless])
    assert cal.sweep_rate["accel"] == DEFAULT_SWEEP_RATE["accel"]
    # python-engine AND unlabeled native_rate must not calibrate the cpp
    # oracle (either would shrink its budget ~50x in the unsafe direction).
    assert cal.oracle_seconds_per_call["cpp"] == DEFAULT_ORACLE_SPC["cpp"]


def test_driver_wrapper_tail_shape(tmp_path):
    # The driver's BENCH_r*.json wraps the headline in a "tail" text blob
    # whose last parseable line is the record.
    p = _write(tmp_path, "BENCH_r04.json", {
        "rc": 0,
        "tail": "noise\n" + json.dumps({
            "device": "TPU v5 lite", "sweep_device_cand_per_sec": 3.2e8,
        }),
    })
    cal = calibrate(paths=[p])
    assert cal.sweep_rate["accel"] == 1.6e8
    assert "sweep_device_cand_per_sec" in cal.provenance["accel"]


def test_repo_artifacts_actually_calibrate():
    # This repo carries the r3 on-chip record: the import-time calibration
    # must be traceable to SOME named artifact, not all-defaults.
    from quorum_intersection_tpu.backends import auto
    from quorum_intersection_tpu.backends.calibration import CALIBRATION

    assert CALIBRATION.provenance["accel"] != "default"
    assert ".json" in CALIBRATION.provenance["accel"]
    # auto.py consumes the calibrated dicts (identity, not a copy).
    assert auto.SWEEP_RATE is CALIBRATION.sweep_rate
    assert auto.ORACLE_SECONDS_PER_CALL is CALIBRATION.oracle_seconds_per_call
