"""Top-tier (union of minimal quorums) analytics tests."""

import json
import math
import subprocess
import sys

import pytest

from quorum_intersection_tpu.analytics.top_tier import _python_top_tier, top_tier
from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.semantics import max_quorum
from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas


def _quorum_scc(data):
    graph = build_graph(parse_fbas(data))
    count, comp = tarjan_scc(graph.n, graph.succ)
    for members in group_sccs(graph.n, comp, count):
        avail = [v in set(members) for v in range(graph.n)]
        if max_quorum(graph, members, avail):
            return graph, members
    raise AssertionError("no quorum-bearing SCC")


def test_majority_top_tier_is_everyone():
    # k-of-n symmetric majority: every node is in some minimal quorum
    # (any k-subset is one), and there are C(n, k) of them.
    for n in (3, 5, 7):
        graph, scc = _quorum_scc(majority_fbas(n))
        members, n_min = top_tier(graph, scc)
        assert members == sorted(scc)
        assert n_min == math.comb(n, n // 2 + 1)


def test_hierarchical_top_tier():
    # 5 orgs x 3: minimal quorums are 3-org coalitions x 2-of-3 picks:
    # C(5,3) * 3^3 = 270; union = all 15 validators.
    graph, scc = _quorum_scc(hierarchical_fbas(5, 3))
    members, n_min = top_tier(graph, scc)
    assert members == sorted(scc)
    assert n_min == math.comb(5, 3) * 27


def test_python_and_native_agree():
    graph, scc = _quorum_scc(hierarchical_fbas(4, 3))
    native = top_tier(graph, scc)
    python = _python_top_tier(graph, scc, budget_calls=0)
    assert native == python


def test_budget_exceeded_reports_none():
    graph, scc = _quorum_scc(majority_fbas(9))
    members, _ = top_tier(graph, scc, budget_calls=5)
    assert members is None
    members, _ = _python_top_tier(graph, scc, budget_calls=5)
    assert members is None


def test_cli_top_tier_piggybackers_excluded(ref_fixture):
    # correct.json: the sink SCC is {SDF1, SDF2, SDF3, Eno} but Eno sits in
    # no minimal quorum — the top tier is exactly the three SDF validators.
    proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "--top-tier"],
        input=ref_fixture("correct.json").read_text(),
        capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0
    assert proc.stdout.startswith("top tier (3 nodes, 3 minimal quorums):")
    assert "Eno" not in proc.stdout


def test_cli_top_tier_no_quorum():
    data = json.dumps(
        [{"publicKey": f"N{i}", "name": "", "quorumSet": None} for i in range(3)]
    )
    proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "--top-tier"],
        input=data, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    assert "empty (no quorum exists)" in proc.stdout
