"""Hostile-input hardening: deep nesting, junk bytes, and truncation must
produce the clean ``invalid FBAS configuration`` diagnostic (exit 1) in both
CLIs — never a traceback, RecursionError, or native stack overflow.  The
reference crashes on all of these (`/root/reference/quorum_intersection.cpp:
402-418` recurses uncapped; its sanitizer tracebacks on malformed stdin).
"""

import json
import subprocess
import sys

import pytest

from quorum_intersection_tpu.fbas.schema import (
    MAX_QSET_DEPTH,
    FbasSchemaError,
    parse_fbas,
)


def nested_qset_node(depth: int) -> str:
    """One node whose quorumSet nests ``depth`` innerQuorumSets levels."""
    qset = '{"threshold": 1, "validators": ["A"]}'
    for _ in range(depth):
        qset = '{"threshold": 1, "validators": ["A"], "innerQuorumSets": [' + qset + "]}"
    return '[{"publicKey": "A", "quorumSet": ' + qset + "}]"


def run_cli(stdin_data: str, *args: str):
    return subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", *args],
        input=stdin_data, capture_output=True, text=True, timeout=120,
    )


def run_sanitizer(stdin_data: str, *args: str):
    return subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu.fbas.sanitize", *args],
        input=stdin_data, capture_output=True, text=True, timeout=120,
    )


def assert_clean_rejection(proc) -> None:
    assert proc.returncode == 1
    assert "invalid FBAS configuration" in proc.stderr
    assert "Traceback" not in proc.stderr
    assert "RecursionError" not in proc.stderr


class TestLibraryDepthCap:
    def test_within_cap_parses(self):
        fbas = parse_fbas(nested_qset_node(MAX_QSET_DEPTH - 1))
        assert len(fbas) == 1
        assert fbas[0].qset.max_depth() == MAX_QSET_DEPTH - 1

    def test_beyond_cap_rejected(self):
        with pytest.raises(FbasSchemaError, match="nesting exceeds depth"):
            parse_fbas(nested_qset_node(MAX_QSET_DEPTH + 10))

    def test_deep_json_array_clean_error(self):
        deep = "[" * 4000 + "]" * 4000
        with pytest.raises(FbasSchemaError):
            parse_fbas(deep)

    def test_encode_guard_on_programmatic_graph(self):
        from quorum_intersection_tpu.encode.circuit import encode_circuit
        from quorum_intersection_tpu.fbas.graph import IndexedQSet, TrustGraph

        q = IndexedQSet(threshold=1, members=(0,))
        for _ in range(MAX_QSET_DEPTH + 10):
            q = IndexedQSet(threshold=1, members=(0,), inner=(q,))
        graph = TrustGraph(n=1, succ=[[0]], qsets=[q], node_ids=["A"], names=[""])
        with pytest.raises(ValueError, match="nesting exceeds depth"):
            encode_circuit(graph)


class TestPythonCliHostileInput:
    def test_deep_qset_nesting(self):
        assert_clean_rejection(run_cli(nested_qset_node(400)))

    def test_deep_json_arrays(self):
        assert_clean_rejection(run_cli("[" * 6000 + "]" * 6000))

    def test_junk_unicode(self):
        assert_clean_rejection(run_cli("你好퟿ \x00\x01 {]["))

    @pytest.mark.parametrize("frac", [0.1, 0.5, 0.9])
    def test_truncated_fixture(self, ref_fixture, frac):
        data = ref_fixture("correct.json").read_text()
        cut = data[: int(len(data) * frac)]
        assert_clean_rejection(run_cli(cut))


class TestSanitizerHostileInput:
    def test_malformed_json(self):
        proc = run_sanitizer("not json at all")
        assert_clean_rejection(proc)

    def test_deep_json(self):
        proc = run_sanitizer("[" * 6000 + "]" * 6000)
        assert_clean_rejection(proc)

    def test_non_array_top_level(self):
        proc = run_sanitizer('{"publicKey": "A"}')
        assert_clean_rejection(proc)

    def test_still_filters_valid_input(self):
        data = [
            {"publicKey": "A", "quorumSet": {"threshold": 99, "validators": ["A"]}},
            {"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["B"]}},
        ]
        proc = run_sanitizer(json.dumps(data))
        assert proc.returncode == 0
        assert [n["publicKey"] for n in json.loads(proc.stdout)] == ["B"]


class TestNativeCliHostileInput:
    @pytest.fixture(scope="class")
    def native(self):
        from quorum_intersection_tpu.backends.cpp import build_native_cli

        try:
            return str(build_native_cli())
        except Exception as exc:  # pragma: no cover - g++ missing
            pytest.skip(f"native CLI unavailable: {exc}")

    def run_native(self, native, stdin_data: str):
        return subprocess.run(
            [native], input=stdin_data, capture_output=True, text=True, timeout=120
        )

    def test_deep_qset_nesting_matches_python(self, native):
        payload = nested_qset_node(400)
        n = self.run_native(native, payload)
        p = run_cli(payload)
        assert n.returncode == p.returncode == 1
        assert "invalid FBAS configuration" in n.stderr

    def test_deep_json_arrays(self, native):
        n = self.run_native(native, "[" * 6000 + "]" * 6000)
        assert n.returncode == 1
        assert "invalid FBAS configuration" in n.stderr

    def test_deep_json_objects(self, native):
        deep = '{"a":' * 6000 + "1" + "}" * 6000
        n = self.run_native(native, deep)
        assert n.returncode == 1
        assert "invalid FBAS configuration" in n.stderr

    def test_junk_unicode(self, native):
        n = self.run_native(native, "你好 \x01 {][")
        assert n.returncode == 1
        assert "invalid FBAS configuration" in n.stderr

    def test_within_cap_depth_agrees_with_python(self, native):
        payload = nested_qset_node(MAX_QSET_DEPTH - 1)
        n = self.run_native(native, payload)
        p = run_cli(payload)
        assert (n.stdout, n.returncode) == (p.stdout, p.returncode)
