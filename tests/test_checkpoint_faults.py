"""Crash-only checkpointing (ISSUE 4): the corruption matrix — truncated
JSON, empty file, non-object JSON, foreign fingerprint, leftover ``.tmp``,
unwritable directory, injected disk-full — plus the durability ordering
(fsync before rename) and the contract that a checkpoint never kills the
run it exists to rescue."""

import json
import os

import pytest

from quorum_intersection_tpu.fbas.synth import majority_fbas
from quorum_intersection_tpu.pipeline import solve
from quorum_intersection_tpu.utils import faults, telemetry
from quorum_intersection_tpu.utils.checkpoint import (
    FrontierCheckpoint,
    SweepCheckpoint,
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    faults.clear_plan()
    rec = telemetry.reset_run_record()
    yield rec
    faults.clear_plan()
    telemetry.reset_run_record()


@pytest.fixture
def rec(_clean):
    return _clean


class TestCorruptionMatrix:
    def test_truncated_json_is_quarantined(self, tmp_path, rec):
        p = tmp_path / "c.ckpt"
        p.write_text('{"position": 12, "tot')
        assert SweepCheckpoint(p).resume_position(100) == 0
        assert not p.exists()
        corpse = tmp_path / "c.ckpt.corrupt"
        assert corpse.exists()
        assert rec.counters.get("checkpoint.corrupt_quarantined") == 1
        ev = [e for e in rec.events
              if e["name"] == "checkpoint.corrupt_quarantined"]
        assert ev and "unparseable JSON" in ev[0]["attrs"]["why"]

    def test_empty_file_is_quarantined(self, tmp_path, rec):
        p = tmp_path / "c.ckpt"
        p.write_text("")
        assert SweepCheckpoint(p).has_progress(100) is False
        assert not p.exists() and (tmp_path / "c.ckpt.corrupt").exists()

    def test_undecodable_bytes_are_quarantined(self, tmp_path, rec):
        # A torn write can leave arbitrary bytes — the most realistic
        # corruption shape must quarantine, not raise UnicodeDecodeError.
        p = tmp_path / "c.ckpt"
        p.write_bytes(b"\xff\xfe\x00garbage from a torn write")
        assert SweepCheckpoint(p).resume_position(100) == 0
        assert not p.exists() and (tmp_path / "c.ckpt.corrupt").exists()
        ev = [e for e in rec.events
              if e["name"] == "checkpoint.corrupt_quarantined"]
        assert ev and "undecodable bytes" in ev[0]["attrs"]["why"]

    def test_non_object_json_is_quarantined(self, tmp_path, rec):
        p = tmp_path / "c.ckpt"
        p.write_text("[1, 2, 3]")
        assert SweepCheckpoint(p).resume_position(100) == 0
        assert (tmp_path / "c.ckpt.corrupt").exists()

    def test_quarantined_file_is_never_retried(self, tmp_path, rec):
        p = tmp_path / "c.ckpt"
        p.write_text("{broken")
        ck = SweepCheckpoint(p)
        assert ck.resume_position(100) == 0
        assert ck.resume_position(100) == 0  # second probe: file is gone
        assert rec.counters.get("checkpoint.corrupt_quarantined") == 1

    def test_foreign_fingerprint_ignored_not_quarantined(self, tmp_path, rec):
        p = tmp_path / "c.ckpt"
        p.write_text(json.dumps(
            {"position": 64, "total": 100, "fingerprint": "deadbeef"}
        ))
        assert SweepCheckpoint(p).resume_position(100, fingerprint="cafe") == 0
        assert p.exists(), "a VALID foreign checkpoint is evidence, not corruption"
        assert rec.counters.get("checkpoint.corrupt_quarantined", 0) == 0

    def test_frontier_corrupt_is_quarantined(self, tmp_path, rec):
        p = tmp_path / "f.ckpt"
        p.write_text('{"fingerprint": "x", "states": [[')
        assert FrontierCheckpoint(p).resume_states("x") is None
        assert (tmp_path / "f.ckpt.corrupt").exists()

    def test_leftover_tmp_is_harmless_and_replaced(self, tmp_path, rec):
        p = tmp_path / "c.ckpt"
        stale = p.with_suffix(".tmp")
        stale.write_text("half-written garbage from a crashed run")
        ck = SweepCheckpoint(p)
        ck.record(32, 100, fingerprint="fp")
        assert not stale.exists(), "the stale tmp must be overwritten away"
        assert ck.resume_position(100, fingerprint="fp") == 32

    def test_newest_corpse_wins_the_quarantine_slot(self, tmp_path, rec):
        p = tmp_path / "c.ckpt"
        p.write_text("{first corpse")
        SweepCheckpoint(p).resume_position(100)
        p.write_text("{second corpse")
        SweepCheckpoint(p).resume_position(100)
        assert (tmp_path / "c.ckpt.corrupt").read_text() == "{second corpse"


class TestSaveErrors:
    def test_unwritable_directory_counts_instead_of_raising(self, tmp_path, rec):
        blocker = tmp_path / "dir"
        blocker.write_text("")  # a FILE where the parent dir should be
        ck = SweepCheckpoint(blocker / "c.ckpt")
        ck.record(5, 10)  # must not raise
        assert rec.counters.get("checkpoint.save_errors") == 1
        assert rec.counters.get("checkpoint.saves", 0) == 0

    def test_injected_disk_full_counts_and_cleans_tmp(self, tmp_path, rec):
        faults.install_plan(faults.parse_faults("checkpoint.write=oserror@1+"))
        p = tmp_path / "c.ckpt"
        SweepCheckpoint(p).record(5, 10)
        assert rec.counters.get("checkpoint.save_errors") == 1
        assert not p.exists() and not p.with_suffix(".tmp").exists()
        ev = [e for e in rec.events if e["name"] == "checkpoint.save_error"]
        assert ev and "injected disk full" in ev[0]["attrs"]["error"]

    def test_frontier_record_is_error_safe(self, tmp_path, rec):
        faults.install_plan(faults.parse_faults("checkpoint.write=oserror@1+"))
        FrontierCheckpoint(tmp_path / "f.ckpt").record(
            [[[1], [2]]], fingerprint="fp"
        )
        assert rec.counters.get("checkpoint.save_errors") == 1

    def test_fsync_before_rename(self, tmp_path, monkeypatch):
        order = []
        real_fsync, real_replace = os.fsync, os.replace
        monkeypatch.setattr(
            os, "fsync",
            lambda fd: (order.append("fsync"), real_fsync(fd))[1],
        )
        monkeypatch.setattr(
            os, "replace",
            lambda a, b: (order.append("replace"), real_replace(a, b))[1],
        )
        SweepCheckpoint(tmp_path / "c.ckpt").record(5, 10)
        # Data fsync strictly before the publishing rename; the (best-
        # effort) directory fsync follows it.
        assert order[:2] == ["fsync", "replace"]

    def test_partial_write_counter_zero_on_happy_path(self, tmp_path, rec):
        SweepCheckpoint(tmp_path / "c.ckpt").record(5, 10)
        assert rec.counters.get("checkpoint.save_errors", 0) == 0
        assert rec.counters.get("checkpoint.saves") == 1


class TestRunSurvival:
    """The first fault the harness exercises end-to-end: a sweep whose
    every checkpoint write hits a full disk must still deliver the exact
    verdict — a checkpoint must never kill the run it exists to rescue."""

    def test_sweep_survives_disk_full_checkpointing(self, tmp_path, rec):
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend

        faults.install_plan(faults.parse_faults("checkpoint.write=oserror@1+"))
        ck = SweepCheckpoint(tmp_path / "c.ckpt")
        res = solve(
            majority_fbas(9),
            backend=TpuSweepBackend(checkpoint=ck, batch=32),
        )
        assert res.intersects is True
        assert rec.counters.get("checkpoint.save_errors", 0) >= 1
        assert not (tmp_path / "c.ckpt").exists()

    def test_sweep_verdict_identical_with_and_without_faults(self, tmp_path, rec):
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend

        data = majority_fbas(9, broken=True)
        clean = solve(data, backend=TpuSweepBackend(batch=32))
        faults.install_plan(faults.parse_faults("checkpoint.write=oserror@1+"))
        ck = SweepCheckpoint(tmp_path / "c.ckpt")
        faulted = solve(data, backend=TpuSweepBackend(checkpoint=ck, batch=32))
        assert faulted.intersects is clean.intersects is False
        assert faulted.q1 == clean.q1 and faulted.q2 == clean.q2
