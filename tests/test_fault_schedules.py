"""Deterministic fault schedules (ISSUE 4 tentpole): the registry contract
(undeclared points raise), the QI_FAULTS grammar, hit selection, and the
determinism guarantee — same seed ⇒ same plan ⇒ same firing sequence —
that makes a chaos failure exactly reproducible (the faults twin of
tests/test_race_schedules.py's forced interleavings)."""

import pytest

from quorum_intersection_tpu.utils import faults, telemetry


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.clear_plan()
    yield
    faults.clear_plan()


class TestRegistry:
    def test_undeclared_point_raises_even_without_a_plan(self):
        with pytest.raises(KeyError, match="not a declared fault point"):
            faults.fault_point("no.such.point")

    def test_undeclared_point_in_a_rule_raises(self):
        with pytest.raises(KeyError, match="not a declared fault point"):
            faults.FaultRule(point="no.such.point", mode="error")

    def test_unknown_mode_raises(self):
        with pytest.raises(ValueError, match="unknown fault mode"):
            faults.FaultRule(point="native.call", mode="explode")

    def test_catalog_is_documented(self):
        reg = faults.registry()
        assert reg, "empty fault-point catalog"
        for name, description in reg.items():
            assert "." in name
            assert len(description) > 20, f"{name} lacks a real description"

    def test_declared_point_without_plan_is_a_noop(self):
        for name in faults.registry():
            faults.fault_point(name)  # must not raise


class TestFiring:
    def test_fire_on_exactly_the_third_hit(self):
        plan = faults.install_plan(
            faults.parse_faults("checkpoint.write=oserror@3")
        )
        faults.fault_point("checkpoint.write")
        faults.fault_point("checkpoint.write")
        with pytest.raises(OSError):
            faults.fault_point("checkpoint.write")
        faults.fault_point("checkpoint.write")  # @3 exactly: 4th is clean
        assert plan.fired == [("checkpoint.write", "oserror", 3)]

    def test_fire_from_second_hit_onward(self):
        plan = faults.install_plan(faults.parse_faults("native.call=error@2+"))
        faults.fault_point("native.call")
        for _ in range(3):
            with pytest.raises(faults.FaultInjected):
                faults.fault_point("native.call")
        assert [hit for _, _, hit in plan.fired] == [2, 3, 4]

    def test_default_is_every_hit(self):
        faults.install_plan(faults.parse_faults("sweep.dispatch=oom"))
        for _ in range(2):
            with pytest.raises(faults.TransientDeviceFault):
                faults.fault_point("sweep.dispatch")

    def test_oom_carries_the_transient_marker(self):
        faults.install_plan(faults.parse_faults("sweep.dispatch=oom@1"))
        with pytest.raises(faults.TransientDeviceFault, match="RESOURCE_EXHAUSTED"):
            faults.fault_point("sweep.dispatch")

    def test_preempt_is_typed(self):
        faults.install_plan(faults.parse_faults("sweep.window=preempt@1"))
        with pytest.raises(faults.FaultPreempted):
            faults.fault_point("sweep.window")

    def test_hang_sleeps_bounded_and_records(self, monkeypatch):
        slept = []
        monkeypatch.setattr(faults.time, "sleep", slept.append)
        plan = faults.install_plan(
            faults.parse_faults("native.call=hang:0.3@1")
        )
        faults.fault_point("native.call")  # hangs, does not raise
        assert slept == [0.3]
        assert plan.fired == [("native.call", "hang", 1)]
        # A pathological duration is capped, never an hours-long wedge.
        slept.clear()
        faults.install_plan(faults.parse_faults("native.call=hang:9999@1"))
        faults.fault_point("native.call")
        assert slept == [faults.HANG_CAP_S]

    def test_counts_are_per_point(self, monkeypatch):
        # With a flight recorder active (the tier-1 wrapper exports
        # QI_FLIGHT_RECORDER), the firing's dump passes through its own
        # telemetry.dump fault point and would add a count here — this
        # test is about per-point hit accounting, not the dump chain.
        monkeypatch.delenv("QI_FLIGHT_RECORDER", raising=False)
        plan = faults.install_plan(faults.parse_faults("native.call=error@2"))
        faults.fault_point("sweep.dispatch")
        faults.fault_point("native.call")
        with pytest.raises(faults.FaultInjected):
            faults.fault_point("native.call")
        assert plan.counts == {"sweep.dispatch": 1, "native.call": 2}

    def test_firing_lands_in_telemetry(self):
        rec = telemetry.reset_run_record()
        try:
            faults.install_plan(faults.parse_faults("native.call=error@1"))
            with pytest.raises(faults.FaultInjected):
                faults.fault_point("native.call")
            assert rec.counters.get("faults.injected") == 1
            ev = [e for e in rec.events if e["name"] == "fault.injected"]
            assert len(ev) == 1
            assert ev[0]["attrs"] == {
                "point": "native.call", "mode": "error", "hit": 1,
            }
        finally:
            telemetry.reset_run_record()


class TestEnvSpec:
    def test_qi_faults_env_drives_fault_point(self, monkeypatch):
        monkeypatch.setenv("QI_FAULTS", "checkpoint.write=oserror@1+")
        with pytest.raises(OSError):
            faults.fault_point("checkpoint.write")
        # Changing the spec re-parses (no stale cache): new rules apply.
        monkeypatch.setenv("QI_FAULTS", "native.call=error@1+")
        faults.fault_point("checkpoint.write")  # old rule gone
        with pytest.raises(faults.FaultInjected):
            faults.fault_point("native.call")

    def test_installed_plan_wins_over_env(self, monkeypatch):
        monkeypatch.setenv("QI_FAULTS", "native.call=error@1+")
        faults.install_plan(faults.FaultPlan([], label="empty"))
        faults.fault_point("native.call")  # the (empty) plan masks the env

    def test_malformed_spec_raises(self):
        with pytest.raises(ValueError, match="malformed QI_FAULTS"):
            faults.parse_faults("native.call")

    def test_spec_roundtrip(self):
        spec = "native.call=hang:0.5@2+,checkpoint.write=oserror@3"
        plan = faults.parse_faults(spec)
        assert ",".join(r.spec() for r in plan.rules) == spec


class TestDeterminism:
    """Same seed ⇒ same plan ⇒ same firing sequence (ISSUE 4 satellite)."""

    def test_same_seed_same_plan(self):
        for seed in range(40):
            a = faults.sample_plan(seed)
            b = faults.sample_plan(seed)
            assert [r.spec() for r in a.rules] == [r.spec() for r in b.rules]

    def test_seeds_actually_vary(self):
        specs = {
            ",".join(r.spec() for r in faults.sample_plan(s).rules)
            for s in range(40)
        }
        assert len(specs) > 5, "sampler collapsed to a handful of plans"

    def test_same_seed_same_firing_sequence(self, monkeypatch):
        monkeypatch.setattr(faults.time, "sleep", lambda s: None)
        workload_points = (
            ["native.call", "sweep.dispatch", "sweep.window",
             "checkpoint.write", "sweep.compile"] * 3
        )

        def run(seed):
            plan = faults.install_plan(faults.sample_plan(seed))
            outcomes = []
            for point in workload_points:
                try:
                    faults.fault_point(point)
                    outcomes.append((point, None))
                except Exception as exc:  # noqa: BLE001 — recording, not hiding
                    outcomes.append((point, type(exc).__name__))
            faults.clear_plan()
            return list(plan.fired), outcomes

        for seed in range(25):
            assert run(seed) == run(seed), f"seed {seed} diverged"
