"""qi-fuse (ISSUE 16): cross-request pack fusion at the serve drain.

Acceptance, per ISSUE 16:

- fused-vs-unfused per-request parity: verdict/witness/cert byte-identical
  (modulo run provenance) across both vendored fixture pairs and mixed
  query kinds, every fused cert revalidated by the independent checker;
- a mid-pack cancel (one request's deadline) retires ONLY that request's
  lane groups: its ledger books the unswept remainder exactly while the
  co-packed request keeps a full-coverage cert;
- the ``serve.fuse`` fault point degrades in place to the unfused path,
  never flipping a verdict;
- ``BatchFormer`` flush accounting: full / drain / timer reasons land in
  ``flush_log`` in order;
- the forced ``fuse_flush_races_late_submit`` interleaving
  (tools/analyze/schedules.py) passes on both topologies.
"""

import copy
import json
import threading
import time

import pytest

from quorum_intersection_tpu.backends.base import CancelToken
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import majority_fbas
from quorum_intersection_tpu.pipeline import check_many, solve
from quorum_intersection_tpu.serve import DeadlineExceeded, ServeEngine
from quorum_intersection_tpu.utils import faults, telemetry
import quorum_intersection_tpu.backends.tpu.sweep as sweep_mod
import quorum_intersection_tpu.fuse as fuse_mod
from quorum_intersection_tpu.fuse import BatchFormer, estimate_lanes
from tools.check_cert import check_certificate

from tests.conftest import VENDORED_DIR

FIXTURE_PAIRS = [
    ("trivial_correct", True),
    ("trivial_broken", False),
    ("nested_correct", True),
    ("nested_broken", False),
]

FUSE_MS = 50.0


def fixture_nodes(name):
    return json.loads((VENDORED_DIR / f"{name}.json").read_text())


@pytest.fixture
def rec():
    record = telemetry.reset_run_record()
    faults.clear_plan()
    yield record
    faults.clear_plan()
    telemetry.reset_run_record()


class _Engine:
    """Context manager: a started ServeEngine that always stops."""

    def __init__(self, **kw):
        self.engine = ServeEngine(**kw)

    def __enter__(self):
        self.engine.start()
        return self.engine

    def __exit__(self, *exc):
        self.engine.stop(drain=True, timeout=30.0)
        return False


def normalized(cert):
    """A cert with the run-volatile provenance block dropped: everything
    load-bearing — verdict, witness, graph digest, guard, ledgers — must
    be byte-identical between the fused and unfused paths."""
    out = copy.deepcopy(cert)
    out.pop("provenance", None)
    return out


def serve_one(nodes, *, fuse, query=None, **kw):
    with _Engine(
        backend=kw.pop("backend", "python"),
        fuse_window_ms=(FUSE_MS if fuse else 0.0), **kw,
    ) as engine:
        return engine.submit(nodes, query=query).result(timeout=120.0)


class TestFusedParity:
    """Per-request byte-parity: the fused drain is invisible in results."""

    @pytest.mark.parametrize("fixture,verdict", FIXTURE_PAIRS)
    def test_fixture_pairs_byte_identical(self, rec, fixture, verdict):
        nodes = fixture_nodes(fixture)
        plain = serve_one(nodes, fuse=False)
        fused = serve_one(nodes, fuse=True)
        assert plain.intersects is verdict
        assert fused.intersects is verdict
        assert json.dumps(normalized(fused.cert), sort_keys=True) == \
            json.dumps(normalized(plain.cert), sort_keys=True)
        # The independent checker accepts the fused cert unmodified.
        check_certificate(fused.cert, nodes)

    def test_mixed_query_kinds_fused(self, rec):
        """Intersection + whatif + relaxed queries drain through ONE fused
        batch; every answer equals its unfused twin."""
        nodes = majority_fbas(9)
        ids = [n["publicKey"] for n in nodes]
        queries = [
            None,
            {"kind": "whatif", "candidates": ids[:3], "max_k": 2},
            {"kind": "relaxed", "family_b": majority_fbas(9, broken=True)},
        ]
        plain, fused = [], []
        for fuse, out in ((False, plain), (True, fused)):
            engine = ServeEngine(
                backend="python", fuse_window_ms=(FUSE_MS if fuse else 0.0),
            )
            tickets = [engine.submit(nodes, query=q) for q in queries]
            engine.start()  # queue before start: ONE drained batch
            try:
                out.extend(t.result(timeout=120.0) for t in tickets)
            finally:
                engine.stop(drain=True, timeout=30.0)
        for p, f in zip(plain, fused):
            assert f.intersects is p.intersects
            assert f.result == p.result  # structured query payloads too
        # The fused run actually flushed through the former.
        events = [e for e in rec.events if e["name"] == "fuse.flush"]
        assert events, "fused drain never flushed the batch former"

    def test_cross_request_lanes_fill_one_tile(self, rec):
        """Three sweep-sized requests from different clients fuse into one
        lane pack: cross_request_lanes > 0, verdicts all one-shot-equal."""
        streams = [majority_fbas(n) for n in (7, 9, 11)]
        engine = ServeEngine(
            backend="auto", pack=True, fuse_window_ms=200.0,
        )
        tickets = [engine.submit(s) for s in streams]  # queue before start:
        engine.start()                                 # ONE drained batch
        try:
            got = [t.result(timeout=120.0) for t in tickets]
        finally:
            engine.stop(drain=True, timeout=30.0)
        for stream, resp in zip(streams, got):
            assert resp.intersects is True
            assert resp.intersects is solve(
                stream, backend="python"
            ).intersects
        counters, gauges = rec.snapshot()
        assert counters.get("fuse.packs_formed", 0) > 0
        assert counters.get("fuse.cross_request_lanes", 0) > 0
        assert gauges.get("fuse.fill_pct", 0) > 0

    def test_unset_window_is_byte_compatible_legacy_drain(self, rec):
        """fuse_window_ms=0 (the QI_SERVE_FUSE_WINDOW_MS default): no
        former, no fuse.* telemetry, no fused span attrs — the drain is
        the pre-fusion code path."""
        resp = serve_one(majority_fbas(7), fuse=False)
        assert resp.intersects is True
        counters, gauges = rec.snapshot()
        assert not [k for k in counters if k.startswith("fuse.")]
        assert not [k for k in gauges if k.startswith("fuse.")]
        assert not [e for e in rec.events if e["name"].startswith("fuse.")]


class TestMidPackCancel:
    """One request's deadline retires ITS lanes; co-packed work survives
    with full coverage (docs/PARITY.md §Fusion invariants)."""

    def _trip_on_first_window(self, token):
        """A sweep fault_point wrapper that cancels ``token`` at the FIRST
        windows-loop iteration — the deterministic stand-in for a deadline
        firing mid-pack."""
        real = sweep_mod.fault_point
        state = {"hits": 0}

        def wrapper(point):
            if point == "sweep.window":
                state["hits"] += 1
                if state["hits"] == 1:
                    token.cancel()
            return real(point)

        return real, wrapper

    def test_ledger_partition_exact(self, rec):
        """check_many with per-job cancels: the cancelled job books its
        unswept remainder, the co-packed job's ledger stays full, both sum
        to 2^(n-1)."""
        sources = [majority_fbas(13), majority_fbas(15)]
        token = CancelToken()
        real, wrapper = self._trip_on_first_window(token)
        sweep_mod.fault_point = wrapper
        try:
            results = check_many(
                sources, backend="auto", pack=True,
                cancels=[token, None], origins=["req-dead", "req-live"],
            )
        finally:
            sweep_mod.fault_point = real
        dead, live = results
        assert dead.stats.get("cancelled") is True
        dead_cov = dead.cert["coverage"]
        assert dead.cert["partial"] is True
        assert dead.cert["verdict"] is None  # partial evidence, no verdict
        assert dead_cov["windows_cancelled"] > 0
        assert (
            dead_cov["windows_enumerated"] + dead_cov["windows_pruned_guard"]
            + dead_cov["windows_skipped_pack_fill"]
            + dead_cov["windows_cancelled"]
        ) == dead_cov["window_space"] == 2 ** (13 - 1)
        assert live.intersects is True
        assert not live.stats.get("cancelled")
        live_cov = live.stats["cert"]
        assert live_cov["windows_cancelled"] == 0
        assert (
            live_cov["windows_enumerated"] + live_cov["windows_pruned_guard"]
            + live_cov["windows_skipped_pack_fill"]
        ) == live_cov["window_space"] == 2 ** (15 - 1)

    def test_pretripped_token_never_occupies_lanes(self, rec):
        """A request already dead at dispatch is retired BEFORE packing:
        its lanes go to live work, its ledger books everything cancelled."""
        token = CancelToken()
        token.cancel()
        dead, live = check_many(
            [majority_fbas(9), majority_fbas(11)], backend="auto", pack=True,
            cancels=[token, None], origins=["req-dead", "req-live"],
        )
        assert dead.stats.get("cancelled") is True
        cov = dead.cert["coverage"]
        assert cov["windows_cancelled"] == cov["window_space"] == 2 ** 8
        assert cov["windows_enumerated"] == 0
        assert live.intersects is True

    def test_serve_deadline_retires_lanes_copacked_cert_full(self, rec):
        """Serve-level: a fused entry whose deadline fires mid-pack gets
        DeadlineExceeded with ITS exact partial ledger; the co-packed
        entry's verdict and checker-valid cert are untouched."""
        real = sweep_mod.fault_point
        state = {"hits": 0}

        def slow_first_window(point):
            if point == "sweep.window":
                state["hits"] += 1
                if state["hits"] == 1:
                    time.sleep(1.2)  # outlive the 0.5 s deadline below
            return real(point)

        slow, fast = majority_fbas(13), majority_fbas(11)
        engine = ServeEngine(backend="auto", pack=True, fuse_window_ms=200.0)
        t_dead = engine.submit(slow, deadline_s=0.5)
        t_live = engine.submit(fast)
        sweep_mod.fault_point = slow_first_window
        try:
            engine.start()
            live = t_live.result(timeout=120.0)
            with pytest.raises(DeadlineExceeded) as err:
                t_dead.result(timeout=120.0)
        finally:
            sweep_mod.fault_point = real
            engine.stop(drain=True, timeout=30.0)
        assert live.intersects is True
        check_certificate(live.cert, fast)
        partial = err.value.cert
        assert partial is not None and partial["partial"] is True
        cov = partial["coverage"]
        assert cov["windows_cancelled"] > 0
        assert (
            cov["windows_enumerated"] + cov["windows_pruned_guard"]
            + cov["windows_skipped_pack_fill"] + cov["windows_cancelled"]
        ) == cov["window_space"] == 2 ** (13 - 1)


class TestFuseFaultPoint:
    def test_serve_fuse_fault_degrades_in_place(self, rec):
        """serve.fuse=error: the batch drains unfused — right answers, a
        counted degrade, zero former activity."""
        faults.install_plan(faults.parse_faults("serve.fuse=error@1+"))
        for fixture, verdict in FIXTURE_PAIRS:
            nodes = fixture_nodes(fixture)
            resp = serve_one(nodes, fuse=True)
            assert resp.intersects is verdict
            check_certificate(resp.cert, nodes)
        counters, _ = rec.snapshot()
        assert counters.get("serve.fuse_faults", 0) >= len(FIXTURE_PAIRS)
        assert counters.get("fuse.packs_formed", 0) == 0
        assert [e for e in rec.events if e["name"] == "serve.fuse_degraded"]
        assert not [e for e in rec.events if e["name"] == "fuse.flush"]


class TestBatchFormerAccounting:
    """Flush-reason accounting straight off the former, no engine."""

    @staticmethod
    def _fn(sources, cancels, origins):
        return check_many(sources, backend="python")

    def test_fill_flush_before_timer(self, rec):
        """Two 9-node sources ladder to 16 lanes each: the second submit
        fills a 32-lane tile and flushes NOW, not at the far timer."""
        former = BatchFormer(self._fn, window_ms=60_000.0, lane_tile=32)
        fbas = parse_fbas(majority_fbas(9))
        assert estimate_lanes(fbas) == 16
        former.register()
        former.register()
        outs = [None, None]

        def worker(ix):
            try:
                outs[ix] = former.submit([fbas], origin=f"req-{ix}")
            finally:
                former.done()

        t0 = time.monotonic()
        threads = [
            threading.Thread(target=worker, args=(ix,), daemon=True)
            for ix in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        assert time.monotonic() - t0 < 30.0  # never waited for the timer
        assert all(o is not None and o[0].intersects for o in outs)
        assert former.flush_log and former.flush_log[0] in ("full", "drain")
        assert sum(
            1 for e in rec.events if e["name"] == "fuse.flush"
        ) == len(former.flush_log)

    def test_timer_flush_then_drain_flush(self, rec):
        """With a registered producer still unsubmitted, only the window
        timer can release the first unit; once that producer is the lone
        submitter, drain releases it immediately."""
        former = BatchFormer(self._fn, window_ms=120.0)
        fbas = parse_fbas(majority_fbas(5))
        former.register()  # p1
        former.register()  # p2: not submitting yet — blocks "drain"
        res1 = former.submit([fbas], origin="req-1")  # held until timer
        former.done()
        assert former.flush_log == ["timer"]
        res2 = former.submit([fbas], origin="req-2")  # lone producer: drain
        former.done()
        assert former.flush_log == ["timer", "drain"]
        assert res1[0].intersects is True
        assert res2[0].intersects is True
        flushes = [e for e in rec.events if e["name"] == "fuse.flush"]
        assert [e["attrs"]["reason"] for e in flushes] == ["timer", "drain"]
        assert all(e["attrs"]["units"] == 1 for e in flushes)

    def test_deadline_beats_timer(self, rec):
        """A pending unit's deadline earlier than the window timer flushes
        with reason=deadline."""
        former = BatchFormer(self._fn, window_ms=60_000.0)
        fbas = parse_fbas(majority_fbas(5))
        former.register()
        former.register()  # a second producer blocks "drain"
        res = former.submit(
            [fbas], origin="req-1", deadline_t=time.monotonic() + 0.1,
        )
        former.done()
        assert former.flush_log == ["deadline"]
        assert res[0].intersects is True

    def test_flush_failure_fans_out(self, rec):
        def boom(sources, cancels, origins):
            raise RuntimeError("flush exploded")

        former = BatchFormer(boom, window_ms=10.0)
        former.register()
        with pytest.raises(RuntimeError, match="flush exploded"):
            former.submit([parse_fbas(majority_fbas(5))], origin="req-1")
        former.done()


class TestForcedFuseSchedules:
    """The flush-vs-late-submit interleaving, forced every run (the same
    harness `python -m tools.analyze race` executes in CI)."""

    @pytest.fixture(scope="class")
    def results(self):
        from tools.analyze.schedules import run_fuse_schedules

        return run_fuse_schedules()

    def test_all_schedules_pass_both_topologies(self, results):
        from tools.analyze.schedules import FUSE_SCHEDULES

        assert "fuse_flush_races_late_submit" in FUSE_SCHEDULES
        assert len(results) == len(FUSE_SCHEDULES) * 2
        bad = [r for r in results if not r.ok]
        assert not bad, bad

    def test_late_submit_lands_in_second_flush(self, results):
        for r in results:
            assert r.trace.index("fuse.flush.formed") < r.trace.index(
                "fuse.flush.done"
            )
            # The late submit arrived while the first flush was in the air
            # and still resolved — via its own (second) flush.
            assert r.trace.count("fuse.submit") == 2

    def test_hook_restored_and_no_leaked_workers(self, results):
        assert fuse_mod._fuse_sync.__name__ == "<lambda>"
        fuse_mod._fuse_sync("no-op")
        assert not [
            t for t in threading.enumerate()
            if t.name.startswith("qi-fuse-sched")
        ]
