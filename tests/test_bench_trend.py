"""Bench-trend regression sentinel + metrics_report satellites (ISSUE 6):
synthetic regressed row fails, committed history passes, schema errors
hard-fail, device partitioning keeps cross-hardware rounds out of each
other's baselines, span TREES render with parent indentation, and --diff
compares two streams."""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))  # `from tools...` resolves without install

from tools import bench_trend  # noqa: E402
from tools import metrics_report  # noqa: E402


def wrapper(n, row, rc=0):
    """A BENCH_rNN.json driver wrapper whose tail ends in one bench row."""
    return {
        "n": n, "cmd": "python bench.py", "rc": rc,
        "tail": "WARNING: noise\n" + json.dumps(row) + "\n", "parsed": None,
    }


def write_history(tmp_path, rows):
    for n, row in enumerate(rows, start=1):
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(
            json.dumps(wrapper(n, row))
        )


GOOD = {"device": "cpu", "value": 1_000_000.0, "sweep_mfu_pct": 3.0,
        "snapshot_verdict_seconds": 0.5}
REGRESSED = {"device": "cpu", "value": 40_000.0, "sweep_mfu_pct": 0.1,
             "snapshot_verdict_seconds": 9.0}


class TestBenchTrend:
    def test_synthetic_regressed_row_exits_nonzero(self, tmp_path, capsys):
        write_history(tmp_path, [GOOD, REGRESSED])
        rc = bench_trend.main(["--repo", str(tmp_path)])
        assert rc == 1
        out = capsys.readouterr()
        assert "REGRESSED" in out.out
        assert "REGRESSION" in out.err

    def test_healthy_history_exits_zero(self, tmp_path, capsys):
        improved = dict(GOOD, value=1_200_000.0, snapshot_verdict_seconds=0.4)
        write_history(tmp_path, [GOOD, improved])
        assert bench_trend.main(["--repo", str(tmp_path)]) == 0
        assert "ok" in capsys.readouterr().out

    def test_committed_history_exits_zero(self, capsys):
        # Acceptance: the sentinel over the repo's own BENCH_r*.json +
        # benchmarks/results history is clean.
        assert bench_trend.main(["--repo", str(REPO)]) == 0
        out = capsys.readouterr().out
        assert "latest bench run:" in out
        # The dryrun multichip rounds trend as their own family, never
        # against the single-chip baselines.
        assert "latest multichip run:" in out

    def test_informational_reports_but_exits_zero(self, tmp_path, capsys):
        write_history(tmp_path, [GOOD, REGRESSED])
        rc = bench_trend.main(["--repo", str(tmp_path), "--informational"])
        assert rc == 0
        assert "REGRESSION" in capsys.readouterr().err

    def test_schema_error_exits_2_even_informational(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text("not json at all")
        assert bench_trend.main(["--repo", str(tmp_path)]) == 2
        assert bench_trend.main(
            ["--repo", str(tmp_path), "--informational"]
        ) == 2

    def test_truncated_tail_is_skipped_not_schema_error(self, tmp_path,
                                                        capsys):
        # A SIGKILLed round leaves a wrapper whose tail has no complete
        # JSON line — expected history, never a hard failure.
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(
            {"n": 1, "cmd": "x", "rc": 124,
             "tail": "WARNING: half a row {\"value\": 12", "parsed": None}
        ))
        write_history_row = wrapper(2, GOOD)
        (tmp_path / "BENCH_r02.json").write_text(json.dumps(write_history_row))
        assert bench_trend.main(["--repo", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "skipped" in out and "BENCH_r01.json" in out

    def test_device_partitioning(self, tmp_path):
        # A cpu-fallback round's fast latencies must not baseline a
        # tunneled-chip round (the committed r3-vs-r5 pair): same numbers,
        # different device string -> no regression.
        cpu_round = dict(GOOD, device="cpu-fallback",
                         snapshot_verdict_seconds=0.02)
        chip_round = dict(GOOD, device="TPU v5 lite",
                          snapshot_verdict_seconds=1.0)
        write_history(tmp_path, [cpu_round, chip_round])
        assert bench_trend.main(["--repo", str(tmp_path)]) == 0

    def test_tolerance_overrides(self, tmp_path):
        mild = dict(GOOD, value=800_000.0)  # -20% vs GOOD
        write_history(tmp_path, [GOOD, mild])
        assert bench_trend.main(["--repo", str(tmp_path)]) == 0
        assert bench_trend.main(
            ["--repo", str(tmp_path), "--tolerance", "10"]
        ) == 1
        assert bench_trend.main(
            ["--repo", str(tmp_path), "--tolerance", "10",
             "--tolerance-metric", "value=30"]
        ) == 0

    def test_telemetry_section(self, tmp_path, capsys):
        write_history(tmp_path, [GOOD])
        stream = tmp_path / "t.jsonl"
        stream.write_text(
            json.dumps({"kind": "gauge", "name": "sweep.candidates_per_sec",
                        "value": 123456.0}) + "\n"
        )
        assert bench_trend.main(
            ["--repo", str(tmp_path), "--telemetry", str(stream)]
        ) == 0
        assert "sweep.candidates_per_sec" in capsys.readouterr().out


class TestMetricsReportSatellites:
    def _stream(self, path, rows):
        path.write_text("".join(json.dumps(r) + "\n" for r in rows))
        return str(path)

    def test_span_tree_indents_children(self, tmp_path, capsys):
        rows = [
            {"kind": "meta", "schema": "qi-telemetry/1", "pid": 1,
             "t_wall": 0.0},
            {"kind": "span", "name": "route", "span_id": 1,
             "parent_id": None, "pid": 1, "start_s": 0.0, "seconds": 1.0},
            {"kind": "span", "name": "race", "span_id": 2, "parent_id": 1,
             "pid": 1, "start_s": 0.1, "seconds": 0.8},
            {"kind": "span", "name": "race.sweep", "span_id": 3,
             "parent_id": 2, "pid": 1, "start_s": 0.2, "seconds": 0.5},
        ]
        text = metrics_report.render(self._stream(tmp_path / "a.jsonl", rows))
        lines = text.splitlines()
        route = next(l for l in lines if l.startswith("route"))
        race = next(l for l in lines if l.lstrip().startswith("race "))
        arm = next(l for l in lines if l.lstrip().startswith("race.sweep"))
        # Depth = indentation: children sit under their parents.
        assert (len(race) - len(race.lstrip())) == 2
        assert (len(arm) - len(arm.lstrip())) == 4
        assert route is not None

    def test_span_tree_cross_pid_ids_do_not_collide(self, tmp_path):
        # Two processes reuse span_id=1; the tree must scope parent lookup
        # by pid instead of grafting one process's span onto the other's.
        rows = [
            {"kind": "span", "name": "parent_a", "span_id": 1,
             "parent_id": None, "pid": 1, "start_s": 0, "seconds": 1.0},
            {"kind": "span", "name": "child_a", "span_id": 2, "parent_id": 1,
             "pid": 1, "start_s": 0, "seconds": 0.5},
            {"kind": "span", "name": "parent_b", "span_id": 1,
             "parent_id": None, "pid": 2, "start_s": 0, "seconds": 1.0},
        ]
        paths = dict(
            (sp["name"], p)
            for p, sp in metrics_report._span_paths(rows)
        )
        assert paths["child_a"] == ("parent_a", "child_a")
        assert paths["parent_b"] == ("parent_b",)

    def test_diff_mode(self, tmp_path):
        a = self._stream(tmp_path / "a.jsonl", [
            {"kind": "counter", "name": "native.bnb_calls", "value": 100},
            {"kind": "gauge", "name": "sweep.candidates_per_sec",
             "value": 1000.0},
            {"kind": "span", "name": "phase.search", "span_id": 1,
             "parent_id": None, "start_s": 0, "seconds": 2.0},
        ])
        b = self._stream(tmp_path / "b.jsonl", [
            {"kind": "counter", "name": "native.bnb_calls", "value": 150},
            {"kind": "gauge", "name": "sweep.candidates_per_sec",
             "value": 500.0},
            {"kind": "span", "name": "phase.search", "span_id": 1,
             "parent_id": None, "start_s": 0, "seconds": 1.0},
        ])
        text = metrics_report.render_diff(a, b)
        assert "native.bnb_calls" in text and "+50" in text
        assert "-50.0%" in text  # the halved gauge and span total
        rows = metrics_report.diff_streams(
            metrics_report.load_stream(a), metrics_report.load_stream(b)
        )
        by_name = {r[0]: r for r in rows}
        assert by_name["span:phase.search"][4] == "-1"

    def test_diff_cli_flag(self, tmp_path):
        a = self._stream(tmp_path / "a.jsonl", [
            {"kind": "counter", "name": "c", "value": 1},
        ])
        b = self._stream(tmp_path / "b.jsonl", [
            {"kind": "counter", "name": "c", "value": 3},
        ])
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "metrics_report.py"),
             a, "--diff", b],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "qi-telemetry diff" in proc.stdout
        assert "+2" in proc.stdout
