"""qi-serve differential suite (ISSUE 8): served verdicts and certificates
identical to the one-shot pipeline across the vendored fixture pairs and
every ladder rung, typed outcomes at every serve.* fault point, the
admission/deadline/shed semantics, the verdict cache + single-flight
coalescing, the crash-only journal replay matrix (torn tail / empty /
corrupt / foreign fingerprint / already-done), a real kill-and-replay CLI
round, /readyz readiness, and churn-trace determinism."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import churn_trace, majority_fbas
from quorum_intersection_tpu.pipeline import solve
from quorum_intersection_tpu.utils import faults, telemetry
from quorum_intersection_tpu.utils.faults import FaultInjected
from quorum_intersection_tpu.utils.metrics_server import readyz_payload
import quorum_intersection_tpu.serve as serve_mod
from quorum_intersection_tpu.serve import (
    DeadlineExceeded,
    Overloaded,
    RequestJournal,
    ServeClosed,
    ServeEngine,
    ServeError,
    snapshot_fingerprint,
)
from tools.check_cert import check_certificate

from tests.conftest import VENDORED_DIR

CLI = [sys.executable, "-m", "quorum_intersection_tpu"]

# The four engines a served solve can route through — the ladder rungs.
BACKENDS = ("python", "cpp", "tpu-sweep", "tpu-frontier")

FIXTURE_PAIRS = [
    ("trivial_correct", True),
    ("trivial_broken", False),
    ("nested_correct", True),
    ("nested_broken", False),
]


def make_backend(name):
    if name == "tpu-sweep":
        return TpuSweepBackend(batch=512)
    if name == "tpu-frontier":
        return TpuFrontierBackend(arena=4096, pop=128)
    return name


def fixture_nodes(name):
    return json.loads((VENDORED_DIR / f"{name}.json").read_text())


def fingerprint_of(nodes):
    return snapshot_fingerprint(build_graph(parse_fbas(nodes)))


@pytest.fixture
def rec():
    record = telemetry.reset_run_record()
    faults.clear_plan()
    yield record
    faults.clear_plan()
    telemetry.reset_run_record()


class _Engine:
    """Context manager: a started ServeEngine that always stops."""

    def __init__(self, **kw):
        self.engine = ServeEngine(**kw)

    def __enter__(self):
        self.engine.start()
        return self.engine

    def __exit__(self, *exc):
        self.engine.stop(drain=True, timeout=30.0)
        return False


def pair_of(witness):
    return {frozenset(witness["q1"]), frozenset(witness["q2"])}


class TestDifferentialParity:
    """Served verdict + cert == one-shot pipeline, on every rung."""

    @pytest.mark.parametrize("backend", BACKENDS)
    @pytest.mark.parametrize("fixture,verdict", FIXTURE_PAIRS)
    def test_served_equals_one_shot(self, rec, backend, fixture, verdict):
        nodes = fixture_nodes(fixture)
        oracle = solve(nodes, backend=make_backend(backend))
        assert oracle.intersects is verdict
        with _Engine(backend=make_backend(backend)) as engine:
            resp = engine.submit(nodes).result(timeout=120.0)
        assert resp.intersects is verdict
        assert resp.cached is False
        cert = resp.cert
        assert cert is not None
        assert cert["verdict"] is verdict
        if not verdict:
            assert pair_of(cert["witness"]) == pair_of(oracle.cert["witness"])
        # The serve provenance stamp rides the cert without breaking the
        # independent checker's soundness verdict.
        stamp = cert["provenance"]["serve"]
        assert stamp["schema"] == serve_mod.SERVE_SCHEMA
        assert stamp["request_id"] == resp.request_id
        assert stamp["cached"] is False
        assert stamp["fingerprint"] == fingerprint_of(nodes)
        check_certificate(cert, nodes)

    @pytest.mark.slow
    def test_snapshot_pair_served(self, rec):
        """The big real-snapshot pair, python rung (the other rungs cover
        it in the one-shot cert suite; serving adds no engine surface —
        slow: ~90 s of independent-checker work on the real snapshot)."""
        for fixture, verdict in (
            ("snapshot_correct", True), ("snapshot_broken", False),
        ):
            nodes = fixture_nodes(fixture)
            with _Engine(backend="python") as engine:
                resp = engine.submit(nodes).result(timeout=120.0)
            assert resp.intersects is verdict
            check_certificate(resp.cert, nodes)

    def test_batched_drain_matches_oracle(self, rec):
        """Many queued snapshots drain through one check_many batch; every
        verdict still equals its own one-shot solve."""
        streams = [majority_fbas(n, broken=b)
                   for n in (5, 7, 9) for b in (False, True)]
        expected = [solve(s, backend="python").intersects for s in streams]
        with _Engine(backend="python", batch_max=6) as engine:
            tickets = [engine.submit(s) for s in streams]
            got = [t.result(timeout=60.0).intersects for t in tickets]
        assert got == expected


class TestServeFaultPoints:
    """Seeded QI_FAULTS at every serve.* boundary: typed outcome or an
    oracle-equal verdict — never a silent drop, never a flip."""

    def test_admit_fault_is_typed_and_isolated(self, rec):
        faults.install_plan(faults.parse_faults("serve.admit=error@1"))
        nodes = majority_fbas(5)
        with _Engine(backend="python") as engine:
            with pytest.raises(FaultInjected):
                engine.submit(nodes)
            # The queue and later requests are unaffected.
            resp = engine.submit(nodes).result(timeout=60.0)
        assert resp.intersects is solve(nodes, backend="python").intersects

    def test_cache_fault_bypasses_never_flips(self, rec):
        faults.install_plan(faults.parse_faults("serve.cache=error@1+"))
        nodes = majority_fbas(7, broken=True)
        expected = solve(nodes, backend="python").intersects
        with _Engine(backend="python") as engine:
            for _ in range(3):  # every probe faulted: all solves from scratch
                assert engine.submit(nodes).result(
                    timeout=60.0).intersects is expected
        counters, _ = rec.snapshot()
        assert counters.get("serve.cache_errors", 0) >= 3
        assert counters.get("serve.cache_hits", 0) == 0

    def test_journal_fault_serves_unjournaled(self, rec, tmp_path):
        faults.install_plan(faults.parse_faults("serve.journal=oserror@1+"))
        journal = tmp_path / "j.jsonl"
        nodes = majority_fbas(5)
        with _Engine(backend="python", journal=journal) as engine:
            resp = engine.submit(nodes).result(timeout=60.0)
        assert resp.intersects is True
        counters, _ = rec.snapshot()
        assert counters.get("serve.journal_errors", 0) >= 1
        # Nothing made it into the journal — replay protection was LOUDLY
        # lost, the verdict was not.
        entries, _, _ = RequestJournal(journal).scan()
        assert entries == []

    def test_drain_fault_degrades_to_per_request(self, rec):
        faults.install_plan(faults.parse_faults("serve.drain=error@1"))
        nodes = majority_fbas(9, broken=True)
        with _Engine(backend="python") as engine:
            resp = engine.submit(nodes).result(timeout=60.0)
        assert resp.intersects is False
        counters, _ = rec.snapshot()
        assert counters.get("serve.drain_faults", 0) == 1

    def test_respond_fault_is_typed_then_cache_hit(self, rec):
        faults.install_plan(faults.parse_faults("serve.respond=error@1"))
        nodes = majority_fbas(5)
        with _Engine(backend="python") as engine:
            with pytest.raises(FaultInjected):
                engine.submit(nodes).result(timeout=60.0)
            # The verdict survived the failed delivery: the retry hits the
            # cache and serves.
            resp = engine.submit(nodes).result(timeout=60.0)
        assert resp.cached is True
        assert resp.intersects is True
        counters, _ = rec.snapshot()
        assert counters.get("serve.respond_errors", 0) == 1

    def test_closed_engine_is_typed(self, rec):
        engine = ServeEngine(backend="python")
        engine.start()
        engine.stop(drain=True, timeout=30.0)
        with pytest.raises(ServeClosed):
            engine.submit(majority_fbas(5))

    def test_no_drain_stop_resolves_queued_tickets_typed(
        self, rec, monkeypatch,
    ):
        """stop(drain=False) discards the queue but every discarded
        waiter gets a typed ServeClosed — never an unresolved ticket."""
        hold = _HeldDrain()
        monkeypatch.setattr(serve_mod, "_serve_sync", hold)
        engine = ServeEngine(backend="python")
        engine.start()
        try:
            t_inflight = engine.submit(majority_fbas(5, prefix="STA"))
            assert hold.popped.wait(10.0)  # drain parked holding t_inflight
            t_queued = engine.submit(majority_fbas(7, prefix="STB"))
            engine.stop(drain=False, timeout=0.1)
            with pytest.raises(ServeClosed):
                t_queued.result(timeout=10.0)
        finally:
            hold.release.set()
            engine.stop(drain=False, timeout=30.0)
        # The popped in-flight entry still delivers normally.
        assert t_inflight.result(timeout=60.0).intersects is True
        counters, _ = rec.snapshot()
        assert counters.get("serve.errors", 0) == 1
        assert counters.get("serve.verdicts", 0) == 1


class _HeldDrain:
    """Park the drain loop at drain.popped until released (the schedule
    harness's trick, scoped to one test)."""

    def __init__(self):
        self.popped = threading.Event()
        self.release = threading.Event()

    def __call__(self, point):
        if point == "drain.popped":
            self.popped.set()
            self.release.wait(30.0)


@pytest.fixture
def held_drain(monkeypatch):
    hold = _HeldDrain()
    monkeypatch.setattr(serve_mod, "_serve_sync", hold)
    yield hold
    hold.release.set()


class TestAdmissionAndDeadlines:
    def test_overflow_sheds_typed_and_admitted_still_serve(
        self, rec, held_drain,
    ):
        a, b, c = (majority_fbas(n, prefix=f"ADM{n}") for n in (5, 7, 9))
        with _Engine(backend="python", queue_depth=1) as engine:
            t_a = engine.submit(a)
            assert held_drain.popped.wait(10.0)
            t_b = engine.submit(b)  # fills the bounded queue
            with pytest.raises(Overloaded) as exc:
                engine.submit(c)
            assert exc.value.code == "overloaded"
            assert exc.value.depth >= exc.value.bound == 1
            held_drain.release.set()
            assert t_a.result(timeout=60.0).intersects is True
            assert t_b.result(timeout=60.0).intersects is True
        counters, _ = rec.snapshot()
        assert counters.get("serve.shed", 0) == 1
        # The shed is a delivered typed failure: requests == verdicts +
        # errors (the registry's zero-silent-drops invariant).
        assert counters.get("serve.requests") == 3
        assert counters.get("serve.verdicts", 0) + counters.get(
            "serve.errors", 0) == 3

    def test_deadline_expiry_is_typed_never_a_wedge(self, rec, held_drain):
        nodes = majority_fbas(5)
        with _Engine(backend="python") as engine:
            ticket = engine.submit(nodes, deadline_s=0.05)
            assert held_drain.popped.wait(10.0)
            while time.monotonic() < ticket.deadline_t:
                time.sleep(0.005)
            held_drain.release.set()
            with pytest.raises(DeadlineExceeded) as exc:
                ticket.result(timeout=60.0)
            assert exc.value.code == "deadline_exceeded"
            assert exc.value.request_id == ticket.request_id
            # The engine is not wedged: the same snapshot still serves.
            assert engine.submit(nodes).result(
                timeout=60.0).intersects is True
        counters, _ = rec.snapshot()
        assert counters.get("serve.deadline_expired", 0) == 1

    def test_late_coalescer_deadline_enforced_at_delivery(
        self, rec, held_drain,
    ):
        """A request that coalesces onto an in-flight entry after the
        batch's deadline supervisor was armed still gets its expiry
        honored at delivery — never a verdict quietly past its budget."""
        nodes = majority_fbas(9)
        with _Engine(backend="python") as engine:
            t_a = engine.submit(nodes)  # no deadline, will be solved
            assert held_drain.popped.wait(10.0)
            t_b = engine.submit(nodes, deadline_s=0.05)  # coalesces late
            while time.monotonic() < t_b.deadline_t:
                time.sleep(0.005)
            held_drain.release.set()
            assert t_a.result(timeout=60.0).intersects is True
            with pytest.raises(DeadlineExceeded):
                t_b.result(timeout=60.0)
            # The verdict was cached, so B's retry is an immediate hit.
            assert engine.submit(nodes).result(timeout=60.0).cached is True


class TestCacheAndCoalesce:
    def test_repeat_snapshot_is_a_cache_hit(self, rec):
        nodes = majority_fbas(7)
        with _Engine(backend="python") as engine:
            first = engine.submit(nodes).result(timeout=60.0)
            second = engine.submit(nodes).result(timeout=60.0)
        assert first.cached is False and second.cached is True
        assert second.intersects is first.intersects
        assert second.cert["provenance"]["serve"]["cached"] is True
        counters, _ = rec.snapshot()
        assert counters.get("serve.cache_hits", 0) == 1

    def test_cosmetic_churn_hits_same_fingerprint(self):
        nodes = majority_fbas(7)
        renamed = json.loads(json.dumps(nodes))
        renamed[0]["name"] = "renamed-for-cosmetics"
        assert fingerprint_of(nodes) == fingerprint_of(renamed)
        rethreshed = json.loads(json.dumps(nodes))
        rethreshed[0]["quorumSet"]["threshold"] -= 1
        assert fingerprint_of(nodes) != fingerprint_of(rethreshed)

    def test_concurrent_identical_queries_coalesce(self, rec, held_drain):
        nodes = majority_fbas(9)
        with _Engine(backend="python") as engine:
            t1 = engine.submit(nodes)
            assert held_drain.popped.wait(10.0)
            t2 = engine.submit(nodes)  # identical, mid-solve: single-flight
            held_drain.release.set()
            r1, r2 = t1.result(timeout=60.0), t2.result(timeout=60.0)
        assert r1.intersects is r2.intersects
        counters, _ = rec.snapshot()
        assert counters.get("serve.coalesced", 0) == 1

    def test_bounded_cache_evicts_lru(self, rec):
        a, b = majority_fbas(5, prefix="EVA"), majority_fbas(5, prefix="EVB")
        with _Engine(backend="python", cache_max=1) as engine:
            engine.submit(a).result(timeout=60.0)
            engine.submit(b).result(timeout=60.0)  # evicts a
            again = engine.submit(a).result(timeout=60.0)
        assert again.cached is False
        counters, _ = rec.snapshot()
        assert counters.get("serve.cache_evictions", 0) >= 1


class TestJournalReplayMatrix:
    """Crash-only journal: every corruption class quarantines instead of
    blocking startup; pending work replays exactly once."""

    def _journal_with(self, path, lines):
        path.write_text("".join(line + "\n" for line in lines))
        return path

    def _req_line(self, rid, nodes, fingerprint=None):
        return json.dumps({
            "kind": "req", "request_id": rid,
            "fingerprint": fingerprint or fingerprint_of(nodes),
            "deadline_s": None, "nodes": nodes, "t_wall": 0.0,
        })

    def test_pending_entry_replays_to_oracle_verdict(self, rec, tmp_path):
        nodes = majority_fbas(7, broken=True)
        journal = self._journal_with(
            tmp_path / "j.jsonl", [self._req_line("r1", nodes)],
        )
        engine = ServeEngine(backend="python", journal=journal)
        report = engine.start()
        try:
            assert report["pending"] == 1
            assert report["verdicts"] == {
                "r1": solve(nodes, backend="python").intersects,
            }
            # Zero duplicated: the replayed verdict is already cached, and
            # a second start on the compacted journal replays nothing.
            resp = engine.submit(nodes).result(timeout=60.0)
            assert resp.cached is True
        finally:
            engine.stop(drain=True, timeout=30.0)
        with _Engine(backend="python", journal=journal) as engine2:
            assert engine2._replay_report["pending"] == 0
            assert engine2._replay_report["verdicts"] == {}

    def test_done_entry_is_final_zero_duplicates(self, rec, tmp_path):
        nodes = majority_fbas(5)
        fp = fingerprint_of(nodes)
        journal = self._journal_with(tmp_path / "j.jsonl", [
            self._req_line("r1", nodes),
            json.dumps({"kind": "done", "request_id": "r1",
                        "fingerprint": fp, "outcome": "verdict",
                        "verdict": True, "t_wall": 0.0}),
        ])
        with _Engine(backend="python", journal=journal) as engine:
            report = engine._replay_report
        assert report["already_done"] == 1
        assert report["pending"] == 0
        assert report["verdicts"] == {}

    def test_torn_tail_is_tolerated(self, rec, tmp_path):
        nodes = majority_fbas(5)
        journal = self._journal_with(tmp_path / "j.jsonl", [
            self._req_line("r1", nodes),
            '{"kind": "req", "request_id": "r2", "trunca',  # kill -9 artifact
        ])
        with _Engine(backend="python", journal=journal) as engine:
            report = engine._replay_report
        assert report["torn_tail"] is True
        assert report["verdicts"] == {"r1": True}
        assert report["quarantined"] == 0

    def test_empty_journal_replays_nothing(self, rec, tmp_path):
        journal = tmp_path / "j.jsonl"
        journal.write_text("")
        with _Engine(backend="python", journal=journal) as engine:
            report = engine._replay_report
        assert report["entries"] == 0
        assert report["pending"] == 0

    def test_corrupt_middle_line_quarantines(self, rec, tmp_path):
        nodes = majority_fbas(5)
        journal = self._journal_with(tmp_path / "j.jsonl", [
            "not json at all {{{",
            self._req_line("r1", nodes),
        ])
        with _Engine(backend="python", journal=journal) as engine:
            report = engine._replay_report
        assert report["quarantined"] == 1
        assert report["verdicts"] == {"r1": True}
        corrupt = journal.with_name(journal.name + ".corrupt")
        assert "not json at all" in corrupt.read_text()

    def test_foreign_fingerprint_quarantines(self, rec, tmp_path):
        nodes = majority_fbas(5)
        journal = self._journal_with(tmp_path / "j.jsonl", [
            self._req_line("r1", nodes, fingerprint="f" * 32),
        ])
        with _Engine(backend="python", journal=journal) as engine:
            report = engine._replay_report
        assert report["quarantined"] == 1
        assert report["verdicts"] == {}
        corrupt = journal.with_name(journal.name + ".corrupt")
        assert '"r1"' in corrupt.read_text()

    def test_unparseable_nodes_quarantine(self, rec, tmp_path):
        journal = self._journal_with(tmp_path / "j.jsonl", [
            json.dumps({"kind": "req", "request_id": "r1",
                        "fingerprint": "a" * 32, "deadline_s": None,
                        "nodes": {"not": "a node array"}, "t_wall": 0.0}),
        ])
        with _Engine(backend="python", journal=journal) as engine:
            report = engine._replay_report
        assert report["quarantined"] == 1

    def test_live_requests_journal_and_mark_done(self, rec, tmp_path):
        journal = tmp_path / "j.jsonl"
        nodes = majority_fbas(7)
        with _Engine(backend="python", journal=journal) as engine:
            engine.submit(nodes).result(timeout=60.0)
        entries, corrupt, torn = RequestJournal(journal).scan()
        assert not corrupt and not torn
        kinds = [e["kind"] for e in entries]
        assert kinds == ["req", "done"]
        assert entries[1]["verdict"] is True
        assert entries[0]["fingerprint"] == entries[1]["fingerprint"]

    def test_coalesced_request_journals_its_own_pair(
        self, rec, tmp_path, held_drain,
    ):
        """A coalesced (single-flight) request is ACCEPTED, so it must be
        as kill-proof as a queued one: its own req entry before delivery,
        its own done mark after."""
        journal = tmp_path / "j.jsonl"
        nodes = majority_fbas(9)
        with _Engine(backend="python", journal=journal) as engine:
            t1 = engine.submit(nodes, request_id="primary")
            assert held_drain.popped.wait(10.0)
            t2 = engine.submit(nodes, request_id="rider")  # coalesces
            held_drain.release.set()
            t1.result(timeout=60.0), t2.result(timeout=60.0)
        entries, _, _ = RequestJournal(journal).scan()
        by_kind = {}
        for e in entries:
            by_kind.setdefault(e["kind"], set()).add(e["request_id"])
        assert by_kind["req"] == {"primary", "rider"}
        assert by_kind["done"] == {"primary", "rider"}

    def test_duplicate_fingerprint_entries_both_replay(self, rec, tmp_path):
        """Two pending entries for the SAME snapshot (a kill that caught a
        coalesced pair in flight): both replay, zero lost."""
        nodes = majority_fbas(7)
        journal = self._journal_with(tmp_path / "j.jsonl", [
            self._req_line("r1", nodes),
            self._req_line("r2", nodes),
        ])
        with _Engine(backend="python", journal=journal) as engine:
            report = engine._replay_report
        assert report["pending"] == 2
        assert report["verdicts"] == {"r1": True, "r2": True}


@pytest.mark.slow
class TestKillAndReplayCLI:
    """A real serve subprocess, SIGKILLed mid-drain: the journal replays
    with zero lost and zero duplicated verdicts, all oracle-equal."""

    def test_hard_kill_then_replay(self, tmp_path):
        journal = tmp_path / "kill.jsonl"
        streams = [majority_fbas(n, broken=b, prefix=f"K{n}{int(b)}")
                   for n, b in ((5, False), (7, True), (9, False))]
        oracle = {
            f"kill-{i}": solve(s, backend="python").intersects
            for i, s in enumerate(streams)
        }
        env = {k: v for k, v in os.environ.items()
               if not k.startswith("QI_")}
        env.update({
            "JAX_PLATFORMS": "cpu",
            # Hold every drain cycle so the kill provably lands with
            # journaled work in flight.
            "QI_FAULTS": "serve.drain=hang:2.0@1+",
        })
        proc = subprocess.Popen(
            CLI + ["serve", "--journal", str(journal),
                   "--backend", "python"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            env=env,
        )
        try:
            for i, s in enumerate(streams):
                proc.stdin.write(json.dumps(
                    {"request_id": f"kill-{i}", "nodes": s}) + "\n")
            proc.stdin.flush()
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if journal.exists() and sum(
                    1 for ln in journal.read_text().splitlines()
                    if '"kind": "req"' in ln
                ) >= len(streams):
                    break
                time.sleep(0.05)
            else:
                pytest.fail("requests never reached the journal")
        finally:
            proc.kill() if proc.poll() is None else None
            os.kill(proc.pid, signal.SIGKILL) if proc.poll() is None else None
            proc.wait(timeout=30.0)

        answered = {}
        out = proc.stdout.read() or ""
        for line in out.splitlines():
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue  # a torn stdout line is the kill's artifact
            if "verdict" in obj:
                answered[obj["request_id"]] = obj["verdict"]

        replay = subprocess.run(
            CLI + ["serve", "--journal", str(journal), "--replay-only",
                   "--backend", "python"],
            capture_output=True, text=True, timeout=300.0,
            env={**env, "QI_FAULTS": ""},
        )
        assert replay.returncode == 0, replay.stderr[-2000:]
        report = json.loads(replay.stdout.splitlines()[0])
        assert report["kind"] == "replay"
        replayed = report["verdicts"]

        # Zero duplicated: a request answered before the kill was marked
        # done before its response line, so it cannot replay again.
        assert not set(answered) & set(replayed)
        # Zero lost: every journaled request reached exactly one outcome.
        assert set(answered) | set(replayed) == set(oracle)
        for rid, verdict in {**answered, **replayed}.items():
            assert verdict is oracle[rid], f"{rid} diverged across the kill"


class TestReadyz:
    def test_one_shot_process_is_ready(self, rec):
        payload, status = readyz_payload()
        assert status == 200
        assert payload["schema"] == "qi-ready/1"
        assert payload["serving"] is False
        assert payload["replay_complete"] is None

    def test_503_while_replaying_200_after(self, rec):
        # The exact gauge protocol ServeEngine.start() drives: 0 published
        # before replay, 1 after.
        rec.gauge("serve.queue_depth", 0)
        rec.gauge("serve.replay_complete", 0)
        payload, status = readyz_payload()
        assert status == 503
        assert payload["status"] == "replaying"
        rec.gauge("serve.replay_complete", 1)
        payload, status = readyz_payload()
        assert status == 200
        assert payload["serving"] is True

    def test_started_engine_reports_ready(self, rec, tmp_path):
        with _Engine(backend="python", journal=tmp_path / "j.jsonl"):
            payload, status = readyz_payload()
            assert status == 200
            assert payload["replay_complete"] is True
            assert payload["serving"] is True


class TestPercentile:
    def test_nearest_rank_exact_integer_positions(self):
        # ceil semantics: p50 of [10, 20] is the 1st sample, and p99 of
        # exactly 100 samples is the 99th — not the maximum (the
        # round-half-even overshoot this pins against).
        assert serve_mod._percentile([10.0, 20.0], 50.0) == 10.0
        hundred = [float(i) for i in range(1, 101)]
        assert serve_mod._percentile(hundred, 99.0) == 99.0
        assert serve_mod._percentile(hundred, 100.0) == 100.0
        assert serve_mod._percentile([], 50.0) == 0.0
        assert serve_mod._percentile([7.0], 99.0) == 7.0


class TestChurnTrace:
    def test_deterministic(self):
        base = majority_fbas(9)
        t1 = churn_trace(base, steps=6, seed=3)
        t2 = churn_trace(base, steps=6, seed=3)
        assert json.dumps(t1) == json.dumps(t2)
        t3 = churn_trace(base, steps=6, seed=4)
        assert json.dumps(t1) != json.dumps(t3)

    def test_bounded_diffs_and_no_aliasing(self):
        base = majority_fbas(9)
        trace = churn_trace(base, steps=8, seed=0, max_diff=2)
        assert len(trace) == 9
        assert trace[0] == base and trace[0] is not base
        for prev, cur in zip(trace, trace[1:]):
            changed = sum(1 for a, b in zip(prev, cur) if a != b)
            assert changed <= 2

    def test_negative_steps_raises(self):
        with pytest.raises(ValueError):
            churn_trace(majority_fbas(5), steps=-1)

    def test_trace_verdicts_solvable(self):
        # Every churned snapshot stays a valid FBAS the pipeline solves.
        for snap in churn_trace(majority_fbas(7), steps=3, seed=1):
            solve(snap, backend="python")
