"""The degradation ladder (ISSUE 4 tentpole): bounded retries with
deterministic backoff for transient device errors, typed ``RungFailed``
fall-through, ``degrade`` telemetry on every transition, the native-call
watchdog (trip → grace → quarantine), the distributed-init bounded retry,
the native build timeout, and a chaos-soak smoke."""

import subprocess
import threading

import pytest

from quorum_intersection_tpu.backends import auto as auto_mod
from quorum_intersection_tpu.backends.auto import (
    AutoBackend,
    DegradationLadder,
    RungFailed,
    _backoff_delay,
)
from quorum_intersection_tpu.backends.base import (
    CancelToken,
    OracleBudgetExceeded,
    SearchCancelled,
)
from quorum_intersection_tpu.fbas.synth import majority_fbas
from quorum_intersection_tpu.pipeline import solve
from quorum_intersection_tpu.utils import faults, telemetry
from quorum_intersection_tpu.utils.faults import TransientDeviceFault


@pytest.fixture(autouse=True)
def _clean():
    faults.clear_plan()
    rec = telemetry.reset_run_record()
    yield rec
    faults.clear_plan()
    telemetry.reset_run_record()


@pytest.fixture
def rec(_clean):
    return _clean


@pytest.fixture
def no_sleep(monkeypatch):
    sleeps = []
    monkeypatch.setattr(auto_mod, "_retry_sleep", sleeps.append)
    return sleeps


class _InstantBurn:
    """Budgeted-oracle stand-in that burns immediately, forcing the router
    onto the sweep rung (mirrors tools/soak.py's chaos driver)."""

    name = "burn"

    def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
        raise OracleBudgetExceeded("test: forced sweep rung")


class _SweepFirstAuto(AutoBackend):
    def _cpu_oracle(self, budget_s=None, cancel=None):
        if budget_s is not None:
            return _InstantBurn()
        return super()._cpu_oracle(budget_s=budget_s, cancel=cancel)


class TestLadderAttempt:
    def test_transient_retries_then_succeeds(self, no_sleep, rec):
        ladder = DegradationLadder(retry_max=2)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise TransientDeviceFault("sweep.dispatch", calls["n"])
            return "verdict"

        assert ladder.attempt("tpu-sweep", flaky, fall_to="native") == "verdict"
        assert calls["n"] == 3
        assert no_sleep == [
            _backoff_delay("tpu-sweep", 0), _backoff_delay("tpu-sweep", 1),
        ]
        assert rec.counters.get("ladder.retries") == 2
        assert rec.counters.get("ladder.degrades", 0) == 0

    def test_transient_budget_exhausted_degrades(self, no_sleep, rec):
        ladder = DegradationLadder(retry_max=2)

        def always_oom():
            raise TransientDeviceFault("sweep.dispatch", 1)

        with pytest.raises(RungFailed) as err:
            ladder.attempt("tpu-sweep", always_oom, fall_to="native")
        assert err.value.attempts == 3  # 1 try + 2 retries
        assert len(no_sleep) == 2
        ev = [e for e in rec.events if e["name"] == "degrade"]
        assert len(ev) == 1
        assert ev[0]["attrs"]["rung"] == "tpu-sweep"
        assert ev[0]["attrs"]["to"] == "native"
        assert ev[0]["attrs"]["transient"] is True
        assert ev[0]["attrs"]["attempts"] == 3

    def test_non_transient_degrades_without_retry(self, no_sleep, rec):
        ladder = DegradationLadder(retry_max=5)

        def broken():
            raise ValueError("no jax on this box")

        with pytest.raises(RungFailed) as err:
            ladder.attempt("tpu-frontier", broken, fall_to="native")
        assert err.value.attempts == 1
        assert no_sleep == []
        assert err.value.cause.args == ("no jax on this box",)

    def test_flow_signals_pass_straight_through(self, no_sleep, rec):
        ladder = DegradationLadder(retry_max=2)
        for signal in (OracleBudgetExceeded("burn"), SearchCancelled("stop")):
            def raising():
                raise signal

            with pytest.raises(type(signal)):
                ladder.attempt("native", raising, fall_to="python-oracle")
        assert rec.counters.get("ladder.degrades", 0) == 0

    def test_quarantined_rung_short_circuits(self, rec):
        ladder = DegradationLadder(retry_max=2)
        ladder.quarantine("native", "wedged in a test")
        called = []
        with pytest.raises(RungFailed, match="quarantined"):
            ladder.attempt("native", lambda: called.append(1), fall_to="python-oracle")
        assert called == []
        assert rec.counters.get("ladder.quarantines") == 1

    def test_retry_max_comes_from_env_registry(self, monkeypatch):
        monkeypatch.setenv("QI_RETRY_MAX", "7")
        assert DegradationLadder().retry_max == 7

    def test_backoff_is_deterministic_and_grows(self):
        assert _backoff_delay("tpu-sweep", 0) == _backoff_delay("tpu-sweep", 0)
        assert _backoff_delay("tpu-sweep", 1) > _backoff_delay("tpu-sweep", 0)
        assert _backoff_delay("tpu-sweep", 2) > _backoff_delay("tpu-sweep", 1)
        # Jitter decorrelates rungs without breaking determinism.
        assert _backoff_delay("native", 0) != _backoff_delay("tpu-sweep", 0)


class TestRouterDegradation:
    def test_native_fault_degrades_to_python_with_event(self, rec):
        faults.install_plan(faults.parse_faults("native.call=error@1+"))
        res = solve(majority_fbas(9), backend=AutoBackend(race=False))
        assert res.intersects is True
        assert res.stats["backend"] == "python"
        ev = [e for e in rec.events if e["name"] == "degrade"]
        assert any(
            e["attrs"]["rung"] == "native"
            and e["attrs"]["to"] == "python-oracle" for e in ev
        )

    def test_sweep_oom_retries_then_degrades_to_host_oracle(self, no_sleep, rec):
        faults.install_plan(faults.parse_faults("sweep.dispatch=oom@1+"))
        res = solve(majority_fbas(9), backend=_SweepFirstAuto(race=False))
        assert res.intersects is True
        assert res.stats["backend"] in ("cpp", "python")
        assert rec.counters.get("ladder.retries", 0) >= 1
        ev = [e for e in rec.events if e["name"] == "degrade"]
        assert any(e["attrs"]["rung"] == "tpu-sweep" for e in ev)

    def test_window_preemption_degrades_not_crashes(self, rec):
        faults.install_plan(faults.parse_faults("sweep.window=preempt@1+"))
        data = majority_fbas(9, broken=True)
        res = solve(data, backend=_SweepFirstAuto(race=False))
        assert res.intersects is False
        assert res.q1 and res.q2 and not set(res.q1) & set(res.q2)

    def test_verdicts_match_fault_free_chain(self, rec):
        for broken in (False, True):
            data = majority_fbas(9, broken=broken)
            faults.clear_plan()
            expected = solve(data, backend=AutoBackend(race=False)).intersects
            faults.install_plan(faults.parse_faults("native.call=error@1+"))
            got = solve(data, backend=AutoBackend(race=False)).intersects
            assert got is expected


class TestWatchdog:
    def test_hang_trips_watchdog_and_quarantines(self, monkeypatch, rec):
        monkeypatch.setenv("QI_NATIVE_WATCHDOG_S", "0.15")
        faults.install_plan(faults.parse_faults("native.call=hang:0.8@1+"))
        backend = AutoBackend(race=False)
        res = solve(majority_fbas(9, broken=True), backend=backend)
        assert res.intersects is False
        assert res.stats["backend"] == "python"
        assert backend._ladder.quarantined("native")
        names = [e["name"] for e in rec.events]
        assert "native.watchdog_cancel" in names
        assert "ladder.quarantined" in names
        # The whole run: one quarantine, later solves skip native silently.
        res2 = solve(majority_fbas(9), backend=backend)
        assert res2.stats["backend"] == "python"
        assert rec.counters.get("ladder.quarantines") == 1

    def test_responsive_cancel_degrades_without_quarantine(self, rec):
        # A native call that honors its CancelToken once tripped: slow,
        # not wedged — the rung must stay available.
        ladder = DegradationLadder(retry_max=0)
        tok = CancelToken()

        class SlowButCancellable:
            name = "cpp"

            def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
                assert tok._event.wait(timeout=30.0)
                raise SearchCancelled("honored the trip")

        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend,
        )
        from quorum_intersection_tpu.fbas.graph import build_graph
        from quorum_intersection_tpu.fbas.schema import parse_fbas

        wrapper = auto_mod._WatchedNativeOracle(
            ladder, SlowButCancellable(), PythonOracleBackend,
            outer_cancel=None, native_cancel=tok, watchdog_s=0.1,
        )
        graph = build_graph(parse_fbas(majority_fbas(9)))
        res = wrapper.check_scc(graph, None, list(range(graph.n)))
        assert res.intersects is True
        assert wrapper.name == "python"
        assert not ladder.quarantined("native")
        ev = [e for e in rec.events if e["name"] == "degrade"]
        assert len(ev) == 1 and "watchdog" in ev[0]["attrs"]["cause"]

    def test_race_cancel_is_forwarded_inward(self, rec):
        # The outer (race) token fires while the native call runs under a
        # generous watchdog: the supervisor must forward the cancel to the
        # native token and propagate SearchCancelled untouched.
        ladder = DegradationLadder(retry_max=0)
        outer, inner = CancelToken(), CancelToken()

        class WaitsForCancel:
            name = "cpp"

            def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
                assert inner._event.wait(timeout=30.0)
                raise SearchCancelled("race cancel observed")

        wrapper = auto_mod._WatchedNativeOracle(
            ladder, WaitsForCancel(), lambda: None,
            outer_cancel=outer, native_cancel=inner, watchdog_s=30.0,
        )
        from quorum_intersection_tpu.fbas.graph import build_graph
        from quorum_intersection_tpu.fbas.schema import parse_fbas

        graph = build_graph(parse_fbas(majority_fbas(9)))
        timer = threading.Timer(0.1, outer.cancel)
        timer.start()
        try:
            with pytest.raises(SearchCancelled):
                wrapper.check_scc(graph, None, list(range(graph.n)))
        finally:
            timer.cancel()
        assert not ladder.quarantined("native")

    def test_watchdog_disabled_runs_on_caller_thread(self, monkeypatch):
        monkeypatch.setenv("QI_NATIVE_WATCHDOG_S", "0")
        seen = {}

        class Probe:
            name = "cpp"

            def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
                seen["thread"] = threading.current_thread().name
                raise RuntimeError("force the python fallback")

        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend,
        )
        from quorum_intersection_tpu.fbas.graph import build_graph
        from quorum_intersection_tpu.fbas.schema import parse_fbas

        ladder = DegradationLadder(retry_max=0)
        wrapper = auto_mod._WatchedNativeOracle(
            ladder, Probe(), PythonOracleBackend,
            outer_cancel=None, native_cancel=None, watchdog_s=0.0,
        )
        graph = build_graph(parse_fbas(majority_fbas(9)))
        res = wrapper.check_scc(graph, None, list(range(graph.n)))
        assert res.intersects is True
        assert seen["thread"] == threading.current_thread().name


class TestDistributedInitRetry:
    def test_bounded_retry_then_loud_degrade(self, monkeypatch, rec):
        from quorum_intersection_tpu.parallel import distributed

        monkeypatch.setattr(distributed, "_initialized", False)
        monkeypatch.setattr(distributed, "_retry_sleep", lambda s: None)
        monkeypatch.setenv("QI_DIST_INIT_TIMEOUT_S", "0")
        faults.install_plan(faults.parse_faults("distributed.init=error@1+"))
        distributed.initialize(
            coordinator_address="127.0.0.1:1", num_processes=2, process_id=0
        )
        ev = [e for e in rec.events if e["name"] == "distributed.init_degraded"]
        assert len(ev) == 1
        assert ev[0]["attrs"]["attempts"] >= 1
        assert "injected" in ev[0]["attrs"]["cause"]

    def test_unrecoverable_cause_degrades_immediately(self, monkeypatch, rec):
        # "XLA backend already touched" cannot be fixed by retrying: the
        # degrade must be instant, not a full retry window spent asleep.
        import jax

        from quorum_intersection_tpu.parallel import distributed

        monkeypatch.setattr(distributed, "_initialized", False)
        slept = []
        monkeypatch.setattr(distributed, "_retry_sleep", slept.append)
        monkeypatch.setenv("QI_DIST_INIT_TIMEOUT_S", "60")

        def touched(**kw):
            raise RuntimeError(
                "jax.distributed.initialize() must be called before "
                "any JAX computations are executed."
            )

        monkeypatch.setattr(jax.distributed, "initialize", touched)
        monkeypatch.setattr(
            jax.distributed, "is_initialized", lambda: False, raising=False
        )
        distributed.initialize(
            coordinator_address="127.0.0.1:1", num_processes=2, process_id=0
        )
        assert slept == [], "unrecoverable cause must not burn the window"
        ev = [e for e in rec.events if e["name"] == "distributed.init_degraded"]
        assert len(ev) == 1 and ev[0]["attrs"]["attempts"] == 1

    def test_transient_coordinator_recovers_within_budget(self, monkeypatch, rec):
        import jax

        from quorum_intersection_tpu.parallel import distributed

        monkeypatch.setattr(distributed, "_initialized", False)
        slept = []
        monkeypatch.setattr(distributed, "_retry_sleep", slept.append)
        monkeypatch.setenv("QI_DIST_INIT_TIMEOUT_S", "60")
        joined = []
        monkeypatch.setattr(
            jax.distributed, "initialize",
            lambda **kw: joined.append(kw),
        )
        monkeypatch.setattr(
            jax.distributed, "is_initialized", lambda: False, raising=False
        )
        # First join attempt dies (injected); the retry succeeds.
        faults.install_plan(faults.parse_faults("distributed.init=error@1"))
        distributed.initialize(
            coordinator_address="127.0.0.1:1", num_processes=2, process_id=0
        )
        assert len(joined) == 1, "the retry must reach the real join"
        assert len(slept) == 1
        assert not [
            e for e in rec.events if e["name"] == "distributed.init_degraded"
        ]


class TestBuildTimeout:
    def test_compile_passes_the_timeout(self, monkeypatch, tmp_path):
        from quorum_intersection_tpu.backends import cpp

        seen = {}

        def fake_run(cmd, capture_output, text, timeout):
            seen["timeout"] = timeout
            tmp_out = cmd[cmd.index("-o") + 1]
            with open(tmp_out, "w") as fh:
                fh.write("")

            class P:
                returncode = 0
                stderr = ""

            return P()

        monkeypatch.setattr(cpp.subprocess, "run", fake_run)
        out = tmp_path / "fake.so"
        assert cpp._compile(out, [cpp._SRC], ["-O2"], "test", force=True) == out
        assert seen["timeout"] == cpp.BUILD_TIMEOUT_S

    def test_timeout_surfaces_compiler_stderr(self, monkeypatch, tmp_path):
        from quorum_intersection_tpu.backends import cpp

        def fake_run(cmd, capture_output, text, timeout):
            raise subprocess.TimeoutExpired(
                cmd, timeout, stderr=b"cc1plus: warning: eating all RAM"
            )

        monkeypatch.setattr(cpp.subprocess, "run", fake_run)
        with pytest.raises(RuntimeError, match="timed out") as err:
            cpp._compile(tmp_path / "fake.so", [cpp._SRC], ["-O2"], "test",
                         force=True)
        assert "eating all RAM" in str(err.value)


class TestChaosSmoke:
    def test_chaos_soak_window_is_clean(self, monkeypatch):
        import tools.soak as soak

        monkeypatch.setenv("QI_NATIVE_WATCHDOG_S", "0.25")
        rc = soak.main(
            ["--chaos", "--instances", "4", "--seed", "11", "--no-ledger"]
        )
        assert rc == 0
