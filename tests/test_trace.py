"""Trace parity (-t): both CLIs must narrate the search trajectory to stderr
the way the reference saturates its solver with BOOST_LOG_TRIVIAL(trace)
messages and a B&B call counter (`/root/reference/quorum_intersection.cpp:
94, 150-152, 258-259, 362`) — while leaving stdout byte-identical to a
non-traced run."""

import subprocess
import sys

import pytest


def run_python(args, stdin_data=""):
    return subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", *args],
        input=stdin_data, capture_output=True, text=True, timeout=180,
    )


@pytest.fixture(scope="module")
def native():
    from quorum_intersection_tpu.backends.cpp import build_native_cli

    try:
        return str(build_native_cli())
    except Exception as exc:  # pragma: no cover - g++ missing
        pytest.skip(f"native CLI unavailable: {exc}")


def test_python_cli_trace_shows_search_trajectory(ref_fixture):
    data = ref_fixture("broken.json").read_text()
    proc = run_python(["-t", "--backend", "python"], data)
    assert proc.returncode == 1
    assert proc.stdout == "false\n"
    assert "B&B call" in proc.stderr
    assert "minimal quorum #1 found" in proc.stderr
    assert "disjointness probe" in proc.stderr
    assert "search done:" in proc.stderr


def test_python_cli_trace_off_is_quiet(ref_fixture):
    data = ref_fixture("broken.json").read_text()
    proc = run_python(["--backend", "python"], data)
    assert "B&B call" not in proc.stderr


def test_cpp_backend_trace(ref_fixture):
    data = ref_fixture("broken.json").read_text()
    proc = run_python(["-t", "--backend", "cpp"], data)
    assert proc.returncode == 1
    assert proc.stdout == "false\n"
    assert "trace: B&B call" in proc.stderr
    assert "trace: search done:" in proc.stderr


def test_native_cli_trace_matches_python_trajectory(native, ref_fixture):
    data = ref_fixture("broken.json").read_text()
    traced = subprocess.run(
        [native, "-t"], input=data, capture_output=True, text=True, timeout=120
    )
    plain = subprocess.run(
        [native], input=data, capture_output=True, text=True, timeout=120
    )
    assert traced.returncode == plain.returncode == 1
    assert traced.stdout == plain.stdout == "false\n"  # stdout untouched
    assert "trace: B&B call" in traced.stderr
    assert "trace: minimal quorum #1 found" in traced.stderr
    assert "trace: disjointness probe" in traced.stderr
    assert "trace: scanning for quorums" not in plain.stderr
    assert "strongly connected components; scanning for quorums" in traced.stderr

    # Deterministic-mode native and python oracles are stats-identical, so
    # the narrated call counts must agree line-for-line in count.
    py = run_python(["-t", "--backend", "python"], data)
    n_calls_native = traced.stderr.count("|toRemove|=")
    n_calls_python = py.stderr.count("|toRemove|=")
    assert n_calls_native == n_calls_python > 0


def test_sweep_backend_trace(ref_fixture):
    data = ref_fixture("broken.json").read_text()
    proc = run_python(["-t", "--backend", "tpu-sweep"], data)
    assert proc.returncode == 1
    assert proc.stdout == "false\n"
    assert "sweep program" in proc.stderr
