"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform *before* jax is imported
anywhere — the TPU-native analog of a fake multi-chip backend (SURVEY.md §4.3):
sharding/mesh tests run against 8 emulated devices without TPU hardware.
"""

import os
import pathlib

# Must be set before the first `import jax` in any test module.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest

REFERENCE_DIR = pathlib.Path("/root/reference")


def reference_fixture(name: str) -> pathlib.Path:
    """Path to a bundled reference fixture, skipping if unavailable.

    The four golden JSON fixtures are loaded straight from the read-only
    reference checkout rather than copied into this repo.
    """
    path = REFERENCE_DIR / name
    if not path.exists():
        pytest.skip(f"reference fixture {name} not available")
    return path


@pytest.fixture
def ref_fixture():
    return reference_fixture
