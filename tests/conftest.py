"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform — the TPU-native analog of a
fake multi-chip backend (SURVEY.md §4.3): sharding/mesh tests run against 8
emulated devices without TPU hardware.

Two mechanisms, because this image's axon sitecustomize imports jax at
interpreter startup (so env vars alone can arrive too late):

1. env vars, for clean environments where jax is not yet imported;
2. ``jax.config.update("jax_platforms", "cpu")`` + XLA_FLAGS before the first
   backend initialization, which still wins after an early ``import jax`` as
   long as no devices were queried yet.
"""

import os
import pathlib

# Tests default to the emulated 8-device CPU platform regardless of the
# image's ambient JAX_PLATFORMS (this image exports =axon globally, which is
# not a per-test choice).  Set QI_TEST_PLATFORM=tpu (or axon) to explicitly
# run the suite against real hardware.
_platform = os.environ.get("QI_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", _platform)

import pytest

REFERENCE_DIR = pathlib.Path("/root/reference")
VENDORED_DIR = pathlib.Path(__file__).resolve().parent.parent / "fixtures"


def reference_fixture(name: str) -> pathlib.Path:
    """Path to a bundled reference fixture, skipping if unavailable.

    The four golden JSON fixtures are loaded straight from the read-only
    reference checkout rather than copied into this repo; the self-contained
    corpus under ``fixtures/`` (see ``vendored_fixture``) keeps the suite
    meaningful when the checkout is absent.
    """
    path = REFERENCE_DIR / name
    if not path.exists():
        pytest.skip(f"reference fixture {name} not available")
    return path


def vendored_fixture_text(name: str) -> str:
    """JSON text of a vendored fixture from ``fixtures/`` (handles .gz)."""
    path = VENDORED_DIR / name
    if name.endswith(".gz"):
        import gzip

        return gzip.decompress(path.read_bytes()).decode()
    return path.read_text()


def vendored_manifest() -> dict:
    import json

    return json.loads((VENDORED_DIR / "MANIFEST.json").read_text())


@pytest.fixture
def ref_fixture():
    return reference_fixture
