"""Racing auto-router (ISSUE 1 tentpole): deterministic CPU-tier coverage.

Fake engines with controlled latencies replace the oracle and the sweep, so
every branch of the race — winner selection in both directions, cooperative
loser cancellation, stats bookkeeping — runs without timing races; the
vendored corpus pins verdict/witness parity between racing and sequential
routing with the REAL engines.
"""

import threading
import time

import pytest

from quorum_intersection_tpu.backends.auto import AutoBackend
from quorum_intersection_tpu.backends.base import (
    CancelToken,
    OracleBudgetExceeded,
    SearchCancelled,
)
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.semantics import is_quorum
from quorum_intersection_tpu.fbas.synth import majority_fbas
from quorum_intersection_tpu.pipeline import solve

from tests.conftest import vendored_fixture_text, vendored_manifest

# The slow fake never finishes on its own: it waits for its cancel token
# (bounded by a loud timeout so a broken cancel path fails the test instead
# of hanging the suite).
_SLOW_TIMEOUT_S = 30.0
_FAST_S = 0.05


class _RecordingEngine:
    """Fake engine: either answers after a short delay or blocks until
    cancelled.  Delegates the actual verdict to the Python oracle so
    witnesses stay real; records lifecycle events for assertions."""

    def __init__(self, name, log, cancel=None, fast=True, burn_budget=False,
                 announce=None, wait_for=None):
        self.name = name
        self.log = log  # shared list of (engine, event) tuples
        self.cancel = cancel
        self.fast = fast
        self.burn_budget = burn_budget
        self.announce = announce  # threading.Event set when check_scc starts
        self.wait_for = wait_for  # threading.Event to await before answering
        self.burn_announce = None  # threading.Event set as the budget burns

    def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend,
        )

        self.log.append((self.name, "start"))
        if self.announce is not None:
            self.announce.set()
        if self.fast:
            if self.wait_for is not None:
                assert self.wait_for.wait(timeout=_SLOW_TIMEOUT_S)
            time.sleep(_FAST_S)
            if self.cancel is not None and self.cancel.cancelled:
                self.log.append((self.name, "cancelled"))
                raise SearchCancelled(f"fake {self.name} cancelled")
            if self.burn_budget:
                self.log.append((self.name, "budget"))
                if self.burn_announce is not None:
                    self.burn_announce.set()
                raise OracleBudgetExceeded(f"fake {self.name} burned")
            res = PythonOracleBackend().check_scc(
                graph, circuit, scc, scope_to_scc=scope_to_scc
            )
            res.stats["backend"] = self.name
            self.log.append((self.name, "verdict"))
            return res
        # Slow side: cooperative-cancel wait, loud on timeout.
        assert self.cancel is not None, "slow fake needs a cancel token"
        if not self.cancel._event.wait(timeout=_SLOW_TIMEOUT_S):
            raise RuntimeError(f"fake {self.name} was never cancelled")
        self.log.append((self.name, "cancelled"))
        raise SearchCancelled(f"fake {self.name} cancelled")


def _fake_auto(log, oracle_fast, oracle_burns_budget=False, **kw):
    class FakeAuto(AutoBackend):
        def _cpu_oracle(self, budget_s=None, cancel=None):
            return _RecordingEngine(
                "cpp", log, cancel=cancel, fast=oracle_fast,
                burn_budget=oracle_burns_budget,
            )

        def _sweep(self, cancel=None, engine=None):
            return _RecordingEngine(
                "tpu-sweep", log, cancel=cancel, fast=not oracle_fast
            )

    return FakeAuto(**kw)


def _no_race_threads():
    return [t for t in threading.enumerate() if t.name == "qi-race-sweep"]


def _join_race_threads(timeout=5.0):
    for t in _no_race_threads():
        t.join(timeout=timeout)
    return _no_race_threads()


class TestRaceWinnerSelection:
    def test_fast_oracle_beats_slow_sweep(self):
        # The common path: the oracle answers while the sweep spins up; the
        # sweep must be cancelled MID-RUN (an event gate guarantees it
        # actually started) and its thread must not leak.
        log = []
        sweep_started = threading.Event()

        class Gated(AutoBackend):
            def _cpu_oracle(self, budget_s=None, cancel=None):
                return _RecordingEngine(
                    "cpp", log, cancel=cancel, fast=True,
                    wait_for=sweep_started,
                )

            def _sweep(self, cancel=None, engine=None):
                return _RecordingEngine(
                    "tpu-sweep", log, cancel=cancel, fast=False,
                    announce=sweep_started,
                )

        res = solve(majority_fbas(9), backend=Gated())
        assert res.intersects is True
        assert res.stats["backend"] == "cpp"
        race = res.stats["race"]
        assert race["winner"] == "oracle"
        assert race["oracle_outcome"] == "verdict"
        assert race["loser_joined"] is True
        assert ("tpu-sweep", "cancelled") in log
        assert ("tpu-sweep", "verdict") not in log
        assert not _join_race_threads(), "race worker thread leaked"

    def test_fast_sweep_beats_stuck_oracle(self):
        # A pathological B&B (never finishes) loses to the sweep, which
        # must cancel it instead of waiting for the budget to burn.
        log = []
        res = solve(majority_fbas(9), backend=_fake_auto(log, oracle_fast=False))
        assert res.intersects is True
        assert res.stats["backend"] == "tpu-sweep"
        race = res.stats["race"]
        assert race["winner"] == "sweep"
        assert race["oracle_outcome"] == "cancelled"
        assert "sweep_seconds" in race
        assert ("cpp", "cancelled") in log
        assert not _join_race_threads(), "race worker thread leaked"

    def test_budget_burn_awaits_sweep(self):
        # Oracle burns its budget: the race must hand the verdict to the
        # (still running) sweep, like the sequential fallback but with the
        # spin-up already overlapped.  The sweep is gated on the burn so
        # the ordering is deterministic.
        log = []
        burned = threading.Event()

        class BothFast(AutoBackend):
            def _cpu_oracle(self, budget_s=None, cancel=None):
                eng = _RecordingEngine(
                    "cpp", log, cancel=cancel, fast=True, burn_budget=True
                )
                eng.burn_announce = burned
                return eng

            def _sweep(self, cancel=None, engine=None):
                return _RecordingEngine(
                    "tpu-sweep", log, cancel=cancel, fast=True,
                    wait_for=burned,
                )

        res = solve(majority_fbas(9), backend=BothFast())
        assert res.intersects is True
        assert res.stats["backend"] == "tpu-sweep"
        assert res.stats["race"]["winner"] == "sweep"
        assert res.stats["race"]["oracle_outcome"] == "budget_exceeded"
        assert not _join_race_threads()

    def test_broken_network_witness_from_each_winner(self):
        data = majority_fbas(9, broken=True)
        graph = build_graph(parse_fbas(data))
        for oracle_fast in (True, False):
            res = solve(data, backend=_fake_auto([], oracle_fast=oracle_fast))
            assert res.intersects is False
            assert res.q1 and res.q2 and not set(res.q1) & set(res.q2)
            assert is_quorum(graph, res.q1) and is_quorum(graph, res.q2)
        assert not _join_race_threads()

    def test_losing_sweep_does_not_poison_checkpoint(self, tmp_path):
        # r1 review finding: progress recorded by a race-LOSING sweep must
        # not survive an oracle win — left on disk it would flip the
        # resumable gate and route every later run of the same problem to
        # a full sweep instead of the milliseconds oracle.
        from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

        ck = SweepCheckpoint(tmp_path / "race.ckpt")
        log = []
        recorded = threading.Event()
        total = 1 << 8  # the enumeration size of a 9-node SCC

        class RecordingSweep:
            def __init__(self, cancel):
                self.cancel = cancel

            def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
                ck.record(16, total)
                recorded.set()
                assert self.cancel._event.wait(timeout=_SLOW_TIMEOUT_S)
                raise SearchCancelled("fake sweep cancelled")

        class Auto(AutoBackend):
            def _cpu_oracle(self, budget_s=None, cancel=None):
                # Gated on the sweep having recorded: the poisoning window
                # is guaranteed open when the oracle wins.
                return _RecordingEngine(
                    "cpp", log, cancel=cancel, fast=True, wait_for=recorded
                )

            def _sweep(self, cancel=None, engine=None):
                return RecordingSweep(cancel)

        data = majority_fbas(9)
        res = solve(data, backend=Auto(checkpoint=ck))
        assert res.intersects is True
        assert res.stats["backend"] == "cpp"
        assert ck.resume_position(total) == 0, "race residue left on disk"
        # Second run must race again (oracle wins), not resume a sweep.
        res2 = solve(data, backend=Auto(checkpoint=ck))
        assert res2.stats["backend"] == "cpp"
        assert not _join_race_threads()

    def test_sequential_mode_spawns_no_worker(self):
        log = []
        res = solve(
            majority_fbas(9),
            backend=_fake_auto(log, oracle_fast=True, race=False),
        )
        assert res.intersects is True
        assert "race" not in res.stats
        assert ("tpu-sweep", "start") not in log
        assert not _no_race_threads()

    def test_race_ineligible_sweep_falls_back_like_sequential(self, monkeypatch):
        # Platform limit below |scc|: the worker declares the sweep
        # ineligible; a budget-burning oracle then falls through to the
        # sequential fallbacks (here: the unbudgeted host oracle).
        import quorum_intersection_tpu.backends.auto as auto_mod

        monkeypatch.setattr(auto_mod, "_platform_sweep_limit", lambda: 4)
        log = []

        class Fake(AutoBackend):
            def _cpu_oracle(self, budget_s=None, cancel=None):
                if budget_s is not None:
                    return _RecordingEngine(
                        "cpp", log, cancel=cancel, fast=True, burn_budget=True
                    )
                return _RecordingEngine("cpp", log, cancel=cancel, fast=True)

            def _sweep(self, cancel=None, engine=None):  # pragma: no cover - must not run
                raise AssertionError("ineligible sweep was constructed")

        res = solve(majority_fbas(9), backend=Fake())
        assert res.intersects is True
        assert res.stats["backend"] == "cpp"
        assert ("cpp", "budget") in log  # the budget DID burn first
        assert not _join_race_threads()


class TestRaceTelemetry:
    """ISSUE 2: both race outcomes land a `race` span in the run record
    with winner/loser attributes — the machine-readable twin of
    res.stats["race"]."""

    @pytest.mark.parametrize("oracle_fast,winner", [
        (True, "oracle"), (False, "sweep"),
    ])
    def test_race_span_both_outcomes(self, oracle_fast, winner):
        from quorum_intersection_tpu.utils import telemetry

        rec = telemetry.reset_run_record()
        try:
            res = solve(
                majority_fbas(9), backend=_fake_auto([], oracle_fast=oracle_fast)
            )
            assert res.intersects is True
            race_spans = [sp for sp in rec.spans if sp.name == "race"]
            assert len(race_spans) == 1
            attrs = race_spans[0].attrs
            assert attrs["winner"] == winner
            assert attrs["oracle_outcome"] == (
                "verdict" if winner == "oracle" else "cancelled"
            )
            assert "loser_joined" in attrs
            # The race event mirrors the span's verdict attributes.
            race_events = [e for e in rec.events if e["name"] == "race"]
            assert race_events and race_events[0]["attrs"]["winner"] == winner
            # Nested under the routing span, which is stamped with the
            # engine that actually answered.
            route = next(sp for sp in rec.spans if sp.name == "route")
            assert race_spans[0].parent_id == route.span_id
            assert route.attrs["backend"] == res.stats["backend"]
        finally:
            telemetry.reset_run_record()
        assert not _join_race_threads()


class TestRaceLatency:
    """ISSUE 1 acceptance: time-to-verdict within 1.2x of the faster
    engine in both race outcomes (the sequential chain measured 3.4x at
    scc 36 on chip).  Sleep-based fakes; generous margins."""

    def test_ratio_both_outcomes(self):
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "benchmarks"))
        from auto_race import fake_rows

        rows = fake_rows(majority_fbas(9))
        assert {r["outcome"] for r in rows} == {"oracle_fast", "sweep_fast"}
        for row in rows:
            assert row["verdict_ok"], row
            assert row["ratio_vs_fast"] <= 1.2, row
        assert not _join_race_threads()


class TestCorpusParity:
    """No verdict changes anywhere: racing on and off agree with the frozen
    golden verdicts on the full vendored corpus, with valid witnesses."""

    @pytest.mark.parametrize("name", [
        "trivial_correct.json", "trivial_broken.json",
        "nested_correct.json", "nested_broken.json",
        "snapshot_correct.json", "snapshot_broken.json",
    ])
    def test_vendored_corpus_race_on_off(self, name):
        data = vendored_fixture_text(name)
        want = vendored_manifest()[name]["verdict"]
        raced = solve(data, backend=AutoBackend())
        seq = solve(data, backend=AutoBackend(race=False))
        assert raced.intersects is seq.intersects is want
        if not want:
            graph = build_graph(parse_fbas(data))
            for res in (raced, seq):
                if res.q1 is not None:  # scc-guard splits carry scan quorums
                    assert not (set(res.q1) & set(res.q2))
                    assert is_quorum(graph, res.q1)
                    assert is_quorum(graph, res.q2)
        assert not _join_race_threads(), "race worker thread leaked"


class TestCancelPlumbing:
    """The cooperative tokens the race relies on, exercised directly."""

    def test_python_oracle_cancel_raises(self):
        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend,
        )

        tok = CancelToken()
        tok.cancel()
        with pytest.raises(SearchCancelled):
            solve(majority_fbas(12), backend=PythonOracleBackend(cancel=tok))

    def test_cpp_oracle_cancel_raises(self):
        from quorum_intersection_tpu.backends.cpp import CppOracleBackend

        backend = CppOracleBackend(cancel=None)
        try:
            backend.ensure_built()
        except Exception as exc:  # noqa: BLE001
            pytest.skip(f"native oracle unavailable: {exc}")
        tok = CancelToken()
        tok.cancel()
        with pytest.raises(SearchCancelled):
            solve(majority_fbas(12), backend=CppOracleBackend(cancel=tok))

    def test_sweep_cancel_pre_setup_and_mid_run(self):
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend

        tok = CancelToken()
        tok.cancel()
        with pytest.raises(SearchCancelled):
            solve(majority_fbas(10), backend=TpuSweepBackend(cancel=tok))

        # Mid-run: cancel from a timer thread while the sweep dispatches
        # many small programs; must raise, not return a verdict.
        tok2 = CancelToken()
        timer = threading.Timer(0.2, tok2.cancel)
        timer.start()
        try:
            with pytest.raises(SearchCancelled):
                solve(
                    majority_fbas(15),
                    backend=TpuSweepBackend(batch=16, cancel=tok2),
                )
        finally:
            timer.cancel()

    def test_cancelled_oracle_never_misreports_verdict(self):
        # Cancellation mid-search must raise, never return intersects=True
        # for a broken network (the race's correctness invariant).
        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend,
        )

        tok = CancelToken()
        tok.cancel()
        with pytest.raises(SearchCancelled):
            solve(
                majority_fbas(12, broken=True),
                backend=PythonOracleBackend(cancel=tok),
            )

    def test_uncancelled_token_is_free(self):
        # A live token must not perturb the search (stats lockstep).
        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend,
        )

        data = majority_fbas(10)
        plain = solve(data, backend=PythonOracleBackend())
        raced = solve(data, backend=PythonOracleBackend(cancel=CancelToken()))
        assert plain.intersects is raced.intersects is True
        assert plain.stats["bnb_calls"] == raced.stats["bnb_calls"]


class TestNoRaceCli:
    def test_no_race_flag_solves(self):
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, "-m", "quorum_intersection_tpu",
             "--no-race", "--timing"],
            input=vendored_fixture_text("nested_correct.json"),
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0
        assert proc.stdout.strip().endswith("true")
        # Sequential mode: no race stats on the record.
        assert "race" not in proc.stderr

    def test_no_race_rejected_for_non_auto_backend(self):
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, "-m", "quorum_intersection_tpu",
             "--no-race", "--backend", "cpp"],
            input=vendored_fixture_text("trivial_correct.json"),
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        assert "--no-race" in proc.stderr


class TestTraceIdentity:
    """qi-trace (ISSUE 6): one trace_id across both race arms and every
    ladder rung of one run — the cross-thread half of the propagation
    contract (the cross-process half lives in tests/test_qi_trace.py)."""

    def test_trace_id_shared_across_race_arms_and_rungs(self):
        from quorum_intersection_tpu.utils import telemetry

        rec = telemetry.reset_run_record()
        try:
            res = solve(majority_fbas(9), backend=AutoBackend())
            assert res.intersects is True
            # The losing sweep arm's span closes when the worker unwinds —
            # join it so the assertion below sees the full tree.
            assert not _join_race_threads()
            spans = list(rec.spans)
            names = {sp.name for sp in spans}
            assert {"route", "race", "race.oracle", "race.sweep",
                    "ladder.rung"} <= names, names
            # ONE trace: every span of the run carries the record's id.
            assert {sp.trace_id for sp in spans} == {rec.trace_id}
            # The sweep arm hangs under the race span despite running on a
            # worker thread (explicit cross-thread parenting).
            race = next(sp for sp in spans if sp.name == "race")
            arm = next(sp for sp in spans if sp.name == "race.sweep")
            assert arm.parent_id == race.span_id
            assert arm.tid != race.tid  # genuinely another OS thread
        finally:
            telemetry.reset_run_record()

    def test_ladder_rung_spans_cover_retries(self):
        # A transient fault burns retries: every attempt is its own
        # ladder.rung span (attempt numbering 1..n) in the same trace.
        from quorum_intersection_tpu.backends import auto as auto_mod
        from quorum_intersection_tpu.backends.auto import DegradationLadder
        from quorum_intersection_tpu.utils import telemetry
        from quorum_intersection_tpu.utils.faults import TransientDeviceFault

        rec = telemetry.reset_run_record()
        try:
            ladder = DegradationLadder(retry_max=2)
            calls = {"n": 0}

            def flaky():
                calls["n"] += 1
                if calls["n"] < 3:
                    raise TransientDeviceFault("sweep.dispatch", calls["n"])
                return "ok"

            old_sleep = auto_mod._retry_sleep
            auto_mod._retry_sleep = lambda s: None
            try:
                assert ladder.attempt("tpu-sweep", flaky, "native") == "ok"
            finally:
                auto_mod._retry_sleep = old_sleep
            rungs = [sp for sp in rec.spans if sp.name == "ladder.rung"]
            assert [sp.attrs["attempt"] for sp in rungs] == [1, 2, 3]
            assert {sp.attrs["rung"] for sp in rungs} == {"tpu-sweep"}
            assert {sp.trace_id for sp in rungs} == {rec.trace_id}
        finally:
            telemetry.reset_run_record()
