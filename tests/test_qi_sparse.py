"""qi-sparse differential suite (ISSUE 20): the streaming bitset
set-intersection engine twin and its density routing.

Pins: the pack/unpack word round-trip and the BitsetCircuit encode
invariants (decoded matrices equal the dense source exactly), engine
resolution precedence (bitset honored on wide AND restricted sweeps,
multi-edge circuits resolve back to xla with a typed reason), the
four-rung differential — xla-dense vs bitset vs pallas vs the host
oracle on the correct/broken pair with identical witnesses and coverage
ledgers (certs differ only in ``provenance.encoding``) through the
unmodified stdlib checker — including composition with rank ordering +
block-guard pruning and the K>1 packed drive, the exact ledger
partition under a mid-sweep cancel on the bitset path, the
``sweep.bitset`` fault degrading IN PLACE to the dense encoding with
the verdict unchanged, the calibration win-region parser
(verdict veto, >= 1.1x win margin, loss-inside-region shrink), and
auto's ``_bitset_hint`` routing gates (env pin, scc floor, density
ceiling, device kind).
"""

import json
from functools import lru_cache

import numpy as np
import pytest

from quorum_intersection_tpu.backends import auto as auto_mod
from quorum_intersection_tpu.backends.base import SearchCancelled
from quorum_intersection_tpu.backends.calibration import _bitset_win, calibrate
from quorum_intersection_tpu.backends.tpu.sweep import (
    TpuSweepBackend,
    resolve_engine,
)
from quorum_intersection_tpu.encode.circuit import (
    bitset_encode,
    bitset_supported,
    encode_circuit,
    pack_mask_words,
    unpack_mask_words,
)
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import (
    graph_density,
    majority_fbas,
    near_disjoint_cores,
    scc_qset_density,
    sparse_giant,
)
from quorum_intersection_tpu.pipeline import quorum_bearing_sccs, solve
from quorum_intersection_tpu.utils import telemetry
from tools.check_cert import check_certificate

CORRECT = near_disjoint_cores(6, 1)
BROKEN = near_disjoint_cores(6, 1, broken=True)
FIXTURES = {"correct": (CORRECT, True), "broken": (BROKEN, False)}


def sweep(engine, **kw):
    kw.setdefault("batch", 256)
    return TpuSweepBackend(engine=engine, **kw)


@lru_cache(maxsize=None)
def sweep_solve(fixture, engine, order="natural", prune=False):
    data, _ = FIXTURES[fixture]
    return solve(
        json.dumps(data), backend=sweep(engine, order=order, prune=prune)
    )


@lru_cache(maxsize=None)
def oracle_solve(fixture):
    data, _ = FIXTURES[fixture]
    return solve(json.dumps(data), backend="python")


def make_job(data):
    graph = build_graph(parse_fbas(data))
    circuit = encode_circuit(graph)
    [(_sid, scc)] = quorum_bearing_sccs(graph, allow_native=False)
    return graph, circuit, scc


@pytest.fixture
def fresh_record():
    rec = telemetry.reset_run_record()
    yield rec
    telemetry.reset_run_record()


class TestEncoding:
    @pytest.mark.parametrize("m", [1, 31, 32, 33, 64, 150])
    def test_word_round_trip(self, m):
        rng = np.random.default_rng(m)
        mask = (rng.random((5, m)) < 0.3).astype(np.uint8)
        words = (m + 31) // 32
        packed = pack_mask_words(mask, words)
        assert packed.dtype == np.uint32
        assert packed.shape == (5, words)
        np.testing.assert_array_equal(unpack_mask_words(packed, m), mask)

    def test_overflow_rejected(self):
        with pytest.raises(ValueError, match="do not fit"):
            pack_mask_words(np.ones((2, 33), dtype=np.uint8), 1)

    @pytest.mark.parametrize("fixture", ["correct", "broken"])
    def test_circuit_round_trip(self, fixture):
        data, _ = FIXTURES[fixture]
        circuit = encode_circuit(build_graph(parse_fbas(data)))
        assert bitset_supported(circuit)
        bits = bitset_encode(circuit)
        assert (bits.n, bits.n_units, bits.depth) == (
            circuit.n, circuit.n_units, circuit.depth,
        )
        np.testing.assert_array_equal(bits.decode_members(), circuit.members)
        np.testing.assert_array_equal(bits.thresholds, circuit.thresholds)
        dense_child = bits.decode_child()
        if dense_child is None:
            assert circuit.n_units == circuit.n
        else:
            np.testing.assert_array_equal(dense_child, circuit.child)

    def test_multiplicity_unsupported(self):
        circuit = encode_circuit(build_graph(parse_fbas(CORRECT)))
        circuit.members[0, int(np.argmax(circuit.members[0]))] = 2
        assert not bitset_supported(circuit)
        with pytest.raises(ValueError, match="0/1-vote only"):
            bitset_encode(circuit)


class TestEngineResolution:
    def _circuit(self):
        return encode_circuit(build_graph(parse_fbas(CORRECT)))

    @pytest.mark.parametrize("wide", [False, True])
    @pytest.mark.parametrize("restricted", [False, True])
    def test_bitset_honored_wide_and_restricted(self, wide, restricted):
        # Unlike pallas, the bitset engine serves EVERY sweep shape.
        res = resolve_engine(
            "bitset", mesh=False, wide=wide, restricted=restricted,
            circuit=self._circuit(),
        )
        assert (res.resolved, res.reason) == ("bitset", "as requested")

    def test_mesh_outranks_bitset(self):
        res = resolve_engine(
            "bitset", mesh=True, wide=False, restricted=False,
            circuit=self._circuit(),
        )
        assert res.resolved == "xla"
        assert "sharded" in res.reason

    def test_multi_edge_circuit_falls_back(self):
        circuit = self._circuit()
        circuit.members[0, int(np.argmax(circuit.members[0]))] = 2
        res = resolve_engine(
            "bitset", mesh=False, wide=False, restricted=False,
            circuit=circuit,
        )
        assert res.resolved == "xla"
        assert "multiplicities" in res.reason

    def test_env_knob_and_ctor_precedence(self, monkeypatch):
        monkeypatch.delenv("QI_SWEEP_ENGINE", raising=False)
        assert TpuSweepBackend()._engine_mode() == "xla"
        monkeypatch.setenv("QI_SWEEP_ENGINE", "bitset")
        assert TpuSweepBackend()._engine_mode() == "bitset"
        assert TpuSweepBackend(engine="pallas")._engine_mode() == "pallas"
        monkeypatch.setenv("QI_SWEEP_ENGINE", "chaotic")  # unknown → xla
        assert TpuSweepBackend()._engine_mode() == "xla"

    def test_unknown_ctor_engine_rejected(self):
        with pytest.raises(ValueError):
            TpuSweepBackend(engine="chaotic")


class TestDifferential:
    @pytest.mark.parametrize("fixture", ["correct", "broken"])
    def test_four_rung_parity(self, fixture):
        _, verdict = FIXTURES[fixture]
        dense = sweep_solve(fixture, "xla")
        pallas = sweep_solve(fixture, "pallas")
        bits = sweep_solve(fixture, "bitset")
        assert oracle_solve(fixture).intersects is verdict
        assert dense.intersects is verdict
        assert pallas.intersects is verdict
        assert bits.intersects is verdict
        # The encoding swaps the arithmetic, not the enumeration: same
        # first-hit window, same witness pair, engine-vs-engine.
        assert (bits.q1, bits.q2) == (dense.q1, dense.q2)
        assert bits.stats.get("hit_index") == dense.stats.get("hit_index")

    @pytest.mark.parametrize("fixture", ["correct", "broken"])
    def test_certs_identical_modulo_encoding(self, fixture):
        data, _ = FIXTURES[fixture]
        dense = sweep_solve(fixture, "xla")
        bits = sweep_solve(fixture, "bitset")
        # The whole evidence payload is byte-equal — coverage ledger on a
        # True verdict, witness pair on a False one — and only the
        # provenance stamp tells the engines apart (dense certs must stay
        # byte-identical to every release before the encoding existed).
        strip = lambda cert: {
            k: v for k, v in cert.items() if k != "provenance"
        }
        assert strip(bits.cert) == strip(dense.cert)
        assert bits.cert["provenance"].get("encoding") == "bitset"
        assert "encoding" not in dense.cert["provenance"]
        # The UNMODIFIED checker validates both: the cert schema carries
        # no encoding-specific evidence forms.
        check_certificate(dense.cert, data)
        check_certificate(bits.cert, data)

    @pytest.mark.parametrize("fixture", ["correct", "broken"])
    def test_composes_with_order_and_prune(self, fixture):
        _, verdict = FIXTURES[fixture]
        dense = sweep_solve(fixture, "xla", order="rank", prune=True)
        bits = sweep_solve(fixture, "bitset", order="rank", prune=True)
        assert dense.intersects is verdict and bits.intersects is verdict
        assert (bits.q1, bits.q2) == (dense.q1, dense.q2)
        # The bitset guard proves the same blocks the dense guard does
        # (the prune rule is encoding-agnostic), so the pruned ledgers —
        # and their exact partition — are equal (False verdicts carry a
        # witness instead of a ledger; it must match too).
        assert {
            k: v for k, v in bits.cert.items() if k != "provenance"
        } == {k: v for k, v in dense.cert.items() if k != "provenance"}
        if verdict:
            led = bits.stats["cert"]
            assert led["windows_pruned_guard"] > 0
            assert (
                led["windows_enumerated"] + led["windows_pruned_guard"]
                == led["window_space"]
            )
        data, _ = FIXTURES[fixture]
        notes = check_certificate(bits.cert, data)
        if verdict:
            assert any("guard-pruned" in n for n in notes)

    def test_packed_bitset_matches_unpacked(self):
        datas = [CORRECT, near_disjoint_cores(6, 1, seed=1), BROKEN]
        jobs = [make_job(d) for d in datas]
        unpacked = [
            sweep("bitset").check_scc(g, c, s) for g, c, s in jobs
        ]
        packed = sweep("bitset").check_sccs(jobs)
        for u, p in zip(unpacked, packed):
            assert u.intersects == p.intersects
            assert (u.q1, u.q2) == (p.q1, p.q2)
            assert p.stats.get("encoding") == "bitset"
        # Dense packs on the same jobs agree too (packed four-rung).
        dense_packed = sweep("xla").check_sccs(jobs)
        for d, p in zip(dense_packed, packed):
            assert d.intersects == p.intersects
            assert (d.q1, d.q2) == (p.q1, p.q2)
            assert "encoding" not in d.stats


class _TrippingCancel:
    def __init__(self, after):
        self.after = after
        self.polls = 0

    @property
    def cancelled(self):
        self.polls += 1
        return self.polls > self.after


class TestCancel:
    def test_cancel_partition_on_bitset_path(self, fresh_record):
        data = near_disjoint_cores(7, 1)  # 2^14 windows at batch 256
        graph, circuit, scc = make_job(data)
        backend = sweep(
            "bitset", max_inflight=2, cancel=_TrippingCancel(6)
        )
        with pytest.raises(SearchCancelled):
            backend.check_scc(graph, circuit, scc)
        counters, _ = fresh_record.snapshot()
        space = 1 << (len(scc) - 1)
        enumerated = counters.get("cert.windows_enumerated", 0)
        cancelled = counters.get("cert.windows_cancelled", 0)
        # Exact partition even mid-flight: every window is enumerated or
        # cancelled, never both, never lost — same conservation contract
        # as the dense path (tools/analyze conserve pins the counters).
        assert cancelled > 0
        assert enumerated + cancelled == space
        assert enumerated < space


class TestFaultDegrade:
    def test_bitset_fault_degrades_in_place_same_verdict(
        self, monkeypatch, fresh_record
    ):
        monkeypatch.setenv("QI_FAULTS", "sweep.bitset=error")
        res = solve(json.dumps(CORRECT), backend=sweep("bitset"))
        assert res.intersects is True
        # Degrade is IN PLACE to the dense encoding: no ladder hop, no
        # encoding stamp (the cert honestly records what executed).
        assert res.stats.get("encoding") is None
        assert "encoding" not in res.cert["provenance"]
        counters, _ = fresh_record.snapshot()
        assert counters.get("sweep.bitset_errors", 0) >= 1
        assert counters.get("faults.injected", 0) >= 1
        assert any(
            e.get("name") == "sweep.bitset_degraded"
            for e in fresh_record.events
        )
        check_certificate(res.cert, CORRECT)

    def test_bitset_fault_degrades_packed_pack(self, monkeypatch):
        monkeypatch.setenv("QI_FAULTS", "sweep.bitset=error")
        jobs = [make_job(CORRECT), make_job(BROKEN)]
        results = sweep("bitset").check_sccs(jobs)
        assert [r.intersects for r in results] == [True, False]
        assert all(r.stats.get("encoding") is None for r in results)


def _bitset_rows(tmp_path, name, rows):
    path = tmp_path / name
    path.write_text(
        "| header noise |\n"
        + "\n".join(
            json.dumps({
                "bitset": True, "device": device, "scc": scc,
                "scc_density": density,
                "bitset_speedup_vs_dense": speed, "verdict_ok": ok,
            })
            for device, scc, density, speed, ok in rows
        )
        + "\n"
    )
    return path


class TestCalibrationParser:
    # (device, scc, density, speedup, verdict_ok) — the r6 shape in
    # miniature: a sub-crossover loss, a tie at density 1.0, two wins.
    R6ISH = [
        ("cpu", 15, 0.2222, 0.95, True),
        ("cpu", 16, 1.0, 1.0, True),
        ("cpu", 21, 0.1667, 6.66, True),
        ("cpu", 24, 0.1481, 19.7, True),
    ]

    def test_win_region_extraction(self, tmp_path):
        path = _bitset_rows(tmp_path, "sweep_vs_native_cpu_r1.txt", self.R6ISH)
        min_scc, dmax, kind, prov = _bitset_win([path])
        # The 1.0x tie never extends the density bound; the 0.95x loss at
        # scc 15 sits below the winning sccs so it never shrinks it.
        assert (min_scc, dmax, kind) == (21, 0.1667, "cpu")
        assert "r1" in prov and "cpu" in prov

    def test_verdict_veto(self, tmp_path):
        rows = self.R6ISH + [("cpu", 22, 0.15, 9.0, False)]
        path = _bitset_rows(tmp_path, "sweep_vs_native_cpu_r1.txt", rows)
        assert _bitset_win([path]) is None

    def test_loss_inside_region_shrinks_density_bound(self, tmp_path):
        rows = [
            ("cpu", 20, 0.30, 2.0, True),
            ("cpu", 24, 0.15, 19.7, True),
            ("cpu", 22, 0.25, 0.8, True),  # loss INSIDE (scc>=20, d<=0.30)
        ]
        path = _bitset_rows(tmp_path, "sweep_vs_native_cpu_r1.txt", rows)
        min_scc, dmax, kind, _ = _bitset_win([path])
        # The d=0.30 win is dropped (>= the losing density), the region
        # re-derives from what survives.
        assert (min_scc, dmax, kind) == (24, 0.15, "cpu")

    def test_accelerator_rows_outrank_cpu(self, tmp_path):
        rows = self.R6ISH + [("tpu", 18, 0.30, 3.0, True)]
        path = _bitset_rows(tmp_path, "sweep_vs_native_tpu_r2.txt", rows)
        min_scc, dmax, kind, _ = _bitset_win([path])
        assert (min_scc, dmax, kind) == (18, 0.30, "tpu")

    def test_newest_round_wins(self, tmp_path):
        old = _bitset_rows(
            tmp_path, "sweep_vs_native_cpu_r1.txt", self.R6ISH
        )
        new = _bitset_rows(
            tmp_path, "sweep_vs_native_cpu_r2.txt",
            [("cpu", 25, 0.10, 3.0, True)],
        )
        min_scc, dmax, _, prov = _bitset_win([old, new])
        assert (min_scc, dmax) == (25, 0.10)
        assert "r2" in prov

    def test_calibrate_wires_the_gate(self, tmp_path):
        path = _bitset_rows(tmp_path, "sweep_vs_native_cpu_r1.txt", self.R6ISH)
        cal = calibrate(paths=[], sweep_window_paths=[path])
        assert cal.bitset_win_min_scc == 21
        assert cal.bitset_win_max_density == pytest.approx(0.1667)
        assert cal.bitset_win_device == "cpu"
        assert "bitset" in cal.provenance
        empty = calibrate(paths=[], sweep_window_paths=[])
        assert empty.bitset_win_min_scc is None
        assert empty.bitset_win_max_density is None

    def test_committed_artifact_lands_a_region(self):
        # The repo's own committed rows must parse (the routing the next
        # session inherits): whatever the region is, it must carry the
        # full (scc, density, device) triple or be absent entirely.
        cal = calibrate()
        if cal.bitset_win_min_scc is not None:
            assert cal.bitset_win_max_density is not None
            assert cal.bitset_win_device in ("cpu", "tpu")


class TestRouting:
    def _arm(self, monkeypatch, win=5, dmax=1.0, device="cpu"):
        monkeypatch.delenv("QI_SWEEP_ENGINE", raising=False)
        cal = auto_mod.CALIBRATION
        monkeypatch.setattr(cal, "bitset_win_min_scc", win)
        monkeypatch.setattr(cal, "bitset_win_max_density", dmax)
        monkeypatch.setattr(cal, "bitset_win_device", device)

    def test_hint_engages_and_records_the_route(
        self, monkeypatch, fresh_record
    ):
        self._arm(monkeypatch)
        graph, _, scc = make_job(CORRECT)
        assert auto_mod.AutoBackend()._bitset_hint(graph, scc) == "bitset"
        [ev] = [
            e for e in fresh_record.events
            if e.get("name") == "route.encoding"
        ]
        assert ev["attrs"]["engine"] == "bitset"
        assert ev["attrs"]["scc"] == len(scc)
        assert "measured win region" in ev["attrs"]["reason"]

    def test_env_pin_short_circuits_the_hint(self, monkeypatch):
        self._arm(monkeypatch)
        monkeypatch.setenv("QI_SWEEP_ENGINE", "pallas")
        graph, _, scc = make_job(CORRECT)
        assert auto_mod.AutoBackend()._bitset_hint(graph, scc) is None

    def test_scc_floor_density_ceiling_and_device_gate(self, monkeypatch):
        graph, _, scc = make_job(CORRECT)
        backend = auto_mod.AutoBackend()
        self._arm(monkeypatch, win=len(scc) + 1)
        assert backend._bitset_hint(graph, scc) is None
        self._arm(monkeypatch, dmax=0.01)  # near_disjoint cores are denser
        assert backend._bitset_hint(graph, scc) is None
        self._arm(monkeypatch, device="tpu")  # measured elsewhere
        assert backend._bitset_hint(graph, scc) is None

    def test_uncalibrated_defaults_off(self, monkeypatch):
        monkeypatch.delenv("QI_SWEEP_ENGINE", raising=False)
        cal = auto_mod.CALIBRATION
        monkeypatch.setattr(cal, "bitset_win_min_scc", None)
        monkeypatch.setattr(cal, "bitset_win_max_density", None)
        monkeypatch.setattr(cal, "bitset_win_device", None)
        graph, _, scc = make_job(CORRECT)
        assert auto_mod.AutoBackend()._bitset_hint(graph, scc) is None


class TestWorkloadShapes:
    def test_sparse_giant_deterministic_with_24_core(self):
        data = sparse_giant(400)
        assert data == sparse_giant(400)
        assert data != sparse_giant(400, seed=8)
        graph = build_graph(parse_fbas(data))
        [(_sid, scc)] = quorum_bearing_sccs(graph, allow_native=False)
        assert len(scc) == 24  # the 8-org x 3-validator core
        # The whole point of the preset: an org-nested core well inside
        # the measured bitset win region's density bound.
        assert scc_qset_density(graph, scc) < 0.2

    def test_density_annotations(self):
        giant = build_graph(parse_fbas(sparse_giant(400)))
        shape = graph_density(giant)
        assert set(shape) >= {"edge_density", "qset_fanout_mean"}
        assert 0.0 < shape["edge_density"] < 0.1  # sparse by construction
        flat = build_graph(parse_fbas(majority_fbas(8)))
        [(_sid, scc)] = quorum_bearing_sccs(flat, allow_native=False)
        # A flat majority qset references every member from every unit —
        # the dense-friendly regime the router must leave on the MXU path.
        assert scc_qset_density(flat, scc) == pytest.approx(1.0)
